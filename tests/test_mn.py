"""NES-compatible instrumentation layer tests."""

import math
import re

import pytest

from spatialflink_tpu.mn import (
    BUCKETS_MS,
    CountingStage,
    CsvParseAndStamp,
    FixedBucketLatency,
    MetricNames,
    MetricRegistry,
    NESFileReporter,
)
from spatialflink_tpu.mn.queries import (
    INSTRUMENTED,
    instrumented_mn_q1,
    instrumented_mn_q2,
)


def test_bucket_boundaries_are_nes():
    assert BUCKETS_MS == [0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1000,
                         2000, 5000, 10000, 20000, 60000]


def test_histogram_bucketing_and_percentiles():
    h = FixedBucketLatency()
    for v in [0, 1, 3, 100, 70_000]:
        h.observe(v)
    # 3ms → bucket le_4; 100 → le_128; 70000 clamps to le_60000.
    assert h.buckets[BUCKETS_MS.index(0)] == 1
    assert h.buckets[BUCKETS_MS.index(1)] == 1
    assert h.buckets[BUCKETS_MS.index(4)] == 1
    assert h.buckets[BUCKETS_MS.index(128)] == 1
    assert h.buckets[-1] == 1
    assert h.count == 5
    assert h.percentile(0.50) == 4.0  # 3rd of 5 samples → le_4
    assert h.percentile(0.99) == 60000.0
    assert math.isnan(FixedBucketLatency().percentile(0.5))


def test_counting_stage_selectivity():
    reg = MetricRegistry()
    stage = CountingStage("6_range", reg)
    out = list(stage.around(range(10), lambda it: (x for x in it if x % 2 == 0)))
    assert out == [0, 2, 4, 6, 8]
    assert reg.counter("pipe_6_range_in_total") == 10
    assert reg.counter("pipe_6_range_out_total") == 5


def test_parse_and_stamp_counts_and_skips():
    reg = MetricRegistry()
    parse = CsvParseAndStamp(lambda ln: int(ln), reg, 1000, 64)
    out = list(parse(["1", "x", "2"]))
    assert [s.value for s in out] == [1, 2]
    assert reg.counter(MetricNames.SOURCE_IN) == 2
    assert out[0].ingest_ns <= out[1].ingest_ns
    snap = reg.snapshot()
    assert snap["theoretical_eps"] == 1000.0
    assert snap["theoretical_throughput_mb_s"] == pytest.approx(0.064)


def test_reporter_line_format(tmp_path):
    reg = MetricRegistry()
    rep = NESFileReporter(reg, "qx", out_dir=str(tmp_path), interval_s=5)
    reg.inc(MetricNames.SOURCE_IN, 100)
    reg.inc(MetricNames.SINK_OUT, 25)
    reg.inc(MetricNames.OUT_BYTES, 12_500)
    line = rep.report(now=1_700_000_000.0)
    m = re.match(
        r"METRICS ts=\S+ eps_in_avg=(\S+) eps_out_avg=(\S+) "
        r"selectivity_e2e=(\S+) throughput_mb_s=(\S+)",
        line,
    )
    assert m, line
    assert float(m.group(3)) == pytest.approx(0.25)
    # Second interval with no traffic → zeros, nan selectivity.
    line2 = rep.report(now=1_700_000_005.0)
    assert "eps_in_avg=0.00" in line2 and "selectivity_e2e=nan" in line2
    assert (tmp_path / "EngineStats_qx_proc.stats").read_text().count("\n") == 2


def _csv_lines(n=3000, near_every=3):
    lines = []
    for i in range(n):
        # Every `near_every`-th point is near the query point (4.3658, 50.6456).
        if i % near_every == 0:
            lon, lat = 4.3658, 50.6456
        else:
            lon, lat = 5.9, 51.9
        lines.append(
            f"{i*10},dev{i%5},z,4.{i%10},5.0,a,b,c,d,e,f,{30+(i%20)},{lat},{lon}"
        )
    return lines


def test_instrumented_q1_end_to_end(tmp_path):
    props = {
        "output.file": str(tmp_path / "q1.txt"),
        "stats.dir": str(tmp_path),
        "tolerance.meters": "2000.0",
    }
    rep = instrumented_mn_q1(iter(_csv_lines()), props)
    assert rep.results > 0
    m = rep.metrics
    assert m["source_in_total"] == 3000
    assert m["pipe_6_range_in_total"] == 3000
    assert m["pipe_6_range_out_total"] == 1000  # 1-in-3 near the query
    assert m["sink_out_total"] == rep.results
    assert m["out_bytes_total"] > 0
    assert rep.p50_ms in [float(b) for b in BUCKETS_MS]
    # Counts per 5s window: 30s of data → 6 windows of ~167 qualifying each.
    total = sum(int(ln.split(",")[2]) for ln in open(props["output.file"]))
    assert total == 1000
    assert "METRICS ts=" in rep.stats_lines[0]


def test_instrumented_q2_variance(tmp_path):
    props = {"output.file": str(tmp_path / "q2.txt"), "stats.dir": str(tmp_path)}
    rep = instrumented_mn_q2(iter(_csv_lines(2000)), props)
    assert rep.results > 0
    # All in-box points (4.0-4.6 × 50.0-50.8) excluded; far points kept.
    assert rep.metrics["pipe_3_exclude_in_total"] == 2000


def test_all_instrumented_queries_run(tmp_path):
    for q, fn in INSTRUMENTED.items():
        props = {
            "output.file": str(tmp_path / f"{q}.txt"),
            "stats.dir": str(tmp_path),
        }
        rep = fn(iter(_csv_lines(1500)), props)
        assert rep.metrics["source_in_total"] == 1500, q


# -- MetricRegistry thread safety ---------------------------------------------


def test_registry_concurrent_inc_and_snapshot():
    """Operator threads inc() while a reporter thread snapshots: no
    RuntimeError from mid-resize iteration and NO lost increments (the
    unlocked read-modify-write could drop counts under preemption)."""
    import threading

    reg = MetricRegistry()
    n_threads, n_incs, n_keys = 4, 8_000, 8
    reg.gauge("g", lambda: 1.0)
    errors = []
    stop = threading.Event()

    def snapshotter():
        while not stop.is_set():
            try:
                reg.snapshot_counters()
                reg.snapshot()
            except Exception as e:  # pragma: no cover - the failure mode
                errors.append(e)
                return

    def incrementer():
        for i in range(n_incs):
            reg.inc(f"c{i % n_keys}")

    snap = threading.Thread(target=snapshotter)
    snap.start()
    threads = [threading.Thread(target=incrementer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    snap.join()
    assert not errors, errors
    for k in range(n_keys):
        assert reg.counter(f"c{k}") == n_threads * n_incs // n_keys


# -- JSON-safe snapshots ------------------------------------------------------


def test_json_safe_converts_numpy_at_the_boundary():
    """json.dumps of any snapshot must never raise, and f-strings must
    format cleanly (the np.float32 repr bug shipped twice)."""
    import json

    import numpy as np

    from spatialflink_tpu.mn.metrics import json_safe

    safe = json_safe({
        "f32": np.float32(1.5),
        "i64": np.int64(7),
        "b": np.bool_(True),
        "arr": np.arange(3, dtype=np.float32),
        "nested": {"t": (np.float64(2.5), "s", None)},
    })
    json.dumps(safe)
    assert type(safe["f32"]) is float and f"{safe['f32']}" == "1.5"
    assert type(safe["i64"]) is int
    assert type(safe["b"]) is bool
    assert safe["arr"] == [0.0, 1.0, 2.0]
    assert safe["nested"]["t"] == [2.5, "s", None]


def test_registry_snapshot_is_json_safe():
    import json

    import numpy as np

    reg = MetricRegistry()
    reg.inc("n", 3)
    reg.gauge("npval", lambda: np.float32(0.25))
    snap = reg.snapshot()
    json.dumps(snap)
    assert type(snap["npval"]) is float


def test_kernel_counters_snapshot_is_json_safe():
    import json

    import numpy as np

    from spatialflink_tpu.ops.counters import KernelCounters

    kc = KernelCounters(enabled=True)
    kc.record_window(np.int64(100), np.int32(40), np.int64(40))
    json.dumps(kc.snapshot())


# -- NESFileReporter timer-thread mode ----------------------------------------


def test_reporter_timer_thread_lifecycle(tmp_path):
    import time as _time

    reg = MetricRegistry()
    rep = NESFileReporter(reg, "qthr", out_dir=str(tmp_path),
                          interval_s=0.05)
    path = tmp_path / "EngineStats_qthr_proc.stats"
    rep.start()
    first = rep._thread
    assert first is not None and first.is_alive()
    rep.start()  # idempotent: no duplicate thread spawned
    assert rep._thread is first

    reg.inc(MetricNames.SOURCE_IN, 10)
    deadline = _time.time() + 10
    while _time.time() < deadline:
        if path.exists() and path.read_text().count("\n") >= 2:
            break
        _time.sleep(0.02)
    rep.stop()  # joins cleanly
    assert rep._thread is None
    assert not first.is_alive()
    rep.stop()  # second stop is a no-op

    lines = path.read_text().splitlines()
    assert len(lines) >= 2
    assert all(ln.startswith("METRICS ts=") for ln in lines)
    # No further lines appended after stop().
    n = len(lines)
    _time.sleep(0.15)
    assert path.read_text().count("\n") == n
