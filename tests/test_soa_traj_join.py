"""Round-2 SoA fast paths: TStats / TKnn / two-stream join, plus the
device-side tJoin pair dedup — each pinned bit-for-bit (or to f64 eps)
against the object path it accelerates (VERDICT round-1 item 4: the host
Python loops in the trajectory operators capped throughput)."""

import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import Point
from spatialflink_tpu.operators import (
    PointPointJoinQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.operators.trajectory import TJoinQuery, TKNNQuery, TStatsQuery
from spatialflink_tpu.utils.interning import Interner

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
W10 = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)


def _chunks(ts, xs, ys, oids, n_chunks=4):
    bounds = np.linspace(0, len(ts), n_chunks + 1).astype(int)
    for a, b in zip(bounds[:-1], bounds[1:]):
        yield {"ts": ts[a:b], "x": xs[a:b], "y": ys[a:b], "oid": oids[a:b]}


def _stream(rng, n, n_obj=6, t_max=30_000):
    ts = np.sort(rng.integers(0, t_max, n)).astype(np.int64)
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = rng.integers(0, n_obj, n).astype(np.int32)
    return ts, xs, ys, oids


def _points(ts, xs, ys, oids):
    return [
        Point(obj_id=str(int(o)), timestamp=int(t), x=float(x), y=float(y))
        for t, x, y, o in zip(ts, xs, ys, oids)
    ]


def test_tstats_soa_matches_object_path(rng):
    ts, xs, ys, oids = _stream(rng, 3000)
    # Interner parity: the object path interns str(oid) in first-seen order;
    # feed the SoA path oids that ARE the dense ints of that interning.
    interner = Interner()
    dense = np.array([interner.intern(str(int(o))) for o in oids], np.int32)

    soa = {}
    op = TStatsQuery(W10, GRID)
    for s, e, spatial, temporal, count in op.run_soa(
        _chunks(ts, xs, ys, dense), num_segments=64
    ):
        soa[(s, e)] = (spatial, temporal, count)

    obj_op = TStatsQuery(W10, GRID)
    for res in obj_op.run(iter(_points(ts, xs, ys, oids))):
        spatial, temporal, count = soa[(res.start, res.end)]
        for oid_str, (sp, tp, ratio) in res.stats.items():
            i = interner.intern(oid_str)
            assert sp == pytest.approx(float(spatial[i]), rel=1e-12)
            assert tp == int(temporal[i])


def test_tknn_soa_matches_object_path(rng):
    ts, xs, ys, oids = _stream(rng, 2500)
    interner = Interner()
    dense = np.array([interner.intern(str(int(o))) for o in oids], np.int32)
    q = Point(x=5.0, y=5.0)
    r, k = 4.0, 4

    soa = {
        (s, e): (list(map(int, o)), [float(d) for d in dd])
        for s, e, o, dd, nv in TKNNQuery(W10, GRID).run_soa(
            _chunks(ts, xs, ys, dense), q, r, k, num_segments=64
        )
    }
    for res in TKNNQuery(W10, GRID).run(iter(_points(ts, xs, ys, oids)), q, r, k):
        got_o, got_d = soa[(res.start, res.end)]
        want = [(interner.intern(oid), d) for oid, d, _ in res.neighbors]
        assert got_o == [o for o, _ in want]
        for gd, (_, wd) in zip(got_d, want):
            assert gd == pytest.approx(wd, rel=1e-9)


@pytest.mark.slow
def test_join_soa_matches_object_path(rng):
    lts, lxs, lys, loids = _stream(rng, 2000)
    rng2 = np.random.default_rng(9)
    rts, rxs, rys, roids = _stream(rng2, 1500)
    r = 0.6

    soa_pairs = {}
    op = PointPointJoinQuery(W10, GRID)
    for s, e, li, ri, dd, count, overflow in op.run_soa(
        _chunks(lts, lxs, lys, loids), _chunks(rts, rxs, rys, roids), r
    ):
        assert overflow == 0
        # Map window-array indices back to (ts, x, y) identities.
        lsel = (lts >= s) & (lts < e)
        rsel = (rts >= s) & (rts < e)
        lt, lx_, ly_ = lts[lsel], lxs[lsel], lys[lsel]
        rt, rx_, ry_ = rts[rsel], rxs[rsel], rys[rsel]
        got = set()
        for a, b, d in zip(li, ri, dd):
            if a < 0:
                continue
            got.add((int(lt[a]), round(float(lx_[a]), 9), int(rt[b]),
                     round(float(rx_[b]), 9), round(float(d), 6)))
        soa_pairs[(s, e)] = got

    obj = PointPointJoinQuery(W10, GRID)
    left = _points(lts, lxs, lys, loids)
    right = [
        Point(obj_id=f"q{int(o)}", timestamp=int(t), x=float(x), y=float(y))
        for t, x, y, o in zip(rts, rxs, rys, roids)
    ]
    for res in obj.run(iter(left), iter(right), r):
        want = {
            (a.timestamp, round(a.x, 9), b.timestamp, round(b.x, 9),
             round(d, 6))
            for a, b, d in res.pairs
        }
        if (res.start, res.end) in soa_pairs:
            assert soa_pairs[(res.start, res.end)] == want
        else:
            assert not want


def test_tjoin_device_dedup_matches_bruteforce(rng):
    """TJoinQuery's pair set and min distances == brute force over all
    point pairs (the device segment-min dedup replaces the reference's
    dedup map AND round 1's host dict loop)."""
    lts, lxs, lys, loids = _stream(rng, 800, n_obj=5)
    rng2 = np.random.default_rng(4)
    rts, rxs, rys, roids = _stream(rng2, 700, n_obj=4)
    r = 0.8
    left = _points(lts, lxs, lys, loids)
    right = [
        Point(obj_id=f"q{int(o)}", timestamp=int(t), x=float(x), y=float(y))
        for t, x, y, o in zip(rts, rxs, rys, roids)
    ]

    results = list(TJoinQuery(W10, GRID).run(iter(left), iter(right), r))
    for res in results:
        got = {(a.obj_id, b.obj_id): d for a, b, d in res.pairs}
        # Brute force within this window.
        want = {}
        for a in left:
            if not (res.start <= a.timestamp < res.end):
                continue
            for b in right:
                if not (res.start <= b.timestamp < res.end):
                    continue
                d = float(np.hypot(a.x - b.x, a.y - b.y))
                if d <= r:
                    key = (a.obj_id, b.obj_id)
                    if key not in want or d < want[key]:
                        want[key] = d
        assert got.keys() == want.keys()
        for kk in got:
            assert got[kk] == pytest.approx(want[kk], rel=1e-9)
    assert any(res.pairs for res in results)


@pytest.mark.slow
def test_tjoin_run_soa_matches_object_path(rng):
    """run_soa's raw (left_oid, right_oid, min_dist) arrays == the object
    path's dedup'd pair set per window, through sliding windows — the
    round-2 gap: tJoin was the one trajectory operator with no SoA path."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10,
                              slide_step=5)
    lts, lxs, lys, loids = _stream(rng, 6_000, n_obj=8)
    rng2 = np.random.default_rng(5)
    rts, rxs, rys, roids = _stream(rng2, 5_000, n_obj=6)
    r = 0.3
    left = _points(lts, lxs, lys, loids)
    right = _points(rts, rxs, rys, roids)

    obj = {}
    for res in TJoinQuery(conf, GRID, cap=256).run(iter(left), iter(right), r):
        obj[(res.start, res.end)] = {
            (a.obj_id, b.obj_id, round(d, 9)) for a, b, d in res.pairs
        }

    soa = {}
    for start, end, lo, ro, dd, count, overflow in TJoinQuery(
        conf, GRID, cap=256
    ).run_soa(
        _chunks(lts, lxs, lys, loids), _chunks(rts, rxs, rys, roids), r,
        num_segments=16,
    ):
        assert overflow == 0
        soa[(start, end)] = {
            (str(int(a)), str(int(b)), round(float(d), 9))
            for a, b, d in zip(lo, ro, dd)
        }
    # The object path skips windows where one side is empty only if BOTH
    # generators agree; compare on the union of spans with pairs.
    spans = set(obj) | set(soa)
    for span in spans:
        assert obj.get(span, set()) == soa.get(span, set()), span
    assert any(soa.values())


def test_traj_stats_sliding_matches_operator(rng):
    """Pane-decomposed tStats (10s/2s, 5x overlap) == the operator's
    per-window recompute, including start-boundary segment truncation."""
    from spatialflink_tpu.streams.panes import traj_stats_sliding

    n = 4000
    ts = np.sort(rng.integers(0, 30_000, n)).astype(np.int64)
    xy = rng.uniform(0, 10, (n, 2))
    oids = rng.integers(0, 8, n).astype(np.int64)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=2)

    interner = Interner()
    dense = np.array([interner.intern(str(int(o))) for o in oids], np.int64)
    res = traj_stats_sliding(ts, xy, dense, 8, 10_000, 2_000)
    by_start = {int(s): i for i, s in enumerate(res.starts)}

    pts = _points(ts, xy[:, 0], xy[:, 1], oids)
    checked = 0
    for r in TStatsQuery(conf, GRID).run(iter(pts)):
        i = by_start[r.start]
        for oid_str, (sp, tp, ratio) in r.stats.items():
            k = interner.intern(oid_str)
            assert sp == pytest.approx(float(res.spatial[i, k]), rel=1e-9)
            assert tp == int(res.temporal[i, k])
            checked += 1
    assert checked > 100


def test_traj_stats_sliding_extreme_overlap(rng):
    """The 10s/10ms reference overlap (1000 panes/window): sparse sanity —
    a single two-point trajectory counts exactly in the windows holding
    both points."""
    from spatialflink_tpu.streams.panes import traj_stats_sliding

    ts = np.array([5_000, 5_600], np.int64)
    xy = np.array([[1.0, 1.0], [4.0, 5.0]])
    res = traj_stats_sliding(ts, xy, np.zeros(2, np.int64), 1, 10_000, 10)
    has_seg = res.spatial[:, 0] > 0
    # Windows with the segment: start in (ts0 - size, ts0] → start ≤ 5000
    # and start > 5600 - 10000 → all fired windows with start ≤ 5000 that
    # still contain 5600.
    starts = res.starts[has_seg]
    assert starts.min() >= 5_600 - 10_000 + 10
    assert starts.max() == 5_000
    np.testing.assert_allclose(res.spatial[has_seg, 0], 5.0)
    # Windows containing only one endpoint: no segment.
    one_pt = (res.count[:, 0] == 1)
    assert (res.spatial[one_pt, 0] == 0).all()


def test_trange_soa_matches_object_path(rng):
    """TRange SoA fast path == object path hit sets (dense-id space)."""
    from spatialflink_tpu.models.objects import Point, Polygon
    from spatialflink_tpu.operators import (
        QueryConfiguration, QueryType, TRangeQuery,
    )

    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    n = 2500
    ts = np.sort(rng.integers(0, 30_000, n)).astype(np.int64)
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = rng.integers(0, 40, n).astype(np.int32)
    polys = [Polygon(rings=[np.array(
        [[3, 3], [4.2, 3], [4.2, 4.2], [3, 4.2], [3, 3]], float)])]

    pts = [Point(obj_id=str(o), timestamp=int(t), x=float(x), y=float(y))
           for t, x, y, o in zip(ts, xs, ys, oids)]
    op = TRangeQuery(conf, GRID)
    obj_res = {
        (r.start, r.end): sorted(int(t.obj_id) for t in r.trajectories)
        for r in op.run(iter(pts), polys)
    }
    bounds = np.linspace(0, n, 5).astype(int)
    chunks = [
        {"ts": ts[a:b], "x": xs[a:b], "y": ys[a:b], "oid": oids[a:b]}
        for a, b in zip(bounds[:-1], bounds[1:])
    ]
    soa_res = {
        (s, e): sorted(int(o) for o in hit_oids)
        for s, e, hit_oids, cnt in TRangeQuery(conf, GRID).run_soa(
            iter(chunks), polys, num_segments=64
        )
    }
    assert obj_res == soa_res and obj_res


def test_taggregate_soa_matches_object_path(rng):
    """TAggregate SoA path == object path per-cell aggregates (ALL mode
    compares dense-id keys against interner-mapped keys)."""
    from spatialflink_tpu.models.objects import Point
    from spatialflink_tpu.operators import (
        QueryConfiguration, QueryType, TAggregateQuery,
    )

    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)
    n = 2000
    ts = np.sort(rng.integers(0, 30_000, n)).astype(np.int64)
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = rng.integers(0, 25, n).astype(np.int32)
    pts = [Point(obj_id=str(o), timestamp=int(t), x=float(x), y=float(y))
           for t, x, y, o in zip(ts, xs, ys, oids)]

    for agg in ("SUM", "ALL"):
        obj_res = [
            (r.start, r.end, {
                c: (cnt, {str(k): v for k, v in d.items()})
                for c, (cnt, d) in r.cells.items()
            })
            for r in TAggregateQuery(conf, GRID, aggregate=agg).run(iter(pts))
        ]
        bounds = np.linspace(0, n, 4).astype(int)
        chunks = [
            {"ts": ts[a:b], "x": xs[a:b], "y": ys[a:b], "oid": oids[a:b]}
            for a, b in zip(bounds[:-1], bounds[1:])
        ]
        soa_res = [
            (r.start, r.end, {
                c: (cnt, {str(k): v for k, v in d.items()})
                for c, (cnt, d) in r.cells.items()
            })
            for r in TAggregateQuery(conf, GRID, aggregate=agg).run_soa(
                iter(chunks))
        ]
        assert obj_res == soa_res and obj_res, agg


def test_tfilter_soa_matches_object_path(rng):
    from spatialflink_tpu.models.objects import Point
    from spatialflink_tpu.operators import (
        QueryConfiguration, QueryType, TFilterQuery,
    )

    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    n = 1500
    ts = np.sort(rng.integers(0, 25_000, n)).astype(np.int64)
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = rng.integers(0, 20, n).astype(np.int32)
    pts = [Point(obj_id=str(o), timestamp=int(t), x=float(x), y=float(y))
           for t, x, y, o in zip(ts, xs, ys, oids)]
    wanted = [3, 7, 11]

    obj_res = {}
    for r in TFilterQuery(conf, GRID).run(iter(pts), [str(w) for w in wanted]):
        obj_res[(r.start, r.end)] = {
            t.obj_id: [tuple(c) for c in t.coords] for t in r.trajectories
        }
    bounds = np.linspace(0, n, 4).astype(int)
    chunks = [
        {"ts": ts[a:b], "x": xs[a:b], "y": ys[a:b], "oid": oids[a:b]}
        for a, b in zip(bounds[:-1], bounds[1:])
    ]
    soa_res = {}
    for s, e, o, t, xy, cnt in TFilterQuery(conf, GRID).run_soa(
        iter(chunks), wanted
    ):
        trajs = {}
        for oid_val in np.unique(o):
            m = o == oid_val
            trajs[str(int(oid_val))] = [tuple(c) for c in xy[m]]
        soa_res[(s, e)] = trajs
    assert obj_res == soa_res and obj_res
