"""Approximate-query mode parity (QueryConfiguration.approximate_query).

The reference honors ``approximateQuery`` in all three operator families:

- Point-ordinary joins emit ALL grid candidates with no distance filter
  (join/PointPointJoinQuery.java:164-166, PointPolygonJoinQuery.java:131).
- Geometry-ordinary joins use bbox min-distances instead of exact JTS
  distances (join/LineStringLineStringJoinQuery.java:173-180,
  PolygonPointJoinQuery.java getPointPolygonBBoxMinEuclideanDistance).
- kNN variants swap the ranking distance for the bbox distance
  (knn/PointPolygonKNNQuery.java:132-146,
  knn/LineStringLineStringKNNQuery.java:95-110); PointPoint ignores the
  flag; PointLineString's "approximate" calls the EXACT point-to-segments
  distance (DistanceFunctions.java:87-90) — quirk preserved.

Each test checks the operator output against an independent numpy oracle
of the reference semantics.
"""

import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import LineString, Point, Polygon
from spatialflink_tpu.operators import QueryConfiguration, QueryType
from spatialflink_tpu.operators.join_query import (
    LineStringLineStringJoinQuery,
    PointPointJoinQuery,
    PointPolygonJoinQuery,
    PolygonPointJoinQuery,
)
from spatialflink_tpu.operators.knn_query import (
    PointLineStringKNNQuery,
    PointPolygonKNNQuery,
    PolygonPolygonKNNQuery,
)

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)


@pytest.fixture
def rng():
    return np.random.default_rng(77)


def _conf(**kw):
    kw.setdefault("window_size", 30)
    kw.setdefault("slide_step", 30)
    return QueryConfiguration(QueryType.WindowBased, approximate_query=True, **kw)


def _points(rng, n, t_span=29_000):
    xy = rng.uniform(0, 10, (n, 2))
    return [
        Point(obj_id=f"p{i}", timestamp=int(i * t_span / n),
              x=float(xy[i, 0]), y=float(xy[i, 1]))
        for i in range(n)
    ]


def _square(cx, cy, r):
    return np.array([
        [cx - r, cy - r], [cx + r, cy - r], [cx + r, cy + r],
        [cx - r, cy + r], [cx - r, cy - r],
    ])


def _polygons(rng, m, t_span=29_000, size=0.3):
    out = []
    for i in range(m):
        cx, cy = rng.uniform(1.0, 9.0, 2)
        out.append(Polygon(
            obj_id=f"g{i}", timestamp=int(i * t_span / m),
            rings=[_square(float(cx), float(cy), size)],
        ))
    return out


def _linestrings(rng, m, t_span=29_000, prefix="l"):
    out = []
    for i in range(m):
        x0, y0 = rng.uniform(1.0, 8.5, 2)
        pts = np.stack([
            np.linspace(x0, x0 + 0.9, 5),
            y0 + 0.3 * np.sin(np.linspace(0.0, 3.0, 5)),
        ], axis=1)
        out.append(LineString(obj_id=f"{prefix}{i}",
                              timestamp=int(i * t_span / m), coords=pts))
    return out


def _bbox_point_dist(px, py, bb):
    dx = max(max(bb[0] - px, 0.0), px - bb[2])
    dy = max(max(bb[1] - py, 0.0), py - bb[3])
    return float(np.hypot(dx, dy))


def _bbox_bbox_dist(a, b):
    dx = max(max(b[0] - a[2], 0.0), a[0] - b[2])
    dy = max(max(b[1] - a[3], 0.0), a[1] - b[3])
    return float(np.hypot(dx, dy))


def _cell_idx(p):
    xi = int(np.floor((p.x - GRID.min_x) / GRID.cell_length))
    yi = int(np.floor((p.y - GRID.min_y) / GRID.cell_length))
    return xi, yi


# ---------------------------------------------------------------- joins


def test_pointpoint_join_approx_emits_all_grid_candidates(rng):
    """Approximate PointPoint join = every pair whose cells are within
    the candidate-layer Chebyshev square, regardless of distance."""
    radius = 0.7
    L = GRID.candidate_layers(radius)
    left = _points(rng, 150)
    right = [
        Point(obj_id=f"q{i}", timestamp=p.timestamp, x=p.x, y=p.y)
        for i, p in enumerate(_points(rng, 60))
    ]
    res = list(PointPointJoinQuery(_conf(), GRID).run(
        iter(left), iter(right), radius))
    got = {(a.obj_id, b.obj_id) for r in res for a, b, _ in r.pairs}

    expect = set()
    for a in left:
        ax, ay = _cell_idx(a)
        for b in right:
            bx, by = _cell_idx(b)
            if max(abs(ax - bx), abs(ay - by)) <= L:
                expect.add((a.obj_id, b.obj_id))
    assert got == expect
    # sanity: approximate must be a strict superset of the exact join
    exact = {
        (a.obj_id, b.obj_id)
        for a in left for b in right
        if np.hypot(a.x - b.x, a.y - b.y) <= radius
    }
    assert exact < got


def test_pointpoint_join_naive_approx_all_pairs(rng):
    """RealTimeNaive approximate = every pair in the window
    (PointPointJoinQuery.java:216, no grid, no filter)."""
    conf = QueryConfiguration(
        QueryType.RealTimeNaive, realtime_batch_ms=30_000,
        approximate_query=True,
    )
    left = _points(rng, 40)
    right = _points(rng, 15)
    right = [Point(obj_id=f"q{i}", timestamp=p.timestamp, x=p.x, y=p.y)
             for i, p in enumerate(right)]
    res = list(PointPointJoinQuery(conf, GRID).run(
        iter(left), iter(right), 0.1))
    got = {(a.obj_id, b.obj_id) for r in res for a, b, _ in r.pairs}
    assert len(got) == 40 * 15


def test_point_polygon_join_approx_emit_all_cells(rng):
    """Approximate point⋈polygon = point's cell inside the polygon's
    layer-expanded bbox-cell rectangle (reference replication set)."""
    radius = 0.6
    L = GRID.candidate_layers(radius)
    pts = _points(rng, 200)
    polys = _polygons(rng, 12)
    res = list(PointPolygonJoinQuery(_conf(), GRID).run(
        iter(pts), iter(polys), radius))
    got = {(a.obj_id, b.obj_id) for r in res for a, b, _ in r.pairs}

    expect = set()
    for g in polys:
        x0, y0, x1, y1 = g.bbox()
        cx0 = np.floor((x0 - GRID.min_x) / GRID.cell_length) - L
        cy0 = np.floor((y0 - GRID.min_y) / GRID.cell_length) - L
        cx1 = np.floor((x1 - GRID.min_x) / GRID.cell_length) + L
        cy1 = np.floor((y1 - GRID.min_y) / GRID.cell_length) + L
        for p in pts:
            xi, yi = _cell_idx(p)
            if cx0 <= xi <= cx1 and cy0 <= yi <= cy1:
                expect.add((p.obj_id, g.obj_id))
    assert got == expect
    exact_pairs = {
        (a.obj_id, b.obj_id)
        for r in list(PointPolygonJoinQuery(
            QueryConfiguration(QueryType.WindowBased, window_size=30,
                               slide_step=30), GRID,
        ).run(iter(pts), iter(polys), radius))
        for a, b, _ in r.pairs
    }
    assert exact_pairs <= got


def test_polygon_point_join_approx_bbox_distance(rng):
    """Approximate polygon-ordinary ⋈ point query = point-to-polygon-BBOX
    min distance ≤ r (NOT emit-all)."""
    radius = 0.8
    polys = _polygons(rng, 15)
    pts = _points(rng, 80)
    res = list(PolygonPointJoinQuery(_conf(), GRID).run(
        iter(polys), iter(pts), radius))
    got = {(a.obj_id, b.obj_id): d for r in res for a, b, d in r.pairs}

    expect = {}
    for g in polys:
        bb = g.bbox()
        for p in pts:
            d = _bbox_point_dist(p.x, p.y, bb)
            if d <= radius:
                expect[(g.obj_id, p.obj_id)] = d
    assert set(got) == set(expect)
    for k, d in expect.items():
        assert got[k] == pytest.approx(d, abs=1e-9)


def test_linestring_join_approx_bbox_bbox(rng):
    """Approximate geometry⋈geometry = bbox↔bbox min distance ≤ r."""
    radius = 0.5
    a = _linestrings(rng, 25, prefix="a")
    b = _linestrings(rng, 18, prefix="b")
    res = list(LineStringLineStringJoinQuery(_conf(), GRID).run(
        iter(a), iter(b), radius))
    got = {(x.obj_id, y.obj_id): d for r in res for x, y, d in r.pairs}

    expect = {}
    for la in a:
        for lb in b:
            d = _bbox_bbox_dist(la.bbox(), lb.bbox())
            if d <= radius:
                expect[(la.obj_id, lb.obj_id)] = d
    assert set(got) == set(expect)
    for k, d in expect.items():
        assert got[k] == pytest.approx(d, abs=1e-9)


# ---------------------------------------------------------------- kNN


def test_knn_point_polygon_approx_bbox_distance(rng):
    """Approximate PointPolygon kNN ranks by point→query-bbox distance
    (0 inside the bbox)."""
    radius, k = 4.0, 5
    pts = _points(rng, 120)
    query = Polygon(rings=[np.array(
        [[4.0, 4.0], [6.0, 4.2], [5.0, 6.5], [4.0, 4.0]])])
    res = list(PointPolygonKNNQuery(_conf(window_size=30, slide_step=30),
                                    GRID).run(iter(pts), query, radius, k))
    first = res[0]
    bb = query.bbox()
    win_pts = [p for p in pts if first.start <= p.timestamp < first.end]
    # oracle: per obj_id min bbox distance, then k smallest within radius
    best = {}
    for p in win_pts:
        d = _bbox_point_dist(p.x, p.y, bb)
        if d <= radius:
            best[p.obj_id] = min(best.get(p.obj_id, np.inf), d)
    expect = sorted(best.items(), key=lambda kv: kv[1])[:k]
    got = [(oid, d) for oid, d, _ in first.neighbors]
    assert [o for o, _ in got] == [o for o, _ in expect]
    for (_, dg), (_, de) in zip(got, expect):
        assert dg == pytest.approx(de, abs=1e-9)


def test_knn_point_linestring_approx_equals_exact(rng):
    """Reference quirk: PointLineString's approximate branch calls the
    EXACT point-to-segments distance, so the flag changes nothing."""
    pts = _points(rng, 100)
    ls = LineString(coords=np.array([[2.0, 2.0], [5.0, 3.0], [8.0, 2.5]]))
    kw = dict(window_size=30, slide_step=30)
    exact = list(PointLineStringKNNQuery(
        QueryConfiguration(QueryType.WindowBased, **kw), GRID,
    ).run(iter(pts), ls, 3.0, 4))
    approx = list(PointLineStringKNNQuery(_conf(**kw), GRID).run(
        iter(pts), ls, 3.0, 4))
    assert [
        [(o, d) for o, d, _ in r.neighbors] for r in exact
    ] == [
        [(o, d) for o, d, _ in r.neighbors] for r in approx
    ]


def test_knn_geometry_stream_approx_bbox_bbox(rng):
    """Approximate geometry-stream kNN ranks by bbox↔bbox distance."""
    radius, k = 5.0, 4
    polys = _polygons(rng, 40)
    query = Polygon(rings=[_square(5.0, 5.0, 0.8)])
    res = list(PolygonPolygonKNNQuery(_conf(), GRID).run(
        iter(polys), query, radius, k))
    first = res[0]
    qb = query.bbox()
    wins = [g for g in polys if first.start <= g.timestamp < first.end]
    best = {}
    for g in wins:
        d = _bbox_bbox_dist(g.bbox(), qb)
        if d <= radius:
            best[g.obj_id] = min(best.get(g.obj_id, np.inf), d)
    expect = sorted(best.items(), key=lambda kv: kv[1])[:k]
    got = [(oid, d) for oid, d, _ in first.neighbors]
    assert [o for o, _ in got] == [o for o, _ in expect]
    for (_, dg), (_, de) in zip(got, expect):
        assert dg == pytest.approx(de, abs=1e-9)


def test_pane_knn_polygon_approx_matches_run(rng):
    """query_panes must honor approximate mode identically to run()."""
    pts = _points(rng, 150, t_span=25_000)
    query = Polygon(rings=[np.array(
        [[3.0, 3.0], [7.0, 3.5], [5.0, 7.0], [3.0, 3.0]])])
    kw = dict(window_size=10, slide_step=5)
    op_r = PointPolygonKNNQuery(_conf(**kw), GRID)
    op_p = PointPolygonKNNQuery(_conf(**kw), GRID)
    runs = list(op_r.run(iter(pts), query, 4.0, 3))
    panes = list(op_p.query_panes(iter(pts), query, 4.0, 3))
    key = lambda rs: [
        (r.start, r.end, [(o, round(d, 12)) for o, d, _ in r.neighbors])
        for r in rs
    ]
    assert key(runs) == key(panes)


def test_knn_soa_geometry_approx_matches_run(rng):
    """run_soa must honor approximate mode (bbox kernel) identically."""
    polys = _polygons(rng, 40)
    query = Polygon(rings=[_square(5.0, 5.0, 0.8)])
    op = PolygonPolygonKNNQuery(_conf(), GRID)
    runs = list(op.run(iter(polys), query, 5.0, 4))

    op2 = PolygonPolygonKNNQuery(_conf(), GRID)
    # one chunk of ragged SoA data; intern ids to match op2's interner
    oid = op2.interner.intern_many(g.obj_id for g in polys)
    lengths = np.array([len(g.rings[0]) for g in polys])
    verts = np.concatenate([g.rings[0] for g in polys], axis=0)
    chunk = {
        "ts": np.array([g.timestamp for g in polys], np.int64),
        "oid": oid,
        "lengths": lengths,
        "verts": verts,
        "edge_valid": np.concatenate(
            [np.ones(len(g.rings[0]) - 1, bool) for g in polys]),
    }
    soa = list(op2.run_soa(iter([chunk]), query, 5.0, 4, num_segments=64))
    assert len(soa) == len(runs)
    for r, (start, end, segs, dists, nv) in zip(runs, soa):
        assert (r.start, r.end) == (start, end)
        got = [(op2.interner.lookup(int(s)), float(d))
               for s, d in zip(segs, dists)]
        expect = [(o, d) for o, d, _ in r.neighbors]
        assert [o for o, _ in got] == [o for o, _ in expect]
        for (_, dg), (_, de) in zip(got, expect):
            assert dg == pytest.approx(de, abs=1e-9)


# ------------------------------------------------------- 8-device mesh


@pytest.fixture
def mesh():
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8])
    return Mesh(devs.reshape(8), ("data",))


def _pair_key(results):
    return sorted(
        (r.start, a.obj_id, b.obj_id, round(float(d), 9))
        for r in results for a, b, d in r.pairs
    )


def test_point_polygon_join_approx_mesh_matches_single(rng, mesh):
    """CLAUDE.md sharding invariant: the emit-all approx path must be
    bit-identical on the 8-device mesh (cell-space coords shard over
    data like any point side)."""
    pts = _points(rng, 160)
    polys = _polygons(rng, 10)

    def run(m):
        return list(PointPolygonJoinQuery(_conf(), GRID, mesh=m).run(
            iter(list(pts)), iter(list(polys)), 0.6))

    assert _pair_key(run(None)) == _pair_key(run(mesh))
    assert _pair_key(run(mesh))  # non-empty


def test_linestring_join_approx_mesh_matches_single(rng, mesh):
    a = _linestrings(rng, 24, prefix="a")
    b = _linestrings(rng, 16, prefix="b")

    def run(m):
        return list(LineStringLineStringJoinQuery(_conf(), GRID, mesh=m).run(
            iter(list(a)), iter(list(b)), 0.5))

    assert _pair_key(run(None)) == _pair_key(run(mesh))


def test_knn_geometry_approx_mesh_matches_single(rng, mesh):
    polys = _polygons(rng, 40)
    query = Polygon(rings=[_square(5.0, 5.0, 0.8)])

    def run(m):
        return list(PolygonPolygonKNNQuery(_conf(), GRID, mesh=m).run(
            iter(list(polys)), query, 5.0, 4))

    key = lambda rs: [
        (r.start, r.end, [(o, round(float(d), 12)) for o, d, _ in r.neighbors])
        for r in rs
    ]
    assert key(run(None)) == key(run(mesh))
    assert any(r.neighbors for r in run(mesh))


def test_pointpoint_join_approx_mesh_matches_single(rng, mesh):
    pts = _points(rng, 120)
    qpts = [Point(obj_id=f"q{i}", timestamp=p.timestamp, x=p.x, y=p.y)
            for i, p in enumerate(_points(rng, 40))]

    def run(m):
        return list(PointPointJoinQuery(_conf(), GRID, mesh=m).run(
            iter(list(pts)), iter(list(qpts)), 0.5))

    assert _pair_key(run(None)) == _pair_key(run(mesh))
