"""hotpath true positives: import-time jnp dispatch + in-kernel wall clock."""

import time

import jax.numpy as jnp
from jax.numpy import full
from time import perf_counter as pc

PAD = jnp.zeros((8,))          # module-level jax.numpy call
FILL = full((2,), 0.0)         # direct-name jax.numpy call


def kernel(x, pad=jnp.ones(4)):  # default executes at module scope
    t0 = time.time()             # wall clock inside an ops/ function
    t1 = pc()                    # aliased wall clock
    return x + pad, t0, t1
