"""Deliberate violation corpus (lock-discipline): the three
hazard-under-lock classes — a cross-module telemetry emit, blocking
work, and a user callback, each inside a `with self._lock:` region."""

import threading
import time


class Busy:
    def __init__(self, tel):
        self._lock = threading.Lock()
        self.tel = tel
        self.done_callback = None

    def flush(self):
        with self._lock:
            self.tel.emit_instant("busy_flush")  # emit under lock

    def wait(self):
        with self._lock:
            time.sleep(0.01)  # blocking under lock

    def snap(self):
        with self._lock:
            self.done_callback()  # arbitrary user code under lock
