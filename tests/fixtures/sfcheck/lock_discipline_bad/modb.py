"""Deliberate violation corpus (lock-discipline): half B of the seeded
lock-order cycle (see moda.py). Never imported — parsed only."""

import threading

import moda

_LOCK_B = threading.Lock()


def bump():
    with _LOCK_B:
        return 2


def pong():
    with _LOCK_B:
        moda.ding()  # B → A: the opposite order — cycle
