"""Deliberate violation corpus (lock-discipline): half A of a seeded
two-module lock-order cycle — `ping` acquires modb's lock while holding
`_LOCK_A`; modb.pong acquires this one while holding `_LOCK_B`."""

import threading

import modb

_LOCK_A = threading.Lock()


def ping():
    with _LOCK_A:
        modb.bump()  # A → B


def ding():
    with _LOCK_A:
        return 1
