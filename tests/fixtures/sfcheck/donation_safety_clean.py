"""Clean twin of donation_safety_bad.py: the rebind idiom and
no-reuse patterns that make donation safe."""

import jax
import jax.numpy as jnp

step = jax.jit(lambda x: x + 1, donate_argnums=(0,))
plain = jax.jit(lambda x: x + 1)  # no donation: reuse is fine


def rebind_loop(x):
    for _ in range(3):
        x = step(x)  # canonical double-buffer idiom: donate + rebind
    return x


def no_reuse(x):
    y = step(x)
    return y * 2  # x never touched again


def fresh_each_iter(chunks):
    out = []
    for c in chunks:
        buf = jnp.asarray(c)  # rebound inside the loop every iteration
        out.append(step(buf))
    return out


def non_donating(x):
    y = plain(x)
    return x + y  # fine: plain jit call keeps x alive
