"""Fixture: the single-device counterpart kernel."""


def base_kernel(x):
    return x * 2
