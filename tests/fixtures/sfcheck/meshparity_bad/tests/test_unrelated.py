"""Fixture test file that references NEITHER parallel kernel — present
so the mesh-parity test-reference half is evaluated (a project with no
test files skips it as vacuous)."""

from ops.single import base_kernel


def test_base_kernel():
    assert base_kernel(2) == 4
