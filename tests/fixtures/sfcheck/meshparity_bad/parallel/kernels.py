"""Fixture mini-repo: parallel/ kernels violating the mesh-parity
contract (analyzed with --project-root at the mini-repo root)."""

from ops.single import base_kernel


def sharded_untested(mesh, x):
    # counterpart resolves (base_kernel in ops/), but NO test names this
    # kernel -> one finding
    return base_kernel(x)


def sharded_orphan(mesh, x):
    # no ops/ counterpart AND no test -> two findings
    return x + 1


def _private_helper(mesh, x):
    # underscore-private: exempt
    return x


def mesh_builder(shape):
    # not a kernel (no mesh-first signature): exempt
    return shape
