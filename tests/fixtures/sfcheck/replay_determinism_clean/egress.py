"""Fixture mini-repo: the deterministic twins of every
replay_determinism_bad violation."""


class FileSink:
    def commit(self, rows):
        # sorted() launders set order into a data-determined order
        for oid in sorted({r.oid for r in rows}):
            self.fh.write(f"{oid}\n")
        # event time (the watermark clock), not wall time
        self.fh.write(f"footer {self.watermark}\n")


def shard_state(rng):
    # caller-supplied seeded generator, checkpointed with the operator
    return {"salt": rng.random()}
