"""fixed-shape clean: the repo's mask-don't-compact idioms."""

import jax.numpy as jnp


def compact(x, mask, budget: int):
    n = x.shape[0]
    idx = jnp.nonzero(mask, size=budget, fill_value=n)[0]  # fixed shape
    overflow = jnp.maximum(jnp.sum(mask) - budget, 0)      # count, don't grow
    sel = jnp.where(mask, x, 0.0)                          # 3-arg select
    uniq = jnp.unique(x, size=budget, fill_value=-1)       # fixed shape
    capped = x.at[x > 1.0].set(1.0)    # .at masked update PRESERVES shape
    return idx, overflow, sel, uniq, capped
