"""Deliberate donation-safety violations (fixture): reads of a local
after it was passed at a donate_argnums position — the device buffer is
deleted at dispatch."""

import jax

step = jax.jit(lambda x: x + 1, donate_argnums=(0,))


def use_after_donate(x):
    y = step(x)
    return x + y  # BAD: x's buffer was donated to step


def inline_form(x):
    y = jax.jit(lambda a: a * 2, donate_argnums=(0,))(x)
    return x - y  # BAD: donated at the inline jit call


def loop_no_rebind(xs, x):
    acc = None
    for _ in range(3):
        acc = step(x)  # BAD: x re-donated (and re-read) every iteration
    return acc


def local_wrapper(x):
    prog = jax.jit(lambda a: a - 1, donate_argnums=(0,))
    out = prog(x)
    return x, out  # BAD: x read after donation to the local wrapper
