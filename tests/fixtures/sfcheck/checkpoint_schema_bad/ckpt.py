"""Fixture mini-repo: checkpoint publish/restore pairs violating every
checkpoint-schema rule (analyzed with --project-root at this root)."""


class WindowOperator:
    def state(self):
        payload = {"carry": self.carry, "watermark": self.wm}
        if self.compaction is not None:
            # conditionally published: old checkpoints lack the key
            payload["compaction_rung"] = self.compaction
        return payload

    def restore(self, state):
        self.carry = state["carry"]
        self.wm = state["watermark"]
        # rule 3: conditionally-published key, bare unconditional read —
        # a pre-compaction checkpoint KeyErrors here mid-resume
        self.compaction = state["compaction_rung"]
        # rule 1: no publisher ever writes this key
        self.retries = state["retry_budget"]


class DroppedStateOperator:
    def state(self):
        # rule 2: "interner" is checkpointed but never read back —
        # silently dropped on every resume
        return {"carry": self.carry, "interner": self.table}

    def restore(self, state):
        self.carry = state["carry"]
