"""Deliberate violation corpus (module-singleton): a module holding an
install-slot global AND a module-level singleton, runnable via
``python -m pkg.state`` — with a __main__ guard that does NOT delegate
to the canonical import. Running it would create a second module
instance whose `install()` is invisible to canonically-importing hooks
(the overload --smoke dual-instance trap)."""

import sys


class Registry:
    def __init__(self):
        self.items = []


registry = Registry()

_slot = None


def install(ctrl):
    global _slot
    _slot = ctrl
    return ctrl


def main():
    install(object())
    return 0


if __name__ == "__main__":
    sys.exit(main())
