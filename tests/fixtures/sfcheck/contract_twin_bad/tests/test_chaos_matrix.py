"""Deliberate violation corpus (contract-twin): the matrix misses a
registered point and carries a dead leg."""

MATRIX = {
    "p.one": None,
    "p.ghost": None,  # matches no registered injection point
}
