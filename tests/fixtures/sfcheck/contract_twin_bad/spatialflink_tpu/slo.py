"""Deliberate violation corpus (contract-twin): the live SLO spec —
one field its mirror lacks, and a drifted version pin."""

SLO_VERSION = 2


class SloSpec:
    name: str = "default"
    lag_ms: float = 0.0
    extra_live_only: int = 0
