"""Deliberate violation corpus (contract-twin): the live SLO spec —
two fields its mirror lacks (one of them an e2e latency ceiling), and
a drifted version pin."""

SLO_VERSION = 2


class SloSpec:
    name: str = "default"
    lag_ms: float = 0.0
    extra_live_only: int = 0
    e2e_p99_ms: float = 0.0  # lineage ceiling the mirror never learned
