"""Deliberate violation corpus (contract-twin): an injection point with
no chaos-matrix leg."""

INJECTION_POINTS = {
    "p.one": "covered point",
    "p.two": "registered but unmatrixed — unrehearsed failure mode",
}
