"""Deliberate violation corpus (contract-twin): an unregistered event
name and a dynamic (uncheckable) event-name head among good emits."""


class Tel:
    def emit_instant(self, name, **args):
        return name


def produce(tel, point):
    tel.emit_instant("good_event")
    tel.emit_instant("typo_event")  # absent from the consumer registry
    tel.emit_instant(f"used_prefix:{point}")
    kind = "x"
    tel.emit_instant(f"{kind}:{point}")  # no literal head: uncheckable
