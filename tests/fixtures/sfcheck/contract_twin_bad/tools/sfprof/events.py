"""Deliberate violation corpus (contract-twin): the consumer registry —
one entry nothing emits."""

INSTANT_EVENTS = frozenset({"good_event", "never_emitted"})

INSTANT_EVENT_PREFIXES = ("used_prefix:",)
