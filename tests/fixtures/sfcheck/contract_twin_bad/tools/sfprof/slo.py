"""Deliberate violation corpus (contract-twin): the post-hoc mirror —
stale version, one missing field, one field the live side never had."""

SLO_VERSION = 1

SPEC_KEYS = ("name", "lag_ms", "mirror_only")
