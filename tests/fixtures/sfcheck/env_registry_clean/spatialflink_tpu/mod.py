"""Clean twin (env-registry): registered reads only."""

import os


def read_config():
    a = os.environ.get("SFT_KNOWN")
    b = os.environ.get("SFT_ARMED_PLAN")
    return a, b
