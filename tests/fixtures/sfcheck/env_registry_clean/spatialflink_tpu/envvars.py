"""Clean twin (env-registry): every read registered, every entry read,
and the gate derives its scrub from the registry."""

HAZARD_CLASSES = ("armed", "capture", "tuning", "internal")

ENV_VARS = {
    "SFT_KNOWN": {
        "owner": "spatialflink_tpu/mod.py", "hazard": "tuning",
        "doc": "a registered knob",
    },
    "SFT_ARMED_PLAN": {
        "owner": "spatialflink_tpu/mod.py", "hazard": "armed",
        "doc": "an armed plan the gate scrubs via gate_scrub_vars",
    },
}


def gate_scrub_vars() -> list:
    return sorted(n for n, meta in ENV_VARS.items()
                  if meta["hazard"] == "armed")
