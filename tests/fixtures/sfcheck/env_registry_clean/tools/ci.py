"""Clean twin (env-registry): the gate scrub is DERIVED from the
registry's hazard classes — new armed vars are scrubbed automatically."""

import os


def _registry():
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "spatialflink_tpu", "envvars.py")
    spec = importlib.util.spec_from_file_location("_envvars", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _cpu_env():
    env = dict(os.environ)
    for var in _registry().gate_scrub_vars():
        env.pop(var, None)
    return env
