"""Clean twin (contract-twin): matrix == registry, both ways."""

MATRIX = {
    "p.one": None,
    "p.two": None,
}
