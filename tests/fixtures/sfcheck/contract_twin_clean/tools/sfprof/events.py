"""Clean twin (contract-twin): registry matches the producers exactly."""

INSTANT_EVENTS = frozenset({"good_event", "blackbox_dumped"})

INSTANT_EVENT_PREFIXES = ("used_prefix:",)
