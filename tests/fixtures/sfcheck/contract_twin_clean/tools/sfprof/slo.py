"""Clean twin (contract-twin): the mirror matches field-for-field."""

SLO_VERSION = 1

SPEC_KEYS = ("name", "lag_ms", "e2e_p50_ms", "e2e_p99_ms")
