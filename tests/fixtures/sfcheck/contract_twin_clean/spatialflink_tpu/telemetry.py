"""Clean twin (contract-twin): every emitted name/prefix registered,
every registered entry emitted, all heads literal."""


class Tel:
    def emit_instant(self, name, **args):
        return name


def produce(tel, point):
    tel.emit_instant("good_event")
    tel.emit_instant("blackbox_dumped")
    tel.emit_instant(f"used_prefix:{point}")
