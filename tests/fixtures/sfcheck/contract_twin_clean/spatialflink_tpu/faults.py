"""Clean twin (contract-twin): every point has a matrix leg."""

INJECTION_POINTS = {
    "p.one": "covered point",
    "p.two": "also covered",
}
