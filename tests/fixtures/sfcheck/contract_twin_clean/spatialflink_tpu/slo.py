"""Clean twin (contract-twin): live SLO spec aligned with its mirror."""

SLO_VERSION = 1


class SloSpec:
    name: str = "default"
    lag_ms: float = 0.0
    e2e_p50_ms: float = 0.0
    e2e_p99_ms: float = 0.0
