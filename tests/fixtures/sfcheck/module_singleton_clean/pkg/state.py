"""Clean twin of module_singleton_bad: the __main__ guard delegates to
the canonical import, so the entry point and every canonically-importing
hook share ONE module instance (the overload.py idiom)."""

import sys


class Registry:
    def __init__(self):
        self.items = []


registry = Registry()

_slot = None


def install(ctrl):
    global _slot
    _slot = ctrl
    return ctrl


def main():
    install(object())
    return 0


if __name__ == "__main__":
    from pkg.state import main as _canonical_main

    sys.exit(_canonical_main())
