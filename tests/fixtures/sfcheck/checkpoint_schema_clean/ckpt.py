"""Fixture mini-repo: the sanctioned checkpoint publish/restore idioms
(clean twin of checkpoint_schema_bad)."""


class WindowOperator:
    def state(self):
        payload = {"carry": self.carry, "watermark": self.wm}
        if self.compaction is not None:
            payload["compaction_rung"] = self.compaction
        return payload

    def restore(self, state):
        self.carry = state["carry"]
        self.wm = state["watermark"]
        # legacy default: checkpoints older than the rung lack the key
        self.compaction = state.get("compaction_rung", None)
        # guarded read of an optional key is the sanctioned residue idiom
        if "retry_budget" in state:
            self.retries = state["retry_budget"]


class DelegatorOperator:
    def state(self):
        # pure delegator: zero literal writes, nothing statically
        # checkable — the pair is skipped
        return self.inner.snapshot()

    def restore(self, state):
        self.inner.load(state["inner_blob"])


class DynamicOperator:
    def state(self):
        return {"carry": self.carry, "counters": dict(self.counters)}

    def restore(self, state):
        # payload-map iteration consumes every key dynamically — the
        # never-restored rule cannot claim a drop
        for key, value in state.items():
            setattr(self, key, value)
