"""Clean twin of recompile_surface_bad.py: the same data-dependent ints
routed through the compaction ladder before they become shapes."""

import jax.numpy as jnp

from spatialflink_tpu.ops.compaction import pick_capacity
from spatialflink_tpu.utils.padding import next_bucket, pad_to_bucket


def run(stream, prog):
    for win in windows(stream):  # noqa: F821
        n = len(win.events)
        b = pick_capacity(n, 1024)  # ladder-routed: ≤K stable shapes
        buf = jnp.zeros((b, 2))
        prog(buf)


def pad_stage(win):
    m = next_bucket(win.xs.shape[0])  # bucketed before it is a shape
    return pad_to_bucket(win.ts, m)


def run_padded(stream, prog):
    for win in windows(stream):  # noqa: F821
        prog(pad_stage(win))
