"""Deliberate recompile-surface violations (fixture): device shapes
derived raw from data-dependent Python ints on a per-window path — one
XLA compile per distinct window size."""

import jax.numpy as jnp


def run(stream, prog):
    for win in windows(stream):  # noqa: F821
        n = len(win.events)
        buf = jnp.zeros((n, 2))  # BAD: raw len() becomes a device shape
        prog(buf)


def pad_stage(win):
    # BAD (reached from the loop below): .shape-derived bucket, unrouted
    m = win.xs.shape[0]
    return pad_to_bucket(win.ts, m)  # noqa: F821


def run_padded(stream, prog):
    for win in windows(stream):  # noqa: F821
        prog(pad_stage(win))
