"""sync-discipline true positives: function and method spellings."""

import jax
from jax import block_until_ready as bur


def timed_step(fn, x):
    out = fn(x)
    jax.block_until_ready(out)     # no-op over the axon tunnel
    out.block_until_ready()        # method form, same no-op
    bur(out)                       # aliased import
    return out
