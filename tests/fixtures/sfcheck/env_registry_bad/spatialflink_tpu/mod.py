"""Deliberate violation corpus (env-registry): one unregistered SFT_*
read among registered ones."""

import os


def read_config():
    a = os.environ.get("SFT_KNOWN")
    b = os.environ.get("SFT_UNREGISTERED")  # not in ENV_VARS
    c = os.environ.get("SFT_ARMED_PLAN")
    d = os.environ.get("SFT_ARMED_UNSCRUBBED")
    return a, b, c, d
