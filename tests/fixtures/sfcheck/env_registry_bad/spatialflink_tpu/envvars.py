"""Deliberate violation corpus (env-registry): registry for the bad
mini-repo — one dead entry, and one armed var the gate never scrubs."""

HAZARD_CLASSES = ("armed", "capture", "tuning", "internal")

ENV_VARS = {
    "SFT_KNOWN": {
        "owner": "spatialflink_tpu/mod.py", "hazard": "tuning",
        "doc": "a registered knob",
    },
    "SFT_ARMED_PLAN": {
        "owner": "spatialflink_tpu/mod.py", "hazard": "armed",
        "doc": "an armed plan the gate scrubs by hand",
    },
    "SFT_ARMED_UNSCRUBBED": {
        "owner": "spatialflink_tpu/mod.py", "hazard": "armed",
        "doc": "an armed plan the hand-listed scrub misses",
    },
    "SFT_DEAD": {
        "owner": "nobody", "hazard": "capture",
        "doc": "registered but read nowhere — drift",
    },
}


def gate_scrub_vars() -> list:
    return sorted(n for n, meta in ENV_VARS.items()
                  if meta["hazard"] == "armed")
