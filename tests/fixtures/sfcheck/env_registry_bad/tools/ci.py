"""Deliberate violation corpus (env-registry): the gate hand-lists its
scrub, so the second armed var leaks into the stages."""

import os


def _cpu_env():
    env = dict(os.environ)
    env.pop("SFT_ARMED_PLAN", None)  # hand-listed: misses the other one
    return env
