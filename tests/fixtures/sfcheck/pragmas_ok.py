"""Every violation class suppressed by a pragma — must yield ZERO findings
from every pass (force-checked by tests/test_sfcheck.py).
"""

import time

import jax
import jax.numpy as jnp

PAD = jnp.zeros((8,))  # sfcheck: ok=hotpath -- fixture: deliberate import-time dispatch
LUT = jnp.full(
    (16,),
    0.0,
)  # sfcheck: ok -- fixture: pragma on the LAST line of a multi-line call spans the whole node


def host_helper(x, scale):
    t0 = time.time()  # hotpath: ok (legacy pragma still honored)
    s = float(scale)  # sfcheck: ok=trace-hygiene -- fixture: host-side scalar by contract
    idx = jnp.nonzero(x)  # sfcheck: ok=fixed-shape,trace-hygiene -- fixture: multi-pass pragma list
    jax.block_until_ready(x)  # sfcheck: ok=sync-discipline -- fixture: CPU-only path, no tunnel
    return f"t={t0:.3f} s={s:.1f}", idx  # sfcheck: ok=fstring-numpy -- fixture: known Python floats
