"""Fixture mini-repo: nondeterminism reachable from egress / checkpoint
decision roots (analyzed with --project-root at this root)."""

import random
import time


def _stamp():
    # wall-clock two hops from the egress root: the evidence chain must
    # name the commit -> _stamp edge
    return time.time()


class FileSink:
    def commit(self, rows):
        # set-iteration straight into egress bytes: the hash seed, not
        # the data, decides output order — resume diverges
        for oid in {r.oid for r in rows}:
            self.fh.write(f"{oid}\n")
        self.fh.write(f"footer {_stamp()}\n")


def shard_state():
    # unseeded global RNG draw inside a checkpoint publisher
    return {"salt": random.random()}
