"""Clean twin of hotpath_interproc_bad.py: the same 2-hop call chain,
but the jnp work is traced (jax.jit) and the loop-body helpers are
host-only — zero findings."""

import jax
import jax.numpy as jnp


@jax.jit
def summarize(dists):
    # Fine: decorated device entry — this jnp.sort is traced, not eager.
    return jnp.sort(dists)[:8]


def tally(dists):
    return summarize(dists)


def stage(win):
    # Fine: jnp.asarray is the sanctioned device SHIP, not compute.
    return jnp.asarray(win.x)


def run(stream):
    out = []
    for win in windows(stream):  # noqa: F821
        out.append(tally(stage(win)))
    return out


def host_only(stream):
    total = 0
    for win in windows(stream):  # noqa: F821
        total += sum(win.counts)  # plain-Python host work: fine
    return total
