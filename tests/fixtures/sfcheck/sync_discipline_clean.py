"""sync-discipline clean: true sync via a real device→host fetch."""

import jax
import numpy as np


def timed_step(fn, x):
    out = fn(x)
    fetched = jax.device_get(out)   # true sync: actually fetches
    return np.asarray(fetched)
