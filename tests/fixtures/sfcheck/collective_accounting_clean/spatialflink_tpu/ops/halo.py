"""Fixture (clean twin): the same halo kernel, now reachable from an
accounted parallel/ wrapper."""

from jax import lax


def halo_exchange_kernel(x, axis_name):
    g = lax.all_gather(x, axis_name)
    total = lax.psum(x, axis_name)
    return g, total
