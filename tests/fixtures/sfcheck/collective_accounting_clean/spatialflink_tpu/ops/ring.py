"""Fixture (clean twin): the same ppermute halo kernel, reachable from
an accounted parallel/ wrapper."""

from jax import lax


def ring_shift_kernel(x, axis_name):
    return lax.ppermute(x, axis_name, [(0, 1)])
