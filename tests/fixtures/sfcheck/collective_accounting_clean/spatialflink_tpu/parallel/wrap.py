"""Fixture (clean twin): the wrapper feeds account_collective from
static shape metadata and calls the kernel — full coverage."""

from spatialflink_tpu.ops.halo import halo_exchange_kernel
from spatialflink_tpu.telemetry import telemetry


def sharded_halo_exchange(mesh, x):
    telemetry.account_collective("all_gather", 8, axis="data")
    telemetry.account_collective("psum", 8, axis="data")
    return halo_exchange_kernel(x, axis_name="data")


def sharded_ring_shift(mesh, x):
    from spatialflink_tpu.ops.ring import ring_shift_kernel

    telemetry.account_collective("ppermute", 8, axis="data")
    return ring_shift_kernel(x, axis_name="data")
