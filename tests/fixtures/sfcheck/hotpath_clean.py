"""hotpath clean: function-scoped jnp, module-scope wall clock only."""

import time

import jax.numpy as jnp
import numpy as np

PAD = np.zeros(8)        # host constant in plain numpy — fine
T_IMPORT = time.time()   # import-time timestamp runs once on the host


def kernel(x):
    return jnp.sum(x) + jnp.asarray(PAD, x.dtype)[0]
