"""fixed-shape true positives: every data-dependent-shape spelling."""

import jax.numpy as jnp


def compact(x, mask):
    idx = jnp.nonzero(mask)            # no size= → data-dependent shape
    hits = jnp.where(mask)             # single-arg where = nonzero
    uniq = jnp.unique(x)               # no size=
    kept = jnp.compress(mask, x)       # no fixed-shape form exists
    picked = x[x > 0]                  # inline boolean-mask subscript
    near = x < 0.5
    named = x[near]                    # named boolean-mask subscript
    return idx, hits, uniq, kept, picked, named
