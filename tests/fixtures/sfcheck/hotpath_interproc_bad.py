"""Deliberate hotpath-interproc violations (fixture — excluded from the
default scan).

The eager jnp work sits TWO call hops away from the per-window loop, so
the per-file syntactic `hotpath` pass (module-scope jnp in ops/ only)
provably cannot see it — tests/test_sfcheck.py pins that blindness."""

import jax.numpy as jnp


def tally(dists):
    # hop 2: innocent-looking forwarder
    return summarize(dists)


def summarize(dists):
    # BAD: eager jnp compute, transitively called per window (2 hops)
    return jnp.sort(dists)[:8]


def run(stream):
    out = []
    for win in windows(stream):  # per-window loop  # noqa: F821
        out.append(tally(win.dists))  # hop 1
    return out


def run_direct(stream):
    for win in windows(stream):  # noqa: F821
        yield jnp.sum(win.x)  # BAD: eager jnp directly inside the loop
