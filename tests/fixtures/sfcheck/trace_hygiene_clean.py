"""trace-hygiene clean: traced math on parameters, host work on locals."""

import jax.numpy as jnp
import numpy as np

_EDGES = np.linspace(0.0, 1.0, 9)  # module-scope host constant — fine


def kernel(x, scale):
    s = jnp.asarray(scale, x.dtype)    # stays traced
    limit = float(np.pi)               # host constant, not a parameter
    return jnp.clip(x * s, 0.0, limit)
