"""Clean twin: emits queued under the lock and drained after release
(the overload._emit_locked idiom); blocking work and callbacks outside
the critical section."""

import threading
import time


class Busy:
    def __init__(self, tel):
        self._lock = threading.Lock()
        self.tel = tel
        self.done_callback = None
        self._pending = []

    def flush(self):
        with self._lock:
            self._pending.append("busy_flush")  # queue, don't emit
        self._drain()

    def _drain(self):
        while True:
            with self._lock:
                if not self._pending:
                    return
                name = self._pending.pop(0)
            self.tel.emit_instant(name)  # emitted lock-free

    def wait(self):
        with self._lock:
            deadline = 0.01
        time.sleep(deadline)  # blocking work outside the lock

    def snap(self):
        with self._lock:
            cb = self.done_callback
        if cb is not None:
            cb()  # user code runs lock-free
