"""Clean twin: modb calls back into moda only OUTSIDE its lock."""

import threading

import moda

_LOCK_B = threading.Lock()


def bump():
    with _LOCK_B:
        return 2


def pong():
    with _LOCK_B:
        staged = 3
    moda.ding()  # lock released first: no B → A edge
    return staged
