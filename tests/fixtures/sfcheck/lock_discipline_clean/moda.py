"""Clean twin of lock_discipline_bad: one global acquisition order
(A before B, never the reverse)."""

import threading

import modb

_LOCK_A = threading.Lock()


def ping():
    with _LOCK_A:
        modb.bump()  # A → B is the one sanctioned order


def ding():
    with _LOCK_A:
        return 1
