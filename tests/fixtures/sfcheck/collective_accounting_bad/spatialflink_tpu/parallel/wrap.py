"""Fixture: the accounted wrapper — covers stats_kernel (call edge),
leaving halo.py's kernel uncovered."""

from spatialflink_tpu.ops.stats import stats_kernel
from spatialflink_tpu.telemetry import telemetry


def sharded_stats(mesh, x):
    telemetry.account_collective("psum", 8, axis="data")
    return stats_kernel(x, axis_name="data")
