"""Fixture: a ppermute halo kernel no accounted parallel/ wrapper
reaches — boundary-pane exchange traffic invisible to the ledger."""

from jax import lax


def ring_shift_kernel(x, axis_name):
    return lax.ppermute(x, axis_name, [(0, 1)])  # finding: unaccounted
