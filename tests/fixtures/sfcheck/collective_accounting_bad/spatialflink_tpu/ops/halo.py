"""Fixture: a shard_map-body kernel whose collectives no accounted
parallel/ wrapper reaches — its ICI traffic is invisible to the
per-node collective ledger."""

from jax import lax


def halo_exchange_kernel(x, axis_name):
    g = lax.all_gather(x, axis_name)       # finding: unaccounted
    total = lax.psum(x, axis_name)         # finding: unaccounted
    return g, total
