"""Fixture: a collective kernel that IS covered by the accounted
wrapper in parallel/wrap.py — must stay clean while halo.py is
flagged."""

from jax import lax


def stats_kernel(x, axis_name):
    return lax.psum(x, axis_name)
