"""Fixture parity test: references both kernels by name."""

from parallel.kernels import sharded_dispatcher, sharded_ok


def test_sharded_ok_matches_single():
    assert sharded_ok(None, 3) == 6


def test_dispatcher():
    assert sharded_dispatcher(None, lambda n: n, 5) == 5
