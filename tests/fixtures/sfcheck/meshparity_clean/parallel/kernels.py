"""Fixture mini-repo: a parallel/ kernel satisfying the mesh-parity
contract — ops/ counterpart + name-referenced parity test."""

from ops.single import base_kernel


def sharded_ok(mesh, x):
    return base_kernel(x)


def sharded_dispatcher(mesh, kernel, n_args):
    # generic dispatcher (kernel param): exempt from the counterpart
    # half, still needs a test reference
    return kernel(n_args)
