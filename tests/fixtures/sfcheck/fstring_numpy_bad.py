"""fstring-numpy true positives: unwrapped float-formatted egress values."""


def emit(eps, lat_ms, stats):
    line = f"eps={eps:.1f} p95={lat_ms:.2f}"          # unwrapped f-string
    legacy = "thr={:.3f}".format(stats)               # unwrapped .format
    named = "sel={s:.4f}".format(s=stats)             # keyword .format
    return line, legacy, named
