"""trace-hygiene true positives: every leak class once."""

import jax
import numpy as np


def kernel(x, scale):
    s = float(scale)          # concretizes a (possibly traced) parameter
    host = np.asarray(x)      # materializes the parameter on the host
    first = x[0].item()       # per-call device→host fetch
    fetched = jax.device_get(x)   # fetch belongs to the operator layer
    print("debug", s)         # host I/O in a traced path
    return host, first, fetched
