"""fstring-numpy clean: wrapped values, spec-free interpolations."""


def emit(eps, lat_ms, count, stats):
    line = f"eps={float(eps):.1f} p95={float(lat_ms):.2f} n={count}"
    legacy = "thr={:.3f}".format(float(stats))
    literal = f"half={0.5:.1f} pct={int(eps):d}"
    return line, legacy, literal
