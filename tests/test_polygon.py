"""Point-in-polygon and polygon-distance kernel tests."""

import jax.numpy as jnp
import numpy as np
import pytest

from spatialflink_tpu.ops.polygon import (
    pack_rings,
    point_polygon_distance,
    points_in_polygon,
    signed_area,
)

SQUARE = np.array([[0.0, 0.0], [4.0, 0.0], [4.0, 4.0], [0.0, 4.0]])
HOLE = np.array([[1.0, 1.0], [3.0, 1.0], [3.0, 3.0], [1.0, 3.0]])


def test_pack_rings_closes_and_seams():
    verts, ev = pack_rings([SQUARE, HOLE])
    assert len(verts) == 10  # 5 + 5 after closing
    assert ev.sum() == 8  # 4 real edges per ring, 1 seam invalid
    assert not ev[4]  # seam between ring 0 end and ring 1 start


def test_containment_with_hole():
    verts, ev = pack_rings([SQUARE, HOLE], pad_to=32)
    pts = jnp.asarray(
        [[0.5, 0.5], [2.0, 2.0], [2.0, 0.5], [5.0, 5.0], [-1.0, 2.0], [3.5, 3.5]]
    )
    inside = np.asarray(points_in_polygon(pts, jnp.asarray(verts), jnp.asarray(ev)))
    np.testing.assert_array_equal(inside, [True, False, True, False, False, True])


def test_containment_random_vs_matplotlibfree_brute(rng):
    # Convex polygon → containment check against half-plane test.
    ring = np.array([[0, 0], [6, 0], [8, 4], [3, 7], [-1, 3]], float)
    verts, ev = pack_rings([ring], pad_to=16)
    pts = rng.uniform(-2, 9, size=(500, 2))
    got = np.asarray(points_in_polygon(jnp.asarray(pts), jnp.asarray(verts), jnp.asarray(ev)))
    closed = np.vstack([ring, ring[:1]])
    edges = closed[1:] - closed[:-1]
    rel = pts[:, None, :] - closed[None, :-1, :]
    cross = edges[None, :, 0] * rel[:, :, 1] - edges[None, :, 1] * rel[:, :, 0]
    expect = np.all(cross > 0, axis=1) | np.all(cross < 0, axis=1)
    # Skip points within 1e-9 of an edge (boundary ambiguity)
    mismatch = got != expect
    assert mismatch.mean() < 0.01


def test_polygon_distance_zero_inside_min_edge_outside():
    verts, ev = pack_rings([SQUARE])
    pts = jnp.asarray([[2.0, 0.5], [6.0, 2.0], [2.0, -3.0], [2.0, 2.0]])
    d = np.asarray(point_polygon_distance(pts, jnp.asarray(verts), jnp.asarray(ev)))
    assert d[0] == 0.0  # inside (between hole-free square edges)
    assert d[1] == pytest.approx(2.0)
    assert d[2] == pytest.approx(3.0)
    assert d[3] == 0.0


def test_distance_inside_hole_is_to_hole_boundary():
    verts, ev = pack_rings([SQUARE, HOLE])
    # Point in the hole: outside the polygon → distance to hole boundary.
    d = float(point_polygon_distance(jnp.asarray([[2.0, 2.0]]), jnp.asarray(verts), jnp.asarray(ev))[0])
    assert d == pytest.approx(1.0)


def test_signed_area_orientation():
    assert signed_area(SQUARE) == pytest.approx(16.0)
    assert signed_area(SQUARE[::-1]) == pytest.approx(-16.0)
