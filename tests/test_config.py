"""Config-system tests: the reference's geoflink-conf.yml schema loads
unchanged (modulo the Java type tag) and validation is strict."""

import pytest

from spatialflink_tpu.config import ConfigError, Params

REFERENCE_YML = """\
!!GeoFlink.utils.ConfigType
clusterMode: False
kafkaBootStrapServers: "localhost:9092"
inputStream1:
  topicName: "TaxiDrive17MillionGeoJSON"
  format: "GeoJSON"
  dateFormat: "yyyy-MM-dd HH:mm:ss"
  geoJSONSchemaAttr: ["oID", "timestamp"]
  csvTsvSchemaAttr: [1, 4, 5, 6]
  gridBBox: [115.5, 39.6, 117.6, 41.1]
  numGridCells: 100
  cellLength: 0
  delimiter: ","
  charset: "UTF-8"
outputStream:
  topicName: "outputTopic"
  delimiter: ","
query:
  option: 2
  parallelism: 15
  approximate: False
  radius: 10.5
  aggregateFunction: "SUM"
  k: 100
  omegaDuration: 1
  trajIDs: [123, 231]
  queryPoints:
    - [116.14319, 40.07271]
    - [117.6, 40.5]
  queryPolygons:
    - [[116.5, 40.5], [117.6, 40.5], [117.6, 41.4], [116.5, 41.4], [116.5, 40.5]]
  queryLineStrings:
    - [[116.5, 40.5], [117.6, 40.5], [117.6, 41.4], [116.5, 41.4]]
  thresholds:
    trajDeletion: 1000
    outOfOrderTuples: 1
window:
  type: "TIME"
  interval: 5
  step: 5
"""


def test_reference_yml_loads():
    p = Params.loads(REFERENCE_YML)
    assert p.cluster_mode is False
    assert p.input_stream1.topic_name == "TaxiDrive17MillionGeoJSON"
    assert p.input_stream1.grid_bbox == [115.5, 39.6, 117.6, 41.1]
    assert p.query.k == 100
    assert p.query.parallelism == 15
    assert p.query.query_points[0] == [116.14319, 40.07271]
    assert len(p.query.query_polygons[0]) == 5
    assert p.query.traj_deletion_threshold == 1000
    assert p.window.interval_ms == 5000 and p.window.step_ms == 5000
    assert p.backend == "tpu"  # default extension


def test_grid_from_config():
    p = Params.loads(REFERENCE_YML)
    g = p.input_stream1.make_grid()
    assert g.n == 100
    assert g.min_x == 115.5


def test_missing_input_stream_fails():
    with pytest.raises(ConfigError, match="inputStream1"):
        Params.loads("clusterMode: False")


def test_bad_format_fails():
    bad = REFERENCE_YML.replace('format: "GeoJSON"', 'format: "XML"')
    with pytest.raises(ConfigError, match="format"):
        Params.loads(bad)


def test_degenerate_bbox_fails():
    bad = REFERENCE_YML.replace(
        "gridBBox: [115.5, 39.6, 117.6, 41.1]", "gridBBox: [115.5, 39.6, 115.5, 41.1]"
    )
    with pytest.raises(ConfigError, match="degenerate"):
        Params.loads(bad)


def test_bad_aggregate_fails():
    bad = REFERENCE_YML.replace('aggregateFunction: "SUM"', 'aggregateFunction: "MEDIAN"')
    with pytest.raises(ConfigError, match="aggregateFunction"):
        Params.loads(bad)


def test_backend_extension():
    p = Params.loads(REFERENCE_YML + "\nbackend: cpu\ndeviceMesh: [2, 4]\n")
    assert p.backend == "cpu"
    assert p.device_mesh == [2, 4]
    with pytest.raises(ConfigError, match="backend"):
        Params.loads(REFERENCE_YML + "\nbackend: cuda\n")
