"""Tier-1 sfcheck (tools/sfcheck): the multi-pass analyzer keeps the whole
tree clean, every pass provably detects its target class (fixture corpus
under tests/fixtures/sfcheck/), pragma suppression and the --json CLI
contract hold, and the violations fixed in this tree stay fixed
(block_until_ready egress, numpy-scalar f-strings).
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.sfcheck import core, driver  # noqa: E402
from tools.sfcheck.passes import (  # noqa: E402
    ALL_PASSES,
    PASS_NAMES,
    PROJECT_PASSES,
    get_pass,
)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "sfcheck")

# Subprocesses must never dial the axon tunnel at interpreter boot.
SUBPROC_ENV = {**os.environ, "PALLAS_AXON_POOL_IPS": ""}


def _check(src, pass_name, name="mod.py"):
    return core.check_source(name, textwrap.dedent(src),
                             [get_pass(pass_name)], force=True)


def _fixture(name, pass_names):
    path = os.path.join(FIXTURES, name)
    return core.check_file(path, [get_pass(n) for n in pass_names],
                           force=True)


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.sfcheck", *args],
        capture_output=True, text=True, cwd=REPO, env=SUBPROC_ENV,
    )


# -- the analyzer itself -----------------------------------------------------

def test_all_seventeen_passes_registered():
    assert set(PASS_NAMES) == {
        # file passes
        "hotpath", "trace-hygiene", "fixed-shape", "sync-discipline",
        "fstring-numpy",
        # whole-program passes
        "hotpath-interproc", "mesh-parity", "recompile-surface",
        "donation-safety", "pragma-staleness",
        # v3: concurrency discipline + cross-module contracts
        "lock-discipline", "module-singleton", "env-registry",
        "contract-twin",
        # v4: checkpoint/replay/collective contract analysis
        "checkpoint-schema", "replay-determinism",
        "collective-accounting",
    }
    for p in ALL_PASSES + PROJECT_PASSES:
        assert p.description and p.invariant


def test_repo_tree_is_clean_file_passes():
    # The per-file framework alone (back-compat surface: run_paths).
    report = core.run_paths(core.default_targets())
    assert report.findings == [], "\n".join(
        f.format() for f in report.findings
    )
    # The scan actually covered the tree, not an empty walk.
    assert report.files > 100


def test_repo_tree_is_clean_whole_program():
    # The full driver: file passes + project passes + pragma-staleness.
    report = driver.run(use_cache=False)
    assert report.findings == [], "\n".join(
        f.format() for f in report.findings
    )
    assert report.files > 100
    assert set(report.pass_names) == set(PASS_NAMES)


def test_cli_json_breakdown_over_subtree():
    # Explicit targets form a PARTIAL project view: the file passes
    # report a zero breakdown; whole-program passes are deliberately
    # absent (they would see an incomplete world — no tests/, missing
    # callers — and manufacture findings). The full ten-pass verdict is
    # the default no-args run (test_repo_tree_is_clean_whole_program).
    res = _cli("--json", "spatialflink_tpu", "bench.py", "tools")
    assert res.returncode == 0, res.stdout + res.stderr
    data = json.loads(res.stdout)
    assert data["findings"] == []
    assert set(data["counts"]) == {p.name for p in ALL_PASSES}
    assert all(v == 0 for v in data["counts"].values())
    assert data["files"] > 70


def test_single_file_invocation_has_no_partial_view_false_positives():
    # `sfcheck <file I edited>` must not exit 1 with bogus mesh-parity /
    # staleness findings just because the rest of the program is outside
    # the view.
    res = _cli("--no-cache", "spatialflink_tpu/parallel/sharded.py")
    assert res.returncode == 0, res.stdout + res.stderr


# -- fixture corpus: one true-positive + one clean file per pass -------------

@pytest.mark.parametrize("pass_name,expect_bad", [
    ("hotpath", 5),
    ("trace-hygiene", 5),
    ("fixed-shape", 6),
    ("sync-discipline", 3),
    ("fstring-numpy", 4),
])
def test_fixture_corpus(pass_name, expect_bad):
    stem = pass_name.replace("-", "_")
    bad = _fixture(f"{stem}_bad.py", [pass_name])
    assert len(bad) == expect_bad, "\n".join(f.format() for f in bad)
    assert all(f.pass_name == pass_name for f in bad)
    assert _fixture(f"{stem}_clean.py", [pass_name]) == []


def test_pragma_fixture_suppresses_every_class():
    assert _fixture("pragmas_ok.py", [p.name for p in ALL_PASSES]) == []


# -- pragma semantics --------------------------------------------------------

def test_bare_pragma_suppresses_all_passes():
    src = """
        import jax
        def f(x):
            jax.block_until_ready(x)  # sfcheck: ok
    """
    assert _check(src, "sync-discipline") == []


def test_named_pragma_suppresses_only_that_pass():
    src = """
        import jax
        def f(x):
            jax.block_until_ready(x)  # sfcheck: ok=sync-discipline -- why
    """
    assert _check(src, "sync-discipline") == []
    # The same pragma naming a DIFFERENT pass does not suppress.
    wrong = src.replace("ok=sync-discipline", "ok=hotpath")
    assert len(_check(wrong, "sync-discipline")) == 1


def test_pragma_spans_multiline_call():
    src = """
        import jax.numpy as jnp
        def f(mask):
            return jnp.nonzero(
                mask,
            )  # sfcheck: ok=fixed-shape -- fixture: pragma on the close paren
    """
    assert _check(src, "fixed-shape") == []


def test_string_embedded_pragma_does_not_suppress_file_pass():
    # pragma-looking text inside a string ARGUMENT of the flagged node
    # must not suppress (the old line-regex suppression did): only real
    # comment tokens count.
    src = """
        import jax
        def f(x):
            return jax.block_until_ready(
                x, "docs say use # sfcheck: ok here"
            )
    """
    assert len(_check(src, "sync-discipline")) == 1


def test_syntax_error_is_reported_not_swallowed():
    findings = core.check_source("broken.py", "def f(:\n", ALL_PASSES,
                                 force=True)
    assert len(findings) == 1 and findings[0].pass_name == "syntax"


# -- CLI contract ------------------------------------------------------------

def test_cli_exit_codes_and_human_output(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\ndef f(x):\n    jax.block_until_ready(x)\n")
    res = _cli("--pass", "sync-discipline", str(bad))
    assert res.returncode == 1
    assert "bad.py:3" in res.stdout and "[sync-discipline]" in res.stdout
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    res = _cli("--pass", "sync-discipline", str(clean))
    assert res.returncode == 0 and res.stdout == ""


def test_cli_json_on_fixture():
    res = _cli("--pass", "fixed-shape", "--json",
               os.path.join(FIXTURES, "fixed_shape_bad.py"))
    assert res.returncode == 1
    data = json.loads(res.stdout)
    assert data["counts"] == {"fixed-shape": 6}
    assert {f["pass"] for f in data["findings"]} == {"fixed-shape"}
    assert all(f["line"] > 0 and f["message"] for f in data["findings"])


def test_cli_json_carries_evidence_chain():
    res = _cli("--no-cache", "--pass", "hotpath-interproc", "--json",
               os.path.join(FIXTURES, "hotpath_interproc_bad.py"))
    assert res.returncode == 1, res.stdout + res.stderr
    data = json.loads(res.stdout)
    assert data["counts"]["hotpath-interproc"] == 2
    evs = [f["evidence"] for f in data["findings"]]
    assert all(evs), "every project finding carries evidence"
    assert any(len(e) >= 3 for e in evs), "2-hop call path resolved"


def test_cli_mesh_parity_fixture_repo_via_project_root():
    root = os.path.join(FIXTURES, "meshparity_bad")
    res = _cli("--no-cache", "--pass", "mesh-parity",
               "--project-root", root, "--json", root)
    assert res.returncode == 1, res.stdout + res.stderr
    data = json.loads(res.stdout)
    assert data["counts"]["mesh-parity"] == 3
    assert any("counterpart: ops/single.py:base_kernel" in e
               for f in data["findings"] for e in f["evidence"])


def test_cli_broken_pipe_preserves_gate_verdict(monkeypatch):
    """`sfcheck | head` closing the pipe mid-print must not flip the
    exit code: findings stay 1, clean stays 0 (the exit code IS the
    pre-commit gate)."""
    import builtins

    from tools.sfcheck import cli
    from tools.sfcheck.core import Finding, Report

    # neutralize the stdout detach under pytest's fd-level capture
    monkeypatch.setattr(os, "dup2", lambda a, b: None)

    def exploding_print(*a, **k):
        raise BrokenPipeError

    monkeypatch.setattr(builtins, "print", exploding_print)
    monkeypatch.setattr(cli.driver, "run", lambda **k: Report(
        [Finding("f.py", 1, 1, "hotpath", "boom")], 1, ["hotpath"]))
    assert cli.main([]) == 1
    monkeypatch.setattr(cli.driver, "run",
                        lambda **k: Report([], 1, ["hotpath"]))
    assert cli.main([]) == 0
    # a pipe break OUTSIDE the guarded print sections: verdict unknown,
    # fail safe
    def boom(args):
        raise BrokenPipeError

    monkeypatch.setattr(cli, "_run", boom)
    assert cli.main([]) == 1


def test_cli_internal_crash_is_exit_three(monkeypatch, capsys):
    from tools.sfcheck import cli

    def crash(**kwargs):
        raise RuntimeError("injected analyzer crash")

    monkeypatch.setattr(cli.driver, "run", crash)
    assert cli.main([]) == 3
    assert "injected analyzer crash" in capsys.readouterr().err


def test_cli_unknown_pass_is_usage_error():
    res = _cli("--pass", "no-such-pass")
    assert res.returncode == 2
    assert "unknown pass" in res.stderr


def test_cli_missing_path_is_usage_error_not_crash():
    res = _cli("no_such_file_xyz.py")
    assert res.returncode == 2
    assert "no such file" in res.stderr
    assert "Traceback" not in res.stderr


def test_cli_list_passes():
    res = _cli("--list-passes")
    assert res.returncode == 0
    for name in PASS_NAMES:
        assert name in res.stdout


# -- whole-program passes: fixture corpus + evidence chains ------------------

def _project_fixture(name, pass_name, project_root=None):
    path = os.path.join(FIXTURES, name)
    report = driver.run(
        paths=[path], pass_names=[pass_name], use_cache=False,
        project_root=project_root,
    )
    return report.findings


@pytest.mark.parametrize("pass_name,expect_bad", [
    ("hotpath-interproc", 2),
    ("recompile-surface", 2),
    ("donation-safety", 4),
])
def test_project_fixture_corpus(pass_name, expect_bad):
    stem = pass_name.replace("-", "_")
    bad = _project_fixture(f"{stem}_bad.py", pass_name)
    assert len(bad) == expect_bad, "\n".join(f.format() for f in bad)
    assert all(f.pass_name == pass_name for f in bad)
    # every finding carries a resolved evidence chain
    assert all(f.evidence for f in bad)
    assert _project_fixture(f"{stem}_clean.py", pass_name) == []


def test_mesh_parity_fixture_repo():
    root = os.path.join(FIXTURES, "meshparity_bad")
    bad = _project_fixture("meshparity_bad", "mesh-parity",
                           project_root=root)
    # sharded_untested: no test; sharded_orphan: no counterpart + no test
    assert len(bad) == 3, "\n".join(f.format() for f in bad)
    msgs = "\n".join(f.message for f in bad)
    assert "referenced by no test" in msgs
    assert "no single-device ops/ counterpart" in msgs
    # cross-file evidence: the resolved counterpart for the tested half
    ev = "\n".join(e for f in bad for e in f.evidence)
    assert "counterpart: ops/single.py:base_kernel" in ev
    clean_root = os.path.join(FIXTURES, "meshparity_clean")
    assert _project_fixture("meshparity_clean", "mesh-parity",
                            project_root=clean_root) == []


def test_interproc_catches_what_the_syntactic_pass_misses():
    """The acceptance pin: eager jnp two call hops from a per-window
    loop. The per-file hotpath pass (module-scope jnp in ops/) finds
    NOTHING even force-run on the file; the call-graph pass finds it and
    names every hop."""
    path = os.path.join(FIXTURES, "hotpath_interproc_bad.py")
    assert _fixture("hotpath_interproc_bad.py", ["hotpath"]) == []
    findings = _project_fixture("hotpath_interproc_bad.py",
                                "hotpath-interproc")
    two_hop = [f for f in findings if len(f.evidence) >= 3]
    assert two_hop, "\n".join(f.format() for f in findings)
    ev = two_hop[0].evidence
    assert "per-window loop" in ev[0]
    assert "`tally` calls `summarize" in ev[1]
    assert "eager `jnp.sort" in ev[2]
    # and the direct-in-loop case is one-step evidence
    direct = [f for f in findings if "directly inside" in f.evidence[0]]
    assert len(direct) == 1


def test_recompile_surface_accepts_ladder_routed_form():
    """The acceptance pin: a raw len() shape is flagged; the
    pick_capacity/next_bucket-routed twin is accepted."""
    bad = _project_fixture("recompile_surface_bad.py", "recompile-surface")
    assert any("len(win.events)" in f.message for f in bad)
    assert any("shape" in f.message and ".shape[0]" in f.message
               for f in bad)
    assert _project_fixture("recompile_surface_clean.py",
                            "recompile-surface") == []


def test_donation_cross_evidence_names_wrapper_definition():
    bad = _project_fixture("donation_safety_bad.py", "donation-safety")
    ev = "\n".join(e for f in bad for e in f.evidence)
    assert "donating wrapper `step" in ev
    assert "inline `jax.jit(…, donate_argnums=…)` call" in ev


# -- pragma staleness --------------------------------------------------------

def _staleness(tmp_path, source):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(source))
    report = driver.run(paths=[str(f)], pass_names=["pragma-staleness"],
                        use_cache=False)
    return report.findings


def test_stale_pragma_is_a_finding(tmp_path):
    findings = _staleness(tmp_path, """
        x = 1  # sfcheck: ok=hotpath -- suppresses nothing
    """)
    assert len(findings) == 1
    assert findings[0].pass_name == "pragma-staleness"
    assert "hotpath" in findings[0].message


def test_live_pragma_is_not_stale(tmp_path):
    findings = _staleness(tmp_path, """
        import jax
        def f(x):
            jax.block_until_ready(x)  # sfcheck: ok=sync-discipline -- why
    """)
    assert findings == []


def test_pragma_in_string_or_prose_is_not_a_pragma(tmp_path):
    findings = _staleness(tmp_path, '''
        SRC = """
        y = jnp.zeros(4)  # sfcheck: ok=hotpath -- inside a string
        """
        # doc comment mentioning `# sfcheck: ok` semantics is prose
        x = 1
    ''')
    assert findings == []


def test_stale_pragma_not_self_suppressible(tmp_path):
    # A bare pragma would suppress every pass on its line — staleness
    # findings deliberately bypass suppression or every dead bare pragma
    # would hide itself.
    findings = _staleness(tmp_path, """
        x = 1  # sfcheck: ok
    """)
    assert len(findings) == 1


# -- incremental cache / --changed -------------------------------------------

def test_cache_invalidation_and_hits(tmp_path, monkeypatch):
    import time as _time

    proj = tmp_path / "proj"
    proj.mkdir()
    a = proj / "aa.py"
    b = proj / "bb.py"
    a.write_text("import jax\ndef f(x):\n    jax.block_until_ready(x)\n")
    b.write_text("x = 1\n")
    monkeypatch.setattr(core, "default_targets", lambda: [str(proj)])
    cache_path = str(tmp_path / "cache.json")

    analyzed = []
    real = driver._analyze_file

    def counting(path, relpath, passes, force):
        analyzed.append(relpath)
        return real(path, relpath, passes, force)

    monkeypatch.setattr(driver, "_analyze_file", counting)

    r1 = driver.run(changed=True, cache_path=cache_path)
    assert sorted(analyzed) == ["aa.py", "bb.py"]
    assert [f.pass_name for f in r1.findings] == ["sync-discipline"]
    assert os.path.exists(cache_path)

    # untouched → cache hit: nothing re-analyzed, identical findings
    analyzed.clear()
    t0 = _time.monotonic()
    r2 = driver.run(changed=True, cache_path=cache_path)
    warm_s = _time.monotonic() - t0
    assert analyzed == []
    assert [(f.pass_name, f.lineno) for f in r2.findings] == \
        [(f.pass_name, f.lineno) for f in r1.findings]
    assert warm_s < 1.0  # the sub-second pre-commit contract

    # edit one file → exactly that file re-analyzed, verdict updates
    a.write_text("x = 2\n")
    analyzed.clear()
    r3 = driver.run(changed=True, cache_path=cache_path)
    assert analyzed == ["aa.py"]
    assert r3.findings == []

    # mtime bump with unchanged content (git checkout): still a cache
    # hit via the sha check, and the entry's stored mtime refreshes so
    # the NEXT run takes the stat fast path again
    os.utime(b, ns=(1, 1))
    analyzed.clear()
    driver.run(changed=True, cache_path=cache_path)
    assert analyzed == []
    entry = json.load(open(cache_path))["files"]["bb.py"]
    assert entry["mtime_ns"] == os.stat(b).st_mtime_ns

    # plain (non --changed) runs ignore the cache and fully re-analyze
    analyzed.clear()
    driver.run(changed=False, cache_path=cache_path)
    assert sorted(analyzed) == ["aa.py", "bb.py"]


def test_cache_entries_survive_roundtrip_uncorrupted(tmp_path, monkeypatch):
    """Two consecutive cached runs must agree with the uncached verdict —
    regression for the facts_from_dict mutation that gutted call facts
    out of the cache on re-save."""
    proj = tmp_path / "proj"
    (proj / "parallel").mkdir(parents=True)
    (proj / "ops").mkdir()
    (proj / "parallel" / "k.py").write_text(
        "from ops.s import base\n\ndef sharded_k(mesh, x):\n"
        "    return base(x)\n"
    )
    (proj / "ops" / "s.py").write_text("def base(x):\n    return x\n")
    (proj / "tests").mkdir()
    (proj / "tests" / "test_k.py").write_text(
        "from parallel.k import sharded_k\n"
    )
    monkeypatch.setattr(core, "default_targets", lambda: [str(proj)])
    monkeypatch.setattr(core, "relpath_of", lambda p: os.path.relpath(
        os.path.abspath(p), str(proj)).replace(os.sep, "/"))
    cache_path = str(tmp_path / "cache.json")
    for _ in range(3):  # cold, warm, warm-after-resave
        report = driver.run(changed=True, cache_path=cache_path)
        assert report.findings == [], "\n".join(
            f.format() for f in report.findings)


# -- v3 passes: fixture mini-repos + evidence chains -------------------------


def _mini_repo(name, pass_name):
    root = os.path.join(FIXTURES, name)
    return driver.run(paths=[root], pass_names=[pass_name],
                      use_cache=False, project_root=root).findings


def test_lock_discipline_fixture_repo():
    bad = _mini_repo("lock_discipline_bad", "lock-discipline")
    assert len(bad) == 4, "\n".join(f.format() for f in bad)
    msgs = "\n".join(f.message for f in bad)
    # the three hazard classes, each detected under a held lock
    assert "telemetry emit/flush" in msgs
    assert "blocking call `time.sleep" in msgs
    assert "user callback" in msgs
    # the seeded two-module cycle, with both halves in the evidence
    cyc = [f for f in bad if "lock-order cycle" in f.message]
    assert len(cyc) == 1
    ev = "\n".join(cyc[0].evidence)
    assert "moda.py" in ev and "modb.py" in ev
    assert "_LOCK_A" in cyc[0].message and "_LOCK_B" in cyc[0].message
    assert all(f.evidence for f in bad)
    assert _mini_repo("lock_discipline_clean", "lock-discipline") == []


def test_module_singleton_fixture_repo():
    bad = _mini_repo("module_singleton_bad", "module-singleton")
    assert len(bad) == 1, "\n".join(f.format() for f in bad)
    f = bad[0]
    assert "python -m pkg.state" in f.message
    ev = "\n".join(f.evidence)
    # both state kinds named: the install slot AND the instance
    assert "rebinds module global `_slot`" in ev
    assert "registry = Registry()" in ev
    assert _mini_repo("module_singleton_clean", "module-singleton") == []


def test_env_registry_fixture_repo():
    bad = _mini_repo("env_registry_bad", "env-registry")
    assert len(bad) == 3, "\n".join(f.format() for f in bad)
    msgs = "\n".join(f.message for f in bad)
    assert "`SFT_UNREGISTERED` is read here but not registered" in msgs
    assert "`SFT_DEAD` has no read site" in msgs
    assert "SFT_ARMED_UNSCRUBBED" in msgs and "gate stages" in msgs
    assert all(f.evidence for f in bad)
    assert _mini_repo("env_registry_clean", "env-registry") == []


def test_contract_twin_fixture_repo():
    bad = _mini_repo("contract_twin_bad", "contract-twin")
    assert len(bad) == 9, "\n".join(f.format() for f in bad)
    msgs = "\n".join(f.message for f in bad)
    # spec-field drift, both directions (incl. the e2e lineage ceiling)
    assert "declares field `extra_live_only`" in msgs
    assert "declares field `e2e_p99_ms`" in msgs
    assert "lists `mirror_only`" in msgs
    # version pin drift
    assert "version twin drift" in msgs
    # injection-point ↔ matrix drift, both directions
    assert "`p.two` is registered in INJECTION_POINTS" in msgs
    assert "`p.ghost` matches no registered" in msgs
    # emit-name contract: typo, dynamic head, and consumer drift
    assert "`typo_event` is emitted but absent" in msgs
    assert "no literal head" in msgs
    assert "`never_emitted` but nothing emits it" in msgs
    assert all(f.evidence for f in bad)
    assert _mini_repo("contract_twin_clean", "contract-twin") == []


def _scratch_repo(tmp_path, files, pass_name):
    root = tmp_path / "proj"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return driver.run(paths=[str(root)], pass_names=[pass_name],
                      use_cache=False, project_root=str(root)).findings


def test_lock_discipline_multi_item_with_orders(tmp_path):
    """`with a, b:` acquires left-to-right: the same-statement spans
    share a lineno, so rank — not line nesting — must supply the A→B
    order edge, or this common form hides a real deadlock."""
    found = _scratch_repo(tmp_path, {"m.py": (
        "import threading\n"
        "_LOCK_A = threading.Lock()\n"
        "_LOCK_B = threading.Lock()\n"
        "def f():\n"
        "    with _LOCK_A, _LOCK_B:\n"
        "        return 1\n"
        "def g():\n"
        "    with _LOCK_B:\n"
        "        with _LOCK_A:\n"
        "            return 2\n"
    )}, "lock-discipline")
    assert len(found) == 1, "\n".join(f.format() for f in found)
    assert "lock-order cycle" in found[0].message


def test_lock_discipline_imported_lock_identity(tmp_path):
    """A lock acquired through `from m1 import _LOCK` is the same
    graph node as m1's own acquisitions — direct opposite-order
    acquisition across two files must close the cycle."""
    found = _scratch_repo(tmp_path, {
        "m1.py": (
            "import threading\n"
            "_LOCK_A = threading.Lock()\n"
            "_LOCK_B = threading.Lock()\n"
            "def f():\n"
            "    with _LOCK_A:\n"
            "        with _LOCK_B:\n"
            "            return 1\n"
        ),
        "m2.py": (
            "from m1 import _LOCK_A, _LOCK_B\n"
            "def g():\n"
            "    with _LOCK_B:\n"
            "        with _LOCK_A:\n"
            "            return 2\n"
        ),
    }, "lock-discipline")
    assert len(found) == 1, "\n".join(f.format() for f in found)
    assert "lock-order cycle" in found[0].message
    ev = "\n".join(found[0].evidence)
    assert "m1.py" in ev and "m2.py" in ev


def test_env_registry_membership_test_is_a_read(tmp_path):
    """`"SFT_X" in os.environ` counts as a read: a registered var read
    only that way is NOT drift, and an unregistered one IS a finding."""
    registry = (
        'ENV_VARS = {"SFT_FLAG": {"owner": "m", "hazard": "tuning"}}\n'
        "def gate_scrub_vars():\n"
        "    return []\n"
    )
    clean = _scratch_repo(tmp_path, {
        "spatialflink_tpu/envvars.py": registry,
        "spatialflink_tpu/mod.py": (
            "import os\n"
            "def f():\n"
            '    return "SFT_FLAG" in os.environ\n'
        ),
    }, "env-registry")
    assert clean == [], "\n".join(f.format() for f in clean)
    bad = _scratch_repo(tmp_path / "b", {
        "spatialflink_tpu/envvars.py": registry,
        "spatialflink_tpu/mod.py": (
            "import os\n"
            "def f():\n"
            '    return ("SFT_FLAG" in os.environ\n'
            '            and "SFT_NOPE" in os.environ)\n'
        ),
    }, "env-registry")
    assert len(bad) == 1, "\n".join(f.format() for f in bad)
    assert "SFT_NOPE" in bad[0].message


def test_v3_cli_json_carries_evidence_chains():
    root = os.path.join(FIXTURES, "lock_discipline_bad")
    res = _cli("--no-cache", "--pass", "lock-discipline",
               "--project-root", root, "--json", root)
    assert res.returncode == 1, res.stdout + res.stderr
    data = json.loads(res.stdout)
    assert data["counts"]["lock-discipline"] == 4
    evs = [f["evidence"] for f in data["findings"]]
    assert all(evs), "every v3 finding carries a resolved chain"
    # the cycle finding resolves the full ring across both modules
    assert any(len(e) >= 5 for e in evs)


def test_lock_discipline_tree_pragmas_are_live():
    """The four telemetry provider-callback sites (stream seal + the
    overload, qserve, and dag snapshot providers) are real findings
    held by documented pragmas — if any goes stale (the hazard is fixed
    or the pass stops seeing it), pragma-staleness fails the tree, so
    this pin just keeps the justification honest."""
    import re

    src = open(os.path.join(
        REPO, "spatialflink_tpu", "telemetry.py")).read()
    assert len(re.findall(r"sfcheck: ok=lock-discipline", src)) == 4


# -- v3 satellite: analyzer-cost telemetry -----------------------------------


def test_json_carries_timings_and_cache_stats(tmp_path, monkeypatch):
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "aa.py").write_text("x = 1\n")
    monkeypatch.setattr(core, "default_targets", lambda: [str(proj)])
    cache_path = str(tmp_path / "cache.json")
    r1 = driver.run(changed=True, cache_path=cache_path)
    assert r1.cache_misses == 1 and r1.cache_hits == 0
    assert r1.elapsed_s > 0
    assert set(PASS_NAMES) - {"pragma-staleness"} <= \
        set(r1.timings) | {p.name for p in ALL_PASSES}
    # project passes + the call-graph build are timed individually
    for name in ("call-graph", "lock-discipline", "contract-twin"):
        assert name in r1.timings
    r2 = driver.run(changed=True, cache_path=cache_path)
    assert r2.cache_hits == 1 and r2.cache_misses == 0


def test_changed_warm_one_file_edit_stays_subsecond(tmp_path, monkeypatch):
    """The satellite pin: with all seventeen passes registered, a warm
    --changed run (everything cached) stays sub-second."""
    import time as _time

    proj = tmp_path / "proj"
    proj.mkdir()
    for i in range(20):
        (proj / f"m{i}.py").write_text(
            "import threading\n_LOCK = threading.Lock()\n"
            "def f():\n    with _LOCK:\n        return 1\n")
    monkeypatch.setattr(core, "default_targets", lambda: [str(proj)])
    cache_path = str(tmp_path / "cache.json")
    driver.run(changed=True, cache_path=cache_path)  # cold fill
    t0 = _time.monotonic()
    report = driver.run(changed=True, cache_path=cache_path)
    assert _time.monotonic() - t0 < 1.0
    assert report.cache_hits == 20 and report.cache_misses == 0


def test_cli_human_summary_line_in_default_mode(tmp_path, monkeypatch):
    """Whole-tree (gate) runs always print the cost summary; targeted
    runs stay quiet-when-clean (pinned above in the exit-code test)."""
    from tools.sfcheck import cli
    from tools.sfcheck.core import Report

    monkeypatch.setattr(cli.driver, "run", lambda **k: Report(
        [], 42, ["hotpath"], timings={"hotpath": 0.5},
        cache_hits=40, cache_misses=2, elapsed_s=0.9,
        default_mode=True))
    import io
    import contextlib

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert cli.main([]) == 0
    out = buf.getvalue()
    assert "42 file(s)" in out and "cache 40 hit / 2 miss" in out
    assert "slowest pass hotpath" in out


def test_cache_roundtrip_preserves_v3_facts(tmp_path, monkeypatch):
    """Cache-invalidation legs for the new fact kinds: verdicts from
    cached facts must equal fresh analysis — lock spans, env reads,
    emit sites, constants, and the main guard all ride the JSON cache."""
    proj = tmp_path / "proj"
    (proj / "spatialflink_tpu").mkdir(parents=True)
    (proj / "tools").mkdir()
    (proj / "spatialflink_tpu" / "envvars.py").write_text(
        'ENV_VARS = {"SFT_A": {"owner": "m", "hazard": "armed"}}\n'
        "def gate_scrub_vars():\n"
        '    return [n for n, m in ENV_VARS.items()'
        ' if m["hazard"] == "armed"]\n'
    )
    mod = proj / "spatialflink_tpu" / "mod.py"
    mod.write_text(
        "import os\nimport threading\n_LOCK = threading.Lock()\n"
        "def f(tel):\n"
        '    a = os.environ.get("SFT_A")\n'
        "    with _LOCK:\n        pass\n"
        "    return a\n"
    )
    (proj / "tools" / "ci.py").write_text(
        "def _cpu_env(reg):\n"
        "    for v in reg.gate_scrub_vars():\n"
        "        pass\n"
    )
    monkeypatch.setattr(core, "default_targets", lambda: [str(proj)])
    monkeypatch.setattr(core, "relpath_of", lambda p: os.path.relpath(
        os.path.abspath(p), str(proj)).replace(os.sep, "/"))
    cache_path = str(tmp_path / "cache.json")
    for _ in range(3):  # cold, warm, warm-after-resave
        report = driver.run(changed=True, cache_path=cache_path)
        assert report.findings == [], "\n".join(
            f.format() for f in report.findings)
    # edit the reader to add an unregistered var + an emit-under-lock:
    # only that file re-analyzes, and BOTH new-fact verdicts update
    mod.write_text(
        "import os\nimport threading\n_LOCK = threading.Lock()\n"
        "def f(tel):\n"
        '    a = os.environ.get("SFT_A")\n'
        '    b = os.environ.get("SFT_NEW_UNREGISTERED")\n'
        "    with _LOCK:\n"
        '        tel.emit_instant("boom")\n'
        "    return a, b\n"
    )
    report = driver.run(changed=True, cache_path=cache_path)
    assert report.cache_misses == 1 and report.cache_hits == 2
    by_pass = {}
    for f in report.findings:
        by_pass.setdefault(f.pass_name, []).append(f)
    assert len(by_pass.get("env-registry", [])) == 1
    assert len(by_pass.get("lock-discipline", [])) == 1


# -- v4: checkpoint-schema / replay-determinism / collective-accounting ------


def test_checkpoint_schema_fixture_repo():
    bad = _mini_repo("checkpoint_schema_bad", "checkpoint-schema")
    assert len(bad) == 3, "\n".join(f.format() for f in bad)
    msgs = "\n".join(f.message for f in bad)
    # all three rules, one finding each
    assert "has no published producer" in msgs
    assert "is never restored" in msgs
    assert "published conditionally but read without a legacy default" \
        in msgs
    assert all(f.evidence for f in bad)
    # the publish-without-legacy-default pair: evidence names BOTH halves
    rule3 = next(f for f in bad if "legacy default" in f.message)
    ev = "\n".join(rule3.evidence)
    assert "writes 'compaction_rung' inside a conditional branch" in ev
    assert "bare unconditional" in ev
    assert _mini_repo("checkpoint_schema_clean", "checkpoint-schema") == []


def test_replay_determinism_fixture_repo():
    bad = _mini_repo("replay_determinism_bad", "replay-determinism")
    assert len(bad) == 3, "\n".join(f.format() for f in bad)
    msgs = "\n".join(f.message for f in bad)
    assert "wall-clock read" in msgs
    assert "global unseeded RNG draw" in msgs
    # the set-iteration-into-commit egress repro, root named in evidence
    setf = next(f for f in bad if "set" in f.message
                and "hash seed" in f.message)
    assert "exactly-once egress commit" in setf.evidence[0]
    # the cross-function leg resolves the commit -> _stamp call step
    wall = next(f for f in bad if "wall-clock" in f.message)
    assert len(wall.evidence) >= 3
    assert any("`commit` calls `_stamp" in e for e in wall.evidence)
    # the checkpoint-publisher root class is also covered
    rng = next(f for f in bad if "RNG" in f.message)
    assert "checkpoint publisher" in rng.evidence[0]
    assert _mini_repo("replay_determinism_clean", "replay-determinism") \
        == []


def test_collective_accounting_fixture_repo():
    bad = _mini_repo("collective_accounting_bad", "collective-accounting")
    assert len(bad) == 3, "\n".join(f.format() for f in bad)
    msgs = "\n".join(f.message for f in bad)
    assert "lax.all_gather" in msgs and "lax.psum" in msgs
    assert "lax.ppermute" in msgs  # the unaccounted halo-exchange kind
    # the wrapper-covered stats_kernel stays clean; only the uncovered
    # kernels (halo.py's gather/psum pair, ring.py's ppermute) flag
    assert all(f.path.endswith(("halo.py", "ring.py")) for f in bad)
    ev = "\n".join(e for f in bad for e in f.evidence)
    assert "unreachable from all 1 accounting wrapper(s)" in ev
    assert all(f.evidence for f in bad)
    assert _mini_repo("collective_accounting_clean",
                      "collective-accounting") == []


@pytest.mark.parametrize("fixture,pass_name,expect", [
    ("checkpoint_schema_bad", "checkpoint-schema", 3),
    ("replay_determinism_bad", "replay-determinism", 3),
    ("collective_accounting_bad", "collective-accounting", 3),
])
def test_v4_cli_json_project_root_evidence(fixture, pass_name, expect):
    """The --project-root CLI leg per new pass: exit 1, per-pass count,
    and a resolved evidence chain on every finding."""
    root = os.path.join(FIXTURES, fixture)
    res = _cli("--no-cache", "--pass", pass_name,
               "--project-root", root, "--json", root)
    assert res.returncode == 1, res.stdout + res.stderr
    data = json.loads(res.stdout)
    assert data["counts"][pass_name] == expect
    assert all(f["evidence"] for f in data["findings"])
    assert any(len(f["evidence"]) >= 2 for f in data["findings"])


def test_cache_roundtrip_preserves_v4_facts(tmp_path, monkeypatch):
    """Cache-invalidation legs for the v4 fact kinds: checkpoint payload
    writes/reads and nondeterminism sites ride the JSON cache, and an
    edit that adds new instances re-analyzes exactly the edited file
    with both verdicts updating."""
    proj = tmp_path / "proj"
    proj.mkdir()
    op = proj / "op.py"
    op.write_text(
        "class Op:\n"
        "    def state(self):\n"
        '        return {"carry": self.carry}\n'
        "    def restore(self, state):\n"
        '        self.carry = state["carry"]\n'
    )
    (proj / "sink.py").write_text(
        "class FileSink:\n"
        "    def commit(self, rows):\n"
        "        for r in sorted({x.oid for x in rows}):\n"
        "            self.fh.write(str(r))\n"
    )
    monkeypatch.setattr(core, "default_targets", lambda: [str(proj)])
    monkeypatch.setattr(core, "relpath_of", lambda p: os.path.relpath(
        os.path.abspath(p), str(proj)).replace(os.sep, "/"))
    cache_path = str(tmp_path / "cache.json")
    for _ in range(2):  # cold fill, then fully-cached verdict
        report = driver.run(changed=True, cache_path=cache_path)
        assert report.findings == [], "\n".join(
            f.format() for f in report.findings)
    # edit: a bare read of a key the publisher never writes + a
    # wall-clock read inside the publisher
    op.write_text(
        "import time\n"
        "class Op:\n"
        "    def state(self):\n"
        '        return {"carry": self.carry, "at": time.time()}\n'
        "    def restore(self, state):\n"
        '        self.carry = state["carry"]\n'
        '        self.wm = state["watermark"]\n'
    )
    report = driver.run(changed=True, cache_path=cache_path)
    assert report.cache_misses == 1 and report.cache_hits == 1
    by_pass = {}
    for f in report.findings:
        by_pass.setdefault(f.pass_name, []).append(f)
    assert len(by_pass.get("checkpoint-schema", [])) >= 1
    assert len(by_pass.get("replay-determinism", [])) == 1


# -- v4 satellite: --format=github + per-pass summary counts -----------------


def test_cli_github_format_emits_workflow_commands(tmp_path):
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import jax.numpy as jnp\nX = jnp.zeros(3)\n")
    res = _cli("--no-cache", "--pass", "hotpath", "--format=github",
               str(dirty))
    assert res.returncode == 1, res.stdout + res.stderr  # codes unchanged
    lines = [ln for ln in res.stdout.splitlines()
             if ln.startswith("::error ")]
    assert len(lines) == 1
    assert "line=2" in lines[0] and "title=hotpath" in lines[0]
    # same input, human mode: identical exit, no workflow commands
    res_h = _cli("--no-cache", "--pass", "hotpath", str(dirty))
    assert res_h.returncode == 1
    assert "::error" not in res_h.stdout
    # clean input exits 0 with no commands either way
    clean = tmp_path / "clean.py"
    clean.write_text("import numpy as np\nX = np.zeros(3)\n")
    res_c = _cli("--no-cache", "--pass", "hotpath", "--format=github",
                 str(clean))
    assert res_c.returncode == 0 and "::error" not in res_c.stdout


def test_cli_github_format_escapes_evidence_chain():
    """Project-pass findings carry the ↳ chain inside the annotation,
    %0A-escaped — one single-line workflow command per finding."""
    root = os.path.join(FIXTURES, "replay_determinism_bad")
    res = _cli("--no-cache", "--pass", "replay-determinism",
               "--project-root", root, "--format=github", root)
    assert res.returncode == 1, res.stdout + res.stderr
    errors = [ln for ln in res.stdout.splitlines()
              if ln.startswith("::error ")]
    assert len(errors) == 3
    assert all("%0A↳" in ln for ln in errors)
    assert all("title=replay-determinism" in ln for ln in errors)


def test_cli_summary_line_prints_per_pass_counts():
    root = os.path.join(FIXTURES, "checkpoint_schema_bad")
    res = _cli("--no-cache", "--pass", "checkpoint-schema",
               "--project-root", root, root)
    assert res.returncode == 1
    assert "(checkpoint-schema 3)" in res.stdout


# -- targeted regressions for the violations fixed in this tree --------------

def test_no_block_until_ready_outside_telemetry():
    # __graft_entry__.py and tests/test_graft_entry.py used the no-op
    # block_until_ready as a "sync"; they now device_get. The ban covers
    # the driver surface, bench, the whole test tree, and the PR 7
    # additions: the SLO engine and the sfprof stream/recover modules
    # (the link probe's true-sync fetch lives in telemetry.py, the ONE
    # exempt module).
    sync = get_pass("sync-discipline")
    report = core.run_paths(
        [os.path.join(REPO, p) for p in
         ("__graft_entry__.py", "bench.py", "bench_suite.py", "tests",
          os.path.join("spatialflink_tpu", "slo.py"),
          os.path.join("tools", "sfprof"))],
        [sync], force_files=True,
    )
    assert report.findings == [], "\n".join(
        f.format() for f in report.findings
    )


def test_egress_fstrings_are_numpy_safe():
    # The twice-shipped bug: numpy ≥2 scalars reaching egress f-strings
    # print as np.float32(…). The egress layers now wrap in float() —
    # including the PR 7 surfaces: the SLO engine (check rows/violation
    # events land in ledgers and streams) and all of tools/sfprof
    # (report/diff/health/recover print parsed ledger values).
    fstr = get_pass("fstring-numpy")
    report = core.run_paths(
        [os.path.join(REPO, "bench.py"),
         os.path.join(REPO, "spatialflink_tpu", "sncb"),
         os.path.join(REPO, "spatialflink_tpu", "mn"),
         os.path.join(REPO, "spatialflink_tpu", "telemetry.py"),
         os.path.join(REPO, "spatialflink_tpu", "slo.py"),
         os.path.join(REPO, "tools", "sfprof")],
        [fstr], force_files=True,
    )
    assert report.findings == [], "\n".join(
        f.format() for f in report.findings
    )


def test_new_observability_modules_are_in_pass_scope():
    """The scope EXTENSION itself is pinned: fstring-numpy must apply to
    the SLO engine and every sfprof module; sync-discipline must apply
    everywhere except telemetry.py (slo.py and the stream modules are
    NOT exempt)."""
    fstr = get_pass("fstring-numpy")
    assert fstr.applies_to("spatialflink_tpu/slo.py")
    assert fstr.applies_to("tools/sfprof/stream.py")
    assert fstr.applies_to("tools/sfprof/slo.py")
    assert fstr.applies_to("tools/sfprof/cli.py")
    sync = get_pass("sync-discipline")
    assert sync.applies_to("spatialflink_tpu/slo.py")
    assert sync.applies_to("tools/sfprof/stream.py")
    assert not sync.applies_to("spatialflink_tpu/telemetry.py")


def test_overload_module_is_in_pass_scope():
    """ISSUE 9 scope pin: overload.py joined the fstring-numpy egress
    scope (transition events + smoke output) and the hotpath
    import-purity scope (the fire-site hooks import it from every
    assembler — an import-time dispatch there would dial the tunnel)."""
    fstr = get_pass("fstring-numpy")
    assert fstr.applies_to("spatialflink_tpu/overload.py")
    hot = get_pass("hotpath")
    assert hot.applies_to("spatialflink_tpu/overload.py")
    assert hot.applies_to("spatialflink_tpu/driver.py")


def test_trajectory_wkt_formats_numpy_scalars_clean():
    from spatialflink_tpu.sncb.common import GpsEvent
    from spatialflink_tpu.sncb.ops import trajectory_wkt

    events = [
        GpsEvent(device_id="t1", ts=i,
                 lon=np.float64(4.5 + i), lat=np.float64(50.85))
        for i in range(2)
    ]
    wkt = trajectory_wkt(events)
    assert "np." not in wkt
    assert wkt == "LINESTRING (4.5 50.85, 5.5 50.85)"
    single = trajectory_wkt(events[:1])
    assert single == "POINT (4.5 50.85)"


def test_metrics_sink_row_numpy_safe(tmp_path):
    from spatialflink_tpu.sncb.metrics import MetricsSink

    sink = MetricsSink("q", path=str(tmp_path / "m.csv"), interval_s=0.0)
    # Event timestamp as a numpy scalar — the latency column must still
    # render as a plain decimal.
    sink.record(event_ts_ms=np.int64(0), n=3)
    sink.close()
    assert sink.rows, "no interval flushed"
    for row in sink.rows:
        assert "np." not in row, row


def test_reporter_line_numpy_safe(tmp_path):
    from spatialflink_tpu.mn.metrics import MetricNames, MetricRegistry
    from spatialflink_tpu.mn.reporter import NESFileReporter

    reg = MetricRegistry()
    reg.inc(MetricNames.SOURCE_IN, 10)
    reg.inc(MetricNames.SINK_OUT, 5)
    rep = NESFileReporter(reg, "q1", out_dir=str(tmp_path))
    line = rep.report(now=rep._last_time + 2.0)
    assert line.startswith("METRICS ts=")
    assert "np." not in line
    assert "eps_in_avg=5.00" in line


def test_fault_tolerance_modules_are_in_pass_scope():
    """ISSUE 8 satellite pin: the fault-tolerance layer joined the
    sfcheck scopes — fstring-numpy (driver/faults render egress lines
    and fault events), sync-discipline (tree-wide already, pinned
    explicitly), and hotpath's import-purity rule (module-scope eager
    jnp would be an import-time tunnel dial — the one thing faults.py
    exists to survive). The wall-clock rule stays ops/-only: retry
    backoff and the hang kind legitimately read the clock."""
    fstr = get_pass("fstring-numpy")
    assert fstr.applies_to("spatialflink_tpu/driver.py")
    assert fstr.applies_to("spatialflink_tpu/faults.py")
    sync = get_pass("sync-discipline")
    assert sync.applies_to("spatialflink_tpu/driver.py")
    assert sync.applies_to("spatialflink_tpu/faults.py")
    hp = get_pass("hotpath")
    assert hp.applies_to("spatialflink_tpu/driver.py")
    assert hp.applies_to("spatialflink_tpu/faults.py")
    assert not hp.applies_to("spatialflink_tpu/streaming_job.py")

    # Import-purity finding fires in the fault-tolerance modules...
    src = """
        import jax.numpy as jnp
        BAD = jnp.zeros(4)
    """
    findings = _check(src, "hotpath", name="spatialflink_tpu/driver.py")
    assert len(findings) == 1 and "module-level" in findings[0].message
    # ...but the wall-clock rule does not (host control plane).
    src = """
        import time

        def backoff():
            return time.monotonic()
    """
    assert _check(src, "hotpath",
                  name="spatialflink_tpu/driver.py") == []
    assert len(_check(src, "hotpath",
                      name="spatialflink_tpu/ops/k.py")) == 1


def test_fault_tolerance_modules_are_clean():
    """The new modules pass their own scopes with zero findings."""
    report = core.run_paths(
        [os.path.join(REPO, "spatialflink_tpu", "driver.py"),
         os.path.join(REPO, "spatialflink_tpu", "faults.py")],
        [get_pass("hotpath"), get_pass("fstring-numpy"),
         get_pass("sync-discipline")],
        force_files=True,
    )
    assert report.findings == [], "\n".join(
        f.format() for f in report.findings
    )
