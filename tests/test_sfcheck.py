"""Tier-1 sfcheck (tools/sfcheck): the multi-pass analyzer keeps the whole
tree clean, every pass provably detects its target class (fixture corpus
under tests/fixtures/sfcheck/), pragma suppression and the --json CLI
contract hold, and the violations fixed in this tree stay fixed
(block_until_ready egress, numpy-scalar f-strings).
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.sfcheck import core  # noqa: E402
from tools.sfcheck.passes import ALL_PASSES, PASS_NAMES, get_pass  # noqa: E402

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "sfcheck")

# Subprocesses must never dial the axon tunnel at interpreter boot.
SUBPROC_ENV = {**os.environ, "PALLAS_AXON_POOL_IPS": ""}


def _check(src, pass_name, name="mod.py"):
    return core.check_source(name, textwrap.dedent(src),
                             [get_pass(pass_name)], force=True)


def _fixture(name, pass_names):
    path = os.path.join(FIXTURES, name)
    return core.check_file(path, [get_pass(n) for n in pass_names],
                           force=True)


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.sfcheck", *args],
        capture_output=True, text=True, cwd=REPO, env=SUBPROC_ENV,
    )


# -- the analyzer itself -----------------------------------------------------

def test_all_five_passes_registered():
    assert set(PASS_NAMES) == {
        "hotpath", "trace-hygiene", "fixed-shape", "sync-discipline",
        "fstring-numpy",
    }
    for p in ALL_PASSES:
        assert p.description and p.invariant


def test_repo_tree_is_clean():
    report = core.run_paths(core.default_targets())
    assert report.findings == [], "\n".join(
        f.format() for f in report.findings
    )
    # The scan actually covered the tree, not an empty walk.
    assert report.files > 100


def test_cli_json_breakdown_over_real_tree():
    # The ISSUE's CI contract: full analyzer over the package, bench.py
    # and tools/ reports a per-pass breakdown of all zeros.
    res = _cli("--json", "spatialflink_tpu", "bench.py", "tools")
    assert res.returncode == 0, res.stdout + res.stderr
    data = json.loads(res.stdout)
    assert data["findings"] == []
    assert set(data["counts"]) == set(PASS_NAMES)
    assert all(v == 0 for v in data["counts"].values())
    assert data["files"] > 70


# -- fixture corpus: one true-positive + one clean file per pass -------------

@pytest.mark.parametrize("pass_name,expect_bad", [
    ("hotpath", 5),
    ("trace-hygiene", 5),
    ("fixed-shape", 6),
    ("sync-discipline", 3),
    ("fstring-numpy", 4),
])
def test_fixture_corpus(pass_name, expect_bad):
    stem = pass_name.replace("-", "_")
    bad = _fixture(f"{stem}_bad.py", [pass_name])
    assert len(bad) == expect_bad, "\n".join(f.format() for f in bad)
    assert all(f.pass_name == pass_name for f in bad)
    assert _fixture(f"{stem}_clean.py", [pass_name]) == []


def test_pragma_fixture_suppresses_every_class():
    assert _fixture("pragmas_ok.py", list(PASS_NAMES)) == []


# -- pragma semantics --------------------------------------------------------

def test_bare_pragma_suppresses_all_passes():
    src = """
        import jax
        def f(x):
            jax.block_until_ready(x)  # sfcheck: ok
    """
    assert _check(src, "sync-discipline") == []


def test_named_pragma_suppresses_only_that_pass():
    src = """
        import jax
        def f(x):
            jax.block_until_ready(x)  # sfcheck: ok=sync-discipline -- why
    """
    assert _check(src, "sync-discipline") == []
    # The same pragma naming a DIFFERENT pass does not suppress.
    wrong = src.replace("ok=sync-discipline", "ok=hotpath")
    assert len(_check(wrong, "sync-discipline")) == 1


def test_pragma_spans_multiline_call():
    src = """
        import jax.numpy as jnp
        def f(mask):
            return jnp.nonzero(
                mask,
            )  # sfcheck: ok=fixed-shape -- fixture: pragma on the close paren
    """
    assert _check(src, "fixed-shape") == []


def test_syntax_error_is_reported_not_swallowed():
    findings = core.check_source("broken.py", "def f(:\n", ALL_PASSES,
                                 force=True)
    assert len(findings) == 1 and findings[0].pass_name == "syntax"


# -- CLI contract ------------------------------------------------------------

def test_cli_exit_codes_and_human_output(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\ndef f(x):\n    jax.block_until_ready(x)\n")
    res = _cli("--pass", "sync-discipline", str(bad))
    assert res.returncode == 1
    assert "bad.py:3" in res.stdout and "[sync-discipline]" in res.stdout
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    res = _cli("--pass", "sync-discipline", str(clean))
    assert res.returncode == 0 and res.stdout == ""


def test_cli_json_on_fixture():
    res = _cli("--pass", "fixed-shape", "--json",
               os.path.join(FIXTURES, "fixed_shape_bad.py"))
    assert res.returncode == 1
    data = json.loads(res.stdout)
    assert data["counts"] == {"fixed-shape": 6}
    assert {f["pass"] for f in data["findings"]} == {"fixed-shape"}
    assert all(f["line"] > 0 and f["message"] for f in data["findings"])


def test_cli_unknown_pass_is_usage_error():
    res = _cli("--pass", "no-such-pass")
    assert res.returncode == 2
    assert "unknown pass" in res.stderr


def test_cli_list_passes():
    res = _cli("--list-passes")
    assert res.returncode == 0
    for name in PASS_NAMES:
        assert name in res.stdout


# -- targeted regressions for the violations fixed in this tree --------------

def test_no_block_until_ready_outside_telemetry():
    # __graft_entry__.py and tests/test_graft_entry.py used the no-op
    # block_until_ready as a "sync"; they now device_get. The ban covers
    # the driver surface, bench, and the whole test tree.
    sync = get_pass("sync-discipline")
    report = core.run_paths(
        [os.path.join(REPO, p) for p in
         ("__graft_entry__.py", "bench.py", "bench_suite.py", "tests")],
        [sync], force_files=True,
    )
    assert report.findings == [], "\n".join(
        f.format() for f in report.findings
    )


def test_egress_fstrings_are_numpy_safe():
    # The twice-shipped bug: numpy ≥2 scalars reaching egress f-strings
    # print as np.float32(…). The egress layers now wrap in float().
    fstr = get_pass("fstring-numpy")
    report = core.run_paths(
        [os.path.join(REPO, "bench.py"),
         os.path.join(REPO, "spatialflink_tpu", "sncb"),
         os.path.join(REPO, "spatialflink_tpu", "mn"),
         os.path.join(REPO, "spatialflink_tpu", "telemetry.py")],
        [fstr], force_files=True,
    )
    assert report.findings == [], "\n".join(
        f.format() for f in report.findings
    )


def test_trajectory_wkt_formats_numpy_scalars_clean():
    from spatialflink_tpu.sncb.common import GpsEvent
    from spatialflink_tpu.sncb.ops import trajectory_wkt

    events = [
        GpsEvent(device_id="t1", ts=i,
                 lon=np.float64(4.5 + i), lat=np.float64(50.85))
        for i in range(2)
    ]
    wkt = trajectory_wkt(events)
    assert "np." not in wkt
    assert wkt == "LINESTRING (4.5 50.85, 5.5 50.85)"
    single = trajectory_wkt(events[:1])
    assert single == "POINT (4.5 50.85)"


def test_metrics_sink_row_numpy_safe(tmp_path):
    from spatialflink_tpu.sncb.metrics import MetricsSink

    sink = MetricsSink("q", path=str(tmp_path / "m.csv"), interval_s=0.0)
    # Event timestamp as a numpy scalar — the latency column must still
    # render as a plain decimal.
    sink.record(event_ts_ms=np.int64(0), n=3)
    sink.close()
    assert sink.rows, "no interval flushed"
    for row in sink.rows:
        assert "np." not in row, row


def test_reporter_line_numpy_safe(tmp_path):
    from spatialflink_tpu.mn.metrics import MetricNames, MetricRegistry
    from spatialflink_tpu.mn.reporter import NESFileReporter

    reg = MetricRegistry()
    reg.inc(MetricNames.SOURCE_IN, 10)
    reg.inc(MetricNames.SINK_OUT, 5)
    rep = NESFileReporter(reg, "q1", out_dir=str(tmp_path))
    line = rep.report(now=rep._last_time + 2.0)
    assert line.startswith("METRICS ts=")
    assert "np." not in line
    assert "eps_in_avg=5.00" in line
