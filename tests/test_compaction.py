"""Live-slot compaction (ops/compaction.py + the compacted positional
probe in ops/tjoin_panes.py): bucket-ladder control plane, exact host
occupancy planning, occupancy-sweep bit-parity of the compacted scan vs
the full-ring scan and run_soa, the cmp_overflow ladder-climb retry,
and the ≤K-stable-signatures recompile contract."""

import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.operators import QueryConfiguration, QueryType
from spatialflink_tpu.operators.trajectory import TJoinQuery
from spatialflink_tpu.ops.compaction import (
    capacity_ladder,
    compact_probe_preferred,
    max_window_cell_count,
    pick_capacity,
    wire_pane_bucket,
)
from spatialflink_tpu.telemetry import telemetry

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)


# ---------------------------------------------------------------------------
# host control plane


def test_capacity_ladder_is_small():
    assert capacity_ladder(64) == (8, 16, 32, 64)
    assert capacity_ladder(256) == (8, 16, 32, 64, 128, 256)
    assert len(capacity_ladder(256)) <= 6  # the ≤K compile bound
    # non-power-of-two ring caps keep the full ring as the top rung
    assert capacity_ladder(48) == (8, 16, 32, 48)
    assert capacity_ladder(4) == (4,)


def test_pick_capacity_buckets():
    assert pick_capacity(0, 64) == 8
    assert pick_capacity(1, 64) == 8
    assert pick_capacity(8, 64) == 8
    assert pick_capacity(9, 64) == 16
    assert pick_capacity(64, 64) == 64
    assert pick_capacity(1000, 64) == 64  # clamps to the ring cap


def test_max_window_cell_count_matches_bruteforce():
    rng = np.random.default_rng(3)
    for ppw in (1, 3, 7):
        pane = rng.integers(0, 40, 400).astype(np.int64)
        cell = rng.integers(0, 9, 400).astype(np.int64)
        got = max_window_cell_count(pane, cell, ppw)
        brute = 0
        for c in range(9):
            ps = pane[cell == c]
            for t in range(41):
                brute = max(
                    brute, int(((ps > t - ppw) & (ps <= t)).sum())
                )
        assert got == brute, (ppw, got, brute)
    assert max_window_cell_count(np.empty(0, np.int64),
                                 np.empty(0, np.int64), 5) == 0


def test_wire_pane_bucket_records_occupancy():
    telemetry.enable()
    try:
        assert wire_pane_bucket(0) == 128
        assert wire_pane_bucket(100) == 128
        assert wire_pane_bucket(129) == 256
        assert wire_pane_bucket(200) == 256
        buckets = telemetry.compaction_buckets("wire_pane_digest")
        assert buckets[128]["picks"] == 2
        assert buckets[128]["max_live"] == 100
        assert buckets[256]["picks"] == 2
        assert buckets[256]["max_live"] == 200
        snap = telemetry.snapshot()
        assert snap["compaction"]["wire_pane_digest"]["256"]["picks"] == 2
    finally:
        telemetry.disable()


@pytest.mark.parametrize("C", [1, 2, 7, 8, 16, 57, 64, 100])
def test_first_k_prefix_indices_matches_topk(C):
    """The sort-free selection must pick the identical set as top_k over
    the int8 mask for ANY row width — including powers of two, where an
    off-by-one in the binary-search depth (⌈log₂(C+1)⌉ halvings of the
    [0, C] interval) once returned wrong lanes (code review)."""
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.ops.select import first_k_prefix_indices

    rng = np.random.default_rng(C)
    for k in (1, 3, 16):
        mask = jnp.asarray(rng.random((13, C)) < 0.3)
        ci, count, over = jax.jit(
            first_k_prefix_indices, static_argnums=1
        )(mask, k)
        m = np.asarray(mask)
        exp_count = m.sum(axis=1)
        np.testing.assert_array_equal(np.asarray(count), exp_count)
        assert int(over) == int(np.maximum(exp_count - k, 0).sum())
        for i in range(m.shape[0]):
            exp = np.flatnonzero(m[i])[:k]
            np.testing.assert_array_equal(
                np.asarray(ci)[i, :len(exp)], exp,
                err_msg=f"C={C} k={k} row={i}",
            )


# ---------------------------------------------------------------------------
# occupancy-sweep parity: compacted scan ≡ full-ring scan ≡ run_soa


def _single_cell_chunks(occ_per_pane, n_panes, slide_ms, n_obj, rng,
                        x=5.05):
    """``occ_per_pane`` same-cell events in each of ``n_panes`` panes —
    window occupancy is exactly occ_per_pane · min(ppw, panes seen)."""
    ts, xs, ys, oid = [], [], [], []
    for p in range(n_panes):
        for j in range(occ_per_pane):
            ts.append(p * slide_ms + (j % slide_ms))
            xs.append(x + 0.001 * j)
            ys.append(5.05 + 0.001 * ((j * 7) % occ_per_pane))
            oid.append(int(rng.integers(0, n_obj)))
    order = np.argsort(np.asarray(ts, np.int64), kind="stable")
    return [{
        "ts": np.asarray(ts, np.int64)[order],
        "x": np.asarray(xs, float)[order],
        "y": np.asarray(ys, float)[order],
        "oid": np.asarray(oid, np.int32)[order],
    }]


def _key(results):
    out = {}
    for start, end, lo, ro, dd, count, over in results:
        assert over == 0
        out[start] = sorted(
            (int(a), int(b), float(d)) for a, b, d in zip(lo, ro, dd)
        )
    return out


def _run_panes(left, right, radius, n_obj, **kw):
    return _key(TJoinQuery(
        QueryConfiguration(QueryType.WindowBased, window_size=1,
                           slide_step=0.25), GRID,
    ).run_soa_panes(
        iter([dict(c) for c in left]), iter([dict(c) for c in right]),
        radius, num_segments=n_obj, backend="device", **kw,
    ))


@pytest.mark.parametrize("occ_per_pane", [2, 3])
def test_compacted_vs_full_ring_quick(occ_per_pane):
    """Quick-tier pin: compacted probe (auto bucket) bit-matches the
    full-ring probe (cap_c=0) on a bucket-interior occupancy."""
    rng = np.random.default_rng(11)
    left = _single_cell_chunks(occ_per_pane, 12, 250, 8, rng)
    right = _single_cell_chunks(occ_per_pane, 12, 250, 8, rng, x=5.06)
    compacted = _run_panes(left, right, 0.5, 8, cap_w=16)
    full = _run_panes(left, right, 0.5, 8, cap_w=16, cap_c=0)
    assert compacted == full
    assert any(compacted.values()), "degenerate: no pairs anywhere"


@pytest.mark.slow
def test_occupancy_sweep_bit_parity():
    """The padding-never-changes-results pin: window occupancies at
    empty / one-live / bucket-boundary ± 1 / full ring, each run three
    ways — compacted (host-planned bucket), full-ring (cap_c=0), and
    the run_soa oracle — with identical pair sets AND bit-identical
    min distances."""
    rng = np.random.default_rng(7)
    ppw, slide = 4, 250
    cap_w = 16  # ladder (8, 16); window occupancy = 4·occ_per_pane
    # occ_per_pane 1 → occupancy 4 (one-ish live, bucket 8); 2 → 8
    # (boundary); 3 → 12 (boundary+: bucket 16); 4 → 16 (full ring).
    for occ_per_pane in (1, 2, 3, 4):
        left = _single_cell_chunks(occ_per_pane, 3 * ppw, slide, 8, rng)
        right = _single_cell_chunks(occ_per_pane, 3 * ppw, slide, 8, rng,
                                    x=5.06)
        occ = max_window_cell_count(
            left[0]["ts"] // slide,
            GRID.assign_cells_np(
                np.stack([left[0]["x"], left[0]["y"]], axis=1)
            ).astype(np.int64), ppw,
        )
        assert occ == occ_per_pane * ppw  # the sweep hits its target
        compacted = _run_panes(left, right, 0.5, 8, cap_w=cap_w)
        full = _run_panes(left, right, 0.5, 8, cap_w=cap_w, cap_c=0)
        soa = _key(TJoinQuery(
            QueryConfiguration(QueryType.WindowBased, window_size=1,
                               slide_step=0.25), GRID,
        ).run_soa(
            iter([dict(c) for c in left]), iter([dict(c) for c in right]),
            0.5, num_segments=8,
        ))
        # compacted vs full ring: BIT-identical (same candidate sets,
        # same scatter-min arithmetic)
        assert compacted == full, f"occ_per_pane={occ_per_pane}"

        def rounded(res):
            return {s: sorted((a, b, round(d, 9)) for a, b, d in p)
                    for s, p in res.items()}

        # vs the full-window oracle: same pairs, distances to 1e-9
        # (differently-fused programs — the suite-wide contract)
        r_soa, r_cmp = rounded(soa), rounded(compacted)
        for start, pairs in r_soa.items():
            assert r_cmp[start] == pairs, f"window {start}"
    # one-sided "empty window" case: left-only stream still fires
    left = _single_cell_chunks(2, 8, slide, 8, rng)
    right = [{
        "ts": np.asarray([10_000], np.int64), "x": np.asarray([5.0]),
        "y": np.asarray([5.0]), "oid": np.asarray([0], np.int32),
    }]
    compacted = _run_panes(left, right, 0.5, 8, cap_w=cap_w)
    full = _run_panes(left, right, 0.5, 8, cap_w=cap_w, cap_c=0)
    assert compacted == full
    assert all(len(p) == 0 for s, p in compacted.items() if s < 2_000)


def test_out_of_grid_events_keep_fifo_ranks_contiguous():
    """Out-of-grid events must not consume ring ranks in the cell their
    placeholder id aliases (cell 0): ``_insert`` drops them and advances
    the cursor only by the valid count, so an inflated rank would park a
    VALID point beyond the cursor — outside the ``[cursor-live, cursor)``
    live range the compacted probe scans (a silent missed/garbage pair
    with cmp_overflow still 0; the full-ring tag scan was immune).
    Code-review repro, pinned: mixed in/out-of-grid stream, compacted ≡
    full-ring ≡ expected pair."""
    ts = np.asarray([100, 150, 300], np.int64)
    left = [{
        "ts": ts,
        # out-of-grid (-5,-5) precedes the valid cell-0 point (0.2, 0.2)
        "x": np.asarray([-5.0, 0.2, 0.2]),
        "y": np.asarray([-5.0, 0.2, 0.2]),
        "oid": np.asarray([3, 1, 1], np.int32),
    }]
    right = [{
        "ts": ts,
        "x": np.asarray([0.25, 0.25, 0.25]),
        "y": np.asarray([0.2, 0.2, 0.2]),
        "oid": np.asarray([2, 2, 2], np.int32),
    }]
    compacted = _run_panes(left, right, 0.5, 8, cap_w=16)
    full = _run_panes(left, right, 0.5, 8, cap_w=16, cap_c=0)
    assert compacted == full
    for pairs in compacted.values():
        assert all(a == 1 and b == 2 for a, b, _ in pairs), pairs
    assert any(compacted.values())


def test_forced_tiny_cap_c_climbs_ladder_to_exactness():
    """A forced cap_c far below the live occupancy must trip
    cmp_overflow and climb the ladder until the result is exact —
    the forced bucket never wins over correctness."""
    rng = np.random.default_rng(13)
    left = _single_cell_chunks(3, 12, 250, 8, rng)
    right = _single_cell_chunks(3, 12, 250, 8, rng, x=5.06)
    honest = _run_panes(left, right, 0.5, 8, cap_w=16)
    forced = _run_panes(left, right, 0.5, 8, cap_w=16, cap_c=2)
    assert forced == honest
    assert any(honest.values())


@pytest.mark.slow
def test_bucket_ladder_stable_signatures():
    """Recompile contract: sweeping occupancy across every rung
    compiles at most ladder-many scan programs (K ≤ 6), and re-running
    an already-seen occupancy adds NO new signature (no churn after
    warmup). Streams share S and pane capacity so the bucket is the
    only varying static."""
    if not compact_probe_preferred():  # pragma: no cover - TPU runs
        pytest.skip("full-ring probe preferred on this backend")
    rng = np.random.default_rng(5)
    cap_w = 32  # ladder (8, 16, 32)
    n_panes, per_pane = 12, 24

    def spread_chunks(n_cells, shift=0.0):
        # per_pane events per pane, spread over n_cells distinct cells:
        # same pane counts (same padded pane capacity), different
        # concentration (different live occupancy → different bucket).
        ts, xs, ys, oid = [], [], [], []
        for p in range(n_panes):
            for j in range(per_pane):
                c = j % n_cells
                ts.append(p * 250 + j)
                xs.append(0.55 + 0.5 * (c % 18) + shift)
                ys.append(0.55 + 0.5 * (c // 18))
                oid.append(int(rng.integers(0, 8)))
        return [{
            "ts": np.asarray(ts, np.int64),
            "x": np.asarray(xs, float), "y": np.asarray(ys, float),
            "oid": np.asarray(oid, np.int32),
        }]

    telemetry.enable()
    try:
        # occupancies: 24 cells → ≤ 4 live/cell (bucket 8); 8 cells →
        # 12 live (16); 3 cells → 32 live (32: full ring).
        for n_cells in (24, 8, 3, 24):  # 24 repeated: stability probe
            left = spread_chunks(n_cells)
            right = spread_chunks(n_cells, shift=0.01)
            # pair_sel sized for the densest rung so the sel-overflow
            # retry can't add its own (pair_sel-keyed) signatures
            _run_panes(left, right, 0.3, 8, cap_w=cap_w, pair_sel=64)
        sigs = telemetry.distinct_shapes("tjoin_pane_scan")
        assert 1 <= sigs <= len(capacity_ladder(cap_w)), sigs
        buckets = telemetry.compaction_buckets("tjoin_pane_scan")
        assert set(buckets) <= set(capacity_ladder(cap_w))
        assert sum(b["picks"] for b in buckets.values()) == 4
        # the repeated occupancy reused its bucket: picks prove the
        # ladder is stable, signatures prove no recompile churn
        assert buckets[8]["picks"] == 2
    finally:
        telemetry.disable()
