"""CheckIn / StayTime app tests."""

import numpy as np
import pytest

from spatialflink_tpu.apps.checkin import CheckInEvent, check_in_query
from spatialflink_tpu.apps.staytime import (
    cell_sensor_range_intersection,
    cell_stay_time,
    normalized_cell_stay_time,
)
from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import Point, Polygon

GRID = UniformGrid(10, 0.0, 10.0, 0.0, 10.0)


def test_checkin_basic_occupancy():
    evs = [
        CheckInEvent("e1", "room1-in", "u1", 1000),
        CheckInEvent("e2", "room1-in", "u2", 2000),
        CheckInEvent("e3", "room1-out", "u1", 3000),
    ]
    out = list(check_in_query(iter(evs), {"room1": 10}))
    rooms = [(r, occ) for r, cap, occ, _ in out]
    assert rooms[0] == ("room1", 1)
    assert rooms[1] == ("room1", 2)
    assert rooms[-1] == ("room1", 1)
    assert all(cap == 10 for _, cap, _, _ in out)


def test_checkin_inserts_missing_out():
    # u1 checks in twice in a row → a synthetic out at the midpoint.
    evs = [
        CheckInEvent("e1", "room1-in", "u1", 1000),
        CheckInEvent("e2", "room1-in", "u1", 3000),
    ]
    out = list(check_in_query(iter(evs), {"room1": 5}))
    occs = [occ for _, _, occ, _ in out]
    # in (1), synthetic out (0), in (1)
    assert occs == [1, 0, 1]


def test_checkin_inserts_missing_in():
    evs = [
        CheckInEvent("e1", "room2-out", "u1", 1000),
        CheckInEvent("e2", "room2-out", "u1", 5000),
    ]
    out = list(check_in_query(iter(evs), {}))
    occs = [occ for _, _, occ, _ in out]
    assert occs == [-1, 0, -1]


def _walk_points():
    # One trajectory dwelling 3 s in cell (1,1) then 2 s in cell (2,1):
    # points at (1.5,1.5) t=0..3000, then (2.5,1.5) t=3000..5000.
    pts = [
        Point(obj_id="a", timestamp=0, x=1.5, y=1.5),
        Point(obj_id="a", timestamp=1500, x=1.6, y=1.5),
        Point(obj_id="a", timestamp=3000, x=2.5, y=1.5),
        Point(obj_id="a", timestamp=5000, x=2.6, y=1.5),
        Point(obj_id="a", timestamp=20_000, x=9.0, y=9.0),  # watermark push
    ]
    return pts


def test_cell_stay_time():
    out = list(cell_stay_time(iter(_walk_points()), set(), 0, 10, 10, GRID))
    first = out[0]
    cells = first[2]
    # Cell (1,1): gaps 1500+1500 = 3000 ms; cell (2,1): 2000 ms.
    assert cells[GRID.cell_name(1 * 10 + 1)] == pytest.approx(3000.0)
    assert cells[GRID.cell_name(2 * 10 + 1)] == pytest.approx(2000.0)


def test_sensor_intersection_and_normalization():
    sensor = Polygon(
        obj_id="s1", timestamp=1000,
        rings=[np.array([[1.2, 1.2], [2.8, 1.2], [2.8, 1.8], [1.2, 1.8], [1.2, 1.2]])],
    )
    late = Polygon(
        obj_id="s2", timestamp=20_000,
        rings=[np.array([[8, 8], [9, 8], [9, 9], [8, 9], [8, 8]])],
    )
    out = list(
        cell_sensor_range_intersection(iter([sensor, late]), set(), 0, 10, 10, GRID)
    )
    cells = out[0][2]
    # The sensor spans cells (1,1) and (2,1).
    assert cells.get(GRID.cell_name(11)) == 1
    assert cells.get(GRID.cell_name(21)) == 1
    norm = list(
        normalized_cell_stay_time(
            iter(_walk_points()), set(), iter([sensor, late]), set(), 0, 10, 10, GRID
        )
    )
    by_cell = {c: v for c, s, e, v in norm}
    # (3000 ms / 1000 / 1 sensor) * 10 s window = 30.
    assert by_cell[GRID.cell_name(11)] == pytest.approx(30.0)
    assert by_cell[GRID.cell_name(21)] == pytest.approx(20.0)


def test_sensor_intersection_thin_strip_crossing():
    """A thin strip crossing a cell's interior with no vertex inside and no
    cell corner inside must still count (edge-vs-rect test)."""
    strip = Polygon(
        obj_id="strip", timestamp=1000,
        rings=[np.array([
            [-1.0, 4.45], [11.0, 4.45], [11.0, 4.55], [-1.0, 4.55], [-1.0, 4.45]
        ])],
    )
    late = Polygon(obj_id="p", timestamp=20_000,
                   rings=[np.array([[8, 8], [9, 8], [9, 9], [8, 9], [8, 8]])])
    out = list(
        cell_sensor_range_intersection(iter([strip, late]), set(), 0, 10, 10, GRID)
    )
    cells = out[0][2]
    # The strip crosses cells (x, 4) for all x; cell (5,4) has no strip
    # vertex inside it and its corners are outside the thin band.
    assert cells.get(GRID.cell_name(5 * 10 + 4)) == 1
