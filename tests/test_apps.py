"""CheckIn / StayTime app tests."""

import numpy as np
import pytest

from spatialflink_tpu.apps.checkin import CheckInEvent, check_in_query
from spatialflink_tpu.apps.staytime import (
    cell_sensor_range_intersection,
    cell_stay_time,
    normalized_cell_stay_time,
)
from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import Point, Polygon

GRID = UniformGrid(10, 0.0, 10.0, 0.0, 10.0)


def test_checkin_basic_occupancy():
    evs = [
        CheckInEvent("e1", "room1-in", "u1", 1000),
        CheckInEvent("e2", "room1-in", "u2", 2000),
        CheckInEvent("e3", "room1-out", "u1", 3000),
    ]
    out = list(check_in_query(iter(evs), {"room1": 10}))
    rooms = [(r, occ) for r, cap, occ, _ in out]
    assert rooms[0] == ("room1", 1)
    assert rooms[1] == ("room1", 2)
    assert rooms[-1] == ("room1", 1)
    assert all(cap == 10 for _, cap, _, _ in out)


def test_checkin_inserts_missing_out():
    # u1 checks in twice in a row → a synthetic out at the midpoint.
    evs = [
        CheckInEvent("e1", "room1-in", "u1", 1000),
        CheckInEvent("e2", "room1-in", "u1", 3000),
    ]
    out = list(check_in_query(iter(evs), {"room1": 5}))
    occs = [occ for _, _, occ, _ in out]
    # in (1), synthetic out (0), in (1)
    assert occs == [1, 0, 1]


def test_checkin_inserts_missing_in():
    evs = [
        CheckInEvent("e1", "room2-out", "u1", 1000),
        CheckInEvent("e2", "room2-out", "u1", 5000),
    ]
    out = list(check_in_query(iter(evs), {}))
    occs = [occ for _, _, occ, _ in out]
    assert occs == [-1, 0, -1]


def _walk_points():
    # One trajectory dwelling 3 s in cell (1,1) then 2 s in cell (2,1):
    # points at (1.5,1.5) t=0..3000, then (2.5,1.5) t=3000..5000.
    pts = [
        Point(obj_id="a", timestamp=0, x=1.5, y=1.5),
        Point(obj_id="a", timestamp=1500, x=1.6, y=1.5),
        Point(obj_id="a", timestamp=3000, x=2.5, y=1.5),
        Point(obj_id="a", timestamp=5000, x=2.6, y=1.5),
        Point(obj_id="a", timestamp=20_000, x=9.0, y=9.0),  # watermark push
    ]
    return pts


def test_cell_stay_time():
    out = list(cell_stay_time(iter(_walk_points()), set(), 0, 10, 10, GRID))
    first = out[0]
    cells = first[2]
    # Cell (1,1): gaps 1500+1500 = 3000 ms; cell (2,1): 2000 ms.
    assert cells[GRID.cell_name(1 * 10 + 1)] == pytest.approx(3000.0)
    assert cells[GRID.cell_name(2 * 10 + 1)] == pytest.approx(2000.0)


def test_sensor_intersection_and_normalization():
    sensor = Polygon(
        obj_id="s1", timestamp=1000,
        rings=[np.array([[1.2, 1.2], [2.8, 1.2], [2.8, 1.8], [1.2, 1.8], [1.2, 1.2]])],
    )
    late = Polygon(
        obj_id="s2", timestamp=20_000,
        rings=[np.array([[8, 8], [9, 8], [9, 9], [8, 9], [8, 8]])],
    )
    out = list(
        cell_sensor_range_intersection(iter([sensor, late]), set(), 0, 10, 10, GRID)
    )
    cells = out[0][2]
    # The sensor spans cells (1,1) and (2,1).
    assert cells.get(GRID.cell_name(11)) == 1
    assert cells.get(GRID.cell_name(21)) == 1
    norm = list(
        normalized_cell_stay_time(
            iter(_walk_points()), set(), iter([sensor, late]), set(), 0, 10, 10, GRID
        )
    )
    by_cell = {c: v for c, s, e, v in norm}
    # (3000 ms / 1000 / 1 sensor) * 10 s window = 30.
    assert by_cell[GRID.cell_name(11)] == pytest.approx(30.0)
    assert by_cell[GRID.cell_name(21)] == pytest.approx(20.0)


def test_sensor_intersection_thin_strip_crossing():
    """A thin strip crossing a cell's interior with no vertex inside and no
    cell corner inside must still count (edge-vs-rect test)."""
    strip = Polygon(
        obj_id="strip", timestamp=1000,
        rings=[np.array([
            [-1.0, 4.45], [11.0, 4.45], [11.0, 4.55], [-1.0, 4.55], [-1.0, 4.45]
        ])],
    )
    late = Polygon(obj_id="p", timestamp=20_000,
                   rings=[np.array([[8, 8], [9, 8], [9, 9], [8, 9], [8, 8]])])
    out = list(
        cell_sensor_range_intersection(iter([strip, late]), set(), 0, 10, 10, GRID)
    )
    cells = out[0][2]
    # The strip crosses cells (x, 4) for all x; cell (5,4) has no strip
    # vertex inside it and its corners are outside the thin band.
    assert cells.get(GRID.cell_name(5 * 10 + 4)) == 1


def test_cell_stay_time_soa_matches_object_path():
    """Device SoA dwell (stay_time_cells_kernel) must equal the object
    path per (window, cell), including zero-gap keys, out-of-grid "out"
    buckets, and the trajId filter semantics."""
    from spatialflink_tpu.apps.staytime import cell_stay_time_soa

    rng = np.random.default_rng(21)
    n, n_obj = 4_000, 12
    ts = np.sort(rng.integers(0, 40_000, n)).astype(np.int64)
    # include some out-of-grid points and some equal timestamps
    x = rng.uniform(-0.5, 10.5, n)
    y = rng.uniform(-0.5, 10.5, n)
    oid = rng.integers(0, n_obj, n)
    ts[100] = ts[101]  # a zero gap somewhere
    names = [f"obj{i}" for i in range(n_obj)]
    pts = [
        Point(obj_id=names[oid[i]], timestamp=int(ts[i]),
              x=float(x[i]), y=float(y[i]))
        for i in range(n)
    ]
    obj = {
        (s_, e): cells
        for s_, e, cells in cell_stay_time(iter(pts), set(), 0, 10, 5, GRID)
    }
    chunks = [{"ts": ts, "x": x, "y": y, "oid": oid.astype(np.int32)}]
    soa = {}
    for s_, e, cid, dwell in cell_stay_time_soa(iter(chunks), 10, 5, GRID):
        soa[(s_, e)] = {
            (GRID.cell_name(int(c)) if c < GRID.num_cells else "out"):
                float(d)
            for c, d in zip(cid, dwell)
        }
    assert obj, "object path fired no windows"
    for span, cells in obj.items():
        assert span in soa, f"SoA missed window {span}"
        assert soa[span] == cells, f"window {span} diverges"


def test_cell_stay_time_soa_traj_filter():
    from spatialflink_tpu.apps.staytime import cell_stay_time_soa

    # two objects alternating in one cell; filtering one must RE-PAIR
    # the other's consecutive points (compaction, not masking)
    pts = []
    ts = [0, 1000, 2000, 3000, 4000, 5000]
    for i, t in enumerate(ts):
        pts.append(Point(obj_id="keep" if i % 2 == 0 else "drop",
                         timestamp=t, x=1.5, y=1.5))
    obj = list(cell_stay_time(iter(pts), {"keep"}, 0, 10, 10, GRID))
    chunks = [{
        "ts": np.asarray(ts, np.int64),
        "x": np.full(6, 1.5), "y": np.full(6, 1.5),
        "oid": np.asarray([0, 1, 0, 1, 0, 1], np.int32),
    }]
    allow = np.asarray([True, False])
    soa = list(cell_stay_time_soa(iter(chunks), 10, 10, GRID,
                                  oid_allow=allow))
    name = GRID.cell_name(GRID.flat_cell(1.5, 1.5))
    assert obj[0][2] == {name: 4000.0}  # keep: 0->2000->4000
    (s_, e, cid, dwell) = soa[0]
    assert [int(c) for c in cid] == [GRID.flat_cell(1.5, 1.5)]
    assert float(dwell[0]) == 4000.0


def test_cell_stay_time_soa_suppresses_fully_filtered_windows():
    from spatialflink_tpu.apps.staytime import cell_stay_time_soa

    # a window whose only events are filtered out must NOT fire (the
    # object path continues); one kept event fires empty.
    chunks = [{
        "ts": np.asarray([100, 200, 10_100], np.int64),
        "x": np.asarray([1.5, 1.6, 1.5]),
        "y": np.asarray([1.5, 1.6, 1.5]),
        "oid": np.asarray([1, 1, 0], np.int32),
    }]
    allow = np.asarray([True, False])
    out = list(cell_stay_time_soa(iter(chunks), 10, 10, GRID,
                                  oid_allow=allow))
    # window [0,10s): only filtered oid=1 events -> suppressed;
    # window [10s,20s): one kept oid=0 event -> fires empty
    assert [(s_, e, len(c)) for s_, e, c, _ in out] == [(10_000, 20_000, 0)]


def test_checkin_soa_matches_host_walk(rng):
    """The device kernel (ops/checkin.py) must reproduce the host
    count-window walk exactly: same emission sequence (synthesized
    missing events included) and same running occupancy values."""
    from spatialflink_tpu.apps.checkin import check_in_query_soa

    rooms = [f"room{i}" for i in range(6)]
    users = [f"u{i}" for i in range(9)]
    evs = []
    for i in range(400):
        evs.append(CheckInEvent(
            f"e{i}",
            f"{rooms[int(rng.integers(0, 6))]}-"
            f"{'in' if rng.integers(0, 2) else 'out'}",
            users[int(rng.integers(0, 9))],
            timestamp=1000 + i * 7,
        ))
    caps = {"room0": 5, "room3": 2}
    host = [(r, c, o) for r, c, o, _w in check_in_query(iter(evs), caps)]
    soa = [(r, c, o) for r, c, o, _w in check_in_query_soa(iter(evs), caps)]
    assert soa == host
    assert len(host) > 400  # synthesized events actually occurred


def test_checkin_soa_empty_stream():
    from spatialflink_tpu.apps.checkin import check_in_query_soa

    assert list(check_in_query_soa(iter([]), {})) == []
