"""Kafka transport seam, exercised against a fake in-process broker.

The environment ships no Kafka client library or broker (the connector is
gated — streams/kafka.py). These tests install a minimal kafka-python
API fake (KafkaConsumer/KafkaProducer over an in-memory topic dict) and
drive the REAL gated code path end to end: KafkaSink → topic →
kafka_source → serde parse → windowed range query. The record boundary
(one GeoJSON/CSV line per message) is the same seam the reference's
FlinkKafkaConsumer/Producer use (StreamingJob.java:188-191,255).
"""

import sys
import types

import numpy as np
import pytest


BROKER: dict = {}


class _Msg:
    def __init__(self, value):
        self.value = value


class _FakeConsumer:
    def __init__(self, topic, bootstrap_servers=None, group_id=None,
                 auto_offset_reset=None):
        self._msgs = list(BROKER.get(topic, []))
        self.closed = False

    def __iter__(self):
        return (_Msg(v) for v in self._msgs)

    def close(self):
        self.closed = True


class _FakeProducer:
    def __init__(self, bootstrap_servers=None):
        self.flushed = False

    def send(self, topic, value):
        BROKER.setdefault(topic, []).append(value)

    def flush(self):
        self.flushed = True


@pytest.fixture
def fake_kafka(monkeypatch):
    mod = types.SimpleNamespace(
        KafkaConsumer=_FakeConsumer, KafkaProducer=_FakeProducer
    )
    monkeypatch.setitem(sys.modules, "kafka", mod)
    BROKER.clear()
    yield mod
    BROKER.clear()


def test_kafka_always_available_via_builtin_client():
    """The built-in wire client (streams/kafka_wire.py) removed the old
    gate: kafka_available() is True in this image with no pip installs."""
    from spatialflink_tpu.streams.kafka import kafka_available

    assert kafka_available()


def test_kafka_roundtrip_geojson_points(fake_kafka):
    from spatialflink_tpu.models.objects import Point
    from spatialflink_tpu.streams.kafka import (
        KafkaSink,
        kafka_available,
        kafka_source,
    )
    from spatialflink_tpu.streams.serde import parse_geojson, to_geojson

    assert kafka_available()
    rng = np.random.default_rng(5)
    pts = [
        Point(obj_id=f"dev{i % 5}", timestamp=i * 100,
              x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
        for i in range(200)
    ]
    sink = KafkaSink("points", "fake:9092", formatter=to_geojson)
    for p in pts:
        sink(p)
    sink.flush()
    assert len(BROKER["points"]) == 200

    got = list(kafka_source("points", "fake:9092", parser=parse_geojson))
    assert len(got) == 200
    for a, b in zip(pts, got):
        assert b.obj_id == a.obj_id and b.timestamp == a.timestamp
        assert b.x == pytest.approx(a.x) and b.y == pytest.approx(a.y)


def test_kafka_source_feeds_windowed_query(fake_kafka):
    """Full pipeline through the gated transport: producer → topic →
    kafka_source → windowed range query, equal to running the query on
    the original objects."""
    from spatialflink_tpu.grid import UniformGrid
    from spatialflink_tpu.models.objects import Point
    from spatialflink_tpu.operators import (
        PointPointRangeQuery,
        QueryConfiguration,
        QueryType,
    )
    from spatialflink_tpu.streams.kafka import KafkaSink, kafka_source
    from spatialflink_tpu.streams.serde import parse_geojson, to_geojson

    grid = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
    rng = np.random.default_rng(9)
    pts = [
        Point(obj_id=f"d{i % 7}", timestamp=int(i * 30),
              x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
        for i in range(500)
    ]
    sink = KafkaSink("gps", "fake:9092", formatter=to_geojson)
    for p in pts:
        sink(p)

    conf = QueryConfiguration(QueryType.WindowBased, window_size=5, slide_step=5)
    q = Point(x=5.0, y=5.0)

    def results(stream):
        return [
            (r.start, r.end, sorted((o.obj_id, o.timestamp) for o in r.objects))
            for r in PointPointRangeQuery(conf, grid).run(stream, [q], 2.0)
        ]

    via_kafka = results(kafka_source("gps", "fake:9092", parser=parse_geojson))
    direct = results(iter(pts))
    assert via_kafka == direct


def test_kafka_source_skips_malformed_records(fake_kafka):
    from spatialflink_tpu.streams.kafka import kafka_source
    from spatialflink_tpu.streams.serde import parse_csv_point

    BROKER["csv"] = [
        b"a,100,1.0,2.0",
        b"not,a,valid,record,at,all,###",
        b"",
        b"b,200,3.0,4.0",
    ]
    got = list(kafka_source("csv", "fake:9092", parser=parse_csv_point))
    assert [p.obj_id for p in got] == ["a", "b"]
