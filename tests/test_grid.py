"""UniformGrid parity tests.

The expected values re-derive the reference's algorithms independently
(set-based, python) and check the flag-table construction against them:
guaranteed layers floor(r/(cell*sqrt2) - 1) (UniformGrid.java:428-439),
candidate layers ceil(r/cell) (UniformGrid.java:441-445), square neighbor
sets clipped to the grid (UniformGrid.java:165-222, 368-426).
"""

import math

import numpy as np
import pytest

from spatialflink_tpu.grid import (
    FLAG_CANDIDATE,
    FLAG_GUARANTEED,
    FLAG_NONE,
    UniformGrid,
)


def brute_force_sets(grid, radius, qx, qy):
    """Independent re-derivation of guaranteed/candidate cell sets."""
    lg = math.floor(radius / (grid.cell_length * math.sqrt(2)) - 1)
    lc = math.ceil(radius / grid.cell_length)
    qi, qj = grid.cell_indices(qx, qy)
    guaranteed = set()
    if lg >= 0:
        for i in range(qi - lg, qi + lg + 1):
            for j in range(qj - lg, qj + lg + 1):
                if 0 <= i < grid.n and 0 <= j < grid.n:
                    guaranteed.add(i * grid.n + j)
    candidate = set()
    if lc > 0:
        for i in range(qi - lc, qi + lc + 1):
            for j in range(qj - lc, qj + lc + 1):
                if 0 <= i < grid.n and 0 <= j < grid.n:
                    c = i * grid.n + j
                    if c not in guaranteed:
                        candidate.add(c)
    return guaranteed, candidate


BEIJING = dict(min_x=115.50, max_x=117.60, min_y=39.60, max_y=41.10)


def test_constructor_by_partitions():
    g = UniformGrid(100, **BEIJING)
    assert g.n == 100
    assert g.cell_length == pytest.approx((117.60 - 115.50) / 100)
    assert g.num_cells == 10000


def test_constructor_by_cell_length_square_adjustment():
    # x span 2.1 > y span 1.5 → y padded symmetrically to 2.1.
    g = UniformGrid.from_cell_length(0.021, **BEIJING)
    assert g.max_x - g.min_x == pytest.approx(g.max_y - g.min_y)
    assert g.min_y == pytest.approx(39.60 - 0.3)
    assert g.max_y == pytest.approx(41.10 + 0.3)
    assert g.n == 100
    assert g.cell_length == pytest.approx(2.1 / 100)


def test_cell_assignment_and_naming():
    g = UniformGrid(100, **BEIJING)
    flat = g.flat_cell(116.5, 40.0)
    xi = math.floor((116.5 - g.min_x) / g.cell_length)
    yi = math.floor((40.0 - g.min_y) / g.cell_length)
    assert flat == xi * 100 + yi
    name = g.cell_name(flat)
    assert len(name) == 10 and name == f"{xi:05d}{yi:05d}"
    assert g.cell_from_name(name) == flat


def test_out_of_grid_assignment():
    g = UniformGrid(100, **BEIJING)
    assert g.flat_cell(0.0, 0.0) == g.num_cells
    xy = np.array([[116.5, 40.0], [0.0, 0.0], [115.50, 39.60]])
    cells = g.assign_cells_np(xy)
    assert cells[1] == g.num_cells
    assert cells[2] == 0  # min corner → cell (0,0)


def test_assign_cells_jax_matches_numpy(rng):
    import jax.numpy as jnp
    from spatialflink_tpu.ops.cells import assign_cells

    g = UniformGrid(100, **BEIJING)
    xy = np.stack(
        [rng.uniform(115.0, 118.0, 1000), rng.uniform(39.0, 41.5, 1000)], axis=1
    )
    dev = np.asarray(assign_cells(jnp.asarray(xy), g.min_x, g.min_y, g.cell_length, g.n))
    np.testing.assert_array_equal(dev, g.assign_cells_np(xy))


@pytest.mark.parametrize("radius", [0.001, 0.02, 0.05, 0.5])
def test_neighbor_flags_match_brute_force(radius):
    g = UniformGrid(100, **BEIJING)
    qx, qy = 116.5, 40.2
    guaranteed, candidate = brute_force_sets(g, radius, qx, qy)
    flags = g.neighbor_flags(radius, [g.flat_cell(qx, qy)])
    assert set(np.nonzero(flags == FLAG_GUARANTEED)[0]) == guaranteed
    assert set(np.nonzero(flags == FLAG_CANDIDATE)[0]) == candidate
    assert flags[g.num_cells] == FLAG_NONE


def test_layer_math_reference_values():
    g = UniformGrid(100, **BEIJING)  # cell = 0.021
    # r smaller than cell diagonal → no guaranteed layer at all
    assert g.guaranteed_layers(0.001) == -1
    assert g.candidate_layers(0.001) == 1
    # r = exactly one cell → guaranteed -1 or 0 per the floor(x-1) formula
    assert g.guaranteed_layers(g.cell_length * math.sqrt(2)) == 0
    assert g.candidate_layers(0.05) == math.ceil(0.05 / g.cell_length)


def test_grid_boundary_clipping():
    g = UniformGrid(10, 0, 10, 0, 10)
    flags = g.neighbor_flags(2.5, [0])  # query at corner cell (0,0)
    lc = g.candidate_layers(2.5)
    assert lc == 3
    nz = np.nonzero(flags[: g.num_cells])[0]
    for c in nz:
        xi, yi = divmod(int(c), g.n)
        assert 0 <= xi <= 3 and 0 <= yi <= 3


def test_polygon_query_cells_union():
    g = UniformGrid(10, 0, 10, 0, 10)
    cells = g.bbox_cells(1.5, 1.5, 3.5, 2.5)
    # x cells 1..3, y cells 1..2 → 6 cells
    assert len(cells) == 6
    flags = g.neighbor_flags(1.0, cells)
    # Union of per-cell candidate squares
    g2, c2 = set(), set()
    for c in cells:
        xi, yi = divmod(int(c), g.n)
        gg, cc = brute_force_sets(g, 1.0, g.min_x + (xi + 0.5) * g.cell_length,
                                  g.min_y + (yi + 0.5) * g.cell_length)
        g2 |= gg
        c2 |= cc
    c2 -= g2
    assert set(np.nonzero(flags == FLAG_GUARANTEED)[0]) == g2
    assert set(np.nonzero(flags == FLAG_CANDIDATE)[0]) == c2


def test_cell_layer_chebyshev():
    g = UniformGrid(100, **BEIJING)
    a = 50 * 100 + 50
    assert g.cell_layer(a, a) == 0
    assert g.cell_layer(a, 52 * 100 + 50) == 2
    assert g.cell_layer(a, 51 * 100 + 53) == 3


def test_neighbor_offsets_cover_candidate_square():
    g = UniformGrid(100, **BEIJING)
    off = g.neighbor_offsets(0.05)
    lc = g.candidate_layers(0.05)
    assert off.shape == ((2 * lc + 1) ** 2, 2)
    assert off.min() == -lc and off.max() == lc
