"""bench.py JSON-contract tests under a simulated device outage.

The driver runs ``python bench.py`` once per round and records the single
stdout JSON line. These tests pin the contract without a device:

- an outage (child exits 3 on every dial) yields rc=3, ``value`` 0 (never
  a stale number), an ``error``, and ``last_good`` metadata from the
  newest persisted capture, labeled ``stale: true``;
- a successful dial is relayed verbatim and persisted to the last-good
  store (value, device, UTC timestamp, git SHA).

The hooks (``SFT_BENCH_FORCE_FAIL`` / ``SFT_BENCH_FAKE_RECORD``) short-
circuit the child before it imports jax, so these run in milliseconds.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")

sys.path.insert(0, REPO)
import bench  # noqa: E402


def _run(tmp_path, extra_env, last_good=None):
    lg = tmp_path / "last_good.json"
    if last_good is not None:
        lg.write_text(json.dumps(last_good))
    env = {
        **os.environ,
        "SFT_BENCH_BACKOFFS": "0",
        "SFT_BENCH_LAST_GOOD": str(lg),
        # These contract tests never dial the device, but a down/half-
        # open tunnel can hang ANY interpreter start via the axon
        # sitecustomize register() (CLAUDE.md) — skip plugin
        # registration in the spawned processes.
        "PALLAS_AXON_POOL_IPS": "",
        **extra_env,
    }
    env.pop("SFT_BENCH_CHILD", None)
    p = subprocess.run(
        [sys.executable, BENCH], env=env, capture_output=True, text=True,
        timeout=120,
    )
    lines = [ln for ln in p.stdout.strip().splitlines() if ln]
    return p, lines, lg


FIXTURE_GOOD = {
    "record": {
        "metric": "continuous_knn_k50_1M_window_points_per_sec_per_chip",
        "value": 3282867.3,
        "unit": "points/s",
        "vs_baseline": 164.14,
        "device": "TPU v5 lite0",
        "device_resident_points_per_sec": 4.9e8,
    },
    "captured_at": "2026-07-30T14:06:27+00:00",
    "git_sha": "70bd1ee5267c960c84ea5137456de82d29049f0b",
}


class TestOutageRecord:
    def test_outage_with_last_good(self, tmp_path):
        p, lines, _ = _run(
            tmp_path, {"SFT_BENCH_FORCE_FAIL": "1"}, last_good=FIXTURE_GOOD
        )
        assert p.returncode == 3
        assert len(lines) == 1, f"driver contract: ONE line, got {lines}"
        rec = json.loads(lines[0])
        # Never report a stale number in `value`.
        assert rec["value"] == 0
        assert rec["vs_baseline"] == 0
        assert "unreachable" in rec["error"]
        lg = rec["last_good"]
        assert lg["stale"] is True
        assert lg["value"] == 3282867.3
        assert lg["device"] == "TPU v5 lite0"
        assert lg["device_resident_points_per_sec"] == 4.9e8
        assert lg["captured_at"].startswith("2026-07-30T")
        assert len(lg["git_sha"]) == 40

    def test_outage_without_last_good(self, tmp_path):
        p, lines, _ = _run(tmp_path, {"SFT_BENCH_FORCE_FAIL": "1"})
        assert p.returncode == 3
        rec = json.loads(lines[0])
        assert rec["value"] == 0
        assert "last_good" not in rec

    def test_corrupt_last_good_is_ignored(self, tmp_path):
        (tmp_path / "last_good.json").write_text("{not json")
        p, lines, _ = _run(tmp_path, {"SFT_BENCH_FORCE_FAIL": "1"})
        rec = json.loads(lines[0])
        assert rec["value"] == 0
        assert "last_good" not in rec


class TestSuccessRecord:
    def test_success_relayed_and_persisted(self, tmp_path):
        good = {
            "metric": "continuous_knn_k50_1M_window_points_per_sec_per_chip",
            "value": 123456.7,
            "unit": "points/s",
            "vs_baseline": 6.17,
            "device": "TPU v5 lite0",
        }
        p, lines, lg_path = _run(
            tmp_path, {"SFT_BENCH_FAKE_RECORD": json.dumps(good)}
        )
        assert p.returncode == 0
        assert len(lines) == 1
        assert json.loads(lines[0]) == good
        stored = json.loads(lg_path.read_text())
        assert stored["record"] == good
        # ISO-8601 UTC timestamp + the capture's git SHA.
        assert "T" in stored["captured_at"]
        assert stored["captured_at"].endswith("+00:00")
        assert len(stored["git_sha"]) == 40

    def test_zero_value_record_not_persisted(self, tmp_path):
        zero = {**bench._ERROR_RECORD}
        p, lines, lg_path = _run(
            tmp_path, {"SFT_BENCH_FAKE_RECORD": json.dumps(zero)}
        )
        assert p.returncode == 0
        assert not lg_path.exists()


class TestLastGoodStore:
    def test_roundtrip(self, tmp_path, monkeypatch):
        monkeypatch.setenv(
            "SFT_BENCH_LAST_GOOD", str(tmp_path / "lg.json")
        )
        bench._record_last_good({"value": 42.0, "unit": "points/s"})
        got = bench._load_last_good()
        assert got["record"]["value"] == 42.0
        assert len(got["git_sha"]) == 40

    def test_missing_file(self, tmp_path, monkeypatch):
        monkeypatch.setenv(
            "SFT_BENCH_LAST_GOOD", str(tmp_path / "absent.json")
        )
        assert bench._load_last_good() is None

    def test_committed_seed_is_valid(self):
        """The repo ships a seed store from the r02 chip capture."""
        with open(os.path.join(REPO, "BENCH_LAST_GOOD.json")) as f:
            seed = json.load(f)
        assert seed["record"]["value"] > 1e6
        assert seed["record"]["device"] == "TPU v5 lite0"
        assert len(seed["git_sha"]) == 40


class TestDeadlineAndKill:
    """VERDICT r5 weak #1: the dial schedule outlived the driver's kill
    budget and the round record was ``parsed: null``. The supervisor is
    now bounded by SFT_BENCH_DEADLINE (default 600 s) checked before
    each dial AND each backoff, and a SIGTERM handler prints the same
    stale-last-good record — a JSON line lands under EVERY outcome
    short of SIGKILL."""

    def test_deadline_preempts_long_backoff_schedule(self, tmp_path):
        # A backoff that would sleep ~3 hours: the deadline check must
        # trip BEFORE the sleep and print the stale record immediately
        # (the test's own 120 s timeout is the enforcement).
        p, lines, _ = _run(
            tmp_path,
            {"SFT_BENCH_FORCE_FAIL": "1", "SFT_BENCH_BACKOFFS": "9999",
             "SFT_BENCH_DEADLINE": "3"},
            last_good=FIXTURE_GOOD,
        )
        assert p.returncode == 3
        assert len(lines) == 1, f"driver contract: ONE line, got {lines}"
        rec = json.loads(lines[0])
        assert rec["value"] == 0
        # the child's own honest error record is still the one relayed
        assert "unreachable" in rec["error"]
        assert rec["last_good"]["stale"] is True
        assert rec["last_good"]["value"] == FIXTURE_GOOD["record"]["value"]

    def test_deadline_zero_emits_without_dialing(self, tmp_path):
        p, lines, _ = _run(
            tmp_path,
            {"SFT_BENCH_FORCE_FAIL": "1", "SFT_BENCH_DEADLINE": "0"},
            last_good=FIXTURE_GOOD,
        )
        assert p.returncode == 3
        rec = json.loads(lines[0])
        assert rec["value"] == 0
        assert "deadline" in rec["error"]
        assert rec["last_good"]["stale"] is True

    def test_truncated_child_json_degrades_to_error_record(self, tmp_path):
        """bench.py's final-failure path must survive a child killed
        mid-print (half-written JSON line) — ADVICE r5."""
        p, lines, _ = _run(
            tmp_path,
            {"SFT_BENCH_FORCE_FAIL": "truncated",
             "SFT_BENCH_BACKOFFS": "0"},
            last_good=FIXTURE_GOOD,
        )
        assert p.returncode == 3
        assert len(lines) == 1
        rec = json.loads(lines[0])  # parses — the truncation never leaks
        assert rec["value"] == 0
        assert "failed rc=3" in rec["error"]
        assert rec["last_good"]["stale"] is True

    def test_sigterm_prints_stale_record(self, tmp_path):
        import signal
        import time

        lg = tmp_path / "lg.json"
        lg.write_text(json.dumps(FIXTURE_GOOD))
        env = {
            **os.environ,
            "SFT_BENCH_BACKOFFS": "0",
            "SFT_BENCH_LAST_GOOD": str(lg),
            "PALLAS_AXON_POOL_IPS": "",
            "SFT_BENCH_HANG": "60",  # child stuck "dialing"
            "SFT_BENCH_DEADLINE": "600",
        }
        env.pop("SFT_BENCH_CHILD", None)
        p = subprocess.Popen(
            [sys.executable, BENCH], env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        time.sleep(2.0)  # supervisor is now waiting on the hung child
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=60)
        assert p.returncode == 3
        lines = [ln for ln in out.strip().splitlines() if ln]
        assert len(lines) == 1, f"driver contract: ONE line, got {lines}"
        rec = json.loads(lines[0])
        assert rec["value"] == 0
        assert "SIGTERM" in rec["error"]
        assert rec["last_good"]["stale"] is True
        assert rec["last_good"]["value"] == FIXTURE_GOOD["record"]["value"]


class TestStreamSealing:
    """Satellite: bench.py's failure paths (deadline / SIGTERM / child
    crash) must seal a configured ledger stream with an epilogue carrying
    the termination reason — the supervisor appends it as plain JSONL (no
    jax), so even a run whose child died dialing leaves an attributable,
    recoverable artifact."""

    @staticmethod
    def _dead_child_stream(tmp_path):
        """A stream as a killed child leaves it: prologue + one
        checkpoint, no epilogue."""
        stream = tmp_path / "stream.jsonl"
        snapshot = {
            "compiles": 1, "bytes_h2d": 64, "bytes_d2h": 64,
            "window_latency_p50_ms": None, "window_latency_p95_ms": None,
            "max_watermark_lag_ms": 0, "watermark_lag_p99_ms": None,
            "late_dropped": 0, "h2d_transfers": 1, "d2h_transfers": 1,
            "events": 0, "dropped_events": 0, "kernels": {"k": 1},
            "compaction": {},
        }
        stream.write_text(
            json.dumps({"t": "prologue", "stream_version": 1,
                        "ledger_version": 1, "created_unix": 1.0,
                        "env": {"python": "3", "pid": 1,
                                "argv0": "bench.py"}}) + "\n"
            + json.dumps({"t": "checkpoint", "seq": 1, "unix": 2.0,
                          "snapshot": snapshot, "kernels": []}) + "\n"
        )
        return stream

    def test_failure_path_seals_stream_with_reason(self, tmp_path):
        stream = self._dead_child_stream(tmp_path)
        p, lines, _ = _run(
            tmp_path,
            {"SFT_BENCH_FORCE_FAIL": "1", "SFT_LEDGER_STREAM": str(stream)},
        )
        assert p.returncode == 3
        assert len(lines) == 1  # the one-line contract holds
        recs = [json.loads(ln) for ln in
                stream.read_text().splitlines() if ln.strip()]
        assert recs[-1]["t"] == "epilogue"
        assert recs[-1]["sealed_by"] == "supervisor"
        assert "failed rc=3" in recs[-1]["reason"]
        # The sealed stream recovers into a valid, attributable ledger.
        from tools.sfprof import ledger as ledger_mod
        from tools.sfprof import stream as stream_mod

        doc, info = stream_mod.recover(str(stream))
        assert ledger_mod.validate(doc) == []
        assert info["sealed"] is True
        assert info["sealed_by"] == "supervisor"
        assert "failed rc=3" in info["reason"]
        # A supervisor seal attributes the crash — it does NOT make the
        # capture complete: the child died without its final flush.
        assert info["truncated"] is True
        assert "one flush interval" in info["loss_bound"]
        # last_seq falls back to the checkpoint's (supervisor epilogues
        # carry no seq).
        assert info["last_seq"] == 1

    def test_sigterm_path_seals_stream(self, tmp_path):
        import signal
        import time

        stream = self._dead_child_stream(tmp_path)
        env = {
            **os.environ,
            "SFT_BENCH_BACKOFFS": "0",
            "SFT_BENCH_LAST_GOOD": str(tmp_path / "lg.json"),
            "PALLAS_AXON_POOL_IPS": "",
            "SFT_BENCH_HANG": "60",
            "SFT_BENCH_DEADLINE": "600",
            "SFT_LEDGER_STREAM": str(stream),
        }
        env.pop("SFT_BENCH_CHILD", None)
        p = subprocess.Popen(
            [sys.executable, BENCH], env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        time.sleep(2.0)
        p.send_signal(signal.SIGTERM)
        p.communicate(timeout=60)
        recs = [json.loads(ln) for ln in
                stream.read_text().splitlines() if ln.strip()]
        assert recs[-1]["t"] == "epilogue"
        assert "SIGTERM" in recs[-1]["reason"]

    def test_already_sealed_stream_not_resealed(self, tmp_path):
        stream = self._dead_child_stream(tmp_path)
        with open(stream, "a") as f:
            f.write(json.dumps({"t": "epilogue", "unix": 3.0,
                                "reason": "complete"}) + "\n")
        before = stream.read_text()
        p, _, _ = _run(
            tmp_path,
            {"SFT_BENCH_FORCE_FAIL": "1", "SFT_LEDGER_STREAM": str(stream)},
        )
        assert p.returncode == 3
        assert stream.read_text() == before  # the child's seal wins

    def test_oversized_child_epilogue_detected_not_resealed(self, tmp_path):
        """A child epilogue longer than any small tail peek (bench
        record + SLO verdict easily beats 4 KiB) must still be detected
        as a seal — a duplicate supervisor epilogue would shadow the
        child's bench/slo blocks in recovery."""
        stream = self._dead_child_stream(tmp_path)
        with open(stream, "a") as f:
            f.write(json.dumps({
                "t": "epilogue", "unix": 3.0, "reason": "complete",
                "bench": {"value": 9.0, "pad": "x" * 8192},
            }) + "\n")
        before = stream.read_text()
        p, _, _ = _run(
            tmp_path,
            {"SFT_BENCH_FORCE_FAIL": "1", "SFT_LEDGER_STREAM": str(stream)},
        )
        assert p.returncode == 3
        assert stream.read_text() == before

    def test_seal_after_partial_tail_line_stays_decodable(self, tmp_path):
        """A child killed mid-flush leaves a half-written LAST line with
        no newline; the supervisor epilogue must land on its OWN line
        (not concatenate into the fragment) and recovery must honor both
        the truncation and the termination reason."""
        stream = self._dead_child_stream(tmp_path)
        with open(stream, "a") as f:
            f.write('{"t": "spans", "seq": 2, "events": [{"na')  # cut
        p, _, _ = _run(
            tmp_path,
            {"SFT_BENCH_FORCE_FAIL": "1", "SFT_LEDGER_STREAM": str(stream)},
        )
        assert p.returncode == 3
        from tools.sfprof import ledger as ledger_mod
        from tools.sfprof import stream as stream_mod

        doc, info = stream_mod.recover(str(stream))
        assert ledger_mod.validate(doc) == []
        assert info["sealed"] is True  # the supervisor's seal survives
        assert "failed rc=3" in info["reason"]
        assert info["partial_tail"] is True
        assert info["truncated"] is True  # honest: data was still lost

    @pytest.mark.slow
    def test_sigkill_chaos_recovers_gateable_ledger(self, tmp_path):
        """The acceptance chaos test: a real bench-smoke run streaming
        with interval 0, SIGKILLed mid-run (no handler can save it),
        must recover into a schema-valid ledger that passes `sfprof
        health`, reporting the truncation honestly."""
        import time

        stream = tmp_path / "chaos_stream.jsonl"
        env = {
            **os.environ,
            "SFT_BENCH_CHILD": "1",  # ONE process: the kill hits the run
            "SFT_BENCH_SMOKE": "1",
            "SFT_BENCH_LAST_GOOD": str(tmp_path / "lg.json"),
            "SFT_LEDGER_STREAM": str(stream),
            "SFT_LEDGER_STREAM_INTERVAL_S": "0",
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
        }
        p = subprocess.Popen(
            [sys.executable, BENCH], env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )

        def n_checkpoints():
            try:
                return stream.read_text().count('"t": "checkpoint"')
            except OSError:
                return 0

        # Wait for ≥2 durable checkpoints (warm-up boundary + first
        # post-run flush), then SIGKILL while the rest of the run —
        # latency probe, resident passes, ledger write — is still ahead.
        deadline = time.time() + 480
        while time.time() < deadline and n_checkpoints() < 2:
            if p.poll() is not None:
                pytest.fail(
                    "bench exited before the kill: rc="
                    f"{p.returncode}\n{p.stderr.read()[-4000:]}"
                )
            time.sleep(0.25)
        assert n_checkpoints() >= 2, "no checkpoints within the deadline"
        p.kill()  # SIGKILL: no handler, no seal, no epilogue
        p.wait(timeout=60)

        from tools.sfprof import ledger as ledger_mod
        from tools.sfprof import stream as stream_mod
        from tools.sfprof.cli import main as sfprof_main

        doc, info = stream_mod.recover(str(stream))
        assert ledger_mod.validate(doc) == [], ledger_mod.validate(doc)
        assert info["sealed"] is False  # honest: the run never completed
        assert info["truncated"] is True
        assert "one flush interval" in info["loss_bound"]
        assert doc["bench"] is None  # no fabricated record
        # The recovered snapshot carries real measured state.
        assert doc["snapshot"]["compiles"] >= 1
        assert doc["snapshot"]["bytes_h2d"] > 0
        # CLI round trip: recover exit 0, recovered ledger passes the
        # post-bench health gate.
        out = tmp_path / "recovered.json"
        assert sfprof_main(["recover", str(stream), "-o", str(out)]) == 0
        assert sfprof_main(["health", str(out)]) == 0


class TestTelemetryBlock:
    def test_fake_record_with_telemetry_relays_verbatim(self, tmp_path):
        """The supervisor must relay the telemetry block untouched."""
        good = {
            "metric": "continuous_knn_k50_1M_window_points_per_sec_per_chip",
            "value": 99.0,
            "unit": "points/s",
            "vs_baseline": 0.005,
            "telemetry": {
                "compiles": 3,
                "bytes_h2d": 663552,
                "bytes_d2h": 1546420,
                "window_latency_p50_ms": 1.0,
                "window_latency_p95_ms": 2.0,
                "max_watermark_lag_ms": 0,
                "late_dropped": 0,
            },
        }
        p, lines, _ = _run(
            tmp_path, {"SFT_BENCH_FAKE_RECORD": json.dumps(good)}
        )
        assert p.returncode == 0
        assert json.loads(lines[0])["telemetry"] == good["telemetry"]

    @pytest.mark.slow
    def test_smoke_run_emits_telemetry_summary(self, tmp_path):
        """SFT_BENCH_SMOKE runs the REAL measured program at toy sizes on
        XLA:CPU: still exactly ONE JSON line, now with the telemetry
        summary, and the Chrome-trace side channel loads as valid JSON.
        SFT_LEDGER_PATH additionally captures the run ledger, which must
        validate against the sfprof schema, attribute the probe's
        window spans, carry CPU cost analysis, and survive the
        ``sfprof diff --gate`` round trip (self-diff 0, injected
        regression nonzero)."""
        trace = tmp_path / "bench_trace.jsonl"
        ledger = tmp_path / "bench_ledger.json"
        env = {
            **os.environ,
            "SFT_BENCH_SMOKE": "1",
            "SFT_BENCH_BACKOFFS": "0",
            "SFT_BENCH_LAST_GOOD": str(tmp_path / "lg.json"),
            "SFT_TRACE_PATH": str(trace),
            "SFT_LEDGER_PATH": str(ledger),
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
        }
        env.pop("SFT_BENCH_CHILD", None)
        p = subprocess.run(
            [sys.executable, BENCH], env=env, capture_output=True,
            text=True, timeout=540,
        )
        assert p.returncode == 0, p.stderr[-4000:]
        lines = [ln for ln in p.stdout.strip().splitlines() if ln]
        assert len(lines) == 1, f"driver contract: ONE line, got {lines}"
        rec = json.loads(lines[0])
        assert rec["smoke"] is True
        assert rec["value"] > 0
        tel = rec["telemetry"]
        assert tel["compiles"] >= 1  # headline step compiled at least once
        assert tel["bytes_h2d"] > 0
        assert tel["bytes_d2h"] > 0
        assert tel["window_latency_p50_ms"] is not None
        assert tel["window_latency_p95_ms"] >= tel["window_latency_p50_ms"]
        assert tel["max_watermark_lag_ms"] == 0  # in-order synthetic stream
        # Toy numbers must never enter the last-good store.
        assert not (tmp_path / "lg.json").exists()
        # The child's trace file is a loadable Chrome-trace document.
        from spatialflink_tpu.telemetry import load_trace

        doc = load_trace(str(trace))
        assert doc["traceEvents"], "trace captured no events"
        json.dumps(doc)
        names = {e["name"] for e in doc["traceEvents"]}
        assert "window.headline" in names
        assert any(n.startswith("compile:") for n in names)
        # Counter-event symmetry: BOTH transfer directions render as
        # Perfetto counter tracks.
        counters = {e["name"] for e in doc["traceEvents"]
                    if e.get("ph") == "C"}
        assert {"h2d_bytes", "d2h_bytes"} <= counters

        # ---- run ledger: schema, attribution, costs, gate. ----
        from tools.sfprof import ledger as ledger_mod
        from tools.sfprof.attribution import attribute_windows
        from tools.sfprof.cli import main as sfprof_main

        led = ledger_mod.load(str(ledger))
        assert ledger_mod.validate(led) == [], ledger_mod.validate(led)
        # The bench block is the SAME record the driver line carried.
        assert led["bench"]["value"] == rec["value"]
        assert led["bench"]["smoke"] is True
        # Per-kernel flops/bytes from XLA cost analysis on CPU.
        costed = [r for r in led["kernels"]
                  if r["cost"] and r["cost"].get("flops")]
        assert costed, led["kernels"]
        assert {"headline_step", "headline_step_donated"} <= {
            r["kernel"] for r in led["kernels"]
        }
        # Every window.* span is ≥90% attributed to its phase children
        # OR the residue is reported explicitly — either way no silently
        # missing time: phases + unattributed == the window's dur,
        # exactly. (At toy smoke sizes the windows are sub-ms, so span-
        # machinery µs can push the residue past 10% — the explicit
        # residue is the contract, the 90% is what real window sizes
        # deliver.)
        windows, ops = attribute_windows(led["events"])
        assert windows, "ledger carried no window spans"
        for w in windows:
            assert (sum(w["phases"].values()) + w["unattributed_us"]
                    == w["dur_us"])
            assert (w["attributed_frac"] >= 0.9
                    or w["unattributed_us"] > 0)
        agg = ops["window.headline"]
        assert {"compute", "fetch"} <= set(agg["phases"])
        attributed = sum(agg["phases"].values())
        assert attributed + agg["unattributed_us"] == agg["dur_us"]
        # The probe's dispatch+fetch dominate even at toy sizes.
        assert attributed / agg["dur_us"] >= 0.5

        # ---- pipelined ingest proof (ISSUE 11). The overlap probe's
        # window.pipeline spans carry their ingest INSIDE the spans
        # (the executor ships pane N+1 while window N computes), so
        # the attributed inter-window host gap must SHRINK vs the
        # synchronous latency probe's window.headline spans on the
        # same toy run — sfprof's host-gap detector is the proof
        # metric. The codec gauges must ride record + ledger.
        import statistics

        from tools.sfprof.attribution import host_gaps

        counters = rec["pipeline"]["counters"]
        assert counters["overlapped"] > 0
        assert counters.get("collapses", 0) == 0
        # Codec-arming identity rides the record (the trend store keys
        # series by it): unarmed smoke run → armed False, codec None.
        assert rec["pipeline"]["armed"] is False
        assert rec["pipeline"]["armed_codec"] is None
        assert 0 < rec["wire_bytes"] <= rec["raw_bytes"]
        assert led["snapshot"]["wire_codec"]["coded_bytes"] \
            == rec["wire_bytes"]
        assert led["snapshot"]["wire_codec"]["raw_bytes"] \
            == rec["raw_bytes"]
        gaps = host_gaps(led["events"])

        def median_gap(name):
            vals = [g["gap_us"] for g in gaps
                    if g["after"] == name and g["before"] == name]
            assert len(vals) >= 2, (name, gaps)
            return float(statistics.median(vals))

        assert median_gap("window.pipeline") \
            < median_gap("window.headline")
        # ship is ATTRIBUTED inside the pipelined window spans (it is
        # dead inter-window time on the sync path).
        assert "ship" in ops["window.pipeline"]["phases"]
        assert "ship" not in ops["window.headline"]["phases"]

        # report renders; self-diff gates clean; an injected EPS
        # regression (beyond the ±50% tolerance band) gates nonzero.
        assert sfprof_main(["report", str(ledger)]) == 0
        assert sfprof_main(["diff", str(ledger), str(ledger),
                            "--gate"]) == 0
        bad = json.loads(json.dumps(led))
        bad["bench"]["value"] = led["bench"]["value"] / 10.0
        bad_path = tmp_path / "bench_ledger_regressed.json"
        bad_path.write_text(json.dumps(bad))
        assert sfprof_main(["diff", str(ledger), str(bad_path),
                            "--gate"]) != 0
        # The post-bench health check (CLAUDE.md) passes on a clean run.
        assert sfprof_main(["health", str(ledger)]) == 0


class TestDialDeadline:
    """ISSUE 8 satellite: the r3–r5 "hang at the dial" mode is bounded by
    SFT_DIAL_DEADLINE_S — the child prints the one-line failure record
    AND seals the ledger stream with reason ``dial_timeout`` instead of
    hanging until the supervisor's full deadline."""

    def test_dial_timeout_prints_record_and_seals_stream(self, tmp_path):
        stream = tmp_path / "dial_stream.jsonl"
        env = {
            **os.environ,
            "SFT_BENCH_CHILD": "1",  # direct child: the watchdog's path
            "SFT_BENCH_SMOKE": "1",
            "SFT_BENCH_LAST_GOOD": str(tmp_path / "lg.json"),
            "SFT_LEDGER_STREAM": str(stream),
            "SFT_DIAL_DEADLINE_S": "8",
            # Simulated half-open tunnel: device discovery succeeds,
            # the first device op never completes.
            "SFT_BENCH_DIAL_HANG": "300",
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
        }
        p = subprocess.run(
            [sys.executable, BENCH], env=env, capture_output=True,
            text=True, timeout=100,
        )
        assert p.returncode == 3
        lines = [ln for ln in p.stdout.strip().splitlines()
                 if ln.startswith("{")]
        assert len(lines) == 1
        rec = json.loads(lines[0])
        assert rec["value"] == 0
        assert "SFT_DIAL_DEADLINE_S" in rec["error"]
        # The stream is sealed with the dial_timeout reason, so `sfprof
        # recover` attributes the loss instead of guessing.
        from tools.sfprof import stream as stream_mod

        doc, info = stream_mod.recover(str(stream))
        assert info["sealed"] is True
        assert info["reason"] == "dial_timeout"

    def test_healthy_smoke_run_unaffected_by_deadline(self, tmp_path):
        """With no hang, the watchdog disarms at the warm-up fetch and a
        tight-but-sane deadline changes nothing (the acceptance
        criterion: the SFT_BENCH_SMOKE contract run is unchanged)."""
        p, lines, _ = _run(
            tmp_path,
            {"SFT_BENCH_SMOKE": "1", "SFT_DIAL_DEADLINE_S": "90"},
        )
        assert p.returncode == 0, p.stderr[-2000:]
        rec = json.loads(lines[-1])
        assert rec["smoke"] is True and rec["value"] > 0
