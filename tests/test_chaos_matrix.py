"""The chaos matrix (ISSUE 8 acceptance): for EVERY registered fault
injection point, inject → crash → resume → the concatenated exactly-once
egress is byte-identical to an uninterrupted run — no gap, no duplicate,
at the sink and not just the source.

Crash semantics: an armed fault rule with ``times`` larger than the
driver's retry budget defeats retries and propagates out of the pipeline
with no cleanup — from the checkpoint/egress protocol's point of view,
the same abandonment as a ``kill -9`` (nothing commits, nothing
flushes). The real-process SIGKILL analog (``abort`` kind,
``os._exit(137)``) is pinned by the slow subprocess test below and runs
on every commit as tools/ci's chaos-smoke stage.

Seven pipeline harnesses cover the sixteen points:

- range-query driver pipeline (collection source): device.ship,
  device.dispatch, device.fetch, window.feed, driver.window, sink.write,
  and — with an admission controller attached — overload.admit;
- SoA driver pipeline (chunked source → run_soa): soa.feed;
- qserve standing-query pipeline (Points + registration commands →
  QServeOperator, registry state checkpointed): qserve.register —
  killed mid-registration-churn, resumed egress byte-identical;
- Kafka driver pipeline (FakeBroker ingest, offsets checkpointed):
  kafka.fetch, kafka.leader;
- tJoin pane-engine pipeline (bounded SoA chunks → run_soa_panes →
  driver.run_precomputed): source.stall — the scan recomputes
  deterministically on resume and the driver skips the committed
  window prefix;
- PIPELINED range driver subprocess (SFT_PIPELINE armed, abort kind —
  the kill -9 analog; on the DRIVER path in-process raise kinds are
  CONTAINED by its sync-fallback, so only a real process death
  exercises the crash contract there): pipeline.ship, pipeline.fetch —
  killed mid-overlap, the resumed pipelined child converges to the
  clean child's bytes, which equal a pipeline-OFF run's bytes too
  (hang kinds have their own legs: bounded hangs are contained
  in-process, a wedge past SFT_DIAL_DEADLINE_S dies on the driver's
  dial watchdog);
- composed SNCB DAG subprocess (7 nodes, 7 transactional sinks, one
  atomic unit checkpoint, SFT_OVERLOAD_POLICY + SFT_PIPELINE armed):
  dag.commit — killed BETWEEN two sink commits of a unit commit —
  and dag.node (mid-node-walk), plus a qserve.register leg inside the
  DAG; every sink must converge byte-identically on resume.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from spatialflink_tpu.checkpoint import load_checkpoint  # noqa: E402
from spatialflink_tpu.driver import (  # noqa: E402
    RetryPolicy,
    WindowedDataflowDriver,
    _toy_pipeline,
    render_range_result,
)
from spatialflink_tpu.faults import (  # noqa: E402
    ABORT_EXIT_CODE,
    INJECTION_POINTS,
    InjectedFault,
    faults,
)
from spatialflink_tpu.operators.range_query import (  # noqa: E402
    PointPointRangeQuery,
)
from spatialflink_tpu import overload  # noqa: E402
from spatialflink_tpu.operators.trajectory import TStatsQuery  # noqa: E402
from spatialflink_tpu.streams.sinks import (  # noqa: E402
    TransactionalFileSink,
)
from spatialflink_tpu.telemetry import telemetry  # noqa: E402


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.disarm()
    telemetry.disable()
    # The overload.admit leg's driver deliberately leaves its controller
    # in the module slot when no prior one was installed (the
    # ledger-seal contract) — clean it so later tests in the process
    # don't inherit a crashed leg's stale controller.
    overload.uninstall()
    from spatialflink_tpu import qserve

    qserve.uninstall()


RETRY = RetryPolicy(max_retries=1, backoff_s=0.0)


# ---------------------------------------------------------------------------
# Harness 1: range-query pipeline (collection source)


def run_range_leg(workdir, fault_plan=None, with_overload=False):
    grid, conf, source, query = _toy_pipeline()
    sink = TransactionalFileSink(os.path.join(workdir, "egress.csv"))
    ctrl = None
    if with_overload:
        # Admission controller with no budgets: nothing sheds, but
        # every event passes through admit_item — the overload.admit
        # injection point's hit stream.
        from spatialflink_tpu import overload

        ctrl = overload.OverloadController(overload.OverloadPolicy())
    driver = WindowedDataflowDriver(
        checkpoint_path=os.path.join(workdir, "ckpt.bin"),
        checkpoint_every=2, sink=sink, retry=RETRY, failover=False,
        overload=ctrl,
    )
    op = PointPointRangeQuery(conf, grid)
    if fault_plan:
        faults.arm(fault_plan)
    try:
        for res in op.run(source(), [query], 1.5, driver=driver):
            for line in render_range_result(res):
                sink.stage(line)
    finally:
        faults.disarm()
    return driver


def chaos_range(tmp_path, point, kind="raise", at=5, with_overload=False):
    clean = tmp_path / "clean"
    chaos = tmp_path / "chaos"
    clean.mkdir()
    chaos.mkdir()
    run_range_leg(str(clean), with_overload=with_overload)
    want = (clean / "egress.csv").read_bytes()
    assert want, "vacuous matrix entry: clean egress is empty"
    with pytest.raises(InjectedFault):
        run_range_leg(str(chaos), fault_plan=[
            {"point": point, "kind": kind, "at": at, "times": 10_000},
        ], with_overload=with_overload)
    drv = run_range_leg(str(chaos), with_overload=with_overload)  # resume
    assert drv.stats["resumed"] is True
    assert (chaos / "egress.csv").read_bytes() == want


# ---------------------------------------------------------------------------
# Harness 2: SoA pipeline (chunked source → driver.run_soa)


def _soa_chunks(n_chunks=12, per=10):
    rng = np.random.default_rng(11)
    for c in range(n_chunks):
        base = c * per
        yield {
            "ts": np.arange(base, base + per, dtype=np.int64) * 100,
            "x": rng.uniform(0.0, 8.0, per),
            "y": rng.uniform(0.0, 8.0, per),
            "oid": (np.arange(base, base + per) % 7).astype(np.int32),
        }


def run_soa_leg(workdir, fault_plan=None):
    from spatialflink_tpu.streams.soa import SoaWindowAssembler

    grid, conf, _, _ = _toy_pipeline()
    op = TStatsQuery(conf, grid)
    sink = TransactionalFileSink(os.path.join(workdir, "egress.csv"))
    driver = WindowedDataflowDriver(
        checkpoint_path=os.path.join(workdir, "ckpt.bin"),
        checkpoint_every=1, sink=sink, retry=RETRY, failover=False,
    )

    def process(win):
        # Host-only per-window reduction: the matrix entry exercises the
        # soa.feed crash/resume machinery, not a device kernel.
        return (win.start, win.end, win.count,
                float(np.sum(win.arrays["x"])))

    driver.bind(op, process)
    if fault_plan:
        faults.arm(fault_plan)
    try:
        asm = SoaWindowAssembler(conf.window_size_ms, conf.slide_step_ms)
        for start, end, count, sx in driver.run_soa(_soa_chunks(), asm):
            sink.stage(f"{start},{end},{count},{float(sx)!r}")
    finally:
        faults.disarm()
    return driver


def chaos_soa(tmp_path, point, kind="raise", at=6):
    clean = tmp_path / "clean"
    chaos = tmp_path / "chaos"
    clean.mkdir()
    chaos.mkdir()
    run_soa_leg(str(clean))
    want = (clean / "egress.csv").read_bytes()
    assert want
    with pytest.raises(InjectedFault):
        run_soa_leg(str(chaos), fault_plan=[
            {"point": point, "kind": kind, "at": at, "times": 10_000},
        ])
    drv = run_soa_leg(str(chaos))
    assert drv.stats["resumed"] is True
    assert (chaos / "egress.csv").read_bytes() == want


# ---------------------------------------------------------------------------
# Harness 2b: tJoin pane-engine pipeline (run_soa_panes →
# driver.run_precomputed). The device scan happens up front; the driver
# owns WINDOW emission, so the checkpointed position counts windows and
# a resume re-runs the (deterministic) scan and skips the committed
# prefix. source.stall fires on the driver's per-window pull.


def _tjoin_chunks(side, n_chunks=10, per=8):
    rng = np.random.default_rng(21 + side)
    out = []
    for c in range(n_chunks):
        base = c * per
        out.append({
            "ts": np.arange(base, base + per, dtype=np.int64) * 250,
            "x": rng.uniform(0.0, 8.0, per),
            "y": rng.uniform(0.0, 8.0, per),
            "oid": (np.arange(base, base + per) % 5).astype(np.int32),
        })
    return out


def run_tjoin_panes_leg(workdir, fault_plan=None):
    from spatialflink_tpu.operators.trajectory import TJoinQuery

    grid, conf, _, _ = _toy_pipeline()
    op = TJoinQuery(conf, grid)
    sink = TransactionalFileSink(os.path.join(workdir, "egress.csv"))
    driver = WindowedDataflowDriver(
        checkpoint_path=os.path.join(workdir, "ckpt.bin"),
        checkpoint_every=1, sink=sink, retry=RETRY, failover=False,
    )
    if fault_plan:
        faults.arm(fault_plan)
    try:
        for s, e, lo, ro, dd, cnt, over in op.run_soa_panes(
            _tjoin_chunks(0), _tjoin_chunks(1), 1.5, 5, driver=driver,
        ):
            for a, b, d in zip(lo, ro, dd):
                sink.stage(f"{s},{e},{int(a)},{int(b)},{float(d)!r}")
    finally:
        faults.disarm()
    return driver


def chaos_tjoin_panes(tmp_path, point, kind="raise", at=4):
    clean = tmp_path / "clean"
    chaos = tmp_path / "chaos"
    clean.mkdir()
    chaos.mkdir()
    run_tjoin_panes_leg(str(clean))
    want = (clean / "egress.csv").read_bytes()
    assert want, "vacuous matrix entry: clean egress is empty"
    with pytest.raises(InjectedFault):
        run_tjoin_panes_leg(str(chaos), fault_plan=[
            {"point": point, "kind": kind, "at": at, "times": 10_000},
        ])
    drv = run_tjoin_panes_leg(str(chaos))  # resume: re-scan, skip prefix
    assert drv.stats["resumed"] is True
    assert (chaos / "egress.csv").read_bytes() == want


# ---------------------------------------------------------------------------
# Harness 2c: qserve standing-query pipeline (Points + registration
# commands on one stream → QServeOperator). The qserve.register point
# fires inside QueryRegistry.apply — mid-registration-churn — and the
# resumed run must re-apply the replayed commands exactly once (the
# applied-uid set) and converge to byte-identical per-tenant egress.


def run_qserve_leg(workdir, fault_plan=None):
    from spatialflink_tpu import qserve

    grid, conf, source, _ = _toy_pipeline()
    op = qserve.QServeOperator(conf, grid)
    sink = TransactionalFileSink(os.path.join(workdir, "egress.csv"))
    driver = WindowedDataflowDriver(
        checkpoint_path=os.path.join(workdir, "ckpt.bin"),
        checkpoint_every=2, sink=sink, retry=RETRY, failover=False,
    )

    def mk(i, kind, x, y, r, k=5, tenant="t0"):
        return qserve.QServeCommand(
            timestamp=0, action="register", uid=f"c{i}",
            query=qserve.StandingQuery(
                qid=f"q{i}", tenant=tenant, kind=kind, x=x, y=y,
                radius=r, k=k,
            ),
        )

    def stream():
        # Boot registrations, then data, then MID-STREAM churn: an
        # unregister + two registers landing around the 6-8 s windows —
        # after several checkpoints, so the crash legs resume mid-churn.
        churn = [
            qserve.QServeCommand(timestamp=6005, action="unregister",
                                 uid="c10", qid="q1"),
            qserve.QServeCommand(timestamp=7005, action="register",
                                 uid="c11", query=qserve.StandingQuery(
                                     qid="q11", tenant="t1", kind="knn",
                                     x=3.0, y=3.0, radius=2.0, k=5)),
            qserve.QServeCommand(timestamp=8005, action="register",
                                 uid="c12", query=qserve.StandingQuery(
                                     qid="q12", tenant="t1", kind="range",
                                     x=5.0, y=5.0, radius=1.8, k=8)),
        ]
        boot = [mk(0, "range", 4.0, 4.0, 1.5),
                mk(1, "knn", 2.0, 6.0, 2.5),
                mk(2, "knn", 6.0, 2.0, 2.5, tenant="t1")]
        pending = sorted(churn, key=lambda c: c.timestamp)
        yield from boot
        for ev in source():
            while pending and pending[0].timestamp <= ev.timestamp:
                yield pending.pop(0)
            yield ev
        yield from pending

    if fault_plan:
        faults.arm(fault_plan)
    try:
        for res in op.run(stream(), driver=driver):
            for line in res.lines():
                sink.stage(line)
    finally:
        faults.disarm()
        qserve.uninstall()
    return driver


def chaos_qserve(tmp_path, point, kind="raise", at=7):
    clean = tmp_path / "clean"
    chaos = tmp_path / "chaos"
    clean.mkdir()
    chaos.mkdir()
    run_qserve_leg(str(clean))
    want = (clean / "egress.csv").read_bytes()
    assert want, "vacuous matrix entry: clean egress is empty"
    with pytest.raises(InjectedFault):
        # at=7: the 3 boot registrations hit twice (two sliding windows
        # contain ts=0 — duplicate applies still count a hit), so hit 7
        # is the FIRST mid-stream churn command (~6 s), after several
        # checkpoints exist to resume from.
        run_qserve_leg(str(chaos), fault_plan=[
            {"point": point, "kind": kind, "at": at, "times": 10_000},
        ])
    drv = run_qserve_leg(str(chaos))  # resume mid-churn
    assert drv.stats["resumed"] is True
    assert (chaos / "egress.csv").read_bytes() == want


# ---------------------------------------------------------------------------
# Harness 3: Kafka pipeline (FakeBroker ingest, offsets checkpointed)


N_KAFKA = 30


def _fill_topic(broker, topic):
    from spatialflink_tpu.streams.kafka_wire import KafkaWireClient

    client = KafkaWireClient(f"127.0.0.1:{broker.port}")
    msgs = []
    rng = np.random.default_rng(3)
    for i in range(N_KAFKA):
        line = (f"o{i % 5},{i * 100},{rng.uniform(0, 8):.4f},"
                f"{rng.uniform(0, 8):.4f}")
        msgs.append((line.encode(), None, i * 100))
    client.produce(topic, 0, msgs)
    client.close()


def run_kafka_leg(workdir, broker, topic, n_events, *, flush_at_end,
                  fault_plan=None):
    import itertools

    from spatialflink_tpu.checkpoint import kafka_source_state
    from spatialflink_tpu.models.objects import Point
    from spatialflink_tpu.streams.kafka import WireKafkaSource

    def parse(line):
        oid, ts, x, y = line.split(",")
        return Point(obj_id=oid, timestamp=int(ts), x=float(x),
                     y=float(y))

    ckpt = os.path.join(workdir, "ckpt.bin")
    start_offsets = None
    consumed = 0
    if os.path.exists(ckpt):
        ck = load_checkpoint(ckpt)
        start_offsets = ck["kafka"]["offsets"]
        consumed = ck["driver"]["events_consumed"]
    src = WireKafkaSource(topic, f"127.0.0.1:{broker.port}", parse,
                          start_offsets=start_offsets)
    grid, conf, _, query = _toy_pipeline()
    sink = TransactionalFileSink(os.path.join(workdir, "egress.csv"))
    driver = WindowedDataflowDriver(
        checkpoint_path=ckpt, checkpoint_every=1, sink=sink, retry=RETRY,
        failover=False, skip_on_resume=False, flush_at_end=flush_at_end,
        extra_state=lambda: {"kafka": kafka_source_state(src)},
    )
    op = PointPointRangeQuery(conf, grid)
    if fault_plan:
        faults.arm(fault_plan)
    try:
        stream = itertools.islice(iter(src), max(n_events - consumed, 0))
        for res in op.run(stream, [query], 1.5, driver=driver):
            for line in render_range_result(res):
                sink.stage(line)
    finally:
        faults.disarm()
        src.close()
    return driver


def chaos_kafka(tmp_path, point, kind="raise"):
    """Mid-stream ingest crash: leg 1 consumes half the topic and
    checkpoints (end-of-source treated as a kill point, open windows
    stay buffered); leg 2 resumes from the checkpointed offsets and dies
    on its first fetch/leader attempt; leg 3 resumes and finishes. The
    stitched egress must equal one uninterrupted run."""
    test_kafka_wire = pytest.importorskip("test_kafka_wire")
    broker = test_kafka_wire.FakeBroker()
    try:
        _fill_topic(broker, "chaos-clean")
        _fill_topic(broker, "chaos-crash")
        clean = tmp_path / "clean"
        chaos = tmp_path / "chaos"
        clean.mkdir()
        chaos.mkdir()
        run_kafka_leg(str(clean), broker, "chaos-clean", N_KAFKA,
                      flush_at_end=True)
        want = (clean / "egress.csv").read_bytes()
        assert want
        run_kafka_leg(str(chaos), broker, "chaos-crash", N_KAFKA // 2,
                      flush_at_end=False)
        with pytest.raises(InjectedFault):
            run_kafka_leg(str(chaos), broker, "chaos-crash", N_KAFKA,
                          flush_at_end=True, fault_plan=[
                              {"point": point, "kind": kind, "at": 1,
                               "times": 10_000},
                          ])
        drv = run_kafka_leg(str(chaos), broker, "chaos-crash", N_KAFKA,
                            flush_at_end=True)
        assert drv.stats["resumed"] is True
        assert (chaos / "egress.csv").read_bytes() == want
    finally:
        broker.close()


# ---------------------------------------------------------------------------
# Harness 5: pipelined range driver (subprocess, SFT_PIPELINE armed).
# The DRIVER path contains in-process raise-kind faults (drain + sync
# reprocess — tests/test_pipeline.py pins that), so the crash legs use
# the abort kind: os._exit(137) mid-overlap, nothing flushes, and the
# resumed pipelined child must still converge byte-exactly.


def chaos_pipeline(tmp_path, point):
    env_base = {**os.environ, "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": ""}
    env_base.pop("SFT_FAULT_PLAN", None)
    env_base.pop("SFT_PIPELINE", None)

    def child(workdir, pipelined=True, plan=None):
        env = dict(env_base)
        if pipelined:
            env["SFT_PIPELINE"] = json.dumps(
                {"depth": 2, "fetch_lag": 2}
            )
        if plan:
            env["SFT_FAULT_PLAN"] = json.dumps(plan)
        return subprocess.run(
            [sys.executable, "-m", "spatialflink_tpu.driver",
             "--chaos-child", str(workdir)],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=REPO,
        )

    sync_dir = tmp_path / "sync"
    clean = tmp_path / "clean"
    chaos = tmp_path / "chaos"
    for d in (sync_dir, clean, chaos):
        d.mkdir()
    p = child(sync_dir, pipelined=False)
    assert p.returncode == 0, p.stderr[-2000:]
    p = child(clean)
    assert p.returncode == 0, p.stderr[-2000:]
    want = (clean / "egress.csv").read_bytes()
    assert want, "vacuous matrix entry: clean egress is empty"
    # Overlap itself must not move results:
    assert want == (sync_dir / "egress.csv").read_bytes()
    at = 5 if point == "pipeline.ship" else 3
    p = child(chaos, plan=[{"point": point, "kind": "abort", "at": at}])
    assert p.returncode == ABORT_EXIT_CODE, (p.returncode,
                                             p.stderr[-2000:])
    p = child(chaos)  # resume, still pipelined
    assert p.returncode == 0, p.stderr[-2000:]
    assert (chaos / "egress.csv").read_bytes() == want


# ---------------------------------------------------------------------------
# Harness 6: the composed SNCB DAG (subprocess, armed overload +
# pipeline policies). Seven nodes, seven transactional sinks, ONE unit
# checkpoint: the abort kind kills the child at the named point —
# including BETWEEN two sink commits of a unit commit (dag.commit at 9
# = the second unit commit's 2nd sub-append) — and the resumed child
# must converge every sink to the clean child's bytes.


def chaos_dag(tmp_path, point, at):
    from spatialflink_tpu.dag import SMOKE_OVERLOAD_POLICY

    env_base = {**os.environ, "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": ""}
    env_base.pop("SFT_FAULT_PLAN", None)
    # Armed overload (the shed schedule CHANGES egress and must replay
    # exactly across the kill) + armed pipeline policy (result-
    # transparent by contract; arming it proves the DAG path tolerates
    # it).
    env_base["SFT_OVERLOAD_POLICY"] = json.dumps(SMOKE_OVERLOAD_POLICY)
    env_base["SFT_PIPELINE"] = json.dumps({"depth": 2, "fetch_lag": 2})

    def child(workdir, plan=None):
        env = dict(env_base)
        if plan:
            env["SFT_FAULT_PLAN"] = json.dumps(plan)
        return subprocess.run(
            [sys.executable, "-m", "spatialflink_tpu.dag",
             "--chaos-child", str(workdir)],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=REPO,
        )

    clean = tmp_path / "clean"
    chaos = tmp_path / "chaos"
    clean.mkdir()
    chaos.mkdir()
    p = child(clean)
    assert p.returncode == 0, p.stderr[-2000:]

    def sinks(d):
        out = {}
        for f in sorted((d / "egress").iterdir()):
            out[f.name] = f.read_bytes()
        return out

    want = sinks(clean)
    assert len(want) == 7 and all(want.values()), {
        k: len(v) for k, v in want.items()}
    p = child(chaos, plan=[{"point": point, "kind": "abort", "at": at}])
    assert p.returncode == ABORT_EXIT_CODE, (p.returncode,
                                             p.stderr[-2000:])
    p = child(chaos)  # resume from the unit checkpoint
    assert p.returncode == 0, p.stderr[-2000:]
    assert sinks(chaos) == want


def test_dag_qserve_register_kill_under_armed_policies(tmp_path):
    """The acceptance's fourth cut: kill -9 at qserve.register INSIDE
    the composed DAG (mid-registration-churn of the qserve node), same
    armed overload + pipeline env, every sink byte-identical after
    resume."""
    chaos_dag(tmp_path, "qserve.register", at=11)


# ---------------------------------------------------------------------------
# Pipeline hang legs: the wedged (not killed) tunnel mid-overlap.
# In-process, a hang-kind fault on the DRIVER's pipelined path is
# CONTAINED (sleep → raise → drain + synchronous reprocess) — results
# must not move. The WEDGE-past-any-patience mode is bounded by the
# driver's dial watchdog (SFT_DIAL_DEADLINE_S): the first device
# window hangs, the watchdog seals and kills the child with bench's
# dial exit code, and a resumed child still converges byte-exactly.


@pytest.mark.parametrize("point", ["pipeline.ship", "pipeline.fetch"])
def test_pipeline_hang_kind_is_contained_in_process(tmp_path, point):
    from spatialflink_tpu import pipeline

    clean = tmp_path / "clean"
    chaos = tmp_path / "chaos"
    clean.mkdir()
    chaos.mkdir()
    pipeline.install(pipeline.PipelinePolicy(depth=2, fetch_lag=2))
    try:
        run_range_leg(str(clean))
        want = (clean / "egress.csv").read_bytes()
        assert want
        # Bounded hangs (10 ms each), MORE than the retry budget: the
        # pipelined driver path must drain and reprocess synchronously,
        # not crash — and the egress must not move.
        drv = run_range_leg(str(chaos), fault_plan=[
            {"point": point, "kind": "hang", "hang_s": 0.01, "at": 2,
             "times": 3},
        ])
        assert drv.stats["resumed"] is False
        assert (chaos / "egress.csv").read_bytes() == want
    finally:
        pipeline.uninstall()


def test_pipeline_hang_wedge_is_bounded_by_dial_deadline(tmp_path):
    """A hang far past any retry patience on the FIRST overlapped ship:
    the driver's dial watchdog (SFT_DIAL_DEADLINE_S) must kill the
    child with bench's dial exit code in bounded time — not ride out
    the wedge — and a fresh child must still converge to the clean
    bytes."""
    env_base = {**os.environ, "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": ""}
    env_base.pop("SFT_FAULT_PLAN", None)
    env_base["SFT_PIPELINE"] = json.dumps({"depth": 2, "fetch_lag": 2})

    def child(workdir, plan=None, deadline=None):
        env = dict(env_base)
        env.pop("SFT_DIAL_DEADLINE_S", None)
        if deadline is not None:
            env["SFT_DIAL_DEADLINE_S"] = str(deadline)
        if plan:
            env["SFT_FAULT_PLAN"] = json.dumps(plan)
        return subprocess.run(
            [sys.executable, "-m", "spatialflink_tpu.driver",
             "--chaos-child", str(workdir)],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=REPO,
        )

    clean = tmp_path / "clean"
    chaos = tmp_path / "chaos"
    clean.mkdir()
    chaos.mkdir()
    assert child(clean).returncode == 0
    want = (clean / "egress.csv").read_bytes()
    assert want
    p = child(chaos, deadline="0.3", plan=[
        {"point": "pipeline.ship", "kind": "hang", "hang_s": 60,
         "at": 1},
    ])
    from spatialflink_tpu.driver import DIAL_TIMEOUT_EXIT_CODE

    assert p.returncode == DIAL_TIMEOUT_EXIT_CODE, (p.returncode,
                                                    p.stderr[-2000:])
    assert "dial_timeout" in p.stderr or "SFT_DIAL_DEADLINE_S" \
        in p.stderr
    p = child(chaos)  # recover: fresh run, no wedge
    assert p.returncode == 0, p.stderr[-2000:]
    assert (chaos / "egress.csv").read_bytes() == want


# ---------------------------------------------------------------------------
# Harness 7: the grid-partitioned pipeline (subprocess, 8-device CPU
# mesh). run_partitioned dispatches through parallel/halo.py, whose
# shard.exchange point fires once per window right before the boundary-
# pane ppermute — the abort kind is kill -9 mid-exchange. The resumed
# child restores the CHECKPOINTED partition plan (checkpoint.py
# validates the shard count) and must converge byte-identically. The
# virtual-device count must be in the env BEFORE jax initializes, hence
# the subprocess harness.


def chaos_sharded(tmp_path, point):
    env_base = {**os.environ, "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": "",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    env_base.pop("SFT_FAULT_PLAN", None)

    def child(workdir, plan=None):
        env = dict(env_base)
        if plan:
            env["SFT_FAULT_PLAN"] = json.dumps(plan)
        return subprocess.run(
            [sys.executable, "-m", "spatialflink_tpu.driver",
             "--chaos-sharded-child", str(workdir)],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=REPO,
        )

    clean = tmp_path / "clean"
    chaos = tmp_path / "chaos"
    clean.mkdir()
    chaos.mkdir()
    p = child(clean)
    assert p.returncode == 0, p.stderr[-2000:]
    want = (clean / "egress.csv").read_bytes()
    assert want, "vacuous matrix entry: clean egress is empty"
    p = child(chaos, plan=[{"point": point, "kind": "abort", "at": 5}])
    assert p.returncode == ABORT_EXIT_CODE, (p.returncode,
                                             p.stderr[-2000:])
    p = child(chaos)  # resume onto the checkpointed placement
    assert p.returncode == 0, p.stderr[-2000:]
    assert (chaos / "egress.csv").read_bytes() == want


# ---------------------------------------------------------------------------
# The matrix


MATRIX = {
    "device.ship": lambda tp: chaos_range(tp, "device.ship"),
    "device.dispatch": lambda tp: chaos_range(tp, "device.dispatch"),
    "device.fetch": lambda tp: chaos_range(tp, "device.fetch"),
    "window.feed": lambda tp: chaos_range(tp, "window.feed", at=60),
    "driver.window": lambda tp: chaos_range(tp, "driver.window"),
    "sink.write": lambda tp: chaos_range(tp, "sink.write",
                                         kind="partial_write", at=3),
    "soa.feed": lambda tp: chaos_soa(tp, "soa.feed"),
    "kafka.fetch": lambda tp: chaos_kafka(tp, "kafka.fetch"),
    "kafka.leader": lambda tp: chaos_kafka(tp, "kafka.leader"),
    # admit fires once per EVENT (like window.feed) — trigger late
    # enough that a checkpoint exists to resume from.
    "overload.admit": lambda tp: chaos_range(tp, "overload.admit", at=60,
                                             with_overload=True),
    "source.stall": lambda tp: chaos_tjoin_panes(tp, "source.stall"),
    "pipeline.ship": lambda tp: chaos_pipeline(tp, "pipeline.ship"),
    "pipeline.fetch": lambda tp: chaos_pipeline(tp, "pipeline.fetch"),
    "qserve.register": lambda tp: chaos_qserve(tp, "qserve.register"),
    # kill -9 mid-halo-exchange on the grid-partitioned path; resume
    # restores the checkpointed partition plan (8-device subprocess).
    "shard.exchange": lambda tp: chaos_sharded(tp, "shard.exchange"),
    # The 7-node SNCB DAG under armed overload + pipeline policies:
    # at=9 is the SECOND unit commit's 2nd sub-append — the between-
    # sink-commits cut the atomic unit checkpoint exists to close.
    "dag.commit": lambda tp: chaos_dag(tp, "dag.commit", at=9),
    "dag.node": lambda tp: chaos_dag(tp, "dag.node", at=25),
}


def test_matrix_covers_every_registered_point():
    """Registering an injection point without a matrix entry is a
    finding: the registry IS the coverage contract."""
    assert set(MATRIX) == set(INJECTION_POINTS)


@pytest.mark.parametrize("point", sorted(INJECTION_POINTS))
def test_inject_crash_resume_egress_exact(tmp_path, point):
    MATRIX[point](tmp_path)


def test_hang_kind_also_resumes_exactly(tmp_path):
    """The hang-with-timeout kind (the half-open-tunnel mode): the stall
    bounds out, the run dies, and resume is still exact."""
    chaos_range(tmp_path, "device.dispatch", kind="hang")


def test_double_crash_then_resume(tmp_path):
    """Two consecutive crashes (the r3–r5 outages came in bursts) still
    converge to the exact clean egress."""
    clean = tmp_path / "clean"
    chaos = tmp_path / "chaos"
    clean.mkdir()
    chaos.mkdir()
    run_range_leg(str(clean))
    want = (clean / "egress.csv").read_bytes()
    for at in (4, 8):
        with pytest.raises(InjectedFault):
            run_range_leg(str(chaos), fault_plan=[
                {"point": "driver.window", "at": at, "times": 10_000},
            ])
    run_range_leg(str(chaos))
    assert (chaos / "egress.csv").read_bytes() == want


@pytest.mark.slow
def test_sigkill_analog_subprocess_round_trip(tmp_path):
    """The real-process leg: an armed ``abort`` fault ``os._exit(137)``s
    the child mid-commit (no handlers, no flush — kill -9 semantics),
    and a resumed child converges to the clean child's bytes. The same
    round trip runs on every commit as tools/ci's chaos-smoke stage."""
    env_base = {**os.environ, "JAX_PLATFORMS": "cpu",
                "PALLAS_AXON_POOL_IPS": ""}
    env_base.pop("SFT_FAULT_PLAN", None)

    def child(workdir, plan=None):
        env = dict(env_base)
        if plan:
            env["SFT_FAULT_PLAN"] = json.dumps(plan)
        return subprocess.run(
            [sys.executable, "-m", "spatialflink_tpu.driver",
             "--chaos-child", workdir],
            env=env, capture_output=True, text=True, timeout=600,
            cwd=REPO,
        )

    clean = tmp_path / "clean"
    chaos = tmp_path / "chaos"
    clean.mkdir()
    chaos.mkdir()
    assert child(str(clean)).returncode == 0
    p = child(str(chaos),
              plan=[{"point": "sink.write", "kind": "abort", "at": 2}])
    assert p.returncode == ABORT_EXIT_CODE, p.stderr[-2000:]
    assert child(str(chaos)).returncode == 0
    want = (clean / "egress.csv").read_bytes()
    assert want
    assert (chaos / "egress.csv").read_bytes() == want
