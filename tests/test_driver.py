"""The self-healing windowed-dataflow driver (spatialflink_tpu/driver.py):
plain-loop equivalence, retry-with-backoff, device→numpy failover parity
(+ telemetry/ledger visibility), checkpoint/resume, and the exactly-once
egress protocol against the transactional sink."""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from spatialflink_tpu.checkpoint import (  # noqa: E402
    CheckpointCorruptError,
    load_checkpoint,
)
from spatialflink_tpu.driver import (  # noqa: E402
    RetryPolicy,
    WindowedDataflowDriver,
    _toy_pipeline,
    render_range_result,
)
from spatialflink_tpu.faults import InjectedFault, faults  # noqa: E402
from spatialflink_tpu.operators.range_query import (  # noqa: E402
    PointPointRangeQuery,
)
from spatialflink_tpu.operators.trajectory import TStatsQuery  # noqa: E402
from spatialflink_tpu.streams.sinks import (  # noqa: E402
    TransactionalFileSink,
)
from spatialflink_tpu.telemetry import telemetry  # noqa: E402


@pytest.fixture(autouse=True)
def _clean():
    yield
    faults.disarm()
    telemetry.disable()


def _run_range(driver=None, radius=1.5, n_events=120):
    grid, conf, source, query = _toy_pipeline(n_events=n_events)
    op = PointPointRangeQuery(conf, grid)
    return list(op.run(source(), [query], radius, driver=driver)), op


def _range_pipeline(workdir, *, fault_plan=None, checkpoint_every=2,
                    retry=None, n_events=120):
    """One (possibly fault-armed) checkpointed pipeline leg; returns the
    driver (crashes propagate to the caller)."""
    grid, conf, source, query = _toy_pipeline(n_events=n_events)
    sink = TransactionalFileSink(os.path.join(workdir, "egress.csv"))
    driver = WindowedDataflowDriver(
        checkpoint_path=os.path.join(workdir, "ckpt.bin"),
        checkpoint_every=checkpoint_every, sink=sink,
        retry=retry or RetryPolicy(max_retries=1, backoff_s=0.0),
        failover=False,
    )
    op = PointPointRangeQuery(conf, grid)
    if fault_plan:
        faults.arm(fault_plan)
    try:
        for res in op.run(source(), [query], 1.5, driver=driver):
            for line in render_range_result(res):
                sink.stage(line)
    finally:
        faults.disarm()
    return driver


class TestPlainLoopEquivalence:
    def test_default_driver_matches_direct_iteration(self):
        """Routing run() through a default driver is the old plain loop:
        same windows, same objects, same dists, bit for bit."""
        base, _ = _run_range()
        driven, _ = _run_range(driver=WindowedDataflowDriver())
        assert len(base) == len(driven) > 0
        for a, b in zip(base, driven):
            assert (a.start, a.end, a.window_count) == \
                   (b.start, b.end, b.window_count)
            assert [p.obj_id for p in a.objects] == \
                   [p.obj_id for p in b.objects]
            np.testing.assert_array_equal(a.dists, b.dists)

    def test_tstats_through_default_driver(self):
        grid, conf, source, _ = _toy_pipeline()
        base = list(TStatsQuery(conf, grid).run(source()))
        driven = list(TStatsQuery(conf, grid).run(
            source(), driver=WindowedDataflowDriver()))
        assert len(base) == len(driven) > 0
        for a, b in zip(base, driven):
            assert a.stats == b.stats

    def test_no_driver_keeps_old_error_semantics(self):
        """Without an explicit driver, operators construct the STRICT
        driver: a device-path failure propagates immediately — no
        silent retry, no silent completion on the numpy twin (which
        would report host-path results as device results)."""
        faults.arm([{"point": "driver.window", "at": 1, "times": 1}])
        with pytest.raises(InjectedFault):
            _run_range()  # one transient fault; a retry WOULD recover
        assert faults.counts.get("driver.window") == 1  # single attempt

    def test_realtime_tstats_is_never_retried(self):
        """The realtime ValueState walk mutates per-oid running state —
        a half-applied window must not re-run (double counting). Even a
        retry-configured driver crashes instead."""
        from spatialflink_tpu.operators.query_config import (
            QueryConfiguration,
            QueryType,
        )

        grid, _, source, _ = _toy_pipeline()
        conf = QueryConfiguration(QueryType.RealTime)
        faults.arm([{"point": "driver.window", "at": 2, "times": 1}])
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=5, backoff_s=0.0))
        with pytest.raises(InjectedFault):
            list(TStatsQuery(conf, grid).run(source(), driver=drv))
        assert drv.stats["retries"] == 0
        assert drv.stats["failovers"] == 0


class TestMigratedOperators:
    """ISSUE 9: KnnQuery.run / JoinQuery.run / TJoinQuery.run_soa_panes
    route through the driver — default-strict semantics pinned (single
    attempt, errors propagate) plus failover parity for the new numpy
    twins."""

    def _knn(self, driver=None):
        grid, conf, source, query = _toy_pipeline()
        from spatialflink_tpu.operators.knn_query import PointPointKNNQuery

        op = PointPointKNNQuery(conf, grid)
        return list(op.run(source(), query, 2.5, 3, driver=driver))

    def _join(self, driver=None, naive=False):
        from spatialflink_tpu.operators.join_query import (
            PointPointJoinQuery,
        )
        from spatialflink_tpu.operators.query_config import (
            QueryConfiguration,
            QueryType,
        )

        grid, conf, source, _ = _toy_pipeline()
        if naive:
            # Micro-batches wide enough that each holds BOTH sides of
            # the interleaved stream (events are 100 ms apart).
            conf = QueryConfiguration(QueryType.RealTimeNaive,
                                      realtime_batch_ms=2000)
        op = PointPointJoinQuery(conf, grid)
        left = [e for i, e in enumerate(source()) if i % 2 == 0]
        right = [e for i, e in enumerate(source()) if i % 2 == 1]
        return list(op.run(iter(left), iter(right), 1.5, driver=driver))

    def test_knn_no_driver_is_single_attempt(self):
        faults.arm([{"point": "driver.window", "at": 1, "times": 1}])
        with pytest.raises(InjectedFault):
            self._knn()  # one transient fault; a retry WOULD recover
        assert faults.counts.get("driver.window") == 1

    def test_join_no_driver_is_single_attempt(self):
        faults.arm([{"point": "driver.window", "at": 1, "times": 1}])
        with pytest.raises(InjectedFault):
            self._join()
        assert faults.counts.get("driver.window") == 1

    def test_knn_failover_parity(self):
        base = self._knn()
        faults.arm([{"point": "driver.window", "at": 2, "times": 10_000}])
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=0, backoff_s=0.0))
        driven = self._knn(driver=drv)
        faults.disarm()
        assert drv.backend == "fallback"
        assert len(driven) == len(base) > 4
        assert any(r.neighbors for r in base), "degenerate: no neighbors"
        for a, b in zip(base, driven):
            assert (a.start, a.end) == (b.start, b.end)
            # Same ordered (objID, representative) winners; distances
            # agree to float ulps (FMA fusion freedom).
            assert [(oid, ev.obj_id) for oid, _, ev in a.neighbors] == \
                   [(oid, ev.obj_id) for oid, _, ev in b.neighbors]
            np.testing.assert_allclose(
                [d for _, d, _ in a.neighbors],
                [d for _, d, _ in b.neighbors], rtol=3e-7)

    def test_join_naive_failover_parity(self):
        base = self._join(naive=True)
        faults.arm([{"point": "driver.window", "at": 1, "times": 10_000}])
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=0, backoff_s=0.0))
        driven = self._join(driver=drv, naive=True)
        faults.disarm()
        assert drv.backend == "fallback"
        assert len(driven) == len(base) > 0
        assert any(r.pairs for r in base), "degenerate: no pairs"
        for a, b in zip(base, driven):
            assert [(x.obj_id, y.obj_id) for x, y, _ in a.pairs] == \
                   [(x.obj_id, y.obj_id) for x, y, _ in b.pairs]
            np.testing.assert_allclose(
                [d for _, _, d in a.pairs], [d for _, _, d in b.pairs],
                rtol=3e-7)

    def test_join_bucketed_has_no_twin_and_stays_strict(self):
        """The window-based grid-hash mode's pair order is device
        compaction order — no twin exists, so even a failover-enabled
        driver crashes when the device path dies (honest, not silent)."""
        faults.arm([{"point": "driver.window", "at": 1, "times": 10_000}])
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=0, backoff_s=0.0))
        with pytest.raises(InjectedFault):
            self._join(driver=drv)
        assert drv.stats["failovers"] == 0

    def test_tjoin_panes_checkpoint_resume_byte_identical(self, tmp_path):
        """run_soa_panes through run_precomputed: the position counts
        fired windows; a resume re-runs the deterministic scan and
        skips the committed prefix. Reuses the chaos-matrix tjoin
        harness at the driver.window point (the matrix leg itself
        exercises source.stall)."""
        from test_chaos_matrix import chaos_tjoin_panes

        chaos_tjoin_panes(tmp_path, "driver.window", at=5)


class TestRetry:
    def test_transient_fault_is_retried_and_recovers(self):
        """One injected failure + one retry budget → the run completes
        with identical results and a driver_retry event."""
        telemetry.enable()
        base, _ = _run_range()
        faults.arm([{"point": "driver.window", "at": 3, "times": 1}])
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=2, backoff_s=0.0))
        driven, _ = _run_range(driver=drv)
        assert drv.stats["retries"] == 1
        assert drv.stats["failovers"] == 0
        assert drv.backend == "device"
        assert len(driven) == len(base)
        for a, b in zip(base, driven):
            np.testing.assert_array_equal(a.dists, b.dists)
        assert telemetry.snapshot()["driver"]["retries"] == 1
        assert "driver_retry" in [e["name"] for e in telemetry.events]

    def test_exhausted_retries_raise_in_strict_mode(self):
        faults.arm([{"point": "driver.window", "at": 1, "times": 99}])
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=1, backoff_s=0.0),
            failover=False)
        with pytest.raises(InjectedFault):
            _run_range(driver=drv)
        assert drv.stats["retries"] == 1

    def test_backoff_schedule_pinned_via_sleep_hook(self):
        """RetryPolicy.sleep is the injectable clock: the full backoff
        schedule is pinned deterministically with ZERO wall-clock
        sleeping and no module monkeypatching (the production default —
        sleep=None → time.sleep — is untouched)."""
        sleeps = []
        faults.arm([{"point": "driver.window", "at": 1, "times": 3}])
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=3, backoff_s=0.1,
                              multiplier=3.0, sleep=sleeps.append))
        _run_range(driver=drv)
        assert sleeps == [0.1, pytest.approx(0.3), pytest.approx(0.9)]
        assert drv.stats["retries"] == 3

    def test_sleep_hook_default_is_time_sleep(self, monkeypatch):
        import spatialflink_tpu.driver as driver_mod

        called = []
        monkeypatch.setattr(driver_mod.time, "sleep", called.append)
        RetryPolicy().do_sleep(0.07)
        assert called == [0.07]


class TestFailoverParity:
    """ISSUE acceptance: device→fallback switch mid-stream changes no
    results and is visible as telemetry events consumable by `sfprof
    health` / the SLO engine."""

    def test_range_failover_set_parity_and_visibility(self, tmp_path):
        telemetry.enable()
        base, _ = _run_range()
        # Device path dies permanently at window 3 → numpy fallback.
        faults.arm([{"point": "driver.window", "at": 3, "times": 10_000}])
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=1, backoff_s=0.0))
        driven, _ = _run_range(driver=drv)
        faults.disarm()
        assert drv.backend == "fallback"
        assert drv.stats["failovers"] == 1
        assert len(driven) == len(base) > 4
        for a, b in zip(base, driven):
            assert (a.start, a.end) == (b.start, b.end)
            # Bit/set parity: the KEPT SET is identical; distances agree
            # to float ulps (XLA may fuse x²+y² with FMA, numpy cannot).
            assert [p.obj_id for p in a.objects] == \
                   [p.obj_id for p in b.objects]
            np.testing.assert_allclose(a.dists, b.dists, rtol=3e-7)

        # Telemetry: failover event + snapshot counter...
        snap = telemetry.snapshot()
        assert snap["driver"]["failovers"] == 1
        assert "failover" in [e["name"] for e in telemetry.events]
        # ...and it reaches a LEDGER health/SLO consumers can read.
        ledger = tmp_path / "ledger.json"
        telemetry.write_ledger(str(ledger), capture_costs=False)
        doc = json.loads(ledger.read_text())
        assert doc["snapshot"]["driver"]["failovers"] == 1

        from tools.sfprof import slo as sfslo

        rows = sfslo.evaluate({"failover_budget": 0}, doc)
        assert rows == [("slo:failover_budget", 1.0, "<= 0", False)]
        rows = sfslo.evaluate({"failover_budget": 1}, doc)
        assert rows[0][3] is True

    def test_tstats_failover_parity(self):
        grid, conf, source, _ = _toy_pipeline()
        base = list(TStatsQuery(conf, grid).run(source()))
        faults.arm([{"point": "driver.window", "at": 1, "times": 10_000}])
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=0, backoff_s=0.0))
        driven = list(TStatsQuery(conf, grid).run(source(), driver=drv))
        faults.disarm()
        assert drv.backend == "fallback"
        assert len(driven) == len(base) > 4
        for a, b in zip(base, driven):
            assert set(a.stats) == set(b.stats)
            for oid in a.stats:
                np.testing.assert_allclose(
                    a.stats[oid][0], b.stats[oid][0], rtol=1e-6)
                assert a.stats[oid][1] == b.stats[oid][1]  # exact ms

    def test_live_slo_engine_budgets_failover(self):
        from spatialflink_tpu import slo

        telemetry.enable()
        engine = slo.SloEngine(slo.SloSpec(failover_budget=0,
                                           retry_budget=0,
                                           eval_interval_s=0.0))
        try:
            faults.arm(
                [{"point": "driver.window", "at": 2, "times": 10_000}])
            drv = WindowedDataflowDriver(
                retry=RetryPolicy(max_retries=1, backoff_s=0.0))
            _run_range(driver=drv)
            rows = {r["check"]: r["ok"] for r in engine.evaluate()}
            assert rows["failover_budget"] is False
            assert rows["retry_budget"] is False
        finally:
            slo.uninstall()


class TestFailoverResume:
    """A checkpoint taken AFTER failover records backend="fallback" —
    resuming it must neither dial the (dead) device path during setup
    nor crash into a None fallback."""

    def _failover_checkpoint(self, tmp_path):
        grid, conf, source, query = _toy_pipeline()
        ck = str(tmp_path / "ck.bin")
        drv = WindowedDataflowDriver(
            checkpoint_path=ck, checkpoint_every=1,
            retry=RetryPolicy(max_retries=0, backoff_s=0.0))
        faults.arm([{"point": "driver.window", "at": 1,
                     "times": 10_000}])
        op = PointPointRangeQuery(conf, grid)
        base = list(op.run(source(), [query], 1.5, driver=drv))
        faults.disarm()
        assert drv.backend == "fallback" and base
        return grid, conf, source, query, ck

    def test_resume_after_failover_skips_device_setup(self, tmp_path,
                                                      monkeypatch):
        grid, conf, source, query, ck = self._failover_checkpoint(tmp_path)
        # Resume on a "dead tunnel": ANY device staging during setup
        # would hang a real resume — simulate by making the evaluator
        # builder (the setup's device-touching step) explode.
        def boom(*a, **k):
            raise AssertionError("resume dialed the dead device path")

        monkeypatch.setattr(PointPointRangeQuery, "_window_evaluator",
                            boom)
        drv2 = WindowedDataflowDriver(
            checkpoint_path=ck,
            retry=RetryPolicy(max_retries=0, backoff_s=0.0))
        op2 = PointPointRangeQuery(conf, grid)
        list(op2.run(source(), [query], 1.5, driver=drv2))
        assert drv2.stats["resumed"] is True
        assert drv2.backend == "fallback"

    def test_resume_fallback_checkpoint_without_fallback_is_loud(
            self, tmp_path):
        grid, conf, source, query, ck = self._failover_checkpoint(tmp_path)
        drv2 = WindowedDataflowDriver(checkpoint_path=ck, failover=False)
        op2 = PointPointRangeQuery(conf, grid)
        with pytest.raises(ValueError, match="failover"):
            list(op2.run(source(), [query], 1.5, driver=drv2))


class TestCheckpointResume:
    def test_crash_resume_egress_byte_identical(self, tmp_path):
        clean = tmp_path / "clean"
        chaos = tmp_path / "chaos"
        clean.mkdir()
        chaos.mkdir()
        _range_pipeline(str(clean))
        want = (clean / "egress.csv").read_bytes()
        assert want
        with pytest.raises(InjectedFault):
            _range_pipeline(
                str(chaos),
                fault_plan=[{"point": "driver.window", "at": 7,
                             "times": 10_000}],
            )
        partial = (chaos / "egress.csv").read_bytes()
        assert partial != want  # the crash really interrupted egress
        drv = _range_pipeline(str(chaos))
        assert drv.stats["resumed"] is True
        assert (chaos / "egress.csv").read_bytes() == want

    def test_resume_skips_consumed_prefix_exactly(self, tmp_path):
        """events_consumed in the checkpoint + the restored assembler
        must hand the resumed run the exact remaining suffix — no window
        fires twice, none is skipped."""
        d = tmp_path / "p"
        d.mkdir()
        with pytest.raises(InjectedFault):
            _range_pipeline(
                str(d),
                fault_plan=[{"point": "window.feed", "at": 70,
                             "times": 10_000}],
            )
        ck = load_checkpoint(str(d / "ckpt.bin"))
        consumed = ck["driver"]["events_consumed"]
        assert 0 < consumed < 120
        drv = _range_pipeline(str(d))
        # the resumed leg consumes exactly the remaining suffix — the
        # full stream is seen once across both legs
        assert drv.stats["events"] == 120 - consumed

    def test_checkpoint_carries_egress_marker_and_backend(self, tmp_path):
        d = tmp_path / "p"
        d.mkdir()
        _range_pipeline(str(d))
        ck = load_checkpoint(str(d / "ckpt.bin"))
        assert ck["egress"]["bytes"] == \
            os.path.getsize(str(d / "egress.csv"))
        assert ck["driver"]["backend"] == "device"
        assert ck["driver"]["events_consumed"] == 120

    def test_corrupt_checkpoint_fails_loudly_on_resume(self, tmp_path):
        d = tmp_path / "p"
        d.mkdir()
        _range_pipeline(str(d))
        path = str(d / "ckpt.bin")
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[:-5])
        with pytest.raises(CheckpointCorruptError, match="truncated"):
            _range_pipeline(str(d))

    def test_run_windows_rejects_checkpointing(self):
        drv = WindowedDataflowDriver(checkpoint_path="x.bin")
        drv.op = object()
        drv.process = lambda w: w
        with pytest.raises(ValueError, match="run_windows"):
            list(drv.run_windows(iter([])))


class TestDialDeadline:
    """The driver's bounded first device touch (the bench dial-deadline
    semantics): a --checkpoint resume on a down tunnel must die in
    bounded time with the ledger stream sealed ``dial_timeout``, never
    hang forever."""

    def test_resolution_order(self, monkeypatch):
        from spatialflink_tpu.driver import resolve_dial_deadline_s

        monkeypatch.delenv("SFT_DIAL_DEADLINE_S", raising=False)
        assert resolve_dial_deadline_s() == 0.0  # unset env → disabled
        monkeypatch.setenv("SFT_DIAL_DEADLINE_S", "7.5")
        assert resolve_dial_deadline_s() == 7.5
        assert resolve_dial_deadline_s(2.0) == 2.0  # explicit wins

    def test_first_window_hang_fires_watchdog_and_seals(
            self, tmp_path, monkeypatch):
        import time as _time

        import spatialflink_tpu.driver as driver_mod

        fired = []
        monkeypatch.setattr(driver_mod, "_dial_timeout_exit",
                            fired.append)
        stream = tmp_path / "run.stream.jsonl"
        telemetry.enable(stream_path=str(stream),
                         stream_flush_interval_s=0.0)
        grid, conf, source, query = _toy_pipeline()
        op = PointPointRangeQuery(conf, grid)
        drv = WindowedDataflowDriver(dial_deadline_s=0.05)

        def slow_first(win):
            _time.sleep(0.4)  # the wedge: > deadline on window 1 only
            return win

        drv.bind(op, slow_first)
        out = list(drv.run(source()))
        assert out  # the recorder exit hook let the run complete
        assert fired == [driver_mod.DIAL_TIMEOUT_EXIT_CODE]
        telemetry.disable()
        recs = [json.loads(ln)
                for ln in stream.read_text().splitlines()]
        sealed = [r for r in recs if r.get("t") == "epilogue"]
        # The watchdog's seal wins; disable() cannot double-seal.
        assert [r["reason"] for r in sealed] == ["dial_timeout"]

    def test_fast_first_window_never_fires(self, monkeypatch):
        import spatialflink_tpu.driver as driver_mod

        fired = []
        monkeypatch.setattr(driver_mod, "_dial_timeout_exit",
                            fired.append)
        grid, conf, source, query = _toy_pipeline()
        op = PointPointRangeQuery(conf, grid)
        drv = WindowedDataflowDriver(dial_deadline_s=5.0)
        drv.bind(op, lambda win: win)
        out = list(drv.run(source()))
        assert out and fired == []
        assert drv._dialed is True  # later windows never re-arm


class TestTransactionalSink:
    def test_partial_write_is_repaired_on_restore(self, tmp_path):
        """A torn (fsync'd!) half-append dies mid-commit; restore from
        the checkpointed marker truncates it and the replay regenerates
        the records — no gap, no dup."""
        path = str(tmp_path / "out.csv")
        s = TransactionalFileSink(path)
        s.reset()
        s.stage("one")
        marker = s.commit()
        s.stage("two")
        s.stage("three")
        faults.arm([{"point": "sink.write", "kind": "partial_write"}])
        with pytest.raises(InjectedFault):
            s.commit()
        faults.disarm()
        torn = open(path, "rb").read()
        assert torn != b"one\n"  # bytes really landed past the marker
        s2 = TransactionalFileSink(path)
        s2.restore(marker)
        assert open(path, "rb").read() == b"one\n"
        s2.stage("two")
        s2.stage("three")
        s2.commit()
        assert open(path, "rb").read() == b"one\ntwo\nthree\n"

    def test_restore_missing_committed_bytes_is_corrupt(self, tmp_path):
        path = str(tmp_path / "out.csv")
        s = TransactionalFileSink(path)
        s.reset()
        s.stage("a" * 100)
        marker = s.commit()
        with open(path, "wb") as f:
            f.write(b"a" * 10)  # committed egress lost out-of-band
        with pytest.raises(CheckpointCorruptError, match="out-of-band"):
            TransactionalFileSink(path).restore(marker)

    def test_exception_path_never_publishes_staged_records(self, tmp_path):
        path = str(tmp_path / "out.csv")
        with pytest.raises(RuntimeError, match="boom"):
            with TransactionalFileSink(path) as s:
                s.reset()
                s.stage("doomed")
                raise RuntimeError("boom")
        assert open(path, "rb").read() == b""

    def test_header_counts_into_committed_bytes(self, tmp_path):
        path = str(tmp_path / "out.csv")
        s = TransactionalFileSink(path, header="h1,h2")
        s.reset()
        s.stage("1,2")
        marker = s.commit()
        assert open(path).read() == "h1,h2\n1,2\n"
        s2 = TransactionalFileSink(path, header="h1,h2")
        s2.restore(marker)
        assert open(path).read() == "h1,h2\n1,2\n"


class TestRejectedConfigPreservesEgress:
    def test_rejected_run_windows_does_not_wipe_prior_egress(self, tmp_path):
        """A driver rejected before running (run_windows + checkpoint is
        invalid) must not have truncated a previous run's committed
        egress during attach/load."""
        path = str(tmp_path / "out.csv")
        prior = TransactionalFileSink(path)
        prior.reset()
        prior.stage("precious")
        prior.commit()

        grid, conf, source, query = _toy_pipeline()
        sink = TransactionalFileSink(path)
        drv = WindowedDataflowDriver(
            checkpoint_path=str(tmp_path / "ck.bin"), sink=sink)
        drv.bind(PointPointRangeQuery(conf, grid), lambda w: w)
        with pytest.raises(ValueError, match="run_windows"):
            list(drv.run_windows(iter([])))
        assert open(path, "rb").read() == b"precious\n"
