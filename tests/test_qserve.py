"""qserve — multi-tenant continuous-query serving (ISSUE 13).

Pins the subsystem's four contracts:

- **parity**: the bucketed vmapped kernel is BIT-identical to per-query
  sequential evaluation of the same serving program (and to the CPU
  mesh counterpart ``sharded_registry_bucket``); vs the independently-
  fused ``knn_points_fused`` operator program, winner sets/indices are
  exact and distances agree to 1 ulp (the suite-wide differently-fused-
  programs contract, same as run_multi's);
- **recompile surface**: randomized register/unregister storms move a
  bucket across occupancy rungs but compile at most ladder-many
  signatures (the telemetry recompile detector is the guard — the
  tests/test_compaction.py idiom);
- **per-tenant QoS**: a firehose tenant class sheds ITSELF (admission +
  result budgets, per-class counters, per-class SLO checks live and
  post-hoc) and never moves the fleet's degradation rung;
- **one intern home**: registration strings intern into the operator's
  objID table — no second string table exists.

The kill-mid-churn crash leg lives in tests/test_chaos_matrix.py
(``qserve.register``); the 1024-query acceptance run is the slow test
at the bottom.
"""

import json

import numpy as np
import pytest

from spatialflink_tpu import overload, qserve, slo
from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import Point
from spatialflink_tpu.operators.query_config import (
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.telemetry import telemetry

GRID = UniformGrid(10, 0.0, 10.0, 0.0, 10.0)
CONF = QueryConfiguration(QueryType.WindowBased, window_size=2.0,
                          slide_step=1.0)


@pytest.fixture(autouse=True)
def _clean_slots():
    yield
    telemetry.disable()
    overload.uninstall()
    qserve.uninstall()


def _mk_query(i, rng, kind=None, k=5, radius=None, tenant_class="default"):
    return qserve.StandingQuery(
        qid=f"q{i}", tenant=f"t{i % 3}",
        kind=kind or ("knn" if i % 2 else "range"),
        x=float(rng.uniform(1, 9)), y=float(rng.uniform(1, 9)),
        radius=float(radius if radius is not None
                     else rng.uniform(0.5, 2.5)),
        k=k, tenant_class=tenant_class,
    )


def _point_stream(rng, n=120, tmax_ms=12_000):
    for i in range(n):
        yield Point(obj_id=f"o{i % 13}", timestamp=(tmax_ms * i) // n,
                    x=float(rng.uniform(0, 10)),
                    y=float(rng.uniform(0, 10)))


def _register_cmds(queries, ts=0, prefix="c"):
    return [
        qserve.QServeCommand(timestamp=ts, action="register",
                             uid=f"{prefix}{i}", query=q)
        for i, q in enumerate(queries)
    ]


# ---------------------------------------------------------------------------
# kernel parity


def _bucket_inputs(rng, n=256, n_obj=40):
    xy = rng.uniform(0, 10, (n, 2))
    oid = rng.integers(0, n_obj, n).astype(np.int32)
    cell = GRID.assign_cells_np(xy)
    valid = np.ones(n, bool)
    return xy, oid, cell, valid


def test_bucket_kernel_bit_matches_sequential_evaluation(rng):
    """The acceptance pin: the bucketed vmapped program's row for query
    i is BIT-identical to evaluating the same serving program for that
    query alone (registry_bucket_query jitted per query)."""
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.ops.query_registry import (
        registry_bucket_kernel,
        registry_bucket_query,
    )

    xy, oid, cell, valid = _bucket_inputs(rng)
    qs = [_mk_query(i, rng) for i in range(6)]
    cap = 8
    qxy, radius, qvalid, tables = qserve.bucket_host_arrays(GRID, qs, cap)
    res = jax.jit(
        registry_bucket_kernel,
        static_argnames=("k", "num_segments", "query_block"),
    )(
        jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(cell),
        jnp.asarray(tables), jnp.asarray(oid), jnp.asarray(qxy),
        jnp.asarray(radius), jnp.asarray(qvalid),
        k=8, num_segments=64, query_block=8,
    )
    single = jax.jit(
        registry_bucket_query, static_argnames=("k", "num_segments")
    )
    for i in range(len(qs)):
        d, seg, idx, nv, within = single(
            jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(cell),
            jnp.asarray(tables[i]), jnp.asarray(oid),
            jnp.asarray(qxy[i]), jnp.asarray(radius[i]),
            jnp.asarray(qvalid[i]), k=8, num_segments=64,
        )
        np.testing.assert_array_equal(np.asarray(res.dist[i]),
                                      np.asarray(d))
        np.testing.assert_array_equal(np.asarray(res.segment[i]),
                                      np.asarray(seg))
        np.testing.assert_array_equal(np.asarray(res.index[i]),
                                      np.asarray(idx))
        assert int(res.num_valid[i]) == int(nv)
        assert int(res.within[i]) == int(within)
    # padded rung lanes are empty (padding never changes results)
    for i in range(len(qs), cap):
        assert int(res.num_valid[i]) == 0
        assert int(res.within[i]) == 0
        assert np.all(np.asarray(res.segment[i]) == -1)


def test_bucket_kernel_vs_operator_kernel_fusion_contract(rng):
    """vs knn_points_fused — a DIFFERENTLY-FUSED program (no `within`
    consumer): winner sets, indices and counts exact, distances to
    1 ulp (rtol 1e-12 — the run_multi/mesh suite-wide contract)."""
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.operators.base import flags_for_queries
    from spatialflink_tpu.ops.knn import knn_points_fused
    from spatialflink_tpu.ops.query_registry import registry_bucket_kernel

    xy, oid, cell, valid = _bucket_inputs(rng)
    qs = [_mk_query(i, rng) for i in range(5)]
    qxy, radius, qvalid, tables = qserve.bucket_host_arrays(GRID, qs, 8)
    res = jax.jit(
        registry_bucket_kernel,
        static_argnames=("k", "num_segments", "query_block"),
    )(
        jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(cell),
        jnp.asarray(tables), jnp.asarray(oid), jnp.asarray(qxy),
        jnp.asarray(radius), jnp.asarray(qvalid),
        k=8, num_segments=64, query_block=8,
    )
    for i, q in enumerate(qs):
        ft = flags_for_queries(GRID, q.radius, [Point(x=q.x, y=q.y)])
        ref = knn_points_fused(
            jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(cell),
            jnp.asarray(ft), jnp.asarray(oid),
            jnp.asarray([q.x, q.y]), q.radius, k=8, num_segments=64,
        )
        np.testing.assert_array_equal(np.asarray(res.segment[i]),
                                      np.asarray(ref.segment))
        np.testing.assert_array_equal(np.asarray(res.index[i]),
                                      np.asarray(ref.index))
        np.testing.assert_allclose(np.asarray(res.dist[i]),
                                   np.asarray(ref.dist), rtol=1e-12)
        assert int(res.num_valid[i]) == int(ref.num_valid)


def test_sharded_registry_bucket_matches_single_device(rng):
    """Mesh parity (the mesh-parity pass's name-referenced test):
    sharded_registry_bucket on the 8-device CPU mesh is bit-identical to
    registry_bucket_kernel — every field, `within` included."""
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.parallel.mesh import make_mesh
    from spatialflink_tpu.parallel.sharded import sharded_registry_bucket
    from spatialflink_tpu.ops.query_registry import registry_bucket_kernel

    xy, oid, cell, valid = _bucket_inputs(rng)
    qs = [_mk_query(i, rng) for i in range(6)]
    qxy, radius, qvalid, tables = qserve.bucket_host_arrays(GRID, qs, 8)
    args = (
        jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(cell),
        jnp.asarray(tables), jnp.asarray(oid), jnp.asarray(qxy),
        jnp.asarray(radius), jnp.asarray(qvalid),
    )
    res = jax.jit(
        registry_bucket_kernel,
        static_argnames=("k", "num_segments", "query_block"),
    )(*args, k=8, num_segments=64, query_block=8)
    mesh = make_mesh((8,), ("data",))
    telemetry.enable()
    try:
        sres = sharded_registry_bucket(mesh, *args, k=8, num_segments=64)
        gauges = telemetry.collective_gauges()
    finally:
        telemetry.disable()
    for field in ("dist", "segment", "index", "num_valid", "within"):
        np.testing.assert_array_equal(
            np.asarray(getattr(res, field)),
            np.asarray(getattr(sres, field)), err_msg=field,
        )
    # The mesh path must account its logical collective traffic
    # (pmin merge + replicated query broadcast) host-side.
    assert gauges is not None and int(gauges["bytes"]) > 0


def test_range_bucket_overflow_counter():
    import jax.numpy as jnp

    from spatialflink_tpu.ops.query_registry import range_bucket_overflow

    within = jnp.asarray([3, 8, 12, 0])
    assert int(range_bucket_overflow(within, 8)) == 4


# ---------------------------------------------------------------------------
# registry semantics


def test_bucket_key_and_rungs(rng):
    q = _mk_query(0, rng, kind="knn", k=5, radius=0.004)
    assert qserve.query_rung(q) == 8
    q2 = _mk_query(1, rng, kind="knn", k=17, radius=0.004)
    assert qserve.query_rung(q2) == 32
    assert qserve.bucket_key(q)[0] == "knn"
    # radius classes: power-of-two bands above the base
    assert qserve.radius_class(0.0005) == 0
    assert qserve.radius_class(0.001) == 0
    assert qserve.radius_class(0.0021) > qserve.radius_class(0.001)


def test_command_application_is_exactly_once(rng):
    """Duplicate uids (sliding-window refires, crash/retry replays) are
    no-ops — the byte-identical-resume contract's foundation."""
    from spatialflink_tpu.utils.interning import Interner

    reg = qserve.QueryRegistry(GRID, Interner())
    q = _mk_query(0, rng)
    cmd = qserve.QServeCommand(timestamp=0, action="register", uid="u0",
                               query=q)
    assert reg.apply(cmd) is True
    assert reg.apply(cmd) is False  # replay: no-op
    assert len(reg) == 1 and reg.registered_total == 1
    un = qserve.QServeCommand(timestamp=1, action="unregister", uid="u1",
                              qid=q.qid)
    assert reg.apply(un) is True
    assert reg.apply(un) is False
    assert len(reg) == 0 and reg.unregistered_total == 1


def test_registry_state_round_trip(rng):
    from spatialflink_tpu.utils.interning import Interner

    reg = qserve.QueryRegistry(GRID, Interner())
    for i, cmd in enumerate(_register_cmds(
            [_mk_query(i, rng) for i in range(5)])):
        reg.apply(cmd)
    reg.apply(qserve.QServeCommand(timestamp=9, action="unregister",
                                   uid="u", qid="q2"))
    state = reg.state()
    reg2 = qserve.QueryRegistry(GRID, Interner())
    reg2.restore(json.loads(json.dumps(state)))  # survives JSON round trip
    assert sorted(reg2._queries) == sorted(reg._queries)
    assert reg2._applied == reg._applied
    assert reg2.unregistered_total == 1
    # flag tables are derived data — rebuilt identically
    for qid in reg2._queries:
        np.testing.assert_array_equal(reg2.flags(qid), reg.flags(qid))


def test_one_intern_home(rng):
    """Registration strings intern into the OPERATOR's objID table —
    one intern home, no second string table anywhere in qserve."""
    import inspect

    op = qserve.QServeOperator(CONF, GRID)
    assert op.qserve_registry.interner is op.interner
    before = len(op.interner)
    op.qserve_registry.apply(qserve.QServeCommand(
        timestamp=0, action="register", uid="u0",
        query=_mk_query(0, rng),
    ))
    assert len(op.interner) == before + 2  # tenant + qid interned there
    assert op.interner._to_int["q0"] is not None
    # the module never constructs its own Interner
    src = inspect.getsource(qserve)
    assert "Interner(" not in src


# ---------------------------------------------------------------------------
# churn vs recompile surface (the ≤K-stable-signatures contract)


def test_registration_storm_keeps_signatures_on_the_ladder(rng):
    """Randomized register/unregister storms sweep a bucket across every
    occupancy rung; the bucket kernel must compile at most ladder-many
    signatures (telemetry recompile detector — the
    tests/test_compaction.py idiom), and re-visiting an occupancy adds
    none."""
    from spatialflink_tpu.ops.compaction import capacity_ladder

    cap_max = 32  # ladder (8, 16, 32)
    op = qserve.QServeOperator(CONF, GRID, cap_max=cap_max)
    reg = op.qserve_registry
    # Same kind/k/radius-class → ONE bucket; occupancy is the only mover.
    pool = [
        qserve.StandingQuery(
            qid=f"q{i}", tenant=f"t{i % 5}", kind="knn",
            x=float(rng.uniform(1, 9)), y=float(rng.uniform(1, 9)),
            radius=1.5, k=5,
        )
        for i in range(cap_max)
    ]
    # Pre-intern every qid/tenant BEFORE enabling telemetry so the
    # interner bucket (num_segments) is stable across the storm — the
    # bucket rung must be the only varying static.
    for q in pool:
        reg.interner.intern(q.tenant)
        reg.interner.intern(q.qid)
    for i in range(130):
        reg.interner.intern(f"o{i % 13}")

    def stream(phase, live_target):
        # (re)register/unregister down to live_target, then some data
        cmds = []
        live = set(reg._queries)
        want = {q.qid for q in pool[:live_target]}
        seq = 0
        for qid in sorted(live - want):
            cmds.append(qserve.QServeCommand(
                timestamp=0, action="unregister",
                uid=f"p{phase}u{seq}", qid=qid))
            seq += 1
        for q in pool:
            if q.qid in want - live:
                cmds.append(qserve.QServeCommand(
                    timestamp=0, action="register",
                    uid=f"p{phase}r{seq}", query=q))
                seq += 1
        yield from cmds
        yield from _point_stream(rng, n=30, tmax_ms=4000)

    telemetry.enable()
    try:
        # occupancies 4 → 12 → 30 → 4 (rungs 8, 16, 32, 8 — the revisit
        # is the stability probe)
        for phase, target in enumerate((4, 12, 30, 4)):
            for _ in op.run(stream(phase, target)):
                pass
        sigs = telemetry.distinct_shapes("registry_bucket_kernel")
        assert 1 <= sigs <= len(capacity_ladder(cap_max)), sigs
        buckets = telemetry.compaction_buckets("qserve_bucket")
        assert set(buckets) <= set(capacity_ladder(cap_max))
        snap = telemetry.snapshot()
        assert snap["qserve"]["recompiles"] == sigs
    finally:
        telemetry.disable()
        qserve.uninstall()


# ---------------------------------------------------------------------------
# per-tenant QoS


def _run_two_class_pipeline(rng, policy):
    ctrl = overload.install(overload.OverloadController(policy))
    op = qserve.QServeOperator(CONF, GRID)
    queries = (
        # firehose: fat-radius queries, lots of results
        [_mk_query(i, rng, kind="range", k=8, radius=3.0,
                   tenant_class="firehose") for i in range(4)]
        # modest: one tight query
        + [_mk_query(9, rng, kind="knn", k=3, radius=1.0,
                     tenant_class="modest")]
    )

    def stream():
        yield from _register_cmds(queries)
        yield from _point_stream(rng, n=150)

    rows = []
    for res in op.run(stream()):
        rows.extend(res.rows)
    return ctrl, rows


def test_firehose_tenant_degrades_itself_not_the_fleet(rng):
    policy = overload.OverloadPolicy(
        tenant_budgets={
            "firehose": {"max_queries": 3, "max_results_per_window": 5},
        },
        # a global ladder exists — tenant sheds must NOT step it
        ladder=({"action": "clamp_compaction", "cap": 0},),
        degrade_cooldown=1,
    )
    ctrl, rows = _run_two_class_pipeline(rng, policy)
    snap = ctrl.snapshot()
    t = snap["tenants"]
    # the 4th firehose registration was rejected (admission budget)
    assert t["firehose"]["queries_live"] == 3
    assert t["firehose"]["queries_shed"] >= 1
    # result rows truncated per window for the firehose class only
    assert t["firehose"]["results_shed"] > 0
    assert t["firehose"]["degraded_windows"] > 0
    assert t["modest"]["results_shed"] == 0
    assert t["modest"]["queries_shed"] == 0
    # per-window firehose rows respect the budget
    per_window = {}
    for cls, _tenant, _qid, _obj, _d in rows:
        per_window[cls] = per_window.get(cls, 0) + 1
    assert any(cls == "modest" for cls, *_ in rows)
    # THE scoping pin: the global degradation rung never moved
    assert snap["rung"] == 0 and snap["rung_transitions"] == 0
    assert ctrl.tenant_shed_total("firehose") > 0
    assert ctrl.tenant_shed_total("modest") == 0


def test_tenant_result_budget_bounds_every_window(rng):
    policy = overload.OverloadPolicy(
        tenant_budgets={"firehose": {"max_results_per_window": 5}},
    )
    ctrl = overload.install(overload.OverloadController(policy))
    op = qserve.QServeOperator(CONF, GRID)
    queries = [_mk_query(i, rng, kind="range", k=8, radius=3.0,
                         tenant_class="firehose") for i in range(4)]

    def stream():
        yield from _register_cmds(queries)
        yield from _point_stream(rng, n=150)

    for res in op.run(stream()):
        n_fire = sum(1 for cls, *_ in res.rows if cls == "firehose")
        assert n_fire <= 5
    assert ctrl.tenant_shed_total("firehose") > 0


def test_tenant_budgets_strict_parse():
    with pytest.raises(ValueError, match="unknown keys"):
        overload.OverloadPolicy(tenant_budgets={"a": {"max_queriez": 1}})
    with pytest.raises(ValueError, match="non-negative int"):
        overload.OverloadPolicy(tenant_budgets={"a": {"max_queries": -1}})
    # round trip through the strict dict parse
    p = overload.OverloadPolicy(tenant_budgets={"a": {"max_queries": 2}})
    p2 = overload.OverloadPolicy.from_dict(p.to_dict())
    assert p2.tenant_budgets == {"a": {"max_queries": 2}}


def test_tenant_state_checkpoint_round_trip(rng):
    policy = overload.OverloadPolicy(
        tenant_budgets={"firehose": {"max_queries": 1}},
    )
    ctrl = overload.OverloadController(policy)
    assert ctrl.admit_tenant_query("firehose") is True
    assert ctrl.admit_tenant_query("firehose") is False  # shed
    state = ctrl.state()
    ctrl2 = overload.OverloadController(policy)
    ctrl2.restore(state)
    assert ctrl2.tenant_shed_total("firehose") == 1
    assert ctrl2.snapshot()["tenants"]["firehose"]["queries_live"] == 1


def test_tenant_slo_budgets_live_engine(rng):
    """SloSpec.tenant_budgets: per-class checks against the controller's
    counters; violations are per class; no controller = silence fails."""
    policy = overload.OverloadPolicy(
        tenant_budgets={"firehose": {"max_results_per_window": 2}},
    )
    ctrl = overload.install(overload.OverloadController(policy))
    spec = slo.SloSpec(
        name="t", eval_interval_s=0.0,
        tenant_budgets={
            "firehose": {"shed_budget": 0, "degraded_window_budget": 0},
            "modest": {"shed_budget": 10},
        },
    )
    engine = slo.install(slo.SloEngine(spec))
    try:
        ctrl.tenant_result_allowance("firehose", 7)  # sheds 5
        rows = engine.evaluate()
        by = {r["check"]: r for r in rows}
        assert by["tenant_shed_budget:firehose"]["ok"] is False
        assert by["tenant_degraded_window_budget:firehose"]["ok"] is False
        assert by["tenant_shed_budget:modest"]["ok"] is True
        assert any(v["check"] == "tenant_shed_budget:firehose"
                   for v in engine.violations)
    finally:
        slo.uninstall()
    # silence fails: same spec, no controller installed
    overload.uninstall()
    engine2 = slo.SloEngine(spec)
    rows = engine2.evaluate()
    by = {r["check"]: r for r in rows}
    assert by["tenant_shed_budget:firehose"]["ok"] is False
    assert by["tenant_shed_budget:modest"]["ok"] is False


def test_range_result_overflow_counts_at_query_cap(rng):
    """A range query's results truncate at ITS k (≤ the rung) — the
    overflow counter must see truncation at k, not only at the rung
    (code-review repro: k=2 on rung 8 with >2 in-radius objects used to
    report 0 overflow while dropping results)."""
    op = qserve.QServeOperator(CONF, GRID)
    q = qserve.StandingQuery(qid="r", tenant="t", kind="range",
                             x=5.0, y=5.0, radius=4.0, k=2)

    def stream():
        yield qserve.QServeCommand(timestamp=0, action="register",
                                   uid="u", query=q)
        yield from _point_stream(rng, n=80, tmax_ms=4000)

    rows_per_window = []
    for res in op.run(stream()):
        rows_per_window.append(len(res.rows))
    assert max(rows_per_window) == 2  # truncated at the query's cap
    assert op.qserve_registry.range_result_overflow > 0


def test_record_range_overflow_is_retry_idempotent():
    """Re-charging the SAME window (a driver retry re-running process)
    replaces the previous charge — the counter never double-counts."""
    from spatialflink_tpu.utils.interning import Interner

    reg = qserve.QueryRegistry(GRID, Interner())
    reg.record_range_overflow(100, 5)
    reg.record_range_overflow(100, 5)  # retry of window 100
    assert reg.range_result_overflow == 5
    reg.record_range_overflow(200, 3)
    assert reg.range_result_overflow == 8
    # the marker survives a checkpoint round trip
    reg2 = qserve.QueryRegistry(GRID, Interner())
    reg2.restore(json.loads(json.dumps(reg.state())))
    reg2.record_range_overflow(200, 3)
    assert reg2.range_result_overflow == 8


def test_commands_are_never_shed_by_admission(rng):
    """Registration commands are CONTROL PLANE: the overload admission
    gate measures them as zero load and must never shed one — a shed
    command would silently diverge the registry from the command stream
    for the rest of the run (code-review repro)."""
    policy = overload.OverloadPolicy(max_buffered_events=1,
                                     lag_shed_ceiling_ms=1,
                                     lag_recover_ms=0)
    ctrl = overload.OverloadController(policy)
    cmd = qserve.QServeCommand(timestamp=0, action="register", uid="u",
                               query=_mk_query(0, rng))
    # force shed mode, then feed a late-tier command: still admitted
    ctrl.on_window_fired(n_events=1, lag_ms=10_000, end=1000)
    assert ctrl._shedding is True
    ctrl._max_ts = 5000
    assert ctrl.admit_item(cmd, pausable=False) is True
    assert ctrl.shed_total == 0


def test_allowed_lateness_is_rejected():
    conf = QueryConfiguration(QueryType.WindowBased, window_size=2.0,
                              slide_step=1.0, allowed_lateness=1.0)
    op = qserve.QServeOperator(conf, GRID)
    with pytest.raises(ValueError, match="allowed_lateness"):
        next(iter(op.run(iter([]))))


def test_tenant_result_charge_is_retry_idempotent():
    """Re-charging the same (class, window) — a driver retry re-running
    process() — replaces the previous charge (the record_range_overflow
    contract applied to the tenant counters)."""
    policy = overload.OverloadPolicy(
        tenant_budgets={"a": {"max_results_per_window": 2}},
    )
    ctrl = overload.OverloadController(policy)
    assert ctrl.tenant_result_allowance("a", 7, window_start=100) == 2
    assert ctrl.tenant_result_allowance("a", 7, window_start=100) == 2
    rec = ctrl.snapshot()["tenants"]["a"]
    assert rec["results_shed"] == 5 and rec["degraded_windows"] == 1
    assert ctrl.tenant_result_allowance("a", 4, window_start=200) == 2
    rec = ctrl.snapshot()["tenants"]["a"]
    assert rec["results_shed"] == 7 and rec["degraded_windows"] == 2
    # the marker survives a checkpoint round trip
    ctrl2 = overload.OverloadController(policy)
    ctrl2.restore(ctrl.state())
    assert ctrl2.tenant_result_allowance("a", 4, window_start=200) == 2
    assert ctrl2.snapshot()["tenants"]["a"]["results_shed"] == 7


def test_applied_uid_set_prunes_behind_the_watermark(rng):
    """The exactly-once uid set keeps only uids a refire/resume can
    still re-present; older ones prune so checkpoints don't grow with
    lifetime command count."""
    from spatialflink_tpu.utils.interning import Interner

    reg = qserve.QueryRegistry(GRID, Interner())
    for i in range(6):
        reg.apply(qserve.QServeCommand(
            timestamp=i * 1000, action="register", uid=f"u{i}",
            query=_mk_query(i, rng)))
    assert len(reg._applied) == 6
    reg.prune_applied(watermark_ts=10_000, horizon_ms=3_000)
    # cut = 7000: uids with ts < 7000 are gone, later ones kept
    assert set(reg._applied) == set()
    reg.apply(qserve.QServeCommand(
        timestamp=12_000, action="register", uid="u9",
        query=_mk_query(9, rng)))
    reg.prune_applied(watermark_ts=12_500, horizon_ms=3_000)
    assert set(reg._applied) == {"u9"}
    # within the horizon a duplicate is still a no-op
    assert reg.apply(qserve.QServeCommand(
        timestamp=12_000, action="register", uid="u9",
        query=_mk_query(9, rng))) is False


def test_dead_bucket_device_arrays_are_evicted(rng):
    """Churn that empties a bucket must drop its cached device arrays —
    dead buckets must not pin device memory for the rest of the run."""
    op = qserve.QServeOperator(CONF, GRID)
    q = _mk_query(0, rng, kind="knn", k=5, radius=1.5)

    def stream():
        yield qserve.QServeCommand(timestamp=0, action="register",
                                   uid="r0", query=q)
        yield from _point_stream(rng, n=40, tmax_ms=4000)
        yield qserve.QServeCommand(timestamp=5000, action="unregister",
                                   uid="u0", qid=q.qid)
        yield from (Point(obj_id=f"o{i}", timestamp=5000 + i * 100,
                          x=5.0, y=5.0) for i in range(40))

    for _ in op.run(stream()):
        pass
    assert op._bucket_dev == {}  # the emptied bucket was evicted


def test_tenant_slo_spec_strict_parse():
    with pytest.raises(ValueError, match="unknown keys"):
        slo.SloSpec(tenant_budgets={"a": {"shed_budgett": 1}})
    with pytest.raises(ValueError, match="non-negative int"):
        slo.SloSpec(tenant_budgets={"a": {"shed_budget": "lots"}})
    with pytest.raises(ValueError, match="non-negative int"):
        slo.SloSpec(tenant_budgets={"a": {"shed_budget": -1}})
    # twin field parity rides test_slo.py's cross-pin; spot-check here
    from tools.sfprof import slo as slo_tool

    assert "tenant_budgets" in slo_tool.SPEC_KEYS


def test_tenant_slo_posthoc_twin(tmp_path):
    """tools/sfprof/slo.py mirrors the live per-class checks against a
    ledger's snapshot.overload.tenants block — including the
    silence-fails rule for a ledger with no overload block."""
    from tools.sfprof import slo as slo_tool

    spec = {
        "tenant_budgets": {
            "firehose": {"shed_budget": 3,
                         "degraded_window_budget": 0},
            "unseen": {"shed_budget": 0},
        },
    }
    doc = {
        "snapshot": {"overload": {
            "shed_total": 0,
            "tenants": {
                "firehose": {"queries_live": 2, "queries_shed": 2,
                             "results_shed": 4, "degraded_windows": 1},
            },
        }},
    }
    rows = {r[0]: r for r in slo_tool.evaluate(spec, doc)}
    name = "slo:tenant_shed_budget:firehose"
    assert rows[name][1] == 6 and rows[name][3] is False
    assert rows["slo:tenant_degraded_window_budget:firehose"][3] is False
    # unseen class in a PRESENT overload block reads as 0 — ok
    assert rows["slo:tenant_shed_budget:unseen"][3] is True
    # no overload block at all: silence fails
    rows2 = {r[0]: r for r in slo_tool.evaluate(spec, {"snapshot": {}})}
    assert rows2[name][3] is False
    # spec with tenant_budgets loads through the strict parser
    p = tmp_path / "spec.json"
    p.write_text(json.dumps(spec))
    assert slo_tool.load_spec(str(p))["tenant_budgets"] == \
        spec["tenant_budgets"]


# ---------------------------------------------------------------------------
# observability surfaces


def test_snapshot_qserve_block_and_events(rng):
    telemetry.enable()
    op = qserve.QServeOperator(CONF, GRID)
    try:
        def stream():
            yield from _register_cmds([_mk_query(i, rng)
                                       for i in range(4)])
            yield from _point_stream(rng, n=60, tmax_ms=6000)

        for _ in op.run(stream()):
            pass
        snap = telemetry.snapshot()
        qs = snap["qserve"]
        assert qs["registered"] == 4 and qs["registered_total"] == 4
        assert qs["buckets"] and all(
            b["capacity"] >= b["live"] for b in qs["buckets"].values()
        )
        assert qs["recompiles"] >= 1
        names = {e["name"] for e in telemetry.events}
        assert "qserve_registered" in names
        assert any(n.startswith("qserve_rung:") for n in names)
    finally:
        telemetry.disable()
        qserve.uninstall()


def test_sfprof_health_and_report_print_tenant_qos(tmp_path, capsys):
    """health/report: per-tenant-class QoS lines next to the overload
    notes, with --json coverage (notes.tenants / notes.qserve)."""
    import time

    from tools.sfprof import cli as sfprof_cli

    doc = {
        "ledger_version": 1,
        "created_unix": time.time(),
        "env": {"python": "3", "jax": "0", "backend": "cpu",
                "device_count": 1, "devices": ["cpu:0"], "x64": True,
                "pid": 1, "argv0": "t"},
        "snapshot": {
            "compiles": 1, "bytes_h2d": 0, "bytes_d2h": 0,
            "window_latency_p50_ms": None, "window_latency_p95_ms": None,
            "max_watermark_lag_ms": 0, "watermark_lag_p99_ms": None,
            "late_dropped": 0, "h2d_transfers": 0, "d2h_transfers": 0,
            "events": 0, "dropped_events": 0, "kernels": {},
            "compaction": {}, "driver": {"retries": 0, "failovers": 0},
            "overload": {
                "version": 1, "shed": {}, "shed_total": 0,
                "degraded_windows": 0, "backpressure_engaged": 0,
                "shedding": False, "rung": 0, "ladder_depth": 0,
                "rung_transitions": 0,
                "tenants": {"firehose": {
                    "queries_live": 3, "queries_shed": 1,
                    "results_shed": 12, "degraded_windows": 2,
                }},
            },
            "qserve": {
                "version": 1, "registered": 4, "registered_total": 5,
                "unregistered_total": 1, "evicted_total": 1,
                "range_result_overflow": 0,
                "buckets": {"knn_k8_rc11": {"live": 4, "capacity": 8}},
                "recompiles": 2,
            },
        },
        "kernels": [],
        "events": [],
        "bench": {"points_per_sec": 1.0},
    }
    path = tmp_path / "ledger.json"
    path.write_text(json.dumps(doc))
    rc = sfprof_cli.main(["health", str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "tenant QoS [firehose]" in out
    assert "results_shed=12" in out
    assert "note qserve: registered=4" in out
    rc = sfprof_cli.main(["health", str(path), "--json"])
    notes = json.loads(capsys.readouterr().out)["notes"]
    assert notes["tenants"]["firehose"]["results_shed"] == 12
    assert notes["qserve"]["registered"] == 4
    rc = sfprof_cli.main(["report", str(path)])
    out = capsys.readouterr().out
    assert "per-tenant-class QoS" in out
    assert "qserve registry: 4 standing queries" in out


# ---------------------------------------------------------------------------
# streaming_job + SFT_QSERVE config


def test_config_from_env_strict(monkeypatch, tmp_path):
    monkeypatch.delenv("SFT_QSERVE", raising=False)
    assert qserve.config_from_env() is None
    monkeypatch.setenv("SFT_QSERVE", json.dumps({
        "queries": [{"qid": "a", "tenant": "t", "kind": "knn",
                     "x": 1.0, "y": 2.0, "radius": 0.5, "k": 3}],
        "tenant_budgets": {"default": {"max_queries": 10}},
    }))
    cfg = qserve.config_from_env()
    qs = qserve.queries_from_config(cfg)
    assert qs[0].qid == "a" and qs[0].k == 3
    monkeypatch.setenv("SFT_QSERVE", json.dumps({"nope": 1}))
    with pytest.raises(ValueError, match="unknown SFT_QSERVE keys"):
        qserve.config_from_env()
    # file-path form (the SFT_FAULT_PLAN convention)
    p = tmp_path / "q.json"
    p.write_text(json.dumps({"cap_max": 64}))
    monkeypatch.setenv("SFT_QSERVE", str(p))
    assert qserve.config_from_env() == {"cap_max": 64}


def test_streaming_job_option9_serves_and_checkpoints(tmp_path,
                                                      monkeypatch):
    """Option 9 end to end with --checkpoint: the run completes with
    per-tenant egress, and re-running against the completed checkpoint
    is an exactly-once no-op (byte-identical output). Kill-mid-churn
    equality is the chaos matrix's qserve.register leg — `--max-records`
    ends the SOURCE (flushing open windows), which is deliberately not
    the same thing as a crash."""
    from spatialflink_tpu import streaming_job

    rng = np.random.default_rng(5)
    csv = tmp_path / "pts.csv"
    lines = []
    for i in range(90):
        lines.append(f"o{i % 7},{i * 100},"
                     f"{rng.uniform(0.5, 9.5):.4f},"
                     f"{rng.uniform(0.5, 9.5):.4f}")
    csv.write_text("\n".join(lines) + "\n")
    yml = tmp_path / "conf.yml"
    yml.write_text(
        """
inputStream1:
  topicName: t
  format: CSV
  csvTsvSchemaAttr: [0, 1, 2, 3]
  gridBBox: [0.0, 0.0, 10.0, 10.0]
  numGridCells: 10
  delimiter: ","
query:
  option: 9
  radius: 1.5
  k: 4
  queryPoints:
    - [4.0, 4.0]
window:
  type: "TIME"
  interval: 2
  step: 1
"""
    )
    monkeypatch.delenv("SFT_QSERVE", raising=False)

    out = tmp_path / "served.csv"
    ck = tmp_path / "ck.bin"
    rc = streaming_job.main([
        "--config", str(yml), "--source", f"csv:{csv}",
        "--output", str(out), "--checkpoint", str(ck),
        "--checkpoint-every", "2",
    ])
    assert rc == 0
    want = out.read_bytes()
    assert want
    # the default query set serves both kinds under the default tenant
    first = want.decode().splitlines()[0].split(",")
    assert first[0] == "default" and first[1] in ("range0", "knn0")
    # resume against the COMPLETED checkpoint: exactly-once no-op
    qserve.uninstall()
    rc = streaming_job.main([
        "--config", str(yml), "--source", f"csv:{csv}",
        "--output", str(out), "--checkpoint", str(ck),
        "--checkpoint-every", "2",
    ])
    assert rc == 0
    assert out.read_bytes() == want


# ---------------------------------------------------------------------------
# acceptance: 1024 standing queries (slow tier)


@pytest.mark.slow
def test_1024_standing_queries_ladder_bounded_and_exact(rng):
    """The ISSUE 13 acceptance leg: 1024 mixed standing queries evaluate
    through ≤ ladder-many compiled signatures per (rung, nseg) pair,
    with a sampled per-query parity check against sequential evaluation
    of the same program."""
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.ops.compaction import capacity_ladder
    from spatialflink_tpu.ops.query_registry import (
        registry_bucket_kernel,
        registry_bucket_query,
    )
    from spatialflink_tpu.ops.compaction import pick_capacity

    nq, n = 1024, 4096
    xy, oid, cell, valid = _bucket_inputs(rng, n=n, n_obj=512)
    queries = [
        qserve.StandingQuery(
            qid=f"q{i}", tenant=f"t{i % 31}",
            kind="range" if i % 2 else "knn",
            x=float(rng.uniform(1, 9)), y=float(rng.uniform(1, 9)),
            radius=float((0.8, 1.6, 2.4)[i % 3]),
            k=(32, 5, 10, 30)[i % 4],
        )
        for i in range(nq)
    ]
    buckets = {}
    for q in queries:
        buckets.setdefault(qserve.bucket_key(q), []).append(q)
    telemetry.enable()
    try:
        jkern = jax.jit(
            registry_bucket_kernel,
            static_argnames=("k", "num_segments", "query_block"),
        )
        from spatialflink_tpu.telemetry import instrument_jit

        ikern = instrument_jit(jkern, name="registry_bucket_kernel")
        results = {}
        args = (jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(cell))
        oid_d = jnp.asarray(oid)
        for key in sorted(buckets):
            qs = buckets[key]
            cap = pick_capacity(len(qs), 1024, minimum=8)
            qxy, radius, qvalid, tables = qserve.bucket_host_arrays(
                GRID, qs, cap
            )
            results[key] = (qs, qxy, radius, qvalid, tables, ikern(
                *args, jnp.asarray(tables), oid_d, jnp.asarray(qxy),
                jnp.asarray(radius), jnp.asarray(qvalid),
                k=int(key[1]), num_segments=512,
                query_block=min(cap, 32),
            ))
        # ≤ ladder-many signatures per rung (nseg/N fixed here, so the
        # global bound is rungs-many ≤ ladder size × distinct k-rungs)
        sigs = telemetry.distinct_shapes("registry_bucket_kernel")
        k_rungs = {key[1] for key in buckets}
        assert sigs <= len(capacity_ladder(1024)) * len(k_rungs), sigs
        # sampled parity vs sequential evaluation (bit-identical)
        single = jax.jit(
            registry_bucket_query, static_argnames=("k", "num_segments")
        )
        for key in sorted(buckets)[:3]:
            qs, qxy, radius, qvalid, tables, res = results[key]
            for lane in (0, len(qs) // 2, len(qs) - 1):
                d, seg, idx, nv, within = single(
                    *args, jnp.asarray(tables[lane]), oid_d,
                    jnp.asarray(qxy[lane]), jnp.asarray(radius[lane]),
                    jnp.asarray(qvalid[lane]),
                    k=int(key[1]), num_segments=512,
                )
                np.testing.assert_array_equal(
                    np.asarray(res.dist[lane]), np.asarray(d))
                np.testing.assert_array_equal(
                    np.asarray(res.segment[lane]), np.asarray(seg))
                assert int(res.within[lane]) == int(within)
    finally:
        telemetry.disable()
