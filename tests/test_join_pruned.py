"""Grid-pruned geometry joins (ops/join.py pruned kernels +
operators/join_query.py): pair sets must be identical to the dense masked
evaluation — sparse, dense/overflow-retry, containment→0, SoA paths."""

import jax.numpy as jnp
import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import LineString, Point, Polygon
from spatialflink_tpu.operators import QueryConfiguration, QueryType
from spatialflink_tpu.operators.join_query import (
    LineStringLineStringJoinQuery,
    PointPolygonJoinQuery,
    PolygonPolygonJoinQuery,
)

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
W = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)


@pytest.fixture
def rng():
    return np.random.default_rng(33)


def _points(rng, n, t_span=9_000):
    xy = rng.uniform(0, 10, (n, 2))
    return [
        Point(obj_id=f"p{i}", timestamp=int(i * t_span / n),
              x=float(xy[i, 0]), y=float(xy[i, 1]))
        for i in range(n)
    ]


def _square(cx, cy, r):
    return np.array([
        [cx - r, cy - r], [cx + r, cy - r], [cx + r, cy + r],
        [cx - r, cy + r], [cx - r, cy - r],
    ])


def _polygons(rng, m, t_span=9_000, size=0.25):
    out = []
    for i in range(m):
        cx, cy = rng.uniform(0.5, 9.5, 2)
        out.append(Polygon(
            obj_id=f"g{i}", timestamp=int(i * t_span / m),
            rings=[_square(float(cx), float(cy), size)],
        ))
    return out


def _linestrings(rng, m, t_span=9_000):
    out = []
    for i in range(m):
        x0, y0 = rng.uniform(0.5, 9.0, 2)
        pts = np.stack([
            np.linspace(x0, x0 + 0.8, 5),
            y0 + 0.2 * np.sin(np.linspace(0, 3, 5)),
        ], axis=1)
        out.append(LineString(obj_id=f"l{i}", timestamp=int(i * t_span / m),
                              coords=pts))
    return out


def _dense_pairs_point_geom(op, pts, geoms, radius, polygonal):
    """Reference pair set straight from the dense kernel."""
    from spatialflink_tpu.operators.base import jitted
    from spatialflink_tpu.ops.join import point_geometry_join_kernel

    lb = op.point_batch(pts)
    gb = op.geometry_batch(geoms)
    kernel = jitted(point_geometry_join_kernel, "polygonal")
    mask, d = kernel(
        op.device_xy(lb, np.float64), jnp.asarray(lb.valid),
        op.device_verts(gb.verts, np.float64), jnp.asarray(gb.edge_valid),
        jnp.asarray(gb.valid), radius, polygonal=polygonal,
    )
    mask, d = np.asarray(mask), np.asarray(d)
    return {
        (pts[i].obj_id, geoms[m].obj_id, round(float(d[m, i]), 12))
        for m in range(len(geoms)) for i in range(len(pts)) if mask[m, i]
    }


def _op_pairs(results):
    return {
        (a.obj_id, b.obj_id, round(float(d), 12))
        for res in results for a, b, d in res.pairs
    }


def test_point_polygon_pruned_matches_dense(rng):
    pts = _points(rng, 3_000)
    polys = _polygons(rng, 120)
    r = 0.15
    op = PointPolygonJoinQuery(W, GRID)
    got = _op_pairs(op.run(iter(pts), iter(polys), r))
    expect = _dense_pairs_point_geom(
        PointPolygonJoinQuery(W, GRID), pts, polys, r, True
    )
    assert got == expect
    assert len(got) > 50  # non-trivial workload


def test_point_polygon_containment_zero_dist(rng):
    pts = [Point(obj_id="in", timestamp=0, x=5.0, y=5.0),
           Point(obj_id="out", timestamp=1, x=9.9, y=9.9)]
    polys = [Polygon(obj_id="g", timestamp=0, rings=[_square(5.0, 5.0, 1.0)])]
    op = PointPolygonJoinQuery(W, GRID)
    got = _op_pairs(op.run(iter(pts), iter(polys), 0.05))
    assert got == {("in", "g", 0.0)}


def test_point_polygon_overflow_retry_exact(rng):
    """cand=1 start with clustered polygons forces overflow growth; the
    retry contract must converge to the exact dense pair set."""
    pts = _points(rng, 800)
    # 40 polygons stacked in one corner: every point tile near the corner
    # has >> 1 candidate.
    polys = []
    for i in range(40):
        cx, cy = 2.0 + 0.02 * i, 2.0 + 0.015 * i
        polys.append(Polygon(obj_id=f"g{i}", timestamp=i * 200,
                             rings=[_square(cx, cy, 0.4)]))
    r = 0.2
    op = PointPolygonJoinQuery(W, GRID)
    op._cand = 1
    got = _op_pairs(op.run(iter(pts), iter(polys), r))
    expect = _dense_pairs_point_geom(
        PointPolygonJoinQuery(W, GRID), pts, polys, r, True
    )
    assert got == expect
    assert op._cand > 1  # growth actually happened


def test_point_polygon_pair_cap_retry_exact(rng):
    """A point inside many stacked polygons exceeds pair_cap=1; the
    per-item selection must retry with a grown cap and still produce the
    exact dense pair set."""
    pts = _points(rng, 400)
    polys = [Polygon(obj_id=f"g{i}", timestamp=i * 400,
                     rings=[_square(5.0, 5.0, 2.0 + 0.05 * i)])
             for i in range(12)]  # concentric: central points match all 12
    r = 0.1
    op = PointPolygonJoinQuery(W, GRID)
    op._pair_cap = 1
    got = _op_pairs(op.run(iter(pts), iter(polys), r))
    expect = _dense_pairs_point_geom(
        PointPolygonJoinQuery(W, GRID), pts, polys, r, True
    )
    assert got == expect
    assert op._pair_cap > 1  # growth actually happened
    # Central points really do match many polygons.
    from collections import Counter

    per_point = Counter(a for a, _, _ in got)
    assert max(per_point.values()) == 12


def test_pruned_kernel_onehot_branch_matches_topk(rng, monkeypatch):
    """The per-backend selection gate picks top_k on CPU; force the
    one-hot branch (the TPU strategy) and assert the pair set is
    identical — both selection strategies implement one contract."""
    import spatialflink_tpu.ops.join as oj

    pts = _points(rng, 2_000)
    polys = _polygons(rng, 80)
    r = 0.15
    op = PointPolygonJoinQuery(W, GRID)
    lb = op.point_batch(pts)
    gb = op.geometry_batch(polys)
    ho = np.argsort(lb.cell, kind="stable")
    from spatialflink_tpu.operators.base import center_coords
    from spatialflink_tpu.operators.join_query import _centered_bbox

    args = (
        jnp.asarray(center_coords(GRID, lb.xy[ho], np.float64)),
        jnp.asarray(lb.valid[ho]),
        jnp.asarray(op.device_verts(gb.verts, np.float64)),
        jnp.asarray(gb.edge_valid),
        jnp.asarray(gb.valid),
        jnp.asarray(_centered_bbox(GRID, gb.bbox, np.float64)),
        np.float64(r),
    )

    def run(force_onehot):
        monkeypatch.setattr(oj, "_onehot_select_preferred",
                            lambda: force_onehot)
        import jax

        res = jax.jit(
            oj.point_geometry_join_pruned_kernel,
            static_argnames=("polygonal", "block", "cand", "max_pairs",
                            "pair_cap"),
        )(*args, polygonal=True, block=256, cand=64, max_pairs=16_384,
          pair_cap=8)
        assert int(res.cand_overflow) == 0 and int(res.pair_overflow) == 0
        n = int(res.count)
        return {
            (int(a), int(b), round(float(d), 12))
            for a, b, d in zip(np.asarray(res.left_index)[:n],
                               np.asarray(res.right_index)[:n],
                               np.asarray(res.dist)[:n])
            if a >= 0
        }

    assert run(True) == run(False)
    assert run(False)


def test_point_linestring_pruned_matches_dense(rng):
    from spatialflink_tpu.operators.join_query import PointLineStringJoinQuery

    pts = _points(rng, 2_000)
    lines = _linestrings(rng, 80)
    r = 0.1
    got = _op_pairs(
        PointLineStringJoinQuery(W, GRID).run(iter(pts), iter(lines), r)
    )
    expect = _dense_pairs_point_geom(
        PointLineStringJoinQuery(W, GRID), pts, lines, r, False
    )
    assert got == expect
    assert got


def test_polygon_polygon_pruned_matches_dense(rng):
    from spatialflink_tpu.operators.base import jitted
    from spatialflink_tpu.ops.join import geometry_geometry_join_kernel

    left = _polygons(rng, 90, size=0.3)
    right = _polygons(np.random.default_rng(7), 70, size=0.35)
    r = 0.2
    op = PolygonPolygonJoinQuery(W, GRID)
    got = _op_pairs(op.run(iter(left), iter(right), r))

    la = op.geometry_batch(left)
    ra = op.geometry_batch(right)
    kernel = jitted(geometry_geometry_join_kernel, "a_polygonal", "b_polygonal")
    mask, d = kernel(
        op.device_verts(la.verts, np.float64), jnp.asarray(la.edge_valid),
        jnp.asarray(la.valid),
        op.device_verts(ra.verts, np.float64), jnp.asarray(ra.edge_valid),
        jnp.asarray(ra.valid), r, a_polygonal=True, b_polygonal=True,
    )
    mask, d = np.asarray(mask), np.asarray(d)
    expect = {
        (left[i].obj_id, right[j].obj_id, round(float(d[i, j]), 12))
        for i in range(len(left)) for j in range(len(right)) if mask[i, j]
    }
    assert got == expect
    # Overlapping polygons exist at these densities → some 0-distance pairs.
    assert any(p[2] == 0.0 for p in got)


def test_linestring_linestring_pruned_matches_dense(rng):
    from spatialflink_tpu.operators.base import jitted
    from spatialflink_tpu.ops.join import geometry_geometry_join_kernel

    left = _linestrings(rng, 60)
    right = _linestrings(np.random.default_rng(8), 50)
    r = 0.15
    op = LineStringLineStringJoinQuery(W, GRID)
    got = _op_pairs(op.run(iter(left), iter(right), r))
    la = op.geometry_batch(left)
    ra = op.geometry_batch(right)
    kernel = jitted(geometry_geometry_join_kernel, "a_polygonal", "b_polygonal")
    mask, d = kernel(
        op.device_verts(la.verts, np.float64), jnp.asarray(la.edge_valid),
        jnp.asarray(la.valid),
        op.device_verts(ra.verts, np.float64), jnp.asarray(ra.edge_valid),
        jnp.asarray(ra.valid), r, a_polygonal=False, b_polygonal=False,
    )
    mask, d = np.asarray(mask), np.asarray(d)
    expect = {
        (left[i].obj_id, right[j].obj_id, round(float(d[i, j]), 12))
        for i in range(len(left)) for j in range(len(right)) if mask[i, j]
    }
    assert got == expect


def test_point_polygon_mesh_matches_single(rng):
    """mesh= shards the locality-sorted point side contiguously; pair set
    must equal single-device (the pruned kernel runs per shard)."""
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices())
    assert devs.size == 8
    mesh = Mesh(devs.reshape(8), ("data",))
    pts = _points(rng, 4_000)
    polys = _polygons(rng, 100)
    r = 0.15

    def run(m):
        return _op_pairs(
            PointPolygonJoinQuery(W, GRID).run(iter(pts), iter(polys), r,
                                               mesh=m)
        )

    single = run(None)
    sharded = run(mesh)
    assert single == sharded
    assert single


def test_polygon_polygon_mesh_matches_single(rng):
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices())
    mesh = Mesh(devs.reshape(8), ("data",))
    left = _polygons(rng, 120, size=0.3)
    right = _polygons(np.random.default_rng(13), 80, size=0.3)
    r = 0.2

    def run(m):
        return _op_pairs(
            PolygonPolygonJoinQuery(W, GRID).run(iter(left), iter(right), r,
                                                 mesh=m)
        )

    assert run(None) == run(mesh)


def _point_chunks(pts, chunk=500):
    for lo in range(0, len(pts), chunk):
        sl = pts[lo:lo + chunk]
        yield {
            "ts": np.asarray([p.timestamp for p in sl], np.int64),
            "x": np.asarray([p.x for p in sl]),
            "y": np.asarray([p.y for p in sl]),
            "oid": np.arange(lo, lo + len(sl), dtype=np.int32),
        }


def _geom_chunks(geoms, chunk=40):
    for lo in range(0, len(geoms), chunk):
        sl = geoms[lo:lo + chunk]
        verts = [np.asarray(g.rings[0] if isinstance(g, Polygon)
                            else g.coords, np.float64) for g in sl]
        yield {
            "ts": np.asarray([g.timestamp for g in sl], np.int64),
            "oid": np.arange(lo, lo + len(sl), dtype=np.int32),
            "lengths": np.asarray([len(v) for v in verts], np.int64),
            "verts": np.concatenate(verts, axis=0),
        }


def test_point_polygon_run_soa_matches_run(rng):
    pts = _points(rng, 2_000)
    polys = _polygons(rng, 60)
    r = 0.15
    obj = _op_pairs(
        PointPolygonJoinQuery(W, GRID).run(iter(pts), iter(polys), r)
    )
    soa_pairs = set()
    for start, end, li, ri, dd, count in PointPolygonJoinQuery(
        W, GRID
    ).run_soa(_point_chunks(pts), _geom_chunks(polys), r):
        for a, b, d in zip(li, ri, dd):
            soa_pairs.add((pts[int(a)].obj_id, polys[int(b)].obj_id,
                           round(float(d), 12)))
    assert soa_pairs == obj


def test_polygon_polygon_run_soa_matches_run(rng):
    left = _polygons(rng, 60, size=0.3)
    right = _polygons(np.random.default_rng(9), 50, size=0.3)
    r = 0.2
    obj = _op_pairs(
        PolygonPolygonJoinQuery(W, GRID).run(iter(left), iter(right), r)
    )
    soa_pairs = set()
    for start, end, li, ri, dd, count in PolygonPolygonJoinQuery(
        W, GRID
    ).run_soa(_geom_chunks(left), _geom_chunks(right), r):
        for a, b, d in zip(li, ri, dd):
            soa_pairs.add((left[int(a)].obj_id, right[int(b)].obj_id,
                           round(float(d), 12)))
    assert soa_pairs == obj
