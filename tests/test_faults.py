"""Deterministic fault injection (spatialflink_tpu/faults.py): plan
parsing, trigger determinism, kinds, the disarmed-free contract, and
telemetry visibility of armed/fired faults."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from spatialflink_tpu.faults import (  # noqa: E402
    ABORT_EXIT_CODE,
    FaultInjector,
    FaultRule,
    InjectedFault,
    INJECTION_POINTS,
    faults,
    parse_plan,
)
from spatialflink_tpu.telemetry import telemetry  # noqa: E402


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.disarm()
    telemetry.disable()


class TestPlanParsing:
    def test_unknown_point_raises(self):
        with pytest.raises(ValueError, match="unknown injection point"):
            parse_plan([{"point": "device.shipp"}])

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            parse_plan([{"point": "device.ship", "kind": "explode"}])

    def test_unknown_key_raises(self):
        with pytest.raises(ValueError, match="unknown keys"):
            parse_plan([{"point": "device.ship", "when": 3}])

    def test_partial_write_only_on_sink(self):
        with pytest.raises(ValueError, match="partial_write"):
            parse_plan([{"point": "device.ship", "kind": "partial_write"}])
        assert parse_plan(
            [{"point": "sink.write", "kind": "partial_write"}]
        )[0].kind == "partial_write"

    def test_single_object_is_one_rule_plan(self):
        assert len(parse_plan({"point": "window.feed"})) == 1

    def test_arm_accepts_inline_json_and_file(self, tmp_path):
        inj = FaultInjector()
        inj.arm('[{"point": "window.feed", "at": 2}]')
        assert inj.armed and inj.rules[0].at == 2
        p = tmp_path / "plan.json"
        p.write_text(json.dumps([{"point": "soa.feed", "times": 3}]))
        inj.arm(str(p))
        assert inj.rules[0].point == "soa.feed"
        assert inj.rules[0].times == 3

    def test_registry_names_every_threaded_point(self):
        # The chaos matrix iterates this registry — keep it exact.
        assert set(INJECTION_POINTS) == {
            "device.ship", "device.dispatch", "device.fetch",
            "window.feed", "soa.feed", "kafka.fetch", "kafka.leader",
            "sink.write", "driver.window",
            "overload.admit", "source.stall",
            "pipeline.ship", "pipeline.fetch", "qserve.register",
            "dag.node", "dag.commit", "shard.exchange",
        }


class TestTriggers:
    def test_fires_at_exact_hit_count(self):
        inj = FaultInjector()
        inj.arm([{"point": "window.feed", "at": 3, "times": 2}])
        assert inj.hit("window.feed") is None
        assert inj.hit("window.feed") is None
        for expect_hit in (3, 4):
            with pytest.raises(InjectedFault) as ei:
                inj.hit("window.feed")
            assert ei.value.hit == expect_hit
        assert inj.hit("window.feed") is None  # budget spent
        assert len(inj.fired) == 2

    def test_points_count_independently(self):
        inj = FaultInjector()
        inj.arm([{"point": "device.ship", "at": 2}])
        assert inj.hit("device.fetch") is None
        assert inj.hit("device.ship") is None
        with pytest.raises(InjectedFault):
            inj.hit("device.ship")

    def test_seeded_prob_replays_identically(self):
        def firing_pattern():
            inj = FaultInjector()
            inj.arm([{"point": "window.feed", "at": 1, "times": 50,
                      "prob": 0.5, "seed": 42}])
            out = []
            for _ in range(50):
                try:
                    inj.hit("window.feed")
                    out.append(False)
                except InjectedFault:
                    out.append(True)
            return out

        a, b = firing_pattern(), firing_pattern()
        assert a == b
        assert any(a) and not all(a)  # the draw actually varies

    def test_hang_kind_sleeps_then_raises(self):
        import time

        inj = FaultInjector()
        inj.arm([{"point": "device.fetch", "kind": "hang",
                  "hang_s": 0.05}])
        t0 = time.monotonic()
        with pytest.raises(InjectedFault) as ei:
            inj.hit("device.fetch")
        assert time.monotonic() - t0 >= 0.05
        assert ei.value.kind == "hang"

    def test_disarm_clears_state(self):
        inj = FaultInjector()
        inj.arm([{"point": "window.feed"}])
        inj.disarm()
        assert not inj.armed and not inj.rules
        assert inj.hit("window.feed") is None  # inert once disarmed


class TestDisarmedFree:
    def test_module_singleton_starts_disarmed(self):
        # SFT_FAULT_PLAN is unset in the test env: the import-time arm
        # must leave the injector inert (the bench-smoke contract run
        # depends on this).
        assert faults.armed is False

    def test_disarmed_hot_paths_do_not_touch_the_injector(self):
        """With no plan, the threaded code paths never call hit() — the
        guard is `if faults.armed` — so counts stay empty even after
        real windows/ships run."""
        from spatialflink_tpu.driver import _toy_pipeline
        from spatialflink_tpu.operators.range_query import (
            PointPointRangeQuery,
        )

        grid, conf, source, query = _toy_pipeline(n_events=40)
        op = PointPointRangeQuery(conf, grid)
        assert list(op.run(source(), [query], 1.5))
        assert faults.counts == {}
        assert faults.fired == []


class TestTelemetryVisibility:
    def test_fired_fault_lands_in_snapshot_and_events(self):
        telemetry.enable()
        inj = faults
        inj.arm([{"point": "window.feed", "at": 1}])
        with pytest.raises(InjectedFault):
            inj.hit("window.feed")
        snap = telemetry.snapshot()
        assert snap["faults"] == {"window.feed": 1}
        names = [e["name"] for e in telemetry.events]
        assert "fault_armed" in names
        assert "fault_fired:window.feed" in names

    def test_plan_armed_before_enable_still_records_fault_armed(self):
        """The SFT_FAULT_PLAN path arms at import — BEFORE any
        telemetry.enable(). The armed schedule must still reach the
        trace/stream, or a recovered chaos artifact couldn't say what
        was armed (only what fired)."""
        faults.arm([{"point": "soa.feed", "at": 3}])
        telemetry.enable()
        armed = [e for e in telemetry.events if e["name"] == "fault_armed"]
        assert len(armed) == 1
        assert armed[0]["args"]["plan"][0]["point"] == "soa.feed"

    def test_no_faults_block_when_nothing_fired(self):
        telemetry.enable()
        assert "faults" not in telemetry.snapshot()
        # the driver block is ALWAYS present (gate on zero, not absence)
        assert telemetry.snapshot()["driver"] == {
            "retries": 0, "failovers": 0,
        }


class TestDispatchPointCoverage:
    def test_device_dispatch_lives_in_instrument_jit(self):
        """The point must fire for EVERY instrumented dispatch — the
        mesh window programs and bench steps skip operators/base.jitted,
        so the hook lives in telemetry.instrument_jit (a plan arming
        device.dispatch on a mesh run must not silently never fire)."""
        from spatialflink_tpu.telemetry import instrument_jit

        calls = []
        f = instrument_jit(lambda x: calls.append(x) or x, name="probe")
        faults.arm([{"point": "device.dispatch", "at": 2}])
        assert f(1) == 1
        with pytest.raises(InjectedFault):
            f(2)
        assert calls == [1]  # the faulted dispatch never ran the kernel


class TestEnvArming:
    def test_subprocess_arms_from_env_and_abort_kind_kills(self):
        """SFT_FAULT_PLAN in the environment arms at import; the abort
        kind dies with the SIGKILL-analog exit code, skipping every
        handler."""
        code = (
            "from spatialflink_tpu.faults import faults\n"
            "assert faults.armed\n"
            "import atexit; atexit.register("
            "lambda: print('HANDLER RAN'))\n"
            "faults.hit('window.feed')\n"
            "print('UNREACHABLE')\n"
        )
        p = subprocess.run(
            [sys.executable, "-c", code],
            env={**os.environ,
                 "SFT_FAULT_PLAN":
                     '[{"point": "window.feed", "kind": "abort"}]'},
            capture_output=True, text=True, timeout=120,
        )
        assert p.returncode == ABORT_EXIT_CODE, p.stderr
        assert "UNREACHABLE" not in p.stdout
        assert "HANDLER RAN" not in p.stdout

    def test_rule_validation_happens_at_arm_time(self):
        with pytest.raises(ValueError):
            FaultRule(point="nope")
