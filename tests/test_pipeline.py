"""Pipelined ingest runtime (spatialflink_tpu/pipeline.py) — policy
parsing, the bounded executor's ordering/lag/drain contracts, the
circuit-breaker collapse, and the BIT-IDENTICAL parity of every
integrated path: run_wire_panes (codec on and off), the tjoin segmented
scan, and the driver's split-protocol window processing. The pipeline
may move sync points; it may never move results."""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from spatialflink_tpu import overload  # noqa: E402
from spatialflink_tpu import pipeline  # noqa: E402
from spatialflink_tpu.faults import InjectedFault, faults  # noqa: E402
from spatialflink_tpu.grid import UniformGrid  # noqa: E402
from spatialflink_tpu.models.objects import Point  # noqa: E402
from spatialflink_tpu.operators.query_config import (  # noqa: E402
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.telemetry import telemetry  # noqa: E402


@pytest.fixture(autouse=True)
def _clean():
    yield
    pipeline.uninstall()
    overload.uninstall()
    faults.disarm()
    telemetry.disable()


# ---------------------------------------------------------------------------
# Policy


class TestPolicy:
    def test_defaults(self):
        pol = pipeline.PipelinePolicy()
        assert (pol.depth, pol.fetch_lag, pol.codec) == (2, 2, "off")

    def test_strict_parse_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown keys"):
            pipeline.PipelinePolicy.from_dict({"depht": 3})

    @pytest.mark.parametrize("bad", [
        {"depth": 0}, {"fetch_lag": -1}, {"codec": "lz4"},
        {"codec_strategy": "mosaic"},
    ])
    def test_invalid_values_raise(self, bad):
        with pytest.raises(ValueError):
            pipeline.PipelinePolicy(**bad)

    def test_from_env_forms(self, tmp_path):
        assert pipeline.PipelinePolicy.from_env("1").depth == 2
        assert pipeline.PipelinePolicy.from_env("on").codec == "off"
        pol = pipeline.PipelinePolicy.from_env(
            '{"depth": 4, "codec": "delta"}'
        )
        assert (pol.depth, pol.codec) == (4, "delta")
        p = tmp_path / "pol.json"
        p.write_text(json.dumps({"fetch_lag": 7}))
        assert pipeline.PipelinePolicy.from_env(str(p)).fetch_lag == 7

    def test_arm_from_env(self, monkeypatch):
        monkeypatch.delenv("SFT_PIPELINE", raising=False)
        assert pipeline.arm_from_env() is False
        monkeypatch.setenv("SFT_PIPELINE", '{"depth": 3}')
        assert pipeline.arm_from_env() is True
        assert pipeline.policy().depth == 3

    def test_install_uninstall(self):
        pol = pipeline.install(pipeline.PipelinePolicy())
        assert pipeline.policy() is pol
        pipeline.uninstall()
        assert pipeline.policy() is None


# ---------------------------------------------------------------------------
# Executor (fake stages — no device)


def _tracing_executor(pol, log, n_items=8, gap_every=None):
    def ship(i):
        log.append(("ship", i))
        return f"staged{i}"

    def compute(i, staged):
        assert staged == f"staged{i}"
        log.append(("compute", i))
        if gap_every and i % gap_every == 0:
            return None
        return i

    def fetch(works):
        log.append(("fetch", tuple(works)))
        return [w * 10 for w in works]

    ex = pipeline.PipelinedExecutor(pol, ship=ship, compute=compute,
                                    fetch=fetch)
    return ex, list(range(n_items))


class TestExecutor:
    def test_order_and_overlap_shape(self):
        log = []
        ex, items = _tracing_executor(
            pipeline.PipelinePolicy(depth=2, fetch_lag=2), log)
        out = list(ex.run(items))
        assert out == [i * 10 for i in range(8)]  # ordered results
        # ship ahead: item i+1's ship precedes item i's compute
        assert log.index(("ship", 1)) < log.index(("compute", 0))
        # lag: item 0's fetch happens only after item 2's compute
        first_fetch = next(k for k, e in enumerate(log)
                           if e[0] == "fetch")
        assert log[first_fetch] == ("fetch", (0,))
        assert log.index(("compute", 2)) < first_fetch
        # final drain is ONE batched fetch of the whole tail
        assert log[-1] == ("fetch", (6, 7))

    def test_ship_ahead_never_exceeds_depth(self):
        log = []
        ex, items = _tracing_executor(
            pipeline.PipelinePolicy(depth=3, fetch_lag=1), log)
        list(ex.run(items))
        computed = shipped = 0
        for e in log:
            if e[0] == "ship":
                shipped += 1
            elif e[0] == "compute":
                computed += 1
            assert shipped - computed <= 3

    def test_depth1_lag0_is_synchronous_cadence(self):
        log = []
        ex, items = _tracing_executor(
            pipeline.PipelinePolicy(depth=1, fetch_lag=0), log)
        out = list(ex.run(items))
        assert out == [i * 10 for i in range(8)]
        # strict ship→compute→fetch per item, no overlap
        per_item = [("ship", 0), ("compute", 0), ("fetch", (0,))]
        assert log[:3] == per_item

    def test_gap_items_yield_nothing(self):
        log = []
        ex, items = _tracing_executor(
            pipeline.PipelinePolicy(depth=2, fetch_lag=2), log,
            gap_every=2)
        out = list(ex.run(items))
        assert out == [10, 30, 50, 70]  # odd items only

    def test_empty_stream(self):
        log = []
        ex, _ = _tracing_executor(pipeline.PipelinePolicy(), log)
        assert list(ex.run([])) == []
        assert log == []

    def test_fault_points_fire(self):
        log = []
        ex, items = _tracing_executor(pipeline.PipelinePolicy(), log)
        faults.arm([{"point": "pipeline.ship", "at": 3,
                     "times": 10_000}])
        with pytest.raises(InjectedFault):
            list(ex.run(items))
        faults.arm([{"point": "pipeline.fetch", "at": 1,
                     "times": 10_000}])
        log2 = []
        ex2, items2 = _tracing_executor(pipeline.PipelinePolicy(), log2)
        with pytest.raises(InjectedFault):
            list(ex2.run(items2))

    def test_breaker_collapse_and_resume(self):
        """An OPEN overload circuit collapses the executor to the
        synchronous cadence (no stacking onto a dead tunnel), emits the
        transition events, and re-opens when the breaker closes."""
        pol = overload.OverloadPolicy(breaker_failures=1)
        ctrl = overload.install(
            overload.OverloadController(pol))
        telemetry.enable()
        ctrl.breaker.record_failure(0, "boom")  # → open
        assert ctrl.breaker.state == "open"
        log = []
        ex, items = _tracing_executor(
            pipeline.PipelinePolicy(depth=3, fetch_lag=3), log,
            n_items=4)
        out = list(ex.run(items))
        assert out == [0, 10, 20, 30]
        # collapsed: every item fetched before the next computes
        assert log[2] == ("fetch", (0,))
        snap = telemetry.snapshot()["pipeline"]
        assert snap["collapses"] == 1
        assert snap["sync"] == 4
        names = [e["name"] for e in telemetry.events]
        assert "pipeline_collapsed" in names
        # breaker closes mid-stream → executor resumes overlapping
        log2 = []
        ex2, items2 = _tracing_executor(
            pipeline.PipelinePolicy(depth=2, fetch_lag=2), log2,
            n_items=6)

        def fetch_and_heal(works):
            if ctrl.breaker.state != "closed":
                ctrl.breaker.state = "closed"
            log2.append(("fetch", tuple(works)))
            return [w * 10 for w in works]

        ex2._fetch_fn = fetch_and_heal
        ctrl.breaker.state = "open"
        out2 = list(ex2.run(items2))
        assert out2 == [i * 10 for i in range(6)]
        assert "pipeline_resumed" in [e["name"] for e in
                                      telemetry.events]


# ---------------------------------------------------------------------------
# run_wire_panes parity (the headline operator path)


GRID = UniformGrid(10, 0.0, 10.0, 0.0, 10.0)
CONF = QueryConfiguration(QueryType.WindowBased, window_size=4.0,
                          slide_step=1.0)


def _wire_fixture(rng, n=3000, with_gap=True):
    from spatialflink_tpu.streams.wire import WireFormat, wire_panes

    wf = WireFormat.for_grid(GRID)
    ts = np.sort(rng.integers(0, 30_000, n)).astype(np.int64)
    if with_gap:  # event-time gap → gap windows + multi-pane bursts
        ts[ts > 12_000] += 9_000
        ts = np.sort(ts)
    xy = np.stack([rng.uniform(0, 10, n), rng.uniform(0, 10, n)],
                  axis=1)
    xyf = wf.dequantize_np(wf.quantize(xy))
    # num_segments 64 sits ABOVE XLA:CPU's host-buffer zero-copy
    # aliasing threshold (~128 B): the codec's predictor tables MUST be
    # shipped as copies or the encoder's in-place updates corrupt the
    # device table — a 32-segment fixture would mask that (found live).
    oids = rng.integers(0, 64, n).astype(np.int32)
    panes = list(wire_panes(
        [{"ts": ts, "x": xyf[:, 0].astype(np.float64),
          "y": xyf[:, 1].astype(np.float64), "oid": oids}],
        wf, CONF.slide_step_ms, start_ms=0,
    ))
    return wf, panes


def _collect_wire(op, panes, wf, flush=True):
    return [
        (s, e, list(map(int, o)), [round(float(x), 9) for x in d], nv)
        for s, e, o, d, nv in op.run_wire_panes(
            panes, Point(x=5.0, y=5.0), 3.0, 6, 64, wf, start_ms=0,
            flush_at_end=flush,
        )
    ]


@pytest.fixture
def rng():
    return np.random.default_rng(77)


class TestRunWirePanesPipelined:
    @pytest.mark.parametrize("polkw", [
        {},
        {"codec": "delta"},
        {"depth": 4, "fetch_lag": 3, "codec": "delta"},
        {"depth": 1, "fetch_lag": 0},
    ])
    def test_bit_identical_to_sync(self, rng, polkw):
        from spatialflink_tpu.operators.knn_query import (
            PointPointKNNQuery,
        )

        wf, panes = _wire_fixture(rng)
        pipeline.uninstall()
        base = _collect_wire(PointPointKNNQuery(CONF, GRID), panes, wf)
        assert base, "vacuous parity fixture"
        pipeline.install(pipeline.PipelinePolicy(**polkw))
        got = _collect_wire(PointPointKNNQuery(CONF, GRID), panes, wf)
        assert got == base

    def test_kill_and_resume_mid_overlap(self, rng, tmp_path):
        """The carry publishes per YIELDED window: a checkpoint cut
        anywhere mid-stream resumes to the exact baseline — codec
        predictor state deliberately restarts (results can't change,
        only compression continuity)."""
        from spatialflink_tpu.checkpoint import (
            load_checkpoint,
            operator_state,
            restore_operator,
            save_checkpoint,
        )
        from spatialflink_tpu.operators.knn_query import (
            PointPointKNNQuery,
        )

        wf, panes = _wire_fixture(rng)
        pipeline.uninstall()
        base = _collect_wire(PointPointKNNQuery(CONF, GRID), panes, wf)
        pipeline.install(pipeline.PipelinePolicy(codec="delta",
                                                 depth=3, fetch_lag=2))
        cut = len(panes) // 3
        op1 = PointPointKNNQuery(CONF, GRID)
        part1 = _collect_wire(op1, panes[:cut], wf, flush=False)
        path = str(tmp_path / "wire.ckpt")
        save_checkpoint(path, op=operator_state(op1))
        op2 = PointPointKNNQuery(CONF, GRID)
        restore_operator(op2, load_checkpoint(path)["op"])
        part2 = _collect_wire(op2, panes[cut:], wf)
        assert part1 + part2 == base
        assert part1 and part2

    def test_checkpoint_cut_at_every_yield_loses_nothing(self, rng):
        """Per-YIELD carry contract: snapshot the operator after EACH
        yielded window of a pipelined run and resume from that
        snapshot's pane position — the stitched output must equal the
        baseline at EVERY cut. A fetch batch that published its last
        window's carry before yielding its first would skip the batch
        siblings on resume (lost egress — the bug this pins)."""
        from spatialflink_tpu.checkpoint import (
            load_checkpoint,
            operator_state,
            restore_operator,
            save_checkpoint,
        )
        from spatialflink_tpu.operators.knn_query import (
            PointPointKNNQuery,
        )

        wf, panes = _wire_fixture(rng, n=1500)
        pipeline.uninstall()
        base = _collect_wire(PointPointKNNQuery(CONF, GRID), panes, wf)
        # fetch_lag 3 → the final drain fetches a multi-window batch.
        # Cuts stop BEFORE the trailing flush: synthetic flush panes
        # never advance the carry (by design, sync path identical), so
        # a checkpoint cut mid-flush replays the whole flush — the
        # documented call-boundary contract, not a pipeline property.
        ppw = CONF.window_size_ms // CONF.slide_step_ms
        last_cut = len(base) - ppw
        cuts = sorted(set(
            list(range(1, 7)) + list(range(7, last_cut, 5))
            + [last_cut]
        ))
        pipeline.install(pipeline.PipelinePolicy(depth=2, fetch_lag=3,
                                                 codec="delta"))
        for cut in cuts:
            op1 = PointPointKNNQuery(CONF, GRID)
            gen = op1.run_wire_panes(panes, Point(x=5.0, y=5.0), 3.0,
                                     6, 64, wf, start_ms=0)
            head = []
            for out in gen:
                head.append((out[0], out[1], list(map(int, out[2])),
                             [round(float(x), 9) for x in out[3]],
                             out[4]))
                if len(head) == cut:
                    break
            gen.close()  # the kill: generator abandoned mid-batch
            next_pane = int(op1._wire_pane_carry["next_pane"])
            st = operator_state(op1)
            op2 = PointPointKNNQuery(CONF, GRID)
            import tempfile

            with tempfile.NamedTemporaryFile(suffix=".ckpt") as f:
                save_checkpoint(f.name, op=st)
                restore_operator(op2, load_checkpoint(f.name)["op"])
            tail = _collect_wire(op2, panes[next_pane:], wf)
            assert head + tail == base, f"cut after window {cut}"

    def test_codec_gauges_and_counters_recorded(self, rng):
        from spatialflink_tpu.operators.knn_query import (
            PointPointKNNQuery,
        )

        wf, panes = _wire_fixture(rng, with_gap=False)
        telemetry.enable()
        pipeline.install(pipeline.PipelinePolicy(codec="delta"))
        _collect_wire(PointPointKNNQuery(CONF, GRID), panes, wf)
        snap = telemetry.snapshot()
        wcg = snap["wire_codec"]
        assert wcg["panes"] > 0
        assert wcg["raw_bytes"] > 0
        assert wcg["coded_bytes"] > 0
        assert snap["pipeline"]["windows"] > 0
        assert snap["pipeline"]["overlapped"] > 0
        # the decode kernel rides the compiled-shape ladder
        assert telemetry.distinct_shapes("wire_pane_decode") <= 8

    def test_codec_kind_recorded(self, rng):
        from spatialflink_tpu.operators.knn_query import (
            PointPointKNNQuery,
        )

        wf, panes = _wire_fixture(rng, with_gap=False)
        pipeline.install(pipeline.PipelinePolicy(codec="delta",
                                                 codec_strategy="jnp"))
        op = PointPointKNNQuery(CONF, GRID)
        _collect_wire(op, panes, wf)
        assert op.last_wire_codec_kind == "jnp"


# ---------------------------------------------------------------------------
# tjoin segmented scan parity


class TestTJoinSegmentedScan:
    def _chunks(self, side, n_chunks=10, per=8):
        rng = np.random.default_rng(21 + side)
        out = []
        for c in range(n_chunks):
            base = c * per
            out.append({
                "ts": np.arange(base, base + per, dtype=np.int64) * 250,
                "x": rng.uniform(0.0, 8.0, per),
                "y": rng.uniform(0.0, 8.0, per),
                "oid": (np.arange(base, base + per) % 5).astype(
                    np.int32),
            })
        return out

    def _collect(self):
        from spatialflink_tpu.operators.trajectory import TJoinQuery

        grid = UniformGrid(8, 0.0, 8.0, 0.0, 8.0)
        conf = QueryConfiguration(QueryType.WindowBased,
                                  window_size=2.0, slide_step=0.5)
        op = TJoinQuery(conf, grid)
        return [
            (s, e, list(map(int, lo)), list(map(int, ro)),
             [float(x) for x in dd], c, o)
            for s, e, lo, ro, dd, c, o in op.run_soa_panes(
                self._chunks(0), self._chunks(1), 1.5, 5,
                backend="device",
            )
        ]

    @pytest.mark.parametrize("polkw", [
        {}, {"depth": 4, "fetch_lag": 3}, {"depth": 1, "fetch_lag": 0},
    ])
    def test_segmented_scan_bit_identical(self, polkw):
        """Chained-carry segments (with explicit expiring panes) must
        reproduce the monolithic scan exactly — the expiring-pane slice
        is the part a naive split gets wrong (stale pairs leak into
        late windows)."""
        pipeline.uninstall()
        base = self._collect()
        assert base
        pipeline.install(pipeline.PipelinePolicy(**polkw))
        got = self._collect()
        assert got == base


# ---------------------------------------------------------------------------
# driver integration (split protocol)


def _run_range_driver(workdir, pol, fault_plan=None):
    from spatialflink_tpu.driver import (
        RetryPolicy,
        WindowedDataflowDriver,
        _toy_pipeline,
        render_range_result,
    )
    from spatialflink_tpu.operators.range_query import (
        PointPointRangeQuery,
    )
    from spatialflink_tpu.streams.sinks import TransactionalFileSink

    grid, conf, source, query = _toy_pipeline()
    sink = TransactionalFileSink(os.path.join(workdir, "egress.csv"))
    drv = WindowedDataflowDriver(
        checkpoint_path=os.path.join(workdir, "ckpt.bin"),
        checkpoint_every=2, sink=sink,
        retry=RetryPolicy(max_retries=1, backoff_s=0.0),
        failover=False, pipeline=pol,
    )
    op = PointPointRangeQuery(conf, grid)
    if fault_plan:
        faults.arm(fault_plan)
    try:
        for res in op.run(source(), [query], 1.5, driver=drv):
            for line in render_range_result(res):
                sink.stage(line)
    finally:
        faults.disarm()
    return drv


class TestDriverPipelined:
    def test_egress_byte_identical(self, tmp_path):
        a = tmp_path / "a"
        b = tmp_path / "b"
        a.mkdir()
        b.mkdir()
        _run_range_driver(str(a), None)
        _run_range_driver(
            str(b), pipeline.PipelinePolicy(depth=2, fetch_lag=3))
        wa = (a / "egress.csv").read_bytes()
        assert wa
        assert (b / "egress.csv").read_bytes() == wa

    def test_module_policy_applies_without_explicit_arg(self, tmp_path):
        a = tmp_path / "a"
        b = tmp_path / "b"
        a.mkdir()
        b.mkdir()
        _run_range_driver(str(a), None)
        telemetry.enable()
        pipeline.install(pipeline.PipelinePolicy(fetch_lag=4))
        _run_range_driver(str(b), None)
        counters = telemetry.pipeline_counters()
        telemetry.disable()
        assert counters.get("overlapped", 0) > 0
        assert (b / "egress.csv").read_bytes() == \
            (a / "egress.csv").read_bytes()

    def test_transient_pipeline_fault_contained(self, tmp_path):
        """A raise-kind fault at pipeline.ship/fetch degrades that
        window to the synchronous retry ladder — the run completes
        with byte-identical egress (containment; the crash legs are
        the chaos matrix's abort-kind subprocesses)."""
        a = tmp_path / "a"
        b = tmp_path / "b"
        c = tmp_path / "c"
        for d in (a, b, c):
            d.mkdir()
        _run_range_driver(str(a), None)
        pol = pipeline.PipelinePolicy(depth=2, fetch_lag=2)
        _run_range_driver(str(b), pol, fault_plan=[
            {"point": "pipeline.ship", "at": 3, "times": 2},
        ])
        _run_range_driver(str(c), pol, fault_plan=[
            {"point": "pipeline.fetch", "at": 2, "times": 1},
        ])
        want = (a / "egress.csv").read_bytes()
        assert want
        assert (b / "egress.csv").read_bytes() == want
        assert (c / "egress.csv").read_bytes() == want

    def test_breaker_collapse_instrumented(self, tmp_path):
        """An open circuit during a pipelined driver run must leave the
        same observable trail as the executor's collapse: the
        pipeline_collapsed instant, the collapses counter, and sync
        window counts — a tunnel death mid-overlap may not be
        invisible in the ledger."""
        telemetry.enable()
        pol = overload.OverloadPolicy(breaker_failures=1)
        ctrl = overload.install(overload.OverloadController(pol))
        ctrl.breaker.record_failure(0, "boom")
        assert ctrl.breaker.state == "open"
        d = tmp_path / "d"
        d.mkdir()
        _run_range_driver(
            str(d), pipeline.PipelinePolicy(depth=2, fetch_lag=2))
        counters = telemetry.pipeline_counters()
        assert counters.get("collapses") == 1
        assert counters.get("sync", 0) > 0
        assert counters.get("overlapped", 0) == 0
        names = [e["name"] for e in telemetry.events]
        assert "pipeline_collapsed" in names
        assert (d / "egress.csv").read_bytes()  # run still completed

    def test_failover_mid_flight_keeps_order_and_degraded_honest(self):
        """A fetch failure that exhausts retries and fails over while
        LATER windows sit in flight must (a) drain those windows before
        any post-failover window is yielded — committed egress order
        identical to the synchronous failover run — and (b) not charge
        device-answered in-flight windows as degraded."""
        from spatialflink_tpu.driver import (
            RetryPolicy,
            WindowedDataflowDriver,
            _toy_pipeline,
        )
        from spatialflink_tpu.operators.range_query import (
            PointPointRangeQuery,
        )

        grid, conf, source, _query = _toy_pipeline()

        def build(pol, ctrl):
            op = PointPointRangeQuery(conf, grid)
            drv = WindowedDataflowDriver(
                failover=True,
                retry=RetryPolicy(max_retries=0, backoff_s=0.0),
                pipeline=pol, overload=ctrl,
            )
            drv.attach(op)
            state = {"n": 0}

            def process(win):
                # The device path dies at the 3rd window (sync AND
                # fetch forms) — retries exhaust, failover flips the
                # backend while in-flight windows remain.
                if win.start == poison["start"]:
                    raise RuntimeError("device died")
                return ("dev", win.start, win.end, len(win.events))

            def pipeline_compute(win):
                state["n"] += 1
                return win

            def pipeline_fetch(win):
                return process(win)

            process.pipeline_compute = pipeline_compute
            process.pipeline_fetch = pipeline_fetch

            def fallback(win):
                return ("fb", win.start, win.end, len(win.events))

            drv.bind(op, process, fallback=fallback)
            return op, drv

        # Find the 3rd fired window's start with a throwaway run.
        poison = {"start": None}
        op0 = PointPointRangeQuery(conf, grid)
        starts = [w.start for w in op0.windows(source())]
        poison["start"] = starts[2]

        ctrl_sync = overload.OverloadController(overload.OverloadPolicy())
        op, drv = build(None, ctrl_sync)
        sync_out = list(drv.run(source()))
        overload.uninstall()
        assert ("fb", poison["start"]) == sync_out[2][:2]

        ctrl_pipe = overload.OverloadController(overload.OverloadPolicy())
        op, drv = build(
            pipeline.PipelinePolicy(depth=2, fetch_lag=2), ctrl_pipe)
        pipe_out = list(drv.run(source()))
        overload.uninstall()
        assert pipe_out == sync_out  # ordered, identical routing
        # Degraded accounting: only the genuinely fallback-answered
        # windows count — identical to the synchronous run's tally.
        assert ctrl_pipe.snapshot()["degraded_windows"] == \
            ctrl_sync.snapshot()["degraded_windows"]

    def test_no_split_protocol_means_sync(self, tmp_path):
        """A process without pipeline_compute/fetch attributes runs the
        exact synchronous loop even with a policy armed."""
        from spatialflink_tpu.driver import (
            WindowedDataflowDriver,
        )
        from spatialflink_tpu.operators.trajectory import TStatsQuery
        from spatialflink_tpu.streams.soa import SoaWindowAssembler

        grid = UniformGrid(8, 0.0, 8.0, 0.0, 8.0)
        conf = QueryConfiguration(QueryType.WindowBased,
                                  window_size=2.0, slide_step=1.0)
        op = TStatsQuery(conf, grid)
        telemetry.enable()
        pipeline.install(pipeline.PipelinePolicy())
        drv = WindowedDataflowDriver(failover=False)

        def process(win):
            return (win.start, win.count)

        drv.bind(op, process)

        def chunks():
            rng = np.random.default_rng(3)
            for i in range(6):
                yield {
                    "ts": np.arange(i * 5, i * 5 + 5,
                                    dtype=np.int64) * 200,
                    "x": rng.uniform(0, 8, 5),
                    "y": rng.uniform(0, 8, 5),
                    "oid": np.zeros(5, np.int32),
                }

        asm = SoaWindowAssembler(conf.window_size_ms,
                                 conf.slide_step_ms)
        out = list(drv.run_soa(chunks(), asm))
        assert out
        assert telemetry.pipeline_counters() == {}


# ---------------------------------------------------------------------------
# sfprof surfaces


class TestSfprofSurfaces:
    def test_health_notes_pipeline_counters(self, tmp_path, capsys):
        telemetry.enable()
        telemetry.record_pipeline(windows=5, overlapped=4, sync=1,
                                  drains=2, collapses=1)
        ledger = tmp_path / "ledger.json"
        telemetry.write_ledger(str(ledger))
        telemetry.disable()
        from tools.sfprof.cli import main as sfprof_main

        assert sfprof_main(["health", str(ledger)]) == 0
        out = capsys.readouterr().out
        assert "note pipeline:" in out
        assert "STALLED" in out

    def test_events_registry_covers_pipeline_transitions(self):
        from tools.sfprof import events as ev

        assert ev.classify("pipeline_collapsed") == "pipeline"
        assert ev.classify("pipeline_resumed") == "pipeline"

    def test_report_prints_codec_and_link_utilization(self, tmp_path,
                                                      capsys):
        import time as _time

        telemetry.enable()
        telemetry.account_wire(6000, 2400)
        telemetry.record_link_sample(0.5, 25.0, 262144)
        telemetry.account_h2d(1_000_000)
        with telemetry.span("window.x"):
            _time.sleep(0.01)
        ledger = tmp_path / "ledger.json"
        telemetry.write_ledger(str(ledger))
        telemetry.disable()
        from tools.sfprof.cli import main as sfprof_main

        assert sfprof_main(["report", str(ledger)]) == 0
        out = capsys.readouterr().out
        assert "wire bytes, post-codec" in out
        assert "wire codec: 1 panes" in out
        assert "link utilization:" in out
        assert "MB/s round-trip bandwidth" in out
