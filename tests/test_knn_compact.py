"""knn_pane_digest_compact must be bit-identical to the scatter digest:
sparse (compact path), dense (automatic scatter fallback), ties, flags
on/off, and through the window merge."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.ops.cells import assign_cells
from spatialflink_tpu.ops.knn import (
    knn_merge_digest_list,
    knn_pane_digest,
    knn_pane_digest_compact,
)

NSEG = 512


@pytest.fixture(scope="module")
def grid():
    return UniformGrid(100, min_x=0.0, max_x=10.0, min_y=0.0, max_y=10.0)


def _pane(rng, n, grid, spread=10.0):
    xy = np.stack([rng.uniform(0, spread, n), rng.uniform(0, spread, n)],
                  axis=1).astype(np.float32)
    oid = rng.integers(0, NSEG, n).astype(np.int32)
    valid = np.ones(n, bool)
    cell = grid.assign_cells_np(xy.astype(np.float64))
    return xy, valid, cell, oid


def _digests(grid, xy, valid, cell, oid, q, radius, flags, cand,
             selection="auto"):
    args = (
        jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(cell),
        None if flags is None else jnp.asarray(flags),
        jnp.asarray(oid), jnp.asarray(q), np.float32(radius),
        jnp.int32(0),
    )
    d_full = jax.jit(knn_pane_digest, static_argnames="num_segments")(
        jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(cell),
        jnp.asarray(flags if flags is not None
                    else np.ones(grid.num_cells + 1, np.uint8)),
        jnp.asarray(oid), jnp.asarray(q), np.float32(radius), jnp.int32(0),
        num_segments=NSEG,
    )
    d_cmp = jax.jit(
        knn_pane_digest_compact,
        static_argnames=("num_segments", "cand", "selection"),
    )(*args, num_segments=NSEG, cand=cand, selection=selection)
    return d_full, d_cmp


def _assert_same(d_full, d_cmp):
    assert np.array_equal(np.asarray(d_full.seg_min), np.asarray(d_cmp.seg_min))
    assert np.array_equal(np.asarray(d_full.rep), np.asarray(d_cmp.rep))


@pytest.mark.parametrize("selection", ["topk", "blocked"])
def test_compact_sparse_matches_scatter(grid, selection):
    """Few in-radius points (< cand): BOTH selection strategies (the
    CPU-best top_k sort and the TPU-best blocked prefix select) must
    produce the scatter digest bit-for-bit."""
    rng = np.random.default_rng(1)
    xy, valid, cell, oid = _pane(rng, 50_000, grid)
    q = np.asarray([5.0, 5.0], np.float32)
    radius = 0.2  # ~60 points in radius
    flags = grid.neighbor_flags(radius, [grid.flat_cell(*q)])
    d_full, d_cmp = _digests(grid, xy, valid, cell, oid, q, radius, flags,
                             cand=1024, selection=selection)
    _assert_same(d_full, d_cmp)
    assert int(np.sum(np.asarray(d_cmp.seg_min) < np.finfo(np.float32).max)) > 0


@pytest.mark.parametrize("selection", ["topk", "blocked"])
def test_compact_blocked_overflow_falls_back(grid, selection):
    """A block crammed with in-radius points (or n_in > cand for topk)
    must take the exact scatter fallback."""
    rng = np.random.default_rng(12)
    n = 4_096
    # Every point in radius and packed into the low blocks.
    xy = np.full((n, 2), 5.0, np.float32) + rng.normal(0, 0.01, (n, 2)).astype(
        np.float32)
    oid = rng.integers(0, NSEG, n).astype(np.int32)
    valid = np.ones(n, bool)
    cell = grid.assign_cells_np(xy.astype(np.float64))
    q = np.asarray([5.0, 5.0], np.float32)
    d_full, d_cmp = _digests(grid, xy, valid, cell, oid, q, 1.0, None,
                             cand=64, selection=selection)
    _assert_same(d_full, d_cmp)


def test_compact_dense_falls_back(grid):
    """More in-radius points than cand: the lax.cond fallback must produce
    the scatter digest bit-for-bit."""
    rng = np.random.default_rng(2)
    xy, valid, cell, oid = _pane(rng, 20_000, grid)
    q = np.asarray([5.0, 5.0], np.float32)
    radius = 8.0  # nearly everything in radius — far more than cand=256
    flags = grid.neighbor_flags(1.0, [grid.flat_cell(*q)])
    flags = np.ones_like(flags)  # all cells candidates at this radius
    d_full, d_cmp = _digests(grid, xy, valid, cell, oid, q, radius, flags,
                             cand=256)
    _assert_same(d_full, d_cmp)


def test_compact_no_flags_matches_flagged(grid):
    """flags_table=None (gather skipped): identical digest — the radius
    test subsumes single-query grid pruning."""
    rng = np.random.default_rng(3)
    xy, valid, cell, oid = _pane(rng, 50_000, grid)
    q = np.asarray([3.0, 7.0], np.float32)
    radius = 0.3
    flags = grid.neighbor_flags(radius, [grid.flat_cell(*q)])
    d_flag, d_noflag = (
        _digests(grid, xy, valid, cell, oid, q, radius, flags, cand=2048)[1],
        _digests(grid, xy, valid, cell, oid, q, radius, None, cand=2048)[1],
    )
    _assert_same(d_flag, d_noflag)


def test_compact_tie_break_first_seen(grid):
    """Duplicate coordinates (equal distances) must keep the lowest index
    as representative — the scatter path's contract."""
    xy = np.asarray(
        [[5.1, 5.0]] * 4 + [[5.2, 5.0]] * 3 + [[9.0, 9.0]], np.float32
    )
    oid = np.asarray([7, 7, 3, 7, 3, 3, 7, 1], np.int32)
    valid = np.ones(len(xy), bool)
    cell = grid.assign_cells_np(xy.astype(np.float64))
    q = np.asarray([5.0, 5.0], np.float32)
    d_full, d_cmp = _digests(grid, xy, valid, cell, oid, q, 1.0, None,
                             cand=4)  # in-radius (7) > cand → fallback
    _assert_same(d_full, d_cmp)
    d_full2, d_cmp2 = _digests(grid, xy, valid, cell, oid, q, 1.0, None,
                               cand=8)
    _assert_same(d_full2, d_cmp2)
    rep = np.asarray(d_cmp2.rep)
    assert rep[7] == 0 and rep[3] == 2  # first-seen at the min distance


def test_compact_through_merge(grid):
    """Two panes digested compactly, merged: same KnnResult as scatter
    digests merged (the carry pipeline is unchanged downstream)."""
    rng = np.random.default_rng(4)
    q = np.asarray([5.0, 5.0], np.float32)
    radius, k = 1.0, 16
    panes_full, panes_cmp = [], []
    for seed in (10, 11):
        xy, valid, cell, oid = _pane(np.random.default_rng(seed), 30_000, grid)
        flags = grid.neighbor_flags(radius, [grid.flat_cell(*q)])
        d_full, d_cmp = _digests(grid, xy, valid, cell, oid, q, radius,
                                 flags, cand=4096)
        panes_full.append(d_full)
        panes_cmp.append(d_cmp)
    bases = np.asarray([0, 30_000], np.int32)
    merge = jax.jit(knn_merge_digest_list, static_argnames="k")
    r_full = merge(tuple(d.seg_min for d in panes_full),
                   tuple(d.rep for d in panes_full), bases, k=k)
    r_cmp = merge(tuple(d.seg_min for d in panes_cmp),
                  tuple(d.rep for d in panes_cmp), bases, k=k)
    for a, b in zip(r_full, r_cmp):
        assert np.array_equal(np.asarray(a), np.asarray(b))
