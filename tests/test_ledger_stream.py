"""Ledger-stream tests: the JSONL segment grammar telemetry writes under
``SFT_LEDGER_STREAM`` (prologue / span batches / checkpoints / sealing
epilogue), the disable()-seals contract, non-finite sanitization on the
stream path, and ``sfprof recover`` rebuilding a schema-valid ledger
from complete AND truncated streams."""

import json

import jax
import jax.numpy as jnp
import pytest

from spatialflink_tpu.telemetry import (
    LEDGER_VERSION,
    STREAM_VERSION,
    instrument_jit,
    telemetry,
)
from tools.sfprof import ledger as ledger_mod
from tools.sfprof import stream as stream_mod
from tools.sfprof.cli import main as sfprof_main


@pytest.fixture(autouse=True)
def _telemetry_off():
    cap = telemetry.max_events
    yield
    telemetry.max_events = cap
    telemetry.enable()
    telemetry.disable()


def _run_stream(tmp_path, name="s.jsonl", windows=3, seal="ledger"):
    """A small instrumented run writing a stream; returns its path.
    ``seal``: "ledger" (write_ledger seals with reason complete),
    "disable" (disable() seals), or None (leave unsealed/open)."""
    path = str(tmp_path / name)
    telemetry.enable(stream_path=path, stream_flush_interval_s=0.0)
    f = instrument_jit(jax.jit(lambda x: x * 2), name="double")
    for w in range(windows):
        with telemetry.span("window.demo", window=w):
            f(jnp.ones((8,), jnp.float32))
    if seal == "ledger":
        telemetry.write_ledger(str(tmp_path / (name + ".ledger.json")),
                               bench={"value": 10.0})
        telemetry.disable()
    elif seal == "disable":
        telemetry.disable()
    return path


def _records(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# -- stream grammar -----------------------------------------------------------


def test_stream_version_constants_in_sync():
    """Writer (telemetry) and recoverer (tools/sfprof) deliberately
    don't import each other — the cross-pin, same as LEDGER_VERSION."""
    assert stream_mod.STREAM_VERSION == STREAM_VERSION


def test_stream_grammar_prologue_segments_epilogue(tmp_path):
    path = _run_stream(tmp_path)
    recs = _records(path)
    assert recs[0]["t"] == "prologue"
    assert recs[0]["stream_version"] == STREAM_VERSION
    assert recs[0]["ledger_version"] == LEDGER_VERSION
    assert recs[0]["created_unix"] > 0
    kinds = [r["t"] for r in recs]
    assert kinds[-1] == "epilogue"
    assert "checkpoint" in kinds and "spans" in kinds
    # Window-boundary flush with interval 0: one checkpoint per window,
    # each preceded (same seq) by its span batch.
    cks = [r for r in recs if r["t"] == "checkpoint"]
    assert len(cks) >= 3
    assert [c["seq"] for c in cks] == sorted(c["seq"] for c in cks)
    for c in cks:
        assert set(c["snapshot"]) >= {"compiles", "bytes_h2d",
                                      "late_dropped", "kernels"}
    # Every emitted event appears in exactly one span batch, in order.
    streamed = [e for r in recs if r["t"] == "spans"
                for e in r["events"]]
    assert [e["name"] for e in streamed
            if e["name"].startswith("window.")] == ["window.demo"] * 3
    ep = recs[-1]
    assert ep["reason"] == "complete"
    assert ep["bench"]["value"] == 10.0


def test_flush_interval_paces_checkpoints(tmp_path):
    path = str(tmp_path / "paced.jsonl")
    telemetry.enable(stream_path=path, stream_flush_interval_s=3600.0)
    for w in range(10):
        with telemetry.span("window.demo", window=w):
            pass
    telemetry.disable()
    # Only the seal flushed: one checkpoint, one span batch, all events.
    recs = _records(path)
    assert sum(r["t"] == "checkpoint" for r in recs) == 1
    batches = [r for r in recs if r["t"] == "spans"]
    assert len(batches) == 1 and len(batches[0]["events"]) == 10


def test_disable_seals_stream_and_flushes_trace(tmp_path):
    """Satellite regression: a mid-run disable() must seal BOTH sinks —
    the stream gets its epilogue (reason: disabled) and the trace file
    keeps every buffered event even though FLUSH_EVERY was never hit."""
    trace = tmp_path / "t.jsonl"
    stream = tmp_path / "s.jsonl"
    telemetry.enable(trace_path=str(trace), stream_path=str(stream),
                     stream_flush_interval_s=3600.0)
    n = 5  # far below FLUSH_EVERY: only disable() can flush these
    assert n < telemetry.FLUSH_EVERY
    for w in range(n):
        with telemetry.span("window.demo", window=w):
            pass
    telemetry.disable()
    recs = _records(str(stream))
    assert recs[-1]["t"] == "epilogue"
    assert recs[-1]["reason"] == "disabled"
    spans = [ln for ln in trace.read_text().splitlines()
             if '"window.demo"' in ln]
    assert len(spans) == n
    # And the sealed stream recovers into a valid ledger.
    doc, info = stream_mod.recover(str(stream))
    assert ledger_mod.validate(doc) == []
    assert info["sealed"] and info["reason"] == "disabled"


def test_stream_sanitizes_nonfinite_values(tmp_path):
    path = str(tmp_path / "nan.jsonl")
    telemetry.enable(stream_path=path, stream_flush_interval_s=0.0)
    with telemetry.span("window.demo", bad=float("nan")):
        pass
    telemetry.disable()
    recs = _records(path)  # json.loads would choke on a bare NaN token
    ep = recs[-1]
    assert ep["nonfinite_values"] >= 1
    doc, _ = stream_mod.recover(path)
    assert ledger_mod.validate(doc) == []
    assert doc["nonfinite_values"] >= 1


# -- recovery -----------------------------------------------------------------


def test_recover_complete_stream_matches_ledger(tmp_path):
    stream = _run_stream(tmp_path)
    ledger_path = stream + ".ledger.json"
    doc, info = stream_mod.recover(stream)
    assert ledger_mod.validate(doc) == []
    assert info["sealed"] is True and info["truncated"] is False
    assert info["loss_bound"].startswith("none")
    ledger = ledger_mod.load(ledger_path)
    # The stream's final checkpoint carries the same gauge state the
    # one-shot ledger recorded (written before costs were captured, so
    # compare the snapshot, not the kernel cost blocks).
    for key in ("compiles", "bytes_h2d", "bytes_d2h", "late_dropped"):
        assert doc["snapshot"][key] == ledger["snapshot"][key]
    assert doc["bench"]["value"] == ledger["bench"]["value"]
    win_names = [e["name"] for e in doc["events"]
                 if e["name"].startswith("window.")]
    assert win_names == [e["name"] for e in ledger["events"]
                         if e["name"].startswith("window.")]


def test_recover_truncated_stream_loses_at_most_one_interval(tmp_path):
    """Simulated SIGKILL: cut the stream mid-final-line, no epilogue.
    Recovery must yield a schema-valid ledger holding everything up to
    the last complete checkpoint and say so honestly."""
    full = _run_stream(tmp_path, windows=4, seal=None)
    telemetry.maybe_flush_stream(force=True)
    raw = open(full, "rb").read()
    telemetry.disable()
    trunc = tmp_path / "trunc.jsonl"
    trunc.write_bytes(raw[: len(raw) - 25])  # half-written tail line
    doc, info = stream_mod.recover(str(trunc))
    assert ledger_mod.validate(doc) == []
    assert info["sealed"] is False
    assert info["truncated"] is True and info["partial_tail"] is True
    assert "one flush interval" in info["loss_bound"]
    assert doc["bench"] is None  # no epilogue — no bench record to fake
    assert doc["recovery"]["checkpoints"] >= 3
    # Events survive up to the truncation point: at least the windows
    # before the last complete flush.
    wins = [e for e in doc["events"]
            if e["name"].startswith("window.")]
    assert len(wins) >= 3


def test_recover_stream_killed_before_first_checkpoint(tmp_path):
    path = tmp_path / "young.jsonl"
    telemetry.enable(stream_path=str(path), stream_flush_interval_s=3600)
    with telemetry.span("window.demo"):
        pass
    raw = open(path, "rb").read()  # prologue only: nothing flushed yet
    telemetry.disable()
    young = tmp_path / "young_cut.jsonl"
    young.write_bytes(raw)
    doc, info = stream_mod.recover(str(young))
    assert ledger_mod.validate(doc) == []
    assert info["snapshot_synthesized"] is True
    assert doc["snapshot"]["synthesized"] is True
    assert info["checkpoints"] == 0 and info["sealed"] is False


def test_recover_honors_epilogue_past_partial_tail(tmp_path):
    """The supervisor-seal shape: valid records, a half-written line,
    then an epilogue appended on its own line. The epilogue's reason
    must survive; any OTHER record past the corruption stays skipped
    (no silent re-synchronization)."""
    full = _run_stream(tmp_path, windows=2, seal=None)
    telemetry.maybe_flush_stream(force=True)
    raw = open(full, "rb").read()
    telemetry.disable()
    cut = tmp_path / "sealed_after_cut.jsonl"
    cut.write_bytes(
        raw[: len(raw) - 20]  # half-written tail, no newline
        + b"\n" + json.dumps({"t": "spans", "seq": 9, "events": [
            {"name": "window.fake", "ph": "X", "ts": 0, "dur": 1,
             "pid": 1, "tid": 1}]}).encode() + b"\n"  # must NOT re-sync
        + json.dumps({"t": "epilogue", "unix": 9.0,
                      "reason": "terminated (SIGTERM)",
                      "sealed_by": "supervisor"}).encode() + b"\n"
    )
    doc, info = stream_mod.recover(str(cut))
    assert ledger_mod.validate(doc) == []
    assert info["sealed"] is True
    assert info["sealed_by"] == "supervisor"
    assert info["reason"] == "terminated (SIGTERM)"
    assert info["partial_tail"] is True and info["truncated"] is True
    assert info["skipped_lines"] == 1  # the post-corruption spans batch
    assert all(e["name"] != "window.fake" for e in doc["events"])


def test_supervisor_seal_on_clean_boundary_still_truncated(tmp_path):
    """A supervisor epilogue on a clean line boundary (child killed
    BETWEEN flushes) attributes the crash but must not masquerade as a
    complete capture: truncated stays True, child seals stay not."""
    full = _run_stream(tmp_path, windows=2, seal=None)
    telemetry.maybe_flush_stream(force=True)
    raw = open(full, "rb").read()
    telemetry.disable()
    crashed = tmp_path / "crashed.jsonl"
    crashed.write_bytes(raw + json.dumps(
        {"t": "epilogue", "unix": 9.0, "reason": "deadline",
         "sealed_by": "supervisor"}).encode() + b"\n")
    _, info = stream_mod.recover(str(crashed))
    assert info["sealed"] is True and info["truncated"] is True
    assert info["sealed_by"] == "supervisor"
    assert "one flush interval" in info["loss_bound"]
    # A CHILD seal ("complete"/"disabled") is the complete-capture case.
    complete = _run_stream(tmp_path, name="done.jsonl", seal="disable")
    _, info = stream_mod.recover(complete)
    assert info["sealed_by"] == "telemetry"
    assert info["truncated"] is False


def test_recover_rejects_non_stream_files(tmp_path):
    not_stream = tmp_path / "x.json"
    not_stream.write_text('{"hello": 1}\n')
    with pytest.raises(ValueError, match="record|prologue"):
        stream_mod.recover(str(not_stream))
    assert sfprof_main(["recover", str(not_stream)]) == 2
    assert sfprof_main(["recover", str(tmp_path / "absent.jsonl")]) == 2


# -- CLI ----------------------------------------------------------------------


def test_recover_cli_roundtrips_into_health(tmp_path, capsys):
    stream = _run_stream(tmp_path)
    out = tmp_path / "recovered.json"
    assert sfprof_main(["recover", stream, "-o", str(out)]) == 0
    printed = capsys.readouterr().out
    assert "sealed: yes" in printed and "valid" in printed
    assert "np." not in printed  # egress stays numpy-repr-free
    # The recovered document passes the post-bench health gate.
    assert sfprof_main(["health", str(out)]) == 0
    # And sfprof report renders it like any ledger.
    assert sfprof_main(["report", str(out)]) == 0


def test_recover_cli_reports_truncation_honestly(tmp_path, capsys):
    full = _run_stream(tmp_path, windows=3, seal=None)
    telemetry.maybe_flush_stream(force=True)
    raw = open(full, "rb").read()
    telemetry.disable()
    trunc = tmp_path / "cut.jsonl"
    trunc.write_bytes(raw[: len(raw) - 10])
    out = tmp_path / "rec.json"
    assert sfprof_main(["recover", str(trunc), "-o", str(out)]) == 0
    printed = capsys.readouterr().out
    assert "sealed: NO" in printed
    assert "truncated: yes" in printed
    assert "half-written tail" in printed
    assert sfprof_main(["health", str(out)]) == 0
