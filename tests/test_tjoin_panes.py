"""Pane-carry tJoin (ops/tjoin_panes.py + TJoinQuery.run_soa_panes):
pair-set and min-distance parity with the full-window run_soa path,
including an extreme-overlap (ppw=100) config and the overflow retry."""

import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.operators import QueryConfiguration, QueryType
from spatialflink_tpu.operators.trajectory import TJoinQuery

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _chunks(rng, n, t_span, n_obj, seed_shift=0.0):
    ts = np.sort(rng.integers(0, t_span, n)).astype(np.int64)
    return [{
        "ts": ts,
        "x": rng.uniform(2 + seed_shift, 8 + seed_shift, n),
        "y": rng.uniform(2, 8, n),
        "oid": rng.integers(0, n_obj, n).astype(np.int32),
    }]


def _runsoa_key(results):
    out = {}
    for start, end, lo, ro, dd, count, over in results:
        assert over == 0
        out[start] = sorted(
            (int(a), int(b), round(float(d), 9))
            for a, b, d in zip(lo, ro, dd)
        )
    return out


def _parity(rng, conf, radius, n=1500, n_obj=24, t_span=4_000,
            backend="auto"):
    left = _chunks(rng, n, t_span, n_obj)
    right = _chunks(rng, n, t_span, n_obj, seed_shift=0.3)
    op1 = TJoinQuery(conf, GRID)
    soa = _runsoa_key(op1.run_soa(
        iter([dict(c) for c in left]), iter([dict(c) for c in right]),
        radius, num_segments=n_obj,
    ))
    op2 = TJoinQuery(conf, GRID)
    panes = _runsoa_key(op2.run_soa_panes(
        iter([dict(c) for c in left]), iter([dict(c) for c in right]),
        radius, num_segments=n_obj, backend=backend,
    ))
    assert soa, "no windows fired"
    hits = 0
    for start, pairs in soa.items():
        assert start in panes, f"pane engine missed window {start}"
        assert panes[start] == pairs, f"window {start} diverges"
        hits += len(pairs)
    assert hits > 0, "degenerate test: no pairs matched anywhere"


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["device", "native"])
def test_tjoin_panes_matches_run_soa_sliding(rng, backend):
    conf = QueryConfiguration(QueryType.WindowBased, window_size=1,
                              slide_step=0.1)
    _parity(rng, conf, radius=0.4, backend=backend)


@pytest.mark.slow
@pytest.mark.parametrize("backend", ["device", "native"])
def test_tjoin_panes_matches_run_soa_extreme_overlap(rng, backend):
    """ppw=100 — the 10s/10ms window shape at test scale."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=1,
                              slide_step=0.01)
    _parity(rng, conf, radius=0.3, n=800, n_obj=16, t_span=2_500,
            backend=backend)


@pytest.mark.slow
def test_tjoin_panes_retry_on_tiny_budgets(rng):
    """Deliberately tiny cap_w/pair_sel must converge via the doubling
    retry to the same exact result."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=1,
                              slide_step=0.25)
    n, n_obj = 600, 8
    left = _chunks(rng, n, 3_000, n_obj)
    right = _chunks(rng, n, 3_000, n_obj, seed_shift=0.2)
    ref = _runsoa_key(TJoinQuery(conf, GRID).run_soa(
        iter([dict(c) for c in left]), iter([dict(c) for c in right]),
        0.5, num_segments=n_obj,
    ))
    got = _runsoa_key(TJoinQuery(conf, GRID).run_soa_panes(
        iter([dict(c) for c in left]), iter([dict(c) for c in right]),
        0.5, num_segments=n_obj, cap_w=2, pair_sel=1, backend="device",
    ))
    for start, pairs in ref.items():
        assert got[start] == pairs


def test_tjoin_panes_one_sided_windows_fire_empty(rng):
    conf = QueryConfiguration(QueryType.WindowBased, window_size=1,
                              slide_step=0.5)
    left = _chunks(rng, 100, 1_000, 8)
    right = [{
        "ts": np.asarray([5_000, 5_100], np.int64),  # far later
        "x": np.asarray([5.0, 5.1]),
        "y": np.asarray([5.0, 5.1]),
        "oid": np.asarray([0, 1], np.int32),
    }]
    res = list(TJoinQuery(conf, GRID).run_soa_panes(
        iter(left), iter(right), 0.5, num_segments=8,
    ))
    starts = [r[0] for r in res]
    # early (left-only) and late (right-only) windows both fire, empty
    assert any(s < 2_000 for s in starts)
    assert any(s >= 4_000 for s in starts)
    assert all(r[5] == 0 for r in res if r[0] < 2_000 or r[0] >= 4_000)
    assert all(r[6] == 0 for r in res)


def test_tjoin_panes_digest_memory_guard():
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10,
                              slide_step=0.01)
    with pytest.raises(ValueError, match="digest memory"):
        list(TJoinQuery(conf, GRID).run_soa_panes(
            iter([]), iter([{
                "ts": np.asarray([0], np.int64), "x": np.asarray([1.0]),
                "y": np.asarray([1.0]), "oid": np.asarray([0], np.int32),
            }]), 0.5, num_segments=2048,
        ))


def test_tjoin_panes_epoch_ms_timestamps(rng):
    """Epoch-ms streams must survive the int32 pane rebasing (absolute
    pane indices ~1.7e11 would overflow int32)."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=1,
                              slide_step=0.25)
    base = 1_753_900_000_000
    n, n_obj = 400, 8
    left = _chunks(rng, n, 2_000, n_obj)
    right = _chunks(rng, n, 2_000, n_obj, seed_shift=0.2)
    for side in (left, right):
        side[0]["ts"] = side[0]["ts"] + base
    ref = _runsoa_key(TJoinQuery(conf, GRID).run_soa(
        iter([dict(c) for c in left]), iter([dict(c) for c in right]),
        0.5, num_segments=n_obj,
    ))
    got = _runsoa_key(TJoinQuery(conf, GRID).run_soa_panes(
        iter([dict(c) for c in left]), iter([dict(c) for c in right]),
        0.5, num_segments=n_obj,
    ))
    assert ref
    for start, pairs in ref.items():
        assert got[start] == pairs


def test_tjoin_panes_single_pane_cell_flood_retries(rng):
    """More same-cell points in ONE pane than cap_w must trip the
    overflow counter (rank wraparound would silently drop points) and
    converge via the retry."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=1,
                              slide_step=1)
    n, n_obj = 120, 8  # all in one cell, one pane
    left = [{
        "ts": np.zeros(n, np.int64) + 100,
        "x": rng.uniform(5.0, 5.4, n),
        "y": rng.uniform(5.0, 5.4, n),
        "oid": rng.integers(0, n_obj, n).astype(np.int32),
    }]
    right = [dict(left[0], x=rng.uniform(5.0, 5.4, n))]
    ref = _runsoa_key(TJoinQuery(conf, GRID, cap=256).run_soa(
        iter([dict(c) for c in left]), iter([dict(c) for c in right]),
        0.5, num_segments=n_obj,
    ))
    got = _runsoa_key(TJoinQuery(conf, GRID).run_soa_panes(
        iter([dict(c) for c in left]), iter([dict(c) for c in right]),
        0.5, num_segments=n_obj, cap_w=16,
    ))
    for start, pairs in ref.items():
        assert got[start] == pairs


def test_tjoin_panes_native_matches_device(rng):
    """The native CPU engine (sf_tjoin_panes) against the device scan on
    the same stream — same windows, same pair sets, min dists to 1e-12
    (double FMA contraction freedom between g++ and XLA)."""
    from spatialflink_tpu import native as _native

    if not _native.available():
        pytest.skip("native library unavailable")
    conf = QueryConfiguration(QueryType.WindowBased, window_size=1,
                              slide_step=0.2)
    n, n_obj = 1_000, 12
    left = _chunks(rng, n, 3_000, n_obj)
    right = _chunks(rng, n, 3_000, n_obj, seed_shift=0.25)

    def run(backend):
        return {
            s: list(zip(map(int, lo), map(int, ro), dd))
            for s, e, lo, ro, dd, c, ov in TJoinQuery(conf, GRID)
            .run_soa_panes(
                iter([dict(c) for c in left]),
                iter([dict(c) for c in right]),
                0.45, num_segments=n_obj, backend=backend,
            )
        }

    dev = run("device")
    nat = run("native")
    assert dev.keys() == nat.keys()
    pairs_total = 0
    for s in dev:
        dpairs = {(a, b): d for a, b, d in dev[s]}
        npairs = {(a, b): d for a, b, d in nat[s]}
        assert dpairs.keys() == npairs.keys(), f"window {s} pair set"
        for k in dpairs:
            assert abs(dpairs[k] - npairs[k]) <= 1e-12 * max(
                abs(dpairs[k]), 1e-30)
        pairs_total += len(dpairs)
    assert pairs_total > 0


def test_tjoin_panes_backend_validation(rng):
    conf = QueryConfiguration(QueryType.WindowBased, window_size=1,
                              slide_step=0.5)
    chunk = [{
        "ts": np.asarray([100], np.int64), "x": np.asarray([5.0]),
        "y": np.asarray([5.0]), "oid": np.asarray([0], np.int32),
    }]
    with pytest.raises(ValueError, match="backend"):
        list(TJoinQuery(conf, GRID).run_soa_panes(
            iter(chunk), iter([dict(chunk[0])]), 0.5, num_segments=4,
            backend="cuda",
        ))
    import unittest.mock as mock

    from spatialflink_tpu import native as _native
    with mock.patch.object(_native, "available", return_value=False):
        with pytest.raises(RuntimeError, match="native library"):
            list(TJoinQuery(conf, GRID).run_soa_panes(
                iter([dict(chunk[0])]), iter([dict(chunk[0])]), 0.5,
                num_segments=4, backend="native",
            ))
