"""Windowing semantics tests: Flink-compatible assignment, watermarks,
allowed lateness, count windows."""

from dataclasses import dataclass

import pytest

from spatialflink_tpu.streams.windows import (
    CountWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
    WindowAssembler,
)


@dataclass
class Ev:
    ts: int
    name: str = ""


def test_sliding_assignment():
    w = SlidingEventTimeWindows(10_000, 5_000)
    specs = w.assign(12_000)
    spans = {(s.start, s.end) for s in specs}
    assert spans == {(10_000, 20_000), (5_000, 15_000)}


def test_tumbling_assignment():
    w = TumblingEventTimeWindows(10_000)
    (s,) = w.assign(12_000)
    assert (s.start, s.end) == (10_000, 20_000)
    (s,) = w.assign(9_999)
    assert (s.start, s.end) == (0, 10_000)


def test_assignment_negative_ts():
    w = SlidingEventTimeWindows(10_000, 5_000)
    spans = {(s.start, s.end) for s in w.assign(-3_000)}
    assert spans == {(-5_000, 5_000), (-10_000, 0)}


def test_windows_fire_on_watermark():
    asm = WindowAssembler(
        TumblingEventTimeWindows(10_000), timestamp_fn=lambda e: e.ts
    )
    fired = []
    for ts in [1000, 5000, 9999, 10001]:
        fired += asm.feed(Ev(ts))
    # The event at 10001 advances the watermark past window [0,10000).
    assert len(fired) == 1
    assert (fired[0].start, fired[0].end) == (0, 10_000)
    assert [e.ts for e in fired[0].events] == [1000, 5000, 9999]
    # Flush fires the remaining [10000,20000) window.
    rest = asm.flush()
    assert len(rest) == 1 and rest[0].start == 10_000


def test_out_of_orderness_delays_firing():
    asm = WindowAssembler(
        TumblingEventTimeWindows(10_000),
        timestamp_fn=lambda e: e.ts,
        max_out_of_orderness_ms=2_000,
    )
    fired = asm.feed(Ev(1000)) + asm.feed(Ev(10_500))
    assert fired == []  # watermark = 8_500 < 10_000
    fired = asm.feed(Ev(12_100))  # watermark = 10_100
    assert len(fired) == 1
    assert [e.ts for e in fired[0].events] == [1000]


def test_allowed_lateness_refires():
    asm = WindowAssembler(
        TumblingEventTimeWindows(10_000),
        timestamp_fn=lambda e: e.ts,
        allowed_lateness_ms=5_000,
    )
    asm.feed(Ev(1000))
    fired = asm.feed(Ev(11_000))  # fires [0,10000) with 1 event
    assert len(fired) == 1 and len(fired[0].events) == 1
    late = asm.feed(Ev(9_000))  # late but within lateness → refire
    assert len(late) == 1
    assert [e.ts for e in late[0].events] == [1000, 9_000]
    asm.feed(Ev(16_000))  # watermark 16000 >= 10000+5000 → GC
    dropped = asm.feed(Ev(8_000))  # beyond lateness → dropped
    assert dropped == [] or all(w.start != 0 for w in dropped)
    assert asm.dropped_late >= 1


def test_sliding_event_in_multiple_windows():
    asm = WindowAssembler(
        SlidingEventTimeWindows(10_000, 5_000), timestamp_fn=lambda e: e.ts
    )
    out = []
    for ts in [7_000, 12_000, 21_000]:
        out += asm.feed(Ev(ts))
    out += asm.flush()
    spans = {(w.start, w.end): [e.ts for e in w.events] for w in out}
    assert spans[(0, 10_000)] == [7_000]
    assert spans[(5_000, 15_000)] == [7_000, 12_000]
    assert spans[(10_000, 20_000)] == [12_000]
    assert (15_000, 25_000) in spans and (20_000, 30_000) in spans


def test_late_drop_counted_per_event_not_per_window():
    """Flink late-side-output semantics: one late event = one drop, even
    when it maps to several expired sliding windows; an event that still
    lands in any live window is not dropped (ADVICE round-1 finding)."""
    asm = WindowAssembler(
        SlidingEventTimeWindows(10_000, 2_000), timestamp_fn=lambda e: e.ts
    )
    asm.feed(Ev(1_000))
    asm.feed(Ev(40_000))  # watermark far ahead; windows of ts=1000 expired
    asm.feed(Ev(1_500))   # late: belongs to 5 expired windows → ONE drop
    assert asm.dropped_late == 1
    # ts=33_000 has expired windows (e.g. [24000,34000)) AND live ones
    # ([26000,36000)+) — landing in a live window means NOT dropped.
    asm.feed(Ev(33_000))
    assert asm.dropped_late == 1


def test_count_windows():
    cw = CountWindows(2, 1)
    buf = []
    fired = []
    for i in range(4):
        fired += cw.feed(buf, i)
    assert fired == [[0, 1], [1, 2], [2, 3]]
    cw2 = CountWindows(2)
    buf2, fired2 = [], []
    for i in range(5):
        fired2 += cw2.feed(buf2, i)
    assert fired2 == [[0, 1], [2, 3]]
