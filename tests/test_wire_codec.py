"""Delta-bitpacked wire-pane codec (ops/wire_codec.py) — the round trip
must be BIT-exact for every input: the codec is allowed to change bytes
on the wire, never results. Property tests cover the regimes the design
calls out (slow random walks = the SNCB GPS regime, incompressible
uniform panes, empty/gap panes, wraparound teleports), the host/device
predictor-table lockstep, the np reference twin, the ladder-bounded
compiled-shape contract, and the Pallas extraction's self-check."""

import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from spatialflink_tpu.ops import wire_codec as wc  # noqa: E402
from spatialflink_tpu.telemetry import telemetry  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


def _device_decode(enc, px, py, *, n_bucket=None, extract=None):
    """One jitted decode at a bucket; returns (pane(3, nb), px2, py2)
    as numpy."""
    nb = n_bucket or max(8, enc.n)
    wb = max(wc.WORD_BUCKET_MIN, len(enc.words))
    step = jax.jit(wc.functools_partial_decode(
        extract or wc.extract_streams, n=nb, num_segments=len(px),
    ))
    pane, px2, py2 = step(
        jnp.asarray(wc.pad_words(enc.words, wb)), jnp.int32(enc.n),
        jnp.int32(enc.bx), jnp.int32(enc.by), jnp.int32(enc.bo),
        jnp.asarray(px), jnp.asarray(py),
    )
    return np.asarray(pane), np.asarray(px2), np.asarray(py2)


def _random_walk_panes(rng, nseg=37, n_panes=12, max_n=60, step=5,
                       teleport_at=None):
    """Pane stream in the slow-moving regime: per-oid random walk of
    ±``step`` lattice cells, optional teleport."""
    pos = rng.integers(0, 65536, (nseg, 2)).astype(np.int64)
    panes = []
    for i in range(n_panes):
        n = int(rng.integers(0, max_n))
        oids = rng.integers(0, nseg, n)
        pos[oids] = (pos[oids] + rng.integers(-step, step + 1,
                                              (n, 2))) % 65536
        if teleport_at is not None and i == teleport_at and n:
            pos[oids[0]] = rng.integers(0, 65536, 2)
        panes.append(np.stack([
            pos[oids, 0].astype(np.uint16),
            pos[oids, 1].astype(np.uint16),
            oids.astype(np.uint16),
        ]))
    return panes


class TestBitPacking:
    def test_pack_unpack_roundtrip_all_widths(self, rng):
        for b in range(17):
            n = int(rng.integers(0, 200))
            vals = rng.integers(0, 1 << b if b else 1, n).astype(
                np.uint32)
            words = wc.pack_bits(vals, b)
            assert words.dtype == np.uint32
            assert len(words) == (0 if b == 0 or n == 0
                                  else -((-n * b) // 32))
            back = wc.unpack_bits_np(words, n, b)
            assert np.array_equal(back, vals), b

    def test_device_extraction_matches_np(self, rng):
        """The jnp extraction and the np twin read identical fields at
        every (offset, width) alignment."""
        for b in (1, 3, 7, 8, 11, 16):
            n = 77
            vals = rng.integers(0, 1 << b, n).astype(np.uint32)
            words = wc.pack_bits(vals, b)
            wb = max(wc.WORD_BUCKET_MIN, len(words))
            got = jax.jit(
                lambda w, nv, bb: wc.extract_streams(
                    w, nv, bb, jnp.int32(0), jnp.int32(0), n=128
                )[0]
            )(jnp.asarray(wc.pad_words(words, wb)), jnp.int32(n),
              jnp.int32(b))
            assert np.array_equal(np.asarray(got)[:n], vals), b


class TestRoundTrip:
    def test_random_walk_bit_exact_with_predictor_lockstep(self, rng):
        """The SNCB regime: every pane decodes bit-identically AND the
        device predictor tables track the host encoder's mirror."""
        nseg = 37
        enc = wc.WirePaneEncoder(nseg)
        px = np.zeros(nseg, np.uint16)
        py = np.zeros(nseg, np.uint16)
        for pane in _random_walk_panes(rng, nseg, teleport_at=7):
            e = enc.encode(pane)
            out, px, py = _device_decode(e, px, py,
                                         n_bucket=max(8, e.n))
            assert np.array_equal(out[:, :e.n], pane)
            assert np.all(out[:, e.n:] == 0)  # padding lanes zeroed
            assert np.array_equal(px, enc.pred_x)
            assert np.array_equal(py, enc.pred_y)

    def test_slow_walk_actually_compresses(self, rng):
        """After warmup (tables populated) a ±5-step walk costs far
        fewer bits than raw — the design's reason to exist. Pane 0
        seeds every oid so later panes are pure walk (no never-seen
        full-width records)."""
        nseg = 64
        enc = wc.WirePaneEncoder(nseg)
        pos = rng.integers(0, 65536, (nseg, 2)).astype(np.int64)
        seed = np.stack([
            pos[:, 0].astype(np.uint16), pos[:, 1].astype(np.uint16),
            np.arange(nseg, dtype=np.uint16),
        ])
        enc.encode(seed)
        warm = []
        for _ in range(8):
            n = 40
            oids = rng.integers(0, nseg, n)
            pos[oids] = (pos[oids]
                         + rng.integers(-5, 6, (n, 2))) % 65536
            warm.append(enc.encode(np.stack([
                pos[oids, 0].astype(np.uint16),
                pos[oids, 1].astype(np.uint16),
                oids.astype(np.uint16),
            ])))
        for e in warm:
            assert e.coded_bytes < e.raw_bytes, (e.n, e.coded_bytes)
            # steady-state widths: zigzag(±5) needs ≤ 4 bits
            assert e.bx <= 4 and e.by <= 4, (e.bx, e.by)

    def test_incompressible_pane_worst_case_bounded(self, rng):
        """Uniform-random coords: still bit-exact, and the worst case
        is raw width + the header + word-alignment slack."""
        nseg = 512
        enc = wc.WirePaneEncoder(nseg)
        n = 300
        pane = np.stack([
            rng.integers(0, 65536, n).astype(np.uint16),
            rng.integers(0, 65536, n).astype(np.uint16),
            rng.integers(0, nseg, n).astype(np.uint16),
        ])
        e = enc.encode(pane)
        out, _, _ = _device_decode(e, np.zeros(nseg, np.uint16),
                                   np.zeros(nseg, np.uint16),
                                   n_bucket=512)
        assert np.array_equal(out[:, :n], pane)
        assert e.coded_bytes <= e.raw_bytes + wc.HEADER_BYTES + 3 * 4

    def test_empty_pane(self):
        enc = wc.WirePaneEncoder(8)
        e = enc.encode(np.zeros((3, 0), np.uint16))
        assert (e.n, e.bx, e.by, e.bo) == (0, 0, 0, 0)
        assert e.raw_bytes == 0 and e.coded_bytes == wc.HEADER_BYTES
        px = np.arange(8, dtype=np.uint16)
        py = px + 1
        out, px2, py2 = _device_decode(e, px, py, n_bucket=8)
        assert np.all(out == 0)
        # predictor tables untouched by an empty pane
        assert np.array_equal(px2, px) and np.array_equal(py2, py)

    def test_wraparound_edges_exact(self):
        """mod-2^16 deltas at the extremes: 0↔65535, ±32768 — the
        zigzag/wraparound arithmetic must be exact everywhere."""
        enc = wc.WirePaneEncoder(4)
        first = np.stack([
            np.asarray([0, 65535, 32768, 1], np.uint16),
            np.asarray([65535, 0, 1, 32768], np.uint16),
            np.asarray([0, 1, 2, 3], np.uint16),
        ])
        second = np.stack([
            np.asarray([65535, 0, 0, 32769], np.uint16),  # max deltas
            np.asarray([0, 65535, 32769, 0], np.uint16),
            np.asarray([0, 1, 2, 3], np.uint16),
        ])
        px = np.zeros(4, np.uint16)
        py = np.zeros(4, np.uint16)
        for pane in (first, second):
            e = enc.encode(pane)
            out, px, py = _device_decode(e, px, py, n_bucket=8)
            assert np.array_equal(out[:, :4], pane)

    def test_duplicate_oids_last_occurrence_wins(self):
        """A pane with one oid appearing twice: both sides must keep
        the LAST position as the next pane's predictor."""
        enc = wc.WirePaneEncoder(4)
        pane = np.stack([
            np.asarray([100, 200], np.uint16),
            np.asarray([300, 400], np.uint16),
            np.asarray([2, 2], np.uint16),
        ])
        e = enc.encode(pane)
        out, px, py = _device_decode(e, np.zeros(4, np.uint16),
                                     np.zeros(4, np.uint16), n_bucket=8)
        assert np.array_equal(out[:, :2], pane)
        assert enc.pred_x[2] == 200 and enc.pred_y[2] == 400
        assert px[2] == 200 and py[2] == 400

    def test_np_twin_matches_device(self, rng):
        nseg = 16
        enc = wc.WirePaneEncoder(nseg)
        npx = np.zeros(nseg, np.uint16)
        npy = np.zeros(nseg, np.uint16)
        dpx = npx.copy()
        dpy = npy.copy()
        for pane in _random_walk_panes(rng, nseg, n_panes=6, max_n=30):
            e = enc.encode(pane)
            d_pane, dpx, dpy = _device_decode(e, dpx, dpy,
                                              n_bucket=max(8, e.n))
            if e.n:
                n_pane, npx, npy = wc.decode_wire_pane_np(e, npx, npy)
                assert np.array_equal(n_pane, d_pane[:, :e.n])
                assert np.array_equal(npx, dpx)
                assert np.array_equal(npy, dpy)


class TestContracts:
    def test_encoder_rejects_out_of_range_oid(self):
        enc = wc.WirePaneEncoder(4)
        pane = np.stack([np.zeros(1, np.uint16), np.zeros(1, np.uint16),
                         np.asarray([7], np.uint16)])
        with pytest.raises(ValueError, match="num_segments"):
            enc.encode(pane)

    def test_encoder_rejects_bad_shape(self):
        with pytest.raises(ValueError, match="plane-major"):
            wc.WirePaneEncoder(4).encode(np.zeros((2, 5), np.uint16))

    def test_state_restore_roundtrip_and_mismatch(self, rng):
        enc = wc.WirePaneEncoder(8)
        enc.encode(np.stack([
            rng.integers(0, 65536, 5).astype(np.uint16),
            rng.integers(0, 65536, 5).astype(np.uint16),
            rng.integers(0, 8, 5).astype(np.uint16),
        ]))
        st = enc.state()
        enc2 = wc.WirePaneEncoder(8)
        enc2.restore(st)
        assert np.array_equal(enc2.pred_x, enc.pred_x)
        assert np.array_equal(enc2.pred_y, enc.pred_y)
        with pytest.raises(ValueError, match="num_segments"):
            wc.WirePaneEncoder(16).restore(st)

    def test_word_bucket_ladder_bounds_compiled_shapes(self, rng):
        """Any mix of pane compressibilities buckets into ≤rung-many
        word counts PER PANE BUCKET (the recompile-surface contract),
        with padding overhead bounded by one rung (~6% of worst case —
        a pow2 ladder could pad ~2x and ship MORE than raw)."""
        telemetry.enable()
        try:
            for nb in (256, 1024):
                worst = 3 * ((nb * 16 + 31) >> 5)
                buckets = set()
                for w in rng.integers(0, worst + 1, 300):
                    b = wc.wire_word_bucket(int(w), nb)
                    assert b >= int(w)
                    assert b - int(w) <= max(
                        wc.WORD_BUCKET_MIN,
                        -(-worst // wc.WORD_LADDER_RUNGS),
                    )
                    buckets.add(b)
                assert len(buckets) <= wc.WORD_LADDER_RUNGS + 1
            logged = telemetry.compaction_buckets("wire_codec_words")
            assert logged  # picks recorded like the pane ladder's
        finally:
            telemetry.disable()

    def test_select_wire_decoder_cpu_default_is_jnp(self):
        kind, fn = wc.select_wire_decoder("auto")
        assert kind == "jnp" and fn is wc.extract_streams
        kind, fn = wc.select_wire_decoder("jnp")
        assert kind == "jnp"


class TestPallasExtraction:
    def test_interpret_mode_agrees_bit_exact(self, rng):
        """The Pallas extraction (interpret mode on CPU) must decode a
        sample pane bit-identically — the adoption self-check."""
        nseg = 32
        enc = wc.WirePaneEncoder(nseg)
        pane = _random_walk_panes(rng, nseg, n_panes=1, max_n=50)[0]
        e = enc.encode(pane)
        if e.n == 0:  # pragma: no cover - rng safeguard
            pytest.skip("empty sample pane")
        px = np.zeros(nseg, np.uint16)
        py = np.zeros(nseg, np.uint16)
        pallas_extract = wc.make_pallas_extract(interpret=True)
        a = _device_decode(e, px, py, n_bucket=64,
                           extract=pallas_extract)
        b = _device_decode(e, px, py, n_bucket=64)
        for xa, xb in zip(a, b):
            assert np.array_equal(xa, xb)

    def test_select_adopts_pallas_under_interpret_with_self_check(
            self, rng):
        nseg = 16
        enc = wc.WirePaneEncoder(nseg)
        pane = _random_walk_panes(rng, nseg, n_panes=1, max_n=30)[0]
        e = enc.encode(pane)
        wb = max(wc.WORD_BUCKET_MIN, len(e.words))
        sample = (
            jnp.asarray(wc.pad_words(e.words, wb)), jnp.int32(e.n),
            jnp.int32(e.bx), jnp.int32(e.by), jnp.int32(e.bo),
            jnp.zeros(nseg, jnp.uint16), jnp.zeros(nseg, jnp.uint16),
        )
        kind, _fn = wc.select_wire_decoder(
            "pallas", interpret=True, sample_args=sample, n=64,
            num_segments=nseg,
        )
        assert kind == "pallas"
