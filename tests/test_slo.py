"""Online SLO engine tests (spatialflink_tpu/slo.py): strict spec
parsing, incremental evaluation from gauge deltas, violation events into
the telemetry buffer/stream, the verdict block, the window-fire hook in
both assemblers, and the live↔post-hoc twin contract with
tools/sfprof/slo.py."""

import dataclasses
import json

import numpy as np
import pytest

from spatialflink_tpu import slo
from spatialflink_tpu.streams.soa import SoaWindowAssembler
from spatialflink_tpu.streams.windows import (
    TumblingEventTimeWindows,
    WindowAssembler,
)
from spatialflink_tpu.models.objects import Point
from spatialflink_tpu.telemetry import telemetry
from tools.sfprof import slo as sfprof_slo


@pytest.fixture(autouse=True)
def _clean():
    """Every test leaves the module slot empty and telemetry disabled +
    reset (same discipline as test_sfprof.py's fixture)."""
    yield
    slo.uninstall()
    telemetry.enable()
    telemetry.disable()


def _spec(**kw):
    kw.setdefault("eval_interval_s", 0.0)  # evaluate on every window
    kw.setdefault("warmup_windows", 0)
    return slo.SloSpec(**kw)


# -- spec parsing -------------------------------------------------------------


def test_spec_from_dict_strict():
    sp = slo.SloSpec.from_dict(
        {"name": "q", "eps_floor": 100.0, "late_drop_budget": 0}
    )
    assert sp.eps_floor == 100.0
    assert sp.watermark_lag_p99_ms is None  # absent = unchecked
    with pytest.raises(ValueError, match="unknown SLO spec keys"):
        slo.SloSpec.from_dict({"eps_flor": 1.0})  # the typo must raise
    with pytest.raises(ValueError, match="slo_version"):
        slo.SloSpec.from_dict({"slo_version": 99})


def test_spec_file_roundtrip(tmp_path):
    p = tmp_path / "spec.json"
    p.write_text(json.dumps({"slo_version": slo.SLO_VERSION,
                             "name": "smoke", "recompile_ceiling": 24}))
    sp = slo.SloSpec.from_file(str(p))
    assert sp.recompile_ceiling == 24
    assert sp.to_dict()["slo_version"] == slo.SLO_VERSION


def test_spec_twin_constants_and_fields_in_sync():
    """Live engine (spatialflink_tpu/slo.py) and post-hoc evaluator
    (tools/sfprof/slo.py) deliberately don't import each other — this is
    the cross-pin: same version, same field set."""
    assert slo.SLO_VERSION == sfprof_slo.SLO_VERSION
    live_fields = {f.name for f in dataclasses.fields(slo.SloSpec)}
    assert live_fields == set(sfprof_slo.SPEC_KEYS)


# -- incremental evaluation ---------------------------------------------------


def test_lag_p99_violation_is_a_transition_not_a_spam():
    telemetry.enable()
    eng = slo.install(slo.SloEngine(_spec(watermark_lag_p99_ms=8.0)))
    for _ in range(20):
        eng.observe_window(10, lag_ms=1.0)
    assert eng.violations == []
    for _ in range(200):  # push p99 over the ceiling, many evaluations
        eng.observe_window(10, lag_ms=5000.0)
    checks = {r["check"]: r for r in eng.evaluate()}
    assert not checks["watermark_lag_p99_ms"]["ok"]
    # One violation record for the whole stall, not one per window.
    assert [v["check"] for v in eng.violations] == ["watermark_lag_p99_ms"]
    # The structured event landed in the telemetry buffer.
    names = [e["name"] for e in telemetry.events]
    assert "slo_violation:watermark_lag_p99_ms" in names
    assert eng.verdict()["ok"] is False


def test_eps_clock_starts_at_first_window_not_install():
    """The EPS denominator must exclude pre-window dead time (XLA
    warm-up, probe samples): a floor the real window rate clears must
    not violate just because the engine was installed early."""
    import time

    telemetry.enable()
    eng = slo.SloEngine(_spec(eps_floor=100_000.0, warmup_windows=0))
    assert eng._t0 is None  # clock not running yet
    time.sleep(0.06)  # "warm-up": would drag EPS under the floor if
    # the clock had started at construction (1000 pts / 0.06 s ≈ 17k)
    eng.observe_window(500)
    eng.observe_window(500)
    rows = {r["check"]: r for r in eng.evaluate()}
    assert rows["eps_floor"]["ok"], rows["eps_floor"]
    assert eng.violations == []


def test_eps_floor_respects_warmup_then_violates():
    telemetry.enable()
    eng = slo.SloEngine(_spec(eps_floor=1e15, warmup_windows=5))
    for _ in range(5):
        eng.observe_window(10)
    assert all(r["check"] != "eps_floor" for r in eng.evaluate())
    eng.observe_window(10)  # past warmup: the impossible floor trips
    rows = {r["check"]: r for r in eng.evaluate()}
    assert not rows["eps_floor"]["ok"]
    assert eng.violations and eng.violations[0]["check"] == "eps_floor"


def test_budget_checks_read_telemetry_gauges():
    telemetry.enable()
    eng = slo.SloEngine(_spec(late_drop_budget=1, recompile_ceiling=0))
    telemetry.record_late_drop(2)
    telemetry.record_jit_call("k", ((4,),))
    rows = {r["check"]: r for r in eng.evaluate()}
    assert not rows["late_drop_budget"]["ok"]
    assert rows["late_drop_budget"]["value"] == 2
    assert not rows["recompile_ceiling"]["ok"]
    v = eng.verdict()
    assert {x["check"] for x in v["violations"]} == {
        "late_drop_budget", "recompile_ceiling"}
    json.dumps(v)  # verdict block is strictly JSON-safe


def test_recovery_transition_emits_event_but_keeps_violation():
    telemetry.enable()
    eng = slo.install(slo.SloEngine(_spec(late_drop_budget=0)))
    telemetry.record_late_drop(1)
    eng.evaluate()
    assert len(eng.violations) == 1
    # The gauge can't go back down in telemetry, so emulate recovery by
    # raising the budget via a fresh spec on the same engine state.
    eng.spec = _spec(late_drop_budget=5)
    eng.evaluate()
    names = [e["name"] for e in telemetry.events]
    assert "slo_recovered:late_drop_budget" in names
    # The verdict is about the RUN: the violation stays recorded.
    assert eng.verdict()["ok"] is False


def test_compliant_run_verdict_ok():
    telemetry.enable()
    eng = slo.install(slo.SloEngine(_spec(
        watermark_lag_p99_ms=10_000, eps_floor=0.001,
        late_drop_budget=0, overflow_budget=0, recompile_ceiling=64,
    )))
    for _ in range(10):
        eng.observe_window(1000, lag_ms=1.0)
    v = eng.verdict()
    assert v["ok"] is True and v["violations"] == []
    assert v["windows"] == 10 and v["points"] == 10_000


# -- window-fire hook ---------------------------------------------------------


def test_hook_free_when_no_engine_installed():
    assert slo.engine() is None
    slo.on_window_fired(100, lag_ms=5.0)  # must be a no-op, no raise


def test_object_assembler_feeds_engine():
    telemetry.enable()
    eng = slo.install(slo.SloEngine(_spec()))
    asm = WindowAssembler(
        TumblingEventTimeWindows(10), timestamp_fn=lambda e: e.timestamp
    )
    asm.feed(Point(obj_id="a", timestamp=1, x=0.0, y=0.0))
    asm.feed(Point(obj_id="a", timestamp=25, x=0.0, y=0.0))  # fires [0,10)
    assert eng.windows == 1
    assert eng.points == 1  # the one event buffered in the fired window
    assert eng.lag.count == 1  # lag observed at the same fire site


def test_soa_assembler_feeds_engine():
    telemetry.enable()
    eng = slo.install(slo.SloEngine(_spec()))
    asm = SoaWindowAssembler(10, 5)
    chunk = {
        "ts": np.asarray([1, 3, 9], np.int64),
        "x": np.zeros(3), "y": np.zeros(3),
        "oid": np.zeros(3, np.int32),
    }
    asm.feed(chunk)
    asm.feed({"ts": np.asarray([27], np.int64), "x": np.zeros(1),
              "y": np.zeros(1), "oid": np.zeros(1, np.int32)})
    assert eng.windows >= 1
    assert eng.points >= 3
    # flush()'s artificial watermark must not feed the engine's lag
    # histogram (same contract as the telemetry gauge).
    before = eng.lag.count
    asm.flush()
    assert eng.lag.count == before


# -- ledger integration -------------------------------------------------------


def test_installed_engine_verdict_rides_ledger_and_health_slo(tmp_path):
    telemetry.enable()
    slo.install(slo.SloEngine(_spec(eps_floor=1e15, warmup_windows=0)))
    eng = slo.engine()
    for _ in range(3):
        eng.observe_window(1)
    path = str(tmp_path / "ledger.json")
    telemetry.write_ledger(path, bench={"value": 1.0,
                                        "points_per_sec": 1.0})
    with open(path) as f:
        doc = json.load(f)
    assert doc["slo"]["ok"] is False
    assert doc["slo"]["spec"]["eps_floor"] == 1e15

    from tools.sfprof.cli import main as sfprof_main

    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps({"name": "gate", "eps_floor": 1e15}))
    # Violated live AND post-hoc: the same spec fails health --slo.
    assert sfprof_main(["health", path, "--slo", str(spec_path)]) == 1
    # Without --slo the embedded verdict does not gate plain health.
    assert sfprof_main(["health", path]) == 0
    # A compliant spec still fails: the LIVE verdict recorded violations.
    ok_spec = tmp_path / "ok.json"
    ok_spec.write_text(json.dumps({"name": "gate", "late_drop_budget": 9}))
    assert sfprof_main(["health", path, "--slo", str(ok_spec)]) == 1


def test_posthoc_evaluate_matches_live_semantics(tmp_path):
    """Post-hoc eps answers come from bench points_per_sec/value; a spec
    naming a floor the ledger cannot answer FAILS (silence never
    passes)."""
    spec = {"name": "x", "eps_floor": 100.0}
    doc = {"snapshot": {}, "bench": {"points_per_sec": 250.0}}
    rows = {r[0]: r for r in sfprof_slo.evaluate(spec, doc)}
    assert rows["slo:eps_floor"][3] is True
    doc_silent = {"snapshot": {}, "bench": {}}
    rows = {r[0]: r for r in sfprof_slo.evaluate(spec, doc_silent)}
    assert rows["slo:eps_floor"][3] is False
    # Lag falls back to the max gauge (an upper bound: stricter, never
    # laxer) when the p99 histogram is absent.
    spec = {"name": "x", "watermark_lag_p99_ms": 10.0}
    doc = {"snapshot": {"max_watermark_lag_ms": 50}, "bench": None}
    rows = {r[0]: r for r in sfprof_slo.evaluate(spec, doc)}
    assert rows["slo:watermark_lag_p99_ms"][3] is False


def test_driver_budgets_live_and_posthoc_twin():
    """ISSUE 8: retry_budget/failover_budget — the live engine reads the
    telemetry driver counters; the post-hoc twin reads the ledger's
    snapshot.driver block; a spec naming them against a pre-driver
    ledger fails on silence (the eps_floor rule)."""
    from spatialflink_tpu.telemetry import telemetry
    from tools.sfprof import slo as sfprof_slo

    telemetry.enable()
    try:
        telemetry.record_driver_retry(0, 1, "err")
        telemetry.record_driver_failover(0, "err")
        eng = slo.SloEngine(slo.SloSpec(retry_budget=1, failover_budget=0,
                                        eval_interval_s=0.0))
        rows = {r["check"]: r for r in eng.evaluate()}
        assert rows["retry_budget"]["ok"] is True
        assert rows["failover_budget"]["ok"] is False

        doc = {"snapshot": telemetry.snapshot(), "bench": {}}
        prows = dict(
            (name, ok) for name, _v, _b, ok in sfprof_slo.evaluate(
                {"retry_budget": 1, "failover_budget": 0}, doc)
        )
        assert prows["slo:retry_budget"] is True
        assert prows["slo:failover_budget"] is False
        # silence fails: a ledger without the driver block cannot pass
        srows = sfprof_slo.evaluate({"failover_budget": 5},
                                    {"snapshot": {}, "bench": {}})
        assert srows[0][3] is False
    finally:
        telemetry.disable()


# -- latency lineage (e2e ceilings) -------------------------------------------


def test_e2e_ceiling_live_warmup_grace_then_silence_fails():
    """ISSUE 19: e2e_p50/p99_ms read the telemetry commit-stage lineage
    percentiles. During warm-up the check is skipped (the eps_floor
    grace — no window has had a chance to commit); past warm-up a run
    that never stamped a commit leaves the ceiling unanswerable and
    silence FAILS."""
    telemetry.enable()
    eng = slo.SloEngine(_spec(e2e_p99_ms=1e9, warmup_windows=2))
    eng.observe_window(10)
    assert all(r["check"] != "e2e_p99_ms" for r in eng.evaluate())
    for _ in range(3):  # past warm-up now, still no commit stamp
        eng.observe_window(10)
    rows = {r["check"]: r for r in eng.evaluate()}
    assert rows["e2e_p99_ms"]["ok"] is False
    assert rows["e2e_p99_ms"]["value"] is None


def test_e2e_ceiling_live_pass_and_deterministic_violation():
    telemetry.enable()
    eng = slo.SloEngine(_spec(e2e_p99_ms=1e9))
    eng.observe_window(10)
    # Anchor the lineage clock at event-time 10_000 ms, then commit a
    # window whose event time is 10 s in the PAST: its anchored
    # staleness is ≈10 s regardless of wall speed — deterministic.
    telemetry.record_e2e(10_000, "commit")
    rows = {r["check"]: r for r in eng.evaluate()}
    assert rows["e2e_p99_ms"]["ok"] is True  # huge ceiling clears
    telemetry.record_e2e(0, "commit")
    eng2 = slo.SloEngine(_spec(e2e_p99_ms=1_000))
    eng2.observe_window(10)
    rows = {r["check"]: r for r in eng2.evaluate()}
    assert rows["e2e_p99_ms"]["ok"] is False
    assert rows["e2e_p99_ms"]["value"] >= 9_000.0


def test_node_e2e_budget_silence_fails_after_warmup():
    """node_budgets e2e keys: no DAG installed → unanswerable → FAIL
    past warm-up; skipped (not failed) during warm-up."""
    telemetry.enable()
    eng = slo.SloEngine(_spec(
        node_budgets={"q1": {"e2e_p99_ms": 5}}, warmup_windows=1))
    eng.observe_window(10)
    assert all(not r["check"].startswith("node_e2e")
               for r in eng.evaluate())
    eng.observe_window(10)  # past warm-up
    rows = {r["check"]: r for r in eng.evaluate()}
    assert rows["node_e2e_p99_ms:q1"]["ok"] is False
    assert rows["node_e2e_p99_ms:q1"]["value"] is None


def test_e2e_spec_parses_and_posthoc_twin_matches():
    """The same spec keys round-trip from_dict (NODE_BUDGET_KEYS knows
    the e2e ceilings) and the post-hoc twin reads the ledger's
    snapshot.e2e.stages.commit / dag.nodes.<n>.e2e_p99_ms — silence
    fails on both surfaces."""
    sp = slo.SloSpec.from_dict({
        "e2e_p50_ms": 50.0, "e2e_p99_ms": 200.0,
        "node_budgets": {"q1": {"e2e_p99_ms": 5}},
    })
    assert sp.e2e_p50_ms == 50.0 and sp.e2e_p99_ms == 200.0
    with pytest.raises(ValueError):
        slo.SloSpec.from_dict({"node_budgets": {"q1": {"e2e_p99_mss": 5}}})

    doc = {"snapshot": {
        "e2e": {"stages": {"commit": {"p50_ms": 10.0, "p99_ms": 100.0,
                                      "count": 4, "sum_ms": 40.0}}},
        "dag": {"nodes": {"q1": {"e2e_p99_ms": 3.0}}},
    }, "bench": {}}
    spec = {"e2e_p50_ms": 50.0, "e2e_p99_ms": 50.0,
            "node_budgets": {"q1": {"e2e_p99_ms": 5}}}
    rows = {r[0]: r for r in sfprof_slo.evaluate(spec, doc)}
    assert rows["slo:e2e_p50_ms"][3] is True      # 10 <= 50
    assert rows["slo:e2e_p99_ms"][3] is False     # 100 > 50
    assert rows["slo:node_e2e_p99_ms:q1"][3] is True
    # Silence fails: no e2e block, no dag block.
    srows = {r[0]: r for r in sfprof_slo.evaluate(
        spec, {"snapshot": {}, "bench": {}})}
    assert srows["slo:e2e_p99_ms"][3] is False
    assert srows["slo:node_e2e_p99_ms:q1"][3] is False
