"""Tier-1 call-graph units (tools/sfcheck/{project,callgraph}): fact
extraction, cross-file call resolution (bare names, aliased module
imports, from-imports, methods incl. inheritance, nested defs), the
jit-boundary classification (device entries / device-reachable / hot
per-window reachability with parent chains), and taint extraction."""

import ast
import os
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.sfcheck.callgraph import CallGraph  # noqa: E402
from tools.sfcheck.project import (  # noqa: E402
    Project,
    extract_facts,
    facts_from_dict,
    module_name_of,
)


def _project(files: dict):
    p = Project()
    for rel, src in files.items():
        src = textwrap.dedent(src)
        p.add(extract_facts(rel, ast.parse(src), src))
    return p, CallGraph(p)


# -- module naming / facts ---------------------------------------------------

def test_module_name_of():
    assert module_name_of("a/b/c.py") == "a.b.c"
    assert module_name_of("a/b/__init__.py") == "a.b"
    assert module_name_of("top.py") == "top"


def test_facts_roundtrip_preserves_calls():
    src = "def f():\n    g(1)\n\ndef g(x):\n    return x\n"
    facts = extract_facts("m.py", ast.parse(src), src)
    back = facts_from_dict(facts.to_dict())
    assert len(back.functions["f"].calls) == 1
    assert back.functions["f"].calls[0].target == "g"
    # and the source dict is NOT mutated by reconstruction (cache re-save)
    d = facts.to_dict()
    facts_from_dict(d)
    assert d["functions"]["f"]["calls"], "cache entry gutted by from_dict"


# -- resolution --------------------------------------------------------------

def test_bare_name_resolves_in_module():
    p, g = _project({"m.py": """
        def helper():
            pass
        def caller():
            helper()
    """})
    assert (("m.py", "helper"), 5) in [
        (r, ln) for r, ln in g.edges[("m.py", "caller")]
    ]


def test_from_import_resolves_cross_file():
    p, g = _project({
        "pkg/util.py": "def helper():\n    pass\n",
        "pkg/main.py": """
            from pkg.util import helper
            def caller():
                helper()
        """,
    })
    assert [r for r, _ in g.edges[("pkg/main.py", "caller")]] == \
        [("pkg/util.py", "helper")]


def test_aliased_module_import_resolves():
    p, g = _project({
        "pkg/util.py": "def helper():\n    pass\n",
        "pkg/main.py": """
            import pkg.util as u
            def caller():
                u.helper()
        """,
    })
    assert [r for r, _ in g.edges[("pkg/main.py", "caller")]] == \
        [("pkg/util.py", "helper")]


def test_aliased_from_import_resolves():
    p, g = _project({
        "pkg/util.py": "def helper():\n    pass\n",
        "pkg/main.py": """
            from pkg.util import helper as h
            def caller():
                h()
        """,
    })
    assert [r for r, _ in g.edges[("pkg/main.py", "caller")]] == \
        [("pkg/util.py", "helper")]


def test_self_method_resolves_through_base_class():
    p, g = _project({
        "base.py": """
            class Base:
                def shared(self):
                    pass
        """,
        "sub.py": """
            from base import Base
            class Sub(Base):
                def run(self):
                    self.shared()
        """,
    })
    assert [r for r, _ in g.edges[("sub.py", "Sub.run")]] == \
        [("base.py", "Base.shared")]


def test_unique_method_name_heuristic():
    # method call on an unknown receiver resolves iff exactly one class
    # project-wide defines it
    p, g = _project({
        "a.py": """
            class Telemetry:
                def record(self):
                    pass
        """,
        "b.py": """
            def caller(t):
                t.record()
        """,
    })
    assert [r for r, _ in g.edges[("b.py", "caller")]] == \
        [("a.py", "Telemetry.record")]
    # ambiguous (two classes define it) -> no edge
    p2, g2 = _project({
        "a.py": "class A:\n    def record(self):\n        pass\n",
        "c.py": "class C:\n    def record(self):\n        pass\n",
        "b.py": "def caller(t):\n    t.record()\n",
    })
    assert g2.edges[("b.py", "caller")] == []


def test_nested_def_resolves_before_module_scope():
    p, g = _project({"m.py": """
        def helper():
            pass
        def outer():
            def helper():
                pass
            helper()
    """})
    assert [r for r, _ in g.edges[("m.py", "outer")]] == \
        [("m.py", "outer.helper")]


# -- jit-boundary classification ---------------------------------------------

def test_decorated_def_is_device_entry():
    p, g = _project({"m.py": """
        import jax
        @jax.jit
        def kernel(x):
            return x
    """})
    assert ("m.py", "kernel") in g.device_entries


def test_partial_jit_decorator_is_device_entry():
    p, g = _project({"m.py": """
        import functools
        import jax
        @functools.partial(jax.jit, static_argnames=("k",))
        def kernel(x, k):
            return x
    """})
    assert ("m.py", "kernel") in g.device_entries


def test_fn_passed_to_jit_wrapper_is_device_entry_and_callees_reachable():
    p, g = _project({"m.py": """
        import jax
        def inner(x):
            return x
        def kernel(x):
            return inner(x)
        prog = jax.jit(kernel)
    """})
    assert ("m.py", "kernel") in g.device_entries
    assert g.is_device("m.py", "inner")          # transitively traced
    assert not g.is_device("m.py", "<module>")


def test_shard_map_closure_is_device():
    p, g = _project({"m.py": """
        from spatialflink_tpu.utils.shardmap_compat import shard_map
        def wrapper(mesh, x):
            def local(x_l):
                return x_l
            return shard_map(local, mesh=mesh)(x)
    """})
    assert ("m.py", "wrapper.local") in g.device_entries


def test_builtin_map_is_not_a_jit_wrapper():
    p, g = _project({"m.py": """
        def f(x):
            return x
        def caller(xs):
            return list(map(f, xs))
    """})
    assert ("m.py", "f") not in g.device_entries


def test_window_loop_hot_chain_two_hops():
    p, g = _project({"m.py": """
        def b():
            return 1
        def a():
            return b()
        def run(stream):
            for win in windows(stream):
                a()
    """})
    chain_a = g.hot_chain("m.py", "a")
    chain_b = g.hot_chain("m.py", "b")
    assert chain_a is not None and len(chain_a) == 1
    assert "per-window loop" in chain_a[0].note
    assert chain_b is not None and len(chain_b) == 2
    assert "`a` calls `b" in chain_b[1].note
    assert g.hot_chain("m.py", "run") is None    # the loop owner itself


def test_hot_does_not_cross_into_device_or_memoized():
    p, g = _project({"m.py": """
        import functools
        import jax
        @jax.jit
        def kernel(x):
            return x
        @functools.lru_cache(maxsize=None)
        def cached_const(n):
            return n
        def run(stream):
            for win in windows(stream):
                kernel(win)
                cached_const(8)
    """})
    assert g.hot_chain("m.py", "kernel") is None
    assert g.hot_chain("m.py", "cached_const") is None


# -- candidate-site extraction ----------------------------------------------

def test_eager_jnp_sites_exclude_ship_and_meta():
    src = textwrap.dedent("""
        import jax.numpy as jnp
        def f(x):
            a = jnp.asarray(x)      # ship: sanctioned
            b = jnp.finfo(a.dtype)  # metadata: free
            return jnp.sort(a)      # compute: eager site
    """)
    facts = extract_facts("m.py", ast.parse(src), src)
    sites = facts.functions["f"].eager_jnp
    assert [s["attr"] for s in sites] == ["sort"]


def test_shape_taint_len_and_sanitizer():
    src = textwrap.dedent("""
        import jax.numpy as jnp
        def bad(events):
            n = len(events)
            return jnp.zeros((n, 2))
        def good(events):
            n = len(events)
            b = next_bucket(n)
            return jnp.zeros((b, 2))
    """)
    facts = extract_facts("m.py", ast.parse(src), src)
    assert len(facts.functions["bad"].shape_sites) == 1
    assert "len(events)" in facts.functions["bad"].shape_sites[0]["src"]
    assert facts.functions["good"].shape_sites == []
