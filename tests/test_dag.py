"""The composed dataflow DAG (spatialflink_tpu/dag.py): topology,
per-node retry/failover/breaker independence, the atomic unit
checkpoint (multi-sink exactly-once), per-node SLO budgets (live +
sfprof twin), telemetry surfaces, and the streaming_job option-10
wiring."""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from spatialflink_tpu import dag as dag_mod  # noqa: E402
from spatialflink_tpu import overload, qserve  # noqa: E402
from spatialflink_tpu.checkpoint import (  # noqa: E402
    CheckpointCorruptError,
    load_checkpoint,
)
from spatialflink_tpu.dag import (  # noqa: E402
    DataflowDAG,
    FunctionNode,
    StayTimeNode,
    build_sncb_dag,
    _toy_sncb_stream,
)
from spatialflink_tpu.driver import (  # noqa: E402
    RetryPolicy,
    WindowedDataflowDriver,
)
from spatialflink_tpu.faults import InjectedFault, faults  # noqa: E402
from spatialflink_tpu.grid import UniformGrid  # noqa: E402
from spatialflink_tpu.models.objects import Point  # noqa: E402
from spatialflink_tpu.operators.query_config import (  # noqa: E402
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.streams.sinks import (  # noqa: E402
    MultiSink,
    TransactionalFileSink,
)
from spatialflink_tpu.telemetry import telemetry  # noqa: E402


@pytest.fixture(autouse=True)
def _clean():
    yield
    faults.disarm()
    telemetry.disable()
    dag_mod.uninstall()
    qserve.uninstall()
    overload.uninstall()


# ---------------------------------------------------------------------------
# Toy two-node function DAG (fast unit harness)


def _toy_conf():
    return QueryConfiguration(QueryType.WindowBased, window_size=2.0,
                              slide_step=1.0)


def _toy_points(n=60):
    rng = np.random.default_rng(5)
    xs = rng.uniform(0.0, 8.0, n)
    ys = rng.uniform(0.0, 8.0, n)
    return [
        Point(obj_id=f"o{i % 5}", timestamp=100 * i,
              x=float(xs[i]), y=float(ys[i]))
        for i in range(n)
    ]


def _count_node(name, fail_windows=(), fallback=True, upstream=None):
    """A node counting window events; optionally raising on the given
    window starts (device path only)."""

    def fn(win, results):
        if win.start in fail_windows:
            raise RuntimeError(f"boom@{win.start}")
        return ("device", len(win.events))

    def fb(win, results):
        return ("fallback", len(win.events))

    def render(result, start, end):
        yield f"{start},{end},{result[1]}"

    return FunctionNode(name, fn, fallback=fb if fallback else None,
                        render_fn=render, upstream=upstream)


def _toy_dag(tmp_path, nodes, **driver_kw):
    grid = UniformGrid(8, 0.0, 8.0, 0.0, 8.0)
    dag = DataflowDAG(_toy_conf(), grid, nodes,
                      out_dir=str(tmp_path / "egress"),
                      retry=RetryPolicy(max_retries=1, backoff_s=0.0,
                                        sleep=lambda s: None))
    return dag


class TestTopology:
    def test_upstream_orders_nodes_and_passes_results(self, tmp_path):
        seen = {}

        def up_fn(win, results):
            return len(win.events)

        def down_fn(win, results):
            seen[win.start] = results["up"]
            return results["up"] * 2

        up = FunctionNode("up", up_fn)
        down = FunctionNode("down", down_fn, upstream="up")
        # Constructed downstream-first: topo sort must still run `up`
        # before `down` every window.
        dag = _toy_dag(tmp_path, [down, up])
        assert dag.dag_nodes == ("up", "down")
        out = list(dag.run(iter(_toy_points())))
        assert out and seen
        for res in out:
            assert res.counts["up"] >= 1

    def test_cycle_and_unknown_upstream_are_loud(self, tmp_path):
        a = FunctionNode("a", lambda w, r: 1, upstream="b")
        b = FunctionNode("b", lambda w, r: 1, upstream="a")
        with pytest.raises(ValueError, match="cycle"):
            _toy_dag(tmp_path, [a, b])
        c = FunctionNode("c", lambda w, r: 1, upstream="ghost")
        with pytest.raises(ValueError, match="unknown upstream"):
            _toy_dag(tmp_path, [c])

    def test_duplicate_names_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="duplicate"):
            _toy_dag(tmp_path, [FunctionNode("x", lambda w, r: 1),
                                FunctionNode("x", lambda w, r: 2)])


class TestPerNodeSelfHealing:
    def test_failover_is_node_local(self, tmp_path):
        """One node's device path dies permanently → that node (and
        ONLY that node) runs its twin for the rest of the run; the
        sibling stays on device, results keep flowing on both sinks."""
        telemetry.enable()
        sick = _count_node("sick", fail_windows=range(-10**9, 10**9))
        healthy = _count_node("healthy")
        dag = _toy_dag(tmp_path, [sick, healthy])
        out = list(dag.run(iter(_toy_points())))
        assert len(out) > 3
        snap = dag.snapshot()
        assert snap["nodes"]["sick"]["backend"] == "fallback"
        assert snap["nodes"]["sick"]["failovers"] == 1
        assert snap["nodes"]["sick"]["degraded_windows"] == len(out)
        assert snap["nodes"]["healthy"]["backend"] == "device"
        assert snap["nodes"]["healthy"]["degraded_windows"] == 0
        # Retries preceded the failover (per-node ladder).
        assert snap["nodes"]["sick"]["retries"] == 1
        names = [e["name"] for e in telemetry.events]
        assert "dag_node_failover:sick" in names
        # Both sinks carry every window.
        sick_lines = (tmp_path / "egress" / "sick.csv").read_bytes()
        ok_lines = (tmp_path / "egress" / "healthy.csv").read_bytes()
        assert sick_lines.count(b"\n") == ok_lines.count(b"\n") > 0

    def test_transient_fault_is_retried_node_locally(self, tmp_path):
        sick = _count_node("sick", fail_windows=())
        calls = {"n": 0}
        real = sick._fn

        def flaky(win, results):
            calls["n"] += 1
            if calls["n"] == 3:
                raise RuntimeError("transient")
            return real(win, results)

        sick._fn = flaky
        dag = _toy_dag(tmp_path, [sick])
        out = list(dag.run(iter(_toy_points())))
        assert len(out) > 3
        snap = dag.snapshot()
        assert snap["nodes"]["sick"]["retries"] == 1
        assert snap["nodes"]["sick"]["failovers"] == 0
        assert snap["nodes"]["sick"]["backend"] == "device"

    def test_no_fallback_node_crashes_the_run(self, tmp_path):
        sick = _count_node("sick", fail_windows=range(-10**9, 10**9),
                           fallback=False)
        dag = _toy_dag(tmp_path, [sick])
        with pytest.raises(RuntimeError, match="boom"):
            list(dag.run(iter(_toy_points())))

    def test_stateful_node_is_never_retried(self, tmp_path):
        hits = {"n": 0}

        def stateful(win, results):
            hits["n"] += 1
            raise RuntimeError("half-applied")

        node = FunctionNode("state", stateful, idempotent=False)
        dag = _toy_dag(tmp_path, [node])
        with pytest.raises(RuntimeError, match="half-applied"):
            list(dag.run(iter(_toy_points())))
        assert hits["n"] == 1  # single attempt: no retry, no twin

    def test_driver_never_rerruns_the_node_walk(self, tmp_path):
        """The DAG's window process is marked non-idempotent: a
        driver-level retry would re-stage lines of nodes that already
        completed. The driver must crash instead."""
        sick = _count_node("sick", fail_windows=range(-10**9, 10**9),
                           fallback=False)
        dag = _toy_dag(tmp_path, [sick])
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=5, backoff_s=0.0))
        with pytest.raises(RuntimeError, match="boom"):
            list(dag.run(iter(_toy_points()), driver=drv))
        assert drv.stats["retries"] == 0

    def test_breaker_is_per_node(self, tmp_path):
        """With a breaker-configured overload policy, each
        fallback-capable node gets its OWN circuit: the sick node's
        circuit opens (windows route to its twin with no retry) while
        the healthy sibling's stays closed."""
        sick = _count_node("sick", fail_windows=range(-10**9, 10**9))
        healthy = _count_node("healthy")
        dag = _toy_dag(tmp_path, [sick, healthy])
        ctrl = overload.OverloadController(overload.OverloadPolicy(
            breaker_failures=2, breaker_probe_every=1000,
        ))
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=0, backoff_s=0.0),
            overload=ctrl,
        )
        out = list(dag.run(iter(_toy_points()), driver=drv))
        assert len(out) > 4
        snap = dag.snapshot()
        assert snap["nodes"]["sick"]["breaker"]["state"] == "open"
        assert snap["nodes"]["sick"]["backend"] == "device"  # no perm.
        assert snap["nodes"]["healthy"]["breaker"]["state"] == "closed"
        assert snap["nodes"]["sick"]["degraded_windows"] == len(out)


# ---------------------------------------------------------------------------
# The atomic unit checkpoint (multi-sink exactly-once)


def _run_sncb_leg(workdir, fault_plan=None, n_events=150):
    dag = build_sncb_dag(
        os.path.join(workdir, "egress"),
        qserve_queries=None,
        retry=RetryPolicy(max_retries=1, backoff_s=0.0),
    )
    driver = WindowedDataflowDriver(
        checkpoint_path=os.path.join(workdir, "ckpt.bin"),
        checkpoint_every=2, sink=None,
        retry=RetryPolicy(max_retries=1, backoff_s=0.0),
        failover=False,
    )
    source = _toy_sncb_stream(n_events)
    if fault_plan:
        faults.arm(fault_plan)
    try:
        for _ in dag.run(source(), driver=driver):
            pass
    finally:
        faults.disarm()
        qserve.uninstall()
        dag_mod.uninstall()
    return driver, dag


SNCB_SINKS = ("q1", "q2", "q3", "q4", "q5", "staytime", "qserve")


def _sink_bytes(workdir):
    out = {}
    for name in SNCB_SINKS:
        with open(os.path.join(workdir, "egress", f"{name}.csv"),
                  "rb") as f:
            out[name] = f.read()
    return out


@pytest.fixture(scope="module")
def sncb_clean(tmp_path_factory):
    """One clean 7-node run shared by the kill/resume legs below."""
    d = tmp_path_factory.mktemp("dag_clean")
    _run_sncb_leg(str(d))
    want = _sink_bytes(str(d))
    assert sum(len(v) for v in want.values()) > 0
    assert all(len(v) > 0 for v in want.values()), {
        k: len(v) for k, v in want.items()}
    return want


class TestUnitCheckpoint:
    @pytest.mark.parametrize("plan", [
        # Between-sink-commits cut: the SECOND unit commit's 2nd
        # sub-append (7 sinks per commit → hit 9), so the crash lands
        # after one sink's bytes of commit #2 are durable, before the
        # next sink's — with commit #1's checkpoint to resume from.
        [{"point": "dag.commit", "at": 9, "times": 10_000}],
        # Mid-node-walk kill (some nodes already staged this window).
        [{"point": "dag.node", "at": 25, "times": 10_000}],
        # Kill mid-registration-churn INSIDE the composed DAG (applies
        # re-hit per window; hit 11 lands on a mid-stream churn
        # command, past the first checkpoint).
        [{"point": "qserve.register", "at": 11, "times": 10_000}],
    ])
    def test_kill_anywhere_resumes_every_sink_exactly(
            self, tmp_path, sncb_clean, plan):
        with pytest.raises(InjectedFault):
            _run_sncb_leg(str(tmp_path), fault_plan=plan)
        drv, dag = _run_sncb_leg(str(tmp_path))  # resume
        assert drv.stats["resumed"] is True
        assert _sink_bytes(str(tmp_path)) == sncb_clean

    def test_unit_checkpoint_carries_all_components(self, tmp_path):
        _run_sncb_leg(str(tmp_path))
        ck = load_checkpoint(os.path.join(str(tmp_path), "ckpt.bin"))
        assert set(ck["egress"]["sinks"]) == set(SNCB_SINKS)
        nodes = ck["op"]["dag"]["nodes"]
        assert set(nodes) == set(SNCB_SINKS)
        # qserve's registry state rides as the node's substate, and the
        # markers match the files on disk (the atomic pair).
        assert "substate" in nodes["qserve"]
        assert nodes["qserve"]["substate"]["queries"]
        for name, marker in ck["egress"]["sinks"].items():
            path = os.path.join(str(tmp_path), "egress", f"{name}.csv")
            assert marker["bytes"] == os.path.getsize(path)
        assert "interner" in ck["op"] and "assembler" in ck["op"]

    def test_one_intern_home(self, tmp_path):
        _, dag = _run_sncb_leg(str(tmp_path))
        interned = set(dag.interner._to_key)
        assert "dev0" in interned            # device ids
        assert {"r0", "ta"} <= interned      # qserve qids + tenants

    def test_resume_fallback_backend_without_twin_is_loud(self,
                                                          tmp_path):
        """A checkpoint taken after a node failed over records
        backend="fallback"; resuming it into a DAG whose node lost its
        twin must fail AT RESTORE (the driver.bind rule per node) —
        never mid-window-walk with earlier nodes' egress staged."""
        sick = _count_node("sick", fail_windows=range(-10**9, 10**9))
        dag = _toy_dag(tmp_path, [sick])
        ck = str(tmp_path / "ck.bin")
        drv = WindowedDataflowDriver(checkpoint_path=ck, sink=None,
                                     checkpoint_every=1)
        list(dag.run(iter(_toy_points()), driver=drv))
        assert dag.snapshot()["nodes"]["sick"]["backend"] == "fallback"
        dag_mod.uninstall()
        twin_less = DataflowDAG(
            _toy_conf(), UniformGrid(8, 0.0, 8.0, 0.0, 8.0),
            [_count_node("sick", fallback=False)],
            out_dir=str(tmp_path / "egress2"))
        drv2 = WindowedDataflowDriver(checkpoint_path=ck, sink=None)
        with pytest.raises(ValueError, match="fallback"):
            list(twin_less.run(iter(_toy_points()), driver=drv2))

    def test_resume_with_missing_node_is_loud(self, tmp_path):
        _run_sncb_leg(str(tmp_path))
        grid = UniformGrid(8, 0.0, 8.0, 0.0, 8.0)
        small = DataflowDAG(_toy_conf(), grid,
                            [FunctionNode("q1", lambda w, r: 1)],
                            out_dir=str(tmp_path / "other"))
        drv = WindowedDataflowDriver(
            checkpoint_path=os.path.join(str(tmp_path), "ckpt.bin"),
            sink=None,
        )
        with pytest.raises(ValueError, match="unknown DAG node"):
            list(small.run(iter([]), driver=drv))


class TestMultiSink:
    def _pair(self, tmp_path):
        return MultiSink({
            "a": TransactionalFileSink(str(tmp_path / "a.csv")),
            "b": TransactionalFileSink(str(tmp_path / "b.csv")),
        })

    def test_torn_tail_on_a_newer_marker_on_b(self, tmp_path):
        """The satellite case: a crash between sub-commits leaves sink
        A with a tail past the checkpointed marker while B never
        committed — restore must truncate A, keep B, and the replay
        regenerates both."""
        ms = self._pair(tmp_path)
        ms.reset()
        ms.stage("a", "a1")
        ms.stage("b", "b1")
        marker = ms.commit()  # the checkpointed unit marker
        ms.stage("a", "a2")
        ms.stage("b", "b2")
        # Crash between A's commit and B's: dag.commit fires per
        # sub-append, and arming resets hit counts — hit 2 is B's side
        # of the commit below (A's append already durable).
        faults.arm([{"point": "dag.commit", "at": 2, "times": 10_000}])
        with pytest.raises(InjectedFault):
            ms.commit()
        faults.disarm()
        assert (tmp_path / "a.csv").read_bytes() == b"a1\na2\n"  # torn
        assert (tmp_path / "b.csv").read_bytes() == b"b1\n"
        ms2 = self._pair(tmp_path)
        ms2.restore(marker)
        assert (tmp_path / "a.csv").read_bytes() == b"a1\n"  # truncated
        assert (tmp_path / "b.csv").read_bytes() == b"b1\n"  # kept
        ms2.stage("a", "a2")
        ms2.stage("b", "b2")
        ms2.commit()
        assert (tmp_path / "a.csv").read_bytes() == b"a1\na2\n"
        assert (tmp_path / "b.csv").read_bytes() == b"b1\nb2\n"

    def test_marker_ahead_of_file_is_loud(self, tmp_path):
        """A sink file SHORTER than its checkpointed marker (committed
        egress lost out-of-band, or a marker from a future checkpoint
        generation) must raise, naming the file."""
        ms = self._pair(tmp_path)
        ms.reset()
        ms.stage("a", "a1" * 50)
        ms.stage("b", "b1")
        marker = ms.commit()
        (tmp_path / "a.csv").write_bytes(b"short")
        with pytest.raises(CheckpointCorruptError, match="out-of-band"):
            self._pair(tmp_path).restore(marker)

    def test_unknown_sink_in_restore_resets_fresh(self, tmp_path):
        ms = self._pair(tmp_path)
        ms.reset()
        ms.stage("a", "a1")
        marker = ms.commit()
        ms3 = MultiSink({
            "a": TransactionalFileSink(str(tmp_path / "a.csv")),
            "b": TransactionalFileSink(str(tmp_path / "b.csv")),
            "c": TransactionalFileSink(str(tmp_path / "c.csv")),
        })
        ms3.restore(marker)  # c has no marker → fresh reset
        assert (tmp_path / "c.csv").read_bytes() == b""


# ---------------------------------------------------------------------------
# Node parity (device vs numpy twin)


class TestNodeParity:
    def test_staytime_device_matches_host_walk(self, tmp_path):
        node = StayTimeNode("st")
        dag = build_sncb_dag(str(tmp_path / "egress"))
        node.bind(dag)
        from spatialflink_tpu.streams.windows import WindowBatch

        src = _toy_sncb_stream(90)
        evs = [e for e in src()
               if getattr(e, "device_id", None) is not None]
        win = WindowBatch(0, 40_000, evs)
        dev = node.process(win, {})
        host = node.fallback_process(win, {})
        assert sorted(dev) == sorted(host)
        assert dev  # non-vacuous

    def test_zone_nodes_device_matches_numpy(self, tmp_path):
        dag = build_sncb_dag(str(tmp_path / "egress"))
        from spatialflink_tpu.streams.windows import WindowBatch

        src = _toy_sncb_stream(90)
        evs = [e for e in src()
               if getattr(e, "device_id", None) is not None]
        win = WindowBatch(0, 40_000, evs)
        for name in ("q1", "q2", "q5"):
            node = dag.node(name)
            dev = node.process(win, {})
            twin = node.fallback_process(win, {})
            assert len(dev) > 0, name
            assert [repr(d) for d in dev] == [repr(t) for t in twin], name


# ---------------------------------------------------------------------------
# CheckIn node (stateful: occupancy + per-user last-event carry)


def _checkin_events(n=40):
    from spatialflink_tpu.apps.checkin import CheckInEvent

    rooms = ("r1", "r2")
    evs = []
    for i in range(n):
        room = rooms[i % 2]
        # Every 7th event repeats the user's previous direction — the
        # missing-opposite-event synthesis path.
        direction = "in" if (i // 2) % 2 == 0 or i % 7 == 0 else "out"
        evs.append(CheckInEvent(
            event_id=f"e{i}", device_id=f"{room}-{direction}",
            user_id=f"u{i % 3}", timestamp=100 * i,
        ))
    return evs


class TestCheckInNode:
    def _dag(self, tmp_path, sub):
        from spatialflink_tpu.dag import CheckInNode

        grid = UniformGrid(8, 0.0, 8.0, 0.0, 8.0)
        node = CheckInNode("checkin", {"r1": 10, "r2": 5})
        return DataflowDAG(_toy_conf(), grid, [node],
                           out_dir=str(tmp_path / sub)), node

    def test_matches_unwindowed_host_walk(self, tmp_path):
        """Each event is processed ONCE (the new-pane filter under the
        sliding clock), so the DAG's occupancy stream equals the
        standalone check_in_query over the same ordered events."""
        from spatialflink_tpu.apps.checkin import check_in_query

        evs = _checkin_events()
        want = [(room, cap, occ)
                for room, cap, occ, _t in check_in_query(
                    iter(evs), {"r1": 10, "r2": 5})]
        dag, node = self._dag(tmp_path, "egress")
        rows = []
        for res in dag.run(iter(evs)):
            pass
        got = [ln.split(",")[2:]
               for ln in (tmp_path / "egress" / "checkin.csv")
               .read_text().splitlines()]
        assert [(r, int(c), int(o)) for r, c, o in got] == \
            [(r, c, o) for r, c, o in want]

    def test_kill_resumes_occupancy_exactly(self, tmp_path):
        evs = _checkin_events()

        def leg(sub, plan=None):
            dag, node = self._dag(tmp_path, sub)
            drv = WindowedDataflowDriver(
                checkpoint_path=str(tmp_path / f"{sub}.ckpt"),
                checkpoint_every=2, sink=None, failover=False,
                retry=RetryPolicy(max_retries=1, backoff_s=0.0),
            )
            if plan:
                faults.arm(plan)
            try:
                for _ in dag.run(iter(evs), driver=drv):
                    pass
            finally:
                faults.disarm()
            return drv

        leg("clean")
        want = (tmp_path / "clean" / "checkin.csv").read_bytes()
        assert want
        with pytest.raises(InjectedFault):
            # dag.node raises mid-walk; the STATEFUL node takes no
            # retry and no twin — crash-and-resume only.
            leg("chaos", plan=[{"point": "dag.node", "at": 4,
                                "times": 10_000}])
        drv = leg("chaos")
        assert drv.stats["resumed"] is True
        assert (tmp_path / "chaos" / "checkin.csv").read_bytes() == want


# ---------------------------------------------------------------------------
# Per-node SLO budgets (live + sfprof twin) and telemetry surfaces


class TestNodeSlo:
    def test_live_node_budgets(self, tmp_path):
        from spatialflink_tpu import slo

        telemetry.enable()
        sick = _count_node("sick", fail_windows=range(-10**9, 10**9))
        ok = _count_node("ok")
        dag = _toy_dag(tmp_path, [sick, ok])
        engine = slo.install(slo.SloEngine(slo.SloSpec(
            eval_interval_s=0.0,
            node_budgets={
                "sick": {"failover_budget": 0},
                "ok": {"failover_budget": 0,
                       "degraded_window_budget": 0},
                "ghost": {"retry_budget": 1},
            },
        )))
        try:
            list(dag.run(iter(_toy_points())))
            rows = {r["check"]: r["ok"] for r in engine.evaluate()}
            assert rows["node_failover_budget:sick"] is False
            assert rows["node_failover_budget:ok"] is True
            assert rows["node_degraded_window_budget:ok"] is True
            # Unknown node: the budget is unanswerable — silence fails.
            assert rows["node_retry_budget:ghost"] is False
        finally:
            slo.uninstall()

    def test_live_node_budgets_without_dag_fail_on_silence(self):
        from spatialflink_tpu import slo

        engine = slo.SloEngine(slo.SloSpec(
            eval_interval_s=0.0,
            node_budgets={"q1": {"watermark_lag_p99_ms": 10_000}},
        ))
        rows = {r["check"]: r["ok"] for r in engine.evaluate()}
        assert rows["node_watermark_lag_p99_ms:q1"] is False

    def test_node_budget_validation_is_strict(self):
        from spatialflink_tpu import slo

        with pytest.raises(ValueError, match="node_budgets"):
            slo.SloSpec(node_budgets={"q1": {"typo_budget": 1}})

    def test_ledger_and_sfprof_twin(self, tmp_path):
        telemetry.enable()
        sick = _count_node("sick", fail_windows=range(-10**9, 10**9))
        dag = _toy_dag(tmp_path, [sick])
        list(dag.run(iter(_toy_points())))
        ledger = tmp_path / "ledger.json"
        telemetry.write_ledger(str(ledger), capture_costs=False)
        doc = json.loads(ledger.read_text())
        nodes = doc["snapshot"]["dag"]["nodes"]
        assert nodes["sick"]["backend"] == "fallback"
        assert nodes["sick"]["failovers"] == 1

        from tools.sfprof import slo as sfslo

        rows = {name: ok for name, _v, _b, ok in sfslo.evaluate(
            {"node_budgets": {
                "sick": {"failover_budget": 0,
                         "watermark_lag_p99_ms": 10_000_000},
                "ghost": {"failover_budget": 0},
            }}, doc)}
        assert rows["slo:node_failover_budget:sick"] is False
        assert rows["slo:node_watermark_lag_p99_ms:sick"] is True
        assert rows["slo:node_failover_budget:ghost"] is False
        # No dag block at all → every node budget fails on silence.
        rows = sfslo.evaluate(
            {"node_budgets": {"sick": {"failover_budget": 0}}},
            {"snapshot": {}})
        assert rows == [("slo:node_failover_budget:sick", None,
                         "<= 0", False)]


# ---------------------------------------------------------------------------
# streaming_job option 10


def _write_conf(tmp_path, option=10):
    conf = tmp_path / "conf.yml"
    conf.write_text(f"""
inputStream1:
  topicName: t
  format: CSV
  csvTsvSchemaAttr: [0, 1, 2, 3]
  gridBBox: [4.25, 50.75, 4.50, 50.95]
  numGridCells: 20
  delimiter: ","
query:
  option: {option}
  radius: 0.05
  k: 3
  queryPoints:
    - [4.37, 50.85]
window:
  type: "TIME"
  interval: 10
  step: 5
""")
    return conf


def _write_csv(tmp_path, n=120):
    rows = []
    for i in range(n):
        x = 4.354 if i % 3 == 0 else (4.404 if i % 3 == 1 else 4.30)
        y = 50.854 if i % 3 != 2 else 50.80
        rows.append(f"dev{i % 4},{i * 400},{x},{y}")
    csv = tmp_path / "in.csv"
    csv.write_text("\n".join(rows))
    return csv


class TestStreamingJobOption10:
    def test_option10_checkpointed_run(self, tmp_path):
        from spatialflink_tpu.streaming_job import main

        conf = _write_conf(tmp_path)
        csv = _write_csv(tmp_path)
        out = tmp_path / "out"
        rc = main(["--config", str(conf), "--source", f"csv:{csv}",
                   "--output", str(out),
                   "--checkpoint", str(tmp_path / "ck.bin")])
        assert rc == 0
        for name in SNCB_SINKS:
            assert (out / f"{name}.csv").exists()
        assert (out / "q1.csv").read_bytes()
        assert (out / "qserve.csv").read_bytes()
        ck = load_checkpoint(str(tmp_path / "ck.bin"))
        assert set(ck["egress"]["sinks"]) == set(SNCB_SINKS)

    def test_option10_needs_output_dir(self, tmp_path):
        from spatialflink_tpu.streaming_job import main

        conf = _write_conf(tmp_path)
        csv = _write_csv(tmp_path)
        with pytest.raises(SystemExit, match="directory"):
            main(["--config", str(conf), "--source", f"csv:{csv}"])
