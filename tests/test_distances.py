"""Distance-kernel parity tests against scalar brute-force re-derivations."""

import math

import jax.numpy as jnp
import numpy as np
import pytest

from spatialflink_tpu.ops.distances import (
    bbox_bbox_min_distance,
    bbox_point_min_distance,
    haversine_distance,
    pairwise_distance,
    point_point_distance,
    point_polyline_distance,
    point_segment_distance,
)
from spatialflink_tpu.ops.polygon import pack_polyline


def scalar_point_segment(x, y, x1, y1, x2, y2):
    """Independent scalar re-derivation of DistanceFunctions.java:96-131."""
    a, b, c, d = x - x1, y - y1, x2 - x1, y2 - y1
    dot, len_sq = a * c + b * d, c * c + d * d
    param = dot / len_sq if len_sq != 0 else -1
    if param < 0:
        xx, yy = x1, y1
    elif param > 1:
        xx, yy = x2, y2
    else:
        xx, yy = x1 + param * c, y1 + param * d
    return math.hypot(x - xx, y - yy)


def test_point_point(rng):
    a = rng.normal(size=(50, 2))
    b = rng.normal(size=(50, 2))
    d = np.asarray(point_point_distance(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(d, np.linalg.norm(a - b, axis=1), rtol=1e-12)


def test_pairwise(rng):
    a = rng.normal(size=(20, 2))
    b = rng.normal(size=(30, 2))
    d = np.asarray(pairwise_distance(jnp.asarray(a), jnp.asarray(b)))
    expect = np.linalg.norm(a[:, None] - b[None, :], axis=2)
    np.testing.assert_allclose(d, expect, rtol=1e-12)


def test_point_segment_matches_scalar(rng):
    p = rng.normal(size=(100, 2))
    s1 = rng.normal(size=(100, 2))
    s2 = rng.normal(size=(100, 2))
    s2[:10] = s1[:10]  # degenerate zero-length segments
    d = np.asarray(point_segment_distance(jnp.asarray(p), jnp.asarray(s1), jnp.asarray(s2)))
    for i in range(100):
        assert d[i] == pytest.approx(
            scalar_point_segment(*p[i], *s1[i], *s2[i]), rel=1e-12
        )


def test_polyline_distance_padding_invariant(rng):
    parts = [rng.normal(size=(7, 2)), rng.normal(size=(5, 2))]
    p = rng.normal(size=(40, 2))
    v1, e1 = pack_polyline(parts)
    v2, e2 = pack_polyline(parts, pad_to=64)
    d1 = np.asarray(point_polyline_distance(jnp.asarray(p), jnp.asarray(v1), jnp.asarray(e1)))
    d2 = np.asarray(point_polyline_distance(jnp.asarray(p), jnp.asarray(v2), jnp.asarray(e2)))
    np.testing.assert_allclose(d1, d2, rtol=1e-12)
    # And the seam between the two parts must not create a phantom edge.
    brute = np.full(40, np.inf)
    for part in parts:
        for i in range(len(part) - 1):
            for j in range(40):
                brute[j] = min(
                    brute[j], scalar_point_segment(*p[j], *part[i], *part[i + 1])
                )
    np.testing.assert_allclose(d1, brute, rtol=1e-12)


def test_haversine_against_law_of_cosines():
    # Brussels → Antwerp, compare against the reference formula's form
    # (acos of dot product) in float64.
    a = jnp.asarray([4.3517, 50.8503])
    b = jnp.asarray([4.4025, 51.2194])
    r = 6371008.7714
    d = float(haversine_distance(a, b, radius=r))
    rlat1, rlat2 = math.radians(50.8503), math.radians(51.2194)
    dlon = math.radians(4.4025 - 4.3517)
    expect = (
        math.acos(
            math.sin(rlat1) * math.sin(rlat2)
            + math.cos(rlat1) * math.cos(rlat2) * math.cos(dlon)
        )
        * r
    )
    # acos-form loses ~1e-8 relative precision even in float64; haversine is
    # the better-conditioned formula, so compare loosely.
    assert d == pytest.approx(expect, rel=1e-6)
    assert 40000 < d < 43000  # sanity: ~41 km


def test_bbox_point_distance():
    box = jnp.asarray([0.0, 0.0, 2.0, 1.0])
    pts = jnp.asarray([[1.0, 0.5], [3.0, 0.5], [-1.0, -1.0], [1.0, 3.0]])
    d = np.asarray(bbox_point_min_distance(pts, box))
    np.testing.assert_allclose(d, [0.0, 1.0, math.sqrt(2), 2.0], rtol=1e-12)


def test_bbox_bbox_distance():
    a = jnp.asarray([0.0, 0.0, 1.0, 1.0])
    assert float(bbox_bbox_min_distance(a, jnp.asarray([0.5, 0.5, 2.0, 2.0]))) == 0.0
    assert float(bbox_bbox_min_distance(a, jnp.asarray([3.0, 0.0, 4.0, 1.0]))) == pytest.approx(2.0)
    assert float(
        bbox_bbox_min_distance(a, jnp.asarray([2.0, 2.0, 3.0, 3.0]))
    ) == pytest.approx(math.sqrt(2))
