"""Two-process jax.distributed dryrun (parallel/multihost_dryrun.py).

Proves the DCN scale-out seam end to end on this machine: both child
processes join through ``initialize_distributed`` (the production entry
point), build ONE mesh over 2 procs × 2 virtual CPU devices, run
``sharded_knn`` with cross-process collectives (gloo standing in for
DCN), and assert bit-equality with the single-device kernel.

Slow marker: spawns two fresh jax interpreters (~30-60 s with cold
compiles).
"""

import pytest

from spatialflink_tpu.parallel.multihost import initialize_distributed
from spatialflink_tpu.parallel.multihost_dryrun import OK_TAG, run_dryrun


@pytest.mark.slow
def test_two_process_mesh_program_end_to_end():
    out = run_dryrun(num_processes=2, local_devices=2)
    assert out.count(OK_TAG) == 2
    assert "procs=2" in out and "devices=4" in out


def test_initialize_distributed_single_process_noop():
    assert initialize_distributed(None, 1, None) is False


def test_initialize_distributed_rejects_partial_config(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    with pytest.raises(ValueError, match="partial multi-host config"):
        initialize_distributed("127.0.0.1:1234", 1, 0)
    with pytest.raises(ValueError, match="partial multi-host config"):
        initialize_distributed(None, 4, 0)
    with pytest.raises(ValueError, match="process id"):
        initialize_distributed("127.0.0.1:1234", 2, None)
