"""Pallas hit-extraction join (ops/pallas_join.py) — interpret-mode parity.

On TPU the dense-bucket join compacts hits with a Pallas kernel whose cost
is proportional to the MATCH count (the XLA nonzero path pays ~9 ns/lane
over the full span²·cells·capL·capR domain). These tests run the same
kernel through the Pallas interpreter on CPU and pin it to the brute-force
cross join and to the XLA bucketed kernel: identical pair sets, counts,
distances, and overflow semantics (exact iff overflow == 0 — the contract
of join/PointPointJoinQuery.java:124-183's windowed distance filter).
"""

import numpy as np
import pytest

from conftest import pallas_int64_xfail
import jax.numpy as jnp

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import Point
from spatialflink_tpu.operators import (
    PointPointJoinQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.ops.join import join_window_bucketed
from spatialflink_tpu.ops.pallas_join import join_window_pallas

GRID_N = 8


def _cells(xy):
    ci = np.clip(np.floor(xy).astype(np.int32), 0, GRID_N - 1)
    out = (ci[:, 0] * GRID_N + ci[:, 1]).astype(np.int32)
    oob = (xy < 0).any(axis=1) | (xy >= GRID_N).any(axis=1)
    out[oob] = GRID_N * GRID_N  # out-of-grid sentinel
    return out


def _pallas(axy, av, bxy, bv, r, cap=16, layers=1, max_pairs=4096):
    return join_window_pallas(
        jnp.asarray(axy), jnp.asarray(av), jnp.asarray(_cells(axy)),
        jnp.asarray(bxy), jnp.asarray(bv), jnp.asarray(_cells(bxy)),
        grid_n=GRID_N, layers=layers, radius=np.float32(r),
        cap_left=cap, cap_right=cap, max_pairs=max_pairs, interpret=True,
    )


def _pairs(res):
    li = np.asarray(res.left_index)
    ri = np.asarray(res.right_index)
    return {(int(a), int(b)) for a, b in zip(li, ri) if a >= 0}


def _brute(axy, av, bxy, bv, r):
    d = np.sqrt(((axy[:, None, :] - bxy[None, :, :]) ** 2).sum(-1))
    keep = (d <= r) & av[:, None] & bv[None, :]
    # In-grid only: out-of-grid points never join (reference key semantics).
    ain = ~((axy < 0).any(1) | (axy >= GRID_N).any(1))
    bin_ = ~((bxy < 0).any(1) | (bxy >= GRID_N).any(1))
    keep &= ain[:, None] & bin_[None, :]
    return {(int(a), int(b)) for a, b in zip(*np.nonzero(keep))}, d


@pytest.fixture
def data():
    rng = np.random.default_rng(7)
    n, m = 260, 240
    axy = rng.uniform(-0.5, GRID_N + 0.5, (n, 2)).astype(np.float32)
    bxy = rng.uniform(-0.5, GRID_N + 0.5, (m, 2)).astype(np.float32)
    av = rng.random(n) > 0.15
    bv = rng.random(m) > 0.15
    return axy, av, bxy, bv


@pallas_int64_xfail
def test_matches_bruteforce_and_distances(data):
    axy, av, bxy, bv = data
    r = 0.7
    res = _pallas(axy, av, bxy, bv, r)
    want, d = _brute(axy, av, bxy, bv, r)
    got = _pairs(res)
    assert got == want
    assert int(res.count) == len(want)
    assert int(res.overflow) == 0
    dm = {
        (int(a), int(b)): float(x)
        for a, b, x in zip(
            np.asarray(res.left_index), np.asarray(res.right_index),
            np.asarray(res.dist),
        )
        if a >= 0
    }
    for k in got:
        assert abs(dm[k] - d[k]) < 1e-5


@pallas_int64_xfail
def test_matches_xla_bucketed(data):
    axy, av, bxy, bv = data
    r = 0.9
    res_p = _pallas(axy, av, bxy, bv, r)
    res_x = join_window_bucketed(
        jnp.asarray(axy), jnp.asarray(av), jnp.asarray(_cells(axy)),
        jnp.asarray(bxy), jnp.asarray(bv), jnp.asarray(_cells(bxy)),
        grid_n=GRID_N, layers=1, radius=np.float32(r),
        cap_left=16, cap_right=16, max_pairs=4096,
    )
    assert _pairs(res_p) == _pairs(res_x)
    assert int(res_p.count) == int(res_x.count)
    assert int(res_p.overflow) == int(res_x.overflow)


@pallas_int64_xfail
def test_two_layer_radius(data):
    axy, av, bxy, bv = data
    r = 1.6  # ceil(1.6 / 1.0) = 2 grid layers
    res = _pallas(axy, av, bxy, bv, r, layers=2, max_pairs=65536)
    want, _ = _brute(axy, av, bxy, bv, r)
    assert _pairs(res) == want
    assert int(res.count) == len(want)


@pallas_int64_xfail
def test_overflow_reported_when_cap_exceeded(data):
    axy, av, bxy, bv = data
    res = _pallas(axy, av, bxy, bv, 0.7, cap=2)
    assert int(res.overflow) > 0  # 260 pts / 64 cells >> cap 2


@pallas_int64_xfail
def test_count_exceeding_budget_reports_true_total(data):
    axy, av, bxy, bv = data
    r = 0.9
    want, _ = _brute(axy, av, bxy, bv, r)
    res = _pallas(axy, av, bxy, bv, r, max_pairs=128)
    assert len(want) > 128
    assert int(res.count) == len(want)  # retry contract: true total


@pallas_int64_xfail
def test_empty_side():
    axy = np.zeros((16, 2), np.float32)
    av = np.zeros(16, bool)
    bxy = np.full((16, 2), 4.2, np.float32)
    bv = np.ones(16, bool)
    res = _pallas(axy, av, bxy, bv, 1.0)
    assert int(res.count) == 0
    assert _pairs(res) == set()


@pallas_int64_xfail
def test_operator_pallas_backend_matches_default():
    rng = np.random.default_rng(3)
    grid = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)
    left = [
        Point(obj_id=f"d{i % 5}", timestamp=i * 120,
              x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
        for i in range(160)
    ]
    right = [
        Point(obj_id=f"q{i}", timestamp=i * 190,
              x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
        for i in range(120)
    ]

    def run(backend):
        op = PointPointJoinQuery(conf, grid, join_backend=backend)
        return [
            {(a.obj_id, a.timestamp, b.obj_id): d for a, b, d in res.pairs}
            for res in op.run(iter(list(left)), iter(list(right)), 0.7)
        ]

    got = run("pallas_interpret")
    want = run(None)  # XLA path (float64 on CPU)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g.keys() == w.keys()
        for k in g:  # Pallas computes f32; distances agree to f32 eps
            assert abs(g[k] - w[k]) < 1e-5
