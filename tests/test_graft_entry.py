"""Driver-contract tests: entry() compiles and dryrun_multichip(8) runs on
the virtual CPU mesh."""

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_entry_compiles_and_runs():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    # True sync is a device fetch — block_until_ready is a no-op over the
    # axon tunnel (sfcheck sync-discipline).
    out = jax.device_get(out)
    assert int(out.num_valid) == 50
    d = np.asarray(out.dist[: int(out.num_valid)])
    assert (np.diff(d) >= 0).all()  # ascending


@pytest.mark.slow
def test_dryrun_multichip_8():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)
