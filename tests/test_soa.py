"""SoA streaming path: assembler parity with the object assembler and
end-to-end operator equivalence + throughput."""

import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import Point
from spatialflink_tpu.operators import (
    PointPointKNNQuery,
    PointPointRangeQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.streams.soa import SoaWindowAssembler
from spatialflink_tpu.streams.windows import SlidingEventTimeWindows, WindowAssembler

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)


def _chunks(ts, xs, ys, oids, n_chunks=5):
    bounds = np.linspace(0, len(ts), n_chunks + 1).astype(int)
    for a, b in zip(bounds[:-1], bounds[1:]):
        yield {"ts": ts[a:b], "x": xs[a:b], "y": ys[a:b], "oid": oids[a:b]}


def test_soa_assembler_matches_object_assembler(rng):
    n = 3000
    ts = np.sort(rng.integers(0, 60_000, n)).astype(np.int64)
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = rng.integers(0, 9, n).astype(np.int32)

    soa = SoaWindowAssembler(10_000, 5_000)
    soa_wins = {
        (w.start, w.end): w.count
        for w in soa.stream(_chunks(ts, xs, ys, oids))
    }

    obj = WindowAssembler(
        SlidingEventTimeWindows(10_000, 5_000), timestamp_fn=lambda e: e.timestamp
    )
    pts = [Point(obj_id=str(o), timestamp=int(t), x=float(x), y=float(y))
           for t, x, y, o in zip(ts, xs, ys, oids)]
    obj_wins = {}
    for w in obj.stream(iter(pts)):
        obj_wins[(w.start, w.end)] = len(w.events)
    assert soa_wins == obj_wins


def test_soa_assembler_gap_skip():
    """A huge event-time gap must not spin over empty windows."""
    ts = np.array([0, 1000, 10**12, 10**12 + 1], np.int64)
    soa = SoaWindowAssembler(10_000, 10)
    wins = list(soa.stream([{"ts": ts, "x": np.zeros(4), "y": np.zeros(4),
                             "oid": np.zeros(4, np.int32)}]))
    spans = {(w.start, w.end): w.count for w in wins}
    total = sum(spans.values())
    # Each event is in size/slide = 1000 windows.
    assert total == 4 * 1000


def test_soa_assembler_out_of_order_within_bound(rng):
    base = np.sort(rng.integers(0, 30_000, 500)).astype(np.int64)
    jitter = rng.integers(-1500, 1500, 500)
    ts = base + jitter  # disorder within 3s bound
    soa = SoaWindowAssembler(10_000, 5_000, ooo_ms=3_000)
    wins = list(soa.stream([{"ts": ts[i:i+50], "x": np.zeros(len(ts[i:i+50])),
                             "y": np.zeros(len(ts[i:i+50])),
                             "oid": np.zeros(len(ts[i:i+50]), np.int32)}
                            for i in range(0, 500, 50)]))
    assert soa.dropped_late == 0
    # Every event lands in exactly size/slide = 2 windows.
    assert sum(w.count for w in wins) == 2 * 500


def test_soa_range_matches_object_path(rng):
    n = 2000
    ts = np.sort(rng.integers(0, 30_000, n)).astype(np.int64)
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = rng.integers(0, 7, n).astype(np.int32)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    q = Point(x=5.0, y=5.0)
    r = 2.0

    soa_res = {}
    for s_, e_, matched, dists in PointPointRangeQuery(conf, GRID).run_soa(
        _chunks(ts, xs, ys, oids), [q], r
    ):
        soa_res[(s_, e_)] = len(matched["ts"])
        # Matched arrays really are the matching events: all within radius.
        assert (np.hypot(matched["x"] - 5.0, matched["y"] - 5.0) <= r + 1e-12).all()
        assert len(dists) == len(matched["ts"])
    pts = [Point(obj_id=str(o), timestamp=int(t), x=float(x), y=float(y))
           for t, x, y, o in zip(ts, xs, ys, oids)]
    obj_res = {
        (res.start, res.end): len(res.objects)
        for res in PointPointRangeQuery(conf, GRID).run(iter(pts), [q], r)
    }
    # SoA path fires only non-empty windows; object path windows always have
    # events by construction here.
    assert {k: v for k, v in soa_res.items() if v} == {
        k: v for k, v in obj_res.items() if v
    }


def test_soa_knn_matches_object_path(rng):
    n = 2000
    ts = np.sort(rng.integers(0, 30_000, n)).astype(np.int64)
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = rng.integers(0, 7, n).astype(np.int32)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)
    q = Point(x=5.0, y=5.0)
    r, k = 4.0, 5

    soa = {
        (s, e): (list(o), [float(d) for d in dd])
        for s, e, o, dd, nv in PointPointKNNQuery(conf, GRID).run_soa(
            _chunks(ts, xs, ys, oids), q, r, k, num_segments=64
        )
    }
    pts = [Point(obj_id=str(o), timestamp=int(t), x=float(x), y=float(y))
           for t, x, y, o in zip(ts, xs, ys, oids)]
    for res in PointPointKNNQuery(conf, GRID).run(iter(pts), q, r, k):
        got_oids, got_dists = soa[(res.start, res.end)]
        assert [int(o) for o in got_oids] == [int(oid) for oid, _, _ in res.neighbors]
        for gd, (_, ed, _) in zip(got_dists, res.neighbors):
            assert gd == pytest.approx(ed, rel=1e-9)


def test_soa_knn_throughput(rng):
    """Streaming SoA path must comfortably beat the 20k EPS reference target."""
    import time

    n = 1_000_000
    ts = (np.arange(n) // 100).astype(np.int64)  # 100 events/ms → 10s of data
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = (np.arange(n) % 500).astype(np.int32)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=5, slide_step=5)
    q = Point(x=5.0, y=5.0)
    # Warm the jitted program for this bucket/k/num_segments so the timed
    # region measures throughput, not first-call XLA compilation.
    warm = {"ts": ts[:70000], "x": xs[:70000], "y": ys[:70000], "oid": oids[:70000]}
    list(PointPointKNNQuery(conf, GRID).run_soa(iter([warm]), q, 4.0, 50,
                                                num_segments=512))
    t0 = time.perf_counter()
    out = list(
        PointPointKNNQuery(conf, GRID).run_soa(
            _chunks(ts, xs, ys, oids, n_chunks=20), q, 4.0, 50, num_segments=512
        )
    )
    dt = time.perf_counter() - t0
    eps = n / dt
    assert out
    assert eps > 500_000, f"SoA streaming too slow: {eps:.0f} EPS"


def test_soa_assembler_ooo_before_first_event():
    """An in-bound out-of-order event earlier than the first event must not
    lose its earliest windows (seeding regression)."""
    asm = SoaWindowAssembler(10_000, 5_000, ooo_ms=3_000)
    z = lambda n: {"x": np.zeros(n), "y": np.zeros(n), "oid": np.zeros(n, np.int32)}
    fired = asm.feed({"ts": np.array([10_000], np.int64), **z(1)})
    # Watermark 7_000: nothing complete yet.
    assert fired == []
    fired = asm.feed({"ts": np.array([9_500, 20_001], np.int64), **z(2)})
    spans = {(w.start, w.end): w.count for w in fired}
    # 9_500 arrived within the bound and belongs to [0,10_000) and
    # [5_000,15_000); [0,10_000) fires complete at watermark 17_001.
    assert spans[(0, 10_000)] == 1
    assert spans[(5_000, 15_000)] == 2  # 9_500 + 10_000
    assert asm.dropped_late == 0


def test_soa_knn_panes_matches_run_soa(rng):
    """run_soa_panes (pane-digest carry) must yield identical per-window
    (oids, dists) to run_soa full recomputation on sliding windows."""
    n = 3000
    ts = np.sort(rng.integers(0, 40_000, n)).astype(np.int64)
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = rng.integers(0, 9, n).astype(np.int32)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=2)
    q = Point(x=5.0, y=5.0)
    r, k = 4.0, 6

    def collect(gen):
        return {
            (s, e): ([int(o) for o in oo], [round(float(d), 12) for d in dd])
            for s, e, oo, dd, nv in gen
        }

    full = collect(PointPointKNNQuery(conf, GRID).run_soa(
        _chunks(ts, xs, ys, oids), q, r, k, num_segments=64))
    pane = collect(PointPointKNNQuery(conf, GRID).run_soa_panes(
        _chunks(ts, xs, ys, oids), q, r, k, num_segments=64))
    assert full == pane


def _geoms_to_ragged_chunks(geoms, interner, n_chunks=4):
    """Objects → ragged SoA chunks via each object's own packed() chain
    (the from_ragged contract: single closed/open boundary chains)."""
    rows = []
    for g in geoms:
        pv, pe = g.packed()
        ln = int(pe.sum()) + 1  # valid chain length
        rows.append((g.timestamp, interner.intern(g.obj_id), pv[:ln]))
    bounds = np.linspace(0, len(rows), n_chunks + 1).astype(int)
    for a, b in zip(bounds[:-1], bounds[1:]):
        part = rows[a:b]
        if not part:
            continue
        yield {
            "ts": np.array([r[0] for r in part], np.int64),
            "oid": np.array([r[1] for r in part], np.int32),
            "lengths": np.array([len(r[2]) for r in part], np.int64),
            "verts": np.concatenate([r[2] for r in part]),
        }


def test_geometry_soa_range_matches_object_path(rng):
    """Ragged-SoA geometry range == object path, including bbox pruning
    and polygon containment semantics."""
    from spatialflink_tpu.models.objects import Polygon
    from spatialflink_tpu.operators import PolygonPointRangeQuery
    from spatialflink_tpu.utils.interning import Interner

    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    polys = []
    for i in range(120):
        cx, cy = rng.uniform(1, 9), rng.uniform(1, 9)
        s = rng.uniform(0.1, 0.4)
        polys.append(Polygon(
            obj_id=f"poly{i}", timestamp=int(i * 250),
            rings=[np.array([[cx - s, cy - s], [cx + s, cy - s],
                             [cx + s, cy + s], [cx - s, cy + s],
                             [cx - s, cy - s]])],
        ))
    q = Point(x=5.0, y=5.0)
    r = 1.2

    obj_op = PolygonPointRangeQuery(conf, GRID)
    obj_res = {
        (res.start, res.end): sorted(
            (p.obj_id, round(float(d), 12))
            for p, d in zip(res.objects, res.dists)
        )
        for res in obj_op.run(iter(polys), [q], r)
    }

    soa_op = PolygonPointRangeQuery(conf, GRID)
    interner = Interner()
    chunks = list(_geoms_to_ragged_chunks(polys, interner))
    soa_res = {
        (s, e): sorted(
            (interner.lookup(int(o)), round(float(d), 12))
            for o, d in zip(oids, dists)
        )
        for s, e, idx, oids, dists, cnt in soa_op.run_soa(
            iter(chunks), [q], r
        )
    }
    assert obj_res == soa_res and obj_res


def test_geometry_soa_knn_matches_object_path(rng):
    from spatialflink_tpu.models.objects import LineString
    from spatialflink_tpu.operators import LineStringPointKNNQuery
    from spatialflink_tpu.utils.interning import Interner

    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)
    lines = []
    for i in range(90):
        start = rng.uniform(1, 9, 2)
        pts = start + np.cumsum(rng.uniform(-0.2, 0.2, (4, 2)), axis=0)
        lines.append(LineString(
            obj_id=f"ls{i}", timestamp=int(i * 300),
            coords=np.vstack([start, pts]),
        ))
    q = Point(x=5.0, y=5.0)
    r, k = 3.0, 6

    obj_res = [
        (res.start, res.end,
         [(o, round(d, 12)) for o, d, _ in res.neighbors])
        for res in LineStringPointKNNQuery(conf, GRID).run(iter(lines), q, r, k)
    ]
    soa_op = LineStringPointKNNQuery(conf, GRID)
    interner = Interner()
    chunks = list(_geoms_to_ragged_chunks(lines, interner))
    soa_res = [
        (s, e, [(interner.lookup(int(o)), round(float(d), 12))
                for o, d in zip(oids, dists)])
        for s, e, oids, dists, nv in soa_op.run_soa(
            iter(chunks), q, r, k, num_segments=128
        )
    ]
    assert obj_res == soa_res and obj_res


def test_soa_point_polygon_range_matches_object_path(rng):
    """The generalized point-stream run_soa must equal the object path for
    a polygon query set (the Q1-style hot path)."""
    from spatialflink_tpu.models.objects import Polygon
    from spatialflink_tpu.operators import PointPolygonRangeQuery

    n = 2500
    ts = np.sort(rng.integers(0, 30_000, n)).astype(np.int64)
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = rng.integers(0, 7, n).astype(np.int32)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    polys = [
        Polygon(rings=[np.array([[4, 4], [6, 4], [6, 6], [4, 6], [4, 4]], float)]),
        Polygon(rings=[np.array([[1, 7], [2, 7], [2, 8.5], [1, 8.5], [1, 7]], float)]),
    ]
    r = 0.4

    soa = {
        (s, e): sorted(zip(m["ts"].tolist(), np.round(dd, 12).tolist()))
        for s, e, m, dd in PointPolygonRangeQuery(conf, GRID).run_soa(
            _chunks(ts, xs, ys, oids), polys, r
        )
    }
    pts = [Point(obj_id=str(o), timestamp=int(t), x=float(x), y=float(y))
           for t, x, y, o in zip(ts, xs, ys, oids)]
    obj = {
        (res.start, res.end): sorted(
            zip((p.timestamp for p in res.objects),
                np.round(res.dists, 12).tolist())
        )
        for res in PointPolygonRangeQuery(conf, GRID).run(iter(pts), polys, r)
    }
    assert soa == obj and soa


def test_soa_point_linestring_range_matches_object_path(rng):
    from spatialflink_tpu.models.objects import LineString
    from spatialflink_tpu.operators import PointLineStringRangeQuery

    n = 2000
    ts = np.sort(rng.integers(0, 20_000, n)).astype(np.int64)
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = rng.integers(0, 5, n).astype(np.int32)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)
    lines = [LineString(coords=np.array([[2, 2], [5, 5], [8, 3]], float))]
    r = 0.5

    soa = {
        (s, e): sorted(zip(m["ts"].tolist(), np.round(dd, 12).tolist()))
        for s, e, m, dd in PointLineStringRangeQuery(conf, GRID).run_soa(
            _chunks(ts, xs, ys, oids), lines, r
        )
    }
    pts = [Point(obj_id=str(o), timestamp=int(t), x=float(x), y=float(y))
           for t, x, y, o in zip(ts, xs, ys, oids)]
    obj = {
        (res.start, res.end): sorted(
            zip((p.timestamp for p in res.objects),
                np.round(res.dists, 12).tolist())
        )
        for res in PointLineStringRangeQuery(conf, GRID).run(iter(pts), lines, r)
    }
    assert soa == obj and soa


def test_soa_large_polygon_set_uses_pruned_path(rng):
    """run_soa with >=64 exact-mode polygons rides the pruned/compact
    evaluator (parity + the operator grows persistent budgets)."""
    from spatialflink_tpu.models.objects import Polygon
    from spatialflink_tpu.operators import PointPolygonRangeQuery

    n = 2000
    ts = np.sort(rng.integers(0, 20_000, n)).astype(np.int64)
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = rng.integers(0, 5, n).astype(np.int32)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)
    polys = []
    for i in range(70):
        cx, cy = rng.uniform(1, 3), rng.uniform(1, 3)
        polys.append(Polygon(rings=[np.array(
            [[cx - .1, cy - .1], [cx + .1, cy - .1], [cx + .1, cy + .1],
             [cx - .1, cy + .1], [cx - .1, cy - .1]])]))
    r = 0.15

    op = PointPolygonRangeQuery(conf, GRID)
    op._cand_budget = 64  # force budget growth through the SoA path
    soa = {
        (s, e): sorted(zip(m["ts"].tolist(), np.round(dd, 12).tolist()))
        for s, e, m, dd in op.run_soa(_chunks(ts, xs, ys, oids), polys, r)
    }
    pts = [Point(obj_id=str(o), timestamp=int(t), x=float(x), y=float(y))
           for t, x, y, o in zip(ts, xs, ys, oids)]
    obj = {
        (res.start, res.end): sorted(
            zip((p.timestamp for p in res.objects),
                np.round(res.dists, 12).tolist())
        )
        for res in PointPolygonRangeQuery(conf, GRID).run(iter(pts), polys, r)
    }
    assert soa == obj
    assert op._cand_budget > 64  # the growth persisted
