"""Wire-format exactness: the 6 B/pt quantized ingest path must add zero
error on top of quantization — device upcast == host reference upcast,
bitwise, and the full kNN digest program fed wire records must equal the
same program fed the host-dequantized f32 coords."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.streams.wire import U16_MAX, WireFormat, wire_scale

BEIJING = dict(min_x=115.5, max_x=117.6, min_y=39.6, max_y=41.1)


@pytest.fixture(scope="module")
def grid():
    return UniformGrid(100, **BEIJING)


def test_wire_scale_contract():
    for span in (2.1, 1.5, 0.001, 360.0, 1e-6, 123.456):
        s = wire_scale(span)
        # Covers the span end to end.
        assert s * U16_MAX >= span
        # The 8-bit mantissa ceiling costs at most 1/128 relative slack.
        assert s * U16_MAX <= span * (1 + 1 / 127) + s
        # m×2^e with m ≤ 8 bits: strip trailing powers of two until the
        # mantissa is an odd integer; it must fit in 8 bits.
        m, e = s, 0
        while m != math.floor(m) or (m >= 2 and m % 2 == 0):
            m = m * 2 if m != math.floor(m) else m / 2
            e += 1
            assert e < 400
        assert 1 <= m <= 255


def test_dequantize_device_matches_host_bitwise(grid):
    rng = np.random.default_rng(5)
    wf = WireFormat.for_grid(grid)
    xy = np.stack([
        rng.uniform(BEIJING["min_x"], BEIJING["max_x"], 50_000),
        rng.uniform(BEIJING["min_y"], BEIJING["max_y"], 50_000),
    ], axis=1)
    q = wf.quantize(xy)
    host = wf.dequantize_np(q)
    dev = np.asarray(jax.jit(wf.dequantize)(jnp.asarray(q)))
    assert host.dtype == np.float32 and dev.dtype == np.float32
    # Bit-identical: the product uint16×(8-bit m×2^e) is exact in f32, so
    # FMA vs separate mul+add cannot round differently.
    assert np.array_equal(host.view(np.uint32), dev.view(np.uint32))


def test_quantization_error_below_one_step(grid):
    rng = np.random.default_rng(6)
    wf = WireFormat.for_grid(grid)
    xy = np.stack([
        rng.uniform(BEIJING["min_x"], BEIJING["max_x"], 10_000),
        rng.uniform(BEIJING["min_y"], BEIJING["max_y"], 10_000),
    ], axis=1)
    back = wf.dequantize_np(wf.quantize(xy)).astype(np.float64)
    err = np.abs(back - xy)
    # One lattice step, plus the single f32 rounding of origin + q*scale
    # (ulp/2 at coordinate magnitude ~128 is 3.8e-6) and the origin's own
    # f32 rounding.
    f32_round = 8e-6
    assert float(err[:, 0].max()) <= float(wf.scale[0]) + f32_round
    assert float(err[:, 1].max()) <= float(wf.scale[1]) + f32_round


def test_knn_digest_parity_wire_vs_f32(grid):
    """The full fused pane-digest program fed 6-byte wire records must
    produce bit-identical digests to the same program fed pre-dequantized
    f32 coordinates (the device upcast is exact, so quantization is the
    ONLY precision event — and it happens at the producer)."""
    from spatialflink_tpu.ops.cells import assign_cells
    from spatialflink_tpu.ops.knn import knn_pane_digest

    rng = np.random.default_rng(7)
    n, nseg = 20_000, 1024
    wf = WireFormat.for_grid(grid)
    xy = np.stack([
        rng.uniform(BEIJING["min_x"], BEIJING["max_x"], n),
        rng.uniform(BEIJING["min_y"], BEIJING["max_y"], n),
    ], axis=1)
    q16 = wf.quantize(xy)
    oid16 = rng.integers(0, nseg, n).astype(np.int16)
    qp = np.asarray([116.40, 40.19], np.float32)
    flags = grid.neighbor_flags(0.05, [grid.flat_cell(*qp)])
    valid = np.ones(n, bool)

    def digest_wire(xyq, oid, flags_table, query_xy):
        xy_f = wf.dequantize(xyq)
        cell = assign_cells(
            xy_f, grid.min_x, grid.min_y, grid.cell_length, grid.n
        )
        return knn_pane_digest(
            xy_f, jnp.asarray(valid), cell, flags_table,
            oid.astype(jnp.int32), query_xy, np.float32(0.05),
            jnp.int32(0), num_segments=nseg,
        )

    def digest_f32(xy_f, oid, flags_table, query_xy):
        cell = assign_cells(
            xy_f, grid.min_x, grid.min_y, grid.cell_length, grid.n
        )
        return knn_pane_digest(
            xy_f, jnp.asarray(valid), cell, flags_table,
            oid.astype(jnp.int32), query_xy, np.float32(0.05),
            jnp.int32(0), num_segments=nseg,
        )

    d_wire = jax.jit(digest_wire)(
        jnp.asarray(q16), jnp.asarray(oid16), jnp.asarray(flags),
        jnp.asarray(qp),
    )
    d_f32 = jax.jit(digest_f32)(
        jnp.asarray(wf.dequantize_np(q16)), jnp.asarray(oid16),
        jnp.asarray(flags), jnp.asarray(qp),
    )
    assert np.array_equal(np.asarray(d_wire.seg_min), np.asarray(d_f32.seg_min))
    assert np.array_equal(np.asarray(d_wire.rep), np.asarray(d_f32.rep))
