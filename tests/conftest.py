"""Test config: force CPU with 8 virtual devices (multi-chip sharding tests)
and float64 (parity with the reference's JTS double math).

Must run before the first ``import jax`` anywhere in the test session.
"""

import os

# Force CPU (the ambient env sets JAX_PLATFORMS=axon for the real chip).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Belt and braces: some pytest plugin may import jax before this conftest
# runs, in which case the env var above is read too late.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
# XLA compiles are ~1s each on this host; the persistent cache makes repeat
# test runs cheap (first run still pays compilation).
jax.config.update("jax_compilation_cache_dir", os.path.expanduser("~/.cache/jax_sft"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


# The sfcheck fixture corpus contains deliberate violations AND mini
# test repos (meshparity_*/tests/test_*.py) that only import relative to
# their own project root — never collect them as real tests.
collect_ignore_glob = ["fixtures/*"]


@pytest.fixture
def rng():
    return np.random.default_rng(42)


# The 16 pre-existing pallas-interpret failures (present since seed, see
# CHANGES.md PR 1 addendum): this jax build's pallas interpret mode on
# CPU rejects the int64 dtypes the digest/join kernels use for index
# math under x64 ("ValueError: Invalid dtype ..."), and the forced-pallas
# self-check paths turn that into a RuntimeError. One shared marker so
# tier-1 is green, and strict=False so a jax upgrade that fixes Pallas
# interpret shows up as XPASS instead of staying silently masked
# (PARITY.md "Known deviations").
PALLAS_INT64_REASON = (
    "pallas interpret-mode int64 dtype gap in this jax build — "
    "pre-existing since seed; PARITY.md 'Known deviations'"
)
pallas_int64_xfail = pytest.mark.xfail(strict=False,
                                       reason=PALLAS_INT64_REASON)
