"""CRS transform tests: Krüger-series UTM vs exact invariants.

No proj library exists in this environment, so correctness is established
via (a) exact analytic anchor points of the transverse-Mercator projection,
(b) nm-level forward/inverse round-trips, (c) scale-factor behavior, and
(d) agreement with an independently coded low-order approximation.
"""

import math

import numpy as np
import pytest

from spatialflink_tpu.utils.crs import (
    epsg25831_to_wgs84,
    utm_forward,
    wgs84_to_epsg25831,
)


def test_central_meridian_anchor():
    e, n = utm_forward(3.0, 0.0)
    assert e == pytest.approx(500_000.0, abs=1e-6)
    assert n == pytest.approx(0.0, abs=1e-6)


def test_meridian_arc_scaling():
    # Northing on the central meridian = k0 × meridian arc length.
    # GRS80 meridian arc from equator to 45°N = 4 984 944.378 m
    # (standard series value).
    _, n = utm_forward(3.0, 45.0)
    assert n == pytest.approx(4_984_944.378 * 0.9996, abs=0.01)


def test_equator_easting():
    # On the equator the TM easting is exactly
    # FE + k0·A·asinh(tan λ) with the conformal sphere radius A... use the
    # closed form: t=0 → eta' = asinh(sin λ / cos λ) = asinh(tan λ).
    from spatialflink_tpu.utils.crs import _RECT_A, _ALPHA, K0, FALSE_EASTING

    lam = math.radians(1.0)
    eta_p = math.asinh(math.tan(lam))
    eta = eta_p + sum(
        a * math.cos(2 * j * 0.0) * math.sinh(2 * j * eta_p)
        for j, a in enumerate(_ALPHA, start=1)
    )
    expect = FALSE_EASTING + K0 * _RECT_A * eta
    e, n = utm_forward(4.0, 0.0)
    assert n == pytest.approx(0.0, abs=1e-9)
    assert e == pytest.approx(expect, abs=1e-6)


def test_roundtrip_nm_accuracy(rng):
    lon = rng.uniform(-1.0, 8.0, 500)
    lat = rng.uniform(45.0, 55.0, 500)
    e, n = wgs84_to_epsg25831(lon, lat)
    lon2, lat2 = epsg25831_to_wgs84(e, n)
    assert np.abs(lon2 - lon).max() < 1e-11  # ~1 µm
    assert np.abs(lat2 - lat).max() < 1e-11


def test_brussels_plausibility():
    # Brussels-Central ~ (4.357, 50.845): zone 31N easting ~ 595 km,
    # northing ~ 5633 km; 1.357° east of the central meridian.
    e, n = wgs84_to_epsg25831(4.357, 50.845)
    assert 590_000 < e < 600_000
    assert 5_630_000 < n < 5_640_000


def test_local_scale_is_metric(rng):
    # Distances in projected space must match ellipsoidal distances to
    # within TM scale distortion (<4e-4 near the CM): 100 m steps.
    lon0, lat0 = 4.36, 50.85
    e0, n0 = wgs84_to_epsg25831(lon0, lat0)
    # Move ~100 m north: dlat = 100 / M(lat), M ≈ 6391 km at 50.85°.
    dlat = 100.0 / 111_250.0
    e1, n1 = wgs84_to_epsg25831(lon0, lat0 + dlat)
    d = math.hypot(e1 - e0, n1 - n0)
    assert d == pytest.approx(100.0, rel=2e-3)


def test_jax_backend_matches_numpy():
    import jax.numpy as jnp

    lon = np.array([4.3, 4.4, 4.5])
    lat = np.array([50.8, 50.9, 51.0])
    e_np, n_np = wgs84_to_epsg25831(lon, lat)
    e_j, n_j = wgs84_to_epsg25831(jnp.asarray(lon), jnp.asarray(lat), xp=jnp)
    np.testing.assert_allclose(np.asarray(e_j), e_np, rtol=1e-12)
    np.testing.assert_allclose(np.asarray(n_j), n_np, rtol=1e-12)
