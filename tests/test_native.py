"""Native C++ ingest parser tests: parity with the Python serde + speed."""

import time

import numpy as np
import pytest

from spatialflink_tpu import native
from spatialflink_tpu.sncb.common import csv_to_gps_event
from spatialflink_tpu.streams.serde import parse_csv_point

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library could not be built"
)


def make_lines(n=5000):
    lines = []
    for i in range(n):
        lines.append(
            f"{i*50},dev{i%17},z,4.{i%10},5.{i%7},a,b,c,d,e,f,"
            f"{20.5+(i%30)},{50.6+i*1e-6},{4.36+i*1e-6}"
        )
    return lines


def test_gps_parser_matches_python_serde():
    lines = make_lines(2000)
    p = native.NativeGpsParser()
    out = p.parse("\n".join(lines))
    assert len(out["ts"]) == 2000
    for i in (0, 1, 999, 1999):
        ref = csv_to_gps_event(lines[i])
        assert out["ts"][i] == ref.ts
        assert out["lon"][i] == pytest.approx(ref.lon, rel=1e-15)
        assert out["lat"][i] == pytest.approx(ref.lat, rel=1e-15)
        assert out["speed"][i] == pytest.approx(ref.gps_speed, rel=1e-15)
        assert out["fa"][i] == pytest.approx(ref.fa, rel=1e-15)
        assert out["ff"][i] == pytest.approx(ref.ff, rel=1e-15)
        assert p.device_name(int(out["dev"][i])) == ref.device_id
    assert p.num_devices == 17


def test_gps_parser_interning_stable_across_calls():
    p = native.NativeGpsParser()
    a = p.parse("\n".join(make_lines(100)))
    b = p.parse("\n".join(make_lines(100)))
    np.testing.assert_array_equal(a["dev"], b["dev"])


def test_gps_parser_skips_short_and_junk_lines():
    p = native.NativeGpsParser()
    lines = make_lines(10)
    data = lines[0] + "\nshort,line\n" + lines[1] + "\n\n" + lines[2]
    out = p.parse(data)
    assert len(out["ts"]) == 3
    # Junk numerics → 0 (reference catch-all parity).
    bad = "xx,devA,z,bad,bad,a,b,c,d,e,f,bad,bad,bad"
    out2 = p.parse(bad)
    assert out2["ts"][0] == 0 and out2["lon"][0] == 0.0
    assert p.device_name(int(out2["dev"][0])) == "devA"


def test_point_parser_schema_positions():
    p = native.NativePointParser(schema=(1, 4, 5, 6))
    line = 'ignored, "veh7", a, b, 123456, 116.5, 40.1'
    out = p.parse(line)
    ref = parse_csv_point(line, schema=[1, 4, 5, 6])
    assert out["ts"][0] == ref.timestamp
    assert out["x"][0] == ref.x and out["y"][0] == ref.y
    assert p.object_name(int(out["oid"][0])) == ref.obj_id


def test_native_parser_speed():
    lines = make_lines(200_000)
    data = "\n".join(lines).encode()
    p = native.NativeGpsParser()
    t0 = time.perf_counter()
    out = p.parse(data)
    dt = time.perf_counter() - t0
    assert len(out["ts"]) == 200_000
    rows_per_sec = 200_000 / dt
    # Must beat Python parsing by a wide margin (>2M rows/s native vs
    # ~0.1M for the Python serde on this host).
    assert rows_per_sec > 2_000_000, f"native parser too slow: {rows_per_sec:.0f}/s"
