"""Native C++ ingest parser tests: parity with the Python serde + speed."""

import time

import numpy as np
import pytest

from spatialflink_tpu import native
from spatialflink_tpu.sncb.common import csv_to_gps_event
from spatialflink_tpu.streams.serde import parse_csv_point

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library could not be built"
)


def make_lines(n=5000):
    lines = []
    for i in range(n):
        lines.append(
            f"{i*50},dev{i%17},z,4.{i%10},5.{i%7},a,b,c,d,e,f,"
            f"{20.5+(i%30)},{50.6+i*1e-6},{4.36+i*1e-6}"
        )
    return lines


def test_gps_parser_matches_python_serde():
    lines = make_lines(2000)
    p = native.NativeGpsParser()
    out = p.parse("\n".join(lines))
    assert len(out["ts"]) == 2000
    for i in (0, 1, 999, 1999):
        ref = csv_to_gps_event(lines[i])
        assert out["ts"][i] == ref.ts
        assert out["lon"][i] == pytest.approx(ref.lon, rel=1e-15)
        assert out["lat"][i] == pytest.approx(ref.lat, rel=1e-15)
        assert out["speed"][i] == pytest.approx(ref.gps_speed, rel=1e-15)
        assert out["fa"][i] == pytest.approx(ref.fa, rel=1e-15)
        assert out["ff"][i] == pytest.approx(ref.ff, rel=1e-15)
        assert p.device_name(int(out["dev"][i])) == ref.device_id
    assert p.num_devices == 17


def test_gps_parser_interning_stable_across_calls():
    p = native.NativeGpsParser()
    a = p.parse("\n".join(make_lines(100)))
    b = p.parse("\n".join(make_lines(100)))
    np.testing.assert_array_equal(a["dev"], b["dev"])


def test_gps_parser_skips_short_and_junk_lines():
    p = native.NativeGpsParser()
    lines = make_lines(10)
    data = lines[0] + "\nshort,line\n" + lines[1] + "\n\n" + lines[2]
    out = p.parse(data)
    assert len(out["ts"]) == 3
    # Junk numerics → 0 (reference catch-all parity).
    bad = "xx,devA,z,bad,bad,a,b,c,d,e,f,bad,bad,bad"
    out2 = p.parse(bad)
    assert out2["ts"][0] == 0 and out2["lon"][0] == 0.0
    assert p.device_name(int(out2["dev"][0])) == "devA"


def test_point_parser_schema_positions():
    p = native.NativePointParser(schema=(1, 4, 5, 6))
    line = 'ignored, "veh7", a, b, 123456, 116.5, 40.1'
    out = p.parse(line)
    ref = parse_csv_point(line, schema=[1, 4, 5, 6])
    assert out["ts"][0] == ref.timestamp
    assert out["x"][0] == ref.x and out["y"][0] == ref.y
    assert p.object_name(int(out["oid"][0])) == ref.obj_id


@pytest.mark.slow
def test_native_parser_speed():
    lines = make_lines(200_000)
    data = "\n".join(lines).encode()
    p = native.NativeGpsParser()
    t0 = time.perf_counter()
    out = p.parse(data)
    dt = time.perf_counter() - t0
    assert len(out["ts"]) == 200_000
    rows_per_sec = 200_000 / dt
    # Must beat Python parsing by a wide margin (~0.1M rows/s for the
    # Python serde). Threshold sized for a loaded 2-core box — the
    # parser measures 2-6M rows/s unloaded, ~1M under full contention.
    assert rows_per_sec > 500_000, f"native parser too slow: {rows_per_sec:.0f}/s"


needs_native = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


@needs_native
def test_wkt_parser_roundtrips_serde_output(rng):
    """Native WKT parsing == serde's parse_wkt on serde-rendered lines
    (single-ring polygons and linestrings), with multi-ring and non-WKT
    lines skipped+counted."""
    from spatialflink_tpu.models.objects import LineString, Polygon
    from spatialflink_tpu.native import NativeWktParser
    from spatialflink_tpu.streams.serde import parse_wkt, to_wkt

    objs = []
    for i in range(40):
        cx, cy = rng.uniform(1, 9), rng.uniform(1, 9)
        if i % 2 == 0:
            objs.append(Polygon(
                obj_id=f"p{i}", timestamp=i * 100,
                rings=[np.array([[cx, cy], [cx + .4, cy], [cx + .4, cy + .4],
                                 [cx, cy]])],
            ))
        else:
            objs.append(LineString(
                obj_id=f"l{i}", timestamp=i * 100,
                coords=rng.uniform(0, 10, (4, 2)),
            ))
    # A polygon WITH A HOLE parses natively too (multi-ring chains with
    # seam edges invalidated, pack_rings' layout); junk is skipped.
    objs.append(Polygon(
        obj_id="hole", timestamp=9999,
        rings=[np.array([[0, 0], [5, 0], [5, 5], [0, 0]], float),
               np.array([[1, 1], [2, 1], [1, 2], [1, 1]], float)],
    ))
    lines = [f"{o.obj_id},{o.timestamp},{to_wkt(o)}" for o in objs]
    lines.append("junk,1,POINT (1 2)")

    p = NativeWktParser()
    chunk = p.parse("\n".join(lines))
    assert p.last_skipped == 1
    assert len(chunk["ts"]) == len(objs)
    offsets = np.concatenate([[0], np.cumsum(chunk["lengths"])])
    e_offsets = np.concatenate([[0], np.cumsum(chunk["lengths"] - 1)])
    for i, o in enumerate(objs):
        assert chunk["ts"][i] == o.timestamp
        assert p.object_name(int(chunk["oid"][i])) == o.obj_id
        got = chunk["verts"][offsets[i]:offsets[i + 1]]
        got_ev = chunk["edge_valid"][e_offsets[i]:e_offsets[i + 1]]
        ref = parse_wkt(to_wkt(o))
        pv, pe = ref.packed()
        assert len(got) == len(pv) and len(got_ev) == len(pe)
        np.testing.assert_allclose(got, pv, rtol=0, atol=0)
        np.testing.assert_array_equal(got_ev, pe)
        assert bool(chunk["polygonal"][i]) == isinstance(o, Polygon)


@needs_native
def test_wkt_parser_feeds_geometry_soa_pipeline(rng):
    """Native WKT lines → ragged chunks → geometry run_soa equals the
    serde-object path end to end."""
    from spatialflink_tpu.models.objects import Point, Polygon
    from spatialflink_tpu.native import NativeWktParser
    from spatialflink_tpu.operators import (
        PolygonPointRangeQuery,
        QueryConfiguration,
        QueryType,
    )
    from spatialflink_tpu.streams.serde import parse_wkt

    from spatialflink_tpu.grid import UniformGrid

    grid = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10,
                              slide_step=5)
    lines = []
    objs = []
    for i in range(150):
        cx, cy = rng.uniform(1, 9), rng.uniform(1, 9)
        s = rng.uniform(0.1, 0.3)
        wkt = (f"POLYGON (({cx - s} {cy - s}, {cx + s} {cy - s}, "
               f"{cx + s} {cy + s}, {cx - s} {cy - s}))")
        lines.append(f"poly{i},{i * 200},{wkt}")
        o = parse_wkt(wkt, obj_id=f"poly{i}", timestamp=i * 200)
        objs.append(o)
    q = Point(x=5.0, y=5.0)
    r = 1.0

    obj_res = {
        (res.start, res.end): sorted(
            (p.obj_id, round(float(d), 12))
            for p, d in zip(res.objects, res.dists))
        for res in PolygonPointRangeQuery(conf, grid).run(iter(objs), [q], r)
    }
    parser = NativeWktParser()
    text = "\n".join(lines)
    cut = len(lines) // 2
    chunks = [parser.parse("\n".join(lines[:cut])),
              parser.parse("\n".join(lines[cut:]))]
    assert parser.last_skipped == 0
    soa_res = {
        (s_, e): sorted(
            (parser.object_name(int(o)), round(float(d), 12))
            for o, d in zip(oids, dists))
        for s_, e, idx, oids, dists, cnt in
        PolygonPointRangeQuery(conf, grid).run_soa(iter(chunks), [q], r)
    }
    assert obj_res == soa_res and obj_res


@needs_native
@pytest.mark.slow
def test_wkt_parser_throughput():
    """The native WKT parser must beat the 20k EPS reference target by a
    wide margin (it replaces per-line Python WKT parsing)."""
    import time

    from spatialflink_tpu.native import NativeWktParser

    n = 50_000
    lines = "\n".join(
        f"d{i % 64},{i},POLYGON (({i % 7} 1, 2 1, 2 2, {i % 7} 1))"
        for i in range(n)
    )
    p = NativeWktParser()
    p.parse(lines[:10_000])  # warm
    t0 = time.perf_counter()
    chunk = p.parse(lines)
    dt = time.perf_counter() - t0
    rate = n / dt
    assert len(chunk["ts"]) == n
    # Threshold sized for a loaded 2-core box (measured ~0.9-3M rows/s
    # depending on contention): still 15x the 20k EPS reference target.
    assert rate > 300_000, f"native WKT parse too slow: {rate:.0f} rows/s"


@needs_native
def test_wkt_holes_through_geometry_soa_pipeline(rng):
    """Polygons WITH HOLES through the native parser + ragged SoA range:
    a query point inside a hole must NOT count as contained — parity with
    the object path end to end."""
    from spatialflink_tpu.grid import UniformGrid
    from spatialflink_tpu.models.objects import Point
    from spatialflink_tpu.native import NativeWktParser
    from spatialflink_tpu.operators import (
        PolygonPointRangeQuery,
        QueryConfiguration,
        QueryType,
    )
    from spatialflink_tpu.streams.serde import parse_wkt

    grid = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10,
                              slide_step=10)
    # Donut centered at (5,5): hole spans (4..6)^2; query point sits in
    # the hole, so distance is to the hole boundary, not 0.
    wkts = [
        "donut,100,POLYGON ((2 2, 8 2, 8 8, 2 8, 2 2), "
        "(4 4, 6 4, 6 6, 4 6, 4 4))",
        "solid,200,POLYGON ((1 1, 3 1, 3 3, 1 3, 1 1))",
    ]
    objs = [parse_wkt(w.split(",", 2)[2], obj_id=w.split(",")[0],
                      timestamp=int(w.split(",")[1])) for w in wkts]
    q = Point(x=5.0, y=5.0)
    r = 1.5

    obj_res = [
        sorted((p.obj_id, round(float(d), 12))
               for p, d in zip(res.objects, res.dists))
        for res in PolygonPointRangeQuery(conf, grid).run(iter(objs), [q], r)
    ]
    parser = NativeWktParser()
    chunk = parser.parse("\n".join(wkts))
    assert parser.last_skipped == 0
    soa_res = [
        sorted((parser.object_name(int(o)), round(float(d), 12))
               for o, d in zip(oids, dists))
        for s_, e, idx, oids, dists, cnt in
        PolygonPointRangeQuery(conf, grid).run_soa(iter([chunk]), [q], r)
    ]
    assert soa_res == obj_res
    # The donut's hole keeps the query point OUT: dist = 1.0 to the hole
    # ring, not 0 (containment would make it 0).
    assert obj_res[0] == [("donut", 1.0)]


@pytest.mark.slow
def test_traj_stats_native_bit_identical_to_numpy(rng):
    """sf_traj_stats must reproduce the numpy pane path BIT-FOR-BIT
    (same float association order), sorted and unsorted inputs, including
    the start-boundary corrections."""
    import unittest.mock as mock

    import spatialflink_tpu.native as native
    from spatialflink_tpu.streams import panes

    if not native.available():
        pytest.skip("native library unavailable")
    n = 60_000
    ts = np.sort(rng.integers(0, 12_000, n)).astype(np.int64)
    xy = np.stack([rng.uniform(0, 10, n), rng.uniform(0, 10, n)], axis=1)
    oid = rng.integers(0, 65, n).astype(np.int64)

    for shuffle in (False, True):
        if shuffle:
            perm = rng.permutation(n)
            t_in, xy_in, o_in = ts[perm], xy[perm], oid[perm]
        else:
            t_in, xy_in, o_in = ts, xy, oid
        got = panes.traj_stats_sliding(t_in, xy_in, o_in, 128, 3_000, 10)
        with mock.patch.object(native, "available", lambda: False):
            ref = panes.traj_stats_sliding(t_in, xy_in, o_in, 128, 3_000, 10)
        assert np.array_equal(got.starts, ref.starts)
        assert np.array_equal(got.count, ref.count)
        assert np.array_equal(got.temporal, ref.temporal)
        assert np.array_equal(got.spatial, ref.spatial)  # bitwise


def test_traj_stats_native_rejects_out_of_range_oid(rng):
    import spatialflink_tpu.native as native

    if not native.available():
        pytest.skip("native library unavailable")
    with pytest.raises(ValueError, match="oid out of"):
        native.traj_stats_native(
            np.asarray([0, 10], np.int64), np.zeros(2), np.zeros(2),
            np.asarray([0, 99], np.int32), 8, 1_000, 100,
        )
