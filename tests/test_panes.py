"""Pane-decomposed sliding aggregation: parity with per-window brute force
and with the streaming Q2 implementation."""

import numpy as np
import pytest

from spatialflink_tpu.sncb.common import GpsEvent, PolygonLoader
from spatialflink_tpu.sncb.queries import q2_brake_monitor, q2_brake_monitor_batch
from spatialflink_tpu.streams.panes import sliding_aggregate


def test_sliding_aggregate_matches_brute(rng):
    n = 2000
    ts = np.sort(rng.integers(0, 30_000, n)).astype(np.int64)
    key = rng.integers(0, 5, n)
    val = rng.normal(size=n)
    size, slide = 10_000, 1_000
    win = sliding_aggregate(
        ts, key, 5, size, slide,
        sum_fields={"v": val}, minmax_fields={"v": val}, sumsq=True,
    )
    assert len(win.starts) > 0
    for w, start in enumerate(win.starts):
        in_win = (ts >= start) & (ts < start + size)
        assert in_win.any()  # only alive windows fire
        for k in range(5):
            m = in_win & (key == k)
            assert win.count[w, k] == m.sum()
            if m.any():
                assert win.sums["v"][w, k] == pytest.approx(val[m].sum(), rel=1e-12)
                assert win.sumsqs["v"][w, k] == pytest.approx((val[m] ** 2).sum(), rel=1e-12)
                assert win.mins["v"][w, k] == val[m].min()
                assert win.maxs["v"][w, k] == val[m].max()


def test_sliding_aggregate_requires_divisible():
    with pytest.raises(ValueError, match="multiple"):
        sliding_aggregate(np.array([0]), np.array([0]), 1, 1000, 300)


def test_sliding_aggregate_empty():
    win = sliding_aggregate(np.array([], np.int64), np.array([], np.int64),
                            3, 1000, 100)
    assert len(win.starts) == 0


def test_q2_batch_matches_streaming(rng):
    maint = PolygonLoader.load_geojson_buffered("maintenance_areas.geojson", 0.0)
    events = []
    for i in range(400):
        dev = f"tr{i % 4}"
        fa = 4.0 + (i % 5) * 0.25  # variation 1.0 > 0.6 within windows
        ff = 5.0 + (i % 3) * 0.1  # variation 0.2 <= 0.5
        events.append(
            GpsEvent(dev, 4.45 + (i % 7) * 0.001, 50.90, i * 97, 20.0, fa, ff)
        )
    streaming = list(q2_brake_monitor(iter(events), maint, slide_ms=500))
    batch = q2_brake_monitor_batch(events, maint, slide_ms=500)
    s_set = {(o.win_start, o.win_end, o.device_id,
              round(o.var_fa, 12), round(o.var_ff, 12)) for o in streaming}
    b_set = {(o.win_start, o.win_end, o.device_id,
              round(o.var_fa, 12), round(o.var_ff, 12)) for o in batch}
    # Streaming mode only fires windows the watermark passes (plus flush) —
    # batch replay fires every window containing events. Batch must cover
    # streaming exactly on the common spans.
    assert s_set == {x for x in b_set if x in s_set}
    assert len(b_set) >= len(s_set)
    # And the per-window values agree wherever both fired.
    b_by_key = {(o.win_start, o.device_id): o for o in batch}
    for o in streaming:
        bo = b_by_key[(o.win_start, o.device_id)]
        assert bo.var_fa == pytest.approx(o.var_fa, rel=1e-12)
        assert bo.var_ff == pytest.approx(o.var_ff, rel=1e-12)
        assert bo.count == o.count


def test_q2_batch_throughput(rng):
    """The 10s/10ms reference config (1000x overlap) at meaningful scale."""
    import time

    maint = []
    n = 200_000
    events = [
        GpsEvent(f"d{i%10}", 4.45, 50.9, i // 20, 20.0, 4.0 + (i % 9) * 0.1, 5.0)
        for i in range(n)
    ]
    t0 = time.perf_counter()
    out = q2_brake_monitor_batch(events, maint, window_s=10.0, slide_ms=10)
    dt = time.perf_counter() - t0
    eps = n / dt
    # Streaming mode would touch 1000 windows per event; the pane engine
    # must sustain well beyond the 20k EPS reference target.
    assert eps > 100_000, f"pane engine too slow: {eps:.0f} EPS"


def test_traj_stats_device_matches_numpy(rng):
    """The device pane engine (ops/trajectory.py:traj_stats_pane_kernel)
    must reproduce the numpy oracle: exact ints, 1e-12 floats — sorted
    and shuffled inputs, including the start-boundary corrections."""
    from spatialflink_tpu.streams import panes

    n = 30_000
    ts = np.sort(rng.integers(0, 9_000, n)).astype(np.int64)
    xy = np.stack([rng.uniform(0, 10, n), rng.uniform(0, 10, n)], axis=1)
    oid = rng.integers(0, 65, n).astype(np.int64)

    for shuffle in (False, True):
        if shuffle:
            perm = rng.permutation(n)
            t_in, xy_in, o_in = ts[perm], xy[perm], oid[perm]
        else:
            t_in, xy_in, o_in = ts, xy, oid
        dev = panes.traj_stats_sliding(
            t_in, xy_in, o_in, 128, 3_000, 10, backend="device")
        ref = panes.traj_stats_sliding(
            t_in, xy_in, o_in, 128, 3_000, 10, backend="numpy")
        assert np.array_equal(dev.starts, ref.starts)
        assert np.array_equal(dev.count, ref.count)
        assert np.array_equal(dev.temporal, ref.temporal)
        # segment_sum associates float adds in a different order than
        # bincount: 1e-12 RELATIVE parity (sums here are O(1e3)).
        assert np.allclose(dev.spatial, ref.spatial, rtol=1e-12, atol=5e-12)


def test_traj_stats_device_single_window_and_empty(rng):
    from spatialflink_tpu.streams import panes

    dev = panes.traj_stats_sliding(
        np.asarray([100, 200, 300], np.int64),
        np.asarray([[0.0, 0.0], [3.0, 4.0], [3.0, 8.0]]),
        np.asarray([2, 2, 2], np.int64), 8, 1_000, 1_000,
        backend="device",
    )
    ref = panes.traj_stats_sliding(
        np.asarray([100, 200, 300], np.int64),
        np.asarray([[0.0, 0.0], [3.0, 4.0], [3.0, 8.0]]),
        np.asarray([2, 2, 2], np.int64), 8, 1_000, 1_000,
        backend="numpy",
    )
    assert np.array_equal(dev.starts, ref.starts)
    assert np.array_equal(dev.count, ref.count)
    assert np.allclose(dev.spatial, ref.spatial)
    # tumbling single window: trajectory 2 walked 5 + 4 units
    w = list(ref.starts).index(0)
    assert dev.spatial[w, 2] == 9.0


def test_traj_stats_device_epoch_ms_timestamps(rng):
    """Epoch-ms timestamps (~1.75e12, the real-stream case) must survive
    the device path's int32 rebasing — raw casts would silently wrap."""
    from spatialflink_tpu.streams import panes

    base = 1_753_900_000_000  # ~2025 epoch ms
    n = 5_000
    ts = base + np.sort(rng.integers(0, 6_000, n)).astype(np.int64)
    xy = np.stack([rng.uniform(0, 10, n), rng.uniform(0, 10, n)], axis=1)
    oid = rng.integers(0, 32, n).astype(np.int64)
    dev = panes.traj_stats_sliding(ts, xy, oid, 64, 3_000, 100,
                                   backend="device")
    ref = panes.traj_stats_sliding(ts, xy, oid, 64, 3_000, 100,
                                   backend="numpy")
    assert np.array_equal(dev.starts, ref.starts)
    assert np.array_equal(dev.count, ref.count)
    assert np.array_equal(dev.temporal, ref.temporal)
    assert np.allclose(dev.spatial, ref.spatial, rtol=1e-12, atol=5e-12)


def test_traj_stats_device_rejects_int32_overflow_span(rng):
    from spatialflink_tpu.streams import panes

    ts = np.asarray([0, np.iinfo(np.int32).max + 10_000], np.int64)
    with pytest.raises(ValueError, match="int32 ms range"):
        panes.traj_stats_sliding(
            ts, np.zeros((2, 2)), np.zeros(2, np.int64), 8, 1_000, 1_000,
            backend="device",
        )


def test_traj_stats_native_forced_raises_when_unavailable(rng):
    import unittest.mock as mock

    import spatialflink_tpu.native as native
    from spatialflink_tpu.streams import panes

    with mock.patch.object(native, "available", lambda: False):
        with pytest.raises(RuntimeError, match="native"):
            panes.traj_stats_sliding(
                np.asarray([0, 10], np.int64), np.zeros((2, 2)),
                np.zeros(2, np.int64), 8, 1_000, 1_000, backend="native",
            )
