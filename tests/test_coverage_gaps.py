"""Coverage for operator classes and stream paths not exercised elsewhere:
geometry-stream kNN, linestring range variants, socket source, CLI options."""

import socket
import threading

import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import LineString, Point, Polygon
from spatialflink_tpu.operators import (
    LineStringLineStringRangeQuery,
    LineStringPointKNNQuery,
    PointLineStringRangeQuery,
    PolygonPointKNNQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.streams.serde import parse_csv_point
from spatialflink_tpu.streams.sources import socket_source

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
W30 = QueryConfiguration(QueryType.WindowBased, window_size=30, slide_step=30)


def _squares(rng, n, size=0.4):
    out = []
    for i in range(n):
        cx, cy = rng.uniform(1, 9), rng.uniform(1, 9)
        out.append(Polygon(
            obj_id=f"poly{i}", timestamp=i * 100,
            rings=[np.array([[cx - size, cy - size], [cx + size, cy - size],
                             [cx + size, cy + size], [cx - size, cy + size],
                             [cx - size, cy - size]])],
        ))
    return out


def _jts_point_poly_dist(q, ring):
    """JTS point.distance(polygon): 0 inside, else min edge distance."""
    verts = np.vstack([ring])
    seg_min = np.inf
    for a, b in zip(verts[:-1], verts[1:]):
        ab = b - a
        t = np.clip(np.dot(q - a, ab) / np.dot(ab, ab), 0, 1)
        seg_min = min(seg_min, float(np.linalg.norm(a + t * ab - q)))
    # Even-odd point-in-polygon.
    inside = False
    for a, b in zip(verts[:-1], verts[1:]):
        if (a[1] > q[1]) != (b[1] > q[1]):
            xcross = a[0] + (q[1] - a[1]) / (b[1] - a[1]) * (b[0] - a[0])
            if q[0] < xcross:
                inside = not inside
    return 0.0 if inside else seg_min


def test_polygon_stream_knn(rng):
    """PolygonPointKNNQuery: JTS getDistance semantics (0 inside)."""
    polys = _squares(rng, 30)
    q = Point(x=5.0, y=5.0)
    results = list(PolygonPointKNNQuery(W30, GRID).run(iter(polys), q, 6.0, 5))
    assert results
    res = results[0]
    assert 1 <= len(res.neighbors) <= 5
    dists = [d for _, d, _ in res.neighbors]
    assert dists == sorted(dists)
    for oid, d, obj in res.neighbors:
        assert d == pytest.approx(
            _jts_point_poly_dist(np.array([5.0, 5.0]), obj.rings[0]), abs=1e-9
        )


def test_polygon_stream_knn_containment_is_zero(rng):
    """A polygon containing the query point ranks first with distance 0
    (JTS point.distance(polygon) == 0 inside — DistanceFunctions.java:15-54
    via getDistance; ADVICE round-1 medium finding)."""
    polys = _squares(rng, 10, size=0.3)
    polys.append(Polygon(
        obj_id="around", timestamp=0,
        rings=[np.array([[4.0, 4.0], [6.0, 4.0], [6.0, 6.0],
                         [4.0, 6.0], [4.0, 4.0]])],
    ))
    q = Point(x=5.0, y=5.0)
    results = list(PolygonPointKNNQuery(W30, GRID).run(iter(polys), q, 6.0, 3))
    top = results[0].neighbors[0]
    assert top[0] == "around"
    assert top[1] == 0.0


def test_linestring_stream_knn(rng):
    lines = [
        LineString(obj_id=f"ls{i}", timestamp=i * 100,
                   coords=np.array([[i * 0.3, 0.0], [i * 0.3, 10.0]]))
        for i in range(20)
    ]
    q = Point(x=5.0, y=5.0)
    results = list(LineStringPointKNNQuery(W30, GRID).run(iter(lines), q, 8.0, 3))
    res = results[0]
    # Vertical lines at x = 0.3i; nearest to x=5 are i=17 (x=5.1), i=16 (4.8)...
    got = [oid for oid, _, _ in res.neighbors]
    dists = {oid: abs(0.3 * i - 5.0) for i, oid in enumerate(f"ls{i}" for i in range(20))}
    expect = sorted(dists, key=dists.get)[:3]
    assert got == expect


def test_point_linestring_range(rng):
    pts = [Point(obj_id=f"p{i}", timestamp=i * 100,
                 x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
           for i in range(300)]
    ls = LineString(coords=np.array([[0.0, 5.0], [10.0, 5.0]]))  # horizontal
    results = list(PointLineStringRangeQuery(W30, GRID).run(iter(pts), [ls], 0.5))
    got = {p.obj_id for r in results for p in r.objects}
    expect = {p.obj_id for p in pts if abs(p.y - 5.0) <= 0.5}
    assert got == expect


def test_linestring_linestring_range(rng):
    lines = [
        LineString(obj_id=f"ls{i}", timestamp=i * 100,
                   coords=np.array([[1.0 + i * 0.5, 1.0], [1.0 + i * 0.5, 2.0]]))
        for i in range(10)
    ]
    q = LineString(coords=np.array([[3.0, 0.0], [3.0, 9.0]]))
    results = list(
        LineStringLineStringRangeQuery(W30, GRID).run(iter(lines), [q], 0.6)
    )
    got = {l.obj_id for r in results for l in r.objects}
    # Lines at x = 1 + 0.5i within 0.6 of x=3: i in {3, 4, 5, 6, 7} →
    # x ∈ {2.5, 3.0, 3.5} within; 2.5 and 3.5 are at exactly 0.5 ≤ 0.6.
    expect = {f"ls{i}" for i in range(10) if abs(1.0 + 0.5 * i - 3.0) <= 0.6}
    assert got == expect


def test_socket_source_loopback():
    """socket_source against a live loopback server."""
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.bind(("127.0.0.1", 0))
    server.listen(1)
    port = server.getsockname()[1]

    def serve():
        conn, _ = server.accept()
        conn.sendall(b"a,100,1.0,2.0\nGARBAGE\nb,200,3.0,4.0\n")
        conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    pts = list(socket_source("127.0.0.1", port,
                             lambda ln: parse_csv_point(ln, schema=[0, 1, 2, 3])))
    t.join(timeout=5)
    server.close()
    assert [(p.obj_id, p.x) for p in pts] == [("a", 1.0), ("b", 3.0)]


@pytest.mark.slow
def test_streaming_job_remaining_options(tmp_path):
    """CLI options 2 (realtime range), 5 (join), 7 (tAggregate),
    8 (multi-query kNN)."""
    from spatialflink_tpu.streaming_job import main

    base = """
inputStream1:
  topicName: t
  format: CSV
  csvTsvSchemaAttr: [0, 1, 2, 3]
  gridBBox: [0.0, 0.0, 10.0, 10.0]
  numGridCells: 20
  delimiter: ","
query:
  option: {opt}
  radius: 3.0
  k: 2
  aggregateFunction: "SUM"
  queryPoints:
    - [5.0, 5.0]
    - [4.5, 5.2]
window:
  type: "TIME"
  interval: 10
  step: 10
"""
    csv = tmp_path / "in.csv"
    # Option 5 splits the stream into halves; keep both halves in the same
    # time range (each half internally sorted) so join windows overlap.
    csv.write_text("\n".join(
        f"dev{i%3},{(i % 40) * 250},{4 + 0.02*(i % 40)},{5 + 0.01*(i % 40)}"
        for i in range(80)
    ))
    for opt in (2, 5, 7, 8):
        conf = tmp_path / f"c{opt}.yml"
        conf.write_text(base.format(opt=opt))
        out = tmp_path / f"o{opt}.csv"
        rc = main(["--config", str(conf), "--source", f"csv:{csv}",
                   "--output", str(out)])
        assert rc == 0
        assert out.read_text().strip(), f"option {opt} produced no output"


@pytest.mark.slow
def test_streaming_job_incremental_flag_matches_full(tmp_path):
    """query.incremental: true routes options 1/3/5 through the carry
    paths; CLI output must equal the full-recompute run line for line
    (order-insensitive for the join's block-major ordering)."""
    from spatialflink_tpu.streaming_job import main

    base = """
inputStream1:
  topicName: t
  format: CSV
  csvTsvSchemaAttr: [0, 1, 2, 3]
  gridBBox: [0.0, 0.0, 10.0, 10.0]
  numGridCells: 20
  delimiter: ","
query:
  option: {opt}
  radius: 3.0
  k: 4
  incremental: {inc}
  aggregateFunction: "SUM"
  queryPoints:
    - [5.0, 5.0]
window:
  type: "TIME"
  interval: 10
  step: 5
"""
    csv = tmp_path / "in.csv"
    csv.write_text("\n".join(
        f"dev{i%5},{i * 120},{3 + 0.05*(i % 60)},{4 + 0.03*(i % 60)}"
        for i in range(160)
    ))
    for opt in (1, 3, 5):
        outs = {}
        for inc in ("false", "true"):
            conf = tmp_path / f"c{opt}_{inc}.yml"
            conf.write_text(base.format(opt=opt, inc=inc))
            out = tmp_path / f"o{opt}_{inc}.csv"
            rc = main(["--config", str(conf), "--source", f"csv:{csv}",
                       "--output", str(out)])
            assert rc == 0
            outs[inc] = sorted(out.read_text().strip().splitlines())
        assert outs["false"] == outs["true"], f"option {opt}"
        assert outs["true"], f"option {opt} produced no output"
