"""Latency lineage (ISSUE 19): the event-time e2e engine in
telemetry.py (anchored staleness, cumulative stage buckets, bounded
open-window set), the flight-recorder black box (ring → dump →
``sfprof blackbox`` / ``recover`` fold), ``sfprof critical``'s
straggler + conservation receipt, and the live follower's e2e lines.
The SLO ceilings over these gauges live in tests/test_slo.py; the
un-armed byte-compat pin lives with the other shape pins in
tests/test_dagmon.py."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from spatialflink_tpu import dag as dag_mod  # noqa: E402
from spatialflink_tpu import overload, qserve  # noqa: E402
from spatialflink_tpu.dag import build_sncb_dag, _toy_sncb_stream  # noqa: E402
from spatialflink_tpu.driver import (  # noqa: E402
    RetryPolicy,
    WindowedDataflowDriver,
)
from spatialflink_tpu.telemetry import telemetry  # noqa: E402
from tools.sfprof import critical as critical_mod  # noqa: E402
from tools.sfprof import live as live_mod  # noqa: E402
from tools.sfprof import stream as stream_mod  # noqa: E402
from tools.sfprof.cli import main as sfprof_main  # noqa: E402


@pytest.fixture(autouse=True)
def _clean():
    yield
    telemetry.disable()
    dag_mod.uninstall()
    qserve.uninstall()
    overload.uninstall()


# -- the e2e engine -----------------------------------------------------------


class TestE2EEngine:
    def test_stages_are_cumulative_and_commit_closes(self):
        telemetry.enable()
        seen = []
        for stage in telemetry.E2E_STAGES:
            seen.append(telemetry.record_e2e(1_000, stage))
        # Cumulative: each later stage records assemble + elapsed —
        # monotone nondecreasing per window by construction.
        assert all(b >= a for a, b in zip(seen, seen[1:])), seen
        e2e = telemetry.e2e_gauges()
        for stage in telemetry.E2E_STAGES:
            assert e2e["stages"][stage]["count"] == 1
            assert e2e["stages"][stage]["p99_ms"] is not None
        # commit closed the entry; a second window stays open.
        assert e2e["open_windows"] == 0
        telemetry.record_e2e(2_000, "assemble")
        assert telemetry.e2e_gauges()["open_windows"] == 1
        p50, p99 = telemetry.e2e_stage_percentiles("commit")
        assert p50 is not None and p99 is not None and p99 >= p50
        assert telemetry.e2e_stage_percentiles("commit",
                                               node="ghost") == (None,
                                                                 None)

    def test_anchor_maps_event_time_onto_wall_clock(self):
        """The first stamp anchors event-time onto the wall clock, so a
        synthetic event clock measures honest pipeline staleness: the
        anchor window reads ≈0, a window 10 s in the event-time PAST
        reads ≈10 s, and an event-time FUTURE clamps to ≥0 — never
        wall-minus-epoch nonsense."""
        telemetry.enable()
        a = telemetry.record_e2e(10_000, "assemble")
        assert 0.0 <= a < 5_000.0  # anchor window: no staleness yet
        past = telemetry.record_e2e(0, "assemble")
        assert past >= 9_000.0  # 10 s stale relative to the anchor
        future = telemetry.record_e2e(60_000, "assemble")
        assert 0.0 <= future < 5_000.0  # clamped, not negative
        anchor = telemetry.e2e_gauges()["anchor"]
        assert anchor["event_ms"] == 10_000.0

    def test_open_set_is_bounded_and_evictions_are_counted(self):
        telemetry.enable()
        telemetry.E2E_OPEN_MAX = 8  # instance override, class untouched
        try:
            for i in range(12):
                telemetry.record_e2e(i * 1_000, "assemble")
            e2e = telemetry.e2e_gauges()
            assert e2e["open_windows"] == 8
            assert e2e["evicted"] == 4
        finally:
            del telemetry.E2E_OPEN_MAX

    def test_disabled_is_free_and_unarmed_gauges_are_none(self):
        assert telemetry.record_e2e(1_000, "commit") is None
        telemetry.enable()
        assert telemetry.e2e_gauges() is None  # v2 byte-compat shape

    def test_e2e_block_rides_ledger_and_stream(self, tmp_path):
        stream = str(tmp_path / "s.jsonl")
        telemetry.enable(stream_path=stream)
        with telemetry.scope("q1"):
            telemetry.record_e2e(1_000, "compute")
        telemetry.record_e2e(1_000, "commit")
        telemetry.maybe_flush_stream(force=True)
        ledger = str(tmp_path / "ledger.json")
        telemetry.write_ledger(ledger, capture_costs=False)
        telemetry.disable()
        with open(ledger) as f:
            doc = json.load(f)
        assert doc["ledger_version"] == 3
        block = doc["snapshot"]["e2e"]
        assert block["stages"]["commit"]["count"] == 1
        assert block["nodes"]["q1"]["compute"]["count"] == 1
        recs, _tail = stream_mod.read_records(stream)
        cks = [r for r in recs if r.get("t") == "checkpoint"]
        assert cks and "e2e" in cks[-1]["snapshot"]


# -- the flight recorder ------------------------------------------------------


class TestBlackbox:
    def test_seal_dumps_a_parseable_blackbox(self, tmp_path):
        stream = str(tmp_path / "s.jsonl")
        telemetry.enable(stream_path=stream)
        with telemetry.span("window.eval", events=3):
            pass
        telemetry.record_e2e(1_000, "commit")
        telemetry.seal_stream("test_seal")
        telemetry.disable()
        dump = stream + ".blackbox.json"
        with open(dump) as f:
            doc = json.load(f)
        assert doc["blackbox_version"] == 1
        assert doc["reason"] == "test_seal"
        assert doc["stream"] == stream
        kinds = {r["t"] for r in doc["ring"]}
        assert "window" in kinds  # the ring kept the window summary
        assert doc["counters"]["events"] >= 1
        assert doc["e2e"]["stages"]["commit"]["count"] == 1
        # The marker instant landed in the stream's final span batch.
        recs, _tail = stream_mod.read_records(stream)
        names = [e.get("name") for r in recs if r.get("t") == "spans"
                 for e in r.get("events") or []]
        assert "blackbox_dumped" in names

    def test_env_zero_disables_the_ring(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SFT_BLACKBOX", "0")
        stream = str(tmp_path / "s.jsonl")
        telemetry.enable(stream_path=stream)
        with telemetry.span("window.eval"):
            pass
        telemetry.seal_stream("test_seal")
        telemetry.disable()
        assert not os.path.exists(stream + ".blackbox.json")

    def test_blackbox_cli_renders_and_rejects(self, tmp_path, capsys):
        stream = str(tmp_path / "s.jsonl")
        telemetry.enable(stream_path=stream)
        with telemetry.span("window.eval", events=3):
            pass
        telemetry.seal_stream("test_seal")
        telemetry.disable()
        dump = stream + ".blackbox.json"
        assert sfprof_main(["blackbox", dump]) == 0
        out = capsys.readouterr().out
        assert "reason=test_seal" in out or "test_seal" in out
        assert sfprof_main(["blackbox", dump, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["blackbox_version"] == 1
        bogus = str(tmp_path / "bogus.json")
        with open(bogus, "w") as f:
            f.write("[1, 2]\n")
        assert sfprof_main(["blackbox", bogus]) == 2
        capsys.readouterr()

    def test_recover_folds_ring_instants_newer_than_the_stream(
            self, tmp_path, capsys):
        """Kill -9 between flushes: the ring holds instants the stream
        never got — ``recover`` folds exactly those (ts newer than the
        last flushed batch), marked ``blackbox: true`` for provenance,
        and the CLI prints the fold as evidence."""
        stream = str(tmp_path / "s.jsonl")
        telemetry.enable(stream_path=stream)
        with telemetry.span("window.eval", events=2):
            pass
        telemetry.maybe_flush_stream(force=True)
        # After the last flush: buffered + ringed, never streamed.
        telemetry.emit_instant("fault_fired:test.point", kind="raise")
        data = open(stream, "rb").read()
        assert telemetry.dump_blackbox("test_crash") is not None
        bb = open(stream + ".blackbox.json", "rb").read()
        telemetry.disable()

        crash = str(tmp_path / "crash.jsonl")
        with open(crash, "wb") as f:
            f.write(data)  # the unsealed prefix, the kill -9 shape
        with open(crash + ".blackbox.json", "wb") as f:
            f.write(bb)
        doc, info = stream_mod.recover(crash)
        assert info["blackbox_folded"] is True
        assert info["blackbox_reason"] == "test_crash"
        assert info["blackbox_events_folded"] >= 1
        folded = [e for e in doc["events"] if e.get("blackbox")]
        assert any(e["name"] == "fault_fired:test.point" for e in folded)
        # Already-flushed ring records are NOT duplicated.
        names = [e.get("name") for e in doc["events"]]
        assert names.count("fault_fired:test.point") == 1
        assert sfprof_main(["recover", crash]) == 0
        out = capsys.readouterr().out
        assert "blackbox dump folded" in out
        assert "test_crash" in out

    def test_recover_without_dump_is_unchanged(self, tmp_path):
        stream = str(tmp_path / "s.jsonl")
        telemetry.enable(stream_path=stream)
        with telemetry.span("window.eval"):
            pass
        telemetry.maybe_flush_stream(force=True)
        # No .blackbox.json beside a COPY of the stream.
        data = open(stream, "rb").read()
        telemetry.disable()
        bare = str(tmp_path / "bare.jsonl")
        with open(bare, "wb") as f:
            f.write(data)
        doc, info = stream_mod.recover(bare)
        assert info["blackbox_folded"] is False
        assert not any(e.get("blackbox") for e in doc["events"])


# -- sfprof critical ----------------------------------------------------------


def _span(name, ts, dur, args=None):
    return {"name": name, "cat": "telemetry", "ph": "X", "ts": ts,
            "dur": dur, "pid": 1, "tid": 1, "args": args or {}}


def _synthetic_ledger(tmp_path, e2e_commit_p99_ms):
    """Three windows; node.b (300 us) always dominates node.a (100 us).
    Path p99 = 400 us = 0.4 ms."""
    events = []
    t = 0
    for _ in range(3):
        events.append(_span("window.dag", t, 450))
        events.append(_span("node.a", t + 10, 100, {"node": "a"}))
        events.append(_span("node.b", t + 120, 300, {"node": "b"}))
        t += 1_000
    doc = {
        "ledger_version": 3, "created_unix": 0.0,
        "snapshot": {"e2e": {"stages": {"commit": {
            "count": 3, "sum_ms": 1.0,
            "p50_ms": e2e_commit_p99_ms, "p99_ms": e2e_commit_p99_ms,
        }}}},
        "events": events,
    }
    path = str(tmp_path / "ledger.json")
    with open(path, "w") as f:
        json.dump(doc, f)
    return path, doc, events


class TestCritical:
    def test_straggler_and_conservation_ok(self, tmp_path, capsys):
        path, doc, events = _synthetic_ledger(tmp_path, 0.5)
        res = critical_mod.analyze(doc, events)
        assert res["windows"] == 3
        assert res["stragglers"]["p99"]["node"] == "b"
        assert res["stragglers"]["p50"]["node"] == "b"
        assert res["nodes"]["b"]["share"] > res["nodes"]["a"]["share"]
        cons = res["conservation"]
        assert cons["ok"] is True
        assert cons["path_p99_ms"] == pytest.approx(0.4)
        assert cons["e2e_commit_p99_ms"] == 0.5
        assert sfprof_main(["critical", path]) == 0
        out = capsys.readouterr().out
        assert "straggler @p99: b" in out
        assert "conservation receipt [ok]" in out
        assert "↳" in out  # evidence chain, not a bare verdict

    def test_conservation_fail_exits_one(self, tmp_path, capsys):
        # e2e commit p99 SMALLER than the path sum: the span graph and
        # the lineage clocks disagree — exit 1, loud evidence.
        path, _doc, _events = _synthetic_ledger(tmp_path, 0.1)
        assert sfprof_main(["critical", path]) == 1
        out = capsys.readouterr().out
        assert "conservation receipt [FAIL]" in out
        assert "DISAGREE" in out

    def test_missing_signals_are_notes_not_failures(self, tmp_path,
                                                    capsys):
        path = str(tmp_path / "empty.json")
        with open(path, "w") as f:
            json.dump({"ledger_version": 3, "snapshot": {},
                       "events": []}, f)
        assert sfprof_main(["critical", path]) == 0
        assert "note:" in capsys.readouterr().out
        assert sfprof_main(["critical",
                            str(tmp_path / "nope.json")]) == 2
        capsys.readouterr()

    def test_json_mode_round_trips(self, tmp_path, capsys):
        path, _doc, _events = _synthetic_ledger(tmp_path, 0.5)
        assert sfprof_main(["critical", path, "--json"]) == 0
        res = json.loads(capsys.readouterr().out)
        assert res["stragglers"]["p99"]["node"] == "b"
        assert res["conservation"]["ok"] is True

    def test_straggler_line_falls_back_to_e2e_nodes(self):
        doc = {"snapshot": {"e2e": {"nodes": {
            "q1": {"compute": {"p99_ms": 2.0}},
            "q2": {"compute": {"p99_ms": 9.0}},
        }}}}
        line = critical_mod.straggler_line(doc, [])
        assert line is not None and "q2" in line

    def test_critical_on_a_real_sncb_dag_capture(self, tmp_path,
                                                 capsys):
        """The acceptance criterion: a real 7-node SNCB DAG capture
        names a straggler and its conservation receipt holds — path
        segments sum ≤ the measured e2e commit p99."""
        telemetry.enable()
        dag = build_sncb_dag(
            str(tmp_path / "egress"), qserve_queries=None,
            retry=RetryPolicy(max_retries=1, backoff_s=0.0),
        )
        driver = WindowedDataflowDriver(
            checkpoint_path=str(tmp_path / "ckpt.bin"),
            checkpoint_every=2, sink=None,
            retry=RetryPolicy(max_retries=1, backoff_s=0.0),
            failover=False,
        )
        for _ in dag.run(_toy_sncb_stream(150)(), driver=driver):
            pass
        ledger = str(tmp_path / "ledger.json")
        telemetry.write_ledger(ledger, capture_costs=False)
        telemetry.disable()
        assert sfprof_main(["critical", ledger]) == 0
        out = capsys.readouterr().out
        assert "straggler @p99:" in out
        assert "conservation receipt [ok]" in out
        with open(ledger) as f:
            doc = json.load(f)
        res = critical_mod.analyze(doc, doc["events"])
        assert res["windows"] > 0
        assert set(res["nodes"]) >= {"q1", "staytime"}
        cons = res["conservation"]
        assert cons is not None and cons["ok"] is True
        assert cons["path_p99_ms"] <= cons["e2e_commit_p99_ms"]


# -- the live follower --------------------------------------------------------


class TestLiveE2E:
    def test_live_json_carries_e2e_and_straggler(self, tmp_path,
                                                 capsys):
        stream = str(tmp_path / "s.jsonl")
        telemetry.enable(stream_path=stream)
        with telemetry.scope("q1"):
            telemetry.record_e2e(1_000, "compute")
        telemetry.record_e2e(1_000, "commit")
        telemetry.maybe_flush_stream(force=True)
        telemetry.disable()  # seals
        assert live_mod.follow(stream, 0.05, None, json_mode=True) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["e2e"]["stages"]["commit"]["count"] == 1
        assert doc["straggler"]["node"] == "q1"
        assert isinstance(doc["straggler"]["e2e_compute_p99_ms"], float)
        # Human mode prints the e2e head + straggler line per checkpoint.
        assert live_mod.follow(stream, 0.05, 5.0, json_mode=False) == 0
        out = capsys.readouterr().out
        assert "e2e p99" in out
        assert "straggler: q1" in out

    def test_live_without_e2e_has_null_straggler(self, tmp_path,
                                                 capsys):
        stream = str(tmp_path / "s.jsonl")
        telemetry.enable(stream_path=stream)
        with telemetry.span("window.eval"):
            pass
        telemetry.maybe_flush_stream(force=True)
        telemetry.disable()
        assert live_mod.follow(stream, 0.05, None, json_mode=True) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["e2e"] is None and doc["straggler"] is None
