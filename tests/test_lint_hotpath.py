"""Tier-1 hot-path lint: the repo's ``ops/`` kernels must stay free of
import-time jax.numpy dispatches and in-kernel wall-clock reads, and the
lint itself must catch both leak classes.

``tools/lint_hotpath.py`` is now a deprecation SHIM over the sfcheck
framework's ``hotpath`` pass (tools/sfcheck). Every behavioral test here
deliberately runs through the shim — same CLI, same exit codes, same
``(path, lineno, message)`` tuples — so the back-compat surface is what
CI pins.
"""

import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import lint_hotpath  # noqa: E402


def _lint(src: str, name: str = "mod.py"):
    return lint_hotpath.lint_source(name, textwrap.dedent(src))


def test_repo_ops_is_clean():
    violations = lint_hotpath.lint_paths([lint_hotpath.default_target()])
    assert violations == [], "\n".join(
        f"{p}:{ln}: {m}" for p, ln, m in violations
    )


def test_flags_module_level_jnp_call():
    (v,) = _lint("""
        import jax.numpy as jnp
        PAD = jnp.zeros((8,))
    """)
    assert v[1] == 3 and "module-level jax.numpy" in v[2]


def test_flags_from_jax_import_numpy_and_direct_name():
    vs = _lint("""
        from jax import numpy as jn
        from jax.numpy import full
        A = jn.ones(4)
        B = full((2,), 0.0)
    """)
    assert [v[1] for v in vs] == [4, 5]


def test_function_scoped_jnp_is_fine():
    assert _lint("""
        import jax.numpy as jnp
        def kernel(x):
            return jnp.sum(x)
    """) == []


def test_default_arg_counts_as_module_level():
    (v,) = _lint("""
        import jax.numpy as jnp
        def kernel(x, pad=jnp.zeros(4)):
            return x + pad
    """)
    assert "module-level" in v[2]


def test_flags_wall_clock_inside_function():
    vs = _lint("""
        import time
        from time import perf_counter as pc
        def kernel(x):
            t0 = time.time()
            t1 = pc()
            return x, t0, t1
    """)
    assert [v[1] for v in vs] == [5, 6]
    assert all("wall-clock" in v[2] for v in vs)


def test_module_level_wall_clock_not_flagged():
    # Import-time timestamps run once on the host — not a kernel leak.
    assert _lint("""
        import time
        T0 = time.time()
    """) == []


def test_pragma_suppresses():
    assert _lint("""
        import time
        def host_tally():
            return time.time()  # sfcheck: ok=hotpath -- host-side tally
    """) == []


def test_legacy_pragma_spelling_still_honored():
    # In-tree code uses only the canonical `# sfcheck: ok=<pass> -- why`
    # spelling, but the shim's legacy_pragma regex keeps the pre-sfcheck
    # form working for out-of-tree callers of lint_hotpath — this pin is
    # the contract (tests/fixtures/sfcheck/pragmas_ok.py carries the
    # fixture twin).
    assert _lint("""
        import time
        def host_tally():
            return time.time()  # hotpath: ok
    """) == []


def test_lambda_default_counts_as_module_level():
    (v,) = _lint("""
        import jax.numpy as jnp
        f = lambda x, p=jnp.zeros(8): x + p
    """)
    assert "module-level jax.numpy call" in v[2]


def test_pragma_suppresses_on_any_line_of_a_multiline_call():
    # Formatter-wrapped calls keep their suppression: the pragma can sit
    # on any line the call spans, not just the first.
    assert _lint("""
        import jax.numpy as jnp
        PAD = jnp.full(
            (8,), 0.0,
        )  # sfcheck: ok=hotpath -- module-level pad constant
    """) == []


def test_allowlisted_host_module_skipped(tmp_path):
    bad = "import time\ndef f():\n    return time.time()\n"
    allowed = tmp_path / "counters.py"
    allowed.write_text(bad)
    flagged = tmp_path / "kern.py"
    flagged.write_text(bad)
    assert lint_hotpath.lint_file(str(allowed)) == []
    assert len(lint_hotpath.lint_file(str(flagged))) == 1


def test_cli_exit_codes(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("import numpy as np\nX = np.zeros(3)\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import jax.numpy as jnp\nX = jnp.zeros(3)\n")
    tool = os.path.join(REPO, "tools", "lint_hotpath.py")
    ok = subprocess.run([sys.executable, tool, str(clean)],
                        capture_output=True, text=True)
    assert ok.returncode == 0 and ok.stdout == ""
    bad = subprocess.run([sys.executable, tool, str(dirty)],
                         capture_output=True, text=True)
    assert bad.returncode == 1
    assert "dirty.py:2" in bad.stdout


# -- shim-specific: the old surface must be the sfcheck hotpath pass ---------

def test_shim_delegates_to_sfcheck():
    # The shim's implementation IS the registered sfcheck pass — not a
    # drifting copy of the rules.
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.sfcheck import core
    from tools.sfcheck.passes import get_pass

    assert lint_hotpath._PASS.name == "hotpath"
    assert type(lint_hotpath._PASS) is type(get_pass("hotpath"))

    src = "import jax.numpy as jnp\nX = jnp.zeros(3)\n"
    via_shim = lint_hotpath.lint_source("m.py", src)
    via_sfcheck = core.check_source("m.py", src, [get_pass("hotpath")],
                                    force=True)
    assert via_shim == [(f.path, f.lineno, f.message) for f in via_sfcheck]


def test_sfcheck_pragma_suppresses_via_shim():
    # New-style pragmas work through the old entry point too.
    assert _lint("""
        import jax.numpy as jnp
        PAD = jnp.zeros(8)  # sfcheck: ok=hotpath -- test fixture
    """) == []
    # …but a pragma naming a different pass does not.
    (v,) = _lint("""
        import jax.numpy as jnp
        PAD = jnp.zeros(8)  # sfcheck: ok=fixed-shape -- wrong pass
    """)
    assert "module-level jax.numpy" in v[2]
