"""Serde format-contract tests: GeoJSON (Kafka envelope + bare), WKT round
trips for all 7 geometry types, CSV/TSV schema positions, date formats."""

import json

import numpy as np
import pytest

from spatialflink_tpu.models.objects import (
    GeometryCollection,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)
from spatialflink_tpu.streams.serde import (
    parse_csv_point,
    parse_geojson,
    parse_timestamp,
    parse_wkt,
    to_csv_point,
    to_geojson,
    to_wkt,
)

# The exact sample from Deserialization.java:121 comment.
KAFKA_ENVELOPE = (
    '{"key":136138,"value":{"geometry":{"coordinates":[116.44412,39.93984],'
    '"type":"Point"},"properties":{"oID":"2560","timestamp":"2008-02-02 20:12:32"},'
    '"type":"Feature"}}'
)


def test_parse_kafka_envelope_point():
    p = parse_geojson(KAFKA_ENVELOPE, date_format="yyyy-MM-dd HH:mm:ss")
    assert isinstance(p, Point)
    assert p.x == pytest.approx(116.44412)
    assert p.y == pytest.approx(39.93984)
    assert p.obj_id == "2560"
    # 2008-02-02 20:12:32 UTC
    assert p.timestamp == 1201983152000


def test_parse_bare_feature_epoch_ts():
    rec = {
        "type": "Feature",
        "geometry": {"type": "Point", "coordinates": [1.0, 2.0]},
        "properties": {"oID": 77, "timestamp": 1234567},
    }
    p = parse_geojson(rec)
    assert p.obj_id == "77" and p.timestamp == 1234567


def test_parse_bare_geometry():
    p = parse_geojson('{"type": "Point", "coordinates": [3.5, 4.5]}')
    assert (p.x, p.y) == (3.5, 4.5)
    assert p.obj_id is None


def test_geojson_polygon_with_hole_roundtrip():
    poly = Polygon(
        obj_id="p1",
        timestamp=42,
        rings=[
            np.array([[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]], float),
            np.array([[1, 1], [2, 1], [2, 2], [1, 2], [1, 1]], float),
        ],
    )
    s = to_geojson(poly)
    back = parse_geojson(s)
    assert isinstance(back, Polygon)
    assert len(back.rings) == 2
    np.testing.assert_allclose(back.rings[1], poly.rings[1])


def test_geojson_all_types_roundtrip():
    objs = [
        MultiPoint(obj_id="mp", coords=np.array([[1, 2], [3, 4]], float)),
        MultiLineString(obj_id="ml", parts=[np.array([[0, 0], [1, 1]], float),
                                            np.array([[2, 2], [3, 3]], float)]),
        MultiPolygon.from_polygons(
            [[np.array([[0, 0], [1, 0], [1, 1], [0, 0]], float)],
             [np.array([[5, 5], [6, 5], [6, 6], [5, 5]], float)]],
            obj_id="mpoly",
        ),
    ]
    for o in objs:
        back = parse_geojson(to_geojson(o))
        assert type(back).__name__ == type(o).__name__


def test_wkt_roundtrip_all_types():
    cases = [
        Point(x=116.5, y=40.25),
        LineString(coords=np.array([[0, 0], [1, 1], [2, 0]], float)),
        Polygon(rings=[np.array([[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]], float),
                       np.array([[1, 1], [2, 1], [2, 2], [1, 2], [1, 1]], float)]),
        MultiPoint(coords=np.array([[1, 2], [3, 4]], float)),
        MultiLineString(parts=[np.array([[0, 0], [1, 1]], float),
                               np.array([[2, 2], [3, 3]], float)]),
        MultiPolygon.from_polygons(
            [[np.array([[0, 0], [1, 0], [1, 1], [0, 0]], float)],
             [np.array([[5, 5], [6, 5], [6, 6], [5, 5]], float)]]),
    ]
    for obj in cases:
        wkt = to_wkt(obj)
        back = parse_wkt(wkt)
        assert type(back).__name__ == type(obj).__name__, wkt
        assert to_wkt(back) == wkt


def test_wkt_geometry_collection():
    gc = GeometryCollection(
        geometries=[Point(x=1, y=2), LineString(coords=np.array([[0, 0], [1, 1]], float))]
    )
    wkt = to_wkt(gc)
    back = parse_wkt(wkt)
    assert isinstance(back, GeometryCollection)
    assert len(back.geometries) == 2
    assert isinstance(back.geometries[0], Point)
    assert isinstance(back.geometries[1], LineString)


def test_wkt_embedded_in_csv_line():
    # The reference locates "POINT" anywhere in the record
    # (Deserialization.WKTToSpatial).
    p = parse_wkt("1351039728.980,9471001,POINT (13.45 52.1),extra")
    assert (p.x, p.y) == (13.45, 52.1)


def test_csv_schema_positions():
    # csvTsvSchemaAttr [1, 4, 5, 6]-style reordering, with quotes + spaces.
    line = 'ignored, "veh7", a, b, 123456, 116.5, 40.1'
    p = parse_csv_point(line, schema=[1, 4, 5, 6], delimiter=",")
    assert p.obj_id == "veh7"
    assert p.timestamp == 123456
    assert (p.x, p.y) == (116.5, 40.1)


def test_csv_roundtrip():
    p = Point(obj_id="o1", timestamp=999, x=1.25, y=-3.5)
    line = to_csv_point(p)
    back = parse_csv_point(line, schema=[0, 1, 2, 3])
    assert (back.obj_id, back.timestamp, back.x, back.y) == ("o1", 999, 1.25, -3.5)


def test_tsv_delimiter():
    line = "veh1\t100\t1.0\t2.0"
    p = parse_csv_point(line, schema=[0, 1, 2, 3], delimiter="\t")
    assert p.obj_id == "veh1" and (p.x, p.y) == (1.0, 2.0)


def test_parse_timestamp_fallbacks():
    assert parse_timestamp("123", None) == 123
    assert parse_timestamp(None, None) == 0
    assert parse_timestamp("garbage", "yyyy-MM-dd HH:mm:ss") == 0
    assert parse_timestamp("2008-02-02 20:12:32", "yyyy-MM-dd HH:mm:ss") == 1201983152000


def test_deserialization_facade_streams():
    from spatialflink_tpu.streams.deserialization import (
        linestring_stream,
        point_stream,
        polygon_stream,
        to_output_record,
        trajectory_stream,
    )

    records = [
        '{"type":"Feature","geometry":{"type":"Point","coordinates":[1,2]},"properties":{"oID":"a","timestamp":100}}',
        '{"type":"Feature","geometry":{"type":"Polygon","coordinates":[[[0,0],[1,0],[1,1],[0,0]]]},"properties":{"oID":"p","timestamp":200}}',
        "not json at all",
    ]
    pts = list(point_stream(records))
    assert len(pts) == 1 and pts[0].obj_id == "a"
    polys = list(polygon_stream(records))
    assert len(polys) == 1 and polys[0].obj_id == "p"
    assert list(linestring_stream(records)) == []
    # Trajectory stream with custom property names.
    rec2 = ['{"type":"Feature","geometry":{"type":"Point","coordinates":[3,4]},"properties":{"vid":"x","t":5}}']
    (p,) = trajectory_stream(rec2, timestamp_property="t", objid_property="vid")
    assert p.obj_id == "x" and p.timestamp == 5
    # WKT + CSV paths.
    (w,) = point_stream(["POINT (7 8)"], input_type="WKT")
    assert (w.x, w.y) == (7.0, 8.0)
    (c,) = point_stream(["a,1,2.0,3.0"], input_type="CSV")
    assert (c.x, c.y) == (2.0, 3.0)
    with pytest.raises(ValueError, match="not supported"):
        list(point_stream([], input_type="XML"))
    # Output schemas.
    assert to_output_record(pts[0], "GeoJSON").startswith('{"type": "Feature"')
    assert to_output_record(pts[0], "WKT") == "a,100,POINT (1 2)"
    assert to_output_record(pts[0], "CSV") == "a,100,1.0,2.0"


def test_kafka_backend_resolves_builtin():
    """The old gate is gone: with no client library installed, the
    transport resolves to the built-in wire client (streams/kafka_wire.py)
    instead of raising (full coverage in tests/test_kafka_wire.py)."""
    from spatialflink_tpu.streams.kafka import _import_kafka, kafka_available

    assert kafka_available()
    kind, mod = _import_kafka()
    assert kind in ("kafka", "confluent", "wire")
    if kind == "wire":
        assert hasattr(mod, "KafkaWireClient")
