"""Trajectory operator tests vs brute-force window recomputation."""

import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import Point, Polygon
from spatialflink_tpu.operators import (
    QueryConfiguration,
    QueryType,
    TAggregateQuery,
    TFilterQuery,
    TJoinQuery,
    TKNNQuery,
    TRangeQuery,
    TStatsQuery,
)

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
W30 = QueryConfiguration(QueryType.WindowBased, window_size=30, slide_step=30)


def make_trajectories(rng, n_traj=6, pts_per=20):
    """Smooth-ish random walks, one per objID, 30s of data."""
    events = []
    for t in range(n_traj):
        x, y = rng.uniform(2, 8), rng.uniform(2, 8)
        for i in range(pts_per):
            x = float(np.clip(x + rng.normal(0, 0.2), 0, 10))
            y = float(np.clip(y + rng.normal(0, 0.2), 0, 10))
            events.append(
                Point(obj_id=f"tr{t}", timestamp=i * 1500 + t, x=x, y=y)
            )
    events.sort(key=lambda p: p.timestamp)
    return events


def test_trange_containment(rng):
    events = make_trajectories(rng)
    poly = Polygon(rings=[np.array([[4, 4], [6, 4], [6, 6], [4, 6], [4, 4]], float)])
    results = list(TRangeQuery(W30, GRID).run(iter(events), [poly]))
    for res in results:
        win_ev = [p for p in events if res.start <= p.timestamp < res.end]
        expect = {
            p.obj_id for p in win_ev if 4 < p.x < 6 and 4 < p.y < 6
        }
        got = {t.obj_id for t in res.trajectories}
        # Boundary-exact points can differ; our fixtures are generic floats,
        # never exactly on an edge.
        assert got == expect
        # Sub-trajectory contains all window points of that objID, sorted.
        for t in res.trajectories:
            n_expect = sum(1 for p in win_ev if p.obj_id == t.obj_id)
            assert len(t.coords) == n_expect
            # timestamps sorted → x sequence matches sort by ts
            evs = sorted(
                [p for p in win_ev if p.obj_id == t.obj_id], key=lambda p: p.timestamp
            )
            np.testing.assert_allclose(t.coords, [[p.x, p.y] for p in evs])


def test_tknn_top_trajectories(rng):
    events = make_trajectories(rng, n_traj=8)
    q = Point(x=5.0, y=5.0)
    results = list(TKNNQuery(W30, GRID).run(iter(events), q, radius=5.0, k=3))
    for res in results:
        win_ev = [p for p in events if res.start <= p.timestamp < res.end]
        best = {}
        for p in win_ev:
            d = float(np.hypot(p.x - 5, p.y - 5))
            if d <= 5.0 and (p.obj_id not in best or d < best[p.obj_id]):
                best[p.obj_id] = d
        expect = sorted(best.items(), key=lambda kv: kv[1])[:3]
        got = [(oid, d) for oid, d, _ in res.neighbors]
        assert [o for o, _ in got] == [o for o, _ in expect]
        for (_, gd), (_, ed) in zip(got, expect):
            assert gd == pytest.approx(ed, rel=1e-12)
        # Sub-trajectories include every window point of the objID.
        for oid, _, traj in res.neighbors:
            assert len(traj.coords) == sum(1 for p in win_ev if p.obj_id == oid)


def test_tjoin_pairs(rng):
    left = make_trajectories(rng, n_traj=4)
    right = make_trajectories(rng, n_traj=3)
    for p in right:
        p.obj_id = "q" + p.obj_id
    r = 1.0
    results = list(TJoinQuery(W30, GRID).run(iter(left), iter(right), r))
    for res in results:
        lwin = [p for p in left if res.start <= p.timestamp < res.end]
        rwin = [p for p in right if res.start <= p.timestamp < res.end]
        expect = {}
        for a in lwin:
            for b in rwin:
                d = float(np.hypot(a.x - b.x, a.y - b.y))
                if d <= r:
                    key = (a.obj_id, b.obj_id)
                    if key not in expect or d < expect[key]:
                        expect[key] = d
        got = {(a.obj_id, b.obj_id): d for a, b, d in res.pairs}
        assert set(got) == set(expect)
        for k in got:
            assert got[k] == pytest.approx(expect[k], rel=1e-12)


def test_tjoin_single_excludes_identity(rng):
    events = make_trajectories(rng, n_traj=3)
    results = list(TJoinQuery(W30, GRID).run_single(iter(events), 10.0))
    for res in results:
        assert all(a.obj_id != b.obj_id for a, b, _ in res.pairs)


def test_taggregate_sum_and_all(rng):
    events = make_trajectories(rng, n_traj=3, pts_per=10)
    agg = TAggregateQuery(W30, GRID, aggregate="ALL")
    results = list(agg.run(iter(events)))
    assert results
    final = results[-1]
    # Brute force: per (cell, objID) min/max ts over ALL events (continuous state).
    state = {}
    for p in events:
        c = GRID.flat_cell(p.x, p.y)
        key = (c, p.obj_id)
        mn, mx = state.get(key, (p.timestamp, p.timestamp))
        state[key] = (min(mn, p.timestamp), max(mx, p.timestamp))
    per_cell = {}
    for (c, oid), (mn, mx) in state.items():
        per_cell.setdefault(GRID.cell_name(c), {})[oid] = mx - mn
    assert final.cells.keys() == per_cell.keys()
    for name, (count, lens) in final.cells.items():
        assert count == len(per_cell[name])
        assert lens == per_cell[name]


def test_taggregate_inactive_deletion(rng):
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)
    # One object stops sending at t=5000; another continues to 40000.
    events = [Point(obj_id="dead", timestamp=t, x=1.0, y=1.0) for t in range(0, 5000, 1000)]
    events += [Point(obj_id="alive", timestamp=t, x=9.0, y=9.0) for t in range(0, 40000, 1000)]
    events.sort(key=lambda p: p.timestamp)
    agg = TAggregateQuery(conf, GRID, aggregate="ALL", inactive_threshold_ms=8000)
    results = list(agg.run(iter(events)))
    last = results[-1]
    oids = {oid for _, lens in last.cells.values() for oid in lens}
    assert "alive" in oids and "dead" not in oids


def test_tstats_windowed_matches_brute(rng):
    events = make_trajectories(rng, n_traj=4)
    results = list(TStatsQuery(W30, GRID).run(iter(events)))
    for res in results:
        win_ev = [p for p in events if res.start <= p.timestamp < res.end]
        for oid_str in {p.obj_id for p in win_ev}:
            pts = sorted(
                [p for p in win_ev if p.obj_id == oid_str], key=lambda p: p.timestamp
            )
            spatial = sum(
                float(np.hypot(b.x - a.x, b.y - a.y)) for a, b in zip(pts, pts[1:])
            )
            temporal = pts[-1].timestamp - pts[0].timestamp
            gs, gt, gr = res.stats[oid_str]
            assert gs == pytest.approx(spatial, rel=1e-9)
            assert gt == temporal
            if temporal:
                assert gr == pytest.approx(spatial / temporal, rel=1e-9)


def test_tstats_realtime_carries_state_and_drops_ooo():
    conf = QueryConfiguration(QueryType.RealTime, realtime_batch_ms=1000)
    events = [
        Point(obj_id="a", timestamp=0, x=0.0, y=0.0),
        Point(obj_id="a", timestamp=500, x=3.0, y=4.0),  # +5
        Point(obj_id="a", timestamp=400, x=100.0, y=100.0),  # out-of-order: dropped
        Point(obj_id="a", timestamp=1500, x=3.0, y=0.0),  # +4
    ]
    results = list(TStatsQuery(conf, GRID).run(iter(events)))
    final = {}
    for res in results:
        final.update(res.stats)
    spatial, temporal, ratio = final["a"]
    assert spatial == pytest.approx(9.0)
    assert temporal == 1500
    assert ratio == pytest.approx(9.0 / 1500)


def test_tfilter(rng):
    events = make_trajectories(rng, n_traj=5)
    results = list(TFilterQuery(W30, GRID).run(iter(events), ["tr1", "tr3"]))
    for res in results:
        got = {t.obj_id for t in res.trajectories}
        win_ev = [p for p in events if res.start <= p.timestamp < res.end]
        expect = {p.obj_id for p in win_ev if p.obj_id in ("tr1", "tr3")}
        assert got == expect
