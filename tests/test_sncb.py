"""SNCB domain-layer tests: CSV schema, zones, Q1–Q5, MN_Q1–Q5, runners."""

import math

import numpy as np
import pytest

from spatialflink_tpu.sncb.common import (
    BufferedZone,
    CRSUtils,
    GpsEvent,
    MnGpsEvent,
    PolygonLoader,
    contains_any_zone,
    csv_to_gps_event,
)
from spatialflink_tpu.sncb.mobility import (
    Q5_FENCE,
    mn_q1,
    mn_q2,
    mn_q3,
    mn_q4,
    mn_q5,
    mobility_runner,
)
from spatialflink_tpu.sncb.ops import trajectory_wkt, traj_speed, variance, variation
from spatialflink_tpu.sncb.queries import (
    q1_high_risk,
    q2_brake_monitor,
    q3_trajectory,
    q4_trajectory_restricted,
    q5_traj_speed_fence,
)
from spatialflink_tpu.sncb.runners import (
    benchmark_runner,
    local_test_runner,
    sample_gps_events,
)


def test_csv_schema_14_columns():
    # ts(0) deviceId(1) _(2) PCFA(3) PCFF(4) ... speed(11) lat(12) lon(13)
    line = "1700000000000,trainX,z,4.5,5.2,a,b,c,d,e,f,33.5,50.8466,4.3517"
    e = csv_to_gps_event(line)
    assert e.device_id == "trainX"
    assert e.ts == 1700000000000
    assert e.fa == 4.5 and e.ff == 5.2
    assert e.gps_speed == 33.5
    assert (e.lon, e.lat) == (4.3517, 50.8466)
    # Bad numerics → 0 (reference's catch-all, CSVToGpsEventMapFunction.java:20-26)
    e2 = csv_to_gps_event("xx,dev,z,bad,bad,a,b,c,d,e,f,bad,bad,bad")
    assert e2.ts == 0 and e2.fa == 0.0 and e2.lon == 0.0
    assert MnGpsEvent is GpsEvent  # the missing com.mn type exists here


def test_zone_loading_and_containment():
    # The reference's own high_risk_zones.geojson: one zone, 4.35–4.36 ×
    # 50.85–50.86 (src/main/resources/high_risk_zones.geojson).
    zones = PolygonLoader.load_geojson_buffered("high_risk_zones.geojson", 20.0)
    assert len(zones) == 1
    assert zones[0].buffer_m == 20.0
    inside = CRSUtils.enrich_batch([GpsEvent("a", 4.355, 50.855, 0)])
    outside = CRSUtils.enrich_batch([GpsEvent("b", 4.5, 50.5, 0)])
    assert contains_any_zone(zones, inside)[0]
    assert not contains_any_zone(zones, outside)[0]
    # Buffer semantics: ~15 m outside the edge must still hit (buffer 20 m).
    edge = CRSUtils.enrich_batch([GpsEvent("c", 4.350, 50.855, 0)])
    edge_shift = edge.copy()
    edge_shift[0, 0] -= 15.0  # 15 m west of the western edge
    assert contains_any_zone(zones, edge_shift)[0]
    edge_shift[0, 0] -= 30.0  # 45 m out → miss
    assert not contains_any_zone(zones, edge_shift)[0]


def test_wkt_fence_loading():
    # Reference fence: 4.40–4.41 × 50.85–50.86 (q5_fence.wkt).
    fence = PolygonLoader.load_wkt_buffered("q5_fence.wkt", 20.0)
    assert len(fence) == 1
    c = CRSUtils.enrich_batch([GpsEvent("a", 4.405, 50.855, 0)])
    assert contains_any_zone(fence, c)[0]
    far = CRSUtils.enrich_batch([GpsEvent("b", 4.45, 50.855, 0)])
    assert not contains_any_zone(fence, far)[0]


def test_ops_aggregations():
    evs = [
        GpsEvent("d", 0, 0, 1000, 10.0, 4.0, 5.0),
        GpsEvent("d", 0, 0, 2000, 20.0, 4.8, 5.4),
        GpsEvent("d", 0, 0, 3000, 30.0, None, None),
    ]
    var_fa, var_ff = variation(evs)
    assert var_fa == pytest.approx(0.8)
    assert var_ff == pytest.approx(0.4)
    n, v_fa, v_ff = variance(evs)
    assert n == 3
    # Reference formula: sums skip None but n counts all events.
    mean_fa = (4.0 + 4.8) / 3
    assert v_fa == pytest.approx(max(0.0, (4.0**2 + 4.8**2) / 3 - mean_fa**2))
    wkt, avg, mn = traj_speed(evs)
    assert avg == pytest.approx(20.0) and mn == 10.0
    assert wkt.startswith("LINESTRING")
    assert trajectory_wkt([]) == "POINT EMPTY"
    assert trajectory_wkt(evs[:1]) == "POINT (0 0)"


def test_q1_high_risk_fixture():
    """Golden expectation from LocalTestRunner.java:91-94: device A's three
    points lie inside the high-risk zone — Q1 flags exactly those."""
    risk = PolygonLoader.load_geojson_buffered("high_risk_zones.geojson", 20.0)
    hits = list(q1_high_risk(iter(sample_gps_events()), risk))
    ids = {h.raw.device_id for h in hits}
    assert ids == {"A"}
    assert len(hits) == 3
    # Enrichment carries metric coordinates.
    assert 5_600_000 < hits[0].y_metric < 5_700_000


def test_q2_brake_monitor_fixture():
    """LocalTestRunner.java:96-99: B sits outside the maintenance area with
    varFA 0.7 > 0.6 and varFF 0.2 ≤ 0.5 → alert. A's spreads (0.7 / 0.3)
    qualify too; C/D/E carry null FA/FF and can never alert."""
    maint = PolygonLoader.load_geojson_buffered("maintenance_areas.geojson", 0.0)
    out = list(q2_brake_monitor(iter(sample_gps_events()), maint, slide_ms=500))
    devs = {o.device_id for o in out}
    assert "B" in devs
    assert "A" in devs
    assert devs <= {"A", "B"}


def test_q3_trajectory_fixture():
    """LocalTestRunner.java:101-108: C and D build simple trajectories."""
    out = list(q3_trajectory(iter(sample_gps_events()), slide_ms=1000))
    c_full = [
        o for o in out
        if o.device_id == "C" and "LINESTRING" in o.wkt and "4.42" in o.wkt
    ]
    assert c_full  # some window holds C's whole 3-point trajectory
    # Coordinates ordered by timestamp.
    assert c_full[0].wkt.index("4.4 ") < c_full[0].wkt.index("4.42")
    assert any(o.device_id == "D" for o in out)


def test_q4_restriction():
    out = list(
        q4_trajectory_restricted(
            iter(sample_gps_events()), 4.3, 4.4, 50.8, 50.9,
            1_700_000_000_000, 1_700_000_002_000, slide_ms=1000,
        )
    )
    devs = {o.device_id for o in out}
    # Inside bbox 4.3–4.4 × 50.8–50.9 and t ≤ t0+2000: A and B only
    # (C/D fail the latitude band, E the longitude band).
    assert devs == {"A", "B"}


def test_q5_fence_fixture():
    """LocalTestRunner.java:110-113: E is inside the fence with avg speed
    51.7 > 50 and min 40 > 20 → qualifies; every other device is outside
    the fence."""
    fence = PolygonLoader.load_wkt_buffered("q5_fence.wkt", 20.0)
    out = list(q5_traj_speed_fence(iter(sample_gps_events()), fence))
    devs = {o.device_id for o in out}
    assert devs == {"E"}


def test_local_test_runner_end_to_end():
    out = local_test_runner()
    assert {r.raw.device_id for r in out["q1"]} == {"A"}
    assert {o.device_id for o in out["q2"]} <= {"A", "B"}
    assert out["q3"]
    assert {o.device_id for o in out["q5"]} == {"E"}


def _mk_events(n=50, lon=4.3658, lat=50.6456, dev="d0", t0=0, dt=100):
    return [GpsEvent(dev, lon, lat, t0 + i * dt, 10.0, 4.0, 5.0) for i in range(n)]


def test_mn_q1_counts():
    # 50 events at the query point + 10 far away, 5s tumbling windows.
    evs = _mk_events(50) + [
        GpsEvent("far", 10.0, 60.0, i * 100, 1.0, 0, 0) for i in range(10)
    ]
    evs.sort(key=lambda e: e.ts)
    out = list(mn_q1(iter(evs), 4.3658, 50.6456, 2.0))
    assert sum(o.cnt for o in out) == 50  # far events outside 2.0-degree tol
    assert all(o.end - o.start == 5000 for o in out)


def test_mn_q2_excludes_box_and_counts_all_key():
    inside_box = [GpsEvent("a", 4.3, 50.4, i * 100, 1, 2.0, 2.0) for i in range(10)]
    outside = [GpsEvent("b", 5.5, 51.5, i * 100, 1, 4.0 + (i % 2), 5.0) for i in range(10)]
    evs = sorted(inside_box + outside, key=lambda e: e.ts)
    out = list(mn_q2(iter(evs), slide_ms=1000))
    assert out
    # Only the 10 outside-box events are aggregated.
    assert max(o.count for o in out) == 10
    assert all(o.device_id == "ALL" for o in out)


def test_mn_q3_q4_trajectories():
    evs = _mk_events(20, dt=500)
    out3 = list(mn_q3(iter(evs)))
    assert out3 and all(o.device_id == "ALL" for o in out3)
    out4 = list(mn_q4(iter(_mk_events(20, dt=500)), 4.0, 50.0, 5.0, 51.0, 0, 10**15))
    assert out4


def test_mn_q5_fence_and_speed_filter():
    # Slow device inside fence → kept (avg < 100); fast device avg>100 &
    # min>20 → filtered out.
    slow = [GpsEvent("slow", 4.41, 50.85, i * 500, 30.0, 0, 0) for i in range(10)]
    fast = [GpsEvent("fast", 4.41, 50.85, i * 500, 150.0, 0, 0) for i in range(10)]
    evs = sorted(slow + fast, key=lambda e: e.ts)
    out = list(mn_q5(iter(evs), Q5_FENCE, 0.001))
    devs = {o.device_id for o in out}
    assert "slow" in devs and "fast" not in devs


def test_mobility_runner_csv_roundtrip(tmp_path):
    lines = [
        f"{i*200},dev{i%3},z,4.0,5.0,a,b,c,d,e,f,25.0,50.6456,4.3658"
        for i in range(100)
    ]
    rows = mobility_runner("q1", iter(lines), out_path=str(tmp_path / "q1.csv"))
    assert rows
    total = sum(int(r.split(",")[2]) for r in rows)
    assert total == 100
    assert (tmp_path / "q1.csv").read_text().strip().count("\n") == len(rows) - 1


def test_benchmark_runner_small():
    rep = benchmark_runner("q1", target_eps=2000, duration_ms=2000)
    assert rep.events == 4000
    assert rep.eps > 0
    # Synthetic Brussels bbox overlaps the risk zones rarely; result count
    # bounded by event count.
    assert 0 <= rep.results <= rep.events
