"""Roofline bound-classification (tools/sfprof/roofline.py): verdicts
pinned on a synthetic ledger corpus — one fixture per bound class —
plus the evidence-chain and CLI (--json) surfaces."""

import json

import pytest

from tools.sfprof import roofline
from tools.sfprof.cli import main as sfprof_main

WALL_US = 100_000  # one 100 ms traced span for every fixture


def _ev(name, ts, dur, tid=1):
    return {"name": name, "ph": "X", "ts": ts, "dur": dur,
            "pid": 7, "tid": tid}


def _kernel(name, calls, steady_ms, flops=1e3, nbytes=1e3):
    steady_ns = int(steady_ms * 1e6)
    return {
        "kernel": name, "signature": "()", "calls": calls,
        "dispatch_ns": steady_ns + 1_000_000,
        "first_call_ns": 1_000_000,
        "steady_ns": steady_ns,
        "cost": {"flops": flops, "bytes_accessed": nbytes},
    }


def _doc(snapshot=None, kernels=None, backend="cpu"):
    snap = {
        "compiles": 1, "bytes_h2d": 0, "bytes_d2h": 0,
        "max_watermark_lag_ms": 0, "late_dropped": 0,
        "dropped_events": 0, "kernels": {},
    }
    snap.update(snapshot or {})
    return {
        "ledger_version": 1, "created_unix": 0.0,
        "env": {"backend": backend},
        "snapshot": snap, "kernels": kernels or [], "events": [],
        "bench": None,
    }


def _one_window_events():
    """A single window span covering the whole wall (keeps host share
    at its unattributed residue only when children fill it)."""
    return [
        _ev("window.x", 0, WALL_US),
        _ev("compute", 0, WALL_US),  # fully attributed: no residue
    ]


# -- the five bound classes ---------------------------------------------------


def test_link_bound():
    # 2.3 MB over a 28 MB/s tunnel ≈ 82 ms of a 100 ms span.
    doc = _doc(snapshot={
        "bytes_h2d": 2_000_000, "bytes_d2h": 300_000,
        "link_probe": {"roundtrip_mbps_p50": 28.0},
    })
    bound = roofline.classify(doc, _one_window_events())
    assert bound["verdict"] == "link-bound"
    assert bound["dominant"] is True
    assert 0.7 < bound["fractions"]["link"] < 1.0
    assert any("probe p50 28.0 MB/s" in e for e in bound["evidence"])


def test_link_share_unknown_without_probe():
    doc = _doc(snapshot={"bytes_h2d": 2_000_000})
    bound = roofline.classify(doc, _one_window_events())
    assert bound["fractions"]["link"] is None
    assert any("no LinkProbe bandwidth gauge" in e
               for e in bound["evidence"])


def test_host_bound():
    # Two windows with a 60 ms gap between them, nothing attributed
    # inside either: 60 ms gap + 40 ms residue = the whole wall.
    events = [
        _ev("window.x", 0, 20_000),
        _ev("window.x", 80_000, 20_000),
    ]
    bound = roofline.classify(_doc(), events)
    assert bound["verdict"] == "host-bound"
    assert bound["dominant"] is True
    assert any("inter-window gaps" in e for e in bound["evidence"])


def test_dispatch_bound_overhead():
    # 80 ms of steady dispatch over 100 calls whose cost-model work is
    # microscopic: per-dispatch overhead, not device work.
    kernels = [_kernel("tiny", calls=101, steady_ms=80.0,
                       flops=1e3, nbytes=1e3)]
    bound = roofline.classify(_doc(kernels=kernels),
                              _one_window_events())
    assert bound["verdict"] == "dispatch-bound"
    assert any("per-dispatch overhead" in e for e in bound["evidence"])


def test_compute_bound():
    # Same 80 ms of dispatch, but the cost model accounts for it with
    # flops (0.8 ms/call ≈ 4e7 flop at the 5e10 flop/s cpu model) and
    # intensity far above the machine balance point.
    kernels = [_kernel("mm", calls=101, steady_ms=80.0,
                       flops=4.0e7, nbytes=1e4)]
    bound = roofline.classify(_doc(kernels=kernels),
                              _one_window_events())
    assert bound["verdict"] == "compute-bound"
    assert any("arithmetic intensity" in e for e in bound["evidence"])


def test_memory_bound():
    # Bytes account for the dispatch time; intensity below balance.
    kernels = [_kernel("scatter", calls=101, steady_ms=80.0,
                       flops=1e4, nbytes=1.6e7)]
    bound = roofline.classify(_doc(kernels=kernels),
                              _one_window_events())
    assert bound["verdict"] == "memory-bound"


def test_inconclusive_without_spans():
    bound = roofline.classify(_doc(), [])
    assert bound["verdict"] == "inconclusive"
    assert bound["wall_us"] is None


def test_weak_dominance_flagged():
    # Every component tiny relative to wall: verdict still names the
    # largest, but says so.
    kernels = [_kernel("k", calls=3, steady_ms=2.0)]
    bound = roofline.classify(_doc(kernels=kernels),
                              _one_window_events())
    assert bound["verdict"] in roofline.BOUND_KINDS
    assert bound["dominant"] is False
    assert any("weak dominance" in e for e in bound["evidence"])


def test_machine_model_override_flips_verdict():
    # The compute-bound fixture becomes overhead-dominated under a
    # 1000x faster machine model: the ridge is configurable.
    kernels = [_kernel("mm", calls=101, steady_ms=80.0,
                       flops=4.0e7, nbytes=1e4)]
    doc = _doc(kernels=kernels)
    assert roofline.classify(doc, _one_window_events())["verdict"] \
        == "compute-bound"
    fast = roofline.classify(doc, _one_window_events(),
                             peak_flops=5e13, peak_bw=2e13)
    assert fast["verdict"] == "dispatch-bound"


def test_per_operator_breakdown():
    events = [
        _ev("window.a", 0, 50_000),
        _ev("ship", 0, 30_000),
        _ev("compute", 30_000, 15_000),
        _ev("window.b", 50_000, 50_000),
        _ev("compute", 50_000, 45_000),
    ]
    bound = roofline.classify(_doc(), events)
    per = bound["per_operator"]
    assert per["window.a"]["verdict"] == "link-bound"
    assert per["window.b"]["verdict"] == "dispatch-bound"
    assert per["window.a"]["phases_us"]["transfer"] == 30_000


def test_verdict_vocabulary_is_closed():
    # Dashboards and the trend store key on the verdict strings.
    assert set(roofline.BOUND_KINDS) == {
        "link-bound", "host-bound", "dispatch-bound", "compute-bound",
        "memory-bound", "inconclusive",
    }


# -- CLI surfaces -------------------------------------------------------------


def _write(tmp_path, doc, events, name="l.json"):
    doc = dict(doc, events=events)
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_report_prints_verdict_with_evidence_chain(tmp_path, capsys):
    doc = _doc(snapshot={
        "bytes_h2d": 2_000_000, "bytes_d2h": 300_000,
        "link_probe": {"roundtrip_mbps_p50": 28.0},
    })
    path = _write(tmp_path, doc, _one_window_events())
    assert sfprof_main(["report", path]) == 0
    out = capsys.readouterr().out
    assert "roofline bound classification" in out
    assert "verdict: link-bound" in out
    assert "↳" in out  # the sfcheck-style evidence chain


def test_report_json_carries_roofline(tmp_path, capsys):
    doc = _doc(snapshot={
        "bytes_h2d": 2_000_000, "bytes_d2h": 300_000,
        "link_probe": {"roundtrip_mbps_p50": 28.0},
    })
    path = _write(tmp_path, doc, _one_window_events())
    assert sfprof_main(["report", path, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["roofline"]["verdict"] == "link-bound"
    assert out["roofline"]["evidence"]
    assert out["ledger"]["env"]["backend"] == "cpu"
    assert out["attribution"]["operators"]["window.x"]["windows"] == 1


def test_health_json_carries_roofline(tmp_path, capsys):
    doc = _doc(snapshot={
        "bytes_h2d": 2_000_000, "bytes_d2h": 300_000,
        "link_probe": {"roundtrip_mbps_p50": 28.0},
    })
    path = _write(tmp_path, doc, _one_window_events())
    assert sfprof_main(["health", path, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["failed"] == 0
    assert out["roofline"]["verdict"] == "link-bound"
    assert out["tainted"] is None
    names = [c["name"] for c in out["checks"]]
    assert "recompile_churn_max_signatures" in names
    # Exit contract unchanged: the human and json paths agree.
    assert sfprof_main(["health", path]) == 0
    human = capsys.readouterr().out
    assert "bound: link-bound" in human


def test_health_json_schema_failure(tmp_path, capsys):
    p = tmp_path / "broken.json"
    p.write_text(json.dumps({"ledger_version": 1}))
    assert sfprof_main(["health", str(p), "--json"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["schema_problems"]
    assert out["failed"] == len(out["schema_problems"])
