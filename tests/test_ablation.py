"""Kernel-ablation harness (spatialflink_tpu/ablation.py): the
substituted dispatch (learning call → cached correct-aval zeros), the
taint contract across snapshot/ledger/stream/record, the gate and
baseline-writer rejections, SFT_ABLATE arming, and the bench_suite
--ablate marginal-cost sweep."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spatialflink_tpu.ablation import _parse_spec, ablation
from spatialflink_tpu.telemetry import instrument_jit, telemetry
from tools.sfprof import ledger as ledger_mod
from tools.sfprof import stream as stream_mod
from tools.sfprof import trend as trend_mod
from tools.sfprof.cli import main as sfprof_main


@pytest.fixture(autouse=True)
def _clean_singletons():
    """Both process-global singletons reset and disarmed around every
    test (the test_sfprof fixture, plus ablation)."""
    yield
    ablation.disarm()
    ablation.reset_counters()
    telemetry.enable()
    telemetry.disable()


# -- the substituted dispatch -------------------------------------------------


def test_learning_call_then_cached_zeros():
    telemetry.enable()
    f = instrument_jit(jax.jit(lambda x: x * 2 + 1), name="twice")
    x = jnp.ones((8,), jnp.float32)
    assert float(np.asarray(f(x))[0]) == 3.0
    ablation.arm(["twice"])
    # First armed call per signature is the REAL kernel (learning).
    assert float(np.asarray(f(x))[0]) == 3.0
    # Then cached zeros with the exact avals.
    out = f(x)
    assert out.shape == (8,) and out.dtype == jnp.float32
    assert float(np.asarray(out).sum()) == 0.0
    t = ablation.taint_block()
    assert t["kind"] == "ablation"
    assert t["kernels"] == ["twice"]
    assert t["learning_calls"] == {"twice": 1}
    assert t["substituted_calls"] == {"twice": 1}


def test_new_signature_relearns():
    telemetry.enable()
    f = instrument_jit(jax.jit(lambda x: x + 1), name="bump")
    ablation.arm(["bump"])
    assert float(np.asarray(f(jnp.ones((4,))))[0]) == 2.0  # learn (4,)
    assert float(np.asarray(f(jnp.ones((4,))))[0]) == 0.0  # zeros
    # A new abstract shape learns again before substituting.
    assert float(np.asarray(f(jnp.ones((6,))))[0]) == 2.0
    assert float(np.asarray(f(jnp.ones((6,))))[0]) == 0.0


def test_pytree_outputs_and_fresh_buffers():
    """NamedTuple outputs mirror structurally, and each substituted
    call returns FRESH buffers — a downstream donate_argnums consumer
    must never invalidate the cache."""
    from typing import NamedTuple

    class Out(NamedTuple):
        a: object
        b: object

    telemetry.enable()
    f = instrument_jit(jax.jit(lambda x: Out(x * 2, (x.sum(),))),
                       name="nt")
    consume = jax.jit(lambda a: a + 1, donate_argnums=(0,))
    x = jnp.ones((16,), jnp.float32)
    ablation.arm(["nt"])
    f(x)  # learning
    o1 = f(x)
    assert isinstance(o1, Out)
    assert float(np.asarray(o1.b[0])) == 0.0
    consume(o1.a)  # donate the substituted buffer
    o2 = f(x)  # the cache must still be alive
    assert float(np.asarray(o2.a).sum()) == 0.0


def test_unablated_kernels_unaffected_and_disarm_restores():
    telemetry.enable()
    f = instrument_jit(jax.jit(lambda x: x * 2), name="keep")
    g = instrument_jit(jax.jit(lambda x: x * 3), name="cut")
    x = jnp.ones((4,), jnp.float32)
    ablation.arm(["cut"])
    g(x)  # learning
    assert float(np.asarray(g(x))[0]) == 0.0
    assert float(np.asarray(f(x))[0]) == 2.0  # untouched
    ablation.disarm()
    assert float(np.asarray(g(x))[0]) == 3.0  # real again
    # Disarmed cost path: the runtime table kept recording "keep".
    assert any(r["kernel"] == "keep" for r in telemetry.kernel_table())


def test_works_with_telemetry_disabled():
    # Substitution is a profiling tool but must not NEED a capture.
    f = instrument_jit(jax.jit(lambda x: x + 5), name="solo")
    x = jnp.ones((4,), jnp.float32)
    ablation.arm(["solo"])
    f(x)
    assert float(np.asarray(f(x))[0]) == 0.0


# -- the taint contract -------------------------------------------------------


def test_taint_rides_snapshot_ledger_and_record(tmp_path):
    telemetry.enable()
    f = instrument_jit(jax.jit(lambda x: x * 2), name="tk")
    x = jnp.ones((4,), jnp.float32)
    ablation.arm(["tk"])
    f(x)
    f(x)
    assert telemetry.snapshot()["tainted"]["kind"] == "ablation"
    path = telemetry.write_ledger(
        str(tmp_path / "t.json"),
        bench={"config": "c", "points_per_sec": 1.0, "value": 1.0})
    doc = ledger_mod.load(path)
    assert doc["tainted"]["kernels"] == ["tk"]
    assert doc["snapshot"]["tainted"]["kind"] == "ablation"
    assert ledger_mod.validate(doc) == []  # taint is schema-legal


def test_taint_scope_resets_with_a_fresh_capture(tmp_path):
    telemetry.enable()
    f = instrument_jit(jax.jit(lambda x: x * 2), name="tk2")
    ablation.arm(["tk2"])
    f(jnp.ones((4,)))
    f(jnp.ones((4,)))
    ablation.disarm()
    # Disarmed but substitutions happened THIS capture: still tainted.
    assert telemetry.snapshot()["tainted"] is not None
    # A fresh capture with ablation disarmed starts clean.
    telemetry.enable()
    assert "tainted" not in telemetry.snapshot()
    path = telemetry.write_ledger(str(tmp_path / "clean.json"))
    assert "tainted" not in ledger_mod.load(path)


def test_taint_survives_stream_recovery(tmp_path):
    stream = str(tmp_path / "s.jsonl")
    telemetry.enable(stream_path=stream)
    f = instrument_jit(jax.jit(lambda x: x * 2), name="sk")
    ablation.arm(["sk"])
    f(jnp.ones((4,)))
    f(jnp.ones((4,)))
    telemetry.maybe_flush_stream(force=True)
    telemetry.disable()  # seals
    doc, _info = stream_mod.recover(stream)
    assert trend_mod.taint_of(doc)["kind"] == "ablation"
    # And the recovered document is still rejected by the trend gate.
    p = tmp_path / "recovered.json"
    p.write_text(json.dumps(doc))
    hist = tmp_path / "hist"
    hist.mkdir()
    for i, v in enumerate((1.0, 2.0, 3.0)):
        (hist / f"r{i}.json").write_text(json.dumps(
            {"metric": "c", "value": v, "device": "cpu",
             "smoke": False}))
    assert sfprof_main(["trend", str(hist), "--gate", str(p)]) == 1


def test_ablation_armed_event_registered_and_counted():
    from tools.sfprof import events as events_mod

    telemetry.enable()
    ablation.arm(["whatever"])
    telemetry.disable()
    evs = [e for e in telemetry.events if e.get("ph") == "i"]
    names = [e["name"] for e in evs]
    assert "ablation_armed" in names
    counts = events_mod.notable_event_counts(evs)
    assert counts.get("ablation") == 1
    # arm-before-enable (the SFT_ABLATE import-time order): enable
    # re-emits the marker, the fault_armed idiom.
    telemetry.enable()
    telemetry.disable()
    assert any(e["name"] == "ablation_armed" for e in telemetry.events)


# -- gates and baseline writers reject taint ----------------------------------


def _tainted_ledger(tmp_path, name="tainted.json"):
    telemetry.enable()
    f = instrument_jit(jax.jit(lambda x: x * 2), name="gk")
    ablation.arm(["gk"])
    f(jnp.ones((4,)))
    f(jnp.ones((4,)))
    path = telemetry.write_ledger(
        str(tmp_path / name),
        bench={"config": "c", "points_per_sec": 9e9, "value": 9e9})
    telemetry.disable()
    ablation.disarm()
    return path


def test_diff_gate_rejects_tainted_ledger(tmp_path, capsys):
    bad = _tainted_ledger(tmp_path)
    telemetry.enable()
    good = telemetry.write_ledger(
        str(tmp_path / "good.json"),
        bench={"config": "c", "points_per_sec": 1.0, "value": 1.0})
    telemetry.disable()
    # Tainted candidate: rejected with the explicit reason, exit 1.
    assert sfprof_main(["diff", good, bad, "--gate"]) == 1
    out = capsys.readouterr().out
    assert "REJECT" in out and "tainted" in out and "ablation" in out
    # Tainted REFERENCE is equally unusable.
    assert sfprof_main(["diff", bad, good, "--gate"]) == 1
    # Un-gated diff: loud refusal to compare, informational exit.
    assert sfprof_main(["diff", good, bad]) == 0
    assert "REJECT" in capsys.readouterr().out


def test_last_good_store_refuses_tainted_records(tmp_path, monkeypatch):
    import bench

    store = tmp_path / "last_good.json"
    monkeypatch.setenv("SFT_BENCH_LAST_GOOD", str(store))
    bench._record_last_good({"value": 5.0, "tainted": {
        "kind": "ablation", "kernels": ["k"]}})
    assert not store.exists()
    bench._record_last_good({"value": 5.0})
    assert store.exists()


def test_cpu_baseline_refuses_armed_ablation(monkeypatch, capsys):
    import bench_suite

    monkeypatch.setenv("SFT_ABLATE", "some_kernel")
    monkeypatch.setattr("sys.argv", ["bench_suite.py", "--cpu-baseline"])
    from spatialflink_tpu.ablation import maybe_arm_from_env

    maybe_arm_from_env()
    with pytest.raises(SystemExit) as exc:
        bench_suite.main()
    assert "CPU_BASELINE" in str(exc.value)
    ablation.disarm()


# -- SFT_ABLATE parsing -------------------------------------------------------


def test_parse_spec_shapes(tmp_path):
    assert _parse_spec("a,b , c") == ["a", "b", "c"]
    assert _parse_spec('["x", "y"]') == ["x", "y"]
    assert _parse_spec('{"kernels": ["z"]}') == ["z"]
    p = tmp_path / "spec.json"
    p.write_text('{"kernels": ["from_file"]}')
    assert _parse_spec(str(p)) == ["from_file"]
    assert _parse_spec("") == []
    with pytest.raises(ValueError):
        _parse_spec('{"kernels": "notalist"}')


def test_maybe_arm_from_env(monkeypatch):
    from spatialflink_tpu.ablation import maybe_arm_from_env

    monkeypatch.setenv("SFT_ABLATE", "k1,k2")
    maybe_arm_from_env()
    assert ablation.armed and ablation.kernels == {"k1", "k2"}
    ablation.disarm()
    monkeypatch.setenv("SFT_ABLATE", "   ")
    with pytest.raises(ValueError):
        maybe_arm_from_env()


# -- the bench_suite --ablate sweep -------------------------------------------


def test_run_ablation_measures_marginal_cost(tmp_path, capsys):
    import bench_suite

    jheavy = instrument_jit(jax.jit(lambda x: (x * 2).sum()),
                            name="heavy_k")
    jlight = instrument_jit(jax.jit(lambda x: x + 1), name="light_k")

    def stub_bench():
        x = jnp.ones((64,), jnp.float32)
        for _ in range(4):
            jheavy(x)
            jlight(x)
        return {"config": "stub", "points_per_sec": 1000.0,
                "value": 1000.0}

    tables = bench_suite.run_ablation(
        [("stub", stub_bench)], top_n=2, ledger_dir=str(tmp_path))
    (table,) = tables
    assert table["ablation_table"] == "stub"
    assert table["tainted"] is True
    assert table["baseline_points_per_sec"] == 1000.0
    kernels = {r["kernel"] for r in table["kernels"]}
    assert kernels == {"heavy_k", "light_k"}
    for row in table["kernels"]:
        assert "marginal_frac" in row and "speedup_if_free" in row
    out = capsys.readouterr().out
    assert '"ablation_table": "stub"' in out
    # Every per-kernel ledger is tainted and self-diff-rejected.
    for k in ("heavy_k", "light_k"):
        ledger = str(tmp_path / f"stub.ablate.{k}.json")
        doc = ledger_mod.load(ledger)
        assert doc["tainted"]["kernels"] == [k]
        assert sfprof_main(["diff", ledger, ledger, "--gate"]) == 1
    # The sweep leaves the process disarmed and the NEXT capture clean.
    assert not ablation.armed
    telemetry.enable()
    assert "tainted" not in telemetry.snapshot()


def test_run_ablation_records_load_bearing_kernels_as_evidence(tmp_path):
    """A config whose asserts reject zeroed results yields an
    error-with-evidence row, never a crashed sweep."""
    import bench_suite

    jcount = instrument_jit(jax.jit(lambda x: x.sum()), name="count_k")

    def strict_bench():
        # Two calls: the armed leg's first is the real learning call,
        # the second returns zeros and trips the underfill assert.
        for _ in range(2):
            out = float(np.asarray(jcount(jnp.ones((8,), jnp.float32))))
            assert out > 0, "underfilled"
        return {"config": "strict", "points_per_sec": 10.0,
                "value": 10.0}

    (table,) = bench_suite.run_ablation(
        [("strict", strict_bench)], top_n=1)
    (row,) = table["kernels"]
    assert row["kernel"] == "count_k"
    assert "AssertionError" in row["error"]
    assert "load-bearing" in row["note"]
