"""Shapefile round-trip, StreamingJob CLI, checkpoint/resume, helpers."""

import os

import numpy as np
import pytest

from spatialflink_tpu.checkpoint import (
    assembler_state,
    load_checkpoint,
    operator_state,
    restore_assembler,
    restore_operator,
    save_checkpoint,
)
from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import LineString, MultiPoint, Point, Polygon
from spatialflink_tpu.streams.shapefile import read_shapefile, write_shapefile
from spatialflink_tpu.streams.windows import TumblingEventTimeWindows, WindowAssembler
from spatialflink_tpu.utils.helper import generate_query_polygons

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)


def test_shapefile_roundtrip_points(tmp_path):
    objs = [Point(x=1.5, y=2.5), Point(x=-3.0, y=4.0)]
    path = str(tmp_path / "pts.shp")
    write_shapefile(path, objs)
    back = list(read_shapefile(path))
    assert len(back) == 2
    assert isinstance(back[0], Point)
    assert (back[0].x, back[0].y) == (1.5, 2.5)
    assert back[0].obj_id == "1"  # record numbers


def test_shapefile_roundtrip_polygon_with_hole(tmp_path):
    poly = Polygon(rings=[
        np.array([[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]], float),
        np.array([[1, 1], [1, 2], [2, 2], [2, 1], [1, 1]], float),  # CW hole? CCW
    ])
    path = str(tmp_path / "poly.shp")
    write_shapefile(path, [poly])
    back = list(read_shapefile(path))
    assert len(back) == 1
    assert isinstance(back[0], Polygon)
    assert len(back[0].rings) == 2


def test_shapefile_roundtrip_polyline_multipoint(tmp_path):
    ls = LineString(coords=np.array([[0, 0], [1, 1], [2, 0]], float))
    mp = MultiPoint(coords=np.array([[5, 5], [6, 6]], float))
    p1 = str(tmp_path / "ls.shp")
    p2 = str(tmp_path / "mp.shp")
    write_shapefile(p1, [ls])
    write_shapefile(p2, [mp])
    (back_ls,) = read_shapefile(p1)
    (back_mp,) = read_shapefile(p2)
    np.testing.assert_allclose(back_ls.coords, ls.coords)
    np.testing.assert_allclose(back_mp.coords, mp.coords)


def test_shapefile_bad_magic(tmp_path):
    path = tmp_path / "bad.shp"
    path.write_bytes(b"\x00" * 120)
    with pytest.raises(ValueError, match="file code"):
        list(read_shapefile(str(path)))


def test_streaming_job_cli_range(tmp_path):
    from spatialflink_tpu.streaming_job import main

    conf = tmp_path / "conf.yml"
    conf.write_text(
        """
inputStream1:
  topicName: t
  format: CSV
  csvTsvSchemaAttr: [0, 1, 2, 3]
  gridBBox: [0.0, 0.0, 10.0, 10.0]
  numGridCells: 20
  delimiter: ","
query:
  option: 1
  radius: 2.0
  k: 3
  queryPoints:
    - [5.0, 5.0]
window:
  type: "TIME"
  interval: 10
  step: 10
"""
    )
    csv = tmp_path / "in.csv"
    rows = []
    for i in range(100):
        x = 5.0 if i % 4 == 0 else 9.5
        rows.append(f"dev{i%3},{i*500},{x},5.0")
    csv.write_text("\n".join(rows))
    out = tmp_path / "out.csv"
    rc = main(["--config", str(conf), "--source", f"csv:{csv}", "--output", str(out)])
    assert rc == 0
    lines = out.read_text().strip().splitlines()
    assert len(lines) == 25  # every 4th point is at the query point


def test_streaming_job_cli_knn_and_tstats(tmp_path):
    from spatialflink_tpu.streaming_job import main

    base = """
inputStream1:
  topicName: t
  format: CSV
  csvTsvSchemaAttr: [0, 1, 2, 3]
  gridBBox: [0.0, 0.0, 10.0, 10.0]
  numGridCells: 20
  delimiter: ","
query:
  option: {opt}
  radius: 5.0
  k: 2
  queryPoints:
    - [5.0, 5.0]
window:
  type: "TIME"
  interval: 10
  step: 10
"""
    csv = tmp_path / "in.csv"
    csv.write_text("\n".join(f"dev{i%3},{i*500},{4+0.01*i},5.0" for i in range(60)))
    for opt in (3, 6):
        conf = tmp_path / f"conf{opt}.yml"
        conf.write_text(base.format(opt=opt))
        out = tmp_path / f"out{opt}.csv"
        rc = main(["--config", str(conf), "--source", f"csv:{csv}", "--output", str(out)])
        assert rc == 0
        assert out.read_text().strip()


def test_checkpoint_roundtrip_assembler(tmp_path):
    asm = WindowAssembler(TumblingEventTimeWindows(10_000), timestamp_fn=lambda e: e.timestamp)
    pts = [Point(obj_id=f"p{i}", timestamp=i * 1000, x=i, y=i) for i in range(5)]
    for p in pts:
        asm.feed(p)
    path = str(tmp_path / "ckpt.pkl")
    save_checkpoint(path, assembler=assembler_state(asm))

    asm2 = WindowAssembler(TumblingEventTimeWindows(10_000), timestamp_fn=lambda e: e.timestamp)
    restore_assembler(asm2, load_checkpoint(path)["assembler"])
    # Resumed assembler fires the same windows as the original would.
    fired_orig = asm.feed(Point(obj_id="x", timestamp=15_000, x=0, y=0))
    fired_rest = asm2.feed(Point(obj_id="x", timestamp=15_000, x=0, y=0))
    assert [(w.start, w.end, len(w.events)) for w in fired_orig] == [
        (w.start, w.end, len(w.events)) for w in fired_rest
    ]


def test_checkpoint_roundtrip_taggregate(tmp_path):
    from spatialflink_tpu.operators import QueryConfiguration, QueryType, TAggregateQuery

    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)
    op = TAggregateQuery(conf, GRID, aggregate="ALL")
    pts = [Point(obj_id=f"tr{i%2}", timestamp=i * 1000, x=1.0 + i * 0.1, y=1.0)
           for i in range(20)]
    results = list(op.run(iter(pts)))
    path = str(tmp_path / "agg.pkl")
    save_checkpoint(path, op=operator_state(op))

    op2 = TAggregateQuery(conf, GRID, aggregate="ALL")
    restore_operator(op2, load_checkpoint(path)["op"])
    np.testing.assert_array_equal(op2._skeys, op._skeys)
    np.testing.assert_array_equal(op2._smin, op._smin)
    np.testing.assert_array_equal(op2._smax, op._smax)
    assert op2.interner._to_key == op.interner._to_key
    # Continue the stream on the restored operator: same final aggregate.
    more = [Point(obj_id="tr0", timestamp=30_000, x=5.0, y=5.0)]
    final2 = list(op2.run(iter(more)))[-1]
    final1 = list(op.run(iter(more)))[-1]
    assert final1.cells == final2.cells


def test_generate_query_polygons():
    polys = generate_query_polygons(10, 0, 0, 10, 10, grid_size=100, seed=1)
    assert len(polys) == 10
    for p in polys:
        b = p.bbox()
        assert 0 <= b[0] and b[2] <= 10
        assert (b[2] - b[0]) == pytest.approx(0.1)


def test_shapefile_hole_winding_roundtrip(tmp_path):
    """Holes must round-trip as holes (CCW in file), not as solid polygons."""
    from spatialflink_tpu.models.objects import MultiPolygon
    from spatialflink_tpu.ops.polygon import signed_area

    poly = Polygon(rings=[
        np.array([[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]], float),
        np.array([[1, 1], [2, 1], [2, 2], [1, 2], [1, 1]], float),
    ])
    path = str(tmp_path / "hole.shp")
    write_shapefile(path, [poly])
    (back,) = read_shapefile(path)
    assert type(back) is Polygon  # NOT a MultiPolygon of two solids
    assert len(back.rings) == 2
    # Containment agrees: a point inside the hole is outside the polygon.
    import jax.numpy as jnp
    from spatialflink_tpu.ops.polygon import pack_rings, points_in_polygon

    verts, ev = pack_rings(back.rings)
    inside = np.asarray(points_in_polygon(
        jnp.asarray([[1.5, 1.5], [3.0, 3.0]]), jnp.asarray(verts), jnp.asarray(ev)))
    assert not inside[0] and inside[1]


def test_checkpoint_restores_round1_agg_format(tmp_path):
    """A round-1 checkpoint stored TAggregate MapState as a plain
    {(cell, oid_str): (min, max)} dict; restore must convert it to the
    sorted key-array form."""
    from spatialflink_tpu.operators import QueryConfiguration, QueryType, TAggregateQuery

    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)
    op = TAggregateQuery(conf, GRID, aggregate="ALL")
    pts = [Point(obj_id=f"tr{i%2}", timestamp=i * 1000, x=1.0 + i * 0.1, y=1.0)
           for i in range(20)]
    list(op.run(iter(pts)))

    # Re-encode the modern state in the legacy dict format.
    legacy = dict(operator_state(op))
    legacy["agg_state"] = {
        (int(k) >> 32, op.interner.lookup(int(k) & 0xFFFFFFFF)): (int(mn), int(mx))
        for k, mn, mx in zip(op._skeys, op._smin, op._smax)
    }
    path = str(tmp_path / "agg_legacy.pkl")
    save_checkpoint(path, op=legacy)

    op2 = TAggregateQuery(conf, GRID, aggregate="ALL")
    restore_operator(op2, load_checkpoint(path)["op"])
    np.testing.assert_array_equal(op2._skeys, op._skeys)
    np.testing.assert_array_equal(op2._smin, op._smin)
    np.testing.assert_array_equal(op2._smax, op._smax)


def test_streaming_job_cli_kafka_to_kafka(tmp_path, monkeypatch):
    """End to end through the reference's DEFAULT transport: CSV records
    produced to a broker topic → --source kafka → windowed range query →
    --output kafka → results fetched back from the output topic. Runs the
    REAL wire protocol over a real socket (tests/test_kafka_wire.py's
    broker), not a monkeypatched client."""
    import builtins
    import sys as _sys

    _sys.path.insert(0, os.path.dirname(__file__))
    from test_kafka_wire import FakeBroker

    from spatialflink_tpu.streaming_job import main
    from spatialflink_tpu.streams import kafka_wire as kw

    real_import = builtins.__import__

    def no_libs(name, *a, **k):
        if name in ("kafka", "confluent_kafka"):
            raise ImportError(name)
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_libs)

    broker = FakeBroker()
    bs = f"127.0.0.1:{broker.port}"
    try:
        producer = kw.KafkaWireClient(bs)
        lines = []
        for i in range(100):
            x, y = (5.0, 5.0) if i % 4 == 0 else (0.5 + (i % 9), 0.5)
            lines.append((f"d{i % 7},{i * 100},{x},{y}".encode(), None, 0))
        producer.produce("gps-in", 0, lines)
        producer.close()

        conf = tmp_path / "conf.yml"
        conf.write_text(
            """
inputStream1:
  topicName: gps-in
  format: CSV
  csvTsvSchemaAttr: [0, 1, 2, 3]
  gridBBox: [0.0, 0.0, 10.0, 10.0]
  numGridCells: 20
  delimiter: ","
outputStream:
  topicName: results-out
kafkaBootStrapServers: "%s"
query:
  option: 1
  radius: 2.0
  k: 3
  queryPoints:
    - [5.0, 5.0]
window:
  type: "TIME"
  interval: 10
  step: 10
""" % bs
        )
        rc = main([
            "--config", str(conf), "--source", "kafka",
            "--output", "kafka", "--max-records", "100",
        ])
        assert rc == 0
        consumer = kw.KafkaWireClient(bs)
        msgs, _ = consumer.fetch("results-out", 0, 0)
        consumer.close()
        assert len(msgs) == 25  # every 4th point sits on the query point
    finally:
        broker.close()


def test_streaming_job_cli_checkpointed_kill_and_resume(tmp_path):
    """ISSUE 8 end-to-end: the --checkpoint pipeline (option 1 through
    the dataflow driver + exactly-once transactional egress) killed
    mid-run by an armed fault resumes to byte-identical output."""
    from spatialflink_tpu.faults import InjectedFault, faults
    from spatialflink_tpu.streaming_job import main

    conf = tmp_path / "conf.yml"
    conf.write_text(
        """
inputStream1:
  topicName: t
  format: CSV
  csvTsvSchemaAttr: [0, 1, 2, 3]
  gridBBox: [0.0, 0.0, 10.0, 10.0]
  numGridCells: 20
  delimiter: ","
query:
  option: 1
  radius: 2.0
  k: 3
  queryPoints:
    - [5.0, 5.0]
window:
  type: "TIME"
  interval: 10
  step: 10
"""
    )
    csv = tmp_path / "in.csv"
    csv.write_text("\n".join(
        f"dev{i%3},{i*500},{5.0 if i % 4 == 0 else 9.5},5.0"
        for i in range(100)
    ))
    clean = tmp_path / "clean.csv"
    assert main(["--config", str(conf), "--source", f"csv:{csv}",
                 "--output", str(clean),
                 "--checkpoint", str(tmp_path / "ck_clean.bin"),
                 "--checkpoint-every", "1"]) == 0
    want = clean.read_bytes()
    assert want

    out = tmp_path / "out.csv"
    args = ["--config", str(conf), "--source", f"csv:{csv}",
            "--output", str(out),
            "--checkpoint", str(tmp_path / "ck.bin"),
            "--checkpoint-every", "1"]
    faults.arm([{"point": "window.feed", "at": 50, "times": 10_000}])
    try:
        with pytest.raises(InjectedFault):
            main(args)
    finally:
        faults.disarm()
    assert out.read_bytes() != want  # really interrupted
    assert main(args) == 0  # resume from the checkpoint
    assert out.read_bytes() == want


@pytest.mark.parametrize("option,fault_at", [(3, 40), (5, 40)])
def test_streaming_job_knn_join_kill_and_resume(tmp_path, option,
                                                fault_at):
    """ISSUE 9: the newly driver-wired operators (option 3 = window
    kNN, option 5 = window join) through --checkpoint — killed mid-run
    by an armed fault, resumed to byte-identical output."""
    from spatialflink_tpu.faults import InjectedFault, faults
    from spatialflink_tpu.streaming_job import main

    conf = tmp_path / "conf.yml"
    conf.write_text(
        """
inputStream1:
  topicName: t
  format: CSV
  csvTsvSchemaAttr: [0, 1, 2, 3]
  gridBBox: [0.0, 0.0, 10.0, 10.0]
  numGridCells: 20
  delimiter: ","
query:
  option: %d
  radius: 3.0
  k: 3
  queryPoints:
    - [5.0, 5.0]
window:
  type: "TIME"
  interval: 10
  step: 10
""" % option
    )
    csv = tmp_path / "in.csv"
    csv.write_text("\n".join(
        f"dev{i%5},{i*500},{4.0 + (i % 7) * 0.4},{4.0 + (i % 5) * 0.5}"
        for i in range(100)
    ))
    clean = tmp_path / "clean.csv"
    assert main(["--config", str(conf), "--source", f"csv:{csv}",
                 "--output", str(clean),
                 "--checkpoint", str(tmp_path / "ck_clean.bin"),
                 "--checkpoint-every", "1"]) == 0
    want = clean.read_bytes()
    assert want, "vacuous: clean run produced no output"

    out = tmp_path / "out.csv"
    args = ["--config", str(conf), "--source", f"csv:{csv}",
            "--output", str(out),
            "--checkpoint", str(tmp_path / "ck.bin"),
            "--checkpoint-every", "1"]
    faults.arm([{"point": "window.feed", "at": fault_at,
                 "times": 10_000}])
    try:
        with pytest.raises(InjectedFault):
            main(args)
    finally:
        faults.disarm()
    assert out.read_bytes() != want  # really interrupted
    assert main(args) == 0  # resume from the checkpoint
    assert out.read_bytes() == want


def test_streaming_job_checkpoint_arg_validation(tmp_path):
    from spatialflink_tpu.streaming_job import main

    conf = tmp_path / "conf.yml"
    conf.write_text(
        """
inputStream1:
  topicName: t
  format: CSV
  csvTsvSchemaAttr: [0, 1, 2, 3]
  gridBBox: [0.0, 0.0, 10.0, 10.0]
  numGridCells: 20
  delimiter: ","
query:
  option: 1
  radius: 2.0
  k: 3
  queryPoints:
    - [5.0, 5.0]
window:
  type: "TIME"
  interval: 10
  step: 10
"""
    )
    # no file output → the exactly-once protocol cannot apply
    with pytest.raises(SystemExit, match="file --output"):
        main(["--config", str(conf), "--source", "synthetic",
              "--checkpoint", str(tmp_path / "ck.bin")])
    # non-replayable source → resume could not replay the prefix
    with pytest.raises(SystemExit, match="replayable"):
        main(["--config", str(conf), "--source", "synthetic",
              "--output", str(tmp_path / "o.csv"),
              "--checkpoint", str(tmp_path / "ck.bin")])
