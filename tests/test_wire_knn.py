"""ops/wire_knn.py — the ONE wire→digest program shared by the shipped
operator (run_wire_panes), bench.py's headline, and bench_suite's kNN
configs. Pins:

- XLA wire step ≡ the operator SoA digest (knn_pane_digest_compact) on
  the dequantized coordinates (set equality, ≤1 ulp distances — FMA
  fusion freedom between differently-fused programs);
- Pallas strategy (interpret mode on CPU) ≡ XLA strategy, including the
  in-program overflow fallback (exact either way);
- bucket padding + n_valid can never leak padding points into results;
- run_wire_panes window parity with run_soa_panes, both strategies.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import pallas_int64_xfail

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import Point
from spatialflink_tpu.operators import QueryConfiguration, QueryType
from spatialflink_tpu.operators.knn_query import PointPointKNNQuery
from spatialflink_tpu.ops.knn import knn_pane_digest_compact
from spatialflink_tpu.ops.wire_knn import (
    digests_agree,
    make_wire_digest_step,
    select_wire_digest_step,
    wire_digest_xla,
)
from spatialflink_tpu.streams.wire import WireFormat

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
WF = WireFormat.for_grid(GRID)
NSEG = 64


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _wire(rng, n, oid_hi=9):
    xy = np.stack([rng.uniform(0, 10, n), rng.uniform(0, 10, n)], axis=1)
    q = WF.quantize(xy)
    oid = rng.integers(0, oid_hi, n).astype(np.int16)
    wire = np.ascontiguousarray(
        np.concatenate([q, oid.view(np.uint16)[:, None]], axis=1).T
    )
    return wire, WF.dequantize_np(q), oid.astype(np.int32)


def _args(wire, n=None):
    return (
        jnp.asarray(wire),
        jnp.int32(wire.shape[1] if n is None else n),
        jnp.asarray(np.asarray([5.0, 5.0], np.float32)),
        jnp.asarray(np.asarray(WF.scale, np.float32)),
        jnp.asarray(np.asarray(WF.origin, np.float32)),
        jnp.float32(2.0),
    )


def test_xla_step_matches_operator_soa_digest(rng):
    wire, xyf, oid = _wire(rng, 1000)
    d_wire = jax.jit(
        make_wire_digest_step(num_segments=NSEG, cand=256)
    )(*_args(wire))
    d_soa = knn_pane_digest_compact(
        jnp.asarray(xyf), jnp.ones(1000, bool), None, None,
        jnp.asarray(oid), jnp.asarray(np.asarray([5.0, 5.0], np.float32)),
        np.float32(2.0), jnp.int32(0), num_segments=NSEG, cand=256,
    )
    assert digests_agree(d_wire.seg_min, d_wire.rep, d_soa.seg_min,
                         d_soa.rep)
    live = np.asarray(d_wire.seg_min) != np.finfo(np.float32).max
    assert live.sum() > 3, "degenerate: almost nothing in radius"


@pallas_int64_xfail
def test_pallas_interpret_matches_xla(rng):
    wire, _, _ = _wire(rng, 700)
    args = _args(wire)
    d_x = jax.jit(make_wire_digest_step(num_segments=NSEG))(*args)
    d_p = jax.jit(make_wire_digest_step(
        num_segments=NSEG, strategy="pallas", interpret=True,
    ))(*args)
    assert digests_agree(d_p.seg_min, d_p.rep, d_x.seg_min, d_x.rep)


@pallas_int64_xfail
def test_pallas_overflow_fallback_exact(rng):
    """More hits than max_cand ⇒ the lax.cond reruns the full XLA
    scatter digest in-program — results stay exact."""
    wire, _, _ = _wire(rng, 600)
    args = list(_args(wire))
    args[5] = jnp.float32(100.0)  # everything in radius: 600 hits
    d_p = jax.jit(make_wire_digest_step(
        num_segments=NSEG, strategy="pallas", interpret=True,
        max_cand=128,
    ))(*args)
    d_x = jax.jit(make_wire_digest_step(num_segments=NSEG))(*args)
    live = np.asarray(d_x.seg_min) != np.finfo(np.float32).max
    assert live.sum() == 9  # every oid present at this radius
    assert digests_agree(d_p.seg_min, d_p.rep, d_x.seg_min, d_x.rep)


@pytest.mark.parametrize("strategy", [
    "xla", pytest.param("pallas", marks=pallas_int64_xfail),
])
def test_n_valid_padding_never_matches(rng, strategy):
    """Bucket padding (u16 zeros → the grid ORIGIN, deliberately within
    radius of an origin-adjacent query) must be masked out by n_valid."""
    n = 300
    wire, _, _ = _wire(rng, n)
    padded = np.concatenate(
        [wire, np.zeros((3, 212), np.uint16)], axis=1
    )
    step = jax.jit(make_wire_digest_step(
        num_segments=NSEG, strategy=strategy, interpret=True,
    ))
    q_origin = jnp.asarray(np.asarray([0.5, 0.5], np.float32))
    sc = jnp.asarray(np.asarray(WF.scale, np.float32))
    og = jnp.asarray(np.asarray(WF.origin, np.float32))
    r = jnp.float32(3.0)
    d_pad = step(jnp.asarray(padded), jnp.int32(n), q_origin, sc, og, r)
    d_ref = step(jnp.asarray(wire), jnp.int32(n), q_origin, sc, og, r)
    np.testing.assert_array_equal(
        np.asarray(d_pad.seg_min), np.asarray(d_ref.seg_min)
    )
    np.testing.assert_array_equal(
        np.asarray(d_pad.rep), np.asarray(d_ref.rep)
    )
    # sanity: unmasked padding WOULD have matched (origin within radius)
    d_leak = step(
        jnp.asarray(padded), jnp.int32(padded.shape[1]), q_origin, sc,
        og, r,
    )
    assert not np.array_equal(
        np.asarray(d_leak.seg_min), np.asarray(d_ref.seg_min)
    )


def test_select_auto_on_cpu_stays_xla(rng):
    wire, _, _ = _wire(rng, 256)
    args = _args(wire)
    kind, _ = select_wire_digest_step(
        *args, num_segments=NSEG, strategy="auto",
    )
    assert kind == "xla"


@pallas_int64_xfail
def test_select_forced_pallas_self_checks(rng):
    wire, _, _ = _wire(rng, 256)
    kind, step = select_wire_digest_step(
        *_args(wire), num_segments=NSEG, strategy="pallas",
        interpret=True,
    )
    assert kind == "pallas"


def _soa_chunks(ts, xyf, oid):
    return iter([{
        "ts": ts,
        "x": xyf[:, 0].astype(np.float64),
        "y": xyf[:, 1].astype(np.float64),
        "oid": oid,
    }])


@pytest.mark.parametrize("strategy", [
    "xla", pytest.param("pallas", marks=pallas_int64_xfail),
])
def test_run_wire_panes_matches_run_soa_panes(rng, strategy):
    """The shipped wire-ingest operator path fires the same windows with
    the same neighbors as the SoA pane path on the same (dequantized)
    coordinates — variable pane sizes exercise the bucket-pad + n_valid
    seam."""
    n = 3000
    ts = np.sort(rng.integers(0, 40_000, n)).astype(np.int64)
    wire, xyf, oid = _wire(rng, n)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10,
                              slide_step=2)
    q = Point(x=5.0, y=5.0)
    r, k = 2.0, 6
    slide_ms = conf.slide_step_ms

    soa = {
        (s, e): (list(map(int, oo)), np.asarray(dd))
        for s, e, oo, dd, nv in PointPointKNNQuery(conf, GRID).run_soa_panes(
            _soa_chunks(ts, xyf, oid), q, r, k,
            num_segments=NSEG, dtype=np.float32,
        )
    }

    slides = []
    for ps in range(0, 40_000, slide_ms):
        sel = (ts >= ps) & (ts < ps + slide_ms)
        slides.append(np.ascontiguousarray(wire[:, sel]))
    op = PointPointKNNQuery(conf, GRID)
    wire_res = {
        (s, e): (list(map(int, oo)), np.asarray(dd))
        for s, e, oo, dd, nv in op.run_wire_panes(
            slides, q, r, k, NSEG, WF, start_ms=0,
            strategy=strategy, interpret=True,
        )
    }
    assert op.last_wire_digest_kind == strategy
    # Every window run_soa_panes fires — INCLUDING the leading partials
    # (negative starts) and the trailing flush — must fire identically
    # on the wire path (the code-review r5 finding: an intersection-only
    # compare would mask dropped partial windows).
    missing = set(soa) - set(wire_res)
    assert not missing, f"wire path dropped windows: {sorted(missing)}"
    assert min(soa)[0] < 0, "expected leading partial windows in the ref"
    matched_neighbors = 0
    for key in sorted(soa):
        o_s, d_s = soa[key]
        o_w, d_w = wire_res[key]
        assert o_s == o_w, f"window {key}: oids diverge"
        np.testing.assert_allclose(d_w, d_s, rtol=5e-7, atol=0)
        matched_neighbors += len(o_s)
    assert matched_neighbors > 0, "degenerate: every window empty"


def test_wire_panes_producer_feeds_run_wire_panes(rng):
    """streams/wire.py:wire_panes (the SoA→plane-major producer) must
    bin identically to hand-built slides — incl. EMPTY panes inside
    event-time gaps — so the full ingest→operator seam matches
    run_soa_panes end to end."""
    from spatialflink_tpu.streams.wire import wire_panes

    n = 2000
    ts = np.sort(rng.integers(0, 30_000, n)).astype(np.int64)
    ts[(ts >= 8_000) & (ts < 14_000)] = 7_999  # a 3-pane event gap
    ts = np.sort(ts)
    wire, xyf, oid = _wire(rng, n)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10,
                              slide_step=2)
    q, r, k = Point(x=5.0, y=5.0), 2.0, 6
    slide_ms = conf.slide_step_ms

    chunks = [
        {"ts": ts[a:b], "x": xyf[a:b, 0].astype(np.float64),
         "y": xyf[a:b, 1].astype(np.float64), "oid": oid[a:b]}
        for a, b in zip(range(0, n, 300), list(range(300, n, 300)) + [n])
    ]
    produced = list(wire_panes(chunks, WF, slide_ms, start_ms=0))
    manual = []
    for ps in range(0, int(ts[-1]) + 1, slide_ms):
        sel = (ts >= ps) & (ts < ps + slide_ms)
        manual.append(np.ascontiguousarray(wire[:, sel]))
    assert len(produced) == len(manual)
    assert any(p.shape[1] == 0 for p in produced), "gap panes missing"
    for a, b in zip(produced, manual):
        np.testing.assert_array_equal(a, b)

    soa = {
        (s, e): (list(map(int, oo)), np.asarray(dd))
        for s, e, oo, dd, nv in PointPointKNNQuery(conf, GRID).run_soa_panes(
            _soa_chunks(ts, xyf, oid), q, r, k,
            num_segments=NSEG, dtype=np.float32,
        )
    }
    got = {
        (s, e): (list(map(int, oo)), np.asarray(dd))
        for s, e, oo, dd, nv in PointPointKNNQuery(conf, GRID)
        .run_wire_panes(produced, q, r, k, NSEG, WF, start_ms=0)
    }
    # Set EQUALITY, not ⊆: windows made only of empty panes (the event
    # gap) are suppressed on the wire path exactly like the SoA
    # assembler never builds them — the r5 every-window-fires deviation
    # is resolved, not documented around (ADVICE r5).
    assert set(soa) == set(got), (
        f"extra: {sorted(set(got) - set(soa))} "
        f"missing: {sorted(set(soa) - set(got))}"
    )
    for key in soa:
        assert soa[key][0] == got[key][0]
        np.testing.assert_allclose(got[key][1], soa[key][1], rtol=5e-7,
                                   atol=0)


def test_wire_panes_rejects_out_of_order():
    from spatialflink_tpu.streams.wire import wire_panes

    chunks = [
        {"ts": np.asarray([5_000], np.int64), "x": np.asarray([1.0]),
         "y": np.asarray([1.0]), "oid": np.asarray([0])},
        {"ts": np.asarray([1_000], np.int64), "x": np.asarray([1.0]),
         "y": np.asarray([1.0]), "oid": np.asarray([0])},
    ]
    with pytest.raises(ValueError, match="out-of-order"):
        list(wire_panes(chunks, WF, 2_000, start_ms=0))
    # disorder WITHIN one chunk must raise too (binary-search binning
    # would silently mis-bin; r5 code review)
    bad = [{
        "ts": np.asarray([11_000, 8_500, 12_000], np.int64),
        "x": np.asarray([1.0, 1.0, 1.0]), "y": np.asarray([1.0, 1.0, 1.0]),
        "oid": np.asarray([0, 0, 0]),
    }]
    with pytest.raises(ValueError, match="out-of-order"):
        list(wire_panes(bad, WF, 2_000, start_ms=0))


def test_run_wire_panes_rejects_bad_input():
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10,
                              slide_step=2)
    op = PointPointKNNQuery(conf, GRID)
    with pytest.raises(ValueError, match="plane-major"):
        list(op.run_wire_panes(
            [np.zeros((100, 3), np.uint16)], Point(x=5.0, y=5.0),
            2.0, 5, NSEG, WF,
        ))
    with pytest.raises(ValueError, match="plane-major"):
        list(op.run_wire_panes(
            [np.zeros((3, 100), np.float32)], Point(x=5.0, y=5.0),
            2.0, 5, NSEG, WF,
        ))


def test_wire_pane_assembler_restore_rejects_mismatched_config():
    """A checkpoint from one (slide, wire-format) must not restore into
    another — pane boundaries/quantization would silently shift (r5
    code review)."""
    from spatialflink_tpu.streams.wire import WireFormat, WirePaneAssembler

    asm = WirePaneAssembler(WF, 2_000, start_ms=0)
    asm.feed({"ts": np.asarray([100], np.int64), "x": np.asarray([1.0]),
              "y": np.asarray([1.0]), "oid": np.asarray([0])})
    snap = asm.state()
    other = WirePaneAssembler(WF, 1_000, start_ms=0)
    with pytest.raises(ValueError, match="slide_ms"):
        other.restore(snap)
    wf2 = WireFormat(0.0, 20.0, 0.0, 20.0)
    other2 = WirePaneAssembler(wf2, 2_000, start_ms=0)
    with pytest.raises(ValueError, match="wire format"):
        other2.restore(snap)
    ok = WirePaneAssembler(WF, 2_000, start_ms=0)
    ok.restore(snap)
    assert ok.state()["cur"] == snap["cur"]
