"""Overload control (spatialflink_tpu/overload.py): bounded admission
(backpressure vs counted shedding), watermark-aware late/oldest-first
shedding, the SLO-driven degradation ladder and its rung effects, the
device-path circuit breaker, checkpointed shed determinism, and the
live/post-hoc SLO budget twins (shed_budget / degraded_window_budget).
"""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from spatialflink_tpu import overload, slo  # noqa: E402
from spatialflink_tpu.driver import (  # noqa: E402
    RetryPolicy,
    WindowedDataflowDriver,
    _toy_pipeline,
    render_range_result,
)
from spatialflink_tpu.faults import InjectedFault, faults  # noqa: E402
from spatialflink_tpu.operators.range_query import (  # noqa: E402
    PointPointRangeQuery,
)
from spatialflink_tpu.overload import (  # noqa: E402
    OverloadController,
    OverloadPolicy,
)
from spatialflink_tpu.streams.sinks import (  # noqa: E402
    TransactionalFileSink,
)
from spatialflink_tpu.telemetry import telemetry  # noqa: E402


@pytest.fixture(autouse=True)
def _clean():
    yield
    slo.uninstall()
    overload.uninstall()
    faults.disarm()
    telemetry.disable()


class _Ev:
    def __init__(self, ts):
        self.timestamp = int(ts)


def _event_names():
    return [e["name"] for e in telemetry.events]


# ---------------------------------------------------------------------------
# Policy parsing


class TestPolicy:
    def test_strict_parse_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown overload policy"):
            OverloadPolicy.from_dict({"max_bufferd_events": 8})

    def test_strict_parse_rejects_unknown_rung_action(self):
        with pytest.raises(ValueError, match="unknown action"):
            OverloadPolicy(ladder=[{"action": "turbo"}])

    def test_strict_parse_rejects_unknown_rung_key(self):
        with pytest.raises(ValueError, match="unknown keys"):
            OverloadPolicy(ladder=[{"action": "batch_slides", "N": 4}])

    def test_dict_roundtrip_and_env_forms(self, tmp_path):
        p = OverloadPolicy(max_buffered_events=8, lag_shed_ceiling_ms=500,
                           ladder=[{"action": "clamp_compaction"}])
        assert OverloadPolicy.from_dict(p.to_dict()) == p
        assert OverloadPolicy.from_env(json.dumps(p.to_dict())) == p
        f = tmp_path / "policy.json"
        f.write_text(json.dumps(p.to_dict()))
        assert OverloadPolicy.from_env(str(f)) == p

    def test_version_mismatch_raises(self):
        with pytest.raises(ValueError, match="overload_version"):
            OverloadPolicy.from_dict({"overload_version": 99})

    def test_strict_parse_rejects_bad_rung_values(self):
        """Value typos must fail at SFT_OVERLOAD_POLICY load, not become
        a silent no-op rung (pane_backend targeting nothing) or a
        mid-overload crash at the first step-down (non-int cap/n)
        (r9 code review)."""
        with pytest.raises(ValueError, match="unknown target"):
            OverloadPolicy(ladder=[{"action": "pane_backend",
                                    "to": "devise"}])
        with pytest.raises(ValueError, match="cap must be"):
            OverloadPolicy(ladder=[{"action": "clamp_compaction",
                                    "cap": "top"}])
        with pytest.raises(ValueError, match="cap must be"):
            OverloadPolicy(ladder=[{"action": "clamp_compaction",
                                    "cap": -1}])
        with pytest.raises(ValueError, match="n must be"):
            OverloadPolicy(ladder=[{"action": "batch_slides",
                                    "n": "four"}])
        with pytest.raises(ValueError, match="n must be"):
            OverloadPolicy(ladder=[{"action": "batch_slides", "n": 0}])


# ---------------------------------------------------------------------------
# Bounded admission


class TestAdmission:
    def test_non_pausable_sheds_beyond_budget(self):
        telemetry.enable()
        c = OverloadController(OverloadPolicy(max_buffered_events=3,
                                              admission_window_ms=10_000))
        verdicts = [c.admit_item(_Ev(t), pausable=False)
                    for t in range(0, 80, 10)]
        assert verdicts[:3] == [True] * 3
        assert verdicts[3:] == [False] * 5
        snap = c.snapshot()
        assert snap["shed"]["admission"]["events"] == 5
        assert snap["shed_total"] == 5
        # Transition, not spam: ONE shedding event for the burst.
        assert _event_names().count("overload_shedding:admission") == 1

    def test_pausable_backpressures_instead_of_shedding(self):
        telemetry.enable()
        c = OverloadController(OverloadPolicy(max_buffered_events=3))
        assert all(c.admit_item(_Ev(t), pausable=True)
                   for t in range(0, 80, 10))
        snap = c.snapshot()
        assert snap["shed_total"] == 0
        assert snap["backpressure_engaged"] == 1
        assert "overload_backpressure:engaged" in _event_names()
        # A fired window drains the burst and releases the signal.
        c.on_window_fired(3, lag_ms=0.0, end=1000)
        assert "overload_backpressure:released" in _event_names()

    def test_event_time_horizon_resets_the_burst(self):
        """Shed events never advance the watermark, so the burst budget
        must reset on EVENT TIME — otherwise one blown budget starves
        the stream forever."""
        c = OverloadController(OverloadPolicy(max_buffered_events=2,
                                              admission_window_ms=1000))
        assert c.admit_item(_Ev(0), pausable=False)
        assert c.admit_item(_Ev(10), pausable=False)
        assert not c.admit_item(_Ev(20), pausable=False)
        # Past the horizon: a new burst interval, admission resumes.
        assert c.admit_item(_Ev(2000), pausable=False)
        assert c.snapshot()["shed_total"] == 1

    def test_chunks_measured_by_arrays_and_bytes(self):
        c = OverloadController(OverloadPolicy(
            max_buffered_bytes=100, admission_window_ms=10_000))
        chunk = {"ts": np.arange(4, dtype=np.int64),
                 "x": np.zeros(4), "y": np.zeros(4)}
        assert c.admit_item(chunk, pausable=False)  # 96 B admitted
        assert not c.admit_item(chunk, pausable=False)  # would be 192 B
        shed = c.snapshot()["shed"]["admission"]
        assert shed["events"] == 4 and shed["bytes"] > 0


# ---------------------------------------------------------------------------
# Watermark-aware shedding


def _lag_controller(**kw):
    kw.setdefault("lag_shed_ceiling_ms", 1000)
    kw.setdefault("lag_recover_ms", 100)
    kw.setdefault("shed_oldest_after_windows", 2)
    return OverloadController(OverloadPolicy(**kw))


class TestLagShedding:
    def test_lag_ceiling_enters_shed_mode_late_first(self):
        telemetry.enable()
        c = _lag_controller()
        c.admit_item(_Ev(5000), pausable=False)  # stream head
        c.on_window_fired(5, lag_ms=4000.0, end=1000)  # way over ceiling
        assert c.snapshot()["shedding"] is True
        assert "overload_shedding:lag" in _event_names()
        # Late-first: an out-of-order straggler sheds...
        assert not c.admit_item(_Ev(1200), pausable=False)
        assert c.snapshot()["shed"]["late"]["events"] == 1
        # ...the stream head does not.
        assert c.admit_item(_Ev(6000), pausable=False)

    def test_escalates_to_oldest_then_recovers(self):
        telemetry.enable()
        c = _lag_controller()
        c.admit_item(_Ev(5000), pausable=False)
        c.on_window_fired(5, lag_ms=4000.0, end=1000)  # enter
        c.on_window_fired(5, lag_ms=4000.0, end=2000)  # still behind 1
        c.on_window_fired(5, lag_ms=4000.0, end=3000)  # still behind 2 → escalate
        assert "overload_shedding:oldest" in _event_names()
        # Oldest-first: an in-order event destined only for the
        # already-behind windows sheds too.
        assert not c.admit_item(_Ev(2500), pausable=False)
        assert c.snapshot()["shed"]["oldest"]["events"] == 1
        # Recovery below the floor exits BOTH modes, transition event.
        c.on_window_fired(5, lag_ms=50.0, end=4000)
        assert c.snapshot()["shedding"] is False
        assert "overload_recovered:lag" in _event_names()
        assert c.admit_item(_Ev(3500), pausable=False)

    def test_shed_schedule_is_deterministic(self):
        """Same stream → same sheds, run to run (the chaos matrix's
        byte-identical-resume premise)."""
        def run_once():
            c = _lag_controller(max_buffered_events=4,
                                admission_window_ms=500)
            rng = np.random.default_rng(3)
            for i in range(300):
                ts = int(rng.integers(0, 20_000))
                c.admit_item(_Ev(ts), pausable=False)
                if i % 7 == 0:
                    c.on_window_fired(3, lag_ms=float(ts % 3000),
                                      end=ts - (ts % 1000))
            return c.snapshot()["shed"]

        assert run_once() == run_once()


# ---------------------------------------------------------------------------
# Degradation ladder


LADDER = (
    {"action": "clamp_compaction", "cap": 32},
    {"action": "batch_slides", "n": 3},
    {"action": "pane_backend", "to": "native"},
)


class TestLadder:
    def test_steps_down_apply_cumulative_effects(self):
        telemetry.enable()
        c = overload.install(OverloadController(OverloadPolicy(
            ladder=LADDER, degrade_cooldown=1, recover_after=2)))
        assert (overload.compaction_clamp(), overload.batch_slides(),
                overload.pane_backend()) == (None, 1, None)
        c.on_slo_evaluation(False)
        assert overload.compaction_clamp() == 32
        c.on_slo_evaluation(False)
        assert overload.batch_slides() == 3
        c.on_slo_evaluation(False)
        assert overload.pane_backend() == "native"
        assert c.rung == 3
        names = _event_names()
        assert "overload_rung_down:clamp_compaction" in names
        assert "overload_rung_down:batch_slides" in names
        assert "overload_rung_down:pane_backend" in names

    def test_sustained_recovery_steps_back_up(self):
        telemetry.enable()
        c = overload.install(OverloadController(OverloadPolicy(
            ladder=LADDER, degrade_cooldown=1, recover_after=2)))
        c.on_slo_evaluation(False)
        c.on_slo_evaluation(False)
        assert c.rung == 2
        for _ in range(4):  # 2 healthy windows per rung
            c.on_window_fired(5, lag_ms=0.0, end=1000)
        assert c.rung == 0
        names = _event_names()
        assert "overload_rung_up:batch_slides" in names
        assert "overload_rung_up:clamp_compaction" in names
        assert (overload.compaction_clamp(), overload.batch_slides(),
                overload.pane_backend()) == (None, 1, None)

    def test_midband_lag_is_neutral_for_the_ladder(self):
        """recover < lag ≤ ceiling without shed mode steps the ladder
        NEITHER down (the documented triggers are shed / backpressure /
        SLO violations only) nor up (not recovered — the healthy streak
        breaks) (r9 code review)."""
        telemetry.enable()
        ctrl = overload.install(OverloadController(OverloadPolicy(
            lag_shed_ceiling_ms=5_000, lag_recover_ms=2_500,
            ladder=({"action": "batch_slides", "n": 2},),
            degrade_cooldown=1, recover_after=2)))
        for _ in range(6):
            ctrl.on_window_fired(1, lag_ms=3_000.0)
        assert ctrl.rung == 0  # sustained mid-band lag never steps down
        ctrl.on_slo_evaluation(False)
        assert ctrl.rung == 1
        for _ in range(4):  # mid-band windows don't count as recovery…
            ctrl.on_window_fired(1, lag_ms=3_000.0)
        assert ctrl.rung == 1
        for _ in range(2):  # …sustained lag ≤ recover does
            ctrl.on_window_fired(1, lag_ms=1_000.0)
        assert ctrl.rung == 0

    def test_sustained_admission_shedding_holds_the_rung_down(self):
        """A fired window amid ongoing admission sheds is NOT a healthy
        observation: the ladder must not step back up (un-clamping
        compaction, re-starting recompile churn) while every cycle is
        still shedding. Backpressure engaged during the cycle counts
        the same way — the fire-site check reads the cycle's state
        captured BEFORE the per-fire resets (r9 code review)."""
        ctrl = overload.install(OverloadController(OverloadPolicy(
            max_buffered_events=2, admission_window_ms=10_000,
            ladder=({"action": "clamp_compaction", "cap": 0},),
            degrade_cooldown=1, recover_after=3)))
        ctrl.on_slo_evaluation(False)  # length-1 ladder: rung 1 is the floor
        assert ctrl.rung == 1
        for cycle in range(6):  # sustained burst: 5 events per fire
            for i in range(5):
                ctrl.admit_item(_Ev(cycle * 100 + i), pausable=False)
            ctrl.on_window_fired(5, lag_ms=0.0, end=cycle * 100)
            assert ctrl.rung == 1, f"rung stepped up mid-shed @ {cycle}"
        assert ctrl.shed_total > 0
        # Same contract for a pausable source: backpressure engaged
        # during the cycle breaks the healthy streak at the fire.
        ctrl2 = overload.install(OverloadController(OverloadPolicy(
            max_buffered_events=2, admission_window_ms=10_000,
            ladder=({"action": "clamp_compaction", "cap": 0},),
            degrade_cooldown=1, recover_after=3)))
        ctrl2.on_slo_evaluation(False)
        assert ctrl2.rung == 1
        for cycle in range(6):
            for i in range(5):
                ctrl2.admit_item(_Ev(cycle * 100 + i), pausable=True)
            ctrl2.on_window_fired(5, lag_ms=0.0, end=cycle * 100)
            assert ctrl2.rung == 1, f"rung stepped up mid-bp @ {cycle}"
        # Once the burst ends, sustained clean fires DO recover.
        for cycle in range(6, 9):
            ctrl2.on_window_fired(1, lag_ms=0.0, end=cycle * 100)
        assert ctrl2.rung == 0

    def test_live_slo_violation_drives_the_ladder(self):
        """The wiring contract: SloEngine.evaluate → overload hook."""
        telemetry.enable()
        ctrl = overload.install(OverloadController(OverloadPolicy(
            ladder=LADDER, degrade_cooldown=1)))
        eng = slo.install(slo.SloEngine(slo.SloSpec(
            late_drop_budget=0, eval_interval_s=0.0)))
        telemetry.record_late_drop(3)  # bust the budget
        eng.evaluate()
        assert ctrl.rung == 1

    def test_pick_capacity_honors_the_clamp(self):
        from spatialflink_tpu.ops.compaction import pick_capacity

        assert pick_capacity(3, 64) == 8  # ladder floor, unclamped
        overload.install(OverloadController(OverloadPolicy(
            ladder=({"action": "clamp_compaction", "cap": 32},),
            degrade_cooldown=1))).on_slo_evaluation(False)
        assert pick_capacity(3, 64) == 32  # floored at the clamp rung
        assert pick_capacity(60, 64) == 64  # exactness still wins
        overload.uninstall()
        overload.install(OverloadController(OverloadPolicy(
            ladder=({"action": "clamp_compaction", "cap": 0},),
            degrade_cooldown=1))).on_slo_evaluation(False)
        assert pick_capacity(3, 64) == 64  # cap 0 = pin the top rung

    def test_traj_stats_auto_backend_biased_host(self):
        """An active pane_backend rung routes backend="auto" away from
        the device engine — and the three engines answer identically,
        so this is pure routing, not results."""
        from spatialflink_tpu.streams import panes

        ctrl = overload.install(OverloadController(OverloadPolicy(
            ladder=({"action": "pane_backend", "to": "native"},),
            degrade_cooldown=1)))
        ctrl.on_slo_evaluation(False)
        ts = np.arange(0, 4000, 100, dtype=np.int64)
        xy = np.stack([np.linspace(0, 1, len(ts)),
                       np.zeros(len(ts))], axis=1)
        oid = (np.arange(len(ts)) % 3).astype(np.int64)
        a = panes.traj_stats_sliding(ts, xy, oid, 3, 1000, 500,
                                     backend="auto")
        b = panes.traj_stats_sliding(ts, xy, oid, 3, 1000, 500,
                                     backend="numpy")
        np.testing.assert_array_equal(a.starts, b.starts)
        np.testing.assert_allclose(a.spatial, b.spatial)


# ---------------------------------------------------------------------------
# Circuit breaker


def _run_range(driver=None, n_events=120):
    grid, conf, source, query = _toy_pipeline(n_events=n_events)
    op = PointPointRangeQuery(conf, grid)
    return list(op.run(source(), [query], 1.5, driver=driver))


class TestCircuitBreaker:
    def test_open_fallback_probe_close_round_trip(self):
        telemetry.enable()
        base = _run_range()
        # Device path fails for exactly 2 windows → the circuit opens;
        # while open, windows route to the twin with NO device attempt;
        # the 3rd fallback window half-opens for a probe, which succeeds
        # and closes the circuit — the device path comes BACK (unlike
        # permanent failover).
        ctrl = OverloadController(OverloadPolicy(
            breaker_failures=2, breaker_probe_every=3))
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=0, backoff_s=0.0), overload=ctrl)
        faults.arm([{"point": "driver.window", "at": 1, "times": 2}])
        driven = _run_range(driver=drv)
        faults.disarm()
        br = ctrl.breaker
        assert br.state == "closed"
        assert br.opens == 1 and br.probes == 1
        assert drv.backend == "device"  # never permanently failed over
        assert drv.stats["failovers"] == 0
        # windows 1-2 (device failures) + 3-4 (circuit open) = degraded
        assert ctrl.degraded_windows == 4
        names = _event_names()
        assert "circuit_open" in names
        assert "circuit_half_open" in names
        assert "circuit_closed" in names
        # Result parity across every route (device / twin / probe).
        assert len(driven) == len(base) > 5
        for a, b in zip(base, driven):
            assert [p.obj_id for p in a.objects] == \
                   [p.obj_id for p in b.objects]
            np.testing.assert_allclose(a.dists, b.dists, rtol=3e-7)

    def test_probe_failure_reopens(self):
        ctrl = OverloadController(OverloadPolicy(
            breaker_failures=1, breaker_probe_every=2))
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=0, backoff_s=0.0), overload=ctrl)
        # Every device attempt fails: open stays open through probes.
        faults.arm([{"point": "driver.window", "at": 1, "times": 10_000}])
        driven = _run_range(driver=drv)
        faults.disarm()
        assert ctrl.breaker.state == "open"
        assert ctrl.breaker.probes >= 2
        assert len(driven) > 5  # the twin carried the whole run

    def test_link_degraded_ratio_opens_preemptively(self):
        telemetry.enable()
        ctrl = OverloadController(OverloadPolicy(
            breaker_failures=9, breaker_link_ratio=0.5))
        # p50 100 MB/s → last 10 MB/s: ratio 0.1 < 0.5.
        for mbps in (100.0, 100.0, 100.0, 10.0):
            telemetry.record_link_sample(1.0, mbps, 1 << 18)
        assert ctrl.breaker.route() == "fallback"
        assert ctrl.breaker.state == "open"
        assert "circuit_open" in _event_names()

    def test_probe_close_not_reopened_by_stale_link_gauges(self):
        """A probe-success close sticks until a FRESHER LinkProbe sample
        arrives: probes only run at bench phase boundaries, so re-reading
        the same degraded sample would flap the circuit
        open→probe→closed→open forever (r9 code review)."""
        telemetry.enable()
        ctrl = OverloadController(OverloadPolicy(
            breaker_link_ratio=0.5, breaker_probe_every=1))
        for mbps in (100.0, 100.0, 100.0, 10.0):
            telemetry.record_link_sample(1.0, mbps, 1 << 18)
        br = ctrl.breaker
        assert br.route() == "fallback" and br.state == "open"
        assert br.route() == "probe"  # half-open re-dial
        br.record_success()  # the device path provably works again
        assert br.state == "closed"
        # The SAME stale degraded sample must not re-open the circuit.
        assert br.route() == "device"
        assert br.state == "closed" and br.opens == 1
        # A fresh degraded sample re-arms the ratio check.
        telemetry.record_link_sample(1.0, 5.0, 1 << 18)
        assert br.route() == "fallback"
        assert br.opens == 2

    def test_link_only_policy_ignores_failure_counts(self):
        """breaker_failures=0 disables count-based opening even when a
        link-ratio-only policy instantiates the breaker (the documented
        '0 disables' contract) (r9 code review)."""
        ctrl = OverloadController(OverloadPolicy(breaker_link_ratio=0.5))
        br = ctrl.breaker
        assert br is not None
        for _ in range(5):
            br.record_failure(window_start=0, error="boom")
        assert br.state == "closed"
        assert br.opens == 0

    def test_without_breaker_permanent_failover_is_preserved(self):
        ctrl = OverloadController(OverloadPolicy())  # no breaker config
        assert ctrl.breaker is None
        drv = WindowedDataflowDriver(
            retry=RetryPolicy(max_retries=0, backoff_s=0.0), overload=ctrl)
        faults.arm([{"point": "driver.window", "at": 1, "times": 10_000}])
        driven = _run_range(driver=drv)
        faults.disarm()
        assert drv.backend == "fallback"  # PR 8 semantics unchanged
        assert drv.stats["failovers"] == 1
        assert ctrl.degraded_windows == len(driven)


# ---------------------------------------------------------------------------
# Driver integration: admission + checkpointed shed determinism


def _shedding_pipeline(workdir, fault_plan=None):
    """Range pipeline under a tiny admission budget over a NON-pausable
    source: sheds are part of the committed stream position."""
    grid, conf, source, query = _toy_pipeline()
    sink = TransactionalFileSink(os.path.join(workdir, "egress.csv"))
    # The toy stream runs 10 events per 1000 ms of event time: a budget
    # of 3 per 500 ms horizon sheds ~2 of every 5 — a sustained burst.
    ctrl = OverloadController(OverloadPolicy(max_buffered_events=3,
                                             admission_window_ms=500))
    driver = WindowedDataflowDriver(
        checkpoint_path=os.path.join(workdir, "ckpt.bin"),
        checkpoint_every=1, sink=sink,
        retry=RetryPolicy(max_retries=1, backoff_s=0.0), failover=False,
        overload=ctrl, source_pausable=False,
    )
    op = PointPointRangeQuery(conf, grid)
    if fault_plan:
        faults.arm(fault_plan)
    try:
        for res in op.run(source(), [query], 1.5, driver=driver):
            for line in render_range_result(res):
                sink.stage(line)
    finally:
        faults.disarm()
    return driver, ctrl


class TestDriverIntegration:
    def test_no_budget_controller_changes_nothing(self):
        base = _run_range()
        ctrl = OverloadController(OverloadPolicy())
        driven = _run_range(driver=WindowedDataflowDriver(overload=ctrl))
        assert ctrl.shed_total == 0
        assert len(driven) == len(base)
        for a, b in zip(base, driven):
            assert [p.obj_id for p in a.objects] == \
                   [p.obj_id for p in b.objects]
            np.testing.assert_array_equal(a.dists, b.dists)

    def test_sheds_count_consumed_and_survive_kill_mid_shed(self, tmp_path):
        """The acceptance round trip in-process: a burst run sheds
        deterministically, dies mid-shed, and resumes to byte-identical
        committed egress with the SAME total shed schedule."""
        clean = tmp_path / "clean"
        chaos = tmp_path / "chaos"
        clean.mkdir()
        chaos.mkdir()
        drv, ctrl = _shedding_pipeline(str(clean))
        want = (clean / "egress.csv").read_bytes()
        clean_sheds = ctrl.snapshot()["shed"]
        assert want and ctrl.shed_total > 0, "vacuous: nothing shed"
        assert drv.stats["shed"] == ctrl.shed_total
        # Kill while the admission path is actively shedding.
        with pytest.raises(InjectedFault):
            _shedding_pipeline(str(chaos), fault_plan=[
                {"point": "overload.admit", "at": 40, "times": 10_000},
            ])
        drv2, ctrl2 = _shedding_pipeline(str(chaos))  # resume
        assert drv2.stats["resumed"] is True
        assert (chaos / "egress.csv").read_bytes() == want
        assert ctrl2.snapshot()["shed"] == clean_sheds

    def test_overload_state_rides_the_checkpoint(self, tmp_path):
        drv, ctrl = _shedding_pipeline(str(tmp_path))
        from spatialflink_tpu.checkpoint import load_checkpoint

        ck = load_checkpoint(str(tmp_path / "ckpt.bin"))
        assert ck["overload"]["shed"] == ctrl.snapshot()["shed"]

    def test_driver_restores_a_preinstalled_controller(self):
        """A controller installed BEFORE the run (bench's
        SFT_OVERLOAD_POLICY global) is restored when the driver's loop
        ends — the ledger seal must read the global slot, not a stale
        driver-owned controller (r9 code review)."""
        global_ctrl = overload.install(OverloadController(OverloadPolicy()))
        drv_ctrl = OverloadController(OverloadPolicy())
        _run_range(driver=WindowedDataflowDriver(overload=drv_ctrl))
        assert overload.controller() is global_ctrl

    def test_run_windows_installs_the_controller_too(self):
        """Count-window runs (run_windows — no event stream) must
        install the driver's controller like _drive does: a breaker
        counting degraded windows there otherwise stays invisible to
        the SLO budgets (silence-fails a configured
        degraded_window_budget) and the rung getters (r9 code review)."""
        drv_ctrl = OverloadController(OverloadPolicy())
        drv = WindowedDataflowDriver(overload=drv_ctrl)
        drv.op = object()
        drv.process = lambda w: w
        seen = []
        for _ in drv.run_windows(iter([1, 2])):
            seen.append(overload.controller())
        assert seen == [drv_ctrl, drv_ctrl]
        assert overload.controller() is drv_ctrl  # empty slot: stays

    def test_driver_controller_stays_installed_without_a_prior_one(self):
        """With an empty slot, the driver's controller stays installed
        after the run: uninstalling to None would turn the run's real
        shed counters into a silence-fails budget violation at the
        ledger-seal SLO verdict."""
        assert overload.controller() is None
        drv_ctrl = OverloadController(OverloadPolicy())
        _run_range(driver=WindowedDataflowDriver(overload=drv_ctrl))
        assert overload.controller() is drv_ctrl


# ---------------------------------------------------------------------------
# SLO budgets: live engine + post-hoc twin


class TestSloBudgets:
    def test_live_shed_budget_reads_the_controller(self):
        telemetry.enable()
        ctrl = overload.install(OverloadController(OverloadPolicy(
            max_buffered_events=1, admission_window_ms=10_000)))
        for t in range(5):
            ctrl.admit_item(_Ev(t), pausable=False)
        eng = slo.SloEngine(slo.SloSpec(shed_budget=2,
                                        degraded_window_budget=0))
        rows = {r["check"]: r for r in eng.evaluate()}
        assert rows["shed_budget"]["ok"] is False
        assert rows["shed_budget"]["value"] == 4
        assert rows["degraded_window_budget"]["ok"] is True

    def test_live_budget_fails_on_silence(self):
        """A spec naming shed_budget with NO controller installed must
        violate — the gate cannot pass on silence."""
        telemetry.enable()
        eng = slo.SloEngine(slo.SloSpec(shed_budget=1000))
        rows = {r["check"]: r for r in eng.evaluate()}
        assert rows["shed_budget"]["ok"] is False
        assert rows["shed_budget"]["value"] is None

    def test_posthoc_twin_reads_the_ledger_block(self, tmp_path):
        telemetry.enable()
        ctrl = overload.install(OverloadController(OverloadPolicy(
            max_buffered_events=1, admission_window_ms=10_000)))
        for t in range(4):
            ctrl.admit_item(_Ev(t), pausable=False)
        ctrl.count_degraded_window()
        ledger = tmp_path / "ledger.json"
        telemetry.write_ledger(str(ledger), capture_costs=False)
        doc = json.loads(ledger.read_text())
        assert doc["snapshot"]["overload"]["shed_total"] == 3

        from tools.sfprof import slo as sfslo

        rows = sfslo.evaluate(
            {"shed_budget": 2, "degraded_window_budget": 0}, doc)
        assert rows == [
            ("slo:shed_budget", 3.0, "<= 2", False),
            ("slo:degraded_window_budget", 1.0, "<= 0", False),
        ]
        rows = sfslo.evaluate(
            {"shed_budget": 10, "degraded_window_budget": 5}, doc)
        assert all(r[3] for r in rows)

    def test_posthoc_twin_fails_on_silence(self):
        from tools.sfprof import slo as sfslo

        rows = sfslo.evaluate({"shed_budget": 1000},
                              {"snapshot": {}, "bench": {}})
        assert rows == [("slo:shed_budget", None, "<= 1000", False)]

    def test_spec_twin_field_sets_stay_in_sync(self):
        import dataclasses

        from tools.sfprof import slo as sfslo

        live = {f.name for f in dataclasses.fields(slo.SloSpec)}
        assert {"shed_budget", "degraded_window_budget"} <= live
        assert live == set(sfslo.SPEC_KEYS)


# ---------------------------------------------------------------------------
# sfprof health visibility


class TestHealthCli:
    def test_health_prints_overload_notes(self, tmp_path, capsys):
        telemetry.enable()
        ctrl = overload.install(OverloadController(OverloadPolicy(
            max_buffered_events=1, admission_window_ms=10_000,
            breaker_failures=2)))
        for t in range(4):
            ctrl.admit_item(_Ev(t), pausable=False)
        ctrl.count_degraded_window()
        ledger = tmp_path / "ledger.json"
        telemetry.write_ledger(str(ledger), capture_costs=False)

        from tools.sfprof.cli import main as sfprof_main

        assert sfprof_main(["health", str(ledger)]) == 0
        out = capsys.readouterr().out
        assert "note overload sheds: total=3 (admission=3)" in out
        assert "note overload degradation: rung=0" in out
        assert "note overload circuit: state=closed" in out

    def test_health_prints_backpressure_only_runs(self, tmp_path, capsys):
        """A run that only engaged backpressure (no sheds, no rungs, no
        degraded windows) still surfaces its overload note — the
        engaged count is the signal the line exists to report (r9 code
        review)."""
        telemetry.enable()
        ctrl = overload.install(OverloadController(OverloadPolicy(
            max_buffered_events=1, admission_window_ms=10_000)))
        for t in range(4):
            ctrl.admit_item(_Ev(t), pausable=True)  # pause, don't shed
        assert ctrl.shed_total == 0
        assert ctrl.backpressure_engaged > 0
        ledger = tmp_path / "ledger.json"
        telemetry.write_ledger(str(ledger), capture_costs=False)

        from tools.sfprof.cli import main as sfprof_main

        assert sfprof_main(["health", str(ledger)]) == 0
        out = capsys.readouterr().out
        assert (f"backpressure engaged "
                f"{int(ctrl.backpressure_engaged)}x") in out


# ---------------------------------------------------------------------------
# run_wire_panes batch_slides rung: batched fetches, identical results


def _wire_pane_setup():
    from spatialflink_tpu.grid import UniformGrid
    from spatialflink_tpu.models.objects import Point
    from spatialflink_tpu.operators import (
        QueryConfiguration,
        QueryType,
    )
    from spatialflink_tpu.operators.knn_query import PointPointKNNQuery
    from spatialflink_tpu.streams.wire import WireFormat

    grid = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
    wf = WireFormat.for_grid(grid)
    rng = np.random.default_rng(5)
    panes = []
    for _ in range(9):
        n = int(rng.integers(5, 40))
        xy = np.stack([rng.uniform(0, 10, n),
                       rng.uniform(0, 10, n)], axis=1)
        q = wf.quantize(xy)
        oid = rng.integers(0, 9, n).astype(np.int16)
        panes.append(np.ascontiguousarray(np.concatenate(
            [q, oid.view(np.uint16)[:, None]], axis=1).T))
    conf = QueryConfiguration(QueryType.WindowBased, window_size=3.0,
                              slide_step=1.0)
    qp = Point(obj_id="q", x=5.0, y=5.0)

    def make_op():
        return PointPointKNNQuery(conf, grid)

    def collect(gen):
        return [
            (s, e, list(map(int, segs)), [float(d) for d in dists], nv)
            for s, e, segs, dists, nv in gen
        ]

    return make_op, collect, panes, qp, wf


def _batching_controller():
    ctrl = overload.install(OverloadController(OverloadPolicy(
        ladder=({"action": "batch_slides", "n": 3},),
        degrade_cooldown=1)))
    ctrl.on_slo_evaluation(False)
    assert overload.batch_slides() == 3
    return ctrl


class TestBatchSlides:
    def test_wire_pane_results_identical_under_batching(self):
        make_op, collect, panes, qp, wf = _wire_pane_setup()

        def run():
            return collect(make_op().run_wire_panes(
                panes, qp, 2.0, 5, 16, wf))

        base = run()
        _batching_controller()
        assert run() == base

    def test_mid_batch_checkpoint_never_loses_pending_windows(
            self, tmp_path):
        """A checkpoint taken at a yield while a batch_slides batch is
        open pairs with the last YIELDED window, not the last consumed
        pane: the pending (batched-but-unyielded) windows recompute on
        resume from the carry — never silently lost (r9 code review)."""
        from spatialflink_tpu.checkpoint import (
            load_checkpoint,
            operator_state,
            restore_operator,
            save_checkpoint,
        )

        make_op, collect, panes, qp, wf = _wire_pane_setup()
        base = collect(make_op().run_wire_panes(panes, qp, 2.0, 5, 16, wf))

        _batching_controller()
        op1 = make_op()
        gen = op1.run_wire_panes(panes, qp, 2.0, 5, 16, wf)
        head = []
        for tup in gen:
            head.append(tup)
            if len(head) == 2:  # suspended mid-flush — the batch is open
                break
        gen.close()
        state = operator_state(op1)
        cut = int(state["knn_wire_pane_carry"]["next_pane"])
        # Three panes were consumed (the width-3 batch filled at pane
        # 2) but only panes 0-1's windows were yielded — the carry must
        # lag at 2, not jump to 3 past the pending window.
        assert cut == 2
        path = str(tmp_path / "wire.ckpt")
        save_checkpoint(path, op=state)

        op2 = make_op()
        restore_operator(op2, load_checkpoint(path)["op"])
        rest = collect(op2.run_wire_panes(panes[cut:], qp, 2.0, 5, 16, wf))
        assert collect(iter(head)) + rest == base


# ---------------------------------------------------------------------------
# The per-commit smoke


def test_overload_smoke_round_trip():
    """The tools/ci stage, in-process: burst → shed → degrade → recover
    → every transition sealed in the stream, exit 0."""
    assert overload.smoke() == 0
