"""Range/kNN/join kernel parity vs brute-force numpy re-derivations of the
reference's window-loop semantics (guaranteed emit, candidate distance check,
per-objID min-dist dedup, grid-hash join)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.batch import PointBatch
from spatialflink_tpu.ops.cells import gather_cell_flags
from spatialflink_tpu.ops.join import cross_join_kernel, join_kernel, sort_by_cell
from spatialflink_tpu.ops.knn import knn_kernel
from spatialflink_tpu.ops.polygon import pack_rings
from spatialflink_tpu.ops.range import (
    range_query_kernel,
    range_query_polygons_kernel,
)

GRID = dict(min_x=0.0, max_x=10.0, min_y=0.0, max_y=10.0)


def make_batch(rng, n=777, bucket=1024):
    xy = rng.uniform(0, 10, size=(n, 2))
    ts = rng.integers(0, 10_000, n)
    oid = rng.integers(0, 60, n).astype(np.int32)
    return PointBatch.from_arrays(xy, ts, oid, bucket=bucket)


def brute_range(grid, flags, batch, q, r):
    """Reference semantics: guaranteed → emit; candidate → min dist ≤ r."""
    keep = np.zeros(batch.capacity, bool)
    for i in range(batch.capacity):
        if not batch.valid[i]:
            continue
        c = int(batch.cell[i])
        f = int(flags[c])
        if f == 2:
            keep[i] = True
        elif f == 1:
            d = np.min(np.linalg.norm(q - batch.xy[i], axis=1))
            keep[i] = d <= r
    return keep


@pytest.mark.parametrize("radius", [0.3, 1.5, 4.0])
def test_range_kernel_matches_brute(rng, radius):
    grid = UniformGrid(20, **GRID)
    batch = make_batch(rng).with_cells(grid)
    q = np.array([[5.0, 5.0], [2.0, 8.0]])
    flags = grid.neighbor_flags(radius, [grid.flat_cell(*p) for p in q])
    pflags = np.asarray(gather_cell_flags(jnp.asarray(batch.cell), jnp.asarray(flags)))
    keep, dist = range_query_kernel(
        jnp.asarray(batch.xy), jnp.asarray(batch.valid), jnp.asarray(pflags),
        jnp.asarray(q), radius,
    )
    np.testing.assert_array_equal(np.asarray(keep), brute_range(grid, flags, batch, q, radius))


def test_range_approximate_emits_candidates_unchecked(rng):
    grid = UniformGrid(20, **GRID)
    batch = make_batch(rng).with_cells(grid)
    q = np.array([[5.0, 5.0]])
    r = 1.0
    flags = grid.neighbor_flags(r, [grid.flat_cell(5.0, 5.0)])
    pflags = np.asarray(gather_cell_flags(jnp.asarray(batch.cell), jnp.asarray(flags)))
    keep, _ = range_query_kernel(
        jnp.asarray(batch.xy), jnp.asarray(batch.valid), jnp.asarray(pflags),
        jnp.asarray(q), r, approximate=True,
    )
    expect = batch.valid & (pflags > 0)
    np.testing.assert_array_equal(np.asarray(keep), expect)


def test_range_polygon_query(rng):
    grid = UniformGrid(20, **GRID)
    batch = make_batch(rng).with_cells(grid)
    ring = np.array([[4.0, 4.0], [6.0, 4.0], [6.0, 6.0], [4.0, 6.0]])
    verts, ev = pack_rings([ring], pad_to=8)
    r = 0.5
    cells = grid.bbox_cells(4.0, 4.0, 6.0, 6.0)
    flags = grid.neighbor_flags(r, cells)
    pflags = np.asarray(gather_cell_flags(jnp.asarray(batch.cell), jnp.asarray(flags)))
    keep, dist = range_query_polygons_kernel(
        jnp.asarray(batch.xy), jnp.asarray(batch.valid), jnp.asarray(pflags),
        jnp.asarray(verts)[None], jnp.asarray(ev)[None], r,
    )
    keep = np.asarray(keep)
    # Brute force: inside or within r of boundary, for candidate cells;
    # guaranteed cells emitted regardless.
    for i in range(batch.capacity):
        if not batch.valid[i]:
            assert not keep[i]
            continue
        f = int(flags[int(batch.cell[i])])
        x, y = batch.xy[i]
        inside = 4 <= x <= 6 and 4 <= y <= 6
        edge_d = min(
            max(4 - x, 0, x - 6) if 4 <= y <= 6 else np.inf,
            max(4 - y, 0, y - 6) if 4 <= x <= 6 else np.inf,
            min(np.hypot(x - cx, y - cy) for cx in (4, 6) for cy in (4, 6)),
        )
        d = 0.0 if inside else edge_d
        expect = f == 2 or (f == 1 and d <= r)
        assert keep[i] == expect, (i, f, x, y, d)


def brute_knn(batch, flags_per_point, q, r, k):
    best = {}
    for i in range(batch.capacity):
        if not batch.valid[i] or flags_per_point[i] == 0:
            continue
        d = np.linalg.norm(batch.xy[i] - q)
        if d <= r:
            o = int(batch.oid[i])
            if o not in best or d < best[o]:
                best[o] = d
    return sorted(best.items(), key=lambda kv: kv[1])[:k]


@pytest.mark.parametrize("k", [1, 5, 50])
def test_knn_kernel_matches_brute(rng, k):
    grid = UniformGrid(20, **GRID)
    batch = make_batch(rng).with_cells(grid)
    q = np.array([5.0, 5.0])
    r = 3.0
    flags = grid.neighbor_flags(r, [grid.flat_cell(*q)])
    pflags = np.asarray(gather_cell_flags(jnp.asarray(batch.cell), jnp.asarray(flags)))
    res = knn_kernel(
        jnp.asarray(batch.xy), jnp.asarray(batch.valid), jnp.asarray(pflags),
        jnp.asarray(batch.oid), jnp.asarray(q), r, k, num_segments=64,
    )
    expect = brute_knn(batch, pflags, q, r, k)
    nv = int(res.num_valid)
    assert nv == len(expect)
    got = [(int(res.segment[i]), float(res.dist[i])) for i in range(nv)]
    for (go, gd), (eo, ed) in zip(got, expect):
        assert gd == pytest.approx(ed, rel=1e-12)
        assert go == eo
    # Padding slots marked -1
    assert all(int(res.segment[i]) == -1 for i in range(nv, k))
    # Representative index points at a point of that object achieving min dist
    for i in range(nv):
        idx, seg = int(res.index[i]), int(res.segment[i])
        assert int(batch.oid[idx]) == seg
        assert np.linalg.norm(batch.xy[idx] - q) == pytest.approx(res.dist[i], rel=1e-12)


def test_knn_empty_result(rng):
    grid = UniformGrid(20, **GRID)
    batch = make_batch(rng, n=10).with_cells(grid)
    q = np.array([500.0, 500.0])  # far outside; no cells flagged
    flags = grid.neighbor_flags(0.5, [grid.flat_cell(*q)])
    pflags = np.asarray(gather_cell_flags(jnp.asarray(batch.cell), jnp.asarray(flags)))
    res = knn_kernel(
        jnp.asarray(batch.xy), jnp.asarray(batch.valid), jnp.asarray(pflags),
        jnp.asarray(batch.oid), jnp.asarray(q), 0.5, 5, num_segments=64,
    )
    assert int(res.num_valid) == 0
    assert all(int(s) == -1 for s in np.asarray(res.segment))


def brute_join(a, b, r):
    pairs = set()
    for i in range(len(a.xy)):
        if not a.valid[i]:
            continue
        for j in range(len(b.xy)):
            if not b.valid[j]:
                continue
            if np.linalg.norm(a.xy[i] - b.xy[j]) <= r:
                pairs.add((i, j))
    return pairs


def test_grid_hash_join_matches_brute(rng):
    grid = UniformGrid(20, **GRID)
    r = 0.8
    a = make_batch(rng, n=300, bucket=512).with_cells(grid)
    b = make_batch(rng, n=200, bucket=256).with_cells(grid)
    cells_sorted, order = sort_by_cell(jnp.asarray(b.cell), grid.num_cells)
    bxy_sorted = jnp.asarray(b.xy)[order]
    bvalid_sorted = jnp.asarray(b.valid)[order]
    # Left cell (xi, yi) indices
    xi = np.floor((a.xy[:, 0] - grid.min_x) / grid.cell_length).astype(np.int32)
    yi = np.floor((a.xy[:, 1] - grid.min_y) / grid.cell_length).astype(np.int32)
    res = join_kernel(
        jnp.asarray(a.xy), jnp.asarray(a.valid), jnp.asarray(np.stack([xi, yi], 1)),
        bxy_sorted, bvalid_sorted, cells_sorted, order,
        jnp.asarray(grid.neighbor_offsets(r)), grid.n, r, cap=32,
    )
    assert int(res.overflow) == 0
    got = set()
    pm = np.asarray(res.pair_mask)
    ri = np.asarray(res.right_index)
    for i in range(a.capacity):
        for slot in np.nonzero(pm[i])[0]:
            got.add((i, int(ri[i, slot])))
    assert got == brute_join(a, b, r)


def test_join_overflow_counted(rng):
    grid = UniformGrid(20, **GRID)
    r = 0.5
    # 100 points in the same tiny spot → one cell with >cap points
    xy = np.full((100, 2), 5.05) + rng.normal(0, 0.001, (100, 2))
    b = PointBatch.from_arrays(xy, bucket=128).with_cells(grid)
    a = PointBatch.from_arrays(np.array([[5.05, 5.05]]), bucket=256).with_cells(grid)
    cells_sorted, order = sort_by_cell(jnp.asarray(b.cell), grid.num_cells)
    xi = np.floor((a.xy[:, 0] - grid.min_x) / grid.cell_length).astype(np.int32)
    yi = np.floor((a.xy[:, 1] - grid.min_y) / grid.cell_length).astype(np.int32)
    res = join_kernel(
        jnp.asarray(a.xy), jnp.asarray(a.valid), jnp.asarray(np.stack([xi, yi], 1)),
        jnp.asarray(b.xy)[order], jnp.asarray(b.valid)[order], cells_sorted, order,
        jnp.asarray(grid.neighbor_offsets(r)), grid.n, r, cap=16,
    )
    assert int(res.overflow) > 0


def test_join_overflow_ignores_padding_lanes(rng):
    """Padding (invalid) left lanes map to cell (0,0) — a real grid cell —
    and must not claim overflow (ADVICE round-1 finding: the overflow==0
    exactness contract has to be tight)."""
    grid = UniformGrid(20, **GRID)
    r = 0.5
    # Crowd the grid-origin cell on the right side beyond cap.
    bxy = np.full((80, 2), 0.05) + rng.normal(0, 0.001, (80, 2))
    b = PointBatch.from_arrays(bxy, bucket=128).with_cells(grid)
    # One real left point far away; batch padded to 256 lanes whose cell
    # indices are (0, 0) → the origin cell's crowd is in their span.
    a = PointBatch.from_arrays(np.array([[9.0, 9.0]]), bucket=256).with_cells(grid)
    cells_sorted, order = sort_by_cell(jnp.asarray(b.cell), grid.num_cells)
    xi = np.floor((a.xy[:, 0] - grid.min_x) / grid.cell_length).astype(np.int32)
    yi = np.floor((a.xy[:, 1] - grid.min_y) / grid.cell_length).astype(np.int32)
    res = join_kernel(
        jnp.asarray(a.xy), jnp.asarray(a.valid), jnp.asarray(np.stack([xi, yi], 1)),
        jnp.asarray(b.xy)[order], jnp.asarray(b.valid)[order], cells_sorted, order,
        jnp.asarray(grid.neighbor_offsets(r)), grid.n, r, cap=16,
    )
    assert int(res.overflow) == 0


def test_cross_join_matches_brute(rng):
    r = 1.2
    a = make_batch(rng, n=50, bucket=64)
    b = make_batch(rng, n=40, bucket=64)
    res = cross_join_kernel(
        jnp.asarray(a.xy), jnp.asarray(a.valid), jnp.asarray(b.xy), jnp.asarray(b.valid), r
    )
    got = set()
    pm = np.asarray(res.pair_mask)
    for i in range(a.capacity):
        for j in np.nonzero(pm[i])[0]:
            got.add((i, int(j)))
    assert got == brute_join(a, b, r)


def test_any_cell_flagged_matches_per_object_loop(rng):
    """Vectorized prefix-sum rectangle test == per-object cell loop."""
    from spatialflink_tpu.models.batch import GeometryBatch
    from spatialflink_tpu.models.objects import Polygon

    grid = UniformGrid(20, **GRID)
    polys = []
    for i in range(60):
        cx, cy = rng.uniform(-1, 11), rng.uniform(-1, 11)  # some out of grid
        w, h = rng.uniform(0.1, 2.5), rng.uniform(0.1, 2.5)
        polys.append(Polygon(
            obj_id=f"p{i}", timestamp=i,
            rings=[np.array([[cx, cy], [cx + w, cy], [cx + w, cy + h],
                             [cx, cy + h], [cx, cy]])],
        ))
    gb = GeometryBatch.from_objects(polys)
    flags = grid.neighbor_flags(1.2, [grid.flat_cell(5.0, 5.0)])
    got = gb.any_cell_flagged(grid, flags)
    # Brute force: per object, max flag over bbox-overlapped cells.
    for i in range(gb.capacity):
        if not gb.valid[i]:
            assert got[i] == 0
            continue
        cells = grid.bbox_cells(*gb.bbox[i])
        expect = flags[cells].max() if len(cells) else 0
        assert got[i] == expect, (i, gb.bbox[i])


def test_polygon_kernel_chunked_matches_unchunked(rng):
    """Large polygon sets via lax.map chunks == plain vmap path."""
    from spatialflink_tpu.ops.range import range_query_polygons_kernel
    from spatialflink_tpu.operators.base import pack_query_geometries
    from spatialflink_tpu.utils.helper import generate_query_polygons

    grid = UniformGrid(20, **GRID)
    batch = make_batch(rng, n=400, bucket=512).with_cells(grid)
    polys = generate_query_polygons(70, 0, 0, 10, 10, seed=5)  # > chunk of 32
    verts, ev = pack_query_geometries(polys)
    cells = [c for p in polys for c in p.grid_cells(grid)]
    flags = grid.neighbor_flags(0.3, cells)
    pflags = np.asarray(gather_cell_flags(jnp.asarray(batch.cell), jnp.asarray(flags)))
    args = (jnp.asarray(batch.xy), jnp.asarray(batch.valid), jnp.asarray(pflags),
            jnp.asarray(verts), jnp.asarray(ev), 0.3)
    keep_c, dist_c = range_query_polygons_kernel(*args, poly_chunk=32)
    keep_u, dist_u = range_query_polygons_kernel(*args, poly_chunk=128)
    np.testing.assert_array_equal(np.asarray(keep_c), np.asarray(keep_u))
    np.testing.assert_allclose(np.asarray(dist_c), np.asarray(dist_u), rtol=1e-12)


def test_bucketed_join_matches_brute(rng):
    """Dense-bucket (roll-shift) join == brute force, exact when no overflow."""
    from spatialflink_tpu.ops.join import join_window_bucketed

    grid = UniformGrid(20, **GRID)
    r = 0.8
    a = make_batch(rng, n=300, bucket=512).with_cells(grid)
    b = make_batch(rng, n=200, bucket=256).with_cells(grid)
    layers = grid.candidate_layers(r)
    res = join_window_bucketed(
        jnp.asarray(a.xy), jnp.asarray(a.valid), jnp.asarray(a.cell),
        jnp.asarray(b.xy), jnp.asarray(b.valid), jnp.asarray(b.cell),
        grid_n=grid.n, layers=layers, radius=r,
        cap_left=16, cap_right=16, max_pairs=65536,
    )
    assert int(res.overflow) == 0
    count = int(res.count)
    assert count <= 65536
    li = np.asarray(res.left_index)
    ri = np.asarray(res.right_index)
    got = {(int(x), int(y)) for x, y in zip(li, ri) if x >= 0}
    assert len(got) == count
    assert got == brute_join(a, b, r)


def test_bucketed_join_overflow_and_truncation(rng):
    from spatialflink_tpu.ops.join import join_window_bucketed

    grid = UniformGrid(20, **GRID)
    # 60 points in one cell with cap 16 → overflow reported.
    xy = np.full((60, 2), 5.05) + rng.normal(0, 0.001, (60, 2))
    b = PointBatch.from_arrays(xy, bucket=64).with_cells(grid)
    a = PointBatch.from_arrays(np.array([[5.05, 5.05]]), bucket=256).with_cells(grid)
    res = join_window_bucketed(
        jnp.asarray(a.xy), jnp.asarray(a.valid), jnp.asarray(a.cell),
        jnp.asarray(b.xy), jnp.asarray(b.valid), jnp.asarray(b.cell),
        grid_n=grid.n, layers=1, radius=0.5,
        cap_left=4, cap_right=16, max_pairs=4096,
    )
    assert int(res.overflow) > 0
    # Truncation signalling: tiny max_pairs → count > max_pairs sentinel.
    a2 = make_batch(rng, n=200, bucket=256).with_cells(grid)
    b2 = make_batch(rng, n=200, bucket=256).with_cells(grid)
    res2 = join_window_bucketed(
        jnp.asarray(a2.xy), jnp.asarray(a2.valid), jnp.asarray(a2.cell),
        jnp.asarray(b2.xy), jnp.asarray(b2.valid), jnp.asarray(b2.cell),
        grid_n=grid.n, layers=grid.candidate_layers(2.0), radius=2.0,
        cap_left=16, cap_right=16, max_pairs=50,
    )
    assert int(res2.count) > 50


@pytest.mark.slow
def test_join_out_of_grid_points_never_match(rng):
    """Reference semantics: points outside the grid bbox carry keys no
    neighbor set contains, so they never join — in every join variant."""
    from spatialflink_tpu.operators import (
        PointPointJoinQuery, QueryConfiguration, QueryType,
    )
    from spatialflink_tpu.models.objects import Point

    grid = UniformGrid(20, **GRID)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=30, slide_step=30)
    left = [Point(obj_id="out", timestamp=100, x=-0.05, y=5.0),
            Point(obj_id="in", timestamp=200, x=0.2, y=5.0)]
    right = [Point(obj_id="r", timestamp=150, x=0.05, y=5.0)]
    for cap in (32, 256):  # bucketed path and gather path
        res = list(PointPointJoinQuery(conf, grid, cap=cap).run(
            iter(list(left)), iter(list(right)), 0.2))
        got = {(a.obj_id, b.obj_id) for r in res for a, b, _ in r.pairs}
        assert got == {("in", "r")}, (cap, got)


def test_pruned_polygon_range_matches_dense(rng):
    """range_query_polygons_pruned_kernel must keep exactly the dense
    kernel's lanes (and equal min_dist on kept lanes) when overflow == 0."""
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.operators.base import pack_query_geometries
    from spatialflink_tpu.ops.range import (
        range_query_polygons_kernel,
        range_query_polygons_pruned_kernel,
    )
    from spatialflink_tpu.utils.helper import generate_query_polygons

    polys = generate_query_polygons(60, 0.0, 0.0, 10.0, 10.0, grid_size=20,
                                    seed=5)
    verts, ev = pack_query_geometries(polys, np.float64)
    n = 3000
    xy = rng.uniform(0, 10, (n, 2))
    valid = np.ones(n, bool)
    flags = np.ones(n, np.uint8)  # all candidate lanes: distances decide
    r = 0.4

    keep_d, dist_d = jax.jit(range_query_polygons_kernel,
                             static_argnames="approximate")(
        jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(flags),
        jnp.asarray(verts), jnp.asarray(ev), r)
    keep_p, dist_p, over = jax.jit(
        range_query_polygons_pruned_kernel,
        static_argnames=("cand", "point_chunk", "approximate"))(
        jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(flags),
        jnp.asarray(verts), jnp.asarray(ev), r,
        cand=8, point_chunk=512)
    assert int(over) == 0
    np.testing.assert_array_equal(np.asarray(keep_p), np.asarray(keep_d))
    kept = np.asarray(keep_d)
    np.testing.assert_allclose(np.asarray(dist_p)[kept],
                               np.asarray(dist_d)[kept], rtol=0, atol=0)


def test_pruned_polygon_range_overflow_detects_undercount(rng):
    """With cand smaller than the number of in-radius polygon bboxes at
    some point, overflow must be nonzero (the retry signal)."""
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.operators.base import pack_query_geometries
    from spatialflink_tpu.models.objects import Polygon
    from spatialflink_tpu.ops.range import range_query_polygons_pruned_kernel

    # 6 concentric small squares around (5,5): any nearby point has 6
    # bbox-candidates within r.
    polys = []
    for i in range(6):
        s = 0.1 + 0.05 * i
        polys.append(Polygon(rings=[np.array(
            [[5 - s, 5 - s], [5 + s, 5 - s], [5 + s, 5 + s], [5 - s, 5 + s],
             [5 - s, 5 - s]])]))
    verts, ev = pack_query_geometries(polys, np.float64)
    xy = np.array([[5.05, 5.0], [9.0, 9.0]])
    keep, dist, over = jax.jit(
        range_query_polygons_pruned_kernel,
        static_argnames=("cand", "point_chunk", "approximate"))(
        jnp.asarray(xy), jnp.asarray(np.ones(2, bool)),
        jnp.asarray(np.ones(2, np.uint8)), jnp.asarray(verts),
        jnp.asarray(ev), 1.0, cand=4, point_chunk=2)
    assert int(over) > 0


def test_pruned_compact_polygon_range_matches_dense(rng):
    """The candidate-compacted pruned kernel must keep exactly the dense
    kernel's lanes (equal dists on kept lanes) when both overflows are 0,
    with realistic mostly-non-candidate flags."""
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.operators.base import pack_query_geometries
    from spatialflink_tpu.ops.range import (
        range_query_polygons_kernel,
        range_query_polygons_pruned_compact_kernel,
    )
    from spatialflink_tpu.utils.helper import generate_query_polygons

    polys = generate_query_polygons(50, 0.0, 0.0, 10.0, 10.0, grid_size=20,
                                    seed=6)
    verts, ev = pack_query_geometries(polys, np.float64)
    n = 4000
    xy = rng.uniform(0, 10, (n, 2))
    valid = np.ones(n, bool)
    # ~10% candidate lanes, rest pruned by flags.
    flags = np.where(rng.uniform(size=n) < 0.1, 1, 0).astype(np.uint8)
    r = 0.35

    keep_d, dist_d = jax.jit(range_query_polygons_kernel,
                             static_argnames="approximate")(
        jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(flags),
        jnp.asarray(verts), jnp.asarray(ev), r)
    keep_c, dist_c, cand_over, budget_over = jax.jit(
        range_query_polygons_pruned_compact_kernel,
        static_argnames=("budget", "cand", "point_chunk"))(
        jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(flags),
        jnp.asarray(verts), jnp.asarray(ev), r,
        budget=1024, cand=8, point_chunk=256)
    assert int(cand_over) == 0 and int(budget_over) == 0
    np.testing.assert_array_equal(np.asarray(keep_c), np.asarray(keep_d))
    kept = np.asarray(keep_d)
    np.testing.assert_allclose(np.asarray(dist_c)[kept],
                               np.asarray(dist_d)[kept], rtol=0, atol=0)


def test_pruned_compact_budget_overflow(rng):
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.operators.base import pack_query_geometries
    from spatialflink_tpu.ops.range import (
        range_query_polygons_pruned_compact_kernel,
    )
    from spatialflink_tpu.utils.helper import generate_query_polygons

    polys = generate_query_polygons(10, 0.0, 0.0, 10.0, 10.0, grid_size=20,
                                    seed=8)
    verts, ev = pack_query_geometries(polys, np.float64)
    n = 512
    xy = rng.uniform(0, 10, (n, 2))
    flags = np.ones(n, np.uint8)  # every lane is a candidate
    _, _, _, budget_over = jax.jit(
        range_query_polygons_pruned_compact_kernel,
        static_argnames=("budget", "cand", "point_chunk"))(
        jnp.asarray(xy), jnp.asarray(np.ones(n, bool)), jnp.asarray(flags),
        jnp.asarray(verts), jnp.asarray(ev), 0.3,
        budget=128, cand=8, point_chunk=128)
    assert int(budget_over) == n - 128
