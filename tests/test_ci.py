"""Tier-1 tools/ci: the pre-commit gate's stage plan, fail-fast
behavior, and environment hygiene (no axon dial, toy last-good). The
stages themselves (sfcheck / pytest / bench+sfprof) have their own
suites — here we pin the orchestration only."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import ci  # noqa: E402


def test_dry_run_lists_all_stages(capsys):
    assert ci.main(["--dry-run"]) == 0
    out = capsys.readouterr().out
    assert "[sfcheck]" in out
    assert "[pytest-quick]" in out
    assert "[bench-smoke+health]" in out
    assert "[chaos-smoke]" in out
    plain = out.replace(sys.executable, "py")
    assert "tools.sfprof health" in plain
    # The trajectory gate: the smoke capture vs the committed toy trend
    # fixture, in the must-have-history CI mode.
    assert "tools.sfprof trend" in plain
    assert os.path.join("tests", "fixtures", "trend") in plain
    assert "--require-history" in plain
    # The crash-recovery round trip: recover the stream the smoke run
    # wrote, then health-gate the recovered ledger.
    assert "tools.sfprof recover" in plain
    assert plain.count("tools.sfprof health") == 2
    # The kill/resume chaos round trip rides every commit too.
    assert "spatialflink_tpu.driver --chaos-smoke" in plain
    # And the burst/shed/degrade/recover overload round trip.
    assert "[overload-smoke]" in out
    assert "spatialflink_tpu.overload --smoke" in plain
    # And the composed-DAG kill-between-sink-commits round trip.
    assert "[dag-smoke]" in out
    assert "spatialflink_tpu.dag --smoke" in plain


def test_skip_flags_trim_stages(capsys):
    assert ci.main(["--dry-run", "--skip-tests", "--skip-bench"]) == 0
    out = capsys.readouterr().out
    assert "[sfcheck]" in out
    assert "pytest" not in out and "bench" not in out
    # --skip-bench does NOT drop the chaos/overload/dag smokes
    # (CPU-only, independent of the bench stage); only their own flags
    # do.
    assert "[chaos-smoke]" in out
    assert "[overload-smoke]" in out
    assert "[dag-smoke]" in out
    assert ci.main(["--dry-run", "--skip-tests", "--skip-bench",
                    "--skip-chaos", "--skip-overload",
                    "--skip-dag"]) == 0
    out = capsys.readouterr().out
    assert "chaos" not in out and "overload" not in out
    assert "dag" not in out


def test_changed_flag_passes_through(capsys):
    assert ci.main(["--dry-run", "--changed"]) == 0
    assert "--changed" in capsys.readouterr().out


def test_github_actions_switches_sfcheck_format(monkeypatch):
    """Under Actions the sfcheck stage emits ::error annotations; locally
    it stays human. Exit codes are format-invariant, so the gate verdict
    is identical either way."""
    def sfcheck_argv():
        (cmds,) = [c for name, c in ci.stages(
            False, True, True, skip_chaos=True, skip_overload=True,
            skip_dag=True) if name == "sfcheck"]
        return cmds[0]

    monkeypatch.delenv("GITHUB_ACTIONS", raising=False)
    assert "--format=github" not in sfcheck_argv()
    monkeypatch.setenv("GITHUB_ACTIONS", "true")
    assert "--format=github" in sfcheck_argv()


def test_fail_fast_propagates_stage_exit(monkeypatch):
    calls = []

    class P:
        def __init__(self, rc):
            self.returncode = rc

    def fake_run(cmd, cwd=None, env=None):
        calls.append(cmd)
        return P(7 if "pytest" in " ".join(cmd) else 0)

    monkeypatch.setattr(ci.subprocess, "run", fake_run)
    assert ci.main([]) == 7
    joined = [" ".join(c) for c in calls]
    assert any("tools.sfcheck" in c for c in joined)
    assert any("pytest" in c for c in joined)
    # fail-fast: the bench stage never ran
    assert not any("bench.py" in c for c in joined)


def test_all_green_runs_every_stage(monkeypatch):
    calls = []
    envs = []

    class P:
        returncode = 0

    def fake_run(cmd, cwd=None, env=None):
        calls.append(" ".join(cmd))
        envs.append(env)
        return P()

    # Seed EVERY hazard-class-`armed` registry var ambient: the scrub
    # is derived from spatialflink_tpu/envvars.py, so all of them —
    # not just the historical FAULT_PLAN/OVERLOAD_POLICY pair — must
    # vanish from every stage env.
    armed = ci._envvars_registry().gate_scrub_vars()
    assert "SFT_FAULT_PLAN" in armed and "SFT_SLO_SPEC" in armed
    for var in armed:
        monkeypatch.setenv(var, "ambient-sabotage")
    monkeypatch.setattr(ci.subprocess, "run", fake_run)
    assert ci.main([]) == 0
    assert any("bench.py" in c for c in calls)
    assert any("tools.sfprof health" in c for c in calls)
    assert any("tools.sfprof recover" in c for c in calls)
    # The trend gate runs on the SAME ledger the smoke run wrote.
    trend_call = next(c for c in calls if "tools.sfprof trend" in c)
    assert "--gate" in trend_call and "--require-history" in trend_call
    assert any("spatialflink_tpu.driver --chaos-smoke" in c for c in calls)
    # recover targets the stream the bench env configured, and the
    # recovered ledger is health-gated too (2 health invocations).
    assert sum("tools.sfprof health" in c for c in calls) == 2
    # every stage env disarms the axon dial AND any ambient fault plan
    # (an armed abort plan would kill healthy stages like a real kill -9)
    assert all(e["PALLAS_AXON_POOL_IPS"] == "" for e in envs)
    assert all("SFT_FAULT_PLAN" not in e for e in envs)
    # the derived scrub: no armed var survives into ANY stage
    assert all(v not in e for e in envs for v in armed)
    bench_env = envs[[i for i, c in enumerate(calls)
                      if "bench.py" in c][0]]
    assert bench_env["SFT_BENCH_SMOKE"] == "1"
    # toy numbers must never enter the real last-good store
    assert "ci_last_good" in bench_env["SFT_BENCH_LAST_GOOD"]
    assert bench_env["SFT_LEDGER_PATH"]
    assert bench_env["SFT_LEDGER_STREAM"]
    recover_call = next(c for c in calls if "tools.sfprof recover" in c)
    assert bench_env["SFT_LEDGER_STREAM"] in recover_call
