"""Pallas kernel parity (interpreter mode on the CPU test platform)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spatialflink_tpu.ops.distances import point_polyline_distance
from spatialflink_tpu.ops.pallas_kernels import (
    pallas_available,
    point_polyline_min_dist_pallas,
)
from spatialflink_tpu.ops.polygon import pack_rings

pytestmark = pytest.mark.skipif(not pallas_available(), reason="no pallas")


def test_pallas_min_dist_matches_xla(rng):
    ring = rng.uniform(0, 10, (37, 2))
    verts, ev = pack_rings([ring], pad_to=64)
    pts = rng.uniform(-2, 12, (3000, 2)).astype(np.float32)
    ref = np.asarray(
        point_polyline_distance(
            jnp.asarray(pts), jnp.asarray(verts.astype(np.float32)), jnp.asarray(ev)
        )
    )
    got = np.asarray(
        point_polyline_min_dist_pallas(
            jnp.asarray(pts), jnp.asarray(verts), jnp.asarray(ev), interpret=True
        )
    )
    np.testing.assert_allclose(got, ref, atol=2e-6)


def test_pallas_min_dist_multi_ring_seams(rng):
    rings = [rng.uniform(0, 5, (9, 2)), rng.uniform(5, 10, (7, 2))]
    verts, ev = pack_rings(rings, pad_to=32)
    pts = rng.uniform(0, 10, (500, 2)).astype(np.float32)
    ref = np.asarray(
        point_polyline_distance(
            jnp.asarray(pts), jnp.asarray(verts.astype(np.float32)), jnp.asarray(ev)
        )
    )
    got = np.asarray(
        point_polyline_min_dist_pallas(
            jnp.asarray(pts), jnp.asarray(verts), jnp.asarray(ev), interpret=True
        )
    )
    np.testing.assert_allclose(got, ref, atol=2e-6)
