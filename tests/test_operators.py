"""End-to-end operator tests: source → windows → TPU kernel → results.

This is the reference's StreamingJob case-1 slice (SURVEY.md §7 "minimum
end-to-end slice") plus kNN and join pipelines, checked against brute-force
window recomputation.
"""

import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import LineString, Point, Polygon
from spatialflink_tpu.operators import (
    PointPointJoinQuery,
    PointPointKNNQuery,
    PointPointRangeQuery,
    PointPolygonRangeQuery,
    PolygonPointRangeQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.streams.sources import SyntheticGpsSource

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)


def synth_points(rng, n=400, t_span=30_000):
    pts = []
    for i in range(n):
        pts.append(
            Point(
                obj_id=f"dev{i % 7}",
                timestamp=int(i * t_span / n),
                x=float(rng.uniform(0, 10)),
                y=float(rng.uniform(0, 10)),
            )
        )
    return pts


def windows_brute(points, size, slide, t_max):
    out = {}
    start = 0
    while start < t_max:
        out[(start, start + size)] = [
            p for p in points if start <= p.timestamp < start + size
        ]
        start += slide
    return out


def test_range_query_end_to_end(rng):
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    pts = synth_points(rng)
    q = Point(x=5.0, y=5.0)
    r = 2.0
    results = list(PointPointRangeQuery(conf, GRID).run(iter(pts), [q], r))
    assert results
    seen_spans = set()
    for res in results:
        seen_spans.add((res.start, res.end))
        expect = {
            id(p)
            for p in pts
            if res.start <= p.timestamp < res.end
            and np.hypot(p.x - 5.0, p.y - 5.0) <= r
        }
        got = {id(p) for p in res.objects}
        assert got == expect, (res.start, res.end)
    # Sliding 10s/5s over 30s of data: spans at 0,5,...
    assert (0, 10_000) in seen_spans and (5_000, 15_000) in seen_spans


def test_range_query_realtime_microbatches(rng):
    conf = QueryConfiguration(QueryType.RealTime, realtime_batch_ms=1_000)
    pts = synth_points(rng, n=100, t_span=5_000)
    q = Point(x=5.0, y=5.0)
    results = list(PointPointRangeQuery(conf, GRID).run(iter(pts), [q], 3.0))
    # ~5 micro-batches of 1s each
    assert 4 <= len(results) <= 6
    for res in results:
        assert res.end - res.start == 1_000


def test_point_polygon_range(rng):
    conf = QueryConfiguration(QueryType.WindowBased, window_size=30, slide_step=30)
    pts = synth_points(rng)
    poly = Polygon(rings=[np.array([[4, 4], [6, 4], [6, 6], [4, 6], [4, 4]], float)])
    results = list(PointPolygonRangeQuery(conf, GRID).run(iter(pts), [poly], 0.5))
    total = sum(len(r.objects) for r in results)
    # brute force over all points (single 30s window covers everything)
    def d(p):
        if 4 <= p.x <= 6 and 4 <= p.y <= 6:
            return 0.0
        dx = max(4 - p.x, 0, p.x - 6)
        dy = max(4 - p.y, 0, p.y - 6)
        return np.hypot(dx, dy)

    expect = sum(1 for p in pts if d(p) <= 0.5)
    assert total == expect


def test_polygon_stream_point_query(rng):
    conf = QueryConfiguration(QueryType.WindowBased, window_size=30, slide_step=30)
    polys = []
    for i in range(40):
        cx, cy = rng.uniform(1, 9), rng.uniform(1, 9)
        polys.append(
            Polygon(
                obj_id=f"poly{i}",
                timestamp=i * 100,
                rings=[np.array([[cx - .3, cy - .3], [cx + .3, cy - .3],
                                 [cx + .3, cy + .3], [cx - .3, cy + .3],
                                 [cx - .3, cy - .3]])],
            )
        )
    q = Point(x=5.0, y=5.0)
    results = list(PolygonPointRangeQuery(conf, GRID).run(iter(polys), [q], 1.0))
    got = {p.obj_id for r in results for p in r.objects}
    expect = set()
    for p in polys:
        b = p.bbox()
        dx = max(b[0] - 5.0, 0, 5.0 - b[2])
        dy = max(b[1] - 5.0, 0, 5.0 - b[3])
        # square polygons: bbox distance == boundary distance outside;
        # inside → 0
        if np.hypot(dx, dy) <= 1.0:
            expect.add(p.obj_id)
    assert got == expect


def test_knn_query_end_to_end(rng):
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)
    pts = synth_points(rng)
    q = Point(x=5.0, y=5.0)
    r, k = 4.0, 5
    results = list(PointPointKNNQuery(conf, GRID).run(iter(pts), q, r, k))
    assert results
    for res in results:
        window_pts = [p for p in pts if res.start <= p.timestamp < res.end]
        best = {}
        for p in window_pts:
            d = float(np.hypot(p.x - 5.0, p.y - 5.0))
            if d <= r and (p.obj_id not in best or d < best[p.obj_id]):
                best[p.obj_id] = d
        expect = sorted(best.items(), key=lambda kv: kv[1])[:k]
        got = [(oid, d) for oid, d, _ in res.neighbors]
        assert [o for o, _ in got] == [o for o, _ in expect]
        for (_, gd), (_, ed) in zip(got, expect):
            assert gd == pytest.approx(ed, rel=1e-12)


def test_join_query_end_to_end(rng):
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)
    left = synth_points(rng, n=150)
    right = [
        Point(obj_id=f"q{i}", timestamp=int(i * 200), x=float(rng.uniform(0, 10)),
              y=float(rng.uniform(0, 10)))
        for i in range(100)
    ]
    r = 0.7
    results = list(PointPointJoinQuery(conf, GRID).run(iter(left), iter(right), r))
    got = {
        (a.obj_id, a.timestamp, b.obj_id)
        for res in results
        for a, b, _ in res.pairs
    }
    expect = set()
    for res_start in (0, 10_000, 20_000):
        res_end = res_start + 10_000
        for a in left:
            if not (res_start <= a.timestamp < res_end):
                continue
            for b in right:
                if not (res_start <= b.timestamp < res_end):
                    continue
                if np.hypot(a.x - b.x, a.y - b.y) <= r:
                    expect.add((a.obj_id, a.timestamp, b.obj_id))
    assert got == expect
    assert all(res.overflow == 0 for res in results)


def test_join_naive_matches_grid(rng):
    left = synth_points(rng, n=80)
    right = synth_points(rng, n=60)
    for p in right:
        p.obj_id = "q" + p.obj_id
    r = 1.1
    conf_g = QueryConfiguration(QueryType.WindowBased, window_size=30, slide_step=30)
    conf_n = QueryConfiguration(QueryType.RealTimeNaive, realtime_batch_ms=30_000)
    grid_pairs = {
        (id(a), id(b))
        for res in PointPointJoinQuery(conf_g, GRID).run(iter(left), iter(right), r)
        for a, b, _ in res.pairs
    }
    naive_pairs = {
        (id(a), id(b))
        for res in PointPointJoinQuery(conf_n, GRID).run(iter(left), iter(right), r)
        for a, b, _ in res.pairs
    }
    assert grid_pairs == naive_pairs


def test_synthetic_source_deterministic():
    src = SyntheticGpsSource(0, 10, 0, 10, target_eps=1000, duration_ms=2000,
                             num_devices=5, seed=42)
    a = list(src)
    b = list(src)
    assert len(a) == 2000
    assert [(p.x, p.y, p.timestamp, p.obj_id) for p in a[:50]] == [
        (p.x, p.y, p.timestamp, p.obj_id) for p in b[:50]
    ]
    # Event times advance at target rate: last event ~2s in.
    assert a[-1].timestamp == pytest.approx(1999, abs=2)
    assert {p.obj_id for p in a} == {f"dev{i}" for i in range(5)}


def test_polygon_join_nested_overlap_is_zero_distance(rng):
    """JTS returns distance 0 for overlapping/nested geometries — a nested
    polygon pair must join even though its boundary gap exceeds the radius."""
    from spatialflink_tpu.operators import PolygonPolygonJoinQuery

    conf = QueryConfiguration(QueryType.WindowBased, window_size=30, slide_step=30)
    inner = Polygon(obj_id="inner", timestamp=100,
                    rings=[np.array([[4.5, 4.5], [5.5, 4.5], [5.5, 5.5], [4.5, 5.5], [4.5, 4.5]])])
    outer = Polygon(obj_id="outer", timestamp=200,
                    rings=[np.array([[1, 1], [9, 1], [9, 9], [1, 9], [1, 1]])])
    far = Polygon(obj_id="far", timestamp=300,
                  rings=[np.array([[-3, -3], [-2.5, -3], [-2.5, -2.5], [-3, -2.5], [-3, -3]])])
    results = list(
        PolygonPolygonJoinQuery(conf, GRID).run(iter([inner, far]), iter([outer]), 1.0)
    )
    pairs = {(a.obj_id, b.obj_id) for r in results for a, b, _ in r.pairs}
    assert ("inner", "outer") in pairs  # nested → dist 0
    assert ("far", "outer") not in pairs  # corner gap ~4.9 > radius 1.0
    dists = {(a.obj_id, b.obj_id): d for r in results for a, b, d in r.pairs}
    assert dists[("inner", "outer")] == 0.0


def test_count_based_windows(rng):
    conf = QueryConfiguration(QueryType.CountBased, count_window_size=50)
    pts = synth_points(rng, n=120)
    q = Point(x=5.0, y=5.0)
    results = list(PointPointRangeQuery(conf, GRID).run(iter(pts), [q], 3.0))
    # 120 events -> windows of 50, 50, 20
    assert [r.window_count for r in results] == [50, 50, 20]


def test_knn_linestring_query_no_phantom_containment(rng):
    """An open linestring query must use pure edge distance: a point
    'enclosed' by the polyline's convex hull is NOT at distance 0."""
    from spatialflink_tpu.operators import PointLineStringKNNQuery

    conf = QueryConfiguration(QueryType.WindowBased, window_size=30, slide_step=30)
    ls = LineString(coords=np.array([[0, 0], [4, 0], [0, 4]], float))
    pts = [
        Point(obj_id="inside", timestamp=100, x=1.0, y=1.0),  # true dist ~1.0
        Point(obj_id="near", timestamp=200, x=4.1, y=0.0),  # true dist 0.1
        Point(obj_id="push", timestamp=40_000, x=9.9, y=9.9),
    ]
    results = list(PointLineStringKNNQuery(conf, GRID).run(iter(pts), ls, 5.0, 2))
    first = results[0]
    assert first.neighbors[0][0] == "near"
    assert first.neighbors[0][1] == pytest.approx(0.1, rel=1e-9)
    assert first.neighbors[1][0] == "inside"
    assert first.neighbors[1][1] > 0.9


def test_incremental_range_matches_windowed(rng):
    """The incremental (ListState-carry) variant must produce the same
    result multiset per window as full recomputation."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    pts = synth_points(rng, n=500)
    q = Point(x=5.0, y=5.0)
    r = 2.5
    full = {
        (res.start, res.end): sorted(id(p) for p in res.objects)
        for res in PointPointRangeQuery(conf, GRID).run(iter(pts), [q], r)
    }
    inc = {
        (res.start, res.end): sorted(id(p) for p in res.objects)
        for res in PointPointRangeQuery(conf, GRID).query_incremental(iter(pts), q, r)
    }
    assert full == inc


def test_incremental_range_rejects_lateness(rng):
    conf = QueryConfiguration(
        QueryType.WindowBased, window_size=10, slide_step=5, allowed_lateness=6
    )
    q = Point(x=5.0, y=5.0)
    with pytest.raises(ValueError, match="allowed_lateness"):
        list(PointPointRangeQuery(conf, GRID).query_incremental(iter([]), q, 1.0))


def test_f32_centering_preserves_radius_boundary():
    """Origin-centering before the f32 cast keeps radius-boundary decisions
    identical to f64 at degree-scale coordinates (Beijing ~116°), where a
    raw f32 cast loses ~7.6e-6° to cancellation."""
    from spatialflink_tpu.grid import UniformGrid

    bj = UniformGrid(100, 115.5, 117.6, 39.6, 41.1)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)
    r = 0.003
    q = []
    left = []
    # Pairs placed within ±2e-6 of the radius boundary.
    rng2 = np.random.default_rng(17)
    for i in range(200):
        x, y = 116.4 + i * 1e-4, 40.2
        # Keep the margin above the centered-f32 noise floor (~2e-8) so
        # the assertion tests centering, not rounding luck.
        sign = 1 if rng2.uniform() < 0.5 else -1
        d = r + sign * rng2.uniform(5e-7, 2e-6)
        theta = rng2.uniform(0, 2 * np.pi)
        left.append(Point(obj_id=f"l{i}", timestamp=i, x=x, y=y))
        q.append(Point(obj_id=f"q{i}", timestamp=i,
                       x=x + d * np.cos(theta), y=y + d * np.sin(theta)))
    # All points share ~2 grid cells; raise the per-cell capacity so the
    # grid-hash join stays exact (overflow == 0).
    res = list(PointPointJoinQuery(conf, bj, cap=256).run(
        iter(left), iter(q), r, dtype=np.float32))
    assert all(rr.overflow == 0 for rr in res)
    got = {(a.obj_id, b.obj_id) for rr in res for a, b, _ in rr.pairs}
    expect = {
        (a.obj_id, b.obj_id)
        for a in left for b in q
        if np.hypot(a.x - b.x, a.y - b.y) <= r
    }
    assert got == expect


def _knn_result_key(results):
    return {
        (res.start, res.end): [
            (oid, round(d, 12), id(ev)) for oid, d, ev in res.neighbors
        ]
        for res in results
    }


def test_pane_knn_matches_windowed(rng):
    """query_panes (pane-digest carry) must equal full recomputation per
    window: same spans, same ordered (objID, dist) lists, same
    representative event objects (tie-break contract)."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    pts = synth_points(rng, n=500)
    q = Point(x=5.0, y=5.0)
    r, k = 4.0, 7
    full = _knn_result_key(PointPointKNNQuery(conf, GRID).run(iter(pts), q, r, k))
    pane = _knn_result_key(
        PointPointKNNQuery(conf, GRID).query_panes(iter(pts), q, r, k)
    )
    assert full == pane


def test_pane_knn_with_empty_panes(rng):
    """A time gap in the stream leaves whole panes empty; merged windows
    must still match full recomputation."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=20, slide_step=5)
    early = synth_points(rng, n=60, t_span=9_000)
    late = [
        Point(obj_id=f"late{i % 5}", timestamp=31_000 + i * 150,
              x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
        for i in range(40)
    ]
    pts = early + late
    q = Point(x=5.0, y=5.0)
    r, k = 5.0, 4
    full = _knn_result_key(PointPointKNNQuery(conf, GRID).run(iter(pts), q, r, k))
    pane = _knn_result_key(
        PointPointKNNQuery(conf, GRID).query_panes(iter(pts), q, r, k)
    )
    assert full == pane


def test_pane_knn_empty_panes_float32(rng):
    """Regression: with a float32 pipeline under x64, the empty-pane digest
    must stay float32 — a default-dtype jnp.full promoted the merge to
    float64, making the float32-max absent-object sentinel compare as a
    real distance and report ghost neighbors (~3.4e38) for any window
    containing an empty pane."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=20, slide_step=5)
    early = synth_points(rng, n=60, t_span=9_000)
    late = [
        Point(obj_id=f"late{i % 5}", timestamp=31_000 + i * 150,
              x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
        for i in range(40)
    ]
    pts = early + late
    q = Point(x=5.0, y=5.0)
    r, k = 5.0, 4
    full = _knn_result_key(
        PointPointKNNQuery(conf, GRID).run(iter(pts), q, r, k,
                                           dtype=np.float32)
    )
    pane = _knn_result_key(
        PointPointKNNQuery(conf, GRID).query_panes(iter(pts), q, r, k,
                                                   dtype=np.float32)
    )
    assert full == pane
    for neighbors in pane.values():
        assert all(d < 1e30 for _, d, _ in neighbors)


def test_pane_knn_excludes_out_of_extent_points(rng):
    """Points outside the grid extent carry cell == num_cells, whose flag
    entry is hard-coded 0 (the reference's key-never-matches semantics,
    HelperClass.assignGridCellID). The flag-less compact pane path must
    exclude them exactly like run() — regression for the host-side
    in-grid mask."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    pts = synth_points(rng, n=200)
    outside = [
        Point(obj_id=f"out{i}", timestamp=i * 400, x=10.2 + 0.01 * i, y=5.0)
        for i in range(20)
    ]
    stream = sorted(pts + outside, key=lambda p: p.timestamp)
    q = Point(x=9.9, y=5.0)  # out-of-extent points are within radius
    r, k = 2.0, 8
    full = _knn_result_key(
        PointPointKNNQuery(conf, GRID).run(iter(stream), q, r, k)
    )
    pane = _knn_result_key(
        PointPointKNNQuery(conf, GRID).query_panes(iter(stream), q, r, k)
    )
    assert full == pane
    assert not any(
        oid.startswith("out") for nb in pane.values() for oid, _, _ in nb
    )


def test_pane_knn_polygon_query(rng):
    """Pane carry through the polygon-query digest (containment → 0)."""
    from spatialflink_tpu.operators import PointPolygonKNNQuery

    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    pts = synth_points(rng, n=300)
    poly = Polygon(
        obj_id="qp",
        rings=[np.array([[4, 4], [6, 4], [6, 6], [4, 6], [4, 4]], float)],
    )
    r, k = 4.0, 6
    full = _knn_result_key(
        PointPolygonKNNQuery(conf, GRID).run(iter(pts), poly, r, k)
    )
    pane = _knn_result_key(
        PointPolygonKNNQuery(conf, GRID).query_panes(iter(pts), poly, r, k)
    )
    assert full == pane


def test_pane_knn_rejects_lateness(rng):
    conf = QueryConfiguration(
        QueryType.WindowBased, window_size=10, slide_step=5, allowed_lateness=3
    )
    q = Point(x=5.0, y=5.0)
    with pytest.raises(ValueError, match="allowed_lateness"):
        list(PointPointKNNQuery(conf, GRID).query_panes(iter([]), q, 1.0, 3))


def test_multi_query_knn_matches_per_query_runs(rng):
    """run_multi (one fused program for the whole query set) must equal
    run() executed per query point — including tie-break/representative
    identity and empty-result queries (a query in a far corner)."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    pts = synth_points(rng, n=400)
    queries = [
        Point(x=2.0, y=2.0), Point(x=5.0, y=5.0), Point(x=8.0, y=3.0),
        Point(x=9.9, y=9.9), Point(x=0.05, y=9.95),
    ]
    r, k = 1.5, 6
    multi = list(
        PointPointKNNQuery(conf, GRID).run_multi(iter(pts), queries, r, k)
    )
    assert multi
    for qi, q in enumerate(queries):
        single = list(PointPointKNNQuery(conf, GRID).run(iter(pts), q, r, k))
        assert len(single) == len(multi)
        for sres, mres in zip(single, multi):
            got = mres.results[qi]
            assert (got.start, got.end) == (sres.start, sres.end)
            assert [(o, round(d, 12), id(ev)) for o, d, ev in got.neighbors] \
                == [(o, round(d, 12), id(ev)) for o, d, ev in sres.neighbors]


def test_multi_query_knn_kernel_parity(rng):
    """Kernel-level: knn_multi_query_kernel row == knn_points_fused per
    query, across a query count that needs block padding."""
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.ops.knn import knn_multi_query_kernel, knn_points_fused

    n, nq, k = 512, 11, 5
    xy = rng.uniform(0, 10, (n, 2))
    oid = rng.integers(0, 31, n).astype(np.int32)
    cell = GRID.assign_cells_np(xy)
    valid = np.ones(n, bool)
    qxy = rng.uniform(0, 10, (nq, 2))
    tables = np.stack([
        GRID.neighbor_flags(2.0, [GRID.flat_cell(*q)]) for q in qxy
    ])
    qb = 16
    tables_p = np.concatenate(
        [tables, np.zeros((qb - nq,) + tables.shape[1:], tables.dtype)])
    qxy_p = np.concatenate([qxy, np.zeros((qb - nq, 2))])

    multi = jax.jit(
        knn_multi_query_kernel,
        static_argnames=("k", "num_segments", "query_block"),
    )(
        jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(cell),
        jnp.asarray(tables_p), jnp.asarray(oid), jnp.asarray(qxy_p),
        2.0, k=k, num_segments=32, query_block=8,
    )
    single = jax.jit(
        knn_points_fused, static_argnames=("k", "num_segments"))
    for qi in range(nq):
        res = single(
            jnp.asarray(xy), jnp.asarray(valid), jnp.asarray(cell),
            jnp.asarray(tables[qi]), jnp.asarray(oid),
            jnp.asarray(qxy[qi]), 2.0, k=k, num_segments=32,
        )
        np.testing.assert_array_equal(np.asarray(multi.segment[qi]),
                                      np.asarray(res.segment))
        np.testing.assert_allclose(np.asarray(multi.dist[qi]),
                                   np.asarray(res.dist), rtol=1e-12)
        np.testing.assert_array_equal(np.asarray(multi.index[qi]),
                                      np.asarray(res.index))
        assert int(multi.num_valid[qi]) == int(res.num_valid)
    # padded query lanes: zero flags -> nothing found
    for qi in range(nq, qb):
        assert int(multi.num_valid[qi]) == 0


def test_point_polygon_range_pruned_path_matches_dense(rng):
    """With >=64 query polygons the operator auto-selects the pruned
    kernel; results must match the dense path exactly."""
    from spatialflink_tpu.utils.helper import generate_query_polygons

    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    pts = synth_points(rng, n=600)
    polys = generate_query_polygons(80, 0.0, 0.0, 10.0, 10.0, grid_size=20,
                                    seed=11)
    op_pruned = PointPolygonRangeQuery(conf, GRID)
    got = {
        (res.start, res.end): sorted(id(p) for p in res.objects)
        for res in op_pruned.run(iter(pts), polys, 0.3)
    }
    # Force the dense path by running per-polygon-chunk under the
    # threshold and unioning.
    dense = {}
    for res in PointPolygonRangeQuery(conf, GRID).run(iter(pts), polys[:63], 0.3):
        dense.setdefault((res.start, res.end), set()).update(
            id(p) for p in res.objects)
    for res in PointPolygonRangeQuery(conf, GRID).run(iter(pts), polys[63:], 0.3):
        dense.setdefault((res.start, res.end), set()).update(
            id(p) for p in res.objects)
    dense_sorted = {k: sorted(v) for k, v in dense.items()}
    assert got == dense_sorted


@pytest.mark.slow
def test_pane_join_matches_windowed(rng):
    """query_panes (pane-block carry) must produce the same pair MULTISET
    per sliding window as run() full recomputation (order may differ:
    block-major vs window-compaction order)."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    left = synth_points(rng, n=250)
    right = [
        Point(obj_id=f"q{i}", timestamp=int(i * 120),
              x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
        for i in range(200)
    ]
    r = 0.8

    def collect(gen):
        return {
            (res.start, res.end): (
                sorted((id(a), id(b), round(d, 12)) for a, b, d in res.pairs),
                res.overflow,
            )
            for res in gen
        }

    full = collect(PointPointJoinQuery(conf, GRID).run(iter(left), iter(right), r))
    pane = collect(
        PointPointJoinQuery(conf, GRID).query_panes(iter(left), iter(right), r)
    )
    assert set(full) == set(pane)
    for k in full:
        assert full[k][0] == pane[k][0], k
        assert full[k][1] == 0 and pane[k][1] == 0


def test_pane_join_rejects_lateness(rng):
    conf = QueryConfiguration(
        QueryType.WindowBased, window_size=10, slide_step=5, allowed_lateness=2
    )
    with pytest.raises(ValueError, match="allowed_lateness"):
        list(PointPointJoinQuery(conf, GRID).query_panes(iter([]), iter([]), 1.0))


def test_point_polygon_range_compact_path_matches_dense(rng):
    """A sparse >=64-polygon query set (clustered: low flag occupancy)
    selects the candidate-compacted pruned kernel; results must match the
    dense path exactly, including across budget-growth retries."""
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)
    pts = synth_points(rng, n=700)
    # 70 tiny polygons clustered in one corner: candidate union is small.
    polys = []
    for i in range(70):
        cx, cy = rng.uniform(1.0, 2.5), rng.uniform(1.0, 2.5)
        polys.append(Polygon(rings=[np.array(
            [[cx - .1, cy - .1], [cx + .1, cy - .1], [cx + .1, cy + .1],
             [cx - .1, cy + .1], [cx - .1, cy - .1]])]))
    op = PointPolygonRangeQuery(conf, GRID)
    op._cand_budget = 64  # force at least one budget-growth retry
    got = {
        (res.start, res.end): sorted(
            (id(p), round(float(d), 12))
            for p, d in zip(res.objects, res.dists))
        for res in op.run(iter(pts), polys, 0.2)
    }
    dense = {}
    for res in PointPolygonRangeQuery(conf, GRID).run(iter(pts), polys[:63], 0.2):
        dense.setdefault((res.start, res.end), set()).update(
            (id(p), round(float(d), 12))
            for p, d in zip(res.objects, res.dists))
    for res in PointPolygonRangeQuery(conf, GRID).run(iter(pts), polys[63:], 0.2):
        dense.setdefault((res.start, res.end), set()).update(
            (id(p), round(float(d), 12))
            for p, d in zip(res.objects, res.dists))
    # Union of the two dense sub-queries: a point can match both halves
    # with different min distances; keep the min like the full query does.
    dense_min = {}
    for k, v in dense.items():
        best = {}
        for pid, d in v:
            if pid not in best or d < best[pid]:
                best[pid] = d
        dense_min[k] = sorted(best.items())
    assert got == dense_min
