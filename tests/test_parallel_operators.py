"""End-to-end operator execution on the 8-device CPU mesh.

VERDICT round-1 gap: the sharded kernels existed but no operator could run
on a mesh. These tests drive the OPERATOR layer (windows → batches →
shard_mapped kernels → decoded results) with ``mesh=`` and require results
identical to the single-device run — the framework analog of the
reference's parallelism default (StreamingJob.java:177,
conf/geoflink-conf.yml:55) with semantics unchanged.

Shapes are ≥100k points for the point-stream paths so shard boundaries,
bucket padding, and the pmin/top-k collectives are exercised at realistic
sizes, not toys.
"""

import numpy as np
import pytest
import jax
from jax.sharding import Mesh

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import LineString, Point, Polygon
from spatialflink_tpu.operators import (
    PointPointJoinQuery,
    PointPointKNNQuery,
    PointPointRangeQuery,
    PolygonPointKNNQuery,
    PolygonPointRangeQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.operators.trajectory import TStatsQuery

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
W = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices())
    assert devs.size == 8, "conftest must provide 8 virtual CPU devices"
    return Mesh(devs.reshape(8), ("data",))


def _points(rng, n, n_obj=512, t_span=10_000):
    xy = rng.uniform(0, 10, (n, 2))
    return [
        Point(obj_id=f"d{i % n_obj}", timestamp=int(i * t_span / n),
              x=float(xy[i, 0]), y=float(xy[i, 1]))
        for i in range(n)
    ]


def test_range_operator_mesh_matches_single(rng, mesh):
    pts = _points(rng, 120_000)
    q = Point(x=5.0, y=5.0)
    r = 0.5

    def run(m):
        return [
            (res.start, res.end,
             [(o.obj_id, o.timestamp) for o in res.objects],
             res.dists.tolist())
            for res in PointPointRangeQuery(W, GRID).run(
                iter(list(pts)), [q], r, mesh=m)
        ]

    single = run(None)
    sharded = run(mesh)
    assert sharded == single
    assert sum(len(s[2]) for s in single) > 0


def test_knn_operator_mesh_bit_matches_single(rng, mesh):
    pts = _points(rng, 120_000)
    q = Point(x=5.0, y=5.0)

    def run(m):
        op = PointPointKNNQuery(W, GRID, mesh=m)  # mesh via constructor
        return [
            (res.start, res.end,
             [(oid, d, obj.obj_id, obj.timestamp)
              for oid, d, obj in res.neighbors])
            for res in op.run(iter(list(pts)), q, 2.0, 50)
        ]

    single = run(None)
    sharded = run(mesh)
    assert sharded == single  # bit-identical incl. tie-breaks
    assert all(len(w[2]) == 50 for w in single)


@pytest.mark.slow
def test_join_operator_mesh_matches_single(rng, mesh):
    # Finer grid so neither side exceeds the per-cell cap (overflow 0 →
    # both the compact single-device path and the dense sharded path are
    # exact and must agree).
    grid_j = UniformGrid(64, 0.0, 10.0, 0.0, 10.0)
    left = _points(rng, 100_000)
    rxy = np.random.default_rng(5).uniform(0, 10, (4_000, 2))
    right = [
        Point(obj_id=f"q{i}", timestamp=int(i * 10_000 / 4_000),
              x=float(rxy[i, 0]), y=float(rxy[i, 1]))
        for i in range(4_000)
    ]
    r = 0.05

    def run(m):
        out = []
        for res in PointPointJoinQuery(W, grid_j, mesh=m).run(
            iter(list(left)), iter(list(right)), r
        ):
            assert res.overflow == 0
            out.append((
                res.start, res.end,
                sorted((a.obj_id, a.timestamp, b.obj_id, round(d, 12))
                       for a, b, d in res.pairs),
            ))
        return out

    single = run(None)
    sharded = run(mesh)
    # Same pair sets; the compact (single) and dense-sharded paths emit in
    # different orders, hence the sort.
    assert len(sharded) == len(single)
    for s, g in zip(single, sharded):
        assert s[0] == g[0] and s[1] == g[1]
        assert s[2] == g[2]
    assert sum(len(s[2]) for s in single) > 100


def test_tstats_operator_mesh_matches_single(rng, mesh):
    pts = _points(rng, 100_000, n_obj=256)

    def run(m):
        return [
            (res.start, res.end, res.stats)
            for res in TStatsQuery(W, GRID, mesh=m).run(iter(list(pts)))
        ]

    single = run(None)
    sharded = run(mesh)
    assert len(sharded) == len(single)
    for s, g in zip(single, sharded):
        assert s[0] == g[0] and s[1] == g[1]
        assert s[2].keys() == g[2].keys()
        for k in s[2]:
            np.testing.assert_allclose(g[2][k], s[2][k], rtol=1e-12)


def test_streaming_job_device_mesh_config(tmp_path, mesh):
    """yml deviceMesh: [8] → run_job executes on the mesh, output identical
    to single-device (the config seam for conf/geoflink-conf.yml:55)."""
    from spatialflink_tpu.streaming_job import main

    def run(device_mesh):
        conf = tmp_path / f"conf{device_mesh}.yml"
        conf.write_text(f"""
inputStream1:
  topicName: t
  format: CSV
  csvTsvSchemaAttr: [0, 1, 2, 3]
  gridBBox: [0.0, 0.0, 10.0, 10.0]
  numGridCells: 20
  delimiter: ","
query:
  option: 1
  radius: 2.0
  k: 3
  queryPoints:
    - [5.0, 5.0]
window:
  type: "TIME"
  interval: 10
  step: 10
deviceMesh: [{device_mesh}]
""")
        csv = tmp_path / "in.csv"
        rng2 = np.random.default_rng(9)
        rows = [
            f"dev{i % 5},{i * 300},{rng2.uniform(0, 10)},{rng2.uniform(0, 10)}"
            for i in range(500)
        ]
        csv.write_text("\n".join(rows))
        out = tmp_path / f"out{device_mesh}.csv"
        rc = main(["--config", str(conf), "--source", f"csv:{csv}",
                   "--output", str(out)])
        assert rc == 0
        return out.read_text()

    assert run(8) == run(1)


def test_geometry_stream_operators_mesh(rng, mesh):
    """Geometry-stream range + kNN on the mesh (object-axis sharding)."""
    polys = []
    for i in range(500):
        cx, cy = rng.uniform(1, 9), rng.uniform(1, 9)
        s = 0.25
        polys.append(Polygon(
            obj_id=f"z{i}", timestamp=i * 20,
            rings=[np.array([[cx - s, cy - s], [cx + s, cy - s],
                             [cx + s, cy + s], [cx - s, cy + s],
                             [cx - s, cy - s]])],
        ))
    q = Point(x=5.0, y=5.0)

    def run_range(m):
        return [
            (res.start, res.end,
             sorted((o.obj_id, round(d, 12))
                    for o, d in zip(res.objects, res.dists)))
            for res in PolygonPointRangeQuery(W, GRID).run(
                iter(list(polys)), [q], 1.5, mesh=m)
        ]

    assert run_range(mesh) == run_range(None)

    def run_knn(m):
        return [
            (res.start, res.end,
             [(oid, d, obj.obj_id) for oid, d, obj in res.neighbors])
            for res in PolygonPointKNNQuery(W, GRID).run(
                iter(list(polys)), q, 5.0, 10, mesh=m)
        ]

    assert run_knn(mesh) == run_knn(None)


def test_trange_operator_mesh_matches_single(rng, mesh):
    from spatialflink_tpu.operators import TRangeQuery

    pts = _points(rng, 100_000, n_obj=256)
    polys = [
        Polygon(rings=[np.array([[3, 3], [4.5, 3], [4.5, 4.5], [3, 4.5],
                                 [3, 3]], float)]),
        Polygon(rings=[np.array([[6, 6], [8, 6], [8, 8], [6, 8],
                                 [6, 6]], float)]),
    ]

    def run(m):
        return [
            (res.start, res.end,
             sorted(t.obj_id for t in res.trajectories))
            for res in TRangeQuery(W, GRID).run(iter(pts), polys, mesh=m)
        ]

    assert run(None) == run(mesh)


def test_tknn_operator_mesh_matches_single(rng, mesh):
    from spatialflink_tpu.operators import TKNNQuery

    pts = _points(rng, 100_000, n_obj=256)
    q = Point(x=5.0, y=5.0)

    def run(m):
        return [
            (res.start, res.end,
             [(o, round(d, 12)) for o, d, _ in res.neighbors])
            for res in TKNNQuery(W, GRID).run(iter(pts), q, 2.0, 7, mesh=m)
        ]

    assert run(None) == run(mesh)


def test_taggregate_operator_mesh_matches_single(rng, mesh):
    from spatialflink_tpu.operators import TAggregateQuery

    pts = _points(rng, 100_000, n_obj=128)

    def run(m):
        out = []
        for res in TAggregateQuery(W, GRID, aggregate="SUM").run(
            iter(pts), mesh=m
        ):
            out.append((res.start, res.end, sorted(res.cells.items())))
        return out

    assert run(None) == run(mesh)


@pytest.mark.slow
def test_tjoin_operator_mesh_matches_single(rng, mesh):
    from spatialflink_tpu.operators import TJoinQuery

    left = _points(rng, 60_000, n_obj=64)
    right = [
        Point(obj_id=f"q{i % 48}", timestamp=int(i * 10_000 / 40_000),
              x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
        for i in range(40_000)
    ]

    def run(m):
        return [
            (res.start, res.end,
             sorted((a.obj_id, b.obj_id, round(d, 12))
                    for a, b, d in res.pairs))
            # cap=256 > the ~150 points/cell of this density: the cap/
            # overflow contract (per-shard caps) only guarantees parity
            # when no cell overflows.
            for res in TJoinQuery(W, GRID, cap=256, mesh=m).run(
                iter(left), iter(right), 0.05
            )
        ]

    assert run(None) == run(mesh)


def test_run_multi_mesh_matches_single(rng, mesh):
    """run_multi on a 1-D data mesh (replicated queries) must produce the
    same per-query winner lists as single-device (distances to 1 ulp)."""
    pts = _points(rng, 80_000, n_obj=256)
    queries = [Point(x=2.0, y=2.0), Point(x=5.0, y=5.0), Point(x=8.0, y=7.0)]

    def run(m):
        return [
            (res.start, res.end,
             [[(o, round(d, 12)) for o, d, _ in r.neighbors]
              for r in res.results])
            for res in PointPointKNNQuery(W, GRID).run_multi(
                iter(pts), queries, 1.5, 6, mesh=m
            )
        ]

    assert run(None) == run(mesh)


def test_tstats_pane_engine_mesh_bit_matches_single(rng, mesh):
    """VERDICT r4 weak #6: the device tStats pane engine on the 8-device
    mesh (trajectory-parallel oid blocks,
    parallel/sharded.py:sharded_traj_stats_pane) must be BIT-identical
    to the single-device kernel at x64 — not the dryrun's f32
    tolerance. Driven through the product path
    (streams/panes.py:traj_stats_sliding(mesh=))."""
    from spatialflink_tpu.streams.panes import traj_stats_sliding

    n, n_obj = 60_000, 64  # 8 oids per shard
    ts = np.sort(rng.integers(0, 30_000, n)).astype(np.int64)
    xy = rng.uniform(0, 10, (n, 2))
    oid = rng.integers(0, n_obj, n).astype(np.int64)

    single = traj_stats_sliding(ts, xy, oid, n_obj, 10_000, 100,
                                backend="device")
    meshed = traj_stats_sliding(ts, xy, oid, n_obj, 10_000, 100,
                                backend="device", mesh=mesh)
    np.testing.assert_array_equal(single.starts, meshed.starts)
    np.testing.assert_array_equal(single.spatial, meshed.spatial)
    np.testing.assert_array_equal(single.temporal, meshed.temporal)
    np.testing.assert_array_equal(single.count, meshed.count)
    assert single.spatial.any(), "degenerate: no spatial sums"
    # ... and the device result matches the host oracle at the engine's
    # documented tolerance (segment_sum associates float adds in a
    # different order than bincount — test_panes.py pins 1e-12 relative;
    # ints exact).
    host = traj_stats_sliding(ts, xy, oid, n_obj, 10_000, 100,
                              backend="numpy")
    np.testing.assert_array_equal(host.count, meshed.count)
    np.testing.assert_array_equal(host.temporal, meshed.temporal)
    assert np.allclose(host.spatial, meshed.spatial, rtol=1e-12,
                       atol=5e-12)


def test_tstats_pane_mesh_rejects_bad_config(rng, mesh):
    from spatialflink_tpu.streams.panes import traj_stats_sliding

    ts = np.arange(100, dtype=np.int64)
    xy = np.zeros((100, 2))
    oid = np.zeros(100, np.int64)
    with pytest.raises(ValueError, match="divide"):
        traj_stats_sliding(ts, xy, oid, 12, 1_000, 100,
                           backend="device", mesh=mesh)
    with pytest.raises(ValueError, match="device backend"):
        traj_stats_sliding(ts, xy, oid, 16, 1_000, 100,
                           backend="numpy", mesh=mesh)


def test_tjoin_pane_engine_mesh_bit_matches_single(rng, mesh):
    """VERDICT r4 weak #5/#6: the pane-carry tJoin engine on the
    8-device mesh (probe-parallel points, replicated window/digest
    state, all-gathered contributions — ops/tjoin_panes.py) must be
    BIT-identical to single-device at x64, through the operator path."""
    from spatialflink_tpu.operators.trajectory import TJoinQuery

    conf = QueryConfiguration(QueryType.WindowBased, window_size=1,
                              slide_step=0.1)
    n, n_obj = 4_000, 16

    def mk(shift):
        ts = np.sort(rng.integers(0, 4_000, n)).astype(np.int64)
        return {
            "ts": ts,
            "x": rng.uniform(2 + shift, 8 + shift, n),
            "y": rng.uniform(2, 8, n),
            "oid": rng.integers(0, n_obj, n).astype(np.int32),
        }

    left, right = mk(0.0), mk(0.2)

    def run(m, **kw):
        return [
            (s, e, list(map(int, lo)), list(map(int, ro)),
             [float(d) for d in dd], c, ov)
            for s, e, lo, ro, dd, c, ov in TJoinQuery(conf, GRID).run_soa_panes(
                iter([dict(left)]), iter([dict(right)]), 0.4,
                num_segments=n_obj, mesh=m, backend="device", **kw,
            )  # backend forced: auto would route the mesh-less run to
        ]  # the NATIVE engine (1e-12, not bit, vs the device scan)

    single = run(None)
    meshed = run(mesh)
    assert single == meshed  # exact — incl. every float distance bit
    assert sum(len(r[2]) for r in single) > 0, "degenerate: no pairs"
    # Compaction commutes with sharding: the live-slot compacted scan
    # (auto bucket — the default above on CPU) under the mesh must also
    # bit-match the FULL-RING probe single-device — replicated live
    # counts + positional heads shard-invariantly reproduce the legacy
    # candidate sets.
    full_ring_single = run(None, cap_c=0)
    assert full_ring_single == meshed
