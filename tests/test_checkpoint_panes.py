"""Kill-and-resume for the incremental pane-carry pipelines (VERDICT r2
item: the ListState-analog state in query_panes lived in generator locals
and could not be checkpointed). A stream is cut mid-way, the operator is
snapshotted (assembler + pane digests/blocks + interner), a FRESH operator
is restored in a "new process" (pickle round-trip through disk), and the
resumed output must equal the uninterrupted run exactly."""

import numpy as np
import pytest

from spatialflink_tpu.checkpoint import (
    load_checkpoint,
    operator_state,
    restore_operator,
    save_checkpoint,
)
from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import Point
from spatialflink_tpu.operators import (
    PointPointJoinQuery,
    PointPointKNNQuery,
    QueryConfiguration,
    QueryType,
)

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
CONF = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=5)


@pytest.fixture
def rng():
    return np.random.default_rng(21)


def _pts(rng, n, prefix="d", n_obj=24, t_span=40_000):
    xy = rng.uniform(0, 10, (n, 2))
    return [
        Point(obj_id=f"{prefix}{i % n_obj}", timestamp=int(i * t_span / n),
              x=float(xy[i, 0]), y=float(xy[i, 1]))
        for i in range(n)
    ]


def _knn_key(results):
    return [
        (r.start, r.end,
         [(o, round(d, 12), ev.obj_id, ev.timestamp)
          for o, d, ev in r.neighbors])
        for r in results
    ]


def test_knn_pane_carry_kill_and_resume(rng, tmp_path):
    pts = _pts(rng, 900)
    q = Point(x=5.0, y=5.0)
    r, k = 3.0, 6
    cut = 500  # mid-stream, mid-window

    baseline = _knn_key(
        PointPointKNNQuery(CONF, GRID).query_panes(iter(pts), q, r, k)
    )

    # "Process 1": source dies after `cut` events; snapshot to disk.
    op1 = PointPointKNNQuery(CONF, GRID)
    part1 = _knn_key(
        op1.query_panes(iter(pts[:cut]), q, r, k, flush_at_end=False)
    )
    path = str(tmp_path / "knn.ckpt")
    save_checkpoint(path, op=operator_state(op1))
    del op1

    # "Process 2": fresh operator, restore, feed the remaining events.
    op2 = PointPointKNNQuery(CONF, GRID)
    restore_operator(op2, load_checkpoint(path)["op"])
    part2 = _knn_key(op2.query_panes(iter(pts[cut:]), q, r, k))

    assert part1 + part2 == baseline
    assert part1 and part2  # the cut actually split fired windows


def test_knn_pane_carry_resume_digests_survive(rng, tmp_path):
    """The resumed run must MERGE carried digests from before the kill —
    cut inside a window so its first slide's data exists only in the
    checkpoint."""
    pts = _pts(rng, 600, t_span=30_000)
    q = Point(x=5.0, y=5.0)
    op1 = PointPointKNNQuery(CONF, GRID)
    # Cut at 60%: the open window's earlier pane was digested pre-kill.
    cut = 360
    _ = _knn_key(op1.query_panes(iter(pts[:cut]), q, 3.0, 5,
                                 flush_at_end=False))
    state = operator_state(op1)
    assert any(v is not None for v in state["knn_pane_carry"].values())
    assert state["assembler"]["buffers"]  # open windows buffered


@pytest.mark.slow
def test_join_pane_carry_kill_and_resume(rng, tmp_path):
    left = _pts(rng, 500, prefix="a")
    right = _pts(np.random.default_rng(9), 400, prefix="b", n_obj=16)
    r = 0.7

    def collect(gen):
        return [
            (res.start, res.end, res.overflow,
             sorted((a.obj_id, a.timestamp, b.obj_id, b.timestamp,
                     round(d, 12)) for a, b, d in res.pairs))
            for res in gen
        ]

    baseline = collect(
        PointPointJoinQuery(CONF, GRID).query_panes(iter(left), iter(right), r)
    )

    lcut, rcut = 280, 220
    op1 = PointPointJoinQuery(CONF, GRID)
    part1 = collect(op1.query_panes(
        iter(left[:lcut]), iter(right[:rcut]), r, flush_at_end=False
    ))
    path = str(tmp_path / "join.ckpt")
    save_checkpoint(path, op=operator_state(op1))
    del op1

    op2 = PointPointJoinQuery(CONF, GRID)
    restore_operator(op2, load_checkpoint(path)["op"])
    part2 = collect(op2.query_panes(iter(left[lcut:]), iter(right[rcut:]), r))

    assert part1 + part2 == baseline
    assert part1 and part2


def test_knn_soa_pane_carry_kill_and_resume(rng, tmp_path):
    n = 4_000
    ts = np.sort(rng.integers(0, 40_000, n)).astype(np.int64)
    xs = rng.uniform(0, 10, n)
    ys = rng.uniform(0, 10, n)
    oids = rng.integers(0, 32, n).astype(np.int32)
    q = Point(x=5.0, y=5.0)
    r, k, nseg = 3.0, 6, 32

    def chunks(lo, hi, step=700):
        for a in range(lo, hi, step):
            b = min(a + step, hi)
            yield {"ts": ts[a:b], "x": xs[a:b], "y": ys[a:b],
                   "oid": oids[a:b]}

    def collect(gen):
        return [
            (s, e, list(map(int, o)), [round(float(x), 12) for x in d], nv)
            for s, e, o, d, nv in gen
        ]

    baseline = collect(PointPointKNNQuery(CONF, GRID).run_soa_panes(
        chunks(0, n), q, r, k, num_segments=nseg
    ))

    cut = 2_300
    op1 = PointPointKNNQuery(CONF, GRID)
    part1 = collect(op1.run_soa_panes(
        chunks(0, cut), q, r, k, num_segments=nseg, flush_at_end=False
    ))
    path = str(tmp_path / "soa.ckpt")
    save_checkpoint(path, op=operator_state(op1))
    del op1

    op2 = PointPointKNNQuery(CONF, GRID)
    restore_operator(op2, load_checkpoint(path)["op"])
    part2 = collect(op2.run_soa_panes(
        chunks(cut, n), q, r, k, num_segments=nseg
    ))

    assert part1 + part2 == baseline
    assert part1 and part2


def test_knn_wire_pane_carry_kill_and_resume(rng, tmp_path):
    """run_wire_panes (the wire-ingest headline path) resumes
    mid-window: the digest ring + next pane index snapshot through
    operator_state; a restored operator fed the REMAINING panes (the
    WireKafkaSource-offsets pairing) continues identically to an
    uninterrupted run."""
    from spatialflink_tpu.streams.wire import WireFormat, wire_panes

    wf = WireFormat.for_grid(GRID)
    n = 5_000
    ts = np.sort(rng.integers(0, 40_000, n)).astype(np.int64)
    xy = np.stack([rng.uniform(0, 10, n), rng.uniform(0, 10, n)], axis=1)
    wq = wf.quantize(xy)
    xyf = wf.dequantize_np(wq)
    oids = rng.integers(0, 32, n).astype(np.int32)
    q = Point(x=5.0, y=5.0)
    r, k, nseg = 3.0, 6, 32
    slide_ms = CONF.slide_step_ms

    panes = list(wire_panes(
        [{"ts": ts, "x": xyf[:, 0].astype(np.float64),
          "y": xyf[:, 1].astype(np.float64), "oid": oids}],
        wf, slide_ms, start_ms=0,
    ))

    def collect(gen):
        return [
            (s, e, list(map(int, o)), [round(float(x), 6) for x in d], nv)
            for s, e, o, d, nv in gen
        ]

    def run(op, pane_list, flush=True):
        return collect(op.run_wire_panes(
            pane_list, q, r, k, nseg, wf, start_ms=0, flush_at_end=flush,
        ))

    baseline = run(PointPointKNNQuery(CONF, GRID), panes)

    cut = len(panes) // 3
    op1 = PointPointKNNQuery(CONF, GRID)
    part1 = run(op1, panes[:cut], flush=False)
    path = str(tmp_path / "wire.ckpt")
    save_checkpoint(path, op=operator_state(op1))
    del op1

    op2 = PointPointKNNQuery(CONF, GRID)
    restore_operator(op2, load_checkpoint(path)["op"])
    part2 = run(op2, panes[cut:])

    assert part1 + part2 == baseline
    assert part1 and part2


def test_knn_wire_pane_carry_not_reentrant_leak(rng, tmp_path):
    """The index-based wire carry is consumed only right after restore:
    an ordinary SECOND call on the same operator must be a fresh run
    (identical output), not a silent time-shifted resume — and a
    checkpoint taken before ANY pane restores to a run that flushes
    nothing on an empty remainder (r5 code review)."""
    from spatialflink_tpu.streams.wire import WireFormat, wire_panes

    wf = WireFormat.for_grid(GRID)
    n = 1_500
    ts = np.sort(rng.integers(0, 20_000, n)).astype(np.int64)
    xy = np.stack([rng.uniform(0, 10, n), rng.uniform(0, 10, n)], axis=1)
    xyf = wf.dequantize_np(wf.quantize(xy))
    oids = rng.integers(0, 16, n).astype(np.int32)
    q = Point(x=5.0, y=5.0)
    panes = list(wire_panes(
        [{"ts": ts, "x": xyf[:, 0].astype(np.float64),
          "y": xyf[:, 1].astype(np.float64), "oid": oids}],
        wf, CONF.slide_step_ms, start_ms=0,
    ))

    def collect(gen):
        return [(s, e, list(map(int, o)), nv) for s, e, o, _d, nv in gen]

    op = PointPointKNNQuery(CONF, GRID)
    first = collect(op.run_wire_panes(panes, q, 3.0, 5, 16, wf))
    second = collect(op.run_wire_panes(panes, q, 3.0, 5, 16, wf))
    assert first == second

    # checkpoint before any pane → restore + empty remainder = nothing
    op1 = PointPointKNNQuery(CONF, GRID)
    none = collect(op1.run_wire_panes([], q, 3.0, 5, 16, wf,
                                      flush_at_end=False))
    assert none == []
    path = str(tmp_path / "wire0.ckpt")
    save_checkpoint(path, op=operator_state(op1))
    op2 = PointPointKNNQuery(CONF, GRID)
    restore_operator(op2, load_checkpoint(path)["op"])
    assert collect(op2.run_wire_panes([], q, 3.0, 5, 16, wf)) == []
