"""Fused Pallas wire-digest (ops/pallas_digest.py) vs the XLA digest
oracle, in interpret mode (the TPU lowering runs on the chip bench with
a runtime self-check — bench.py)."""

import numpy as np
import pytest

from conftest import pallas_int64_xfail

import jax
import jax.numpy as jnp

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.ops.pallas_digest import wire_digest_pallas
from spatialflink_tpu.streams.wire import WireFormat

GRID = UniformGrid(100, min_x=115.5, max_x=117.6, min_y=39.6, max_y=41.1)


@pytest.fixture
def rng():
    return np.random.default_rng(5)


def _wire(rng, n, nseg=512):
    wf = WireFormat.for_grid(GRID)
    xyq = wf.quantize(np.stack(
        [rng.uniform(115.5, 117.6, n), rng.uniform(39.6, 41.1, n)], axis=1
    ))
    oid = rng.integers(0, nseg, n).astype(np.int16)
    wire = np.concatenate([xyq, oid.view(np.uint16)[:, None]], axis=1)
    return wf, np.ascontiguousarray(wire.T)


def _oracle(wf, wire_t, q, radius, nseg):
    from spatialflink_tpu.ops.distances import point_point_distance
    from spatialflink_tpu.ops.knn import _digest_from_point_dists

    xy = wf.dequantize(jnp.asarray(wire_t[:2].T))
    dist = point_point_distance(xy, jnp.asarray(q)[None, :])
    return _digest_from_point_dists(
        dist, jnp.ones(wire_t.shape[1], bool), None,
        jnp.asarray(wire_t[2].astype(np.int32)), np.float32(radius), nseg,
        index_base=jnp.int32(0),
    )


@pallas_int64_xfail
def test_wire_digest_pallas_matches_oracle(rng):
    n, nseg, radius = 4096, 512, 0.05
    wf, wire_t = _wire(rng, n, nseg)
    q = np.asarray([116.40, 40.19], np.float32)
    dig, cnt = wire_digest_pallas(
        jnp.asarray(wire_t), jnp.asarray(q), wf.scale, wf.origin,
        np.float32(radius), num_segments=nseg, max_cand=2048,
        interpret=True,
    )
    assert int(cnt) <= 2048, "test sized to fit the candidate budget"
    ref = _oracle(wf, wire_t, q, radius, nseg)
    sa, sb = np.asarray(dig.seg_min), np.asarray(ref.seg_min)
    big = np.float32(np.finfo(np.float32).max)
    # distance rounding may differ by <= 1 ulp (FMA fusion freedom);
    # the in-radius SET must match exactly
    assert np.array_equal(sa == big, sb == big)
    both = sa != big
    assert both.sum() > 5, "degenerate: no in-radius objects"
    ulp = np.spacing(np.maximum(np.abs(sa), np.abs(sb)).astype(np.float32))
    assert np.all(np.abs(sa[both] - sb[both]) <= ulp[both])
    # representatives must agree wherever distances agree bitwise
    same = both & (sa == sb)
    ra, rb = np.asarray(dig.rep), np.asarray(ref.rep)
    assert np.array_equal(ra[same], rb[same])


@pallas_int64_xfail
def test_wire_digest_pallas_count_overflow_flagged(rng):
    n, nseg = 2048, 64
    wf, wire_t = _wire(rng, n, nseg)
    q = np.asarray([116.40, 40.19], np.float32)
    # huge radius: every point matches, far over the candidate budget
    dig, cnt = wire_digest_pallas(
        jnp.asarray(wire_t), jnp.asarray(q), wf.scale, wf.origin,
        np.float32(5.0), num_segments=nseg, max_cand=256, interpret=True,
    )
    assert int(cnt) == n  # honest count even though output truncated


@pallas_int64_xfail
def test_wire_digest_pallas_empty_radius(rng):
    n, nseg = 2048, 64
    wf, wire_t = _wire(rng, n, nseg)
    q = np.asarray([116.40, 40.19], np.float32)
    dig, cnt = wire_digest_pallas(
        jnp.asarray(wire_t), jnp.asarray(q), wf.scale, wf.origin,
        np.float32(1e-9), num_segments=nseg, max_cand=256, interpret=True,
    )
    assert int(cnt) == 0
    big = np.float32(np.finfo(np.float32).max)
    assert np.all(np.asarray(dig.seg_min) == big)


@pallas_int64_xfail
def test_wire_digest_pallas_non_divisible_n(rng):
    """The headline SLIDE (500k) is not a blk multiple — padding lanes
    must never enter the candidate set."""
    n, nseg, radius = 3000, 128, 0.08  # 3000 % 2048 != 0
    wf, wire_t = _wire(rng, n, nseg)
    q = np.asarray([116.40, 40.19], np.float32)
    dig, cnt = wire_digest_pallas(
        jnp.asarray(wire_t), jnp.asarray(q), wf.scale, wf.origin,
        np.float32(radius), num_segments=nseg, max_cand=2048,
        interpret=True,
    )
    ref = _oracle(wf, wire_t, q, radius, nseg)
    sa, sb = np.asarray(dig.seg_min), np.asarray(ref.seg_min)
    big = np.float32(np.finfo(np.float32).max)
    assert np.array_equal(sa == big, sb == big)
    assert (sa != big).sum() > 5
    # all extracted indices must point inside the real N
    rep = np.asarray(dig.rep)
    live = rep != np.iinfo(np.int32).max
    assert live.any() and int(rep[live].max()) < n
