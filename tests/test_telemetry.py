"""Runtime telemetry tests (telemetry.py): span tracing + Chrome-trace
validity, recompile detection, device-boundary accounting, watermark/late
gauges, metric-registry export, and the disabled-by-default contract.
"""

import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.operators import base as base_mod
from spatialflink_tpu.mn.metrics import MetricRegistry
from spatialflink_tpu.models.objects import Point
from spatialflink_tpu.operators import (
    PointPointRangeQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.streams.soa import SoaWindowAssembler
from spatialflink_tpu.streams.windows import (
    TumblingEventTimeWindows,
    WindowAssembler,
)
from spatialflink_tpu.telemetry import (
    RecompileWarning,
    abstract_signature,
    instrument_jit,
    load_trace,
    telemetry,
)

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)


@pytest.fixture(autouse=True)
def _telemetry_off():
    """Every test leaves the process-global singleton disabled, with the
    event-buffer cap restored (enable() resets counters but deliberately
    not the configured cap — a test shrinking it must not leak that into
    later files)."""
    cap = telemetry.max_events
    yield
    telemetry.max_events = cap
    telemetry.disable()


# -- disabled-by-default contract ---------------------------------------------


def test_disabled_by_default_and_free():
    assert telemetry.enabled is False
    # The disabled span is ONE shared null object — no per-call allocation
    # in operator hot paths while telemetry is off.
    assert telemetry.span("window.x") is telemetry.span("window.y")
    telemetry.account_h2d(4096)
    telemetry.account_d2h(4096)
    telemetry.record_late_drop()
    telemetry.record_watermark_lag(17)
    telemetry.record_jit_call("k", ((4,),))
    assert telemetry.h2d_bytes == 0
    assert telemetry.d2h_bytes == 0
    assert telemetry.late_drops == 0
    assert telemetry.max_watermark_lag_ms == 0
    assert telemetry.compile_count == 0


def test_fetch_passthrough_when_disabled():
    out = telemetry.fetch(jnp.arange(8))
    np.testing.assert_array_equal(np.asarray(out), np.arange(8))
    assert telemetry.d2h_transfers == 0


def test_enable_resets_state():
    telemetry.enable()
    telemetry.account_h2d(100)
    telemetry.record_watermark_lag(9)
    telemetry.enable()
    assert telemetry.h2d_bytes == 0
    assert telemetry.max_watermark_lag_ms == 0


# -- spans / Chrome trace -----------------------------------------------------


def test_spans_nest_and_trace_is_chrome_loadable(tmp_path):
    path = tmp_path / "trace.jsonl"
    telemetry.enable(trace_path=str(path))
    with telemetry.span("window.test", events=3):
        with telemetry.span("assemble"):
            pass
        with telemetry.span("compute"):
            pass
    telemetry.disable()

    doc = load_trace(str(path))
    json.dumps(doc)  # must be valid JSON end to end
    assert set(doc) == {"traceEvents"}
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {m["name"] for m in meta} == {"process_name", "thread_name"}
    evs = {e["name"]: e for e in spans}
    assert set(evs) == {"window.test", "assemble", "compute"}
    for e in spans:
        # Chrome-trace complete events: microsecond ts/dur, pid/tid.
        assert isinstance(e["ts"], int) and isinstance(e["dur"], int)
        assert "pid" in e and "tid" in e
    win = evs["window.test"]
    assert win["args"] == {"events": 3}
    for child in ("assemble", "compute"):
        c = evs[child]
        assert win["ts"] <= c["ts"]
        # +1 µs tolerance for the independent ns→µs floor of ts and dur.
        assert c["ts"] + c["dur"] <= win["ts"] + win["dur"] + 1


def test_window_spans_feed_latency_histogram():
    telemetry.enable()
    with telemetry.span("window.knn"):
        pass
    with telemetry.span("assemble"):  # non-window span: not a latency
        pass
    assert telemetry.window_latency.count == 1
    s = telemetry.summary()
    assert s["window_latency_p50_ms"] is not None
    assert s["window_latency_p95_ms"] is not None


def test_event_buffer_caps_and_counts_drops():
    telemetry.enable()
    telemetry.max_events = 4
    for i in range(6):
        with telemetry.span(f"s{i}"):
            pass
    assert len(telemetry.events) == 4
    assert telemetry.dropped_events == 2


def test_trace_file_roundtrip_and_drop_counter_pinned(tmp_path):
    """The in-memory buffer caps at max_events (drops COUNTED, exported
    in snapshot()); the trace FILE keeps every event — the cap bounds
    memory, not the artifact. load_trace round-trips what _write_trace
    wrote, in emit order."""
    path = tmp_path / "cap.jsonl"
    telemetry.enable(trace_path=str(path))
    telemetry.max_events = 2
    for i in range(5):
        with telemetry.span(f"s{i}"):
            pass
    assert len(telemetry.events) == 2
    assert telemetry.dropped_events == 3
    assert telemetry.snapshot()["dropped_events"] == 3
    telemetry.disable()

    doc = load_trace(str(path))
    json.dumps(doc)
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert [e["name"] for e in spans] == [f"s{i}" for i in range(5)]
    # Buffered events and file events agree where both exist.
    assert spans[:2] == telemetry.events


def test_disable_mid_span_exit_is_silent(tmp_path):
    """A span open across disable() must exit silently (the _emit_span
    early return): no raise — the trace file is already closed — no
    event, no latency observation."""
    telemetry.enable(trace_path=str(tmp_path / "mid.jsonl"))
    sp = telemetry.span("window.mid")
    sp.__enter__()
    telemetry.disable()
    assert sp.__exit__(None, None, None) is False  # and no exception
    assert all(e.get("name") != "window.mid" for e in telemetry.events)
    assert telemetry.window_latency.count == 0


def test_trace_metadata_names_process_and_threads(tmp_path):
    """ph:"M" metadata: process_name once per pid (at enable), thread_name
    once per NEW tid at its first event — so Perfetto rows carry thread
    names instead of raw idents."""
    import threading

    path = tmp_path / "meta.jsonl"
    telemetry.enable(trace_path=str(path))
    with telemetry.span("window.a"):
        pass
    with telemetry.span("window.b"):
        pass

    def emit():
        with telemetry.span("window.worker"):
            pass

    t = threading.Thread(target=emit, name="op-worker")
    t.start()
    t.join()
    telemetry.disable()

    evs = load_trace(str(path))["traceEvents"]
    procs = [e for e in evs if e["ph"] == "M"
             and e["name"] == "process_name"]
    threads = [e for e in evs if e["ph"] == "M"
               and e["name"] == "thread_name"]
    assert len(procs) == 1  # once per pid
    assert procs[0]["args"]["name"].startswith("spatialflink_tpu:")
    assert len(threads) == 2  # once per tid, not per event
    names = {e["tid"]: e["args"]["name"] for e in threads}
    assert "op-worker" in names.values()
    # Each thread_name precedes that tid's first span in file order.
    for tid, _name in names.items():
        first_meta = next(i for i, e in enumerate(evs)
                          if e["ph"] == "M" and e.get("tid") == tid)
        first_span = next(i for i, e in enumerate(evs)
                          if e["ph"] == "X" and e.get("tid") == tid)
        assert first_meta < first_span


def test_account_d2h_emits_counter_event_like_h2d(tmp_path):
    """The counter-event symmetry: account_d2h emits the same ph:"C"
    running-total counter account_h2d does, so device→host traffic is
    visible in Perfetto too (it used to update totals invisibly)."""
    path = tmp_path / "counters.jsonl"
    telemetry.enable(trace_path=str(path))
    telemetry.account_h2d(64)
    telemetry.account_d2h(128)
    telemetry.account_d2h(128)
    telemetry.disable()

    counters = [e for e in load_trace(str(path))["traceEvents"]
                if e["ph"] == "C"]
    h2d = [e["args"]["bytes"] for e in counters
           if e["name"] == "h2d_bytes"]
    d2h = [e["args"]["bytes"] for e in counters
           if e["name"] == "d2h_bytes"]
    assert h2d == [64]
    assert d2h == [128, 256]  # running totals, mirroring h2d


# -- recompile detection ------------------------------------------------------


def test_recompile_detector_two_bucket_sizes_two_events():
    telemetry.enable()
    f = instrument_jit(jax.jit(lambda x: x * 2), name="double")
    f(jnp.ones((64,), jnp.float32))
    f(jnp.ones((64,), jnp.float32))  # same abstract shape → no new event
    assert telemetry.compile_count == 1
    f(jnp.ones((128,), jnp.float32))  # bucket growth → second compile
    assert telemetry.compile_count == 2
    assert telemetry.distinct_shapes("double") == 2
    kernels = [k for k, _ in telemetry.compile_events]
    assert kernels == ["double", "double"]


def test_recompile_threshold_warns_once():
    telemetry.enable(recompile_warn_threshold=3)
    f = instrument_jit(jax.jit(lambda x: x + 1), name="churny")
    with warnings.catch_warnings():
        warnings.simplefilter("error", RecompileWarning)
        f(jnp.ones((8,), jnp.float32))
        f(jnp.ones((16,), jnp.float32))  # below threshold: silent
    with pytest.warns(RecompileWarning, match="churny"):
        f(jnp.ones((32,), jnp.float32))  # crosses threshold
    with warnings.catch_warnings():
        warnings.simplefilter("error", RecompileWarning)
        f(jnp.ones((64,), jnp.float32))  # warned already: once per kernel


def test_recompile_detector_sees_tuple_arg_shape_churn():
    """Container args recurse: the knn pane digests arrive as tuples of
    arrays, and repadding every element to a grown nseg is a REAL jit
    recompile — a signature that collapsed tuples to 'tuple' would record
    one compile forever and the detector would miss its primary target."""
    telemetry.enable()
    f = instrument_jit(
        jax.jit(lambda xs, bases: sum(xs) + bases), name="merge"
    )
    small = tuple(jnp.ones((64,), jnp.float32) for _ in range(2))
    grown = tuple(jnp.ones((128,), jnp.float32) for _ in range(2))
    bases = jnp.zeros((), jnp.float32)
    f(small, bases)
    f(small, bases)  # same leaf avals → no new event
    assert telemetry.compile_count == 1
    f(grown, bases)  # every tuple element repadded → second compile
    assert telemetry.compile_count == 2
    assert telemetry.distinct_shapes("merge") == 2


def test_abstract_signature_statics_and_dtypes():
    a64 = np.zeros((4, 2), np.float32)
    assert abstract_signature((a64,)) == abstract_signature(
        (np.ones((4, 2), np.float32),)
    )  # values don't key the cache, avals do
    assert abstract_signature((a64,)) != abstract_signature(
        (np.zeros((4, 2), np.float64),)
    )  # dtype does
    # kwargs are static arguments: the VALUE keys the compile cache.
    assert abstract_signature((), {"k": 5}) != abstract_signature(
        (), {"k": 6}
    )
    # kwarg CONTAINERS of arrays contribute avals, not repr — repr
    # would materialize the arrays (a device fetch per call; the pane
    # scan's lps_expire tuples hit this)
    t1 = (np.zeros((8, 4), np.int32), np.zeros((8, 4), bool))
    t2 = (np.ones((8, 4), np.int32), np.ones((8, 4), bool))
    assert abstract_signature((), {"e": t1}) == abstract_signature(
        (), {"e": t2}
    )  # same avals, different values → one compile
    assert abstract_signature((), {"e": t1}) != abstract_signature(
        (), {"e": (np.zeros((4, 4), np.int32), np.zeros((4, 4), bool))}
    )


def test_instrument_jit_passes_attributes_through():
    jf = jax.jit(lambda x: x + 1)
    f = instrument_jit(jf, name="attrs")
    assert f.lower is jf.lower


# -- device-boundary accounting -----------------------------------------------


def test_fetch_accounts_bytes_and_emits_event():
    telemetry.enable()
    x = jnp.arange(1024, dtype=jnp.float32)
    out = telemetry.fetch((x, x))
    np.testing.assert_array_equal(out[0], np.arange(1024, dtype=np.float32))
    assert telemetry.d2h_transfers == 1
    assert telemetry.d2h_bytes == 2 * 1024 * 4
    (ev,) = [e for e in telemetry.events if e["name"] == "fetch"]
    assert ev["args"]["bytes"] == 2 * 1024 * 4


def test_operator_ship_path_accounts_h2d():
    telemetry.enable()
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10,
                              slide_step=5)
    op = PointPointRangeQuery(conf, GRID)
    op.device_q(np.zeros((16, 2)), np.float32)
    assert telemetry.h2d_transfers == 1
    assert telemetry.h2d_bytes == 16 * 2 * 4  # float32 after centering cast
    # Batch-metadata lanes (valid/cell/oid) count too — the AoS window
    # paths ship them alongside the coordinates.
    base_mod.ship(np.ones(16, bool), np.zeros(16, np.int32))
    assert telemetry.h2d_bytes == 16 * 2 * 4 + 16 + 16 * 4


# -- watermark / lateness gauges ----------------------------------------------


def _soa_chunk(*ts):
    a = np.asarray(ts, np.int64)
    return {
        "ts": a,
        "x": np.zeros(len(a)),
        "y": np.zeros(len(a)),
        "oid": np.zeros(len(a), np.int32),
    }


def test_soa_assembler_feeds_gauges():
    telemetry.enable()
    asm = SoaWindowAssembler(10, 5)
    asm.feed(_soa_chunk(1, 3, 9))
    asm.feed(_soa_chunk(27))  # fires [0,10) at wm=27 → lag 17
    assert telemetry.max_watermark_lag_ms == 17
    asm.feed(_soa_chunk(2))  # older than every live window
    asm.feed(_soa_chunk(38))  # next consolidation trims+counts the drop
    assert asm.dropped_late == 1
    assert telemetry.late_drops == 1
    assert telemetry.max_watermark_lag_ms == 17
    # flush()'s artificial end-of-stream watermark must not spike the lag
    # gauge.
    asm.flush()
    assert telemetry.max_watermark_lag_ms == 17


def test_object_assembler_feeds_gauges():
    telemetry.enable()
    asm = WindowAssembler(
        TumblingEventTimeWindows(10), timestamp_fn=lambda e: e.timestamp
    )
    asm.feed(Point(obj_id="a", timestamp=1, x=0.0, y=0.0))
    fired = asm.feed(Point(obj_id="a", timestamp=25, x=0.0, y=0.0))
    assert len(fired) == 1  # [0,10) fired at wm=25
    assert telemetry.max_watermark_lag_ms == 15
    asm.feed(Point(obj_id="a", timestamp=2, x=0.0, y=0.0))  # dropped late
    assert telemetry.late_drops == 1


# -- telemetry must never change results --------------------------------------


def test_range_query_results_identical_with_telemetry(rng, tmp_path):
    conf = QueryConfiguration(QueryType.WindowBased, window_size=10,
                              slide_step=5)
    pts = [
        Point(obj_id=f"d{i % 7}", timestamp=int(i * 75),
              x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
        for i in range(400)
    ]
    q = Point(x=5.0, y=5.0)

    def run():
        return [
            (r.start, r.end, sorted(id(o) for o in r.objects))
            for r in PointPointRangeQuery(conf, GRID).run(iter(pts), [q], 2.0)
        ]

    baseline = run()
    telemetry.enable(trace_path=str(tmp_path / "range_trace.jsonl"))
    instrumented = run()
    telemetry.disable()
    assert instrumented == baseline

    # The per-window phase spans landed, nested under window.range, and
    # the trace is loadable.
    doc = load_trace(str(tmp_path / "range_trace.jsonl"))
    names = [e["name"] for e in doc["traceEvents"]]
    assert "window.range" in names
    for phase in ("assemble", "ship", "compute", "fetch"):
        assert phase in names, phase
    assert telemetry.window_latency.count == names.count("window.range")
    # Instrumentation rides the operator's own fetches, never adds one:
    # exactly one counted d2h transfer per "fetch" phase span (the byte-
    # carrying fetch events and the phase spans share the name; tell them
    # apart by the args payload).
    fetch_spans = [e for e in doc["traceEvents"]
                   if e["name"] == "fetch" and "bytes" not in e.get("args", {})]
    assert telemetry.d2h_transfers == len(fetch_spans)
    assert telemetry.h2d_bytes > 0 and telemetry.d2h_bytes > 0


# -- export ------------------------------------------------------------------


def test_summary_is_json_safe_and_has_bench_fields():
    telemetry.enable()
    telemetry.account_h2d(np.int64(4096))  # numpy scalars at the boundary
    telemetry.record_watermark_lag(np.int32(12))
    s = telemetry.summary()
    json.dumps(s)  # must never raise
    assert set(s) >= {
        "compiles", "bytes_h2d", "bytes_d2h", "window_latency_p50_ms",
        "window_latency_p95_ms", "max_watermark_lag_ms", "late_dropped",
    }
    assert type(s["bytes_h2d"]) is int and s["bytes_h2d"] == 4096
    assert s["max_watermark_lag_ms"] == 12
    # Empty histogram percentiles export as None, not NaN (strict JSON).
    assert s["window_latency_p50_ms"] is None
    assert "NaN" not in json.dumps(s)
    json.dumps(telemetry.snapshot())


def test_register_metrics_exports_gauges():
    telemetry.enable()
    telemetry.record_watermark_lag(33)
    telemetry.record_late_drop(2)
    telemetry.account_h2d(128)
    reg = MetricRegistry()
    telemetry.register_metrics(reg)
    snap = reg.snapshot()
    assert snap["watermark_lag_ms_max"] == 33
    assert snap["late_dropped_total"] == 2
    assert snap["h2d_bytes_total"] == 128
    json.dumps(snap)


def test_reporter_line_gains_telemetry_columns(tmp_path):
    from spatialflink_tpu.mn import MetricRegistry, NESFileReporter

    telemetry.enable()
    telemetry.record_watermark_lag(21)
    telemetry.record_late_drop(3)
    rep = NESFileReporter(MetricRegistry(), "qtel", out_dir=str(tmp_path))
    line = rep.report(now=1_700_000_000.0)
    assert "watermark_lag_ms_max=21" in line
    assert "late_dropped_total=3" in line
    assert "compiles_total=0" in line
    telemetry.disable()
    # Off → the reference's exact column set, no telemetry columns.
    line = rep.report(now=1_700_000_001.0)
    assert "watermark_lag_ms_max" not in line
