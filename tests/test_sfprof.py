"""sfprof tests — the per-kernel runtime table + lazy cost capture
(telemetry side), the run-ledger schema, span attribution, and the CLI
contracts (report / diff --gate / health exit codes)."""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spatialflink_tpu.telemetry import (
    LEDGER_VERSION,
    instrument_jit,
    telemetry,
)
from tools.sfprof import attribution
from tools.sfprof import ledger as ledger_mod
from tools.sfprof.cli import compare, main as sfprof_main


@pytest.fixture(autouse=True)
def _telemetry_off():
    """Leave the process-global singleton disabled AND reset: this file
    runs before test_telemetry.py, whose disabled-by-default test asserts
    the pristine zero counters (disable() alone keeps state readable)."""
    cap = telemetry.max_events
    yield
    telemetry.max_events = cap
    telemetry.enable()  # enable() resets all state...
    telemetry.disable()  # ...and leave it off for the next test


# -- per-kernel runtime table -------------------------------------------------


def test_kernel_table_counts_dispatch_and_first_call():
    telemetry.enable()
    f = instrument_jit(jax.jit(lambda x: x * 2 + 1), name="twice")
    f(jnp.ones((64,), jnp.float32))
    f(jnp.ones((64,), jnp.float32))
    f(jnp.ones((128,), jnp.float32))
    rows = telemetry.kernel_table()
    assert len(rows) == 2  # one row per (kernel, signature)
    (r64,) = [r for r in rows if "(64,)" in r["signature"]]
    (r128,) = [r for r in rows if "(128,)" in r["signature"]]
    assert r64["kernel"] == "twice" and r64["calls"] == 2
    assert r128["calls"] == 1
    # First call includes the XLA compile; cumulative >= first > 0.
    assert r64["dispatch_ns"] >= r64["first_call_ns"] > 0
    assert r64["cost"] is None  # lazy — nothing captured on the hot path
    json.dumps(rows)  # JSON-safe as exported


def test_disabled_is_a_noop():
    telemetry.enable()
    telemetry.disable()  # enable() resets state; leave it clean AND off
    f = instrument_jit(jax.jit(lambda x: x + 1), name="off")
    f(jnp.ones((8,), jnp.float32))
    assert telemetry.kernel_table() == []
    telemetry.capture_costs()  # no state, no raise
    assert telemetry.kernel_table() == []


def test_cost_capture_flops_bytes_zero_device_round_trips():
    telemetry.enable()
    f = instrument_jit(jax.jit(lambda x: (x @ x).sum()), name="mm")
    f(jnp.ones((32, 32), jnp.float32))
    h2d, d2h = telemetry.h2d_transfers, telemetry.d2h_transfers
    # AOT lower/compile from stashed avals: any implicit transfer in
    # either direction would trip the guard.
    with jax.transfer_guard("disallow"):
        telemetry.capture_costs()
    (row,) = telemetry.kernel_table()
    cost = row["cost"]
    assert "error" not in cost
    assert cost["flops"] > 0  # XLA:CPU cost analysis delivers flops
    assert cost["bytes_accessed"] > 0
    assert cost["peak_memory_bytes"] > 0
    assert telemetry.h2d_transfers == h2d
    assert telemetry.d2h_transfers == d2h
    telemetry.capture_costs()  # idempotent: costs captured once
    (row2,) = telemetry.kernel_table()
    assert row2["cost"] == cost


def test_cost_capture_through_jitted_statics():
    """operators/base.py:jitted routes statics as kwargs via partial —
    the deferred lowering must replay them as static values, arrays as
    avals."""
    from spatialflink_tpu.operators.base import jitted

    telemetry.enable()

    def scaled_sum(x, *, k):
        return (x * k).sum()

    f = jitted(scaled_sum, "k")
    f(jnp.ones((16,), jnp.float32), k=3)
    telemetry.capture_costs()
    (row,) = [r for r in telemetry.kernel_table()
              if r["kernel"] == "scaled_sum"]
    assert "error" not in row["cost"]
    assert row["cost"]["flops"] > 0


def test_cost_capture_namedtuple_args():
    """Pane-scan kernels take NamedTuple carries positionally; the
    deferred-lowering aval mirror must rebuild them via the positional
    ctor (a NamedTuple rejects the single-iterable tuple ctor), or cost
    capture silently dies for exactly the flagship kernels."""
    from typing import NamedTuple

    class Carry(NamedTuple):
        seg: object
        rep: object

    telemetry.enable()

    def step(carry, x):
        return Carry(carry.seg + x.sum(), carry.rep), x * 2

    f = instrument_jit(jax.jit(step), name="nt_step")
    c = Carry(jnp.float32(0.0), jnp.int32(0))
    f(c, jnp.ones((16,), jnp.float32))
    with jax.transfer_guard("disallow"):
        telemetry.capture_costs()
    (row,) = [r for r in telemetry.kernel_table()
              if r["kernel"] == "nt_step"]
    assert row["cost"] and "error" not in row["cost"]
    assert row["cost"]["flops"] > 0


def test_cost_capture_dict_args_and_no_buffer_pinning():
    """Dict-of-array args recurse to avals like tuples do; an arbitrary
    object that could hide a device buffer makes _lower_ctx give up
    (cost honestly unavailable) instead of pinning it in the table."""
    telemetry.enable()
    f = instrument_jit(jax.jit(lambda d: d["x"] * 2 + d["y"]),
                       name="dicty")
    f({"x": jnp.ones((16,), jnp.float32),
       "y": jnp.ones((16,), jnp.float32)})
    with jax.transfer_guard("disallow"):
        telemetry.capture_costs()
    (row,) = [r for r in telemetry.kernel_table()
              if r["kernel"] == "dicty"]
    assert row["cost"] and "error" not in row["cost"]

    from spatialflink_tpu.telemetry import _lower_ctx

    class Opaque:
        pass

    jf = jax.jit(lambda x: x)
    assert _lower_ctx(jf, (Opaque(),), {}) is None


def test_uninstrumentable_callable_records_error_not_crash():
    telemetry.enable()
    f = instrument_jit(lambda x: np.asarray(x) + 1, name="plain")
    f(np.ones(4, np.float32))
    telemetry.capture_costs()
    (row,) = telemetry.kernel_table()
    # A plain callable has no AOT surface: cost stays honest-unavailable.
    assert row["cost"] is None or "error" in row["cost"]


# -- run ledger ---------------------------------------------------------------


def _make_ledger(tmp_path, name="ledger.json", bench=None):
    telemetry.enable()
    f = instrument_jit(jax.jit(lambda x: x * 2), name="double")
    with telemetry.span("window.demo", window=0):
        with telemetry.span("assemble"):
            pass
        with telemetry.span("ship"):
            pass
        with telemetry.span("compute"):
            f(jnp.ones((64,), jnp.float32))
        with telemetry.span("fetch"):
            telemetry.fetch(jnp.ones((64,), jnp.float32))
    if bench is None:
        bench = {
            "config": "continuous_knn_k50_5s_sliding",
            "points_per_sec": 70_000_000.0,
            "device_resident_points_per_sec": 100_000_000.0,
            "value": 70_000_000.0,
        }
    path = str(tmp_path / name)
    telemetry.write_ledger(path, bench=bench)
    telemetry.disable()
    return path


def test_ledger_version_constants_in_sync():
    """Writer (telemetry) and validator (tools/sfprof) deliberately don't
    import each other — this is the cross-pin both files point at."""
    assert ledger_mod.LEDGER_VERSION == LEDGER_VERSION


def test_ledger_schema_valid_and_complete(tmp_path):
    path = _make_ledger(tmp_path)
    doc = ledger_mod.load(path)
    assert ledger_mod.validate(doc) == []
    assert doc["ledger_version"] == LEDGER_VERSION
    assert doc["env"]["backend"] == "cpu"
    assert doc["env"]["jax"] == jax.__version__
    assert doc["snapshot"]["bytes_d2h"] > 0
    names = [e["name"] for e in doc["events"]]
    assert "window.demo" in names
    (row,) = [r for r in doc["kernels"] if r["kernel"] == "double"]
    # write_ledger captured costs lazily on the way out.
    assert row["cost"] and row["cost"].get("flops", 0) > 0


def test_validate_flags_broken_documents(tmp_path):
    path = _make_ledger(tmp_path)
    doc = ledger_mod.load(path)

    missing = {k: v for k, v in doc.items() if k != "snapshot"}
    assert any("snapshot" in p for p in ledger_mod.validate(missing))

    wrong_ver = dict(doc, ledger_version=LEDGER_VERSION + 1)
    assert any("ledger_version" in p
               for p in ledger_mod.validate(wrong_ver))

    # The fstring-numpy bug class: a numpy scalar repr in a string field.
    leaked = dict(doc, bench={"note": "rate was np.float32(1234.5)"})
    assert any("numpy scalar repr" in p
               for p in ledger_mod.validate(leaked))

    assert ledger_mod.validate([1, 2]) == ["ledger is not a JSON object"]


def test_write_ledger_sanitizes_nonfinite(tmp_path):
    """Regression: a NaN/Inf in the bench record used to raise out of
    write_ledger (allow_nan=False) at the very END of a run — losing the
    whole capture. Non-finite floats now become null, counted in the
    ``nonfinite_values`` warning field, and the document stays
    schema-valid."""
    telemetry.enable()
    path = telemetry.write_ledger(
        str(tmp_path / "nan.json"),
        bench={"value": float("nan"), "rate": float("inf"),
               "series": [1.0, float("-inf"), 3.0], "fine": 7.0},
    )
    doc = ledger_mod.load(path)
    assert ledger_mod.validate(doc) == []
    assert doc["bench"]["value"] is None
    assert doc["bench"]["rate"] is None
    assert doc["bench"]["series"] == [1.0, None, 3.0]
    assert doc["bench"]["fine"] == 7.0
    assert doc["nonfinite_values"] == 3
    # A clean ledger carries no warning field at all.
    clean = ledger_mod.load(_make_ledger(tmp_path, name="clean.json"))
    assert "nonfinite_values" not in clean


def test_load_any_accepts_trace_shapes(tmp_path):
    # JSON-lines trace (the SFT_TRACE_PATH format).
    jl = tmp_path / "t.jsonl"
    evs = [{"name": "window.x", "ph": "X", "ts": 0, "dur": 5,
            "pid": 1, "tid": 1},
           {"name": "compute", "ph": "X", "ts": 1, "dur": 3,
            "pid": 1, "tid": 1}]
    jl.write_text("".join(json.dumps(e) + "\n" for e in evs))
    doc, events = ledger_mod.load_any(str(jl))
    assert doc is None and len(events) == 2
    # {"traceEvents": [...]} document.
    td = tmp_path / "t.json"
    td.write_text(json.dumps({"traceEvents": evs}))
    doc, events = ledger_mod.load_any(str(td))
    assert doc is None and len(events) == 2
    # Ledger.
    lp = _make_ledger(tmp_path)
    doc, events = ledger_mod.load_any(lp)
    assert doc is not None and events == doc["events"]


# -- span attribution ---------------------------------------------------------


def _ev(name, ts, dur, tid=1):
    return {"name": name, "ph": "X", "ts": ts, "dur": dur,
            "pid": 7, "tid": tid}


def test_attribution_phases_residue_and_nesting():
    events = [
        _ev("window.knn", 0, 100),
        _ev("assemble", 0, 30),
        _ev("compute", 30, 50),
        _ev("pane.digest", 35, 10),  # nested in compute: not re-counted
        _ev("fetch", 85, 10),
    ]
    windows, ops = attribution.attribute_windows(events)
    (w,) = windows
    assert w["operator"] == "window.knn"
    assert w["phases"] == {"assemble": 30, "compute": 50, "fetch": 10}
    assert w["unattributed_us"] == 10  # 80..85 — reported, never silent
    assert w["attributed_frac"] == pytest.approx(0.9)
    agg = ops["window.knn"]
    assert agg["windows"] == 1 and agg["dur_us"] == 100
    assert (sum(agg["phases"].values()) + agg["unattributed_us"]
            == agg["dur_us"])


def test_attribution_separates_threads_and_windows():
    events = [
        _ev("window.a", 0, 50, tid=1),
        _ev("compute", 0, 50, tid=1),
        _ev("window.a", 100, 50, tid=1),
        _ev("compute", 100, 25, tid=1),
        # Same ts range on ANOTHER thread: not a child of tid=1 windows.
        _ev("compute", 0, 40, tid=2),
    ]
    windows, ops = attribution.attribute_windows(events)
    assert len(windows) == 2
    assert ops["window.a"]["windows"] == 2
    assert ops["window.a"]["phases"]["compute"] == 75
    assert ops["window.a"]["unattributed_us"] == 25


def test_host_gap_detection():
    events = [
        _ev("window.a", 0, 50),
        _ev("window.a", 90, 50),   # 40 µs host gap
        _ev("window.a", 141, 50),  # 1 µs gap
    ]
    gaps = attribution.host_gaps(events)
    assert [g["gap_us"] for g in gaps] == [40, 1]
    assert gaps[0]["after"] == "window.a"


# -- CLI: report --------------------------------------------------------------


def test_report_cli_on_ledger(tmp_path, capsys):
    path = _make_ledger(tmp_path)
    assert sfprof_main(["report", path]) == 0
    out = capsys.readouterr().out
    assert "window.demo" in out
    for phase in ("assemble", "ship", "compute", "fetch"):
        assert phase in out
    assert "unattributed" in out  # the residue is always reported
    assert "double" in out  # kernel table rendered
    assert "np." not in out  # egress stays numpy-repr-free


def test_report_cli_on_raw_trace(tmp_path, capsys):
    jl = tmp_path / "t.jsonl"
    jl.write_text(json.dumps(_ev("window.x", 0, 10)) + "\n"
                  + json.dumps(_ev("compute", 0, 9)) + "\n")
    assert sfprof_main(["report", str(jl)]) == 0
    out = capsys.readouterr().out
    assert "window.x" in out and "compute" in out


def test_report_cli_unreadable_input(tmp_path, capsys):
    assert sfprof_main(["report", str(tmp_path / "absent.json")]) == 2


def test_report_and_health_json_on_real_ledger(tmp_path, capsys):
    """--json on a ledger telemetry actually wrote (not a synthetic
    fixture): parseable single document, roofline verdict present,
    checks mirrored, exit codes unchanged."""
    path = _make_ledger(tmp_path)
    assert sfprof_main(["report", path, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["roofline"]["verdict"] in (
        "link-bound", "host-bound", "dispatch-bound", "compute-bound",
        "memory-bound", "inconclusive")
    assert rep["roofline"]["evidence"]
    assert "window.demo" in rep["attribution"]["operators"]
    assert any(r["kernel"] == "double" for r in rep["kernels"])
    assert sfprof_main(["health", path, "--json"]) == 0
    hea = json.loads(capsys.readouterr().out)
    assert hea["failed"] == 0 and hea["tainted"] is None
    assert hea["roofline"]["verdict"] == rep["roofline"]["verdict"]
    assert {c["name"] for c in hea["checks"]} >= {
        "recompile_churn_max_signatures", "late_dropped",
        "max_watermark_lag_ms", "dropped_trace_events"}


# -- CLI: diff / gate ---------------------------------------------------------


def test_diff_gate_self_diff_exits_zero(tmp_path):
    path = _make_ledger(tmp_path)
    assert sfprof_main(["diff", path, path, "--gate"]) == 0


def test_diff_gate_flags_injected_eps_regression(tmp_path, capsys):
    path = _make_ledger(tmp_path)
    doc = ledger_mod.load(path)
    bad = dict(doc)
    bad["bench"] = dict(doc["bench"])
    bad["bench"]["points_per_sec"] = doc["bench"]["points_per_sec"] / 10
    bad["bench"]["value"] = doc["bench"]["value"] / 10
    bad_path = tmp_path / "bad.json"
    bad_path.write_text(json.dumps(bad))

    assert sfprof_main(["diff", path, str(bad_path), "--gate"]) == 1
    out = capsys.readouterr().out
    assert "regression" in out
    # Without --gate the same diff is informational: exit 0.
    assert sfprof_main(["diff", path, str(bad_path)]) == 0
    # Inside the ±50% band: not a regression.
    near = dict(doc)
    near["bench"] = dict(doc["bench"],
                         points_per_sec=doc["bench"]["points_per_sec"] * 0.7,
                         value=doc["bench"]["value"] * 0.7)
    near_path = tmp_path / "near.json"
    near_path.write_text(json.dumps(near))
    assert sfprof_main(["diff", path, str(near_path), "--gate"]) == 0


def test_diff_latency_and_counter_bands(tmp_path):
    path = _make_ledger(tmp_path)
    doc = ledger_mod.load(path)
    bad = dict(doc)
    bad["snapshot"] = dict(doc["snapshot"])
    bad["snapshot"]["window_latency_p50_ms"] = 1e6  # far past 2x + 1ms
    bad["snapshot"]["dropped_events"] = 99  # any increase regresses
    bad_path = tmp_path / "slow.json"
    bad_path.write_text(json.dumps(bad))
    rows = compare(doc, ledger_mod.load(str(bad_path)),
                   eps_tol=0.5, lat_tol=1.0)
    verdicts = {r["name"]: r["verdict"] for r in rows}
    assert verdicts["snapshot.window_latency_p50_ms"] == "regression"
    assert verdicts["snapshot.dropped_events"] == "regression"
    assert sfprof_main(["diff", path, str(bad_path), "--gate"]) == 1


def test_diff_gate_fails_when_candidate_loses_a_metric(tmp_path):
    """A gateable metric the candidate ledger LOST entirely (broken
    telemetry, truncated bench block) must gate as a regression — the
    gate cannot pass on silence. Metrics new in B stay informational."""
    path = _make_ledger(tmp_path)
    doc = ledger_mod.load(path)
    lost = json.loads(json.dumps(doc))
    del lost["bench"]["points_per_sec"]
    lost_path = tmp_path / "lost.json"
    lost_path.write_text(json.dumps(lost))
    assert sfprof_main(["diff", path, str(lost_path), "--gate"]) == 1
    # The reverse direction — B gained a metric A lacks — is fine.
    assert sfprof_main(["diff", str(lost_path), path, "--gate"]) == 0


def test_diff_link_annotation_never_gates(tmp_path, capsys):
    """Link-probe gauges ANNOTATE a diff (tunnel degraded vs chip slow)
    but never gate it, and never widen the bands: two ledgers identical
    except for a 2x-degraded link must still self-diff clean — with the
    degradation called out in the output."""
    path = _make_ledger(tmp_path)
    doc = ledger_mod.load(path)
    for name, bw in (("fast.json", 28.0), ("slow_link.json", 11.0)):
        d = json.loads(json.dumps(doc))
        d["snapshot"]["link_probe"] = {
            "samples": 8, "latency_ms_p50": 1.0, "latency_ms_last": 1.0,
            "roundtrip_mbps_p50": bw, "roundtrip_mbps_last": bw,
            "payload_bytes": 262144,
        }
        (tmp_path / name).write_text(json.dumps(d))
    fast, slow = str(tmp_path / "fast.json"), str(tmp_path / "slow_link.json")
    assert sfprof_main(["diff", fast, slow, "--gate"]) == 0  # not gated
    out = capsys.readouterr().out
    assert "DEGRADED" in out and "tunnel" in out
    assert sfprof_main(["diff", fast, fast, "--gate"]) == 0
    assert "comparable tunnels" in capsys.readouterr().out


def test_diff_guards_cpu_baseline_medians(tmp_path):
    """A candidate EPS below the CPU_BASELINE median band is a NEW
    regression when the reference ledger was inside the band — but a
    self-diff of an already-slow ledger stays informational (the gate
    is monotone; acceptance: self-diff exits 0)."""
    baseline = {"configs": {"cfg_x": 1_000_000.0},
                "configs_resident": {}}
    bl_path = tmp_path / "CPU_BASELINE.json"
    bl_path.write_text(json.dumps(baseline))

    def ledger_with_eps(name, eps):
        bench = {"config": "cfg_x", "points_per_sec": eps, "value": eps}
        return _make_ledger(tmp_path, name=name, bench=bench)

    good = ledger_with_eps("good.json", 950_000.0)   # inside band
    slow = ledger_with_eps("slow.json", 200_000.0)   # below median/2
    args = ["--gate", "--baseline", str(bl_path), "--eps-tol", "0.5"]
    assert sfprof_main(["diff", good, slow] + args) == 1
    assert sfprof_main(["diff", slow, slow] + args) == 0  # pre-existing
    assert sfprof_main(["diff", good, good] + args) == 0


# -- CLI: health --------------------------------------------------------------


def test_health_clean_ledger_exits_zero(tmp_path, capsys):
    path = _make_ledger(tmp_path)
    assert sfprof_main(["health", path]) == 0
    out = capsys.readouterr().out
    assert "0 failed" in out


def test_health_flags_each_pathology(tmp_path):
    path = _make_ledger(tmp_path)
    doc = ledger_mod.load(path)

    def write(mut, name):
        bad = json.loads(json.dumps(doc))
        mut(bad)
        p = tmp_path / name
        p.write_text(json.dumps(bad))
        return str(p)

    churn = write(lambda d: d["snapshot"]["kernels"].update(spin=64),
                  "churn.json")
    assert sfprof_main(["health", churn]) == 1
    dropped = write(lambda d: d["snapshot"].update(dropped_events=7),
                    "dropped.json")
    assert sfprof_main(["health", dropped]) == 1
    late = write(lambda d: d["snapshot"].update(late_dropped=3),
                 "late.json")
    assert sfprof_main(["health", late]) == 1
    lag = write(lambda d: d["snapshot"].update(max_watermark_lag_ms=99_999),
                "lag.json")
    assert sfprof_main(["health", lag]) == 1
    over = write(lambda d: d["bench"].update(cmp_overflow=2), "over.json")
    assert sfprof_main(["health", over]) == 1
    # Thresholds are arguments: the same churn passes a higher bar.
    assert sfprof_main(["health", churn,
                        "--recompile-threshold", "100"]) == 0
    # An invalid document fails health outright.
    broken = write(lambda d: d.pop("kernels"), "broken.json")
    assert sfprof_main(["health", broken]) == 1


# -- instrumentation must not leak across threads -----------------------------


def test_kernel_table_thread_safe_updates():
    telemetry.enable()
    f = instrument_jit(jax.jit(lambda x: x + 1), name="mt")
    x = jnp.ones((32,), jnp.float32)
    f(x)  # compile once before the race

    def worker():
        for _ in range(50):
            f(x)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    (row,) = telemetry.kernel_table()
    assert row["calls"] == 1 + 4 * 50


def test_report_and_health_collective_split(tmp_path, capsys):
    """The per-kind collective classes (halo vs gather vs reduce) and
    the replication-ratio line (collective bytes / boundary-state
    bytes the halo wrappers declared) — text, --json, and health
    notes all carry the same split."""
    telemetry.enable()
    telemetry.account_collective("ppermute", 6_000, axis="data", calls=6)
    telemetry.account_collective("all_gather", 80_000, axis="data",
                                 calls=4)
    telemetry.account_collective("psum", 64, axis="data", calls=2)
    telemetry.account_halo_state(3_000)
    path = str(tmp_path / "halo_ledger.json")
    telemetry.write_ledger(path, bench={
        "config": "range_8shard_halo", "points_per_sec": 50_000.0,
        "value": 50_000.0,
    })
    telemetry.disable()

    assert sfprof_main(["report", path]) == 0
    out = capsys.readouterr().out
    assert "by class" in out
    assert "halo=" in out and "gather=" in out and "reduce=" in out
    assert "replication ratio" in out
    assert "boundary-pane state" in out  # the ↳ evidence line

    assert sfprof_main(["report", path, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    split = rep["collective_split"]
    assert split["by_class"]["halo"]["bytes"] == 6_000
    assert split["by_class"]["halo"]["kinds"] == ["ppermute"]
    assert split["by_class"]["gather"]["bytes"] == 80_000
    assert split["by_class"]["reduce"]["bytes"] == 64
    assert split["halo_state_bytes"] == 3_000
    assert split["replication_ratio"] == pytest.approx(
        (6_000 + 80_000 + 64) / 3_000)

    assert sfprof_main(["health", path, "--json"]) == 0
    hea = json.loads(capsys.readouterr().out)
    assert hea["notes"]["collective_split"]["by_class"]["halo"][
        "bytes"] == 6_000
