"""dagmon — per-node telemetry attribution (telemetry.scope), the
conservation contract (node buckets sum EXACTLY to the untagged
globals), v1 byte-compat for un-scoped captures, node tags surviving
``sfprof recover``, and the ``sfprof live`` follower's exit-code
contract. Mesh-collective accounting parity lives with the sharded
parity tests (tests/test_parallel.py ``collectives`` fixture)."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from spatialflink_tpu import dag as dag_mod  # noqa: E402
from spatialflink_tpu import overload, qserve  # noqa: E402
from spatialflink_tpu.dag import build_sncb_dag, _toy_sncb_stream  # noqa: E402
from spatialflink_tpu.driver import (  # noqa: E402
    RetryPolicy,
    WindowedDataflowDriver,
)
from spatialflink_tpu.faults import faults  # noqa: E402
from spatialflink_tpu.telemetry import telemetry  # noqa: E402
from tools.sfprof import live as live_mod  # noqa: E402
from tools.sfprof import stream as stream_mod  # noqa: E402


SNCB_NODES = ("q1", "q2", "q3", "q4", "q5", "staytime", "qserve")

# Node-bucket counters with an untagged global twin: the sum over every
# bucket ("(unscoped)" included) must equal the global EXACTLY — tagging
# re-labels accounting, it never creates or loses any.
CONSERVED = ("h2d_bytes", "h2d_transfers", "d2h_bytes", "d2h_transfers",
             "compiles", "collective_bytes", "shed_events", "fault_fires")


@pytest.fixture(autouse=True)
def _clean():
    yield
    faults.disarm()
    telemetry.disable()
    dag_mod.uninstall()
    qserve.uninstall()
    overload.uninstall()


def _bucket_sums(rollup):
    return {k: sum(row.get(k, 0) for row in rollup.values())
            for k in CONSERVED + ("dispatch_ns", "kernel_calls")}


class TestConservation:
    def test_sncb_dag_attributes_all_seven_nodes(self, tmp_path):
        """One in-process 7-node SNCB run: every node gets a bucket with
        real window/event/span accounting, and every conserved counter
        sums back to its untagged global."""
        telemetry.enable()
        dag = build_sncb_dag(
            str(tmp_path / "egress"), qserve_queries=None,
            retry=RetryPolicy(max_retries=1, backoff_s=0.0),
        )
        driver = WindowedDataflowDriver(
            checkpoint_path=str(tmp_path / "ckpt.bin"),
            checkpoint_every=2, sink=None,
            retry=RetryPolicy(max_retries=1, backoff_s=0.0),
            failover=False,
        )
        try:
            for _ in dag.run(_toy_sncb_stream(150)(), driver=driver):
                pass
            rollup = telemetry.node_rollup()
            assert set(SNCB_NODES) <= set(rollup), sorted(rollup)
            for name in SNCB_NODES:
                row = rollup[name]
                assert row["windows"] > 0, name
                assert row["events"] > 0, name
                assert row["span_us"] > 0, name
                assert row["window_latency_p50_ms"] is not None, name

            sums = _bucket_sums(rollup)
            assert sums["h2d_bytes"] == telemetry.h2d_bytes
            assert sums["h2d_transfers"] == telemetry.h2d_transfers
            assert sums["d2h_bytes"] == telemetry.d2h_bytes
            assert sums["d2h_transfers"] == telemetry.d2h_transfers
            assert sums["compiles"] == len(telemetry.compile_events)
            assert sums["fault_fires"] == sum(
                telemetry.fault_fires.values())
            assert sums["shed_events"] == telemetry.shed_events
            table = telemetry.kernel_table()
            assert sums["dispatch_ns"] == sum(
                r["dispatch_ns"] for r in table)
            assert sums["kernel_calls"] == sum(r["calls"] for r in table)
            # The DAG moved real data somewhere — conservation over all
            # zeros would be vacuous.
            assert sums["h2d_bytes"] + sums["d2h_bytes"] > 0
            # The snapshot's nodes block is the same rollup.
            assert telemetry.snapshot()["nodes"] == rollup
        finally:
            telemetry.disable()

    def test_scoped_collective_bytes_land_in_the_node_bucket(self):
        telemetry.enable()
        try:
            with telemetry.scope("meshnode"):
                telemetry.account_collective("psum", 4096, axis="data",
                                             calls=3)
            telemetry.account_collective("broadcast", 100, axis="data")
            rollup = telemetry.node_rollup()
            assert rollup["meshnode"]["collective_bytes"] == 4096
            assert rollup["meshnode"]["collective_calls"] == 3
            assert rollup["(unscoped)"]["collective_bytes"] == 100
            g = telemetry.collective_gauges()
            assert g["bytes"] == 4196 and g["calls"] == 4
            assert _bucket_sums(rollup)["collective_bytes"] == g["bytes"]
        finally:
            telemetry.disable()

    def test_nested_scope_reentrancy_conserves_exactly(self):
        """scope() is a re-entrant stack: the innermost node wins while
        it is active, the outer tag is restored on exit (not cleared),
        and every conserved counter still sums EXACTLY to its untagged
        global — re-labeling across nesting never double-counts."""
        telemetry.enable()
        try:
            with telemetry.scope("outer"):
                telemetry.account_h2d(100)
                with telemetry.scope("inner"):
                    assert telemetry.current_node() == "inner"
                    telemetry.account_h2d(30)
                    telemetry.account_collective("psum", 2048,
                                                 axis="data")
                    telemetry.record_e2e(1_000, "compute")
                # the outer tag must come back — a scope exit that
                # cleared instead of popped would orphan this byte
                assert telemetry.current_node() == "outer"
                telemetry.account_d2h(7)
            assert telemetry.current_node() is None
            telemetry.account_h2d(5)  # unscoped remainder

            rollup = telemetry.node_rollup()
            assert rollup["outer"]["h2d_bytes"] == 100
            assert rollup["outer"]["d2h_bytes"] == 7
            assert rollup["inner"]["h2d_bytes"] == 30
            assert rollup["inner"]["collective_bytes"] == 2048
            assert rollup["(unscoped)"]["h2d_bytes"] == 5

            sums = _bucket_sums(rollup)
            assert sums["h2d_bytes"] == telemetry.h2d_bytes == 135
            assert sums["d2h_bytes"] == telemetry.d2h_bytes == 7
            assert sums["collective_bytes"] == \
                telemetry.collective_gauges()["bytes"]

            # e2e lineage honors the same innermost-wins rule: the
            # stamp inside the inner scope lands in inner's bucket only.
            e2e = telemetry.e2e_gauges()
            assert set(e2e["nodes"]) == {"inner"}
            assert e2e["nodes"]["inner"]["compute"]["count"] == 1
            assert e2e["stages"]["compute"]["count"] == 1
        finally:
            telemetry.disable()


class TestByteCompat:
    def test_unscoped_capture_snapshots_the_v1_shape(self, tmp_path):
        """No scope ever entered → no ``nodes``/``collectives`` blocks
        anywhere: rollup empty, snapshot v1-shaped, ledger v1-shaped
        (modulo the version literal) — old readers keep working."""
        telemetry.enable()
        try:
            telemetry.account_h2d(1024)
            with telemetry.span("window.eval"):
                pass
            assert telemetry.node_rollup() == {}
            snap = telemetry.snapshot()
            assert "nodes" not in snap
            assert "collectives" not in snap
            path = str(tmp_path / "ledger.json")
            telemetry.write_ledger(path, capture_costs=False)
            with open(path) as f:
                doc = json.load(f)
            assert doc["ledger_version"] == 3
            assert "nodes" not in doc["snapshot"]
            assert "collectives" not in doc["snapshot"]
            # Latency lineage is opt-in the same way: no e2e stamp ever
            # → no e2e block (the v2 byte-compat rule).
            assert "e2e" not in doc["snapshot"]
            for row in doc["kernels"]:
                assert "node" not in row
        finally:
            telemetry.disable()


def _scoped_stream(path, flushes=2):
    """A stream capture with one scoped node block, flushed
    ``flushes`` times (so a tail truncation still leaves a complete
    node-carrying checkpoint), NOT sealed."""
    telemetry.enable(stream_path=path)
    with telemetry.scope("q1"), telemetry.span("node.q1", events=5):
        telemetry.account_h2d(512)
        telemetry.account_collective("psum", 2048, axis="data")
    for _ in range(flushes):
        telemetry.maybe_flush_stream(force=True)
    with open(path, "rb") as f:
        return f.read()


class TestRecoverKeepsNodes:
    def test_truncated_stream_recovers_node_blocks(self, tmp_path):
        """Kill-mid-capture: cut the stream inside its LAST checkpoint
        line — recover must rebuild a ledger whose snapshot still
        carries the per-node attribution from the previous flush."""
        stream = str(tmp_path / "s.jsonl")
        data = _scoped_stream(stream)
        telemetry.disable()
        crash = str(tmp_path / "crash.jsonl")
        with open(crash, "wb") as f:
            f.write(data[:-7])  # mid-line cut, the kill -9 shape
        doc, info = stream_mod.recover(crash)
        assert info["partial_tail"] is True
        assert "q1" in info["nodes_recovered"]
        assert info["collective_bytes_recovered"] == 2048
        nodes = doc["snapshot"]["nodes"]
        assert nodes["q1"]["h2d_bytes"] == 512
        assert nodes["q1"]["events"] == 5
        assert nodes["q1"]["collective_bytes"] == 2048


class TestLive:
    def test_sealed_stream_exits_zero(self, tmp_path, capsys):
        stream = str(tmp_path / "s.jsonl")
        _scoped_stream(stream)
        telemetry.disable()  # seals (reason: disabled)
        assert live_mod.follow(stream, 0.05, None, json_mode=True) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["sealed"] is True and doc["reason"] == "disabled"
        assert "q1" in doc["nodes"]
        assert doc["collectives"]["bytes"] == 2048
        # Follow mode reaches the epilogue and exits 0 too.
        assert live_mod.follow(stream, 0.05, 5.0, json_mode=False) == 0
        assert "sealed: reason=disabled" in capsys.readouterr().out

    def test_unsealed_stream_exits_one(self, tmp_path, capsys):
        stream = str(tmp_path / "s.jsonl")
        _scoped_stream(stream)
        try:
            assert live_mod.follow(stream, 0.05, None,
                                   json_mode=True) == 1
            doc = json.loads(capsys.readouterr().out)
            assert doc["sealed"] is False
            assert doc["checkpoints"] >= 1
            # Follow mode gives up at --timeout on an unsealed stream.
            assert live_mod.follow(stream, 0.02, 0.1,
                                   json_mode=False) == 1
        finally:
            telemetry.disable()

    def test_truncated_tail_self_heals(self, tmp_path, capsys):
        """A half-written tail (the crash shape) must not break the
        follower: it reports the decodable prefix and exits by the
        seal state, exactly as recover does."""
        stream = str(tmp_path / "s.jsonl")
        data = _scoped_stream(stream)
        telemetry.disable()
        crash = str(tmp_path / "crash.jsonl")
        with open(crash, "wb") as f:
            f.write(data[:-7])
        assert live_mod.follow(crash, 0.05, None, json_mode=True) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["sealed"] is False and doc["checkpoints"] >= 1
        assert "q1" in doc["nodes"]

    def test_not_a_stream_exits_two(self, tmp_path, capsys):
        bogus = str(tmp_path / "bogus.jsonl")
        with open(bogus, "w") as f:
            f.write(json.dumps({"t": "checkpoint", "seq": 1}) + "\n")
        assert live_mod.follow(bogus, 0.05, None, json_mode=True) == 2
        capsys.readouterr()
