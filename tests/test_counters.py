"""Kernel-level op counters (ops/counters.py) — the distance-computation
counter and throughput-meter analogs (Point.java:220-235, :237-253)."""

import numpy as np
import pytest

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.models.objects import Point
from spatialflink_tpu.operators import (
    PointPointJoinQuery,
    PointPointKNNQuery,
    PointPointRangeQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.ops import counters as oc

GRID = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
W = QueryConfiguration(QueryType.WindowBased, window_size=10, slide_step=10)


@pytest.fixture(autouse=True)
def _counters_off():
    yield
    oc.disable()


def _pts(rng, n, prefix="d"):
    return [
        Point(obj_id=f"{prefix}{i % 5}", timestamp=int(i * 10_000 / n),
              x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
        for i in range(n)
    ]


def test_disabled_counts_nothing(rng):
    oc.counters.reset()
    list(PointPointRangeQuery(W, GRID).run(iter(_pts(rng, 200)), [Point(x=5, y=5)], 0.5))
    assert oc.counters.windows == 0 and oc.counters.dist_computations == 0


def test_range_counts_candidates(rng):
    oc.enable()
    pts = _pts(rng, 400)
    q = [Point(x=5.0, y=5.0), Point(x=2.0, y=2.0)]
    r = 0.5
    list(PointPointRangeQuery(W, GRID).run(iter(list(pts)), q, r))
    snap = oc.counters.snapshot()
    assert snap["windows"] >= 1
    assert snap["points_in"] == 400
    # Candidates = points in flagged cells; brute-check against the grid.
    from spatialflink_tpu.operators.base import flags_for_queries

    flags = flags_for_queries(GRID, r, q)
    want = sum(
        1 for p in pts if flags[GRID.flat_cell(p.x, p.y)] > 0
    )
    assert snap["candidate_lanes"] == want
    assert snap["dist_computations"] == want * 2  # × query points
    assert snap["throughput_eps"] > 0


def test_knn_and_join_count(rng):
    oc.enable()
    pts = _pts(rng, 300)
    list(PointPointKNNQuery(W, GRID).run(iter(list(pts)), Point(x=5, y=5), 2.0, 5))
    knn_windows = oc.counters.windows
    assert knn_windows >= 1 and oc.counters.dist_computations > 0

    oc.enable()  # reset
    left = _pts(rng, 300)
    right = _pts(rng, 200, prefix="q")
    list(PointPointJoinQuery(W, GRID).run(iter(left), iter(right), 0.4))
    snap = oc.counters.snapshot()
    # Exact candidate pairs: brute-count right points in each left point's
    # neighbor cell square.
    layers = GRID.candidate_layers(0.4)
    want = 0
    for a in left:
        ax, ay = GRID.cell_indices(a.x, a.y)
        for b in right:
            bx, by = GRID.cell_indices(b.x, b.y)
            if abs(ax - bx) <= layers and abs(ay - by) <= layers:
                want += 1
    assert snap["dist_computations"] == want


def test_nes_reporter_appends_counters(tmp_path, rng):
    from spatialflink_tpu.mn.metrics import MetricRegistry
    from spatialflink_tpu.mn.reporter import NESFileReporter

    oc.enable()
    list(PointPointRangeQuery(W, GRID).run(
        iter(_pts(rng, 100)), [Point(x=5, y=5)], 0.5))
    reg = MetricRegistry()
    rep = NESFileReporter(reg, query_id="t", out_dir=str(tmp_path))
    line = rep.report()
    assert "dist_comp_total=" in line and "candidate_lanes_total=" in line
    oc.disable()
    line2 = rep.report()
    assert "dist_comp_total" not in line2


def test_metrics_sink_opcounter_column(tmp_path, rng):
    from spatialflink_tpu.sncb.metrics import MetricsSink

    oc.enable()
    sink = MetricsSink("t", path=str(tmp_path / "m.csv"),
                       interval_s=0.0, include_opcounters=True)
    assert sink.HEADER.endswith(",distComp")
    oc.counters.record_candidates(10, 42)
    sink.record(event_ts_ms=0)
    sink.close()
    rows = (tmp_path / "m.csv").read_text().strip().splitlines()
    assert rows[0].endswith(",distComp")
    assert rows[1].split(",")[-1] == "42"
