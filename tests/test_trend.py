"""Trend store (tools/sfprof/trend.py + the ``trend`` CLI): history
ingestion across every record shape (ledgers, streams, legacy BENCH_r*
supervisor records, last-good stores, bare bench records), the
skip-with-counted-evidence contract, MAD-band gating, and taint
rejection."""

import json
import os

import pytest

from tools.sfprof import trend
from tools.sfprof.cli import main as sfprof_main


# -- corpus builders ----------------------------------------------------------


def _bench(value, config="cfg_a", smoke=True, device="TFRT_CPU_0",
           resident=None, pipeline=False, tainted=None):
    out = {
        "metric": config, "value": float(value), "unit": "points/s",
        "device": device, "smoke": smoke,
        "pipeline": {"armed": bool(pipeline)},
    }
    if resident is not None:
        out["device_resident_points_per_sec"] = float(resident)
    if tainted is not None:
        out["tainted"] = tainted
    return out


def _supervisor(value, n=1, rc=0, **kw):
    return {"n": n, "cmd": "python bench.py", "rc": rc,
            "parsed": _bench(value, **kw)}


def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc) + "\n")
    return str(p)


def _history_dir(tmp_path, values=(90e3, 100e3, 110e3, 120e3), **kw):
    d = tmp_path / "hist"
    d.mkdir(exist_ok=True)
    for i, v in enumerate(values):
        _write(d, f"r{i:02d}.json", _supervisor(v, n=i, **kw))
    return str(d)


def _ledger(value, tainted=None, created=1000.0, **kw):
    doc = {
        "ledger_version": 1, "created_unix": created,
        "env": {"backend": "cpu", "devices": ["TFRT_CPU_0"]},
        "snapshot": {"compiles": 0, "bytes_h2d": 0, "bytes_d2h": 0,
                     "max_watermark_lag_ms": 0, "late_dropped": 0,
                     "dropped_events": 0, "kernels": {}},
        "kernels": [], "events": [],
        "bench": _bench(value, **kw),
    }
    if tainted is not None:
        doc["tainted"] = tainted
    return doc


TAINT = {"kind": "ablation", "kernels": ["k"],
         "substituted_calls": {"k": 3}, "learning_calls": {"k": 1}}


# -- ingestion across record shapes -------------------------------------------


def test_ingest_supervisor_ledger_lastgood_and_bare(tmp_path):
    d = tmp_path / "mix"
    d.mkdir()
    _write(d, "a_supervisor.json", _supervisor(100e3))
    _write(d, "b_ledger.json", _ledger(110e3))
    _write(d, "c_lastgood.json", {
        "record": _bench(120e3), "git_sha": "abc123",
        "captured_at": "2026-08-01T00:00:00+00:00",
    })
    _write(d, "d_bare.json", _bench(130e3))
    points, skipped = trend.ingest_paths([str(d)])
    assert skipped == []
    assert sorted(p["value"] for p in points) \
        == [100e3, 110e3, 120e3, 130e3]
    (lg,) = [p for p in points if p["commit"]]
    assert lg["commit"] == "abc123"
    # One series: every shape lands on the same key.
    assert len(trend.build_series(points)) == 1


def test_ingest_stream_via_recovery(tmp_path):
    lines = [
        {"t": "prologue", "stream_version": 1, "ledger_version": 1,
         "created_unix": 5.0, "env": {"python": "3"}},
        {"t": "checkpoint", "seq": 1, "unix": 6.0,
         "snapshot": {"compiles": 0}, "kernels": []},
        {"t": "epilogue", "seq": 1, "unix": 7.0, "reason": "complete",
         "bench": _bench(140e3)},
    ]
    p = tmp_path / "run.stream.jsonl"
    p.write_text("".join(json.dumps(ln) + "\n" for ln in lines))
    points, skipped = trend.ingest_paths([str(p)])
    assert skipped == []
    assert points[0]["value"] == 140e3


def test_legacy_failures_skip_with_counted_evidence(tmp_path):
    d = tmp_path / "hist"
    d.mkdir()
    # The r5 outage shape: rc=124, parsed null — skipped, not a crash.
    _write(d, "r05.json", {"n": 5, "cmd": "python bench.py", "rc": 124,
                           "tail": "WARNING: axon experimental\n",
                           "parsed": None})
    # rc=0 but only a tail: the one-line contract means the last JSON
    # line IS the record.
    _write(d, "r06.json", {
        "n": 6, "cmd": "python bench.py", "rc": 0, "parsed": None,
        "tail": "WARNING: noise\n" + json.dumps(_bench(150e3)) + "\n",
    })
    # Unparseable tail, rc=0: skipped with its reason.
    _write(d, "r07.json", {"n": 7, "cmd": "python bench.py", "rc": 0,
                           "parsed": None, "tail": "no json here"})
    # A zero-value error record (honest outage output): skipped.
    _write(d, "r08.json", _supervisor(0.0))
    # Garbage file: skipped, never a crash.
    (d / "r09.json").write_text("{not json")
    points, skipped = trend.ingest_paths([str(d)])
    assert [p["value"] for p in points] == [150e3]
    reasons = " | ".join(s["reason"] for s in skipped)
    assert "rc=124" in reasons
    assert "no parseable record" in reasons
    assert "zero/absent EPS" in reasons
    assert len(skipped) == 4


def test_tainted_history_is_skipped_with_reason(tmp_path):
    d = tmp_path / "hist"
    d.mkdir()
    _write(d, "clean.json", _ledger(100e3))
    _write(d, "stubbed.json", _ledger(900e3, tainted=TAINT))
    # Taint riding only the snapshot (the stream-recovery shape) must
    # also be caught.
    snap_tainted = _ledger(901e3)
    snap_tainted["snapshot"]["tainted"] = TAINT
    _write(d, "stubbed2.json", snap_tainted)
    points, skipped = trend.ingest_paths([str(d)])
    assert [p["value"] for p in points] == [100e3]
    assert all("tainted: ablation" in s["reason"] for s in skipped)
    assert len(skipped) == 2


def test_series_keys_separate_device_smoke_and_pipeline(tmp_path):
    d = tmp_path / "hist"
    d.mkdir()
    _write(d, "a.json", _bench(1.0, smoke=True))
    _write(d, "b.json", _bench(2.0, smoke=False))
    _write(d, "c.json", _bench(3.0, smoke=False, device="TPU v5 lite0"))
    _write(d, "d.json", _bench(4.0, smoke=False, device="TPU v5 lite0",
                               pipeline=True))
    points, _ = trend.ingest_paths([str(d)])
    assert len(trend.build_series(points)) == 4
    assert trend.device_class("TPU v5 lite0") == "tpu"
    assert trend.device_class("TFRT_CPU_0") == "cpu"
    assert trend.device_class("axon:0") == "tpu"


# -- robust stats + gate math -------------------------------------------------


def test_gate_metric_mad_band_and_relative_floor():
    hist = [90e3, 100e3, 110e3, 120e3]  # median 105k, MAD 10k
    ok = trend.gate_metric(hist, 95e3, mad_k=4.0, eps_tol=0.5)
    assert ok["ok"] is True
    # Below the MAD band AND below median/2: regression.
    bad = trend.gate_metric(hist, 40e3, mad_k=4.0, eps_tol=0.5)
    assert bad["ok"] is False
    # Outside the MAD band but above the relative floor: tolerated
    # (both legs must agree — a tight series must not flag noise).
    tight = [100e3, 100e3, 100e3, 100e3]  # MAD 0
    assert trend.gate_metric(tight, 60e3, 4.0, 0.5)["ok"] is True
    assert trend.gate_metric(tight, 49e3, 4.0, 0.5)["ok"] is False
    # Faster is never a regression.
    assert trend.gate_metric(hist, 10 * 120e3, 4.0, 0.5)["ok"] is True


# -- the CLI gate -------------------------------------------------------------


def test_trend_gate_pass_and_injected_regression(tmp_path, capsys):
    hist = _history_dir(tmp_path, resident=400e3)
    good = _write(tmp_path, "good.json",
                  _ledger(101e3, resident=410e3))
    assert sfprof_main(["trend", hist, "--gate", good]) == 0
    out = capsys.readouterr().out
    assert "gate verdict: PASS" in out
    bad = _write(tmp_path, "bad.json", _ledger(30e3, resident=410e3))
    assert sfprof_main(["trend", hist, "--gate", bad]) == 1
    out = capsys.readouterr().out
    assert "FAIL points_per_sec" in out
    assert "gate verdict: FAIL" in out


def test_trend_gate_resident_column(tmp_path):
    hist = _history_dir(tmp_path, resident=400e3)
    # e2e fine, resident collapsed: the silicon column gates too.
    bad_res = _write(tmp_path, "badres.json",
                     _ledger(101e3, resident=30e3))
    assert sfprof_main(["trend", hist, "--gate", bad_res]) == 1


def test_trend_gate_rejects_tainted_candidate(tmp_path, capsys):
    hist = _history_dir(tmp_path)
    cand = _write(tmp_path, "stub.json",
                  _ledger(500e3, tainted=TAINT))
    assert sfprof_main(["trend", hist, "--gate", cand]) == 1
    out = capsys.readouterr().out
    assert "REJECT" in out and "tainted" in out and "ablation" in out


def test_trend_gate_insufficient_history(tmp_path, capsys):
    hist = _history_dir(tmp_path, values=(100e3,))
    cand = _write(tmp_path, "c.json", _ledger(100e3))
    # Advisory by default; the CI mode (--require-history) fails.
    assert sfprof_main(["trend", hist, "--gate", cand]) == 0
    assert "insufficient history" in capsys.readouterr().out
    assert sfprof_main(["trend", hist, "--gate", cand,
                        "--require-history"]) == 1


def test_trend_gate_excludes_candidate_from_its_own_history(tmp_path):
    # The SFT_LEDGER_DIR layout: the candidate sits IN the history dir.
    d = tmp_path / "hist"
    d.mkdir()
    for i, v in enumerate((90e3, 100e3, 110e3, 120e3)):
        _write(d, f"r{i:02d}.json", _supervisor(v, n=i))
    cand = _write(d, "candidate.json", _ledger(95e3))
    assert sfprof_main(["trend", str(d), "--gate", cand]) == 0


def test_twin_artifacts_of_one_capture_count_once(tmp_path):
    """The SFT_LEDGER_DIR layout writes a ledger AND its stream per
    capture; the stream's recovery carries the identical bench record.
    The series must count each capture once — twin double-counting
    shrinks the MAD and gates candidates against themselves."""
    d = tmp_path / "hist"
    d.mkdir()
    for i, v in enumerate((90e3, 100e3, 110e3)):
        _write(d, f"r{i:02d}.json", _supervisor(v, n=i))
        # The stream twin of the same capture (identical bench record).
        (d / f"r{i:02d}.stream.jsonl").write_text("".join(
            json.dumps(ln) + "\n" for ln in [
                {"t": "prologue", "stream_version": 1,
                 "ledger_version": 1, "created_unix": float(i),
                 "env": {}},
                {"t": "epilogue", "seq": 0, "unix": float(i) + 1,
                 "reason": "complete", "bench": _bench(v)},
            ]))
    points, skipped = trend.ingest_paths([str(d)])
    assert skipped == []
    assert len(points) == 6
    (series,) = trend.build_series(points).values()
    assert [p["value"] for p in series] == [90e3, 100e3, 110e3]


def test_trend_gate_self_exclusion_covers_the_stream_twin(tmp_path):
    """A candidate whose OWN run also sits in history under another
    path (its stream twin) must not be gated against itself: with only
    twins in the dir, the gate reports insufficient history."""
    d = tmp_path / "hist"
    d.mkdir()
    cand = _write(d, "cfg.json", _ledger(200e3))
    (d / "cfg.stream.jsonl").write_text("".join(
        json.dumps(ln) + "\n" for ln in [
            {"t": "prologue", "stream_version": 1, "ledger_version": 1,
             "created_unix": 1.0, "env": {}},
            {"t": "epilogue", "seq": 0, "unix": 2.0,
             "reason": "complete", "bench": _bench(200e3)},
        ]))
    assert sfprof_main(["trend", str(d), "--gate", cand,
                        "--require-history"]) == 1


def test_trend_gate_min_history_zero_never_crashes(tmp_path, capsys):
    """--min-history 0 with an empty series must hit the insufficient-
    history path (stats need >= 1 point), not an IndexError — the exit
    code contract is 0/1/2, never a traceback."""
    d = tmp_path / "hist"
    d.mkdir()
    cand = _write(tmp_path, "c.json", _ledger(100e3))
    assert sfprof_main(["trend", str(d), "--gate", cand,
                        "--min-history", "0"]) == 0
    assert "insufficient history" in capsys.readouterr().out


def test_point_key_carries_armed_codec():
    pt, reason = trend.point_from_bench(
        dict(_bench(100e3), pipeline={"armed": True,
                                      "armed_codec": "delta"}),
        "x.json")
    assert reason is None
    assert pt["pipeline"] is True and pt["codec"] == "delta"
    key = dict(zip(trend.SERIES_KEY_FIELDS, trend.series_key(pt)))
    assert key["codec"] == "delta"


def test_trend_gate_unreadable_candidate(tmp_path):
    hist = _history_dir(tmp_path)
    assert sfprof_main(["trend", hist, "--gate",
                        str(tmp_path / "absent.json")]) == 2


def test_trend_json_schema(tmp_path, capsys):
    hist = _history_dir(tmp_path)
    cand = _write(tmp_path, "c.json", _ledger(101e3))
    assert sfprof_main(["trend", hist, "--gate", cand, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    (row,) = out["series"]
    assert row["key"]["config"] == "cfg_a"
    assert row["key"]["device_class"] == "cpu"
    assert row["n"] == 4 and row["median"] == 105e3
    assert out["gate"]["checks"][0]["metric"] == "points_per_sec"
    assert out["gate"]["checks"][0]["ok"] is True
    assert out["skipped"] == []


def test_trend_without_gate_reports_series(tmp_path, capsys):
    hist = _history_dir(tmp_path)
    assert sfprof_main(["trend", hist]) == 0
    out = capsys.readouterr().out
    assert "1 series" in out and "median=105000.0" in out


# -- the committed CI fixture stays self-consistent ---------------------------


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_DIR = os.path.join(REPO, "tests", "fixtures", "trend")


def test_committed_ci_fixture_matches_the_smoke_key(tmp_path):
    """The toy trajectory tools.ci gates the smoke ledger against: it
    must ingest cleanly (one skipped outage record — the evidence
    contract), form ONE smoke/cpu series with enough history, and
    accept a typical smoke capture while rejecting a collapsed one."""
    points, skipped = trend.ingest_paths([FIXTURE_DIR])
    assert len(points) >= trend.DEFAULT_MIN_HISTORY
    assert len(skipped) == 1 and "rc=124" in skipped[0]["reason"]
    series = trend.build_series(points)
    (key,) = series.keys()
    key_d = dict(zip(trend.SERIES_KEY_FIELDS, key))
    assert key_d["config"] \
        == "continuous_knn_k50_1M_window_points_per_sec_per_chip"
    assert key_d["device_class"] == "cpu"
    assert key_d["smoke"] is True
    assert key_d["pipeline"] is False
    # A smoke record 5x the fixture median passes; a collapsed one
    # (50x under) fails — the CI chain gates something real.
    ok = _write(tmp_path, "ok.json", _ledger(
        500e3, config=key_d["config"], resident=2e6))
    assert sfprof_main(["trend", FIXTURE_DIR, "--gate", ok,
                        "--require-history"]) == 0
    broken = _write(tmp_path, "broken.json", _ledger(
        2e3, config=key_d["config"], resident=2e6))
    assert sfprof_main(["trend", FIXTURE_DIR, "--gate", broken,
                        "--require-history"]) == 1
