"""Built-in Kafka wire-protocol client (streams/kafka_wire.py).

Three layers of coverage:
1. GOLDEN FRAMES — requests compared byte-for-byte against independently
   hand-packed frames following the Kafka protocol spec (pins the
   encoding; a fake broker alone would only prove self-consistency).
2. Message-set encode/decode: CRC validation, v0/v1 magic, partial
   trailing message truncation.
3. End-to-end over a REAL TCP socket: a threaded in-process broker
   speaking Metadata/Produce/Fetch/ListOffsets v0/v2 serves
   KafkaSink → topic → kafka_source → windowed range query.
"""

import itertools
import socket
import struct
import threading
import zlib

import numpy as np
import pytest

from spatialflink_tpu.streams import kafka_wire as kw


# ---------- 1. golden frames ----------

def test_metadata_request_golden_bytes():
    body = kw.encode_metadata_request(["gps"])
    frame = kw.encode_request(kw.API_METADATA, 0, 7, "c", body)
    expect = b"".join([
        struct.pack(">i", 2 + 2 + 4 + 2 + 1 + 4 + 2 + 3),  # size
        struct.pack(">h", 3),      # api_key = Metadata
        struct.pack(">h", 0),      # api_version
        struct.pack(">i", 7),      # correlation_id
        struct.pack(">h", 1), b"c",   # client_id
        struct.pack(">i", 1),      # topic array count
        struct.pack(">h", 3), b"gps",
    ])
    assert frame == expect


def test_produce_request_golden_bytes():
    msg_body = b"".join([
        struct.pack(">b", 1),          # magic = 1
        struct.pack(">b", 0),          # attributes
        struct.pack(">q", 1234),       # timestamp
        struct.pack(">i", -1),         # null key
        struct.pack(">i", 2), b"hi",   # value
    ])
    msg = struct.pack(">I", zlib.crc32(msg_body) & 0xFFFFFFFF) + msg_body
    mset = struct.pack(">qi", 0, len(msg)) + msg
    expect_body = b"".join([
        struct.pack(">h", 1),          # acks
        struct.pack(">i", 10_000),     # timeout
        struct.pack(">i", 1),          # topic array
        struct.pack(">h", 1), b"t",
        struct.pack(">i", 1),          # partition array
        struct.pack(">i", 0),          # partition id
        struct.pack(">i", len(mset)),
        mset,
    ])
    got = kw.encode_produce_request(
        "t", 0, kw.encode_message_set([(b"hi", None, 1234)]), acks=1
    )
    assert got == expect_body


def test_fetch_request_golden_bytes():
    expect = b"".join([
        struct.pack(">i", -1),        # replica_id
        struct.pack(">i", 500),       # max_wait_ms
        struct.pack(">i", 1),         # min_bytes
        struct.pack(">i", 1),         # topic array
        struct.pack(">h", 3), b"gps",
        struct.pack(">i", 1),         # partition array
        struct.pack(">i", 2),         # partition
        struct.pack(">q", 42),        # fetch offset
        struct.pack(">i", 1 << 20),   # max_bytes
    ])
    assert kw.encode_fetch_request("gps", 2, 42) == expect


def test_list_offsets_request_golden_bytes():
    expect = b"".join([
        struct.pack(">i", -1),        # replica_id
        struct.pack(">i", 1),
        struct.pack(">h", 1), b"t",
        struct.pack(">i", 1),
        struct.pack(">i", 0),         # partition
        struct.pack(">q", -2),        # EARLIEST
        struct.pack(">i", 1),         # max_offsets (v0)
    ])
    assert kw.encode_list_offsets_request("t", 0, kw.EARLIEST) == expect


# ---------- 2. message sets ----------

def test_message_set_roundtrip_and_crc():
    msgs = [(b"a", None, 10), (b"bb", b"k", 20), (None, None, 30)]
    wire = kw.encode_message_set(msgs)
    out = kw.decode_message_set(wire)
    assert [(v, k, t) for _, t, k, v in out] == msgs
    # Corrupt one payload byte → CRC must catch it.
    bad = bytearray(wire)
    bad[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        kw.decode_message_set(bytes(bad))


def test_message_set_partial_trailing_message():
    wire = kw.encode_message_set([(b"full", None, 1), (b"cutoff", None, 2)])
    out = kw.decode_message_set(wire[:-3])  # broker truncated at max_bytes
    assert len(out) == 1 and out[0][3] == b"full"


def _gzip_wrapper(inner: bytes, wrapper_offset: int, wrapper_ts: int,
                  attrs: int = 0x01, magic: int = 1) -> bytes:
    """Broker-style gzip wrapper message around an inner message set."""
    import gzip as _gzip

    comp = _gzip.compress(inner)
    if magic >= 1:
        body = struct.pack(">bbq", magic, attrs, wrapper_ts)
    else:
        body = struct.pack(">bb", magic, attrs)
    body += kw.enc_bytes(None) + kw.enc_bytes(comp)
    msg = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
    return struct.pack(">qi", wrapper_offset, len(msg)) + msg


def test_bootstrap_parsing_portless_and_ipv6():
    c = kw.KafkaWireClient("localhost")
    assert c.bootstrap == [("localhost", 9092)]
    c = kw.KafkaWireClient("[::1]:9093, broker:1234, [fe80::2]")
    assert c.bootstrap == [("::1", 9093), ("broker", 1234),
                           ("fe80::2", 9092)]


def test_gzip_message_set_decodes_with_relative_offsets():
    """KIP-31 v1 wrappers: inner offsets are relative; wrapper offset is
    the absolute offset of the LAST inner message."""
    inner = kw.encode_message_set(
        [(b"a", None, 10), (b"b", b"k", 20), (b"c", None, 30)]
    )  # inner offsets 0,1,2
    wire = _gzip_wrapper(inner, wrapper_offset=41, wrapper_ts=99)
    out = kw.decode_message_set(wire)
    assert [(o, t, k, v) for o, t, k, v in out] == [
        (39, 10, None, b"a"), (40, 20, b"k", b"b"), (41, 30, None, b"c"),
    ]


def test_gzip_log_append_time_overrides_inner_timestamps():
    inner = kw.encode_message_set([(b"a", None, 10), (b"b", None, 20)])
    wire = _gzip_wrapper(inner, wrapper_offset=7, wrapper_ts=555,
                         attrs=0x01 | 0x08)
    out = kw.decode_message_set(wire)
    assert [(o, t) for o, t, _, _ in out] == [(6, 555), (7, 555)]


def test_gzip_magic0_wrapper_keeps_absolute_offsets():
    # magic-0 inner messages with absolute offsets, magic-0 wrapper.
    msgs = []
    for off, val in [(3, b"x"), (4, b"y")]:
        body = struct.pack(">bb", 0, 0) + kw.enc_bytes(None) + kw.enc_bytes(val)
        m = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
        msgs.append(struct.pack(">qi", off, len(m)) + m)
    wire = _gzip_wrapper(b"".join(msgs), wrapper_offset=4, wrapper_ts=0,
                         magic=0)
    out = kw.decode_message_set(wire)
    assert [(o, v) for o, _, _, v in out] == [(3, b"x"), (4, b"y")]


def test_snappy_decompress_literals_roundtrip():
    import os
    payload = os.urandom(200_000)  # spans multiple 64k literal chunks
    assert kw.snappy_decompress(kw.snappy_compress_literal(payload)) == payload
    assert kw.snappy_decompress(kw.snappy_compress_literal(b"")) == b""


def test_snappy_decompress_copies_and_xerial():
    # hand-crafted raw stream: literal "abcd" + copy1(off=4, len=4)
    # + copy2(off=2, len=3 overlapping)
    raw = bytes([
        11,            # varint uncompressed length = 11
        (4 - 1) << 2,  # literal, len 4
    ]) + b"abcd" + bytes([
        ((4 - 4) & 7) << 2 | ((4 >> 8) << 5) | 1, 4 & 0xFF,  # copy1 off=4 len=4
        (3 - 1) << 2 | 2, 2, 0,  # copy2 off=2 len=3 (overlapping: "cdc")
    ])
    assert kw.snappy_decompress(raw) == b"abcdabcdcdc"
    # xerial framing: magic + version ints + one length-prefixed block
    framed = (b"\x82SNAPPY\x00" + struct.pack(">ii", 1, 1)
              + struct.pack(">i", len(raw)) + raw)
    assert kw.snappy_decompress(framed) == b"abcdabcdcdc"


def test_snappy_message_set_decodes():
    inner = kw.encode_message_set([(b"a", None, 10), (b"b", b"k", 20)])
    comp = kw.snappy_compress_literal(inner)
    body = struct.pack(">bbq", 1, 0x02, 99) + kw.enc_bytes(None) + kw.enc_bytes(comp)
    msg = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
    wire = struct.pack(">qi", 8, len(msg)) + msg
    out = kw.decode_message_set(wire)
    assert [(o, t, k, v) for o, t, k, v in out] == [
        (7, 10, None, b"a"), (8, 20, b"k", b"b"),
    ]


def test_lz4_message_set_still_rejected():
    inner = kw.encode_message_set([(b"a", None, 1)])
    wire = _gzip_wrapper(inner, wrapper_offset=0, wrapper_ts=0, attrs=0x03)
    with pytest.raises(NotImplementedError, match="lz4"):
        kw.decode_message_set(wire)


def test_snappy_garbage_raises_value_error():
    # attrs=0x02 but the payload is GZIP bytes — the snappy decoder must
    # fail loudly, not return garbage
    inner = kw.encode_message_set([(b"a", None, 1)])
    wire = _gzip_wrapper(inner, wrapper_offset=0, wrapper_ts=0, attrs=0x02)
    with pytest.raises((ValueError, IndexError)):
        kw.decode_message_set(wire)


def test_message_set_magic0_decodes():
    body = struct.pack(">bb", 0, 0) + kw.enc_bytes(None) + kw.enc_bytes(b"v0")
    msg = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
    wire = struct.pack(">qi", 5, len(msg)) + msg
    [(off, ts, key, value)] = kw.decode_message_set(wire)
    assert (off, ts, key, value) == (5, -1, None, b"v0")


# ---------- 3. in-process TCP broker ----------

class FakeBroker:
    """Threaded single-node broker: Metadata v0, Produce v2, Fetch v2,
    ListOffsets v0; auto-creates topics, one partition (id 0)."""

    def __init__(self):
        self.logs: dict = {}  # topic → list[(ts, key, value)]
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def close(self):
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn):
        try:
            while True:
                hdr = self._recv(conn, 4)
                if hdr is None:
                    return
                size = struct.unpack(">i", hdr)[0]
                payload = self._recv(conn, size)
                if payload is None:
                    return
                r = kw.Reader(payload)
                api, ver, corr = r.int16(), r.int16(), r.int32()
                r.string()  # client_id
                body = self._dispatch(api, ver, r)
                resp = struct.pack(">i", corr) + body
                conn.sendall(struct.pack(">i", len(resp)) + resp)
        except OSError:
            pass
        finally:
            conn.close()

    @staticmethod
    def _recv(conn, n):
        chunks = []
        while n:
            try:
                c = conn.recv(n)
            except OSError:
                return None
            if not c:
                return None
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)

    def _dispatch(self, api, ver, r):
        if api == kw.API_METADATA:
            topics = [r.string() for _ in range(r.int32())]
            parts = [struct.pack(">hiii", 0, 0, 0, 1) + struct.pack(">i", 0)
                     + struct.pack(">i", 1) + struct.pack(">i", 0)]
            return (
                kw.enc_array([struct.pack(">i", 0)
                              + kw.enc_string("127.0.0.1")
                              + struct.pack(">i", self.port)])
                + kw.enc_array([
                    struct.pack(">h", 0) + kw.enc_string(t)
                    + kw.enc_array(parts)
                    for t in topics
                ])
            )
        if api == kw.API_PRODUCE:
            acks = r.int16()
            r.int32()  # timeout
            out_topics = []
            for _ in range(r.int32()):
                topic = r.string()
                for _ in range(r.int32()):
                    r.int32()  # partition id
                    mset = r.bytes_() or b""
                    log = self.logs.setdefault(topic, [])
                    base = len(log)
                    for _off, ts, key, value in kw.decode_message_set(mset):
                        log.append((ts, key, value))
                    out_topics.append(
                        kw.enc_string(topic)
                        + kw.enc_array([struct.pack(">ihqq", 0, 0, base, -1)])
                    )
            return kw.enc_array(out_topics) + struct.pack(">i", 0)
        if api == kw.API_FETCH:
            r.int32(), r.int32(), r.int32()  # replica, max_wait, min_bytes
            out_topics = []
            for _ in range(r.int32()):
                topic = r.string()
                for _ in range(r.int32()):
                    r.int32()  # partition
                    off = r.int64()
                    r.int32()  # max_bytes
                    log = self.logs.get(topic, [])
                    msgs = []
                    for i, (ts, key, value) in enumerate(log[off:], start=off):
                        m = kw.encode_message_v1(value, key, ts)
                        msgs.append(struct.pack(">qi", i, len(m)) + m)
                    mset = b"".join(msgs)
                    out_topics.append(
                        kw.enc_string(topic)
                        + kw.enc_array([
                            struct.pack(">ihq", 0, 0, len(log))
                            + kw.enc_bytes(mset)
                        ])
                    )
            return struct.pack(">i", 0) + kw.enc_array(out_topics)
        if api == kw.API_LIST_OFFSETS:
            r.int32()  # replica
            out_topics = []
            for _ in range(r.int32()):
                topic = r.string()
                for _ in range(r.int32()):
                    r.int32()  # partition
                    ts = r.int64()
                    r.int32()  # max_offsets
                    log = self.logs.get(topic, [])
                    off = 0 if ts == kw.EARLIEST else len(log)
                    out_topics.append(
                        kw.enc_string(topic)
                        + kw.enc_array([
                            struct.pack(">ih", 0, 0)
                            + kw.enc_array([struct.pack(">q", off)])
                        ])
                    )
            return kw.enc_array(out_topics)
        raise AssertionError(f"unexpected api_key {api}")


@pytest.fixture
def broker():
    b = FakeBroker()
    yield b
    b.close()


def _no_libs(monkeypatch):
    """Force the built-in backend even if a kafka lib were importable."""
    import builtins

    real_import = builtins.__import__

    def guarded(name, *a, **kw_):
        if name in ("kafka", "confluent_kafka"):
            raise ImportError(name)
        return real_import(name, *a, **kw_)

    monkeypatch.setattr(builtins, "__import__", guarded)


def test_wire_client_produce_fetch_roundtrip(broker):
    client = kw.KafkaWireClient(f"127.0.0.1:{broker.port}")
    assert client.metadata(["t"]) == {"t": [0]}
    base = client.produce("t", 0, [(b"a", None, 1), (b"b", b"k", 2)])
    assert base == 0
    assert client.list_offset("t", 0, kw.EARLIEST) == 0
    assert client.list_offset("t", 0, kw.LATEST) == 2
    msgs, hw = client.fetch("t", 0, 0)
    assert hw == 2
    assert [(v, k) for _, _, k, v in msgs] == [(b"a", None), (b"b", b"k")]
    # Offset-resumed fetch.
    msgs2, _ = client.fetch("t", 0, 1)
    assert [v for *_, v in msgs2] == [b"b"]
    client.close()


def test_kafka_available_via_builtin(monkeypatch):
    _no_libs(monkeypatch)
    from spatialflink_tpu.streams.kafka import _import_kafka, kafka_available

    assert kafka_available()
    assert _import_kafka()[0] == "wire"


def test_sink_and_source_over_real_socket(broker, monkeypatch):
    """KafkaSink → wire protocol → broker → kafka_source → windowed range
    query, equal to running the query on the original objects."""
    _no_libs(monkeypatch)
    from spatialflink_tpu.grid import UniformGrid
    from spatialflink_tpu.models.objects import Point
    from spatialflink_tpu.operators import (
        PointPointRangeQuery,
        QueryConfiguration,
        QueryType,
    )
    from spatialflink_tpu.streams.kafka import KafkaSink, kafka_source
    from spatialflink_tpu.streams.serde import parse_geojson, to_geojson

    grid = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
    rng = np.random.default_rng(9)
    pts = [
        Point(obj_id=f"d{i % 7}", timestamp=int(i * 30),
              x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
        for i in range(400)
    ]
    bs = f"127.0.0.1:{broker.port}"
    sink = KafkaSink("gps", bs, formatter=to_geojson, batch=64)
    for p in pts:
        sink(p)
    sink.close()
    assert len(broker.logs["gps"]) == 400

    stream = itertools.islice(
        kafka_source("gps", bs, parser=parse_geojson), len(pts)
    )
    conf = QueryConfiguration(QueryType.WindowBased, window_size=5,
                              slide_step=5)
    q = Point(x=5.0, y=5.0)

    def results(s):
        return [
            (r.start, r.end, sorted((o.obj_id, o.timestamp) for o in r.objects))
            for r in PointPointRangeQuery(conf, grid).run(s, [q], 2.0)
        ]

    assert results(stream) == results(iter(pts))


def test_wire_source_skips_malformed(broker, monkeypatch):
    _no_libs(monkeypatch)
    from spatialflink_tpu.streams.kafka import kafka_source
    from spatialflink_tpu.streams.serde import parse_csv_point

    client = kw.KafkaWireClient(f"127.0.0.1:{broker.port}")
    client.produce("csv", 0, [
        (b"a,100,1.0,2.0", None, 0),
        (b"not,a,valid,record,###", None, 0),
        (b"", None, 0),
        (b"b,200,3.0,4.0", None, 0),
    ])
    client.close()
    got = list(itertools.islice(
        kafka_source("csv", f"127.0.0.1:{broker.port}",
                     parser=parse_csv_point), 2,
    ))
    assert [p.obj_id for p in got] == ["a", "b"]
