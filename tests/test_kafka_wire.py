"""Built-in Kafka wire-protocol client (streams/kafka_wire.py).

Three layers of coverage:
1. GOLDEN FRAMES — requests compared byte-for-byte against independently
   hand-packed frames following the Kafka protocol spec (pins the
   encoding; a fake broker alone would only prove self-consistency).
2. Message-set encode/decode: CRC validation, v0/v1 magic, partial
   trailing message truncation.
3. End-to-end over a REAL TCP socket: a threaded in-process broker
   speaking Metadata/Produce/Fetch/ListOffsets v0/v2 serves
   KafkaSink → topic → kafka_source → windowed range query.
"""

import itertools
import socket
import struct
import threading
import zlib

import numpy as np
import pytest

from spatialflink_tpu.streams import kafka_wire as kw


# ---------- 1. golden frames ----------

def test_metadata_request_golden_bytes():
    body = kw.encode_metadata_request(["gps"])
    frame = kw.encode_request(kw.API_METADATA, 0, 7, "c", body)
    expect = b"".join([
        struct.pack(">i", 2 + 2 + 4 + 2 + 1 + 4 + 2 + 3),  # size
        struct.pack(">h", 3),      # api_key = Metadata
        struct.pack(">h", 0),      # api_version
        struct.pack(">i", 7),      # correlation_id
        struct.pack(">h", 1), b"c",   # client_id
        struct.pack(">i", 1),      # topic array count
        struct.pack(">h", 3), b"gps",
    ])
    assert frame == expect


def test_produce_request_golden_bytes():
    msg_body = b"".join([
        struct.pack(">b", 1),          # magic = 1
        struct.pack(">b", 0),          # attributes
        struct.pack(">q", 1234),       # timestamp
        struct.pack(">i", -1),         # null key
        struct.pack(">i", 2), b"hi",   # value
    ])
    msg = struct.pack(">I", zlib.crc32(msg_body) & 0xFFFFFFFF) + msg_body
    mset = struct.pack(">qi", 0, len(msg)) + msg
    expect_body = b"".join([
        struct.pack(">h", 1),          # acks
        struct.pack(">i", 10_000),     # timeout
        struct.pack(">i", 1),          # topic array
        struct.pack(">h", 1), b"t",
        struct.pack(">i", 1),          # partition array
        struct.pack(">i", 0),          # partition id
        struct.pack(">i", len(mset)),
        mset,
    ])
    got = kw.encode_produce_request(
        "t", 0, kw.encode_message_set([(b"hi", None, 1234)]), acks=1
    )
    assert got == expect_body


def test_fetch_request_golden_bytes():
    expect = b"".join([
        struct.pack(">i", -1),        # replica_id
        struct.pack(">i", 500),       # max_wait_ms
        struct.pack(">i", 1),         # min_bytes
        struct.pack(">i", 1),         # topic array
        struct.pack(">h", 3), b"gps",
        struct.pack(">i", 1),         # partition array
        struct.pack(">i", 2),         # partition
        struct.pack(">q", 42),        # fetch offset
        struct.pack(">i", 1 << 20),   # max_bytes
    ])
    assert kw.encode_fetch_request("gps", 2, 42) == expect


def test_list_offsets_request_golden_bytes():
    expect = b"".join([
        struct.pack(">i", -1),        # replica_id
        struct.pack(">i", 1),
        struct.pack(">h", 1), b"t",
        struct.pack(">i", 1),
        struct.pack(">i", 0),         # partition
        struct.pack(">q", -2),        # EARLIEST
        struct.pack(">i", 1),         # max_offsets (v0)
    ])
    assert kw.encode_list_offsets_request("t", 0, kw.EARLIEST) == expect


# ---------- 2. message sets ----------

def test_message_set_roundtrip_and_crc():
    msgs = [(b"a", None, 10), (b"bb", b"k", 20), (None, None, 30)]
    wire = kw.encode_message_set(msgs)
    out = kw.decode_message_set(wire)
    assert [(v, k, t) for _, t, k, v in out] == msgs
    # Corrupt one payload byte → CRC must catch it.
    bad = bytearray(wire)
    bad[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        kw.decode_message_set(bytes(bad))


def test_message_set_partial_trailing_message():
    wire = kw.encode_message_set([(b"full", None, 1), (b"cutoff", None, 2)])
    out = kw.decode_message_set(wire[:-3])  # broker truncated at max_bytes
    assert len(out) == 1 and out[0][3] == b"full"


def _gzip_wrapper(inner: bytes, wrapper_offset: int, wrapper_ts: int,
                  attrs: int = 0x01, magic: int = 1) -> bytes:
    """Broker-style gzip wrapper message around an inner message set."""
    import gzip as _gzip

    comp = _gzip.compress(inner)
    if magic >= 1:
        body = struct.pack(">bbq", magic, attrs, wrapper_ts)
    else:
        body = struct.pack(">bb", magic, attrs)
    body += kw.enc_bytes(None) + kw.enc_bytes(comp)
    msg = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
    return struct.pack(">qi", wrapper_offset, len(msg)) + msg


def test_bootstrap_parsing_portless_and_ipv6():
    c = kw.KafkaWireClient("localhost")
    assert c.bootstrap == [("localhost", 9092)]
    c = kw.KafkaWireClient("[::1]:9093, broker:1234, [fe80::2]")
    assert c.bootstrap == [("::1", 9093), ("broker", 1234),
                           ("fe80::2", 9092)]


def test_gzip_message_set_decodes_with_relative_offsets():
    """KIP-31 v1 wrappers: inner offsets are relative; wrapper offset is
    the absolute offset of the LAST inner message."""
    inner = kw.encode_message_set(
        [(b"a", None, 10), (b"b", b"k", 20), (b"c", None, 30)]
    )  # inner offsets 0,1,2
    wire = _gzip_wrapper(inner, wrapper_offset=41, wrapper_ts=99)
    out = kw.decode_message_set(wire)
    assert [(o, t, k, v) for o, t, k, v in out] == [
        (39, 10, None, b"a"), (40, 20, b"k", b"b"), (41, 30, None, b"c"),
    ]


def test_gzip_log_append_time_overrides_inner_timestamps():
    inner = kw.encode_message_set([(b"a", None, 10), (b"b", None, 20)])
    wire = _gzip_wrapper(inner, wrapper_offset=7, wrapper_ts=555,
                         attrs=0x01 | 0x08)
    out = kw.decode_message_set(wire)
    assert [(o, t) for o, t, _, _ in out] == [(6, 555), (7, 555)]


def test_gzip_magic0_wrapper_keeps_absolute_offsets():
    # magic-0 inner messages with absolute offsets, magic-0 wrapper.
    msgs = []
    for off, val in [(3, b"x"), (4, b"y")]:
        body = struct.pack(">bb", 0, 0) + kw.enc_bytes(None) + kw.enc_bytes(val)
        m = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
        msgs.append(struct.pack(">qi", off, len(m)) + m)
    wire = _gzip_wrapper(b"".join(msgs), wrapper_offset=4, wrapper_ts=0,
                         magic=0)
    out = kw.decode_message_set(wire)
    assert [(o, v) for o, _, _, v in out] == [(3, b"x"), (4, b"y")]


def test_snappy_decompress_literals_roundtrip():
    import os
    payload = os.urandom(200_000)  # spans multiple 64k literal chunks
    assert kw.snappy_decompress(kw.snappy_compress_literal(payload)) == payload
    assert kw.snappy_decompress(kw.snappy_compress_literal(b"")) == b""


def test_snappy_decompress_copies_and_xerial():
    # hand-crafted raw stream: literal "abcd" + copy1(off=4, len=4)
    # + copy2(off=2, len=3 overlapping)
    raw = bytes([
        11,            # varint uncompressed length = 11
        (4 - 1) << 2,  # literal, len 4
    ]) + b"abcd" + bytes([
        ((4 - 4) & 7) << 2 | ((4 >> 8) << 5) | 1, 4 & 0xFF,  # copy1 off=4 len=4
        (3 - 1) << 2 | 2, 2, 0,  # copy2 off=2 len=3 (overlapping: "cdc")
    ])
    assert kw.snappy_decompress(raw) == b"abcdabcdcdc"
    # xerial framing: magic + version ints + one length-prefixed block
    framed = (b"\x82SNAPPY\x00" + struct.pack(">ii", 1, 1)
              + struct.pack(">i", len(raw)) + raw)
    assert kw.snappy_decompress(framed) == b"abcdabcdcdc"


def test_snappy_message_set_decodes():
    inner = kw.encode_message_set([(b"a", None, 10), (b"b", b"k", 20)])
    comp = kw.snappy_compress_literal(inner)
    body = struct.pack(">bbq", 1, 0x02, 99) + kw.enc_bytes(None) + kw.enc_bytes(comp)
    msg = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
    wire = struct.pack(">qi", 8, len(msg)) + msg
    out = kw.decode_message_set(wire)
    assert [(o, t, k, v) for o, t, k, v in out] == [
        (7, 10, None, b"a"), (8, 20, b"k", b"b"),
    ]


def test_zstd_message_set_still_rejected():
    inner = kw.encode_message_set([(b"a", None, 1)])
    wire = _gzip_wrapper(inner, wrapper_offset=0, wrapper_ts=0, attrs=0x04)
    with pytest.raises(NotImplementedError, match="zstd"):
        kw.decode_message_set(wire)


def test_xxh32_known_vectors():
    """Spec vectors for the pure-python xxHash32 the LZ4 frame checks
    ride on (covers <16-byte tail-only and >16-byte 4-lane paths)."""
    assert kw._xxh32(b"") == 0x02CC5D05
    assert kw._xxh32(b"a") == 0x550D7456
    assert kw._xxh32(b"abc") == 0x32D153FF
    assert kw._xxh32(b"Nobody inspects the spammish repetition") == 0xE2293B2F


def _lz4_frame(blocks, flg=0x60, content=None):
    """Hand-assembled LZ4 frame: list of (data, is_compressed) blocks."""
    header = bytes([flg, 0x40])
    out = bytearray(b"\x04\x22\x4d\x18" + header)
    out.append((kw._xxh32(header) >> 8) & 0xFF)
    for data, is_comp in blocks:
        size = len(data) | (0 if is_comp else 0x80000000)
        out += size.to_bytes(4, "little")
        out += data
    out += (0).to_bytes(4, "little")
    if content is not None:  # flg must carry 0x04
        out += kw._xxh32(content).to_bytes(4, "little")
    return bytes(out)


def test_lz4_decompress_matches_and_overlaps():
    # token lit=10/mlen=11 → "0123456789" + 15-byte copy at offset 10
    blk1 = bytes([0xAB]) + b"0123456789" + b"\x0a\x00"
    want1 = b"0123456789012345678901234"
    # token lit=2/mlen ext: "ab" + 20-byte OVERLAPPING copy at offset 2
    blk2 = bytes([0x2F]) + b"ab" + b"\x02\x00" + bytes([1])
    want2 = b"ab" * 11
    got = kw.lz4_decompress(_lz4_frame([(blk1, True)]))
    assert got == want1
    got = kw.lz4_decompress(_lz4_frame([(blk2, True)]))
    assert got == want2
    # uncompressed block + compressed block in one frame; matches in a
    # later block may reach back into the earlier one (block-dependent
    # frames — flg without the independence bit)
    reach_back = bytes([0x0F]) + b"\x05\x00" + bytes([3])  # 22-byte copy
    got = kw.lz4_decompress(
        _lz4_frame([(b"hello", False), (reach_back, True)], flg=0x40)
    )
    assert got == b"hello" + (b"hello" * 5)[:22]


def test_lz4_roundtrip_and_checksums():
    import os
    payload = os.urandom(200_000)  # spans multiple 64k blocks
    assert kw.lz4_decompress(kw.lz4_compress_literal(payload)) == payload
    assert kw.lz4_decompress(
        kw.lz4_compress_literal(payload, block_checksum=True)
    ) == payload
    assert kw.lz4_decompress(kw.lz4_compress_literal(b"")) == b""
    # the pre-KIP-57 Kafka header-checksum variant is accepted too
    assert kw.lz4_decompress(
        kw.lz4_compress_literal(b"legacy", legacy_hc=True)
    ) == b"legacy"


def test_lz4_corrupt_inputs_raise():
    good = kw.lz4_compress_literal(b"payload payload payload")
    with pytest.raises(ValueError, match="magic"):
        kw.lz4_decompress(b"\x00\x00\x00\x00" + good[4:])
    bad_hc = bytearray(good)
    bad_hc[6] ^= 0xFF  # header checksum byte
    with pytest.raises(ValueError, match="header checksum"):
        kw.lz4_decompress(bytes(bad_hc))
    bad_content = bytearray(good)
    bad_content[-1] ^= 0xFF  # trailing content checksum
    with pytest.raises(ValueError, match="content checksum"):
        kw.lz4_decompress(bytes(bad_content))
    with pytest.raises(ValueError, match="EndMark"):
        kw.lz4_decompress(good[:10])
    bad_blk = bytearray(
        kw.lz4_compress_literal(b"block checksum", block_checksum=True)
    )
    bad_blk[-9] ^= 0xFF  # block checksum (before EndMark + content cksum)
    with pytest.raises(ValueError):
        kw.lz4_decompress(bytes(bad_blk))
    # snappy bytes labeled lz4 must fail loudly, not return garbage
    with pytest.raises((ValueError, IndexError)):
        kw.lz4_decompress(kw.snappy_compress_literal(b"not lz4"))
    # content-size flag set but the header is truncated: ValueError with
    # context, not a bare IndexError (r5 code review)
    with pytest.raises(ValueError, match="truncated header"):
        kw.lz4_decompress(b"\x04\x22\x4d\x18" + bytes([0x48, 0x40, 0x00]))
    # token promises a match but only 1 byte remains for the offset —
    # must raise, not silently decode partial garbage (r5 code review)
    with pytest.raises(ValueError, match="match offset"):
        kw.lz4_block_decompress(b"\x12A\x01", bytearray())
    with pytest.raises(ValueError, match="reserved bit"):
        kw.lz4_decompress(_lz4_frame([], flg=0x62))
    with pytest.raises(ValueError, match="BD byte"):
        bad_bd = bytearray(good)
        bad_bd[5] = 0x30  # block-max code 3: below the legal 4-7 range
        # re-stamp HC so the BD check itself (not HC) is what trips
        bad_bd[6] = (kw._xxh32(bytes(bad_bd[4:6])) >> 8) & 0xFF
        kw.lz4_decompress(bytes(bad_bd))


def test_lz4_literal_frames_respect_declared_block_max():
    """The test encoder must emit frames a SPEC decoder accepts: every
    stored block (token + length ext + literals) within the 64 KiB the
    BD byte declares (r5 code review: 64 KiB chunks overflowed to
    65794-byte blocks)."""
    frame = kw.lz4_compress_literal(b"x" * 200_000)
    pos = 7  # magic + FLG/BD + HC (no content size in these frames)
    sizes = []
    while True:
        bsize = int.from_bytes(frame[pos:pos + 4], "little")
        pos += 4
        if bsize == 0:
            break
        assert not bsize & 0x80000000  # compressed blocks
        sizes.append(bsize)
        pos += bsize
    assert max(sizes) <= 65536
    assert len(sizes) == 4  # 200k / 65200-literal chunks


def test_lz4_message_set_decodes():
    inner = kw.encode_message_set([(b"a", None, 10), (b"b", b"k", 20)])
    comp = kw.lz4_compress_literal(inner)
    body = (struct.pack(">bbq", 1, 0x03, 99)
            + kw.enc_bytes(None) + kw.enc_bytes(comp))
    msg = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
    wire = struct.pack(">qi", 8, len(msg)) + msg
    out = kw.decode_message_set(wire)
    assert [(o, t, k, v) for o, t, k, v in out] == [
        (7, 10, None, b"a"), (8, 20, b"k", b"b"),
    ]


def test_snappy_garbage_raises_value_error():
    # attrs=0x02 but the payload is GZIP bytes — the snappy decoder must
    # fail loudly, not return garbage
    inner = kw.encode_message_set([(b"a", None, 1)])
    wire = _gzip_wrapper(inner, wrapper_offset=0, wrapper_ts=0, attrs=0x02)
    with pytest.raises((ValueError, IndexError)):
        kw.decode_message_set(wire)


def test_message_set_magic0_decodes():
    body = struct.pack(">bb", 0, 0) + kw.enc_bytes(None) + kw.enc_bytes(b"v0")
    msg = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
    wire = struct.pack(">qi", 5, len(msg)) + msg
    [(off, ts, key, value)] = kw.decode_message_set(wire)
    assert (off, ts, key, value) == (5, -1, None, b"v0")


# ---------- 3. in-process TCP broker ----------

class FakeBroker:
    """Threaded single-node broker: Metadata v0, Produce v2, Fetch v2,
    ListOffsets v0; auto-creates topics with ``num_partitions``."""

    def __init__(self, num_partitions: int = 1):
        self.num_partitions = num_partitions
        # topic → {partition → list[(ts, key, value)]}
        self.logs: dict = {}
        self.fetch_codec = None  # None | gzip | snappy | lz4 | lz4-legacy
        # (topic, partition) → offsets DELETED by log compaction: they
        # stay in the offset sequence but never appear in a fetch.
        self.holes: dict = {}
        # Fault hooks (leader-retry regression tests): ``kill_after_bytes``
        # sends only that many bytes of the NEXT fetch response frame and
        # then kills the connection (a broker dying mid-fetch);
        # ``fetch_errors`` pops one error code per fetch and returns it in
        # the partition response (e.g. 6 = NOT_LEADER — a leader change).
        # Both one-shot-per-entry so the client's retry can succeed.
        self.kill_after_bytes: int = 0
        self.fetch_errors: list = []
        self.metadata_requests = 0
        self.fetch_requests = 0
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def close(self):
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn):
        try:
            while True:
                hdr = self._recv(conn, 4)
                if hdr is None:
                    return
                size = struct.unpack(">i", hdr)[0]
                payload = self._recv(conn, size)
                if payload is None:
                    return
                r = kw.Reader(payload)
                api, ver, corr = r.int16(), r.int16(), r.int32()
                r.string()  # client_id
                body = self._dispatch(api, ver, r)
                resp = struct.pack(">i", corr) + body
                frame = struct.pack(">i", len(resp)) + resp
                if api == kw.API_FETCH and self.kill_after_bytes:
                    # Die mid-response: N bytes of the frame land, then
                    # the socket closes under the client's recv.
                    n, self.kill_after_bytes = self.kill_after_bytes, 0
                    conn.sendall(frame[:n])
                    conn.close()
                    return
                conn.sendall(frame)
        except OSError:
            pass
        finally:
            conn.close()

    @staticmethod
    def _recv(conn, n):
        chunks = []
        while n:
            try:
                c = conn.recv(n)
            except OSError:
                return None
            if not c:
                return None
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)

    def log(self, topic: str, partition: int = 0) -> list:
        return self.logs.setdefault(topic, {}).setdefault(partition, [])

    def total(self, topic: str) -> int:
        return sum(len(v) for v in self.logs.get(topic, {}).values())

    def _dispatch(self, api, ver, r):
        if api == kw.API_METADATA:
            self.metadata_requests += 1
            topics = [r.string() for _ in range(r.int32())]
            parts = [
                struct.pack(">hiii", 0, p, 0, 1) + struct.pack(">i", 0)
                + struct.pack(">i", 1) + struct.pack(">i", 0)
                for p in range(self.num_partitions)
            ]
            return (
                kw.enc_array([struct.pack(">i", 0)
                              + kw.enc_string("127.0.0.1")
                              + struct.pack(">i", self.port)])
                + kw.enc_array([
                    struct.pack(">h", 0) + kw.enc_string(t)
                    + kw.enc_array(parts)
                    for t in topics
                ])
            )
        if api == kw.API_PRODUCE:
            acks = r.int16()
            r.int32()  # timeout
            out_topics = []
            for _ in range(r.int32()):
                topic = r.string()
                for _ in range(r.int32()):
                    pid = r.int32()
                    mset = r.bytes_() or b""
                    log = self.log(topic, pid)
                    base = len(log)
                    for _off, ts, key, value in kw.decode_message_set(mset):
                        log.append((ts, key, value))
                    out_topics.append(
                        kw.enc_string(topic)
                        + kw.enc_array([struct.pack(">ihqq", pid, 0, base,
                                                    -1)])
                    )
            return kw.enc_array(out_topics) + struct.pack(">i", 0)
        if api == kw.API_FETCH:
            self.fetch_requests += 1
            err_code = self.fetch_errors.pop(0) if self.fetch_errors else 0
            r.int32(), r.int32(), r.int32()  # replica, max_wait, min_bytes
            out_topics = []
            for _ in range(r.int32()):
                topic = r.string()
                for _ in range(r.int32()):
                    pid = r.int32()
                    off = r.int64()
                    r.int32()  # max_bytes
                    if err_code:
                        out_topics.append(
                            kw.enc_string(topic)
                            + kw.enc_array([
                                struct.pack(">ihq", pid, err_code, -1)
                                + kw.enc_bytes(b"")
                            ])
                        )
                        continue
                    log = self.log(topic, pid)
                    holes = self.holes.get((topic, pid), ())
                    msgs = []
                    for i, (ts, key, value) in enumerate(log[off:], start=off):
                        if i in holes:  # compacted away — never served
                            continue
                        m = kw.encode_message_v1(value, key, ts)
                        msgs.append(struct.pack(">qi", i, len(m)) + m)
                    mset = b"".join(msgs)
                    if self.fetch_codec and msgs:
                        mset = self._compressed_wrapper(log, off)
                    out_topics.append(
                        kw.enc_string(topic)
                        + kw.enc_array([
                            struct.pack(">ihq", pid, 0, len(log))
                            + kw.enc_bytes(mset)
                        ])
                    )
            return struct.pack(">i", 0) + kw.enc_array(out_topics)
        if api == kw.API_LIST_OFFSETS:
            r.int32()  # replica
            out_topics = []
            for _ in range(r.int32()):
                topic = r.string()
                for _ in range(r.int32()):
                    pid = r.int32()
                    ts = r.int64()
                    r.int32()  # max_offsets
                    log = self.log(topic, pid)
                    off = 0 if ts == kw.EARLIEST else len(log)
                    out_topics.append(
                        kw.enc_string(topic)
                        + kw.enc_array([
                            struct.pack(">ih", pid, 0)
                            + kw.enc_array([struct.pack(">q", off)])
                        ])
                    )
            return kw.enc_array(out_topics)
        raise AssertionError(f"unexpected api_key {api}")

    def _compressed_wrapper(self, log, off):
        """Broker-style compressed fetch: inner messages with RELATIVE
        offsets (KIP-31) inside one wrapper whose offset is the last
        message's ABSOLUTE offset."""
        import gzip as _gzip

        entries = log[off:]
        rel = []
        for j, (ts, key, value) in enumerate(entries):
            m = kw.encode_message_v1(value, key, ts)
            rel.append(struct.pack(">qi", j, len(m)) + m)
        inner = b"".join(rel)
        comp = {
            "gzip": _gzip.compress,
            "snappy": kw.snappy_compress_literal,
            "lz4": kw.lz4_compress_literal,
            "lz4-legacy": lambda d: kw.lz4_compress_literal(
                d, legacy_hc=True),
        }[self.fetch_codec](inner)
        attrs = {"gzip": 1, "snappy": 2, "lz4": 3, "lz4-legacy": 3}[
            self.fetch_codec]
        body = (struct.pack(">bbq", 1, attrs, -1)
                + kw.enc_bytes(None) + kw.enc_bytes(comp))
        msg = struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF) + body
        return struct.pack(">qi", off + len(entries) - 1, len(msg)) + msg


@pytest.fixture
def broker():
    b = FakeBroker()
    yield b
    b.close()


def _no_libs(monkeypatch):
    """Force the built-in backend even if a kafka lib were importable."""
    import builtins

    real_import = builtins.__import__

    def guarded(name, *a, **kw_):
        if name in ("kafka", "confluent_kafka"):
            raise ImportError(name)
        return real_import(name, *a, **kw_)

    monkeypatch.setattr(builtins, "__import__", guarded)


def test_wire_client_produce_fetch_roundtrip(broker):
    client = kw.KafkaWireClient(f"127.0.0.1:{broker.port}")
    assert client.metadata(["t"]) == {"t": [0]}
    base = client.produce("t", 0, [(b"a", None, 1), (b"b", b"k", 2)])
    assert base == 0
    assert client.list_offset("t", 0, kw.EARLIEST) == 0
    assert client.list_offset("t", 0, kw.LATEST) == 2
    msgs, hw = client.fetch("t", 0, 0)
    assert hw == 2
    assert [(v, k) for _, _, k, v in msgs] == [(b"a", None), (b"b", b"k")]
    # Offset-resumed fetch.
    msgs2, _ = client.fetch("t", 0, 1)
    assert [v for *_, v in msgs2] == [b"b"]
    client.close()


@pytest.mark.parametrize("codec", ["gzip", "snappy", "lz4", "lz4-legacy"])
def test_wire_client_compressed_fetch_roundtrip(broker, codec):
    """Broker-side compression (any fetch may come back compressed,
    whatever the producer sent): KIP-31 relative offsets, timestamps
    and offset-resumed fetches must survive every codec — including
    the pre-KIP-57 legacy lz4 header checksum old brokers emit."""
    client = kw.KafkaWireClient(f"127.0.0.1:{broker.port}")
    client.produce("t", 0, [(b"a", None, 1), (b"b", b"k", 2),
                            (b"c", None, 3)])
    broker.fetch_codec = codec
    msgs, hw = client.fetch("t", 0, 0)
    assert hw == 3
    assert [(o, t, k, v) for o, t, k, v in msgs] == [
        (0, 1, None, b"a"), (1, 2, b"k", b"b"), (2, 3, None, b"c"),
    ]
    msgs2, _ = client.fetch("t", 0, 2)
    assert [(o, v) for o, _, _, v in msgs2] == [(2, b"c")]
    client.close()


def test_multi_partition_timestamp_merge(monkeypatch):
    """Records interleave across 2 partitions in event-time order per
    fetch round (a fixed round-robin would feed the pane paths out of
    order)."""
    _no_libs(monkeypatch)
    from spatialflink_tpu.streams.kafka import kafka_source

    b = FakeBroker(num_partitions=2)
    try:
        client = kw.KafkaWireClient(f"127.0.0.1:{b.port}")
        # even timestamps → partition 0, odd → partition 1
        client.produce("t", 0, [(f"r{t}".encode(), None, t)
                                for t in range(0, 20, 2)])
        client.produce("t", 1, [(f"r{t}".encode(), None, t)
                                for t in range(1, 20, 2)])
        client.close()
        got = list(itertools.islice(
            kafka_source("t", f"127.0.0.1:{b.port}", parser=str), 20
        ))
        assert got == [f"r{t}" for t in range(20)]
    finally:
        b.close()


def test_multi_partition_nonmonotone_ts_no_duplicates(monkeypatch):
    """Within-partition timestamp skew (producer retry / CreateTime)
    must never step a partition's offset backwards — the ts-only merge
    sort can yield a later offset first, and a regressed position would
    re-deliver the earlier record next round (r5 code review)."""
    _no_libs(monkeypatch)
    from spatialflink_tpu.streams.kafka import WireKafkaSource

    b = FakeBroker(num_partitions=2)
    try:
        client = kw.KafkaWireClient(f"127.0.0.1:{b.port}")
        # partition 0: offsets 0,1 carry ts 100, 50 (NON-monotone)
        client.produce("t", 0, [(b"p0a", None, 100), (b"p0b", None, 50)])
        client.produce("t", 1, [(b"p1a", None, 60), (b"p1b", None, 70)])
        client.close()
        src = WireKafkaSource("t", f"127.0.0.1:{b.port}", parser=str)
        got = list(itertools.islice(iter(src), 4))
        src.close()
        assert sorted(got) == ["p0a", "p0b", "p1a", "p1b"], got
        assert len(set(got)) == 4, f"duplicate delivery: {got}"
    finally:
        b.close()


def test_compacted_topic_offset_gap_no_stall_no_dupes(monkeypatch):
    """Log holes (compacted-away offsets) in a multi-partition topic
    must neither stall the position nor re-deliver the post-hole
    records every round (ADVICE r5): a fetched batch starting past the
    requested position snaps it to the batch's base offset, and within
    the batch the position follows the offsets the broker actually
    delivered — the out-of-sequence parking applies only to the
    ts-sort's reordering of one batch, never to deleted offsets."""
    _no_libs(monkeypatch)
    from spatialflink_tpu.streams.kafka import WireKafkaSource

    b = FakeBroker(num_partitions=2)
    try:
        client = kw.KafkaWireClient(f"127.0.0.1:{b.port}")
        client.produce("t", 0, [(f"p0-{i}".encode(), None, 10 * i)
                                for i in range(6)])
        client.produce("t", 1, [(f"p1-{i}".encode(), None, 10 * i + 5)
                                for i in range(3)])
        client.close()
        # Compaction deleted p0 offsets 0 and 2-3: exercises BOTH the
        # batch-base snap (hole at the requested position) and the
        # within-batch successor chain (hole inside the batch).
        b.holes[("t", 0)] = {0, 2, 3}
        src = WireKafkaSource("t", f"127.0.0.1:{b.port}", parser=str)
        got = list(itertools.islice(iter(src), 6))
        src.close()
        assert sorted(got) == ["p0-1", "p0-4", "p0-5",
                               "p1-0", "p1-1", "p1-2"], got
        assert len(set(got)) == 6, f"duplicate delivery: {got}"
        # The regression trigger: pre-fix, partition 0's position stalls
        # at the hole (0) and every later round re-fetches + re-yields.
        assert src.offsets == {0: 6, 1: 3}, src.offsets
    finally:
        b.close()


def test_mid_round_checkpoint_nonmonotone_ts_no_loss(monkeypatch):
    """A checkpoint taken mid round while ts skew made a LATER offset
    yield first must not skip the earlier, not-yet-yielded record:
    positions advance contiguously, so the resume re-delivers the
    parked record (at-least-once) instead of losing the earlier one
    (r5 code review)."""
    _no_libs(monkeypatch)
    from spatialflink_tpu.streams.kafka import WireKafkaSource

    b = FakeBroker(num_partitions=2)
    try:
        bs = f"127.0.0.1:{b.port}"
        client = kw.KafkaWireClient(bs)
        # partition 0: off 0 carries the LATER ts — it yields second
        client.produce("t", 0, [(b"late", None, 200), (b"early", None, 100)])
        client.produce("t", 1, [(b"mid", None, 150)])
        client.close()
        src1 = WireKafkaSource("t", bs, parser=str)
        first = list(itertools.islice(iter(src1), 1))
        assert first == ["early"]  # off 1, parked out-of-sequence
        snap = src1.offsets
        src1.close()
        assert snap.get(0, 0) == 0, "position must not skip offset 0"
        src2 = WireKafkaSource("t", bs, parser=str, start_offsets=snap)
        rest = list(itertools.islice(iter(src2), 3))
        src2.close()
        # no loss: every record observed across the checkpoint; the
        # parked record may legitimately repeat (at-least-once).
        assert set(first) | set(rest) == {"late", "early", "mid"}
    finally:
        b.close()


def test_kill_and_resume_replays_no_gap_no_dup(monkeypatch):
    """The VERDICT r4 missing item: consumer offsets snapshot through
    checkpoint.py so a killed ingest resumes exactly where it left off —
    the FlinkKafkaConsumer checkpointed-offsets role
    (StreamingJob.java:255). The first consumer is killed MID fetch
    round (both partitions' records buffered in the timestamp merge),
    the hardest consistency point."""
    _no_libs(monkeypatch)
    from spatialflink_tpu.checkpoint import (
        kafka_source_state,
        load_checkpoint,
        restore_kafka_source_offsets,
        save_checkpoint,
    )
    from spatialflink_tpu.streams.kafka import WireKafkaSource

    b = FakeBroker(num_partitions=2)
    try:
        bs = f"127.0.0.1:{b.port}"
        client = kw.KafkaWireClient(bs)
        client.produce("t", 0, [(f"r{t}".encode(), None, t)
                                for t in range(0, 30, 2)])
        client.produce("t", 1, [(f"r{t}".encode(), None, t)
                                for t in range(1, 30, 2)])
        client.close()

        src1 = WireKafkaSource("t", bs, parser=str)
        first = list(itertools.islice(iter(src1), 13))
        assert first == [f"r{t}" for t in range(13)]
        import tempfile
        with tempfile.TemporaryDirectory() as d:
            path = f"{d}/ckpt.pkl"
            save_checkpoint(path, source=kafka_source_state(src1))
            src1.close()  # kill

            state = load_checkpoint(path)["source"]
            with pytest.raises(ValueError, match="topic"):
                restore_kafka_source_offsets(state, "other")
            src2 = WireKafkaSource(
                "t", bs, parser=str,
                start_offsets=restore_kafka_source_offsets(state, "t"),
            )
        rest = list(itertools.islice(iter(src2), 17))
        src2.close()
        assert rest == [f"r{t}" for t in range(13, 30)], \
            "resume must continue exactly after the last yielded record"
    finally:
        b.close()


def test_full_wire_pipeline_kill_and_resume(monkeypatch):
    """THE round-5 resume story end to end over a real socket: Kafka
    CSV records → WireKafkaSource (checkpointed offsets) →
    WirePaneAssembler (checkpointed open-pane buffer) →
    run_wire_panes (checkpointed digest ring). Killed between two
    windows and restored from the three snapshots, the pipeline's
    remaining windows equal an uninterrupted run's exactly.

    Checkpoint alignment note: snapshots are taken between yielded
    windows, i.e. at pane boundaries; the stream's ts deltas stay under
    one slide so a single record never completes more than one pane
    (multi-pane bursts must drain before snapshotting — the barrier
    alignment any checkpointing runtime imposes)."""
    _no_libs(monkeypatch)
    from spatialflink_tpu.checkpoint import (
        kafka_source_state,
        load_checkpoint,
        operator_state,
        restore_kafka_source_offsets,
        restore_operator,
        restore_wire_pane_assembler,
        save_checkpoint,
        wire_pane_assembler_state,
    )
    from spatialflink_tpu.grid import UniformGrid
    from spatialflink_tpu.models.objects import Point
    from spatialflink_tpu.operators import (
        PointPointKNNQuery,
        QueryConfiguration,
        QueryType,
    )
    from spatialflink_tpu.streams.kafka import WireKafkaSource
    from spatialflink_tpu.streams.wire import WireFormat, WirePaneAssembler

    grid = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
    wf = WireFormat.for_grid(grid)
    conf = QueryConfiguration(QueryType.WindowBased, window_size=4,
                              slide_step=2)
    slide_ms = conf.slide_step_ms
    q, radius, k, nseg = Point(x=5.0, y=5.0), 2.0, 5, 32

    rng = np.random.default_rng(77)
    n = 1_200
    ts = np.cumsum(rng.integers(1, slide_ms // 2, n)).astype(np.int64)
    xy = np.stack([rng.uniform(0, 10, n), rng.uniform(0, 10, n)], axis=1)
    xyf = wf.dequantize_np(wf.quantize(xy))  # the coords on the wire
    oid = rng.integers(0, nseg, n).astype(np.int64)

    b = FakeBroker()
    try:
        bs = f"127.0.0.1:{b.port}"
        client = kw.KafkaWireClient(bs)
        # float() wrap: numpy>=2 reprs f32 scalars as "np.float32(...)"
        # (the CLAUDE.md f-string gotcha — this killed the parser once)
        client.produce("gps", 0, [
            (f"{ts[i]},{float(xyf[i, 0])!r},{float(xyf[i, 1])!r},"
             f"{oid[i]}".encode(), None, int(ts[i]))
            for i in range(n)
        ])
        client.close()

        def parse(line):
            t, x, y, o = line.split(",")
            return int(t), float(x), float(y), int(o)

        def windows(src, asm, op):
            def panes():
                for t, x, y, o in iter(src):
                    for p in asm.feed({"ts": [t], "x": [x], "y": [y],
                                       "oid": [o]}):
                        yield p

            yield from op.run_wire_panes(
                panes(), q, radius, k, nseg, wf, start_ms=0,
                flush_at_end=False,
            )

        def collect(gen, count):
            return [
                (s, e, list(map(int, oo)), [round(float(d), 9) for d in dd])
                for s, e, oo, dd, nv in itertools.islice(gen, count)
            ]

        total = int(ts[-1] // slide_ms) - 2  # full panes only

        src0 = WireKafkaSource("gps", bs, parser=parse)
        asm0 = WirePaneAssembler(wf, slide_ms, start_ms=0)
        baseline = collect(
            windows(src0, asm0, PointPointKNNQuery(conf, grid)), total
        )
        src0.close()

        cut = total // 3
        src1 = WireKafkaSource("gps", bs, parser=parse)
        asm1 = WirePaneAssembler(wf, slide_ms, start_ms=0)
        op1 = PointPointKNNQuery(conf, grid)
        part1 = collect(windows(src1, asm1, op1), cut)
        import tempfile
        with tempfile.TemporaryDirectory() as d:
            path = f"{d}/pipeline.ckpt"
            save_checkpoint(
                path,
                source=kafka_source_state(src1),
                panes=wire_pane_assembler_state(asm1),
                op=operator_state(op1),
            )
            src1.close()  # kill
            del asm1, op1

            snap = load_checkpoint(path)
            src2 = WireKafkaSource(
                "gps", bs, parser=parse,
                start_offsets=restore_kafka_source_offsets(
                    snap["source"], "gps"),
            )
            asm2 = WirePaneAssembler(wf, slide_ms, start_ms=0)
            restore_wire_pane_assembler(asm2, snap["panes"])
            op2 = PointPointKNNQuery(conf, grid)
            restore_operator(op2, snap["op"])
        part2 = collect(windows(src2, asm2, op2), total - cut)
        src2.close()

        assert part1 + part2 == baseline
        assert part1 and part2
        assert sum(len(w[2]) for w in baseline) > 0
    finally:
        b.close()


def test_kafka_available_via_builtin(monkeypatch):
    _no_libs(monkeypatch)
    from spatialflink_tpu.streams.kafka import _import_kafka, kafka_available

    assert kafka_available()
    assert _import_kafka()[0] == "wire"


def test_sink_and_source_over_real_socket(broker, monkeypatch):
    """KafkaSink → wire protocol → broker → kafka_source → windowed range
    query, equal to running the query on the original objects."""
    _no_libs(monkeypatch)
    from spatialflink_tpu.grid import UniformGrid
    from spatialflink_tpu.models.objects import Point
    from spatialflink_tpu.operators import (
        PointPointRangeQuery,
        QueryConfiguration,
        QueryType,
    )
    from spatialflink_tpu.streams.kafka import KafkaSink, kafka_source
    from spatialflink_tpu.streams.serde import parse_geojson, to_geojson

    grid = UniformGrid(20, 0.0, 10.0, 0.0, 10.0)
    rng = np.random.default_rng(9)
    pts = [
        Point(obj_id=f"d{i % 7}", timestamp=int(i * 30),
              x=float(rng.uniform(0, 10)), y=float(rng.uniform(0, 10)))
        for i in range(400)
    ]
    bs = f"127.0.0.1:{broker.port}"
    sink = KafkaSink("gps", bs, formatter=to_geojson, batch=64)
    for p in pts:
        sink(p)
    sink.close()
    assert broker.total("gps") == 400

    stream = itertools.islice(
        kafka_source("gps", bs, parser=parse_geojson), len(pts)
    )
    conf = QueryConfiguration(QueryType.WindowBased, window_size=5,
                              slide_step=5)
    q = Point(x=5.0, y=5.0)

    def results(s):
        return [
            (r.start, r.end, sorted((o.obj_id, o.timestamp) for o in r.objects))
            for r in PointPointRangeQuery(conf, grid).run(s, [q], 2.0)
        ]

    assert results(stream) == results(iter(pts))


def test_wire_source_skips_malformed(broker, monkeypatch):
    _no_libs(monkeypatch)
    from spatialflink_tpu.streams.kafka import kafka_source
    from spatialflink_tpu.streams.serde import parse_csv_point

    client = kw.KafkaWireClient(f"127.0.0.1:{broker.port}")
    client.produce("csv", 0, [
        (b"a,100,1.0,2.0", None, 0),
        (b"not,a,valid,record,###", None, 0),
        (b"", None, 0),
        (b"b,200,3.0,4.0", None, 0),
    ])
    client.close()
    got = list(itertools.islice(
        kafka_source("csv", f"127.0.0.1:{broker.port}",
                     parser=parse_csv_point), 2,
    ))
    assert [p.obj_id for p in got] == ["a", "b"]


# ---------------------------------------------------------------------------
# _with_leader_retry under injected transport faults (ISSUE 8 satellite):
# a broker dying mid-fetch and a leader change must both retry and
# resume at the correct offset — every record delivered exactly once.


def test_mid_fetch_socket_drop_retries_at_same_offset_no_dup(broker):
    """The broker kills the connection after 7 bytes of the fetch
    response frame: the client sees a short read (OSError), drops the
    socket, and _with_leader_retry refetches the SAME offset on a fresh
    connection — no record lost, none duplicated."""
    client = kw.KafkaWireClient(f"127.0.0.1:{broker.port}")
    client.produce("drop", 0, [(f"r{i}".encode(), None, i) for i in range(8)])
    broker.kill_after_bytes = 7  # dies inside the first fetch response
    msgs, hw = client.fetch("drop", 0, 0)
    assert hw == 8
    assert [m[0] for m in msgs] == list(range(8))
    assert [m[3] for m in msgs] == [f"r{i}".encode() for i in range(8)]
    client.close()


def test_mid_fetch_drop_through_source_yields_each_record_once(broker):
    """End to end through WireKafkaSource: the drop lands between two
    consumed batches, and the stream still yields every record exactly
    once in order (the checkpointed-offsets contract survives transport
    faults, not just clean runs)."""
    from spatialflink_tpu.streams.kafka import WireKafkaSource

    client = kw.KafkaWireClient(f"127.0.0.1:{broker.port}")
    client.produce("dropsrc", 0,
                   [(f"a{i}".encode(), None, i) for i in range(5)])
    src = WireKafkaSource("dropsrc", f"127.0.0.1:{broker.port}",
                          parser=str)
    it = iter(src)
    got = [next(it) for _ in range(5)]
    # Arm the mid-frame kill for the NEXT fetch, then extend the log.
    broker.kill_after_bytes = 5
    client.produce("dropsrc", 0,
                   [(f"b{i}".encode(), None, 5 + i) for i in range(5)])
    got += [next(it) for _ in range(5)]
    assert got == [f"a{i}" for i in range(5)] + [f"b{i}" for i in range(5)]
    assert src.offsets == {0: 10}  # resumed at the correct position
    client.close()
    src.close()


def test_leader_change_refreshes_metadata_and_resumes(broker):
    """Error 6 (NOT_LEADER) on a fetch: the client must drop its cached
    leader, re-query metadata, and refetch the same offset — the
    reference gets this from the Flink Kafka connector; the built-in
    client must match it."""
    client = kw.KafkaWireClient(f"127.0.0.1:{broker.port}")
    client.produce("lead", 0, [(f"x{i}".encode(), None, i) for i in range(6)])
    before = broker.metadata_requests
    broker.fetch_errors = [6]  # one leader change
    msgs, _hw = client.fetch("lead", 0, 2)
    assert [m[0] for m in msgs] == [2, 3, 4, 5]
    assert [m[3] for m in msgs] == [f"x{i}".encode() for i in range(2, 6)]
    assert broker.metadata_requests > before  # leader table was refreshed
    assert broker.fetch_requests >= 2  # the failed try + the retry
    client.close()


def test_leader_retry_budget_exhausts_loudly(broker):
    """A leader that NEVER comes back must surface the KafkaError after
    the bounded retries — not spin forever (the r3–r5 lesson: bounded
    beats hung)."""
    client = kw.KafkaWireClient(f"127.0.0.1:{broker.port}")
    client.produce("dead", 0, [(b"v", None, 0)])
    broker.fetch_errors = [6, 6, 6, 6, 6]  # outlives the 3-attempt budget
    with pytest.raises(kw.KafkaError):
        client.fetch("dead", 0, 0)
    client.close()


def test_injected_kafka_leader_fault_is_not_retried(broker):
    """faults.py chaos contract: an InjectedFault at kafka.leader is
    NOT a retriable transport error — it must propagate immediately so
    chaos runs crash deterministically at the armed hit."""
    from spatialflink_tpu.faults import InjectedFault, faults

    client = kw.KafkaWireClient(f"127.0.0.1:{broker.port}")
    client.produce("chaos", 0, [(b"v", None, 0)])
    faults.arm([{"point": "kafka.leader", "at": 1}])
    try:
        with pytest.raises(InjectedFault):
            client.fetch("chaos", 0, 0)
        assert broker.fetch_requests == 0  # died before any attempt
    finally:
        faults.disarm()
        client.close()
