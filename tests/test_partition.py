"""Grid-partitioned placement: planner units, halo-kernel mesh parity,
and the collective-traffic contract.

Parity pins the tentpole invariant (CLAUDE.md "Architecture
invariants"): every ``parallel/halo.py`` wrapper — sharded_range_halo,
sharded_join_halo, sharded_tjoin_panes_halo,
sharded_registry_bucket_halo — is BIT-identical to its single-device
``ops/halo.py`` counterpart on the 8-device CPU mesh (the single-device
side runs jitted too: eager-vs-jitted may differ in the last ulp, which
is compiler slack, not semantics). The traffic tests assert the point
of the rebuild: accounted halo bytes < 25% of the replicated kernels'
broadcast/all-gather bytes on the SAME workload, via
``snapshot()["collectives"]``.
"""

import functools

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from spatialflink_tpu.grid import UniformGrid
from spatialflink_tpu.ops.halo import (
    join_partitioned_kernel,
    range_partitioned_kernel,
    registry_bucket_partitioned_kernel,
)
from spatialflink_tpu.parallel.halo import (
    sharded_join_halo,
    sharded_range_halo,
    sharded_registry_bucket_halo,
    sharded_tjoin_panes_halo,
)
from spatialflink_tpu.parallel.partition import (
    PLAN_VERSION,
    PartitionPlan,
    gather_rows,
    halo_width,
    plan_partition,
    scatter_rows,
    shard_layout,
)
from spatialflink_tpu.telemetry import telemetry

GRID = UniformGrid(64, 0.0, 1.0, 0.0, 1.0)
RADIUS = 0.012  # one candidate layer on GRID: halo width 65


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices())
    assert devs.size == 8, "conftest must provide 8 virtual CPU devices"
    return Mesh(devs.reshape(8), ("data",))


def _cloud(rng, n):
    xy = rng.uniform(0.0, 1.0, (n, 2))
    return xy, GRID.assign_cells_np(xy), np.ones(n, bool)


# -- planner -----------------------------------------------------------------


def test_plan_contiguous_cover_and_balance():
    plan = plan_partition(GRID, 8, RADIUS)
    assert plan.bounds[0] == 0 and plan.bounds[-1] == GRID.num_cells
    widths = plan.shard_widths()
    assert (widths > 0).all()
    assert (widths == GRID.num_cells // 8).all()  # uniform occupancy
    assert plan.halo == halo_width(GRID.n, plan.layers) == 65


def test_plan_occupancy_balancing_with_min_width_clamp():
    occ = np.zeros(GRID.num_cells)
    occ[:100] = 1.0  # all live mass in the first 100 cells
    plan = plan_partition(GRID, 8, RADIUS, occupancy=occ)
    # Cuts chase the mass but every shard keeps >= the halo width — the
    # single-hop exchange contract survives arbitrary skew.
    assert (plan.shard_widths() >= plan.halo).all()
    assert plan.bounds[1] <= 100 + plan.halo


def test_plan_infeasible_raises():
    tiny = UniformGrid(8, 0.0, 1.0, 0.0, 1.0)
    with pytest.raises(ValueError, match="finer grid or fewer shards"):
        plan_partition(tiny, 8, 0.5)


def test_plan_shard_of_sentinel_goes_last():
    plan = plan_partition(GRID, 8, RADIUS)
    cells = np.array([0, plan.bounds[1] - 1, plan.bounds[1],
                      GRID.num_cells - 1, GRID.num_cells])
    np.testing.assert_array_equal(
        plan.shard_of(cells), [0, 0, 1, 7, 7]
    )


def test_plan_dict_roundtrip_and_validation():
    plan = plan_partition(GRID, 8, RADIUS)
    d = plan.to_dict()
    back = PartitionPlan.from_dict(d)
    assert back.n_shards == plan.n_shards
    assert back.halo == plan.halo
    np.testing.assert_array_equal(back.bounds, plan.bounds)

    with pytest.raises(ValueError, match="unknown keys"):
        PartitionPlan.from_dict({**d, "surprise": 1})
    with pytest.raises(ValueError, match="missing keys"):
        PartitionPlan.from_dict({k: v for k, v in d.items()
                                 if k != "bounds"})
    with pytest.raises(ValueError, match="version"):
        PartitionPlan.from_dict({**d, "version": PLAN_VERSION + 1})
    with pytest.raises(ValueError, match="does not match"):
        PartitionPlan.from_dict({**d, "n_shards": 4})
    bad = list(d["bounds"])
    bad[1], bad[2] = bad[2], bad[1]
    with pytest.raises(ValueError, match="monotone"):
        PartitionPlan.from_dict({**d, "bounds": bad})


def test_shard_layout_rows_and_scatter_roundtrip(rng):
    plan = plan_partition(GRID, 8, RADIUS)
    xy, cell, valid = _cloud(rng, 1024)
    valid[::5] = False
    lay = shard_layout(plan, cell, valid)
    shard = plan.shard_of(cell)
    for s in range(8):
        rows = lay.own[s][lay.own[s] >= 0]
        expect = np.nonzero(valid & (shard == s))[0]
        np.testing.assert_array_equal(rows, expect)  # stable order
        lo, hi = plan.bounds[s], plan.bounds[s + 1]
        lrows = lay.left[s][lay.left[s] >= 0]
        assert (cell[lrows] < lo + plan.halo).all()
        rrows = lay.right[s][lay.right[s] >= 0]
        assert (cell[rrows] >= hi - plan.halo).all()
    assert lay.live_boundary_rows == int(
        (lay.left >= 0).sum() + (lay.right >= 0).sum()
    )
    vals = gather_rows(lay.own, xy[:, 0], np.nan)
    back = scatter_rows(lay.own, vals, 1024, np.nan)
    np.testing.assert_array_equal(back[valid], xy[valid, 0])
    assert np.isnan(back[~valid]).all()


# -- mesh parity (bit-identical single-device counterparts) ------------------


def test_sharded_range_halo_bit_parity(mesh):
    rng = np.random.default_rng(7)
    xy, cell, valid = _cloud(rng, 4096)
    valid[::7] = False
    qxy, qcell, qok = _cloud(rng, 512)
    plan = plan_partition(GRID, 8, RADIUS)
    keep, dist = sharded_range_halo(
        mesh, plan, xy, valid, cell, qxy, qcell, qok, RADIUS,
    )
    single = jax.jit(functools.partial(
        range_partitioned_kernel, grid_n=GRID.n, layers=plan.layers,
        guaranteed=plan.guaranteed, approximate=False,
    ))
    keep1, dist1 = single(xy, valid, cell, qxy, qcell, qok, RADIUS)
    np.testing.assert_array_equal(keep, np.asarray(keep1))
    np.testing.assert_array_equal(dist, np.asarray(dist1))  # bitwise


def test_sharded_range_halo_approximate_parity(mesh):
    rng = np.random.default_rng(17)
    xy, cell, valid = _cloud(rng, 2048)
    qxy, qcell, qok = _cloud(rng, 256)
    plan = plan_partition(GRID, 8, RADIUS)
    keep, _ = sharded_range_halo(
        mesh, plan, xy, valid, cell, qxy, qcell, qok, RADIUS,
        approximate=True,
    )
    single = jax.jit(functools.partial(
        range_partitioned_kernel, grid_n=GRID.n, layers=plan.layers,
        guaranteed=plan.guaranteed, approximate=True,
    ))
    keep1, _ = single(xy, valid, cell, qxy, qcell, qok, RADIUS)
    np.testing.assert_array_equal(keep, np.asarray(keep1))


def _expected_pairs(lxy, lok, lcell, rxy, rok, rcell, radius, budget,
                    plan):
    single = jax.jit(functools.partial(
        join_partitioned_kernel, grid_n=GRID.n, layers=plan.layers,
        budget=budget,
    ))
    li, ri, dv, count, over = single(
        lxy, lok, lcell, rxy, rok, rcell, radius,
    )
    li, ri, dv = (np.asarray(a) for a in (li, ri, dv))
    found = li >= 0
    li, ri, dv = li[found], ri[found], dv[found]
    order = np.lexsort((ri, li))
    return li[order], ri[order], dv[order], int(count), int(over)


def test_sharded_join_halo_bit_parity(mesh):
    rng = np.random.default_rng(11)
    lxy, lcell, lok = _cloud(rng, 2048)
    rxy, rcell, rok = _cloud(rng, 2048)
    lok[::9] = False
    plan = plan_partition(GRID, 8, RADIUS)
    li, ri, dv, count, over = sharded_join_halo(
        mesh, plan, lxy, lok, lcell, rxy, rok, rcell, RADIUS, 4096,
    )
    eli, eri, edv, ecount, eover = _expected_pairs(
        lxy, lok, lcell, rxy, rok, rcell, RADIUS, 4096, plan,
    )
    assert count == ecount and over == eover == 0
    np.testing.assert_array_equal(li, eli)
    np.testing.assert_array_equal(ri, eri)
    np.testing.assert_array_equal(dv, edv)  # bitwise


def test_sharded_tjoin_panes_halo_bit_parity(mesh):
    rng = np.random.default_rng(13)
    n_slides, slide_pts, ppw = 4, 512, 2
    plan = plan_partition(GRID, 8, RADIUS)

    def panes():
        out = []
        for _ in range(n_slides):
            xy, cell, ok = _cloud(rng, slide_pts)
            out.append((xy, ok, cell))
        return out

    lp, rp = panes(), panes()
    ts = np.arange(n_slides, dtype=np.int64) * 100
    results = sharded_tjoin_panes_halo(
        mesh, plan, ts, lp, rp, RADIUS, ppw, 8192,
    )
    assert len(results) == n_slides
    for i, (li, ri, dv, count, over) in enumerate(results):
        lo = max(0, i - ppw + 1)
        lxy, lok, lcell = (
            np.concatenate([p[j] for p in lp[lo: i + 1]])
            for j in range(3)
        )
        rxy, rok, rcell = (
            np.concatenate([p[j] for p in rp[lo: i + 1]])
            for j in range(3)
        )
        eli, eri, edv, ecount, eover = _expected_pairs(
            lxy, lok, lcell, rxy, rok, rcell, RADIUS, 8192, plan,
        )
        assert count == ecount and over == eover == 0
        np.testing.assert_array_equal(li, eli)
        np.testing.assert_array_equal(ri, eri)
        np.testing.assert_array_equal(dv, edv)


def test_sharded_registry_bucket_halo_bit_parity(mesh):
    rng = np.random.default_rng(11)
    xy, cell, valid = _cloud(rng, 2048)
    valid[::11] = False
    oid = rng.integers(0, 300, 2048).astype(np.int32)
    qxy, qcell, qok = _cloud(rng, 128)
    rad = np.full(128, RADIUS)
    plan = plan_partition(GRID, 8, RADIUS)
    dist, seg, nv, win = sharded_registry_bucket_halo(
        mesh, plan, xy, valid, cell, oid, qxy, qcell, rad, qok,
        k=8, num_segments=300,
    )
    single = jax.jit(functools.partial(
        registry_bucket_partitioned_kernel, grid_n=GRID.n,
        layers=plan.layers, k=8, num_segments=300,
    ))
    d1, s1, n1, w1 = single(xy, valid, cell, oid, qxy, qcell, rad, qok)
    np.testing.assert_array_equal(dist, np.asarray(d1))  # bitwise
    np.testing.assert_array_equal(seg, np.asarray(s1))
    np.testing.assert_array_equal(nv, np.asarray(n1))
    np.testing.assert_array_equal(win, np.asarray(w1))


# -- collective traffic: halo must beat replication >= 4x --------------------


def test_range_halo_bytes_beat_broadcast_4x(mesh):
    from spatialflink_tpu.parallel.sharded import sharded_range_query

    grid = UniformGrid(1024, 115.5, 117.6, 39.6, 41.1)
    radius = 0.002  # one layer: boundary region ~1.6% of the grid
    rng = np.random.default_rng(47)
    n, nq = 8192, 4096
    xy = np.stack([rng.uniform(115.5, 117.6, n),
                   rng.uniform(39.6, 41.1, n)], axis=1)
    qxy = np.stack([rng.uniform(115.6, 117.5, nq),
                    rng.uniform(39.7, 41.0, nq)], axis=1)
    cell = grid.assign_cells_np(xy)
    qcell = grid.assign_cells_np(qxy)
    ok, qok = np.ones(n, bool), np.ones(nq, bool)
    plan = plan_partition(grid, 8, radius)

    telemetry.enable()
    keep_h, _ = sharded_range_halo(
        mesh, plan, xy, ok, cell, qxy, qcell, qok, radius,
    )
    coll = telemetry.snapshot()["collectives"]
    telemetry.disable()
    halo_bytes = coll["by_kind"]["ppermute"]["bytes"]
    assert coll["halo_state_bytes"] > 0

    # The replicated kernel on the SAME window: every shard receives the
    # whole query set.
    table = grid.neighbor_flags(radius, [int(c) for c in qcell])
    telemetry.enable()
    keep_l, _ = sharded_range_query(mesh, xy, ok, table[cell], qxy,
                                    radius)
    legacy = telemetry.snapshot()["collectives"]
    telemetry.disable()
    legacy_bytes = legacy["bytes"]
    assert legacy_bytes == nq * 2 * xy.dtype.itemsize  # query broadcast
    assert halo_bytes * 4 <= legacy_bytes, (
        f"halo moved {halo_bytes} B vs replicated {legacy_bytes} B"
    )
    # Same answer set on this geometry's common lanes: a traffic win
    # that changed results would be a miscount, not an optimization.
    assert int(np.asarray(keep_h).sum()) == int(np.asarray(keep_l).sum())


def test_tjoin_halo_bytes_beat_all_gather_4x(mesh):
    from spatialflink_tpu.ops.tjoin_panes import (
        pane_cell_ranks,
        tjoin_pane_init,
    )
    from spatialflink_tpu.operators.base import center_coords
    from spatialflink_tpu.parallel.sharded import sharded_tjoin_pane_scan

    import jax.numpy as jnp

    grid = UniformGrid(256, 115.5, 117.6, 39.6, 41.1)
    radius = 0.005
    n_slides, slide_pts, ppw = 3, 512, 2
    n_obj = 64
    total = n_slides * slide_pts
    rng = np.random.default_rng(53)

    def mk_side():
        sxy = np.stack([rng.uniform(115.5, 117.6, total),
                        rng.uniform(39.6, 41.1, total)], axis=1)
        return sxy, grid.assign_cells_np(sxy), \
            rng.integers(0, n_obj, total).astype(np.int32)

    lxy, lcell, loid = mk_side()
    rxy, rcell, roid = mk_side()
    ok = np.ones(slide_pts, bool)
    plan = plan_partition(grid, 8, radius)

    def panes_of(sxy, scell):
        return [
            (sxy[i * slide_pts:(i + 1) * slide_pts], ok,
             scell[i * slide_pts:(i + 1) * slide_pts])
            for i in range(n_slides)
        ]

    ts = np.arange(n_slides, dtype=np.int64) * 1000
    telemetry.enable()
    sharded_tjoin_panes_halo(
        mesh, plan, ts, panes_of(lxy, lcell), panes_of(rxy, rcell),
        radius, ppw, 16384,
    )
    coll = telemetry.snapshot()["collectives"]
    telemetry.disable()
    halo_bytes = coll["by_kind"]["ppermute"]["bytes"]

    # The replicated pane scan on the same panes: per slide it
    # all-gathers both sides' 8 pane field arrays + contribution lanes.
    def side_fields(sxy, scell, soid):
        cxy = center_coords(grid, sxy, np.float32)
        ci = grid.cell_xy_indices_np(sxy)
        ing = scell < grid.num_cells
        pane_of = np.repeat(np.arange(n_slides), slide_pts)
        rank = pane_cell_ranks(pane_of, scell, valid=ing)
        sh = (n_slides, slide_pts)
        host = (
            cxy[:, 0].astype(np.float32), cxy[:, 1].astype(np.float32),
            ci[:, 0], ci[:, 1],
            np.where(ing, scell, 0).astype(np.int32),
            rank.astype(np.int32), soid, ing,
        )
        return tuple(jnp.asarray(a.reshape(sh)) for a in host)

    telemetry.enable()
    carry0 = tjoin_pane_init(grid.num_cells, 8, ppw, n_obj, jnp.float32)
    _, wmins = sharded_tjoin_pane_scan(
        mesh, carry0, jnp.arange(n_slides, dtype=jnp.int32),
        side_fields(lxy, lcell, loid), side_fields(rxy, rcell, roid),
        np.float32(radius), grid_n=grid.n, cap_w=8,
        layers=grid.candidate_layers(radius), ppw=ppw, num_ids=n_obj,
        pair_sel=16,
    )
    jax.device_get(wmins)
    legacy = telemetry.snapshot()["collectives"]
    telemetry.disable()
    legacy_bytes = legacy["by_kind"]["all_gather"]["bytes"]
    assert halo_bytes * 4 <= legacy_bytes, (
        f"halo moved {halo_bytes} B vs all-gather {legacy_bytes} B"
    )


# -- cross-shard watermarks --------------------------------------------------


def test_shard_watermark_gauges(mesh):
    rng = np.random.default_rng(7)
    xy, cell, valid = _cloud(rng, 4096)
    qxy, qcell, qok = _cloud(rng, 512)
    ts = rng.integers(0, 10_000, 4096).astype(np.int64)
    plan = plan_partition(GRID, 8, RADIUS)
    telemetry.enable()
    sharded_range_halo(
        mesh, plan, xy, valid, cell, qxy, qcell, qok, RADIUS, ts=ts,
    )
    wm = telemetry.snapshot()["shard_watermarks"]
    telemetry.disable()
    assert wm["shards"] == 8
    shard = plan.shard_of(cell)
    for s in range(8):
        assert wm["per_shard"][str(s)] == int(ts[shard == s].max())
    assert wm["merged_min"] == min(wm["per_shard"].values())


# -- checkpoint contract -----------------------------------------------------


def test_partition_plan_checkpoint_roundtrip():
    from spatialflink_tpu.checkpoint import (
        operator_state,
        restore_operator,
    )
    from spatialflink_tpu.operators import (
        PointPointRangeQuery,
        QueryConfiguration,
        QueryType,
    )

    conf = QueryConfiguration(QueryType.WindowBased, window_size=10,
                              slide_step=10)
    op = PointPointRangeQuery(conf, GRID)
    op.partition_plan = plan_partition(GRID, 8, RADIUS)
    state = operator_state(op)
    assert state["partition"]["n_shards"] == 8

    op2 = PointPointRangeQuery(conf, GRID)
    restore_operator(op2, state)
    np.testing.assert_array_equal(
        op2.partition_plan.bounds, op.partition_plan.bounds
    )

    # Resuming onto a different shard count is a re-plan, not a restore.
    op3 = PointPointRangeQuery(conf, GRID)
    op3.partition_plan = plan_partition(GRID, 4, RADIUS)
    with pytest.raises(ValueError, match="shard-count"):
        restore_operator(op3, state)
