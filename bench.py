"""Headline benchmark — continuous kNN (k=50) over 1M-point sliding windows.

The BASELINE.md north-star metric: points/sec/chip + p50 window latency on
continuous kNN, k=50, 1M-point windows, Beijing-extent stream, vs the
single-node CPU reference. The reference publishes no numbers; its own
benchmark harness is configured for a 20,000 events/sec single-node target
(BenchmarkRunner.java:25-26, InstrumentedMN_Q1.java:88-89), so
``vs_baseline`` = measured points/sec/chip ÷ 20,000.

The measured loop is the real per-window path: host window slice → pad →
device transfer → fused XLA program (cell-flag gather, masked distances,
per-object segment-min dedup, top-50) → result fetch. Object ids are dense
ints (the framework interns strings at ingest; interning is amortized
stream-side, not per window).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


WINDOW = 1_000_000
SLIDE = WINDOW // 2
N_WINDOWS = 20
K = 50
NUM_SEGMENTS = 16_384  # distinct objIDs
RADIUS = 0.05
BASELINE_EPS = 20_000.0


def main() -> None:
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.grid import UniformGrid
    from spatialflink_tpu.ops.cells import assign_cells, gather_cell_flags
    from spatialflink_tpu.ops.knn import knn_kernel

    from __graft_entry__ import BEIJING_GRID_ARGS, QUERY_POINT

    dev = jax.devices()[0]
    grid = UniformGrid(**BEIJING_GRID_ARGS)
    q = np.asarray(QUERY_POINT, np.float32)
    flags = grid.neighbor_flags(RADIUS, [grid.flat_cell(*q)])

    # Synthetic Beijing stream: enough points for N sliding windows.
    rng = np.random.default_rng(42)
    total = SLIDE * (N_WINDOWS - 1) + WINDOW
    xs = rng.uniform(115.5, 117.6, total).astype(np.float32)
    ys = rng.uniform(39.6, 41.1, total).astype(np.float32)
    stream_xy = np.stack([xs, ys], axis=1)
    # Wire format: object ids ship as int16 (NUM_SEGMENTS <= 32768) and
    # upcast on device — ingest bandwidth is the bottleneck in this
    # environment, not compute.
    stream_oid = (rng.integers(0, NUM_SEGMENTS, total)).astype(np.int16)
    valid = np.ones(WINDOW, bool)

    def step(xy_a, xy_b, oid_a, oid_b, valid, flags_table, query_xy):
        # Window = two consecutive slides, concatenated on device — each
        # ingested point crosses host→device exactly once (streaming
        # ingest), like the window assembler's slide panes.
        xy = jnp.concatenate([xy_a, xy_b], axis=0)
        oid = jnp.concatenate([oid_a, oid_b], axis=0).astype(jnp.int32)
        cell = assign_cells(xy, grid.min_x, grid.min_y, grid.cell_length, grid.n)
        pflags = gather_cell_flags(cell, flags_table)
        return knn_kernel(
            xy, valid, pflags, oid, query_xy, np.float32(RADIUS),
            k=K, num_segments=NUM_SEGMENTS,
        )

    jstep = jax.jit(step)
    flags_d = jax.device_put(jnp.asarray(flags), dev)
    q_d = jax.device_put(jnp.asarray(q), dev)
    valid_d = jax.device_put(jnp.asarray(valid), dev)

    def slide_arrays(i):
        lo, hi = i * SLIDE, (i + 1) * SLIDE
        return (
            jax.device_put(stream_xy[lo:hi], dev),
            jax.device_put(stream_oid[lo:hi], dev),
        )

    # Warm-up (compile) on window 0.
    xy_a, oid_a = slide_arrays(0)
    xy_b, oid_b = slide_arrays(1)
    res = jstep(xy_a, xy_b, oid_a, oid_b, valid_d, flags_d, q_d)
    jax.block_until_ready(res)

    # Kernel-level tracing hook (the SURVEY §5 "jax.profiler traces"
    # analog of the reference's Flink metric operators): set
    # SFT_PROFILE_DIR=<dir> to capture an XLA/runtime trace of the
    # measured loop (view with tensorboard or xprof).
    import contextlib
    import os as _os

    profile_dir = _os.environ.get("SFT_PROFILE_DIR")
    trace_ctx = (
        jax.profiler.trace(profile_dir)
        if profile_dir
        else contextlib.nullcontext()
    )

    latencies = []
    results = []
    slides = [(xy_a, oid_a), (xy_b, oid_b)]
    t_total0 = time.perf_counter()
    with trace_ctx:
        for w in range(N_WINDOWS):
            t0 = time.perf_counter()
            if w + 2 <= N_WINDOWS:
                # The slide after next starts transferring now (async
                # device_put) and overlaps this window's compute + result
                # fetch — streaming double-buffering.
                slides.append(slide_arrays(w + 2))
            (xy_a, oid_a), (xy_b, oid_b) = slides[w], slides[w + 1]
            res = jstep(xy_a, xy_b, oid_a, oid_b, valid_d, flags_d, q_d)
            nv = int(res.num_valid)  # result fetch = end-to-end window answer
            latencies.append(time.perf_counter() - t0)
            results.append(nv)
            if w >= 1:
                slides[w - 1] = None  # free the pane that left the window
    t_total = time.perf_counter() - t_total0

    # Ingest rate: distinct stream points consumed per second (each point
    # is ingested once but evaluated in 2 overlapping windows). This is the
    # quantity comparable to the reference's 20k events/sec baseline;
    # window-evaluations/sec would double-count the 50% overlap.
    distinct_points = SLIDE * (N_WINDOWS + 1)
    points_per_sec = distinct_points / t_total
    p50_ms = float(np.percentile(latencies, 50) * 1000)
    assert all(r == K for r in results), f"kNN underfilled: {results[:3]}"

    out = {
        "metric": "continuous_knn_k50_1M_window_points_per_sec_per_chip",
        "value": round(points_per_sec, 1),
        "unit": "points/s",
        "vs_baseline": round(points_per_sec / BASELINE_EPS, 2),
        "p50_window_latency_ms": round(p50_ms, 3),
        "device": str(dev),
        "windows": N_WINDOWS,
        "k": K,
    }
    # Measured CPU-backend throughput of the same fused program on this
    # host (bench_suite.py --cpu-baseline) — the measured counterpart to
    # the reference's configured 20k EPS target.
    try:
        from bench_suite import load_cpu_baseline

        cpu = load_cpu_baseline().get("continuous_knn_k50_1M_window")
        if cpu:
            out["vs_measured_cpu"] = round(points_per_sec / cpu, 2)
            # The CPU figure is the SAME fused kernel on XLA:CPU with data
            # already in RAM (no ingest); the chip path here is bound by the
            # ~28 MB/s measurement tunnel, not TPU silicon. See BASELINE.md
            # "Measured CPU baseline" for the full interpretation.
            out["measured_cpu_is"] = "same-kernel XLA:CPU in-RAM upper bound"
    except Exception:
        pass
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
