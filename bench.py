"""Headline benchmark — continuous kNN (k=50) over 1M-point sliding windows.

The BASELINE.md north-star metric: points/sec/chip + p50 window latency on
continuous kNN, k=50, 1M-point windows, Beijing-extent stream, vs the
single-node CPU reference. The reference publishes no numbers; its own
benchmark harness is configured for a 20,000 events/sec single-node target
(BenchmarkRunner.java:25-26, InstrumentedMN_Q1.java:88-89), so
``vs_baseline`` = measured points/sec/chip ÷ 20,000.

The measured loop is the real per-window path: host window slice → pad →
device transfer → fused XLA program (cell-flag gather, masked distances,
per-object segment-min dedup, top-50) → result fetch. Object ids are dense
ints (the framework interns strings at ingest; interning is amortized
stream-side, not per window).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


WINDOW = 1_000_000
SLIDE = WINDOW // 2
N_WINDOWS = 20
K = 50
NUM_SEGMENTS = 16_384  # distinct objIDs
RADIUS = 0.05
BASELINE_EPS = 20_000.0


def main() -> None:
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.grid import UniformGrid
    from spatialflink_tpu.ops.cells import assign_cells
    from spatialflink_tpu.ops.knn import knn_merge_digest_list, knn_pane_digest

    from __graft_entry__ import BEIJING_GRID_ARGS, QUERY_POINT

    dev = jax.devices()[0]
    grid = UniformGrid(**BEIJING_GRID_ARGS)
    q = np.asarray(QUERY_POINT, np.float32)
    flags = grid.neighbor_flags(RADIUS, [grid.flat_cell(*q)])

    # Synthetic Beijing stream: enough points for N sliding windows.
    rng = np.random.default_rng(42)
    total = SLIDE * (N_WINDOWS - 1) + WINDOW
    xs = rng.uniform(115.5, 117.6, total).astype(np.float32)
    ys = rng.uniform(39.6, 41.1, total).astype(np.float32)
    stream_xy = np.stack([xs, ys], axis=1)
    # Wire format: object ids ship as int16 (NUM_SEGMENTS <= 32768) and
    # upcast on device — ingest bandwidth is the bottleneck in this
    # environment, not compute.
    stream_oid = (rng.integers(0, NUM_SEGMENTS, total)).astype(np.int16)
    valid = np.ones(SLIDE, bool)  # digest operates on one slide pane

    def digest_step(xy_s, oid_s, valid, flags_table, query_xy):
        # One slide pane → per-object minima digest. Each ingested point
        # crosses host→device once and is DIGESTED once; every window is a
        # merge of its two slides' carried digests (ops/knn.py pane carry —
        # the same program the operator's query_panes/run_soa_panes run).
        cell = assign_cells(
            xy_s, grid.min_x, grid.min_y, grid.cell_length, grid.n
        )
        return knn_pane_digest(
            xy_s, valid, cell, flags_table, oid_s.astype(jnp.int32),
            query_xy, np.float32(RADIUS), jnp.int32(0),
            num_segments=NUM_SEGMENTS,
        )

    jdigest = jax.jit(digest_step)
    jmerge = jax.jit(knn_merge_digest_list, static_argnames="k")
    bases = np.asarray([0, SLIDE], np.int32)  # window-local slide offsets
    flags_d = jax.device_put(jnp.asarray(flags), dev)
    q_d = jax.device_put(jnp.asarray(q), dev)
    valid_d = jax.device_put(jnp.asarray(valid), dev)

    def slide_arrays(i):
        lo, hi = i * SLIDE, (i + 1) * SLIDE
        return (
            jax.device_put(stream_xy[lo:hi], dev),
            jax.device_put(stream_oid[lo:hi], dev),
        )

    # Warm-up (compile) + slide-0 digest (its ingest precedes window 0).
    xy_a, oid_a = slide_arrays(0)
    d_prev = jdigest(xy_a, oid_a, valid_d, flags_d, q_d)
    warm = jmerge((d_prev.seg_min, d_prev.seg_min),
                  (d_prev.rep, d_prev.rep), bases, k=K)
    jax.device_get(warm.num_valid)  # true sync (block_until_ready is a
    # no-op on the axon tunnel)

    # Kernel-level tracing hook (the SURVEY §5 "jax.profiler traces"
    # analog of the reference's Flink metric operators): set
    # SFT_PROFILE_DIR=<dir> to capture an XLA/runtime trace of the
    # measured loop (view with tensorboard or xprof).
    import contextlib
    import os as _os

    profile_dir = _os.environ.get("SFT_PROFILE_DIR")
    trace_ctx = (
        jax.profiler.trace(profile_dir)
        if profile_dir
        else contextlib.nullcontext()
    )

    # Throughput loop: fully pipelined — ingest double-buffered, window
    # results collected as handles and materialized once at the end
    # (device_get is the only true sync on this tunnel; a per-window fetch
    # would drain the pipeline every slide). The measurement tunnel's
    # bandwidth fluctuates ±50% run to run, so the loop runs 5 times and
    # the MEDIAN rate is reported.
    d_slide0 = d_prev  # window 0's carried slide; re-seeded per repetition

    def timed_run():
        nonlocal d_prev
        # Re-seed outside the timed region: carrying the previous run's
        # final slide into window 0 would merge non-adjacent panes (same
        # timing, wrong window semantics in the reported results).
        d_prev = d_slide0
        fired = []
        t0 = time.perf_counter()
        staged = [slide_arrays(1), slide_arrays(2)]
        for w in range(N_WINDOWS):
            if w + 3 <= N_WINDOWS:
                staged.append(slide_arrays(w + 3))
            xy_s, oid_s = staged.pop(0)
            d_new = jdigest(xy_s, oid_s, valid_d, flags_d, q_d)
            fired.append(jmerge((d_prev.seg_min, d_new.seg_min),
                                (d_prev.rep, d_new.rep), bases, k=K))
            d_prev = d_new  # the slide that stays in the next window
        results = [int(r.num_valid) for r in jax.device_get(fired)]
        return time.perf_counter() - t0, results

    with trace_ctx:
        runs = [timed_run() for _ in range(5)]
    t_total = float(np.median([t for t, _ in runs]))
    results = runs[-1][1]

    # Latency probe: window-close → answer-on-host, measured synchronously
    # on pre-staged slides (in a live stream the slide's events finished
    # transferring during the window interval; what remains at window
    # close is digest + merge + result fetch).
    latencies = []
    for w in range(5):
        xy_s, oid_s = slide_arrays(w + 1)
        # Staged: BOTH buffers' ingest completed before window close.
        jax.device_get((xy_s, oid_s))
        t0 = time.perf_counter()
        d_new = jdigest(xy_s, oid_s, valid_d, flags_d, q_d)
        res = jmerge((d_prev.seg_min, d_new.seg_min),
                     (d_prev.rep, d_new.rep), bases, k=K)
        int(res.num_valid)
        latencies.append(time.perf_counter() - t0)
        d_prev = d_new

    # Ingest rate: distinct stream points consumed per second (each point
    # is ingested once, digested once, and evaluated in 2 overlapping
    # windows via the digest merge). The timed region ingests slides
    # 1..N_WINDOWS (slide 0 precedes window 0). Comparable to the
    # reference's 20k events/sec target; window-evaluations/sec would
    # double-count the 50% overlap.
    distinct_points = SLIDE * N_WINDOWS
    points_per_sec = distinct_points / t_total
    p50_ms = float(np.percentile(latencies, 50) * 1000)
    assert all(r == K for r in results), f"kNN underfilled: {results[:3]}"

    out = {
        "metric": "continuous_knn_k50_1M_window_points_per_sec_per_chip",
        "value": round(points_per_sec, 1),
        "unit": "points/s",
        "vs_baseline": round(points_per_sec / BASELINE_EPS, 2),
        "p50_window_latency_ms": round(p50_ms, 3),
        "device": str(dev),
        "windows": N_WINDOWS,
        "k": K,
    }
    # Measured CPU-backend throughput of the same fused program on this
    # host (bench_suite.py --cpu-baseline) — the measured counterpart to
    # the reference's configured 20k EPS target.
    try:
        from bench_suite import load_cpu_baseline

        cpu = load_cpu_baseline().get("continuous_knn_k50_1M_window")
        if cpu:
            out["vs_measured_cpu"] = round(points_per_sec / cpu, 2)
            # The CPU figure is the SAME fused kernel on XLA:CPU with data
            # already in RAM (no ingest); the chip path here is bound by the
            # ~28 MB/s measurement tunnel, not TPU silicon. See BASELINE.md
            # "Measured CPU baseline" for the full interpretation.
            out["measured_cpu_is"] = "same-kernel XLA:CPU in-RAM upper bound"
    except Exception:
        pass
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
