"""Headline benchmark — continuous kNN (k=50) over 1M-point sliding windows.

The BASELINE.md north-star metric: points/sec/chip + p50 window latency on
continuous kNN, k=50, 1M-point windows, Beijing-extent stream, vs the
single-node CPU reference. The reference publishes no numbers; its own
benchmark harness is configured for a 20,000 events/sec single-node target
(BenchmarkRunner.java:25-26, InstrumentedMN_Q1.java:88-89), so
``vs_baseline`` = measured points/sec/chip ÷ 20,000.

The measured program is the pane-carry sliding-window pipeline in its
TPU-first form (ops/knn.py):

  6 B/pt wire record (uint16 grid-relative coords + int16 interned oid,
  streams/wire.py — device upcast bit-exact) → top-``cand``-compacted
  pane digest (``knn_pane_digest_compact``: radius-masked distances →
  lax.top_k → tiny segment-min scatters; automatic exact fallback) →
  window merge + top-50. One transfer and ONE dispatch per slide.

TWO throughputs in the single JSON line:

- ``value`` (points/s, e2e): host slide → wire transfer → digest+merge →
  pipelined result fetch. In this environment the host→device link is a
  ~20-30 MB/s measurement tunnel, so this is TUNNEL-bound (~6 B/pt ⇒
  ceiling ≈ link/6), not silicon.
- ``device_resident_points_per_sec``: same wire records staged in HBM
  once, same digest+merge per window inside one compiled scan per pass,
  passes chained through the carried digest, EVERY window's full top-50
  result kept live and fetched. The chip's own sustained rate on the
  flagship kernel — compare against the measured XLA:CPU in-RAM figure
  (CPU_BASELINE.json, regenerated with this same program).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


WINDOW = 1_000_000
SLIDE = WINDOW // 2
N_WINDOWS = 20
K = 50
NUM_SEGMENTS = 16_384  # distinct objIDs
RADIUS = 0.05
CAND = 8_192  # top-k compaction width (exact fallback above this)
BASELINE_EPS = 20_000.0


def build_headline_step(jnp, wf, slide=SLIDE, k=K, nseg=NUM_SEGMENTS,
                        radius=RADIUS, cand=CAND, pallas=False):
    """The headline program, shared verbatim with the CPU-baseline run
    (bench_suite.bench_headline_knn_1m) AND the shipped operator path
    (operators/knn_query.py:run_wire_panes): one slide of packed wire
    records + the carried digest → (new digest, window KnnResult).

    The wire→digest step itself lives in ops/wire_knn.py — ONE program
    for operator, bench, and suite (VERDICT r4 weak #3: the measured
    and shipped programs had diverged). This wrapper adds only the
    2-pane window merge and bakes the statics.

    ``wire_s``: (3, slide) uint16 PLANE-MAJOR rows — x_q, y_q, oid (int16
    bits). Returns a raw fn for jax.jit / lax.scan embedding.

    ``pallas=True`` (TPU): the fused Pallas extraction with the
    IN-PROGRAM ``lax.cond`` overflow fallback — exact either way;
    main() self-checks one slide against the XLA step before trusting
    the lowering (ops/wire_knn.py:digests_agree).
    """
    from spatialflink_tpu.ops.knn import knn_merge_digest_list
    from spatialflink_tpu.ops.wire_knn import make_wire_digest_step

    bases = np.asarray([0, slide], np.int32)
    scale = jnp.asarray(np.asarray(wf.scale, np.float32))
    origin = jnp.asarray(np.asarray(wf.origin, np.float32))
    r32 = np.float32(radius)
    digest = make_wire_digest_step(
        num_segments=nseg, cand=cand,
        strategy="pallas" if pallas else "xla",
    )

    def step(seg_prev, rep_prev, wire_s, query_xy):
        d = digest(wire_s, wire_s.shape[1], query_xy, scale, origin, r32)
        res = knn_merge_digest_list(
            (seg_prev, d.seg_min), (rep_prev, d.rep), bases, k=k
        )
        return d.seg_min, d.rep, res

    return step


_ERROR_RECORD = {
    "metric": "continuous_knn_k50_1M_window_points_per_sec_per_chip",
    "value": 0,
    "unit": "points/s",
    "vs_baseline": 0,
}


def _last_good_path():
    import os

    return os.environ.get(
        "SFT_BENCH_LAST_GOOD",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_LAST_GOOD.json"),
    )


def _record_last_good(record: dict) -> None:
    """Persist the newest successful capture (value > 0) so a later
    outage degrades the bench record to "stale" instead of zero. Stored
    alongside the record: capture wall-clock (UTC ISO) and the git SHA
    the capture ran against."""
    import datetime
    import os
    import subprocess

    if not record.get("value") or record.get("smoke") \
            or record.get("tainted"):
        # Toy-size smoke captures (SFT_BENCH_SMOKE contract runs) and
        # TAINTED ablation captures (kernels stubbed to zeros —
        # spatialflink_tpu/ablation.py) must never shadow a real chip
        # number in the last-good store.
        return
    sha = None
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or None
    except Exception:
        pass
    try:
        with open(_last_good_path(), "w") as f:
            json.dump({
                "record": record,
                "captured_at": datetime.datetime.now(
                    datetime.timezone.utc
                ).isoformat(timespec="seconds"),
                "git_sha": sha,
            }, f, indent=1)
            f.write("\n")
    except OSError as e:  # pragma: no cover - fs trouble is non-fatal
        sys.stderr.write(f"last-good store not written: {e}\n")


def _load_last_good():
    try:
        with open(_last_good_path()) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _seal_stream_supervisor(reason: str, sealed_by: str = "supervisor") -> None:
    """Failure-path ledger-stream seal WITHOUT telemetry/jax.

    The child's telemetry owns the stream, but on the deadline/SIGTERM/
    child-crash paths the child died without its epilogue — and on the
    child's own dial-timeout path (below) jax may be wedged in an
    unkillable C call, so even in-process the seal must not touch it.
    The stream is plain JSONL, so anyone can append the sealing epilogue
    directly, turning an abandoned stream into an attributable artifact
    (``sfprof recover`` reports the termination reason instead of
    guessing). Skips cleanly when no stream was configured/created or
    the child already sealed."""
    import os
    import time

    path = os.environ.get("SFT_LEDGER_STREAM")
    if not path or not os.path.exists(path):
        return
    try:
        # Tail big enough to hold any single record (epilogues carry the
        # bench record + SLO verdict; checkpoints the kernel table) — a
        # 4 KiB peek once started MID-epilogue and double-sealed.
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(size - (4 << 20), 0))
            tail = f.read()
        # Walk complete tail lines newest-first; the first one that
        # parses tells us whether the child already sealed (the chunk
        # boundary may cut the oldest line — parse failures there are
        # expected and skipped).
        for line in reversed(tail.splitlines()):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # half-written tail / chunk-boundary fragment
            if isinstance(rec, dict) and rec.get("t") == "epilogue":
                return  # the child sealed it before dying
            break  # newest parseable record is not an epilogue: seal
        with open(path, "ab") as f:
            lead = b"" if tail.endswith(b"\n") or not tail else b"\n"
            # The leading newline terminates a half-written last line so
            # the epilogue starts on its own line (recover scans past
            # the corrupt fragment and still honors this seal).
            f.write(lead + json.dumps({
                "t": "epilogue", "unix": time.time(),
                "reason": str(reason), "sealed_by": str(sealed_by),
            }).encode() + b"\n")
    except OSError as e:  # pragma: no cover - fs trouble is non-fatal
        sys.stderr.write(f"ledger stream not sealed: {e}\n")


def _supervise() -> None:
    """Retry-with-backoff around the real benchmark: a down tunnel hangs
    device init in an unkillable C call, so each dial attempt is a FRESH
    subprocess (in-process retry cannot recover a hung init). The driver
    run is the round's ONE shot at an on-chip record, so the dials
    spread over a wall-clock window (default backoffs 30/60/120/300/
    600 s). Override with SFT_BENCH_BACKOFFS="s1,s2,..." (tests use
    "0").

    The whole schedule is bounded by a HARD wall-clock deadline
    (SFT_BENCH_DEADLINE seconds, default 600): the r5 record was
    ``parsed: null`` because the dial schedule outlived the driver's
    kill budget — the process died mid-backoff without ever printing.
    The deadline is checked before each dial AND each backoff sleep,
    each child's timeout is clipped to the remaining budget, and a
    SIGTERM handler prints the same final record before exiting, so the
    only unreachable path is SIGKILL — which the deadline exists to
    preempt. NOTE the default trade-off: printing SOMETHING within the
    driver's patience beats riding out a long outage, so under the
    600 s default only the early dials (and a clipped child budget)
    ever run — the full 30…600 s schedule and the 3000 s child timeout
    only play out when the driver raises SFT_BENCH_DEADLINE (a
    measurement session that can wait hours for the tunnel should set
    it to e.g. 7200).

    Outcomes, always exactly ONE stdout JSON line:
    - success → the child's record relayed verbatim; also persisted to
      BENCH_LAST_GOOD.json (value, device, UTC timestamp, git SHA).
    - final failure / deadline / SIGTERM → an honest error record
      (``value`` 0, never a stale number) carrying ``last_good``
      metadata from the newest persisted capture, clearly labeled
      ``stale: true``. A child killed mid-print can leave a truncated
      JSON-ish line on stdout — that parse failure degrades to the
      error record, never a crash (the driver contract is ONE line)."""
    import os
    import signal
    import subprocess
    import time

    backoffs = [
        float(s) for s in os.environ.get(
            "SFT_BENCH_BACKOFFS", "30,60,120,300,600"
        ).split(",") if s.strip()
    ]
    deadline = float(os.environ.get("SFT_BENCH_DEADLINE", "600"))
    t0 = time.monotonic()
    state = {"out": "", "rc": 3, "attempts": 0, "done": False}

    def final_record(error):
        lines = [ln for ln in state["out"].strip().splitlines()
                 if ln.startswith("{")]
        record = None
        if lines:
            try:
                record = json.loads(lines[-1])
            except ValueError:
                record = None  # child died mid-print: truncated JSON
        if record is None:
            record = {**_ERROR_RECORD, "error": error}
        good = _load_last_good()
        if good and good.get("record", {}).get("value"):
            record["last_good"] = {
                "stale": True,
                "value": good["record"]["value"],
                "unit": good["record"].get("unit"),
                "vs_baseline": good["record"].get("vs_baseline"),
                "device": good["record"].get("device"),
                "device_resident_points_per_sec": good["record"].get(
                    "device_resident_points_per_sec"),
                "captured_at": good.get("captured_at"),
                "git_sha": good.get("git_sha"),
            }
        return record

    def emit_failure(error):
        if state["done"]:  # the one-line contract: never print twice
            return
        state["done"] = True
        # Seal BEFORE printing: the driver may kill us the instant the
        # line lands, and the epilogue is what makes the dead child's
        # stream recoverable with an honest termination reason.
        _seal_stream_supervisor(error)
        print(json.dumps(final_record(error)))
        sys.stdout.flush()

    def on_sigterm(signum, frame):
        # The driver's patience beat ours: print the stale-last-good
        # record NOW — dying silently is the r5 `parsed: null` failure.
        emit_failure(
            f"terminated (SIGTERM) after {state['attempts']} dial "
            "attempts"
        )
        os._exit(3)

    signal.signal(signal.SIGTERM, on_sigterm)

    fail_reason = ""
    for attempt in range(len(backoffs) + 1):
        if attempt:
            wait = backoffs[attempt - 1]
            if time.monotonic() - t0 + wait >= deadline:
                fail_reason = (
                    f"bench deadline {float(deadline):.0f}s reached after "
                    f"{state['attempts']} dial attempts"
                )
                break
            time.sleep(wait)
        remaining = deadline - (time.monotonic() - t0)
        if remaining <= 0:
            fail_reason = (
                f"bench deadline {float(deadline):.0f}s reached after "
                f"{state['attempts']} dial attempts"
            )
            break
        state["attempts"] += 1
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env={**os.environ, "SFT_BENCH_CHILD": "1"},
                capture_output=True, text=True,
                timeout=min(3000.0, max(remaining, 10.0)),
            )
            state["out"], state["rc"] = p.stdout, p.returncode
            sys.stderr.write(p.stderr[-4000:])
        except subprocess.TimeoutExpired as e:
            state["out"] = (e.stdout or b"").decode(
                errors="replace") if isinstance(
                e.stdout, bytes) else (e.stdout or "")
            state["rc"] = 3
            continue
        if p.returncode == 0:
            # From here the child's record IS the output: stop honoring
            # SIGTERM first — a kill landing between `done = True` and
            # the relay would otherwise print NOTHING (the handler sees
            # done and returns), recreating the r5 zero-line record.
            signal.signal(signal.SIGTERM, signal.SIG_IGN)
            state["done"] = True
            sys.stdout.write(p.stdout)
            lines = [ln for ln in p.stdout.strip().splitlines()
                     if ln.startswith("{")]
            if lines:
                try:
                    _record_last_good(json.loads(lines[-1]))
                except ValueError:
                    pass
            return
    emit_failure(
        fail_reason or f"bench child failed rc={state['rc']} "
                       f"after {state['attempts']} attempts"
    )
    sys.exit(3)


def main() -> None:
    global WINDOW, SLIDE, N_WINDOWS, NUM_SEGMENTS, RADIUS, CAND
    import os as _os
    import threading

    if not _os.environ.get("SFT_BENCH_CHILD"):
        _supervise()
        return

    hang = _os.environ.get("SFT_BENCH_HANG")
    if hang:
        # Contract-test hook: simulate a child stuck dialing a
        # half-open tunnel (sleeps without printing) so the supervisor's
        # deadline / SIGTERM paths can be pinned without a device.
        time.sleep(float(hang))
    if _os.environ.get("SFT_BENCH_FORCE_FAIL"):
        # Simulated-outage hook for the JSON-contract test: behave
        # exactly like the init-watchdog firing, without dialing the
        # device (a real down tunnel hangs for 180 s per dial).
        if _os.environ["SFT_BENCH_FORCE_FAIL"] == "truncated":
            # A child SIGKILLed mid-print leaves a half-written JSON
            # line — the supervisor must degrade it to the error
            # record, not crash the one-line driver contract.
            sys.stdout.write('{"metric": "continuous_knn_k50_1M_wind')
            sys.stdout.flush()
            sys.exit(3)
        print(json.dumps({
            **_ERROR_RECORD,
            "error": "device tunnel unreachable (simulated outage)",
        }))
        sys.exit(3)
    fake = _os.environ.get("SFT_BENCH_FAKE_RECORD")
    if fake:
        # Simulated-success hook (contract test): the supervisor must
        # relay this verbatim AND persist it to the last-good store.
        print(fake)
        return

    # Dial watchdogs: the tunnel's site hook dials the device while jax
    # initializes, and a down/half-open tunnel can hang EITHER that C
    # call (the r3–r5 "hang at interpreter boot" mode) OR the first real
    # device op after a seemingly healthy init. TWO bounded phases, each
    # under SFT_DIAL_DEADLINE_S (default 180 s ≈ 6× a cold plugin
    # start): phase 1 covers import jax → device discovery; phase 2
    # re-arms just before the warm-up step and covers the first
    # ship + compile + true-sync fetch (the only ops that can wedge on a
    # half-open tunnel). Host-side work in between — stream generation,
    # packing — is deliberately OUTSIDE both windows: it cannot hang on
    # the tunnel and must not eat the dial budget. On timeout the
    # watchdog seals the ledger stream with reason ``dial_timeout``
    # (plain JSONL append — jax is wedged, telemetry must not be asked
    # to flush through it), prints the honest one-line record, and
    # exits so the supervisor can retry the dial in a fresh process
    # instead of riding out its full deadline.
    _dial_deadline = float(_os.environ.get("SFT_DIAL_DEADLINE_S", "180"))

    def _arm_dial_watchdog(label: str) -> threading.Event:
        ok = threading.Event()

        def _watchdog():
            if not ok.wait(_dial_deadline):
                if ok.is_set():  # lost the race at the boundary
                    return
                _seal_stream_supervisor("dial_timeout",
                                        sealed_by="watchdog")
                print(json.dumps({
                    **_ERROR_RECORD,
                    "error": f"device tunnel unreachable ({label} hang "
                             f"> {float(_dial_deadline):.0f} s; "
                             "SFT_DIAL_DEADLINE_S)",
                }))
                sys.stdout.flush()
                _os._exit(3)

        threading.Thread(target=_watchdog, daemon=True).start()
        return ok

    _init_ok = _arm_dial_watchdog("interpreter/device dial")

    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.grid import UniformGrid
    from spatialflink_tpu.streams.wire import WireFormat

    from __graft_entry__ import BEIJING_GRID_ARGS, QUERY_POINT

    dev = jax.devices()[0]
    _init_ok.set()  # phase 1 done: the dial answered. Device DISCOVERY
    # succeeding does not prove the tunnel can move bytes (the half-open
    # mode) — phase 2 below re-arms around the first real device op.

    smoke = bool(_os.environ.get("SFT_BENCH_SMOKE"))
    if smoke:
        # Contract-test preset (tests/test_bench_contract.py): the SAME
        # program at toy sizes — window stays 2× slide, density/radius
        # chosen so every window still fills its top-50 — runnable on
        # XLA:CPU in seconds. Never persisted to the last-good store.
        WINDOW, SLIDE, N_WINDOWS = 4_096, 2_048, 8
        NUM_SEGMENTS, RADIUS, CAND = 512, 0.5, 256

    from spatialflink_tpu.telemetry import (
        LinkProbe,
        instrument_jit,
        telemetry,
    )

    # Runtime telemetry rides the measured run: recompile detection on the
    # jitted steps, host→device bytes at the staging device_puts,
    # device→host bytes + true-sync timing at the fetches the loops
    # already do (zero extra round trips), window latency from the
    # latency-probe spans. Summary lands in the JSON line's "telemetry"
    # block; SFT_TRACE_PATH additionally captures a Chrome-trace file;
    # SFT_LEDGER_STREAM makes the capture incrementally durable (JSONL
    # checkpoints at window/phase boundaries — a SIGKILL mid-run loses at
    # most one flush interval; `sfprof recover` rebuilds the ledger).
    telemetry.enable(
        trace_path=_os.environ.get("SFT_TRACE_PATH"),
        stream_path=_os.environ.get("SFT_LEDGER_STREAM"),
    )

    # Live SLO gating (SFT_SLO_SPEC=<spec.json>): the declarative spec is
    # evaluated incrementally as probe windows fire; violations become
    # slo_violation:* events in the trace/stream and the verdict block
    # rides the record + ledger. `sfprof health --slo` applies the SAME
    # spec post-hoc.
    slo_engine = None
    _spec_path = _os.environ.get("SFT_SLO_SPEC")
    if _spec_path:
        from spatialflink_tpu import slo as slo_mod

        slo_engine = slo_mod.install(
            slo_mod.SloEngine(slo_mod.SloSpec.from_file(_spec_path))
        )

    # Overload control (SFT_OVERLOAD_POLICY=<inline JSON | policy.json>):
    # installs the process-global controller so chip captures get the
    # degradation ladder (SLO violations step it down), the counters
    # ride snapshot()["overload"] into the record/ledger/stream, and a
    # shed_budget/degraded_window_budget spec can gate the run.
    overload_ctrl = None
    _ov_spec = _os.environ.get("SFT_OVERLOAD_POLICY")
    if _ov_spec:
        from spatialflink_tpu import overload as overload_mod

        overload_ctrl = overload_mod.install(
            overload_mod.OverloadController(
                overload_mod.OverloadPolicy.from_env(_ov_spec)
            )
        )

    grid = UniformGrid(**BEIJING_GRID_ARGS)
    wf = WireFormat.for_grid(grid)
    q = np.asarray(QUERY_POINT, np.float32)

    # Synthetic Beijing stream packed in the 6 B/pt wire format: one
    # contiguous (n, 3) uint16 record stream (quantized coords ~3.2e-5°
    # lattice ≈ 3.6 m — beneath GPS accuracy, upcast bit-exact per
    # tests/test_wire.py; int16 interned oid). ONE transfer per slide.
    rng = np.random.default_rng(42)
    total = SLIDE * (N_WINDOWS - 1) + WINDOW
    xyq = wf.quantize(np.stack(
        [rng.uniform(115.5, 117.6, total), rng.uniform(39.6, 41.1, total)],
        axis=1,
    ))
    oid16 = (rng.integers(0, NUM_SEGMENTS, total)).astype(np.int16)
    wire = np.concatenate([xyq, oid16.view(np.uint16)[:, None]], axis=1)

    step = build_headline_step(jnp, wf, slide=SLIDE, nseg=NUM_SEGMENTS,
                               radius=RADIUS, cand=CAND)
    jstep = instrument_jit(jax.jit(step), name="headline_step")
    # Throughput loops donate the carried digest buffers: without
    # donation every dispatch materializes fresh (nseg,) seg/rep outputs
    # and the runtime schedules carry copies (~230 ms per 100 steps in
    # the round-3 profiler trace, BASELINE.md). Donated inputs are dead
    # after the call, so resets re-copy seg0/rep0 device-side.
    jstep_d = instrument_jit(
        jax.jit(step, donate_argnums=(0, 1)), name="headline_step_donated"
    )
    jcopy = jax.jit(lambda a: a.copy())
    q_d = jax.device_put(jnp.asarray(q), dev)
    big = np.float32(np.finfo(np.float32).max)
    empty_seg = jax.device_put(
        jnp.full((NUM_SEGMENTS,), big, jnp.float32), dev
    )
    empty_rep = jax.device_put(
        jnp.full((NUM_SEGMENTS,), np.iinfo(np.int32).max, jnp.int32), dev
    )

    def slide_wire(i):
        # plane-major (3, SLIDE) — see build_headline_step's layout note
        host = np.ascontiguousarray(wire[i * SLIDE:(i + 1) * SLIDE].T)
        telemetry.account_h2d(host.nbytes)
        return jax.device_put(host, dev)

    # Phase 2: the first device op (ship + compile + true-sync fetch)
    # under its own fresh dial deadline — host data generation above is
    # excluded, it cannot hang on the tunnel.
    _first_op_ok = _arm_dial_watchdog("first device op")
    _dial_hang = _os.environ.get("SFT_BENCH_DIAL_HANG")
    if _dial_hang:
        # Contract-test hook: simulate the first device op hanging on a
        # half-open tunnel (device discovery succeeded, bytes don't
        # move) so the dial watchdog's seal/record path can be pinned
        # without a device (tests/test_bench_contract.py).
        time.sleep(float(_dial_hang))

    # Warm-up (compile) + slide-0 digest (its ingest precedes window 0).
    seg0, rep0, warm = jstep(empty_seg, empty_rep, slide_wire(0), q_d)
    jax.device_get(warm.num_valid)  # true sync (block_until_ready is a
    # no-op on the axon tunnel)
    _first_op_ok.set()  # bytes moved through the tunnel — disarmed

    # Link-health probe: tiny fixed-shape round trips at PHASE BOUNDARIES
    # only (never inside a window span), so "chip slow" and "tunnel
    # degraded" are distinguishable in the record — the gauges land in
    # the telemetry snapshot and the JSON line's "link_probe" block, and
    # `sfprof diff` annotates (never widens) its bands with them.
    probe = None
    if not _os.environ.get("SFT_NO_LINK_PROBE"):
        probe = LinkProbe(dev)
        probe.sample()
    # Phase boundary: warm-up done — checkpoint the ledger stream now so
    # a crash during the throughput loops already has a recoverable
    # prefix (the SIGKILL chaos test kills right after this point).
    telemetry.maybe_flush_stream(force=True)

    import contextlib
    import os as _os

    # Fused Pallas digest selection (TPU only): self-check one slide
    # against the XLA step — the in-radius SET must match exactly,
    # distances within 1 ulp (Mosaic vs XLA FMA freedom) — then the
    # throughput loops run the fused step (exactness is in-program via
    # its lax.cond fallback). Any failure → stay on the XLA step.
    step_kind = "xla"
    if dev.platform in ("tpu", "axon") and not _os.environ.get(
            "SFT_NO_PALLAS_DIGEST"):
        try:
            from spatialflink_tpu.ops.wire_knn import digests_agree

            pstep = build_headline_step(jnp, wf, slide=SLIDE,
                                        nseg=NUM_SEGMENTS, radius=RADIUS,
                                        cand=CAND, pallas=True)
            jp = instrument_jit(jax.jit(pstep), name="headline_step_pallas")
            s_p, r_p, res_p = jp(empty_seg, empty_rep, slide_wire(0), q_d)
            if digests_agree(s_p, r_p, seg0, rep0):
                step = pstep
                jstep = jp
                jstep_d = instrument_jit(
                    jax.jit(pstep, donate_argnums=(0, 1)),
                    name="headline_step_pallas_donated",
                )
                seg0, rep0 = s_p, r_p  # slide-0 digest from the same step
                step_kind = "pallas"
        except Exception as e:  # pragma: no cover - lowering failure
            sys.stderr.write(f"pallas digest disabled: {e!r}\n")

    # Kernel-level tracing hook (the SURVEY §5 "jax.profiler traces"
    # analog of the reference's Flink metric operators): set
    # SFT_PROFILE_DIR=<dir> to capture an XLA/runtime trace of the
    # measured loop (view with tensorboard or xprof).

    profile_dir = _os.environ.get("SFT_PROFILE_DIR")
    trace_ctx = (
        jax.profiler.trace(profile_dir)
        if profile_dir
        else contextlib.nullcontext()
    )

    # Throughput loop: fully pipelined through the shared ingest
    # executor (spatialflink_tpu/pipeline.py — the promoted form of the
    # hand-rolled slide double-buffering this loop used to carry): one
    # transfer + one dispatch per slide, window results collected as
    # in-flight handles and materialized once at the end-of-run drain
    # (device_get is the only true sync on this tunnel; a per-window
    # fetch would drain the pipeline every slide — fetch_lag=N_WINDOWS
    # keeps every fetch in the single final drain). depth counts the
    # in-compute item (pipeline.py), so depth=3 reproduces the old
    # loop's cadence exactly: TWO slides staged beyond the one being
    # computed. The tunnel's bandwidth fluctuates ±50% run to run, so
    # the loop runs 5 times and the MEDIAN rate is reported.
    from spatialflink_tpu.pipeline import PipelinedExecutor, PipelinePolicy
    from spatialflink_tpu import pipeline as pipeline_mod

    throughput_pol = PipelinePolicy(depth=3, fetch_lag=N_WINDOWS)

    def timed_run():
        # Re-seed from slide 0's digest outside the timed region:
        # carrying the previous run's final slide into window 0 would
        # merge non-adjacent panes. Copies, not aliases — jstep_d
        # donates its carry inputs (the executor hands each shipped
        # slide to exactly one compute, so donation never aliases an
        # in-flight transfer).
        st = {"sp": jcopy(seg0), "rp": jcopy(rep0)}

        def compute(w, wire_d):
            st["sp"], st["rp"], res = jstep_d(st["sp"], st["rp"],
                                              wire_d, q_d)
            return res.num_valid

        ex = PipelinedExecutor(
            throughput_pol, ship=slide_wire, compute=compute,
            fetch=telemetry.fetch, label="headline", node="headline",
        )
        t0 = time.perf_counter()
        results = [int(v) for v in ex.run(range(1, N_WINDOWS + 1))]
        return time.perf_counter() - t0, results

    if slo_engine is not None:
        # Start the engine's EPS clock NOW: the first real feed happens
        # after run 1 completes, and crediting run 1's points without
        # run 1's elapsed time would inflate live EPS ~25% (an
        # eps_floor gate that under-gates is worse than none).
        slo_engine.observe_window(0)
    with trace_ctx:
        runs = []
        for _ in range(5):
            runs.append(timed_run())
            # Between timed runs = a phase boundary: probe the link and
            # feed the SLO engine the windows that just fired (outside
            # the timed region — the engine's counters are host-cheap
            # but the EPS floor must see real points).
            if probe is not None:
                probe.sample()
            if slo_engine is not None:
                for _ in range(N_WINDOWS):
                    slo_engine.observe_window(SLIDE, lag_ms=0.0)
    telemetry.maybe_flush_stream(force=True)
    t_total = float(np.median([t for t, _ in runs]))
    results = runs[-1][1]

    # Latency probe: window-close → answer-on-host, measured synchronously
    # on pre-staged slides (in a live stream the slide's events finished
    # transferring during the window interval; what remains at window
    # close is digest + merge + result fetch).
    latencies = []
    sp, rp = seg0, rep0
    # Fresh trace-flush budget: the throughput loop above may have pushed
    # the buffered writer near its FLUSH_EVERY boundary, and the probe's
    # ~4 emits per window must never trip a synchronous disk flush inside
    # the timed region.
    telemetry.flush_trace()
    for w in range(5):
        wire_s = slide_wire(w + 1)
        jax.device_get(wire_s[:1])  # staged before window close
        t0 = time.perf_counter()
        # window.* span → FixedBucketLatency → telemetry p50/p95. The
        # timed region holds dispatch + the true-sync device_get (the
        # probe's own fetch), wrapped in compute/fetch child spans so
        # the run ledger attributes the probe's phases (tools/sfprof):
        # their buffered span emits cost ~µs against ms-scale windows,
        # far inside the tunnel's ±50% noise. The heavier telemetry
        # work — d2h accounting (a counter-event trace write) and the
        # window span-exit write — happens after the clock stops, and
        # OUTSIDE the window span so it lands in the inter-window host
        # gap, not in the window's unattributed residue.
        with telemetry.span("window.headline", window=w):
            with telemetry.span("compute"):
                sp, rp, res = jstep(sp, rp, wire_s, q_d)
            with telemetry.span("fetch"):
                nv = jax.device_get(res.num_valid)
                latencies.append(time.perf_counter() - t0)
        telemetry.account_d2h(np.asarray(nv).nbytes)
        if slo_engine is not None:
            # Outside the window span, after the clock stopped: the
            # bench's synthetic stream is in order, so lag is 0 — the
            # engine still sees every probe window for its EPS/budget
            # checks.
            slo_engine.observe_window(SLIDE, lag_ms=0.0)
    if probe is not None:
        probe.sample()  # phase boundary: latency probe done
    telemetry.maybe_flush_stream(force=True)

    # ---- Overlap proof: the pipelined ingest runtime, span-visible. ----
    # The latency probe above is the SYNCHRONOUS cadence: ship lands
    # BETWEEN window.headline spans, so ingest is attributed host gap.
    # This probe runs the same windows through the executor with spans
    # on (window.pipeline) and the delta-bitpacked codec on the wire:
    # ship rides INSIDE the window spans and pane bytes shrink, so the
    # run ledger itself proves the overlap (sfprof host-gap detection —
    # the SFT_BENCH_SMOKE contract asserts pipelined gaps < sync gaps)
    # and carries the compression gauges (record: wire_bytes vs
    # raw_bytes). Results must stay exact: every probe window still
    # fills its top-50.
    from spatialflink_tpu.ops import wire_codec as wc

    overlap_pol = PipelinePolicy(depth=2, fetch_lag=2, codec="delta")
    n_probe = min(6, N_WINDOWS)
    codec_enc = wc.WirePaneEncoder(NUM_SEGMENTS)
    codec_dec = {
        # COPIES: XLA:CPU zero-copy-aliases host buffers, and the
        # encoder mutates its tables in place per pane (see
        # run_wire_panes' pipelined branch for the full note).
        "px": jax.device_put(codec_enc.pred_x.copy(), dev),
        "py": jax.device_put(codec_enc.pred_y.copy(), dev),
    }
    # ONE jit instance: the pane capacity (SLIDE) is static, the word
    # bucket just retraces — at most ladder-many compiled shapes. The
    # predictor tables are NOT donated (the multi-executable px chain
    # corrupts under XLA:CPU donation — see run_wire_panes'
    # decode_step note; retraced word buckets = multiple executables
    # here too).
    jdecode = instrument_jit(
        jax.jit(wc.functools_partial_decode(
            wc.extract_streams, n=SLIDE, num_segments=NUM_SEGMENTS,
        )),
        name="wire_pane_decode",
    )
    pst = {"sp": jcopy(seg0), "rp": jcopy(rep0)}

    def probe_ship(w):
        host = np.ascontiguousarray(wire[w * SLIDE:(w + 1) * SLIDE].T)
        enc = codec_enc.encode(host)
        wb = wc.wire_word_bucket(len(enc.words), SLIDE)
        # Charge the padded bucket — what actually ships (h2d agrees).
        telemetry.account_wire(enc.raw_bytes, 4 * wb + wc.HEADER_BYTES)
        words = wc.pad_words(enc.words, wb)
        telemetry.account_h2d(words.nbytes)
        return (jax.device_put(words, dev), enc)

    def probe_compute(w, staged):
        words_d, enc = staged
        pane_d, codec_dec["px"], codec_dec["py"] = jdecode(
            words_d, jnp.int32(enc.n), jnp.int32(enc.bx),
            jnp.int32(enc.by), jnp.int32(enc.bo),
            codec_dec["px"], codec_dec["py"],
        )
        pst["sp"], pst["rp"], res = jstep_d(pst["sp"], pst["rp"],
                                            pane_d, q_d)
        return res.num_valid

    overlap_ex = PipelinedExecutor(
        overlap_pol, ship=probe_ship, compute=probe_compute,
        fetch=telemetry.fetch, label="pipeline", spans=True,
        node="headline",
    )
    pipeline_results = [
        int(v) for v in overlap_ex.run(range(1, n_probe + 1))
    ]
    assert all(v == K for v in pipeline_results), \
        f"pipelined kNN underfilled: {pipeline_results[:3]}"
    if probe is not None:
        probe.sample()  # phase boundary: overlap probe done
    telemetry.maybe_flush_stream(force=True)

    # ---- Device-resident throughput: ingest off the critical path. ----
    # Slides 1..N stay staged in HBM (60 MB of wire records); one
    # compiled scan digests every slide, merges every window, and keeps
    # each window's FULL top-50 result live (dist/segment/index/num_valid
    # all fetched — nothing is dead code). Passes chain through the
    # carried digest (a wrap-around continuous stream); one fetch at the
    # end is the only sync. This is the silicon number comparable to the
    # measured XLA:CPU in-RAM baseline.
    wire_all_host = np.ascontiguousarray(
        wire[SLIDE:].reshape(N_WINDOWS, SLIDE, 3).transpose(0, 2, 1)
    )
    telemetry.account_h2d(wire_all_host.nbytes)
    wire_all = jax.device_put(wire_all_host, dev)

    def resident_pass(seg_prev, rep_prev, wire_r):
        def body(carry, wire_s):
            sp, rp, res = step(carry[0], carry[1], wire_s, q_d)
            return (sp, rp), tuple(res)
        carry, outs = jax.lax.scan(body, (seg_prev, rep_prev), wire_r)
        return carry[0], carry[1], outs

    jresident = instrument_jit(
        jax.jit(resident_pass, donate_argnums=(0, 1)), name="resident_pass"
    )

    # Compile + force staging, then calibrate the pass count so a timed
    # run spans ~2 s (amortizes the final fetch's tunnel round trip).
    s, r, outs = jresident(jcopy(seg0), jcopy(rep0), wire_all)
    jax.device_get(outs[-1])
    t0 = time.perf_counter()
    s, r, outs = jresident(jcopy(seg0), jcopy(rep0), wire_all)
    fetched = jax.device_get(outs)
    t_pass = time.perf_counter() - t0
    resident_results = [int(v) for v in fetched[-1]]
    passes = int(np.clip(np.ceil(2.0 / max(t_pass, 1e-4)), 2, 64))

    def resident_run():
        sp, rp = jcopy(seg0), jcopy(rep0)
        handles = []
        t0 = time.perf_counter()
        for _ in range(passes):
            sp, rp, outs = jresident(sp, rp, wire_all)
            handles.append(outs)
        all_out = telemetry.fetch(handles)  # the only true sync
        return time.perf_counter() - t0, all_out

    res_runs = [resident_run() for _ in range(5)]
    if probe is not None:
        probe.sample()  # phase boundary: resident loops done
    telemetry.maybe_flush_stream(force=True)
    t_res = float(np.median([t for t, _ in res_runs]))
    resident_pps = passes * N_WINDOWS * SLIDE / t_res
    for _, all_out in res_runs[-1:]:
        for outs in all_out:
            assert all(int(v) == K for v in outs[-1]), "resident underfill"

    # Ingest rate: distinct stream points consumed per second (each point
    # is ingested once, digested once, and evaluated in 2 overlapping
    # windows via the digest merge). The timed region ingests slides
    # 1..N_WINDOWS (slide 0 precedes window 0). Comparable to the
    # reference's 20k events/sec target; window-evaluations/sec would
    # double-count the 50% overlap.
    distinct_points = SLIDE * N_WINDOWS
    points_per_sec = distinct_points / t_total
    p50_ms = float(np.percentile(latencies, 50) * 1000)
    assert all(v == K for v in results), f"kNN underfilled: {results[:3]}"
    assert all(v == K for v in resident_results), \
        f"resident kNN underfilled: {resident_results[:3]}"

    out = {
        "metric": "continuous_knn_k50_1M_window_points_per_sec_per_chip",
        "value": round(points_per_sec, 1),
        "unit": "points/s",
        "vs_baseline": round(points_per_sec / BASELINE_EPS, 2),
        "p50_window_latency_ms": round(p50_ms, 3),
        "device": str(dev),
        "windows": N_WINDOWS,
        "k": K,
        "wire_bytes_per_point": wf.bytes_per_point,
        "digest_step": step_kind,
        "device_resident_points_per_sec": round(resident_pps, 1),
        "device_resident_passes": passes,
        "device_resident_vs_baseline": round(resident_pps / BASELINE_EPS, 2),
        # Runtime-telemetry summary (telemetry.py): XLA compile count from
        # the recompile detector, device-boundary bytes both ways, window
        # latency p50/p95 from the probe spans, watermark gauges (0 here —
        # the bench's synthetic stream is in order by construction).
        "telemetry": telemetry.summary(),
    }
    # Per-node attribution table (telemetry.node_rollup — the pipelined
    # executors above run under node "headline"): rides the record AND
    # the ledger snapshot; the smoke contract below asserts the two are
    # identical (record↔ledger round trip).
    _nodes = telemetry.node_rollup()
    if _nodes:
        out["telemetry"]["nodes"] = _nodes
    # Pipelined-ingest proof block: the executor's counters (overlapped
    # vs collapsed windows, drains) + whether SFT_PIPELINE armed the
    # OPERATOR paths too (the throughput loop and overlap probe always
    # run through the executor). wire_bytes/raw_bytes are the overlap
    # probe's codec gauges: post-codec bytes actually shipped for wire
    # panes vs what the raw 6 B/pt format would have cost — the
    # uniform-random bench stream bounds the ratio near 1 + the oid
    # width win; the SNCB random-walk regime is where it pays
    # (tests/test_wire_codec.py).
    _armed_pol = pipeline_mod.policy()
    out["pipeline"] = {
        "armed": _armed_pol is not None,
        # The armed policy's codec is part of the capture's identity:
        # the trend store keys series by (pipeline, codec) arming so a
        # codec-on capture never gates against codec-off history.
        "armed_codec": _armed_pol.codec if _armed_pol is not None
        else None,
        "probe_policy": overlap_pol.to_dict(),
        "counters": telemetry.pipeline_counters(),
    }
    wg = telemetry.wire_codec_gauges()
    if wg:
        out["raw_bytes"] = wg["raw_bytes"]
        out["wire_bytes"] = wg["coded_bytes"]
        if wg["ratio"]:
            out["wire_compression_ratio"] = round(wg["ratio"], 4)
    # Measured link health at the record's phase boundaries: lets the
    # reader (and sfprof diff) separate "tunnel degraded" from "chip
    # slow" instead of blaming the ±50% band blindly.
    link = telemetry.link_gauges()
    if link:
        out["link_probe"] = link
    if slo_engine is not None:
        out["slo"] = slo_engine.verdict()
    if overload_ctrl is not None:
        out["overload"] = overload_ctrl.snapshot()
    if smoke:
        out["smoke"] = True
    # Ablation taint (SFT_ABLATE armed at import, ablation.py): the
    # record itself says it is a profiling artifact, so the trend
    # ingester / last-good store / diff gate reject it even when only
    # the one-line record (not the ledger) survives.
    from spatialflink_tpu.ablation import ablation as _ablation

    _taint = _ablation.taint_block()
    if _taint is not None:
        out["tainted"] = _taint
    # Measured CPU-backend throughput of the same fused program on this
    # host (bench_suite.py --cpu-baseline) — the measured counterpart to
    # the reference's configured 20k EPS target.
    try:
        from bench_suite import load_cpu_baseline

        cpu = load_cpu_baseline().get("continuous_knn_k50_1M_window")
        if cpu:
            out["vs_measured_cpu"] = round(points_per_sec / cpu, 2)
            out["device_resident_vs_measured_cpu"] = round(
                resident_pps / cpu, 2
            )
            # The CPU figure is the SAME program (build_headline_step) on
            # XLA:CPU with the wire records already in RAM (no ingest):
            # the honest comparator for device_resident_points_per_sec.
            # The e2e `value` is bound by the ~20-30 MB/s measurement
            # tunnel, not TPU silicon. See BASELINE.md.
            out["measured_cpu_is"] = "same-program XLA:CPU in-RAM"
    except Exception:
        pass
    print(json.dumps(out))
    ledger_path = _os.environ.get("SFT_LEDGER_PATH")
    if ledger_path:
        # Run ledger (tools/sfprof): full telemetry state + this record
        # in one schema-versioned document. Written AFTER the contract
        # line is on stdout (flushed): the lazy cost capture re-pays one
        # AOT compile per signature, and on the chip the supervisor's
        # deadline could kill the child mid-capture — the dial's record
        # must already be out. A ledger failure degrades to stderr.
        sys.stdout.flush()
        try:
            telemetry.write_ledger(ledger_path, bench=out)
        except Exception as e:
            sys.stderr.write(f"ledger not written: {e!r}\n")
        else:
            if smoke:
                # Contract: the per-node table printed in the record is
                # byte-for-byte the one the ledger snapshot carries —
                # nothing between the print and the ledger write may
                # touch a node bucket (cost capture is node-blind).
                with open(ledger_path) as f:
                    _doc = json.load(f)
                _rec = out["telemetry"].get("nodes") or {}
                _led = (_doc.get("snapshot") or {}).get("nodes") or {}
                if json.dumps(_rec, sort_keys=True) != json.dumps(
                        _led, sort_keys=True):
                    raise SystemExit(
                        "bench smoke: per-node table diverged between "
                        f"record ({sorted(_rec)}) and ledger "
                        f"({sorted(_led)})"
                    )
                if not _rec:
                    raise SystemExit(
                        "bench smoke: no per-node attribution in the "
                        "record (the headline executors should scope "
                        "node='headline')"
                    )
    # A run with only a stream (no SFT_LEDGER_PATH) still seals cleanly;
    # no-op when write_ledger above already sealed it.
    telemetry.seal_stream("complete", bench=out)


if __name__ == "__main__":
    sys.exit(main())
